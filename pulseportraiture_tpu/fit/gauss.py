"""Gaussian profile/portrait fitters + automatic component seeding.

TPU-native equivalents of the reference's lmfit drivers
(``fit_gaussian_profile`` /root/reference/pplib.py:1842-1922,
``fit_gaussian_portrait`` :1924-2052) and a non-interactive
generalization of the GaussianSelector GUI's ``auto_gauss`` seeding
(/root/reference/ppgauss.py:442-479): iterative peak-pick-fit-subtract,
so model building needs no matplotlib event loop.

The minimizer is the in-repo batched Levenberg-Marquardt (fit.lm) with
forward-mode Jacobians through the vectorized portrait generator — one
jitted program per (model_code, ngauss) instead of lmfit's per-call
MINPACK host loop.
"""

import jax.numpy as jnp
import numpy as np

from ..config import wid_max
from ..ops.profiles import (gaussian_profile, gen_gaussian_portrait,
                            gen_gaussian_profile)
from ..utils.databunch import DataBunch
from .lm import lm_solve
from .phase_shift import fit_phase_shift

__all__ = ["fit_gaussian_profile", "fit_gaussian_portrait",
           "auto_gauss_seed", "peak_pick_seed", "dc_seed"]


def dc_seed(profile):
    """DC-level seed: the 10th-percentile sample of the profile (the
    reference GUI's DCguess, /root/reference/ppgauss.py:419)."""
    profile = np.asarray(profile)
    return float(np.sort(profile)[len(profile) // 10 + 1])


def fit_gaussian_profile(data, init_params, errs, fit_flags=None,
                         fit_scattering=False, quiet=True):
    """Fit [dc, tau_bins, (loc, wid, amp)*ngauss] to a profile.

    Bounds as the reference: tau >= 0, 0 <= wid <= wid_max, amp >= 0.
    Returns DataBunch(fitted_params, fit_errs, residuals, chi2, dof).
    Equivalent of /root/reference/pplib.py:1842-1922.
    """
    data = jnp.asarray(data, dtype=jnp.float64)
    nbin = data.shape[-1]
    errs = jnp.broadcast_to(jnp.asarray(errs, dtype=jnp.float64),
                            data.shape)
    init_params = np.asarray(init_params, dtype=np.float64)
    nparam = len(init_params)
    if fit_flags is None:
        flags = np.ones(nparam)
        flags[1] = float(fit_scattering)
    else:
        # reference semantics: caller flags cover the non-scattering
        # params; tau's flag always comes from fit_scattering
        flags = np.asarray(
            [float(fit_flags[0]), float(fit_scattering)]
            + [float(f) for f in fit_flags[1:nparam - 1]])
    lo = np.full(nparam, -np.inf)
    hi = np.full(nparam, np.inf)
    lo[1] = 0.0
    lo[3::3] = 0.0
    hi[3::3] = wid_max
    lo[4::3] = 0.0

    def residual(x):
        return (data - gen_gaussian_profile(x, nbin)) / errs

    r = lm_solve(residual, init_params, fit_flags=flags, bounds=(lo, hi))
    residuals = np.asarray(residual(r.params)) * np.asarray(errs)
    dof = nbin - int(flags.sum())
    if not quiet:
        print("Multi-Gaussian profile fit: %d gaussians, dof %d, "
              "red chi2 %.2f" % ((nparam - 2) // 3, dof,
                                 float(r.chi2) / max(dof, 1)))
    return DataBunch(fitted_params=np.asarray(r.params),
                     fit_errs=np.asarray(r.param_errs),
                     residuals=residuals, chi2=float(r.chi2), dof=dof)


def fit_gaussian_portrait(model_code, data, init_params, scattering_index,
                          errs, fit_flags, fit_scattering_index, phases,
                          freqs, nu_ref, join_params=(), P=None,
                          quiet=True):
    """Fit evolving Gaussian components to a portrait.

    init_params = [dc, tau_bins, (loc, dloc, wid, dwid, amp, damp)*n];
    the scattering index rides as an extra trailing parameter (fit when
    ``fit_scattering_index``), and join (phase, DM) pairs append after
    it when ``join_params`` = [join_ichans(x), params, flags] is given.
    Returns DataBunch(fitted_params, fit_errs, scattering_index(+err),
    chi2, dof).  Equivalent of /root/reference/pplib.py:1924-2052.
    """
    data = jnp.asarray(data, dtype=jnp.float64)
    errs = jnp.broadcast_to(jnp.asarray(errs, dtype=jnp.float64),
                            data.shape)
    phases = jnp.asarray(phases)
    freqs = jnp.asarray(freqs)
    init_params = np.asarray(init_params, dtype=np.float64)
    nparam = len(init_params)
    flags = np.asarray(fit_flags, dtype=np.float64)[:nparam].copy()

    if len(join_params):
        join_ichans = [np.asarray(ic) for ic in join_params[0]]
        join_vals = np.asarray(join_params[1], dtype=np.float64)
        join_flags = np.asarray(join_params[2], dtype=np.float64)
        njoin = len(join_ichans)
    else:
        join_ichans, join_vals, join_flags, njoin = [], np.array([]), \
            np.array([]), 0

    # full vector: model params + [scattering_index] + join params
    x0 = np.concatenate([init_params, [float(scattering_index)], join_vals])
    xflags = np.concatenate([flags, [float(bool(fit_scattering_index))],
                             join_flags])
    lo = np.full(len(x0), -np.inf)
    hi = np.full(len(x0), np.inf)
    lo[1] = 0.0
    lo[4:nparam:6] = 0.0
    hi[4:nparam:6] = wid_max
    lo[6:nparam:6] = 0.0

    def residual(x):
        mpar = x[:nparam]
        alpha = x[nparam]
        if njoin:
            mpar = jnp.concatenate([mpar, x[nparam + 1:]])
        model = gen_gaussian_portrait(model_code, mpar, alpha, phases,
                                      freqs, nu_ref,
                                      join_ichans=join_ichans, P=P)
        return ((data - model) / errs).ravel()

    r = lm_solve(residual, x0, fit_flags=xflags, bounds=(lo, hi))
    params = np.asarray(r.params)
    perrs = np.asarray(r.param_errs)
    dof = data.size - int(xflags.sum())
    fitted = np.concatenate([params[:nparam], params[nparam + 1:]]) \
        if njoin else params[:nparam]
    fitted_errs = np.concatenate([perrs[:nparam], perrs[nparam + 1:]]) \
        if njoin else perrs[:nparam]
    if not quiet:
        resid = np.asarray(residual(params)).reshape(data.shape) * \
            np.asarray(errs)
        print("Gaussian portrait fit: %d gaussians, dof %d, red chi2 "
              "%.2g, resid std %.3g" % ((nparam - 2) // 6, dof,
                                        float(r.chi2) / max(dof, 1),
                                        resid.std()))
    return DataBunch(fitted_params=fitted, fit_errs=fitted_errs,
                     scattering_index=float(params[nparam]),
                     scattering_index_err=float(perrs[nparam]),
                     chi2=float(r.chi2), dof=dof)


def auto_gauss_seed(profile, errs, wid_guess=0.05, tau=0.0,
                    fit_scattering=False):
    """Single-component automatic seed + fit (the reference GUI's
    auto_gauss mode, /root/reference/ppgauss.py:442-479): amp from the
    peak, loc from an FFTFIT against a centered template, DC from the
    10th percentile.  Returns the fit_gaussian_profile result.
    """
    profile = np.asarray(profile)
    nbin = len(profile)
    dc_guess = dc_seed(profile)
    amp = profile.max()
    first = amp * np.asarray(gaussian_profile(nbin, 0.5, wid_guess))
    loc = 0.5 + float(np.asarray(fit_phase_shift(
        profile, first, noise=errs if np.ndim(errs) == 0 else None).phase))
    init = [dc_guess, tau, loc % 1.0, wid_guess, amp]
    return fit_gaussian_profile(profile, init, errs,
                                fit_scattering=fit_scattering)


def peak_pick_seed(profile, errs, max_ngauss=6, snr_stop=5.0, tau=0.0,
                   fit_scattering=False, quiet=True):
    """Iterative peak-pick-fit-subtract seeding for multi-component
    profiles (the non-interactive generalization of GaussianSelector,
    SURVEY.md section 7.1): add a component at the residual peak with a
    local-HWHM width guess, refit all components, stop when the residual
    peak drops below snr_stop * noise or max_ngauss is reached.

    Returns the final fit_gaussian_profile result (params include all
    accepted components).
    """
    profile = np.asarray(profile, dtype=np.float64)
    nbin = len(profile)
    err_level = float(np.median(np.atleast_1d(np.asarray(errs))))
    dc_guess = dc_seed(profile)
    comps = []
    best = None
    resid = profile - dc_guess
    for _ in range(max_ngauss):
        ipk = int(np.argmax(resid))
        amp = float(resid[ipk])
        if amp < snr_stop * err_level:
            break
        # local half-max width estimate around the peak (circular)
        half = amp / 2.0
        w = 1
        while w < nbin // 2 and (
                resid[(ipk + w) % nbin] > half
                or resid[(ipk - w) % nbin] > half):
            w += 1
        wid = max(2.0 * w / nbin, 1.5 / nbin)
        comps.append([(ipk + 0.5) / nbin, min(wid, wid_max), amp])
        init = [dc_guess, tau] + [v for c in comps for v in c]
        best = fit_gaussian_profile(profile, init, errs,
                                    fit_scattering=fit_scattering,
                                    quiet=quiet)
        # refine the accepted component list from the fit
        fp = best.fitted_params
        comps = [[fp[2 + 3 * i] % 1.0, fp[3 + 3 * i], fp[4 + 3 * i]]
                 for i in range(len(comps))]
        dc_guess = fp[0]
        model = np.asarray(gen_gaussian_profile(fp, nbin))
        resid = profile - model
    if best is None:
        best = auto_gauss_seed(profile, errs, tau=tau,
                               fit_scattering=fit_scattering)
    return best
