"""Unrolled symmetric solves for tiny (n<=8) systems, TPU-f64-safe.

TPU's LuDecomposition/LAPACK custom calls only implement f32/c64; the
fit kernels need f64 5x5 Newton solves and covariance inversions.  For
fixed tiny n, Cholesky factorization unrolled into scalar elementwise
ops compiles on any backend in any real dtype, vmaps cleanly, and is
faster than a general LU at this size anyway.

A non-positive-definite input yields NaNs (sqrt of a negative pivot) —
deliberate: the Levenberg loop rejects NaN trial steps and raises its
damping, and NaN covariance flags a failed fit (reference behavior).
"""

import jax
import jax.numpy as jnp

__all__ = ["chol_factor", "chol_solve", "solve_sym", "inv_sym",
           "solve_refined", "inv_refined"]


def chol_factor(A):
    """Lower-triangular Cholesky factor of symmetric A [..., n, n],
    unrolled over the (static) n."""
    n = A.shape[-1]
    L = [[None] * n for _ in range(n)]
    for i in range(n):
        for j in range(i + 1):
            s = A[..., i, j]
            for p in range(j):
                s = s - L[i][p] * L[j][p]
            if i == j:
                L[i][j] = jnp.sqrt(s)
            else:
                L[i][j] = s / L[j][j]
    rows = [jnp.stack([L[i][j] if j <= i else jnp.zeros_like(A[..., 0, 0])
                       for j in range(n)], axis=-1) for i in range(n)]
    return jnp.stack(rows, axis=-2)


def chol_solve(L, b):
    """Solve A x = b given L = chol_factor(A); b [..., n]."""
    n = L.shape[-1]
    # forward substitution: L y = b
    y = [None] * n
    for i in range(n):
        s = b[..., i]
        for p in range(i):
            s = s - L[..., i, p] * y[p]
        y[i] = s / L[..., i, i]
    # back substitution: L^T x = y
    x = [None] * n
    for i in reversed(range(n)):
        s = y[i]
        for p in range(i + 1, n):
            s = s - L[..., p, i] * x[p]
        x[i] = s / L[..., i, i]
    return jnp.stack(x, axis=-1)


def solve_refined(A, b, refinements=2):
    """General small solve: f32 LU + f64 iterative refinement.

    TPU's LU only implements f32; a f32 solve refined twice in f64
    (r = b - A x; x += A_f32^-1 r) recovers ~f64 accuracy for
    well-conditioned systems and stays *finite* (unlike Cholesky) on
    indefinite A — which the Levenberg loop requires far from the
    minimum.
    """
    A32 = A.astype(jnp.float32)
    lu, piv = jax.scipy.linalg.lu_factor(A32)

    def solve32(rhs):
        return jax.scipy.linalg.lu_solve(
            (lu, piv), rhs.astype(jnp.float32)).astype(A.dtype)

    x = solve32(b)
    for _ in range(refinements):
        r = b - jnp.einsum("...ij,...j->...i", A, x)
        x = x + solve32(r)
    return x


def inv_refined(A, refinements=2):
    """General small inverse: f32 LU + f64 Newton refinement
    (X <- X (2 I - A X))."""
    A32 = A.astype(jnp.float32)
    X = jnp.linalg.inv(A32).astype(A.dtype)
    n = A.shape[-1]
    eye = jnp.eye(n, dtype=A.dtype)
    for _ in range(refinements):
        X = X @ (2.0 * eye - A @ X)
    return X


def solve_sym(A, b):
    """x = A^-1 b for symmetric (positive-definite) A [..., n, n]."""
    return chol_solve(chol_factor(A), b)


def inv_sym(A):
    """Inverse of symmetric (positive-definite) A [..., n, n]."""
    n = A.shape[-1]
    L = chol_factor(A)
    eye = jnp.eye(n, dtype=A.dtype)
    cols = [chol_solve(L, jnp.broadcast_to(eye[i], A.shape[:-2] + (n,)))
            for i in range(n)]
    return jnp.stack(cols, axis=-1)
