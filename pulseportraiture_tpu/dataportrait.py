"""DataPortrait: the t/p-scrunched portrait container for model building.

TPU-native equivalent of the reference's ``DataPortrait`` class
(/root/reference/pplib.py:138-649), including the multi-archive "join"
machinery (:163-305) used for multi-receiver model building.  Differences
from the reference (deliberate):

* No ``exec``-based attribute plumbing — load_data fields are carried in
  ``self.data`` (a DataBunch) and mirrored explicitly.
* The condensed ("x"-suffixed) views are the dense arrays indexed by
  ``ok_ichans``; the device kernels themselves consume dense arrays with
  weight masks, so the condensed views exist for host-side model
  construction only (PCA, splprep) exactly where the reference uses them.
* Join alignment seeds come from the batched FFTFIT (one device call),
  not per-archive scipy brute loops.
"""

import numpy as np

from .fit.phase_shift import fit_phase_shift
from .io.archive import file_is_type, load_data, parse_metafile
from .ops.noise import get_noise
from .ops.normalize import normalize_portrait
from .ops.fourier import rotate_data
from .ops.wavelet import smart_smooth, wavelet_smooth

__all__ = ["DataPortrait"]


class DataPortrait:
    """One (tscrunched, pscrunched) portrait + condensed views + metadata.

    datafile: a PSRFITS archive path, or a metafile listing several
    archives — the latter activates "join" mode, concatenating the bands
    in frequency order with per-band (phase, DM) alignment parameters.
    joinfile: optional persisted join parameters (write_join_parameters).
    """

    def __init__(self, datafile=None, joinfile=None, quiet=True,
                 **load_data_kwargs):
        self.init_params = []
        self.joinfile = joinfile
        self.datafile = datafile
        if file_is_type(datafile) == "ASCII":
            self._init_join(datafile, quiet, **load_data_kwargs)
        else:
            self._init_single(datafile, quiet, **load_data_kwargs)

    # -- construction -----------------------------------------------------

    def _init_single(self, datafile, quiet, **load_data_kwargs):
        self.njoin = 0
        self.join_params = np.array([])
        self.join_param_errs = np.array([])
        self.join_fit_flags = np.array([])
        self.join_ichans = []
        self.join_ichanxs = []
        self.all_join_params = []
        self.datafiles = [datafile]
        d = self.data = load_data(
            datafile, dedisperse=True, dededisperse=False, tscrunch=True,
            pscrunch=True, fscrunch=False, flux_prof=True,
            refresh_arch=True, return_arch=True, quiet=quiet,
            **load_data_kwargs)
        for key in ("source", "arch", "nbin", "nchan", "nu0", "bw", "Ps",
                    "freqs", "weights", "masks", "ok_ichans", "ok_isubs",
                    "noise_stds", "SNRs", "phases", "prof", "flux_prof",
                    "DM", "epochs", "telescope", "telescope_code"):
            setattr(self, key, d[key])
        # load_data may hand out non-writable (device-backed) arrays;
        # normalize/smooth update noise levels in place
        self.noise_stds = np.array(self.noise_stds)
        if self.source is None:
            self.source = "noname"
        ok = self.ok_ichans[0]
        self.port = (self.masks * d.subints)[0, 0]
        self.portx = self.port[ok]
        self.flux_profx = self.flux_prof[ok]
        self.freqsxs = [self.freqs[0, ok]]
        self.noise_stdsxs = self.noise_stds[0, 0, ok]
        self.SNRsxs = self.SNRs[0, 0, ok]
        self.weightsxs = np.array([self.weights[0, ok]])

    def _init_join(self, metafile, quiet, **load_data_kwargs):
        """Concatenate several single-receiver archives in frequency order
        with per-band alignment parameters (ref pplib.py:163-305)."""
        self.metafile = metafile
        self.datafiles = parse_metafile(metafile)
        self.njoin = len(self.datafiles)
        join_params, join_fit_flags = [], []
        join_nchans, join_nchanxs = [0], [0]
        freqs, freqsxs, masks, port, portx = [], [], [], [], []
        flux_prof, flux_profx = [], []
        noise_stds, noise_stdsxs, SNRs, SNRsxs = [], [], [], []
        weights, weightsxs = [], []
        Psum, nchan, nchanx = 0.0, 0, 0
        lofreq, hifreq = np.inf, 0.0
        refprof = None
        d = None
        for ifile, fname in enumerate(self.datafiles):
            d = load_data(fname, dedisperse=True, tscrunch=True,
                          pscrunch=True, fscrunch=False, flux_prof=True,
                          return_arch=True, quiet=quiet, **load_data_kwargs)
            nchan += d.nchan
            nchanx += len(d.ok_ichans[0])
            join_nchans.append(nchan)
            join_nchanxs.append(nchanx)
            if ifile == 0:
                # first band anchors the frame: phase fixed, DM offset fit
                join_params.extend([0.0, 0.0])
                join_fit_flags.extend([0, 1])
                self.nbin = d.nbin
                self.phases = d.phases
                refprof = d.prof
                self.source = d.source
                self.arch = d.arch
            else:
                phi = -float(np.asarray(fit_phase_shift(
                    d.prof, refprof, Ns=self.nbin).phase))
                join_params.extend([phi, 0.0])
                join_fit_flags.extend([1, 1])
            Psum += d.Ps.mean()
            lofreq = min(lofreq, d.freqs.min() - abs(d.bw) / (2 * d.nchan))
            hifreq = max(hifreq, d.freqs.max() + abs(d.bw) / (2 * d.nchan))
            ok = d.ok_ichans[0]
            freqs.extend(d.freqs[0])
            freqsxs.extend(d.freqs[0, ok])
            masks.extend(d.masks[0, 0])
            port.extend(d.subints[0, 0] * d.masks[0, 0])
            portx.extend(d.subints[0, 0, ok])
            flux_prof.extend(d.flux_prof)
            flux_profx.extend(d.flux_prof[ok])
            noise_stds.extend(d.noise_stds[0, 0])
            noise_stdsxs.extend(d.noise_stds[0, 0, ok])
            SNRs.extend(d.SNRs[0, 0])
            SNRsxs.extend(d.SNRs[0, 0, ok])
            weights.extend(d.weights[0])
            weightsxs.extend(d.weights[0, ok])
        self.data = d
        self.DM = d.DM
        self.nchan, self.nchanx = nchan, nchanx
        self.Ps = np.array([Psum / self.njoin])
        self.lofreq, self.hifreq = lofreq, hifreq
        self.bw = hifreq - lofreq
        freqs = np.asarray(freqs)
        freqsxs = np.asarray(freqsxs)
        self.nu0 = freqs.mean()
        isort = np.argsort(freqs)
        isortx = np.argsort(freqsxs)
        self.isort, self.isortx = isort, isortx
        self.join_ichans = []
        self.join_ichanxs = []
        for ij in range(self.njoin):
            self.join_ichans.append(np.flatnonzero(
                (isort >= join_nchans[ij]) & (isort < join_nchans[ij + 1])))
            self.join_ichanxs.append(np.flatnonzero(
                (isortx >= join_nchanxs[ij])
                & (isortx < join_nchanxs[ij + 1])))
        self.masks = np.asarray(masks)[isort][None, None]
        self.port = np.asarray(port)[isort]
        self.portx = np.asarray(portx)[isortx]
        self.flux_prof = np.asarray(flux_prof)[isort]
        self.flux_profx = np.asarray(flux_profx)[isortx]
        self.noise_stds = np.asarray(noise_stds)[isort][None, None]
        self.noise_stdsxs = np.asarray(noise_stdsxs)[isortx]
        self.SNRs = np.asarray(SNRs)[isort][None, None]
        self.SNRsxs = np.asarray(SNRsxs)[isortx]
        self.weights = np.asarray(weights)[isort][None]
        self.weightsxs = np.asarray(weightsxs)[isortx][None]
        self.freqs = np.sort(freqs)[None]
        self.freqsxs = [np.sort(freqsxs)]
        self.ok_ichans = [np.flatnonzero(self.weights[0] > 0.0)]
        self.join_params = np.asarray(join_params, dtype=np.float64)
        self.join_param_errs = np.zeros_like(self.join_params)
        self.join_fit_flags = np.asarray(join_fit_flags, dtype=int)
        if self.joinfile:
            self._read_joinfile(self.joinfile)
        self.all_join_params = [self.join_ichanxs, self.join_params,
                                self.join_fit_flags]

    def _read_joinfile(self, joinfile):
        """Re-seed join parameters from a persisted joinfile
        (ref pplib.py:282-299)."""
        with open(joinfile) as f:
            lines = [ln.split() for ln in f
                     if ln.strip() and not ln.startswith("#")]
        for parts in lines[-len(self.datafiles):]:
            try:
                ij = self.datafiles.index(parts[0])
            except ValueError:
                continue
            phi = float(parts[1])
            DM = float(parts[3]) if len(parts) > 3 else float(parts[2])
            self.join_params[ij * 2] = phi
            self.join_params[ij * 2 + 1] = DM

    # -- manipulation ------------------------------------------------------

    def apply_joinfile(self, nu_ref, undo=False):
        """Rotate each band by its join (phase, DM) parameters
        (ref pplib.py:329-355)."""
        sign = -1.0 if undo else 1.0
        for ij in range(self.njoin):
            phi = sign * self.join_params[2 * ij]
            DM = sign * self.join_params[2 * ij + 1]
            jic = self.join_ichans[ij]
            self.port[jic] = np.asarray(rotate_data(
                self.port[jic], -phi, -DM, self.Ps[0], self.freqs[0, jic],
                nu_ref))
            jicx = self.join_ichanxs[ij]
            self.portx[jicx] = np.asarray(rotate_data(
                self.portx[jicx], -phi, -DM, self.Ps[0],
                self.freqsxs[0][jicx], nu_ref))

    def normalize_portrait(self, method="rms"):
        """Per-channel normalization of port and portx
        (ref pplib.py:357-382)."""
        weights = self.weights[0] if method == "prof" else None
        weightsx = self.weights[self.weights > 0.0] \
            if method == "prof" else None
        self.unnorm_noise_stds = np.copy(self.noise_stds)
        port, norms = normalize_portrait(self.port, method, weights=weights,
                                         return_norms=True)
        self.port = np.asarray(port)
        self.norm_values = np.asarray(norms)
        self.noise_stds[0, 0] = np.asarray(get_noise(self.port))
        self.flux_prof = self.port.mean(axis=1)
        self.unnorm_noise_stdsxs = np.copy(self.noise_stdsxs)
        self.portx = np.asarray(normalize_portrait(self.portx, method,
                                                   weights=weightsx))
        self.noise_stdsxs = np.asarray(get_noise(self.portx))
        self.flux_profx = self.portx.mean(axis=1)

    def unnormalize_portrait(self):
        """Undo normalize_portrait (ref pplib.py:384-398)."""
        if not hasattr(self, "unnorm_noise_stds"):
            return
        self.port = self.norm_values[:, None] * self.port
        self.noise_stds = np.copy(self.unnorm_noise_stds)
        del self.unnorm_noise_stds
        self.flux_prof = self.port.mean(axis=1)
        self.portx = self.norm_values[self.ok_ichans[0]][:, None] * \
            self.portx
        self.noise_stdsxs = np.copy(self.unnorm_noise_stdsxs)
        del self.unnorm_noise_stdsxs
        self.flux_profx = self.portx.mean(axis=1)
        self.norm_values = np.ones(len(self.port))

    def smooth_portrait(self, smart=False, **kwargs):
        """Wavelet-smooth port/portx in place (ref pplib.py:400-424)."""
        if smart:
            kwargs.setdefault("try_nlevels",
                              min(8, int(np.log2(self.nbin))))
            self.port = np.asarray(smart_smooth(self.port, **kwargs))
            self.portx = np.asarray(smart_smooth(self.portx, **kwargs))
        else:
            self.port = np.asarray(wavelet_smooth(self.port, **kwargs))
            self.portx = np.asarray(wavelet_smooth(self.portx, **kwargs))
        self.noise_stds[0, 0] = np.asarray(get_noise(self.port))
        self.noise_stdsxs = np.asarray(get_noise(self.portx))
        self.flux_prof = self.port.mean(axis=1)
        self.flux_profx = self.portx.mean(axis=1)

    def fit_flux_profile(self, channel_errs=None, nu_ref=None, guessA=1.0,
                         guessalpha=0.0, quiet=True):
        """Power-law fit to the phase-averaged flux spectrum
        (ref pplib.py:426-485, sans plotting)."""
        from .fit.powlaw import fit_powlaw

        if nu_ref is None:
            nu_ref = self.nu0
        if channel_errs is None:
            channel_errs = np.ones(len(self.freqsxs[0]))
        fp = fit_powlaw(self.flux_profx, np.array([guessA, guessalpha]),
                        channel_errs, self.freqsxs[0], nu_ref)
        if not quiet:
            print("Flux power law: A = %.3f +/- %.3f at %.2f MHz, "
                  "alpha = %.3f +/- %.3f" % (fp.amp, fp.amp_err, fp.nu_ref,
                                             fp.alpha, fp.alpha_err))
        self.flux_fit = fp
        self.spect_A, self.spect_A_err = fp.amp, fp.amp_err
        self.spect_A_ref = fp.nu_ref
        self.spect_index, self.spect_index_err = fp.alpha, fp.alpha_err
        return fp

    def rotate_stuff(self, phase=0.0, DM=0.0, ichans=None, ichanxs=None,
                     nu_ref=None, model=False):
        """Rotate port/portx (optionally the model) by (phase, DM), and —
        when rotating the full band — keep the stored model-building
        attributes (prof, mean_prof, eigenprofiles) aligned in lockstep
        (ref pplib.py:523-570)."""
        P = self.Ps[0]
        if nu_ref is None:
            nu_ref = self.nu0
        all_chans = ichans is None and ichanxs is None
        if ichans is None:
            ichans = np.arange(self.port.shape[0])
        if ichanxs is None:
            ichanxs = np.arange(self.portx.shape[0])
        self.port[ichans] = np.asarray(rotate_data(
            self.port[ichans], phase, DM, P, self.freqs[0, ichans], nu_ref))
        self.portx[ichanxs] = np.asarray(rotate_data(
            self.portx[ichanxs], phase, DM, P, self.freqsxs[0][ichanxs],
            nu_ref))
        if all_chans:
            # achromatic companions rotate by the phase term only
            for attr in ("prof", "mean_prof", "smooth_mean_prof"):
                if getattr(self, attr, None) is not None:
                    setattr(self, attr, np.asarray(rotate_data(
                        np.asarray(getattr(self, attr)), phase)))
            for attr in ("eigvec", "smooth_eigvec"):
                ev = getattr(self, attr, None)
                if ev is not None and np.size(ev):
                    setattr(self, attr, np.asarray(rotate_data(
                        np.asarray(ev).T, phase)).T)
        if model and hasattr(self, "model"):
            self.model[ichans] = np.asarray(rotate_data(
                self.model[ichans], phase, DM, P, self.freqs[0, ichans],
                nu_ref))
            self.model_masked = self.model * self.masks[0, 0]
            self.modelx = self.model[self.ok_ichans[0]]

    # -- visualization (ref pplib.py:617-649) ------------------------------
    def show_data_portrait(self, **kwargs):
        from .viz import show_data_portrait
        return show_data_portrait(self, **kwargs)

    def show_model_portrait(self, **kwargs):
        from .viz import show_portrait
        return show_portrait(np.asarray(self.modelx),
                             phases=np.asarray(self.phases),
                             freqs=np.asarray(self.freqsxs[0]), **kwargs)

    def show_model_fit(self, **kwargs):
        from .viz import show_model_fit
        return show_model_fit(self, **kwargs)

    def write_join_parameters(self, joinfile=None):
        """Persist join parameters (ref pplib.py:486-521)."""
        if joinfile is None:
            joinfile = self.joinfile or \
                (getattr(self, "model_name", self.datafile) + ".join")
        errs = self.join_param_errs if len(self.join_param_errs) else \
            np.zeros_like(self.join_params)
        with open(joinfile, "a") as jf:
            jf.write("# archive name" + " " * 32
                     + "-phase offset & err [rot]" + " " * 2
                     + "-delta-DM & err [cm**-3 pc]\n")
            for ifile, datafile in enumerate(self.datafiles):
                jf.write("%s%s% .10f %.10f  % .6f %.6f\n" % (
                    datafile, " " * abs(45 - len(datafile)),
                    self.join_params[2 * ifile], errs[2 * ifile],
                    self.join_params[2 * ifile + 1], errs[2 * ifile + 1]))
        return joinfile

    def unload_archive(self, outfile=None, quiet=True):
        """Write the (possibly modified) portrait back to PSRFITS
        (ref pplib.py:572-595)."""
        from .io.archive import unload_new_archive

        if outfile is None:
            outfile = self.datafile + ".port.fits"
        unload_new_archive(self.port[None, None], self.arch, outfile,
                           DM=self.DM, dmc=0, weights=self.weights,
                           quiet=quiet)
        return outfile

    def write_model_archive(self, outfile, quiet=True):
        """Write the current model portrait to PSRFITS
        (ref pplib.py:597-615)."""
        from .io.archive import unload_new_archive

        if not hasattr(self, "model"):
            raise AttributeError("no model built yet")
        unload_new_archive(np.asarray(self.model)[None, None], self.arch,
                           outfile, DM=0.0, dmc=0, weights=self.weights,
                           quiet=quiet)
        return outfile
