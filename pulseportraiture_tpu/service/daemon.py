"""TOA-as-a-service: the resident multi-tenant fitting daemon.

``run_survey`` (runner/execute.py) is batch-shaped: every invocation
pays archive load, bucket compile and process bring-up.
:class:`TOAService` keeps all of that resident so every request after
warm-up is *fit-bound*:

* **Intake / tenancy** — each tenant owns a ledger-backed work queue
  (``runner/queue.WorkQueue`` under ``<workdir>/tenants/<name>/``):
  the same append-only / bounded-retry / quarantine semantics the
  survey runner trusts, so a request's full lifecycle — attempts,
  failure reasons, terminal state — is crash-safe on disk and a
  restarted daemon resumes whatever was accepted but unfinished.
  Fitted TOAs land in the tenant's own ``toas.tim`` through the
  pipeline's exactly-once checkpoint protocol (block + ``pp_done``
  marker per archive), which also makes duplicate submissions replay
  the recorded result instead of refitting.
* **Warm bucket pools** — per-(nchan, nbin)-bucket
  ``_BucketedGetTOAs`` fitters are pooled and reused across requests
  (result state reset between checkouts), and ``warm()`` AOT-compiles
  + primes every program a plan enumerates (service/warm.py), so a
  request on a planned bucket triggers zero new XLA compiles.
* **Micro-batching** — the dispatcher coalesces same-bucket requests
  that arrive within ``batch_window_s`` (up to ``batch_max``) into one
  cycle; their device dispatches merge through the bucket's
  :class:`~.batcher.MicroBatcher`, so K single-archive submissions
  cost ~ceil(K/batch) dispatches on one compiled program.
* **Fairness / backpressure** — cycles seed from the tenant whose
  oldest ready request has waited longest, each tenant holds at most
  ``tenant_max_inflight`` slots of a cycle, and a tenant whose open
  requests reach ``tenant_max_queue`` gets ``backpressure`` rejections
  instead of unbounded intake; no tenant can starve another.
* **SLO under chaos** (testing/faults.py, docs/SERVICE.md failure
  matrix) — injected ``archive_read``/``dispatch`` faults travel the
  same per-archive isolation path as the survey runner
  (``runner/execute._fit_one``): the affected request retries with
  backoff and quarantines on exhaustion, concurrent requests —
  including the rest of its own micro-batch cycle — complete.
  SIGTERM (cli/ppserve.py) flips :meth:`request_drain`: intake starts
  rejecting, everything already accepted finishes, state flushes, the
  daemon exits 0.

Observability: the daemon runs under one long-lived obs run
(``<workdir>/obs``, events rotated via ``PPTPU_OBS_MAX_BYTES``), and
every request additionally gets its own run directory under
``<workdir>/obs_requests`` (manifest + lifecycle events + its compile
counters — a warm request's manifest proves ``backend_compiles: 0``).
Request run dirs are pruned to a count/byte budget
(``run_dirs_max``/``run_bytes_max``, env
``PPTPU_SERVE_MAX_RUNS``/``PPTPU_SERVE_MAX_RUN_BYTES``) so a resident
process cannot grow obs state without bound.
"""

import collections
import contextlib
import functools
import itertools
import os
import re
import shutil
import threading
import time

from .. import obs
from ..io.timfile import format_toa_line
from ..obs import flight, memory, metrics, quality, tracing, usage
from ..obs import health as obs_health
from ..obs.metrics import PHASE_HISTOGRAM
from ..obs.core import Recorder
from ..runner.execute import _BucketedGetTOAs, _fit_one
from ..runner.plan import SurveyPlan, canonical_shape, \
    estimate_archive_bytes, load_bucketed_databunch, \
    scan_archive_header
from ..runner.prefetch import HostPrefetcher
from ..runner.queue import DONE, FAILED, QUARANTINED, WorkQueue
from ..testing import faults
from .batcher import MicroBatcher

__all__ = ["TOAService", "Request"]

_TENANT_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9_.-]{0,63}$")

# request-side states layered over the ledger's: "dispatching" marks a
# request claimed by the current micro-batch cycle
PENDING = "pending"
DISPATCHING = "dispatching"

_REQ_SEQ = itertools.count(1)

# deadline-aware parking (docs/SERVICE.md "Deadline semantics"): a
# request is never parked past this fraction of its deadline budget —
# the rest is reserved for the fit itself
PARK_FRACTION = 0.5

# adaptive window ceiling: under sustained load the parking window
# stretches up to this multiple of ``batch_window_s`` (denser batches
# when arrivals keep coming), never beyond
WINDOW_STRETCH_MAX = 4.0

# arrival-rate window feeding the load stretch [s]
_LOAD_WINDOW_S = 1.0


def _blabel(key):
    """Metrics label for a shape bucket ('-' before classification)."""
    return "-" if key is None else "%dx%d" % tuple(key)


def _env_int(name, default):
    v = os.environ.get(name, "").strip()
    try:
        return int(v) if v else default
    except ValueError:
        return default


class Request:
    """One accepted TOA request (in-memory view; the tenant ledger is
    the durable record)."""

    __slots__ = ("id", "tenant", "path", "key", "config", "bucket",
                 "nsub", "nchan", "nbin", "state", "reason", "attempts",
                 "n_toas", "toa_lines", "quality", "t_submit", "t_done",
                 "done_evt", "recorder", "recovered", "batch_id",
                 "trace_id", "parent_span_id", "span_id", "ticket",
                 "priority", "deadline_s", "fit_s", "fit_peak_bytes",
                 "bytes_in")

    def __init__(self, req_id, tenant, path, key, config,
                 priority=0, deadline_s=None):
        self.id = req_id
        self.tenant = tenant
        self.path = path
        self.key = key
        self.config = config or {}
        self.bucket = None
        self.nsub = self.nchan = self.nbin = 0
        self.state = PENDING
        self.reason = None
        self.attempts = 0
        self.n_toas = 0
        self.toa_lines = None
        # fit-quality fingerprint of the request's archive
        # (obs/quality.py gt_fingerprint, stamped before checkin)
        self.quality = None
        # usage accounting (obs/usage.py): fit-phase device seconds
        # accumulate across attempts, peak fit footprint and decoded
        # archive bytes bill at finalize
        self.fit_s = 0.0
        self.fit_peak_bytes = 0
        self.bytes_in = 0
        # deadline class (docs/SERVICE.md): higher priority seeds
        # cycles first; ``deadline_s`` is a completion budget from
        # submit time — the dispatcher never parks the request past
        # PARK_FRACTION of it (None = no deadline, window semantics)
        self.priority = int(priority or 0)
        self.deadline_s = None if deadline_s is None \
            else max(0.0, float(deadline_s))
        self.t_submit = time.time()
        self.t_done = None
        self.done_evt = threading.Event()
        self.recorder = None
        self.recovered = False
        self.batch_id = None
        # decode-at-intake hand-off (runner/prefetch.py): the ticket
        # whose buffer the fit worker consumes via gt.preload
        self.ticket = None
        # causal identity (obs/tracing.py): the trace this request
        # belongs to (client-minted via the traceparent carrier, or
        # daemon-minted), the client span it parents on, and the id of
        # the daemon-side request span every lifecycle child references
        self.trace_id = None
        self.parent_span_id = None
        self.span_id = tracing.new_span_id()

    def ctx(self):
        """(trace_id, request_span_id): the context lifecycle children
        parent on."""
        return (self.trace_id, self.span_id)

    def park_cutoff(self):
        """Absolute time by which this request must leave the parking
        window (half its deadline budget spent), or None when it has
        no deadline."""
        if self.deadline_s is None:
            return None
        return self.t_submit + PARK_FRACTION * self.deadline_s

    def deadline_at(self):
        """Absolute completion deadline, or None."""
        if self.deadline_s is None:
            return None
        return self.t_submit + self.deadline_s

    def payload(self, cached=False):
        out = {"ok": True, "request_id": self.id, "tenant": self.tenant,
               "archive": self.path, "state": self.state,
               "attempts": self.attempts}
        if self.trace_id:
            out["trace_id"] = self.trace_id
        if self.bucket:
            out["bucket"] = "%dx%d" % self.bucket
        if self.priority:
            out["priority"] = self.priority
        if self.deadline_s is not None:
            out["deadline_s"] = self.deadline_s
            if self.t_done is not None:
                out["deadline_miss"] = \
                    (self.t_done - self.t_submit) > self.deadline_s
        if self.reason:
            out["reason"] = self.reason
        if self.state == DONE:
            out["n_toas"] = self.n_toas
            if self.quality is not None:
                out["quality"] = self.quality
            if self.toa_lines is not None:
                out["toa_lines"] = self.toa_lines
        if self.t_done is not None:
            out["wall_s"] = round(self.t_done - self.t_submit, 6)
        if cached:
            out["cached"] = True
        return out


class _Tenant:
    """Per-tenant intake: ledger queue, checkpoint, open-request FIFO."""

    def __init__(self, name, root, max_attempts, backoff_s):
        self.name = name
        self.dir = os.path.join(root, name)
        os.makedirs(self.dir, exist_ok=True)
        self.queue = WorkQueue(os.path.join(self.dir, "ledger.0.jsonl"),
                               max_attempts=max_attempts,
                               backoff_s=backoff_s)
        self.checkpoint = os.path.join(self.dir, "toas.tim")
        self.fifo = []        # open request ids, submit order
        self.inflight = 0     # requests in the current cycle
        self.n_submitted = 0
        self.n_completed = 0
        self.n_rejected = 0


class _Bucket:
    """Warm per-bucket state: the micro-batcher + a fitter pool."""

    def __init__(self, key, modelfile, window_s):
        self.key = tuple(key)
        self.batcher = MicroBatcher(bucket=self.key, window_s=window_s)
        self.modelfile = modelfile
        self._pool = []
        self._lock = threading.Lock()
        self.n_requests = 0

    def checkout(self):
        with self._lock:
            if self._pool:
                return self._pool.pop()
        gt = _BucketedGetTOAs([], self.modelfile, self.key, quiet=True)
        return gt

    def checkin(self, gt):
        from ..pipelines.toas import GetTOAs

        for attr in GetTOAs.RESULT_ATTRS:
            setattr(gt, attr, [])
        gt.TOA_list = []
        gt.failed_datafiles = []
        gt.poisoned_datafiles = []
        gt.fit_batch = None
        if hasattr(gt, "_data_cache"):
            gt._data_cache = {}
        with self._lock:
            self._pool.append(gt)


class _Info:
    """Duck-typed ArchiveInfo for runner/execute._fit_one."""

    __slots__ = ("path",)

    def __init__(self, path):
        self.path = path


class TOAService:
    """The resident fitting daemon (module docstring).

    In-process API (the socket server in service/server.py is a thin
    shell over it): :meth:`start`, :meth:`warm`, :meth:`submit`,
    :meth:`wait`, :meth:`status`, :meth:`request_drain`,
    :meth:`shutdown`.
    """

    def __init__(self, modelfile, workdir, plan=None, narrowband=False,
                 batch_window_s=0.25, batch_max=8, solo_window_s=0.1,
                 tenant_max_inflight=4, tenant_max_queue=64,
                 max_attempts=3, backoff_s=0.0, run_dirs_max=None,
                 run_bytes_max=None, mem_budget_bytes=None,
                 quotas=None, return_toa_lines=True, get_toas_kw=None,
                 prefetch=2, quiet=True):
        self.modelfile = modelfile
        self.workdir = workdir
        if isinstance(plan, str):
            plan = SurveyPlan.load(plan)
        self.plan = plan
        self.narrowband = bool(narrowband)
        self.batch_window_s = float(batch_window_s)
        self.batch_max = max(1, int(batch_max))
        # adaptive-window floor: a cycle with no other joinable
        # candidate dispatches after this grace instead of the full
        # window — the window only ever buys coalescing, never pure
        # latency (the solo-late-arriver fix, docs/SERVICE.md)
        self.solo_window_s = min(float(solo_window_s),
                                 self.batch_window_s)
        self.tenant_max_inflight = max(1, int(tenant_max_inflight))
        self.tenant_max_queue = max(1, int(tenant_max_queue))
        self.max_attempts = int(max_attempts)
        self.backoff_s = float(backoff_s)
        self.run_dirs_max = _env_int("PPTPU_SERVE_MAX_RUNS", 256) \
            if run_dirs_max is None else int(run_dirs_max)
        self.run_bytes_max = _env_int("PPTPU_SERVE_MAX_RUN_BYTES", 0) \
            if run_bytes_max is None else int(run_bytes_max)
        # memory-aware admission: a request whose analytical footprint
        # estimate (runner/plan.estimate_archive_bytes) exceeds this
        # device budget is rejected at intake (0 = disabled)
        self.mem_budget_bytes = _env_int("PPTPU_SERVE_MEM_BUDGET", 0) \
            if mem_budget_bytes is None else int(mem_budget_bytes)
        # per-tenant usage quotas (obs/usage.py): admission checks the
        # metered totals against these budgets; {} = unlimited.  A
        # malformed explicit spec raises at construction (a quota typo
        # must not silently admit forever); the env fallback is lax.
        self.quotas = usage.quotas_from_env() if quotas is None \
            else usage.parse_quotas(quotas)
        self.return_toa_lines = bool(return_toa_lines)
        self.get_toas_kw = dict(get_toas_kw or {})
        # decode-at-intake (docs/SERVICE.md): up to ``prefetch``
        # admitted requests have their FITS decode + bucket pad run on
        # the host-prefetch pool during the micro-batch window instead
        # of inside ``fit`` — the measured 21-27 ms load tail on the
        # warmed critical path (PERF.md §5).  0 disables (decode runs
        # inline in the fit worker, the pre-prefetch behavior).
        self.prefetch = max(0, int(prefetch))
        self._prefetcher = None
        self.quiet = quiet

        os.makedirs(workdir, exist_ok=True)
        self._tenant_root = os.path.join(workdir, "tenants")
        self._req_obs_dir = os.path.join(workdir, "obs_requests")
        os.makedirs(self._tenant_root, exist_ok=True)
        os.makedirs(self._req_obs_dir, exist_ok=True)

        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._tenants = {}
        self._requests = {}       # open requests by id
        self._done_requests = {}  # terminal requests, bounded FIFO
        self._done_order = []
        self._done_keep = 4096
        self._buckets = {}
        self._draining = False
        # recent submit timestamps: the arrival-rate signal the
        # adaptive parking window stretches on (bounded, lock-held)
        self._recent_submits = collections.deque(maxlen=64)
        self._stopped = threading.Event()
        self._drained = threading.Event()
        self._thread = None
        self._obs_stack = contextlib.ExitStack()
        self._batch_seq = itertools.count(1)
        self.t_start = None
        self.warm_summary = None

    # -- lifecycle ------------------------------------------------------

    def start(self):
        """Open the daemon obs run, recover accepted-but-unfinished
        requests from the tenant ledgers, start the dispatcher."""
        if self._thread is not None:
            raise RuntimeError("TOAService already started")
        self.t_start = time.time()
        self._obs_stack.enter_context(obs.run(
            "ppserve", base_dir=os.path.join(self.workdir, "obs"),
            config={"modelfile": self.modelfile,
                    "narrowband": self.narrowband,
                    "batch_window_s": self.batch_window_s,
                    "solo_window_s": self.solo_window_s,
                    "batch_max": self.batch_max,
                    "tenant_max_inflight": self.tenant_max_inflight,
                    "tenant_max_queue": self.tenant_max_queue,
                    "max_attempts": self.max_attempts,
                    "run_dirs_max": self.run_dirs_max,
                    "run_bytes_max": self.run_bytes_max,
                    "mem_budget_bytes": self.mem_budget_bytes,
                    "quotas": self.quotas or None,
                    "prefetch": self.prefetch}))
        if self.quotas:
            # install the budgets on the usage plane: metering keeps
            # the pps_quota_burn gauge live for the quota_burn rule
            usage.configure_quotas(self.quotas)
        if self.mem_budget_bytes:
            # the memory_watermark health rule prices device usage
            # against this budget gauge (obs/health.py)
            metrics.set_gauge("pps_mem_budget_bytes",
                              self.mem_budget_bytes)
        # prime the alert-rule engine so the exporter evaluates on
        # every snapshot tick from the first one
        obs_health.evaluate()
        if self.prefetch:
            # before recovery: recovered requests prefetch like fresh
            # ones, so a restarted daemon's first cycle is warm too
            self._prefetcher = HostPrefetcher(depth=self.prefetch,
                                              name="ppserve-prefetch")
        self._recover_tenants()
        self._thread = threading.Thread(target=self._dispatcher,
                                        name="ppserve-dispatcher",
                                        daemon=True)
        self._thread.start()
        obs.event("service_started", workdir=self.workdir,
                  n_tenants=len(self._tenants))
        return self

    def warm(self, coalesce=None, aot=True):
        """Warm every program the startup plan enumerates
        (service/warm.py); stores + returns the summary."""
        from .warm import warm_plan

        if self.plan is None:
            return None
        if coalesce is None:
            # every cycle size a full-rate tenant mix can produce: the
            # batch-glue programs key on the raw combined batch, so
            # K=2..batch_max each warm their own total (warm.py)
            coalesce = tuple(range(2, self.batch_max + 1))
        self.warm_summary = warm_plan(
            self.plan, self.modelfile, get_toas_kw=self.get_toas_kw,
            coalesce=coalesce, aot=aot, narrowband=self.narrowband,
            quiet=self.quiet)
        rec = obs.current()
        if rec is not None:
            # the warm-path proof marker: everything compiled so far
            # happened before the first request (docs/SERVICE.md)
            obs.gauge("warm_backend_compiles",
                      int(rec.counters.get("backend_compiles", 0)))
        # compile-cache misses after this point are a warm-path leak:
        # arm the compile_cache_postwarm health rule's guard
        metrics.set_gauge("pps_warm_complete", 1)
        return self.warm_summary

    def request_drain(self):
        """Stop accepting; finish everything accepted; then stop."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
            self._cond.notify_all()
        obs.event("service_drain")
        obs.counter("service_drains")
        metrics.set_gauge("pps_draining", 1)

    def drained(self, timeout=None):
        """Block until a drain completed; True when it has."""
        return self._drained.wait(timeout)

    def shutdown(self, timeout=60.0):
        """Drain and stop the dispatcher; close obs state.  Returns
        True when the drain completed in time."""
        if self._thread is None:
            self._drained.set()
        self.request_drain()
        ok = self._drained.wait(timeout)
        self._stopped.set()
        with self._lock:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if self._prefetcher is not None:
            self._prefetcher.stop()
            self._prefetcher = None
        with self._lock:
            tenants = list(self._tenants.values())
            requests = list(self._requests.values())
        for rq in requests:
            self._close_request_recorder(rq)
        for t in tenants:
            t.queue.close()
        obs.event("service_stopped", drained=bool(ok))
        self._obs_stack.close()
        return ok

    # -- intake ---------------------------------------------------------

    def _tenant(self, name):
        """Get-or-create a tenant (caller holds the lock)."""
        t = self._tenants.get(name)
        if t is None:
            t = _Tenant(name, self._tenant_root, self.max_attempts,
                        self.backoff_s)
            self._tenants[name] = t
        return t

    def _recover_tenants(self):
        """Re-enqueue ledger entries a previous daemon accepted but
        never finished (crash/drain leftovers): the accepted-work
        contract survives restarts."""
        if not os.path.isdir(self._tenant_root):
            return
        recovered = []
        for name in sorted(os.listdir(self._tenant_root)):
            if not _TENANT_RE.match(name) or not os.path.isfile(
                    os.path.join(self._tenant_root, name,
                                 "ledger.0.jsonl")):
                continue
            with self._lock:
                t = self._tenant(name)
                for key in t.queue.outstanding():
                    rq = self._new_request(t, key, key, {},
                                           recovered=True)
                    recovered.append(rq)
        for rq in recovered:
            # header scan outside the lock (file IO); unreadable
            # leftovers quarantine exactly like a fresh submission's
            if self._classify(rq):
                self._maybe_prefetch(rq)
                self._emit_request(rq, "recovered")

    def _maybe_prefetch(self, rq):
        """Decode-at-intake: hand a freshly admitted request's FITS
        decode + bucket pad to the prefetch pool so it overlaps the
        micro-batch window instead of extending ``fit``.  Best-effort —
        past ``depth`` live tickets :meth:`~HostPrefetcher.try_submit`
        refuses and the request simply decodes inline at fit time, the
        pre-prefetch behavior."""
        pf = self._prefetcher
        if pf is None or rq.bucket is None or rq.ticket is not None \
                or rq.t_done is not None:
            return
        kw = dict(self.get_toas_kw)
        kw.update(rq.config or {})
        rq.ticket = pf.try_submit(
            rq.path,
            functools.partial(load_bucketed_databunch, rq.path,
                              tuple(rq.bucket),
                              tscrunch=bool(kw.get("tscrunch", False)),
                              quiet=self.quiet),
            est_bytes=estimate_archive_bytes(rq.nchan, rq.nbin,
                                             nsub=rq.nsub),
            ctx=rq.ctx())

    def _new_request(self, tenant, path, key, config, recovered=False,
                     traceparent=None, priority=0, deadline_s=None):
        """Register an open request (caller holds the lock)."""
        rq = Request("r%06d" % next(_REQ_SEQ), tenant.name, path, key,
                     config, priority=priority, deadline_s=deadline_s)
        rq.recovered = recovered
        self._recent_submits.append(rq.t_submit)
        # join the client's trace (traceparent carrier) or mint a new
        # one: every accepted request is traceable, client-aware or not
        ctx = tracing.parse_traceparent(traceparent)
        if ctx is not None:
            rq.trace_id, rq.parent_span_id = ctx
        else:
            rq.trace_id = tracing.new_trace_id()
        self._requests[rq.id] = rq
        tenant.fifo.append(rq.id)
        tenant.n_submitted += 1
        metrics.inc("pps_requests_total", tenant=tenant.name,
                    outcome="accepted")
        metrics.set_gauge("pps_queue_depth", len(tenant.fifo),
                          tenant=tenant.name)
        metrics.set_gauge("pps_open_requests", len(self._requests))
        self._open_request_recorder(rq)
        self._cond.notify_all()
        return rq

    def submit(self, tenant, archive, config=None, wait=False,
               timeout=None, traceparent=None, priority=0,
               deadline_s=None):
        """Accept one TOA request; returns the response payload.

        ``priority`` (int, higher = more urgent) orders cycle seeding;
        ``deadline_s`` is a completion budget from submit time: the
        dispatcher never parks the request past ``PARK_FRACTION`` of
        it, and a terminal result past it counts a deadline miss
        (``pps_deadline_total``).

        Replays: an archive this tenant's ledger already records as
        done/quarantined responds with the recorded outcome instead of
        refitting (the checkpoint holds its TOA block).  Rejections
        (``ok: False``): bad tenant name, unreadable archive header
        recorded as an immediate quarantine, ``backpressure`` beyond
        the tenant's open-request budget, ``draining`` after a drain
        began.

        ``traceparent`` (W3C carrier string, obs/tracing.py) threads
        the caller's trace through the whole request lifecycle; without
        one the daemon mints a trace of its own.  Replays echo the
        recorded outcome's trace id so a duplicate submission is
        causally linked to the fit that actually served it.
        """
        if not _TENANT_RE.match(str(tenant or "")):
            return {"ok": False, "error": "bad_tenant",
                    "detail": "tenant must match %s" % _TENANT_RE.pattern}
        path = str(archive)
        key = WorkQueue.key_for(path)
        with self._lock:
            if self._draining:
                metrics.inc("pps_requests_total", tenant=tenant,
                            outcome="rejected_draining")
                return {"ok": False, "error": "draining"}
            t = self._tenant(tenant)
            state = t.queue.state(key)
            if state in (DONE, QUARANTINED):
                rec = t.queue.record(key) or {}
                obs.counter("service_replays")
                metrics.inc("pps_requests_total", tenant=tenant,
                            outcome="replayed")
                obs.event("service_replay", tenant=tenant,
                          archive=path, state=state,
                          trace_id=rec.get("trace"),
                          replay_traceparent=traceparent)
                return {"ok": True, "request_id": None, "cached": True,
                        "tenant": tenant, "archive": path,
                        "state": state,
                        "n_toas": rec.get("n_toas"),
                        "trace_id": rec.get("trace"),
                        "reason": rec.get("reason")}
            for rid in t.fifo:
                rq = self._requests[rid]
                if rq.key == key:  # already accepted: attach to it
                    break
            else:
                rq = None
            if rq is None:
                if len(t.fifo) >= self.tenant_max_queue:
                    t.n_rejected += 1
                    obs.event("service_backpressure", tenant=tenant,
                              archive=path, open=len(t.fifo))
                    obs.counter("service_backpressure_rejections")
                    metrics.inc("pps_requests_total", tenant=tenant,
                                outcome="rejected_backpressure")
                    metrics.inc("pps_backpressure_total",
                                tenant=tenant)
                    return {"ok": False, "error": "backpressure",
                            "tenant": tenant, "open": len(t.fifo)}
                rq = self._new_request(t, path, key, config,
                                       traceparent=traceparent,
                                       priority=priority,
                                       deadline_s=deadline_s)
                obs.counter("service_requests")
        if rq.bucket is None:
            if self._classify(rq):
                rejection = self._memory_admission(rq) \
                    or self._quota_admission(rq)
                if rejection is not None:
                    return rejection
                self._maybe_prefetch(rq)
            # else: header scan failed — quarantined at intake, like
            # the survey planner's unreadable-archive path
        self._emit_request(rq, "submitted")
        if wait:
            rq.done_evt.wait(timeout)
        return rq.payload()

    def _memory_admission(self, rq):
        """Memory-aware admission (docs/SERVICE.md): settle a freshly
        classified request at intake when its analytical footprint
        estimate exceeds the configured device budget — dispatching it
        would OOM deterministically, burning a device cycle and a
        retry budget to learn what the plan already knows.  Returns
        the ``rejected_memory`` payload, or None when admitted."""
        budget = self.mem_budget_bytes
        if budget <= 0 or rq.bucket is None:
            return None
        est = estimate_archive_bytes(rq.nchan, rq.nbin, nsub=rq.nsub)
        if est <= budget:
            return None
        reason = ("memory: estimated %d bytes exceeds device budget %d"
                  % (est, budget))
        with self._lock, tracing.activate(rq.ctx()):
            t = self._tenants[rq.tenant]
            t.queue.quarantine(rq.path, reason)
            self._finalize_locked(rq, QUARANTINED, reason)
        metrics.inc("pps_requests_total", tenant=rq.tenant,
                    outcome="rejected_memory")
        obs.event("service_memory_reject", tenant=rq.tenant,
                  archive=rq.path, request=rq.id, est_bytes=est,
                  budget_bytes=budget, bucket="%dx%d" % rq.bucket,
                  nsub=rq.nsub)
        obs.counter("service_memory_rejections")
        return {"ok": False, "error": "memory", "tenant": rq.tenant,
                "archive": rq.path, "request_id": rq.id,
                "est_bytes": est, "budget_bytes": budget}

    def _quota_admission(self, rq):
        """Quota admission (obs/usage.py): settle a freshly classified
        request at intake when its tenant has exhausted a configured
        budget against the locally metered usage.  Quarantine-at-
        submit, like the memory shed: the rejection lands in the
        tenant ledger, so a duplicate submit replays it without
        burning another admission — and without re-metering.  Returns
        the ``rejected_quota`` payload, or None when admitted."""
        if not self.quotas:
            return None
        breach = usage.check(rq.tenant, self.quotas)
        if breach is None:
            return None
        reason = ("quota: %s used %s of limit %s"
                  % (breach["quota"], breach["used"], breach["limit"]))
        with self._lock, tracing.activate(rq.ctx()):
            t = self._tenants[rq.tenant]
            t.queue.quarantine(rq.path, reason)
            self._finalize_locked(rq, QUARANTINED, reason)
        metrics.inc("pps_requests_total", tenant=rq.tenant,
                    outcome="rejected_quota")
        metrics.inc("pps_shed_total", reason="quota")
        obs.event("service_quota_reject", tenant=rq.tenant,
                  archive=rq.path, request=rq.id, **breach)
        obs.counter("service_quota_rejections")
        return {"ok": False, "error": "quota", "tenant": rq.tenant,
                "archive": rq.path, "request_id": rq.id, **breach}

    def _classify(self, rq):
        """Header-scan the archive into its shape bucket; quarantine on
        failure.  Returns True when the request is fittable."""
        if rq.bucket is not None or rq.t_done is not None:
            return rq.bucket is not None
        try:
            info = scan_archive_header(rq.path)
        except (OSError, ValueError, KeyError,
                faults.InjectedFault) as e:
            with self._lock, tracing.activate(rq.ctx()):
                t = self._tenants[rq.tenant]
                if t.queue.state(rq.key) is None:
                    t.queue.add([rq.path])
                t.queue.quarantine(rq.path,
                                   "unreadable at intake: %s" % e)
                self._finalize_locked(rq, QUARANTINED,
                                      "unreadable at intake: %s" % e)
            return False
        with self._lock, tracing.activate(rq.ctx()):
            rq.nsub, rq.nchan, rq.nbin = info.nsub, info.nchan, info.nbin
            rq.bucket = canonical_shape(info.nchan, info.nbin)
            try:
                # the bytes-decoded usage measure (obs/usage.py): the
                # archive the fit will decode, billed at finalize
                rq.bytes_in = os.path.getsize(rq.path)
            except OSError:
                rq.bytes_in = 0
            t = self._tenants[rq.tenant]
            if t.queue.state(rq.key) is None:
                t.queue.add([rq.path])
            self._cond.notify_all()
        return True

    def wait(self, request_id, timeout=None):
        with self._lock:
            rq = self._requests.get(request_id) \
                or self._done_requests.get(request_id)
        if rq is None:
            return {"ok": False, "error": "unknown_request",
                    "request_id": request_id}
        rq.done_evt.wait(timeout)
        return rq.payload()

    # -- scheduling -----------------------------------------------------

    def _ready_locked(self, rq, now):
        if rq.state != PENDING or rq.bucket is None:
            return False
        t = self._tenants[rq.tenant]
        rec = t.queue.record(rq.key)
        if rec is None:
            return False
        if rec["state"] == FAILED:
            return now >= rec.get("retry_at", 0.0)
        return rec["state"] not in (DONE, QUARANTINED)

    @staticmethod
    def _seed_key(rq):
        """Cycle-seeding order: highest priority class first; within
        a class the nearest park cutoff (deadline-bearing requests),
        then oldest.  Deadline-free requests sort by age alone, the
        pre-deadline behavior."""
        cut = rq.park_cutoff()
        return (-rq.priority,
                cut if cut is not None else float("inf"),
                rq.t_submit)

    def _joinable_locked(self, batch, seed):
        """Could waiting grow this cycle?  True when any other open
        request might still land in the seed's bucket (unclassified
        requests count: their bucket is not known yet)."""
        members = {rq.id for rq in batch}
        for rq in self._requests.values():
            if rq.id in members or rq.state != PENDING:
                continue
            if rq.bucket is not None and rq.bucket != seed.bucket:
                continue
            return True
        return False

    def _fire_at_locked(self, batch, seed, now):
        """Absolute dispatch time for the assembled cycle — the
        adaptive parking window (docs/SERVICE.md "Deadline
        semantics"):

        * base window anchored at the seed's submit time;
        * stretched up to ``WINDOW_STRETCH_MAX``× by the recent
          arrival rate (denser batches under load);
        * collapsed to ``solo_window_s`` when nothing else can join
          (a solo late arriver never pays the full window);
        * clamped to the earliest member's park cutoff — a request is
          never parked past ``PARK_FRACTION`` of its deadline.
        """
        window = self.batch_window_s
        if window > 0:
            if len(batch) == 1 and not self._joinable_locked(batch,
                                                             seed):
                window = self.solo_window_s
            else:
                cutoff = now - _LOAD_WINDOW_S
                arrivals = sum(1 for t in self._recent_submits
                               if t >= cutoff)
                stretch = min(WINDOW_STRETCH_MAX,
                              1.0 + arrivals / float(self.batch_max))
                window *= stretch
        t_fire = seed.t_submit + window
        for rq in batch:
            cut = rq.park_cutoff()
            if cut is not None:
                t_fire = min(t_fire, cut)
        return t_fire

    def _collect_batch(self):
        """Assemble the next micro-batch: seed by priority class /
        park cutoff / age (:meth:`_seed_key`), fill with same-bucket
        ready requests (seed order, per-tenant inflight cap), and hold
        the cycle open until the adaptive window expires
        (:meth:`_fire_at_locked`) or the batch is full."""
        with self._lock:
            while True:
                if self._stopped.is_set():
                    return None
                now = time.time()
                ready = [rq for rid, rq in self._requests.items()
                         if self._ready_locked(rq, now)]
                if not ready:
                    if self._draining and not self._requests:
                        return None
                    # wake for the earliest backoff expiry, a new
                    # submission, or a drain
                    self._cond.wait(timeout=0.1)
                    continue
                seed = min(ready, key=self._seed_key)
                batch = self._fill_batch_locked(ready, seed)
                t_fire = self._fire_at_locked(batch, seed, now)
                if len(batch) >= self.batch_max or now >= t_fire:
                    for rq in batch:
                        rq.state = DISPATCHING
                        self._tenants[rq.tenant].inflight += 1
                    for name in {rq.tenant for rq in batch}:
                        metrics.set_gauge(
                            "pps_inflight",
                            self._tenants[name].inflight, tenant=name)
                    return batch
                self._cond.wait(timeout=max(0.01, t_fire - now))

    def _fill_batch_locked(self, ready, seed):
        per_tenant = {}
        batch = []
        for rq in sorted(ready, key=self._seed_key):
            if rq.bucket != seed.bucket:
                continue
            n = per_tenant.get(rq.tenant, 0)
            if n >= self.tenant_max_inflight:
                continue
            per_tenant[rq.tenant] = n + 1
            batch.append(rq)
            if len(batch) >= self.batch_max:
                break
        return batch

    def _dispatcher(self):
        try:
            while True:
                batch = self._collect_batch()
                if batch is None:
                    break
                self._dispatch(batch)
        finally:
            self._drained.set()
            with self._lock:
                self._cond.notify_all()

    def _dispatch(self, batch):
        batch_id = "b%05d" % next(self._batch_seq)
        bucket = self._bucket(batch[0].bucket)
        bucket.n_requests += len(batch)
        n_disp0 = bucket.batcher.n_dispatches
        with self._lock:
            for rq in batch:
                rq.batch_id = batch_id
                t = self._tenants[rq.tenant]
                with tracing.activate(rq.ctx()):
                    # the ambient context stamps the ledger's running
                    # record with the trace id (runner/queue.py)
                    claim = t.queue.claim(rq.path)
                rq.attempts = claim.get("attempts", 0)
        now = time.time()
        for rq in batch:
            # queue-wait: submission (or last retry release) to the
            # cycle that finally claimed the request
            wait_s = max(0.0, now - rq.t_submit)
            metrics.observe(PHASE_HISTOGRAM, wait_s,
                            phase="queue_wait", tenant=rq.tenant,
                            bucket=_blabel(rq.bucket),
                            exemplar=rq.trace_id)
            tracing.emit_span("queue_wait", wait_s, ctx=rq.ctx(),
                              request=rq.id, batch=batch_id)
            self._emit_request(rq, "dispatching")
        # deadline hint: a stalled cycle sibling cannot hold the
        # barrier past the most urgent member's completion deadline
        deadlines = [rq.deadline_at() for rq in batch]
        deadlines = [d for d in deadlines if d is not None]
        bucket.batcher.begin(len(batch),
                             deadline=min(deadlines) if deadlines
                             else None)
        workers = []
        for rq in batch:
            w = threading.Thread(target=self._run_one,
                                 args=(rq, bucket),
                                 name="ppserve-fit-%s" % rq.id,
                                 daemon=True)
            workers.append(w)
            w.start()
        for w in workers:
            w.join()
        obs.event("service_batch", batch=batch_id,
                  bucket="%dx%d" % bucket.key, n_requests=len(batch),
                  tenants=sorted({rq.tenant for rq in batch}),
                  dispatches=bucket.batcher.n_dispatches - n_disp0)

    def _bucket(self, key):
        with self._lock:
            b = self._buckets.get(tuple(key))
            if b is None:
                b = _Bucket(key, self.modelfile, self.batch_window_s)
                self._buckets[tuple(key)] = b
            return b

    def _run_one(self, rq, bucket):
        # the worker thread adopts the request's trace context: every
        # span/event/metric below — including the GetTOAs phase spans
        # and the batcher's park/dispatch — is causally stamped
        with tracing.activate(rq.ctx()):
            self._run_one_traced(rq, bucket)

    def _run_one_traced(self, rq, bucket):
        t = self._tenants[rq.tenant]
        blabel = _blabel(bucket.key)
        t0 = time.perf_counter()
        gt = bucket.checkout()
        checkout_s = time.perf_counter() - t0
        metrics.observe(PHASE_HISTOGRAM, checkout_s,
                        phase="checkout", bucket=blabel)
        tracing.emit_span("checkout", checkout_s, request=rq.id)
        gt.fit_batch = bucket.batcher.fit
        if rq.ticket is not None and self._prefetcher is not None:
            # decode-at-intake hand-off: the fit's own _load_archive
            # call site replays the prefetched outcome (data or fault)
            # exactly as if it had loaded inline.  A retry after a
            # consumed faulty ticket decodes inline, same as serial.
            ticket, rq.ticket = rq.ticket, None
            gt.preload(rq.path, self._prefetcher.consume(ticket))
        kw = dict(self.get_toas_kw)
        kw.update(rq.config or {})
        flags = dict(kw.get("addtnl_toa_flags") or {})
        flags.setdefault("pp_tenant", rq.tenant)
        kw["addtnl_toa_flags"] = flags
        padded = (rq.nchan, rq.nbin) != tuple(bucket.key)
        state = None
        # usage accounting (obs/usage.py): the fit phase is the
        # device-seconds measure; its peak footprint rides the memory
        # plane's watermark bracket.  Accumulated across attempts —
        # a retried request burned every attempt's device time.
        rec = obs.current()
        mem = rec.memory_state() if rec is not None else None
        mtok = mem.mark() if mem is not None else None
        tfit = time.perf_counter()
        try:
            with metrics.timed(PHASE_HISTOGRAM, phase="fit",
                               tenant=rq.tenant, bucket=blabel), \
                    obs.span("fit", request=rq.id, tenant=rq.tenant,
                             bucket=blabel), \
                    quality.context(bucket=blabel, tenant=rq.tenant):
                state = _fit_one(gt, t.queue, _Info(rq.path),
                                 t.checkpoint, padded, kw, self.quiet,
                                 narrowband=self.narrowband)
        except Exception as e:  # noqa: BLE001 — total per-request guard
            reason = "%s: %s" % (type(e).__name__, e)
            if memory.is_oom(e):
                # _fit_one classifies OOMs it sees itself; this covers
                # allocator exhaustion escaping around it (checkout
                # machinery, batch glue) — same quarantine-not-retry
                memory.record_oom("service_fit", e, request=rq.id,
                                  tenant=rq.tenant, archive=rq.path)
                rec = t.queue.quarantine(rq.path,
                                         "oom: %s" % reason[:400])
            else:
                rec = t.queue.fail(rq.path, reason)
            state = rec["state"]
        finally:
            rq.fit_s += time.perf_counter() - tfit
            if mem is not None and mtok is not None:
                pk = mem.peak(mtok)
                if pk:
                    rq.fit_peak_bytes = max(rq.fit_peak_bytes, pk)
            bucket.batcher.worker_done()
            n_toas = len(gt.TOA_list)
            lines = [format_toa_line(toa) for toa in gt.TOA_list] \
                if self.return_toa_lines else None
            # fingerprint BEFORE checkin: checkin resets the pooled
            # instance's result arrays for the next request
            rq.quality = quality.gt_fingerprint(gt)
            bucket.checkin(gt)
        self._settle(rq, state, n_toas, lines)

    def _settle(self, rq, state, n_toas, toa_lines):
        with self._lock:
            t = self._tenants[rq.tenant]
            t.inflight = max(0, t.inflight - 1)
            metrics.set_gauge("pps_inflight", t.inflight,
                              tenant=rq.tenant)
            rec = t.queue.record(rq.key) or {}
            state = rec.get("state", state)
            rq.attempts = rec.get("attempts", rq.attempts)
            if state in (DONE, QUARANTINED):
                if state == DONE:
                    rq.n_toas = n_toas
                    rq.toa_lines = toa_lines
                self._finalize_locked(rq, state, rec.get("reason"))
            else:
                rq.state = PENDING  # failed: backoff, then retried
                rq.reason = rec.get("reason")
                obs.counter("service_retries")
                metrics.inc("pps_retries_total", tenant=rq.tenant)
                self._emit_request(rq, "retrying")
            self._cond.notify_all()

    def _finalize_locked(self, rq, state, reason):
        if rq.t_done is not None:
            return  # already finalized (racing duplicate settle)
        if rq.ticket is not None and self._prefetcher is not None:
            # settled without the fit consuming its buffer (e.g. a
            # quarantine racing ahead of dispatch): drop it — no
            # ledger transition, the settle already wrote the record
            ticket, rq.ticket = rq.ticket, None
            self._prefetcher.discard(ticket, "settled_before_fit")
        rq.state = state
        rq.reason = reason
        rq.t_done = time.time()
        t = self._tenants[rq.tenant]
        if rq.id in t.fifo:
            t.fifo.remove(rq.id)
        t.n_completed += 1
        self._requests.pop(rq.id, None)
        # keep the terminal view queryable (wait/replay) under a
        # bounded budget — a resident process must not grow this map
        self._done_requests[rq.id] = rq
        self._done_order.append(rq.id)
        while len(self._done_order) > self._done_keep:
            self._done_requests.pop(self._done_order.pop(0), None)
        obs.counter("service_done" if state == DONE
                    else "service_quarantined")
        metrics.inc("pps_requests_total", tenant=rq.tenant,
                    outcome=state)
        total_s = max(0.0, rq.t_done - rq.t_submit)
        metrics.observe(PHASE_HISTOGRAM, total_s,
                        phase="total", tenant=rq.tenant,
                        bucket=_blabel(rq.bucket),
                        priority=str(rq.priority),
                        exemplar=rq.trace_id)
        if rq.deadline_s is not None:
            missed = total_s > rq.deadline_s
            metrics.inc("pps_deadline_total", tenant=rq.tenant,
                        outcome="miss" if missed else "met")
            if missed:
                obs.event("service_deadline_miss", request=rq.id,
                          tenant=rq.tenant, archive=rq.path,
                          deadline_s=rq.deadline_s,
                          wall_s=round(total_s, 6),
                          priority=rq.priority, state=state)
        # the daemon-side request span: the root every lifecycle child
        # (queue_wait/checkout/fit/...) parents on, itself a child of
        # the client's submit span when a traceparent arrived
        tracing.emit_span("request", total_s,
                          ctx=(rq.trace_id, rq.parent_span_id),
                          span_id=rq.span_id, request=rq.id,
                          tenant=rq.tenant, archive=rq.path,
                          state=state, batch=rq.batch_id,
                          attempts=rq.attempts)
        metrics.set_gauge("pps_queue_depth", len(t.fifo),
                          tenant=rq.tenant)
        metrics.set_gauge("pps_open_requests", len(self._requests))
        # bill the request exactly once, at the terminal transition:
        # a duplicate submit replays from the ledger and never gets
        # here again (obs/usage.py exactly-once accounting).  Metered
        # before the per-request recorder closes, and before waiters
        # wake — a quota check racing this finalize sees the bill.
        usage.meter("request", tenant=rq.tenant,
                    bucket=_blabel(rq.bucket), wall_s=total_s,
                    device_s=rq.fit_s, peak_bytes=rq.fit_peak_bytes,
                    archives=1 if state == DONE else 0,
                    bytes_decoded=rq.bytes_in, request=rq.id,
                    state=state, attempts=rq.attempts)
        self._emit_request(rq, "terminal")
        if state != DONE:
            # quarantine forensics: the terminal service_request event
            # above is already in the flight ring when the bundle is
            # cut, and the quarantine_spike health rule sees the inc
            metrics.inc("pps_quarantined_total", tenant=rq.tenant)
            flight.dump("quarantine", request=rq.id, tenant=rq.tenant,
                        archive=rq.path, reason=str(reason)[:200])
        self._close_request_recorder(rq)
        rq.done_evt.set()

    # -- per-request obs runs ------------------------------------------

    def _open_request_recorder(self, rq):
        try:
            rq.recorder = Recorder(
                "req-%s" % rq.id, self._req_obs_dir,
                config={"request": rq.id, "tenant": rq.tenant,
                        "archive": rq.path})
        except OSError:
            rq.recorder = None

    def _emit_request(self, rq, phase, **extra):
        fields = dict(request=rq.id, tenant=rq.tenant, archive=rq.path,
                      phase=phase, state=rq.state,
                      attempts=rq.attempts,
                      bucket=None if rq.bucket is None
                      else "%dx%d" % rq.bucket,
                      batch=rq.batch_id, reason=rq.reason,
                      trace_id=rq.trace_id, span_id=rq.span_id,
                      **extra)
        if rq.state == DONE:
            fields["n_toas"] = rq.n_toas
        if rq.t_done is not None:
            fields["wall_s"] = round(rq.t_done - rq.t_submit, 6)
        if phase == "terminal" and rq.quality is not None:
            fields["quality"] = rq.quality
        fields = {k: v for k, v in fields.items() if v is not None}
        obs.event("service_request", **fields)
        if rq.recorder is not None:
            rq.recorder.emit("event", name="service_request", **fields)

    def _close_request_recorder(self, rq):
        rec, rq.recorder = rq.recorder, None
        if rec is None:
            return
        rec.close()
        self._prune_request_runs()

    def _prune_request_runs(self):
        """Bound the retained per-request run dirs by count and bytes
        (oldest pruned first); open requests' runs are kept."""
        keep = {os.path.basename(rq.recorder.dir)
                for rq in self._requests.values()
                if rq.recorder is not None}
        try:
            names = os.listdir(self._req_obs_dir)
        except OSError:
            return
        entries = []
        total = 0
        for name in names:
            if name in keep:
                continue
            path = os.path.join(self._req_obs_dir, name)
            if not os.path.isdir(path):
                continue
            size = mtime = 0
            for root, _, files in os.walk(path):
                for f in files:
                    try:
                        st = os.stat(os.path.join(root, f))
                    except OSError:
                        continue
                    size += st.st_size
                    mtime = max(mtime, st.st_mtime)
            entries.append((mtime, path, size))
            total += size
        entries.sort()
        budget_dirs = self.run_dirs_max or 0
        budget_bytes = self.run_bytes_max or 0
        n_pruned = 0
        while entries and (
                (budget_dirs and len(entries) > budget_dirs)
                or (budget_bytes and total > budget_bytes)):
            _, path, size = entries.pop(0)
            shutil.rmtree(path, ignore_errors=True)
            total -= size
            n_pruned += 1
        if n_pruned:
            obs.counter("service_runs_pruned", n_pruned)

    # -- introspection --------------------------------------------------

    def metrics_snapshot(self):
        """Current streaming-metrics snapshot of the daemon's obs run
        (obs/metrics.py) — the ``metrics`` socket verb's payload; None
        when no run is active."""
        return metrics.snapshot()

    def status(self):
        with self._lock:
            tenants = {}
            for name, t in self._tenants.items():
                tenants[name] = {
                    "counts": t.queue.counts(),
                    "open": len(t.fifo), "inflight": t.inflight,
                    "submitted": t.n_submitted,
                    "completed": t.n_completed,
                    "rejected": t.n_rejected}
            buckets = {}
            for key, b in self._buckets.items():
                buckets["%dx%d" % key] = {
                    "requests": b.n_requests,
                    "dispatches": b.batcher.n_dispatches,
                    "coalesced": b.batcher.n_coalesced,
                    "fit_calls": b.batcher.n_calls,
                    "pool": len(b._pool)}
            out = {"ok": True,
                   "uptime_s": round(time.time() - (self.t_start
                                                    or time.time()), 3),
                   "draining": self._draining,
                   "open_requests": len(self._requests),
                   "tenants": tenants, "buckets": buckets,
                   "narrowband": self.narrowband,
                   "batch_window_s": self.batch_window_s,
                   "solo_window_s": self.solo_window_s,
                   "batch_max": self.batch_max}
        rec = obs.current()
        if rec is not None:
            out["counters"] = dict(rec.counters)
            out["obs_run"] = rec.dir
        if self.warm_summary is not None:
            out["warm"] = {k: self.warm_summary[k]
                           for k in ("n_programs", "wall_s",
                                     "backend_compiles",
                                     "compile_cache_hits",
                                     "compile_cache_misses")}
        return out

    def health(self):
        """Liveness/readiness + firing alerts — the ``health`` socket
        verb (docs/SERVICE.md), the probe surface a fleet router or
        autoscaler consumes.  Liveness is the dispatcher thread;
        readiness is "accepting new work" (live and not draining).
        Runs a fresh rule pass (obs/health.py) so the answer reflects
        now, not the last exporter tick."""
        alerts = obs_health.evaluate()
        if alerts is None:
            alerts = []
        live = self._thread is not None and self._thread.is_alive()
        with self._lock:
            draining = self._draining
            open_requests = len(self._requests)
        out = {"ok": live,
               "live": live,
               "ready": live and not draining,
               "draining": draining,
               "open_requests": open_requests,
               "alerts_firing": len(alerts),
               "alerts": alerts}
        rec = obs.current()
        if rec is not None:
            out["alerts_fired"] = int(
                rec.counters.get("alerts_fired", 0))
            out["postmortems_written"] = int(
                rec.counters.get("postmortems_written", 0))
            out["obs_run"] = rec.dir
        return out
