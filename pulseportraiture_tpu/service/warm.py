"""Service-side alias of the shared warm core (``runner/warm.py``).

ISSUE 15 moved the warm implementation into the runner so the daemon
(``ppserve warm``) and the batch engine (``ppsurvey warm`` /
``ppsurvey run --warm``) prime the SAME program enumeration against
the same persistent compile cache — see runner/warm.py for the design
and docs/RUNNER.md "Warm start" for the contract.  This module stays
as the service's import surface (``service.warm_plan`` etc. keep
working unchanged).
"""

from ..runner.warm import (  # noqa: F401
    WARM_WORKLOADS, WarmSpec, _bucket_freqs, _CompileWatch,
    _fit_kwargs, _synth_model, _warm_archive_spec, _warm_coalesced_spec,
    _WARM_EPHEMERIS, enable_persistent_cache, program_specs,
    solver_program, synth_databunch, warm_plan, write_warm_archive)

__all__ = ["WarmSpec", "program_specs", "warm_plan",
           "enable_persistent_cache", "synth_databunch"]
