"""Fleet front-end: bucket-routed serving across N ppserve daemons.

One :class:`TOAService` daemon is the single-host unit (daemon.py);
this module is the layer above it — the routing front the ROADMAP's
"heavy traffic" north-star needs.  A :class:`FleetRouter`:

* **spawns or adopts** N ``ppserve`` daemons, every one sharing the
  same persistent compile cache and warm plan, so the fleet pays the
  AOT compile exactly once and every replica starts fit-bound
  (PR 15's zero-cold-start contract, multiplied);
* **routes by shape bucket** — each submission is header-scanned
  router-side (``runner/plan.scan_archive_header``) and forwarded to
  the daemon that owns its ``(nchan, nbin)`` bucket, so same-bucket
  traffic from many tenants lands on ONE warm fitter pool and
  coalesces into dense micro-batches instead of spreading thin across
  replicas.  Bucket→daemon assignment is sticky; a load-based
  rebalance pass moves the coldest bucket off the hottest daemon when
  the open-request skew exceeds ``rebalance_delta``;
* **supervises** the fleet: a poll loop consumes each daemon's
  ``health`` verb (PR 17) and its process exit status; a daemon that
  dies or fails ``unhealthy_after`` consecutive probes is declared
  down, its buckets re-route to live daemons for NEW work, and it is
  respawned **in place** — same workdir, same per-tenant ledgers — so
  accepted-but-unfinished requests replay exactly once.  In-flight
  forwards that lose their connection retry against the SAME daemon
  after respawn (never a sibling): the ledger that accepted the work
  is the only one that can dedupe it;
* **sheds load** at the front door: fleet-level memory-aware
  admission (the PR 12 estimate against ``mem_budget_bytes``) and an
  optional fleet open-request ceiling reject requests the fleet would
  only queue or OOM on, before they burn a forward;
* **merges observability**: the ``metrics`` verb returns one
  :func:`~..obs.metrics.merge_snapshots` view over the router and
  every live daemon, and the router's own obs run records the fleet
  lifecycle (``router_*`` events) that ``tools/obs_report``'s
  "## fleet" section renders.

The router duck-types the :class:`~.server.ServiceServer` service
interface (submit/wait/status/health/metrics_snapshot/request_drain),
so the same JSONL-over-Unix-socket protocol serves both a daemon and
a fleet; ``request_id``s are namespaced ``d<i>:r<nnnnnn>`` so ``wait``
can find the owning daemon.

Host-side orchestration only — subprocess + socket + threading; no
device code (jaxlint J002 covers the ``service.*`` surface).
"""

import contextlib
import json
import os
import subprocess
import sys
import threading
import time

from .. import obs
from ..obs import metrics, usage
from ..obs import health as obs_health
from ..runner.plan import SurveyPlan, canonical_shape, \
    estimate_archive_bytes, scan_archive_header
from ..runner.respawn import PARK, RespawnPolicy, RespawnTracker
from .server import DEFAULT_SOCKET_NAME, client_request

__all__ = ["FleetRouter", "DEFAULT_ROUTER_SOCKET_NAME"]

DEFAULT_ROUTER_SOCKET_NAME = "pprouter.sock"

# ppserve readiness marker (cli/ppserve.py prints it; the smoke tools
# and this supervisor both key on it)
_READY_MARK = "PPSERVE_READY"


def _blabel(bucket):
    return "-" if bucket is None else "%dx%d" % tuple(bucket)


class _Daemon:
    """One supervised fleet member (spawned subprocess or adopted
    socket)."""

    __slots__ = ("idx", "name", "workdir", "socket", "proc", "ready",
                 "adopted", "fails", "open_requests", "buckets",
                 "n_routed", "respawns", "last_health", "pid",
                 "drain_sent")

    def __init__(self, idx, workdir, socket_path, adopted=False):
        self.idx = idx
        self.name = "d%d" % idx
        self.workdir = workdir
        self.socket = socket_path
        self.proc = None
        self.ready = threading.Event()
        self.adopted = adopted
        self.fails = 0
        self.open_requests = 0
        self.buckets = set()
        self.n_routed = 0
        self.respawns = 0
        self.last_health = None
        self.pid = None
        self.drain_sent = False

    def load(self):
        """Routing load score: open requests dominate; bucket count
        breaks ties so fresh buckets spread before traffic does."""
        return (self.open_requests, len(self.buckets), self.idx)


class FleetRouter:
    """The fleet front-end (module docstring).

    In-process API mirrors :class:`~.daemon.TOAService` so
    :class:`~.server.ServiceServer` can serve it unchanged:
    :meth:`start`, :meth:`submit`, :meth:`wait`, :meth:`status`,
    :meth:`health`, :meth:`metrics_snapshot`, :meth:`request_drain`,
    :meth:`drained`, :meth:`shutdown`.
    """

    def __init__(self, modelfile, workdir, n_daemons=3, plan=None,
                 compile_cache=None, warm=True, batch_window_s=0.25,
                 batch_max=8, solo_window_s=0.1, mem_budget_bytes=None,
                 quotas=None, fleet_max_open=0, health_interval_s=1.0,
                 unhealthy_after=2, rebalance_delta=8,
                 respawn_timeout_s=300.0, forward_attempts=3,
                 adopt_sockets=None, daemon_args=None, daemon_env=None,
                 flap_count=5, flap_window_s=60.0, quiet=True):
        self.modelfile = modelfile
        self.workdir = workdir
        self.compile_cache = compile_cache
        self.warm = bool(warm)
        self.batch_window_s = float(batch_window_s)
        self.batch_max = int(batch_max)
        self.solo_window_s = float(solo_window_s)
        self.mem_budget_bytes = int(mem_budget_bytes or 0)
        # per-tenant usage quotas (obs/usage.py): enforced at the
        # router's own admission over its metered forwards, AND
        # propagated to every spawned daemon (--quotas), whose
        # device-seconds metering is the authoritative enforcement
        self.quotas = usage.quotas_from_env() if quotas is None \
            else usage.parse_quotas(quotas)
        self.fleet_max_open = int(fleet_max_open or 0)
        self.health_interval_s = float(health_interval_s)
        self.unhealthy_after = max(1, int(unhealthy_after))
        self.rebalance_delta = max(1, int(rebalance_delta))
        self.respawn_timeout_s = float(respawn_timeout_s)
        self.forward_attempts = max(1, int(forward_attempts))
        # extra ppserve-start argv for every spawn (e.g. --no_bary)
        self.daemon_args = list(daemon_args or [])
        # extra env for the FIRST spawn of each daemon (the chaos
        # hook: fleet_smoke injects a sigkill clause here); respawns
        # scrub PPTPU_FAULTS — a replacement must come back clean
        self.daemon_env = dict(daemon_env or {})
        self.quiet = quiet

        os.makedirs(workdir, exist_ok=True)
        if isinstance(plan, SurveyPlan):
            path = os.path.join(workdir, "fleet_plan.json")
            plan.save(path)
            plan = path
        self.plan_path = plan

        self._daemons = []
        self._by_name = {}
        if adopt_sockets:
            for i, sock in enumerate(adopt_sockets):
                d = _Daemon(i, os.path.dirname(sock), sock,
                            adopted=True)
                self._daemons.append(d)
        else:
            for i in range(max(1, int(n_daemons))):
                wd = os.path.join(workdir, "d%d" % i)
                d = _Daemon(i, wd,
                            os.path.join(wd, DEFAULT_SOCKET_NAME))
                self._daemons.append(d)
        self._by_name = {d.name: d for d in self._daemons}
        # crash-loop guard (runner/respawn.py): zero backoff keeps the
        # below-threshold path exactly the old immediate in-place
        # respawn; a daemon that dies flap_count times inside
        # flap_window_s is parked (router_flap) instead of burning CPU
        policy = RespawnPolicy(backoff_s=0.0,
                               flap_count=max(1, int(flap_count)),
                               flap_window_s=float(flap_window_s))
        self._flap = {d.name: RespawnTracker(policy, key=d.name)
                      for d in self._daemons}

        self._lock = threading.Lock()
        self._assign = {}          # bucket -> _Daemon
        self._bucket_routed = {}   # bucket -> routed count (rebalance)
        self._draining = False
        self._stop = threading.Event()
        self._drained = threading.Event()
        self._thread = None
        self._obs_stack = contextlib.ExitStack()
        self.t_start = None

    # -- lifecycle ------------------------------------------------------

    def start(self, ready_timeout=600.0):
        """Open the router obs run, bring the fleet up (spawn or
        adopt), start the supervisor.  Blocks until every daemon is
        ready (or ``ready_timeout`` expires — stragglers keep coming
        up under supervision)."""
        if self._thread is not None:
            raise RuntimeError("FleetRouter already started")
        self.t_start = time.time()
        self._obs_stack.enter_context(obs.run(
            "pprouter", base_dir=os.path.join(self.workdir, "obs"),
            config={"modelfile": self.modelfile,
                    "n_daemons": len(self._daemons),
                    "plan": self.plan_path,
                    "compile_cache": self.compile_cache,
                    "mem_budget_bytes": self.mem_budget_bytes,
                    "quotas": self.quotas or None,
                    "fleet_max_open": self.fleet_max_open,
                    "batch_window_s": self.batch_window_s,
                    "batch_max": self.batch_max}))
        if self.quotas:
            usage.configure_quotas(self.quotas)
        obs_health.evaluate()
        for d in self._daemons:
            if d.adopted:
                # adopted daemons are someone else's processes: probe
                # once, then supervise like any other (no respawn);
                # fleet-lifecycle events, no one request trace to
                # adopt (jaxlint J008)
                threading.Thread(target=self._probe_adopted,  # jaxlint: disable=J008
                                 args=(d,), daemon=True,
                                 name="pprouter-adopt-%s" % d.name
                                 ).start()
            else:
                self._spawn(d, first=True)
        deadline = time.time() + float(ready_timeout)
        for d in self._daemons:
            d.ready.wait(timeout=max(0.0, deadline - time.time()))
        self._thread = threading.Thread(target=self._supervise,
                                        name="pprouter-supervisor",
                                        daemon=True)
        self._thread.start()
        obs.event("router_started", workdir=self.workdir,
                  n_daemons=len(self._daemons),
                  ready=sum(1 for d in self._daemons
                            if d.ready.is_set()))
        self._publish_gauges()
        return self

    def _probe_adopted(self, d):
        try:
            h = client_request(d.socket, {"op": "health"},
                               timeout=10.0)
        except (OSError, ValueError):
            return
        if h.get("live"):
            d.pid = None
            d.last_health = h
            d.ready.set()
            obs.event("router_daemon_ready", daemon=d.name,
                      socket=d.socket, adopted=True)

    def _daemon_cmd(self):
        cmd = [sys.executable, "-m",
               "pulseportraiture_tpu.cli.ppserve", "start",
               "-m", self.modelfile,
               "--window", str(self.batch_window_s),
               "--solo-window", str(self.solo_window_s),
               "--batch", str(self.batch_max)]
        if self.plan_path:
            cmd += ["--plan", self.plan_path]
            if self.warm:
                cmd += ["--warm"]
        if self.compile_cache:
            cmd += ["--compile-cache", self.compile_cache]
        if self.quotas:
            # every daemon enforces the same budgets over its OWN
            # metered usage (per-enforcement-point totals)
            cmd += ["--quotas", json.dumps(self.quotas)]
        if self.quiet:
            cmd += ["--quiet"]
        cmd += self.daemon_args
        return cmd

    def _spawn(self, d, first):
        """Launch (or relaunch) one daemon; a waiter thread flips
        ``d.ready`` when the PPSERVE_READY marker appears."""
        os.makedirs(d.workdir, exist_ok=True)
        env = dict(os.environ)
        if first:
            env.update(self.daemon_env)
        else:
            # a respawn must come back clean: one-shot chaos clauses
            # (sigkill specs) died with the process they killed
            env.pop("PPTPU_FAULTS", None)
        log = open(os.path.join(d.workdir, "daemon.log"), "ab")
        try:
            d.proc = subprocess.Popen(
                self._daemon_cmd() + ["-w", d.workdir],
                stdout=subprocess.PIPE, stderr=log, env=env)
        finally:
            log.close()
        # ready-marker watcher: fleet-lifecycle telemetry only, no
        # request trace to adopt (jaxlint J008)
        threading.Thread(target=self._wait_ready, args=(d, first),  # jaxlint: disable=J008
                         daemon=True,
                         name="pprouter-wait-%s" % d.name).start()

    def _wait_ready(self, d, first):
        proc = d.proc
        marked = False
        for raw in proc.stdout:
            line = raw.decode("utf-8", errors="replace").strip()
            if not marked and line.startswith(_READY_MARK):
                try:
                    info = json.loads(line[len(_READY_MARK):].strip())
                except (json.JSONDecodeError, ValueError):
                    info = {}
                d.pid = info.get("pid", proc.pid)
                d.fails = 0
                d.last_health = None
                d.ready.set()
                marked = True
                obs.event("router_daemon_ready", daemon=d.name,
                          pid=d.pid, warmed=info.get("warmed"),
                          respawn=not first)
                self._publish_gauges()
            # keep draining stdout either way: a full pipe would
            # wedge the daemon on its next print
        if not marked:
            obs.event("router_daemon_down", daemon=d.name,
                      reason="spawn_failed",
                      returncode=proc.poll())

    # -- supervision ----------------------------------------------------

    def _supervise(self):
        while not self._stop.wait(self.health_interval_s):
            try:
                self._poll_once()
            except Exception:  # noqa: BLE001 — supervisor never dies
                pass
        self._drained.set()

    def _poll_once(self):
        for d in self._daemons:
            if not d.ready.is_set():
                continue  # spawning/respawning; the waiter owns it
            if d.proc is not None and d.proc.poll() is not None:
                if self._draining:
                    continue  # drained exit is the expected path
                self._daemon_down(
                    d, "exit_%s" % d.proc.returncode)
                continue
            try:
                h = client_request(d.socket, {"op": "health"},
                                   timeout=10.0)
            except (OSError, ValueError) as e:
                d.fails += 1
                if d.fails >= self.unhealthy_after \
                        and not self._draining:
                    self._daemon_down(d, "health_unreachable: %s"
                                      % type(e).__name__)
                continue
            d.fails = 0
            d.last_health = h
            d.open_requests = int(h.get("open_requests") or 0)
            if not h.get("live") and not self._draining:
                self._daemon_down(d, "not_live")
        self._rebalance()
        self._publish_gauges()
        obs_health.evaluate()

    def _publish_gauges(self):
        metrics.set_gauge("pps_fleet_daemons",
                          sum(1 for d in self._daemons
                              if d.ready.is_set()))
        metrics.set_gauge("pps_fleet_open_requests",
                          sum(d.open_requests for d in self._daemons
                              if d.ready.is_set()))

    def _daemon_down(self, d, reason):
        """Declare a daemon dead: re-route its buckets for new work,
        respawn it in place (same workdir → same ledgers → replay is
        exactly-once).  Callable from the supervisor AND from a
        forwarder that noticed the death first — the check-and-clear
        under the lock makes it fire once."""
        with self._lock:
            if not d.ready.is_set():
                return
            d.ready.clear()
            d.open_requests = 0
        obs.event("router_daemon_down", daemon=d.name, reason=reason,
                  pid=d.pid)
        with self._lock:
            moved = []
            for bucket in sorted(d.buckets):
                target = self._pick_locked(exclude=d)
                if target is None:
                    continue  # nowhere to go; forwards wait on respawn
                self._assign[bucket] = target
                target.buckets.add(bucket)
                moved.append((bucket, target.name))
            for bucket, _ in moved:
                d.buckets.discard(bucket)
        for bucket, target in moved:
            obs.event("router_rebalance", bucket=_blabel(bucket),
                      src=d.name, dst=target, cause="daemon_down")
        if d.proc is not None:
            # make sure a half-dead process is fully gone before its
            # replacement binds the same socket path
            with contextlib.suppress(OSError):
                d.proc.kill()
            with contextlib.suppress(Exception):
                d.proc.wait(timeout=10.0)
        if d.adopted or self._draining:
            return
        verdict = self._flap[d.name].record_death(time.time())
        if verdict["action"] == PARK:
            # crash-looping daemon: park it instead of respawning
            # forever — the fleet degrades onto the survivors (its
            # buckets were just re-routed above)
            obs.event("router_flap", daemon=d.name,
                      deaths=verdict.get("deaths"),
                      window_s=verdict.get("window_s"),
                      respawns=d.respawns)
            self._publish_gauges()
            return
        d.respawns += 1
        obs.counter("router_respawns")
        metrics.inc("pps_respawns_total", daemon=d.name)
        obs.event("router_respawn", daemon=d.name, reason=reason,
                  respawns=d.respawns)
        self._spawn(d, first=False)
        self._publish_gauges()

    def _rebalance(self):
        """Load-based rebalance: when the open-request skew between
        the hottest and coldest ready daemon exceeds
        ``rebalance_delta``, move the hottest daemon's
        least-trafficked bucket to the coldest (new work only —
        accepted work stays on the ledger that owns it)."""
        with self._lock:
            ready = [d for d in self._daemons if d.ready.is_set()]
            if len(ready) < 2:
                return
            hot = max(ready, key=lambda d: d.open_requests)
            cold = min(ready, key=lambda d: d.open_requests)
            if hot.open_requests - cold.open_requests \
                    < self.rebalance_delta:
                return
            if len(hot.buckets) < 2:
                return  # moving its only bucket just moves the spot
            bucket = min(hot.buckets,
                         key=lambda b: self._bucket_routed.get(b, 0))
            hot.buckets.discard(bucket)
            cold.buckets.add(bucket)
            self._assign[bucket] = cold
        obs.counter("router_rebalances")
        metrics.inc("pps_rebalances_total")
        obs.event("router_rebalance", bucket=_blabel(bucket),
                  src=hot.name, dst=cold.name, cause="load",
                  hot_open=hot.open_requests,
                  cold_open=cold.open_requests)

    # -- routing --------------------------------------------------------

    def _pick_locked(self, exclude=None):
        ready = [d for d in self._daemons
                 if d.ready.is_set() and d is not exclude]
        if not ready:
            return None
        return min(ready, key=_Daemon.load)

    def _owner(self, bucket):
        """The daemon owning ``bucket`` (sticky; assigned to the
        least-loaded ready daemon on first sight).  Unclassifiable
        archives (bucket None) go wherever load is lowest — the
        daemon's intake quarantine owns them."""
        with self._lock:
            if bucket is None:
                return self._pick_locked()
            d = self._assign.get(bucket)
            if d is None:
                d = self._pick_locked()
                if d is None:
                    return None
                self._assign[bucket] = d
                d.buckets.add(bucket)
                obs.event("router_assign", bucket=_blabel(bucket),
                          daemon=d.name)
            return d

    def _classify(self, archive):
        """(bucket, est_bytes) from a router-side header scan; both
        None when the archive is unreadable (the daemon quarantines
        it at intake)."""
        try:
            info = scan_archive_header(archive)
        except (OSError, ValueError, KeyError):
            return None, None
        return (canonical_shape(info.nchan, info.nbin),
                estimate_archive_bytes(info.nchan, info.nbin,
                                       nsub=info.nsub))

    def _admission(self, tenant, archive, est):
        """Fleet-level load-shed before any forward: the memory
        estimate against the per-daemon device budget, the tenant's
        usage quota against the router's metered forwards
        (obs/usage.py), and the fleet open-request ceiling."""
        if self.quotas:
            breach = usage.check(tenant, self.quotas)
            if breach is not None:
                obs.counter("router_sheds")
                metrics.inc("pps_shed_total", reason="quota")
                obs.event("router_shed", tenant=tenant,
                          archive=archive, reason="quota", **breach)
                return {"ok": False, "error": "quota",
                        "tenant": tenant, "archive": archive,
                        **breach}
        if self.mem_budget_bytes and est is not None \
                and est > self.mem_budget_bytes:
            obs.counter("router_sheds")
            metrics.inc("pps_shed_total", reason="memory")
            obs.event("router_shed", tenant=tenant, archive=archive,
                      reason="memory", est_bytes=est,
                      budget_bytes=self.mem_budget_bytes)
            return {"ok": False, "error": "memory", "tenant": tenant,
                    "archive": archive, "est_bytes": est,
                    "budget_bytes": self.mem_budget_bytes}
        if self.fleet_max_open:
            open_total = sum(d.open_requests for d in self._daemons
                             if d.ready.is_set())
            if open_total >= self.fleet_max_open:
                obs.counter("router_sheds")
                metrics.inc("pps_shed_total", reason="overloaded")
                obs.event("router_shed", tenant=tenant,
                          archive=archive, reason="overloaded",
                          open=open_total,
                          limit=self.fleet_max_open)
                return {"ok": False, "error": "overloaded",
                        "tenant": tenant, "open": open_total,
                        "limit": self.fleet_max_open}
        return None

    def submit(self, tenant, archive, config=None, wait=False,
               timeout=None, traceparent=None, priority=0,
               deadline_s=None):
        """Route one submission to its bucket's daemon; the response
        is the daemon's, with the ``request_id`` namespaced
        ``d<i>:...``."""
        if self._draining:
            metrics.inc("pps_requests_total", tenant=str(tenant),
                        outcome="rejected_draining")
            return {"ok": False, "error": "draining"}
        obs.counter("router_requests")
        path = str(archive)
        bucket, est = self._classify(path)
        shed = self._admission(tenant, path, est)
        if shed is not None:
            return shed
        payload = {"op": "submit", "tenant": tenant, "archive": path,
                   "wait": bool(wait)}
        if config:
            payload["config"] = config
        if timeout is not None:
            payload["timeout_s"] = timeout
        if traceparent:
            payload["traceparent"] = traceparent
        if priority:
            payload["priority"] = int(priority)
        if deadline_s is not None:
            payload["deadline_s"] = float(deadline_s)
        conn_timeout = (float(timeout) if timeout else 300.0) + 30.0
        return self._forward(bucket, payload, conn_timeout)

    def _forward(self, bucket, payload, conn_timeout):
        """Forward with supervised retry.  A connection that dies
        mid-forward retries against the SAME daemon after respawn —
        the ledger that may have accepted the work is the only one
        that can replay it exactly once.  A ``draining`` rejection
        (daemon being replaced while the fleet is live) provably did
        NOT accept, so the bucket re-routes and the forward moves on.
        """
        d = None
        last_err = None
        for _ in range(self.forward_attempts):
            if d is None:
                d = self._owner(bucket)
            if d is None:
                return {"ok": False, "error": "no_daemon",
                        "detail": "no ready daemon in the fleet"}
            if not d.ready.wait(timeout=self.respawn_timeout_s):
                return {"ok": False, "error": "daemon_unavailable",
                        "daemon": d.name,
                        "detail": "respawn did not become ready"}
            try:
                resp = client_request(d.socket, payload,
                                      timeout=conn_timeout)
            except (OSError, ValueError) as e:
                last_err = e
                obs.counter("router_forward_retries")
                metrics.inc("pps_forward_retries_total")
                obs.event("router_forward_retry", daemon=d.name,
                          archive=payload.get("archive"),
                          error=type(e).__name__)
                d.fails += 1
                # the forwarder is a failure detector too: a dead
                # process gets declared down (and respawned) NOW
                # instead of after the next health-poll window, so
                # the retry below blocks on d.ready instead of
                # spinning against a dead socket
                if not self._draining:
                    if d.proc is not None and d.proc.poll() is not None:
                        self._daemon_down(d, "exit_%s"
                                          % d.proc.returncode)
                    else:
                        time.sleep(min(1.0, self.health_interval_s))
                continue  # same daemon: wait out its respawn
            if not resp.get("ok") and resp.get("error") == "draining" \
                    and not self._draining:
                with self._lock:
                    if bucket is not None \
                            and self._assign.get(bucket) is d:
                        d.buckets.discard(bucket)
                        self._assign.pop(bucket, None)
                d = None
                continue
            with self._lock:
                d.n_routed += 1
                if bucket is not None:
                    self._bucket_routed[bucket] = \
                        self._bucket_routed.get(bucket, 0) + 1
            metrics.inc("pps_routed_total", bucket=_blabel(bucket),
                        daemon=d.name)
            # meter the forward (obs/usage.py): the router's own
            # usage view — request counts and, when the daemon
            # answered with a terminal payload, its wall seconds.
            # Device seconds stay on the daemon that burned them; the
            # fleet-merged metrics verb sums both sides per tenant.
            wall = resp.get("wall_s")
            usage.meter("forward", tenant=payload.get("tenant"),
                        bucket=_blabel(bucket),
                        wall_s=wall if isinstance(
                            wall, (int, float)) else 0.0,
                        daemon=d.name, ok=bool(resp.get("ok")))
            if resp.get("request_id"):
                resp["request_id"] = "%s:%s" % (d.name,
                                                resp["request_id"])
            return resp
        return {"ok": False, "error": "daemon_unavailable",
                "daemon": d.name if d else None,
                "detail": "%s: %s" % (type(last_err).__name__,
                                      last_err)
                if last_err else "forward attempts exhausted"}

    def wait(self, request_id, timeout=None):
        name, _, rid = str(request_id or "").partition(":")
        d = self._by_name.get(name)
        if d is None or not rid:
            return {"ok": False, "error": "unknown_request",
                    "request_id": request_id}
        try:
            resp = client_request(
                d.socket, {"op": "wait", "request_id": rid,
                           "timeout_s": timeout},
                timeout=(float(timeout) if timeout else 300.0) + 30.0)
        except (OSError, ValueError) as e:
            return {"ok": False, "error": "daemon_unavailable",
                    "daemon": d.name, "detail": str(e)}
        if resp.get("request_id"):
            resp["request_id"] = "%s:%s" % (d.name,
                                            resp["request_id"])
        return resp

    # -- introspection --------------------------------------------------

    def status(self):
        with self._lock:
            daemons = {}
            for d in self._daemons:
                daemons[d.name] = {
                    "ready": d.ready.is_set(),
                    "adopted": d.adopted,
                    "pid": d.pid,
                    "open_requests": d.open_requests,
                    "routed": d.n_routed,
                    "respawns": d.respawns,
                    "parked": self._flap[d.name].parked,
                    "buckets": sorted(_blabel(b)
                                      for b in d.buckets)}
            assignment = {_blabel(b): d.name
                          for b, d in self._assign.items()}
        out = {"ok": True,
               "uptime_s": round(time.time() - (self.t_start
                                                or time.time()), 3),
               "draining": self._draining,
               "n_daemons": len(self._daemons),
               "daemons": daemons,
               "assignment": assignment}
        rec = obs.current()
        if rec is not None:
            out["counters"] = dict(rec.counters)
            out["obs_run"] = rec.dir
        return out

    def health(self):
        """Fleet probe surface: the router is live while its
        supervisor runs; ready while at least one daemon accepts
        work."""
        alerts = obs_health.evaluate() or []
        live = self._thread is not None and self._thread.is_alive()
        ready_daemons = [d for d in self._daemons if d.ready.is_set()]
        out = {"ok": live,
               "live": live,
               "ready": live and not self._draining
               and bool(ready_daemons),
               "draining": self._draining,
               "daemons_ready": len(ready_daemons),
               "daemons_total": len(self._daemons),
               "open_requests": sum(d.open_requests
                                    for d in ready_daemons),
               "respawns": sum(d.respawns for d in self._daemons),
               "alerts_firing": len(alerts),
               "alerts": alerts}
        rec = obs.current()
        if rec is not None:
            out["obs_run"] = rec.dir
        return out

    def metrics_snapshot(self):
        """One merged fleet snapshot: the router's own registry plus
        every live daemon's, via
        :func:`~..obs.metrics.merge_snapshots` (counters/histograms
        sum; gauges keep per-process identity under ``p<name>/``)."""
        snaps = {}
        own = metrics.snapshot()
        if own:
            snaps["router"] = own
        for d in self._daemons:
            if not d.ready.is_set():
                continue
            try:
                snap = client_request(d.socket, {"op": "metrics"},
                                      timeout=15.0).get("snapshot")
            except (OSError, ValueError):
                continue
            if snap:
                snaps[d.name] = snap
        if not snaps:
            return None
        if len(snaps) == 1:
            return next(iter(snaps.values()))
        return metrics.merge_snapshots(snaps)

    # -- drain / shutdown -----------------------------------------------

    def request_drain(self):
        """Fleet drain: stop routing, ask every daemon to drain."""
        with self._lock:
            if self._draining:
                return
            self._draining = True
        obs.event("router_drain")
        metrics.set_gauge("pps_draining", 1)
        self._notify_drain()

    def _notify_drain(self):
        """Forward the shutdown op to every ready daemon not yet
        told.  A daemon mid-respawn when the drain started (not ready
        yet) is notified later, from drained()'s wait loop, the
        moment its warm-up finishes — otherwise it would outlive the
        fleet."""
        for d in self._daemons:
            if d.drain_sent or not d.ready.is_set():
                continue
            d.drain_sent = True
            with contextlib.suppress(OSError, ValueError):
                client_request(d.socket, {"op": "shutdown"},
                               timeout=10.0)

    def drained(self, timeout=None):
        """True when every spawned daemon has exited after a drain.
        An adopted-only fleet (no child processes) counts as drained
        once the drain was requested — adopted daemons are not ours
        to wait on."""
        if all(d.proc is None for d in self._daemons):
            if not self._draining and timeout:
                time.sleep(timeout)
            return self._draining
        deadline = None if timeout is None else time.time() + timeout
        while True:
            if self._draining:
                self._notify_drain()
            alive = [d for d in self._daemons
                     if d.proc is not None and d.proc.poll() is None]
            if not alive:
                return True
            if deadline is not None and time.time() >= deadline:
                return False
            left = 0.2 if deadline is None \
                else min(0.2, max(0.01, deadline - time.time()))
            try:
                alive[0].proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                pass

    def shutdown(self, timeout=120.0):
        """Drain the fleet, stop the supervisor, close obs state.
        Returns True when every daemon exited in time."""
        self.request_drain()
        ok = self.drained(timeout=timeout)
        self._stop.set()
        if self._thread is not None:
            self._thread.join(10.0)
            self._thread = None
        for d in self._daemons:
            if d.proc is not None and d.proc.poll() is None:
                with contextlib.suppress(OSError):
                    d.proc.kill()
                with contextlib.suppress(Exception):
                    d.proc.wait(timeout=10.0)
            d.ready.clear()
        obs.event("router_stopped", drained=bool(ok),
                  respawns=sum(d.respawns for d in self._daemons))
        self._obs_stack.close()
        return ok
