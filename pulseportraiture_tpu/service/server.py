"""Local transport for the TOA service: JSONL over a Unix socket.

One connection carries one request: the client sends a single JSON
object terminated by a newline, the server answers with one JSON line
and closes.  Blocking ops (``submit`` with ``wait``, ``wait``) hold
their connection open until the request settles, so a caller needs no
polling loop.  No new dependencies — this is stdlib ``socket`` +
``json``, matching the daemon's single-host scope (a fleet fronts
many daemons with its own RPC; docs/SERVICE.md).

Ops (all responses carry ``ok``)::

    {"op": "ping"}
    {"op": "submit", "tenant": T, "archive": PATH,
     "config": {...}, "wait": true, "timeout_s": 300,
     "priority": 1, "deadline_s": 5.0,          # deadline class
     "traceparent": "00-<32hex>-<16hex>-01"}   # optional W3C carrier
    {"op": "wait", "request_id": "r000001", "timeout_s": 300}
    {"op": "status"}
    {"op": "health"}            # liveness/readiness + firing alerts
    {"op": "metrics"}           # live streaming-metrics snapshot
    {"op": "metrics", "format": "prometheus"}   # + text exposition
    {"op": "shutdown"}          # begins a drain; daemon exits 0 after

The ``metrics`` payload is the daemon run's cumulative snapshot
(obs/metrics.py): counters, gauges and the request-lifecycle latency
histograms ``pploadgen``'s SLO gate and the ``ppserve status --watch``
view are driven by.
"""

import json
import os
import socket
import threading

from .. import obs
from ..obs import metrics as _metrics

__all__ = ["ServiceServer", "client_request", "DEFAULT_SOCKET_NAME"]

DEFAULT_SOCKET_NAME = "ppserve.sock"

_MAX_LINE = 1 << 20  # a request line this long is a protocol error


class ServiceServer:
    """Accept loop + per-connection handler threads over a
    :class:`~.daemon.TOAService`."""

    def __init__(self, service, socket_path):
        self.service = service
        self.socket_path = socket_path
        self._sock = None
        self._thread = None
        self._stop = threading.Event()

    def start(self):
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)  # stale socket from a crash
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.socket_path)
        self._sock.listen(16)
        self._sock.settimeout(0.2)
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="ppserve-server",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True,
                             name="ppserve-conn-%d" % conn.fileno(),
                             ).start()

    def _handle(self, conn):
        try:
            req = self._read_line(conn)
            resp = self._dispatch(req)
        except Exception as e:  # noqa: BLE001 — protocol boundary
            resp = {"ok": False, "error": "protocol",
                    "detail": "%s: %s" % (type(e).__name__, e)}
        try:
            conn.sendall((json.dumps(resp, default=str) + "\n")
                         .encode("utf-8"))
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _read_line(conn):
        buf = b""
        while b"\n" not in buf:
            if len(buf) > _MAX_LINE:
                raise ValueError("request line too long")
            chunk = conn.recv(65536)
            if not chunk:
                break
            buf += chunk
        line = buf.split(b"\n", 1)[0].strip()
        if not line:
            raise ValueError("empty request")
        return json.loads(line.decode("utf-8"))

    def _dispatch(self, req):
        op = req.get("op")
        svc = self.service
        if op == "ping":
            return {"ok": True, "pid": os.getpid()}
        if op == "submit":
            return svc.submit(req.get("tenant"), req.get("archive"),
                              config=req.get("config"),
                              wait=bool(req.get("wait")),
                              timeout=req.get("timeout_s"),
                              traceparent=req.get("traceparent"),
                              priority=req.get("priority") or 0,
                              deadline_s=req.get("deadline_s"))
        if op == "wait":
            return svc.wait(req.get("request_id"),
                            timeout=req.get("timeout_s"))
        if op == "status":
            return svc.status()
        if op == "health":
            return svc.health()
        if op == "metrics":
            snap = svc.metrics_snapshot()
            resp = {"ok": True, "snapshot": snap}
            if req.get("format") == "prometheus":
                resp["text"] = _metrics.render_prometheus(snap)
            return resp
        if op == "shutdown":
            obs.event("service_shutdown_requested", via="socket")
            svc.request_drain()
            return {"ok": True, "draining": True}
        return {"ok": False, "error": "unknown_op", "op": op}


def client_request(socket_path, payload, timeout=300.0):
    """Send one op to a running daemon; returns the response dict."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout)
        s.connect(socket_path)
        s.sendall((json.dumps(payload) + "\n").encode("utf-8"))
        buf = b""
        while b"\n" not in buf:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    line = buf.split(b"\n", 1)[0].strip()
    if not line:
        raise ConnectionError("ppserve daemon closed the connection "
                              "without a response")
    return json.loads(line.decode("utf-8"))
