"""TOA-as-a-service: resident multi-tenant fitting daemon.

Turns the batch-shaped survey pipeline into a long-lived service:
per-tenant ledger-backed intake, warm per-bucket fitter pools with AOT
program warm-up, cross-request micro-batching, fairness/backpressure
between tenants, and per-request observability runs.  See
docs/SERVICE.md and the ``ppserve`` CLI (cli/ppserve.py).

Host-side orchestration by contract: no entry point here may be
called inside jit (jaxlint J002 covers the ``service.*`` surface).
"""

from .batcher import MicroBatcher
from .daemon import Request, TOAService
from .router import DEFAULT_ROUTER_SOCKET_NAME, FleetRouter
from .server import DEFAULT_SOCKET_NAME, ServiceServer, client_request
from .warm import (enable_persistent_cache, program_specs,
                   synth_databunch, warm_plan)

__all__ = ["TOAService", "Request", "MicroBatcher", "ServiceServer",
           "client_request", "DEFAULT_SOCKET_NAME", "warm_plan",
           "program_specs", "synth_databunch",
           "enable_persistent_cache", "FleetRouter",
           "DEFAULT_ROUTER_SOCKET_NAME"]
