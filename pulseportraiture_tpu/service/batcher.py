"""Cross-request micro-batching: coalesce same-bucket device dispatches.

The service fits each request of a micro-batch on its own worker
thread, through an ordinary per-request ``GetTOAs`` whose ``fit_batch``
hook points at one shared :class:`MicroBatcher` per shape bucket.  The
hook is where the requests meet: each worker's batched-fit call parks
with its argument set, and once every live worker of the cycle has
either parked or finished, the last arriver becomes the *leader* — it
concatenates the parked batches along the subint axis, issues ONE
``fit_portrait_full_batch`` dispatch for the combined batch, splits the
result rows back per caller and releases everyone.  K same-bucket
single-archive submissions therefore execute as ``ceil(K / batch_max)``
device dispatches instead of K (ISSUE 7 acceptance; the service's
dispatcher sizes the cycles).

Coalescing is correctness-transparent:

* only calls with identical *static* fit configuration (fit flags,
  bounds, iteration caps, ...) merge — a config mismatch degrades to
  separate dispatches in the same cycle, never to a wrong program;
* per-call arrays (data, models, init, errs, weights, nu columns)
  concatenate on the batch axis and the result rows are sliced back,
  so each request sees exactly the rows its own solo dispatch would
  have produced (the solver is row-independent: vmap over subints);
* the harmonic cutoff ``kmax`` is pinned to the max over the parked
  calls' models — without it the combined dispatch would inherit the
  first caller's cutoff (``model_kmax`` inspects one batch row);
* the combined batch is padded to the power-of-two batch bucket
  (``bucket_batch_size``), so coalesced programs stay O(log batch_max)
  per shape bucket rather than one per distinct K.

Failure semantics (docs/SERVICE.md failure matrix): a combined
dispatch that raises fails every parked call of that group — each
request then retries through its tenant ledger's backoff, and a retry
may land in a different (possibly solo) cycle.  Injected ``dispatch``
faults (testing/faults.py) fire per archive *before* the hook, so a
chaos-faulted request never reaches the shared dispatch at all.

Host-side only: the batcher is threading + numpy concatenation around
the jit boundary (jaxlint J002 covers the ``service.*`` surface).
"""

import threading
import time

import numpy as np

from .. import obs
from ..obs import metrics, tracing
from ..obs.metrics import PHASE_HISTOGRAM

__all__ = ["MicroBatcher"]


def _static_key(kw):
    """Hashable static-configuration key; calls coalesce only within
    one key (same compiled program family)."""
    bounds = kw.get("bounds")
    if bounds is not None:
        bounds = tuple(tuple(b) for b in bounds)
    nu_outs = kw.get("nu_outs")
    nu_outs_shape = None if nu_outs is None else \
        tuple(col is not None for col in nu_outs)
    return (
        tuple(kw.get("fit_flags", (1, 1, 0, 0, 0))),
        bounds,
        bool(kw.get("log10_tau", True)),
        int(kw.get("max_iter", 50)),
        kw.get("polish_iter"), kw.get("coarse_iter"),
        kw.get("coarse_kmax"),
        nu_outs_shape,
        kw.get("errs") is None,
        kw.get("weights") is None,
    )


class _Parked:
    """One worker's fit call waiting for the cycle's leader."""

    __slots__ = ("args", "kw", "n", "event", "result", "error", "t0",
                 "ctx")

    def __init__(self, args, kw):
        self.args = args
        self.kw = kw
        self.n = int(np.asarray(args[0]).shape[0])
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.t0 = time.perf_counter()  # park time (metrics)
        # the parking worker's trace context (obs/tracing.py): the
        # combined dispatch span links back to every member through it
        self.ctx = tracing.current()


class MicroBatcher:
    """Per-bucket coalescing ``fit_batch`` hook (module docstring).

    ``begin(n)`` opens a cycle expecting ``n`` worker threads;
    each worker must call ``worker_done()`` exactly once (in a
    ``finally``) so a request that never reaches a fit call — load
    failure, injected read fault, quarantine — releases the barrier
    instead of stalling the cycle until ``window_s``.
    """

    def __init__(self, bucket=None, window_s=2.0, fit=None):
        self.bucket = tuple(bucket) if bucket else None
        self.window_s = float(window_s)
        self._fit = fit  # injectable for tests; default resolved lazily
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._parked = []
        self._expected = 0
        self._done = 0
        # absolute dispatch deadline for the current cycle (service
        # dispatcher: the most urgent member's completion deadline);
        # None = window-only semantics
        self._deadline = None
        # cumulative stats (service status / obs)
        self.n_dispatches = 0
        self.n_calls = 0
        self.n_coalesced = 0  # calls that shared a dispatch

    # -- cycle management ----------------------------------------------

    def begin(self, n, deadline=None):
        """Open a cycle of ``n`` workers (dispatcher thread).

        ``deadline`` (absolute ``time.time()``) caps how long parked
        members wait for stragglers: past it, whoever notices leads a
        partial dispatch — a stalled sibling cannot park the rest of
        the cycle beyond the most urgent member's deadline.
        """
        with self._lock:
            self._expected = int(n)
            self._done = 0
            self._parked = []
            self._deadline = None if deadline is None \
                else float(deadline)

    def worker_done(self):
        """A worker of the cycle finished (fit call resolved, or it
        never made one)."""
        with self._lock:
            self._done += 1
            self._cond.notify_all()

    # -- the fit_batch hook --------------------------------------------

    def _resolve_fit(self):
        if self._fit is None:
            from ..fit.portrait import fit_portrait_full_batch

            self._fit = fit_portrait_full_batch
        return self._fit

    def fit(self, *args, **kw):
        """``fit_portrait_full_batch`` drop-in (GetTOAs.fit_batch)."""
        slot = _Parked(args, kw)
        with self._lock:
            self.n_calls += 1
            if self._expected <= 1:
                # solo cycle: no one to wait for
                return self._dispatch_alone(slot)
            self._parked.append(slot)
            if self._barrier_met():
                self._fire_locked()
            else:
                while not slot.event.is_set():
                    if not self._cond.wait(timeout=self._park_timeout()):
                        # window (or cycle deadline) expired: whoever
                        # notices first leads a partial dispatch so one
                        # slow sibling cannot hold the batch hostage
                        if not slot.event.is_set():
                            self._fire_locked()
                        break
                    if slot.event.is_set():
                        break
                    if self._barrier_met():
                        self._fire_locked()
        slot.event.wait()
        if slot.error is not None:
            raise slot.error
        return slot.result

    def _park_timeout(self):
        """In-barrier wait budget: the configured window, trimmed to
        the cycle deadline when one is nearer (caller holds the
        lock)."""
        timeout = threading.TIMEOUT_MAX if self.window_s <= 0 \
            else self.window_s
        if self._deadline is not None:
            timeout = min(timeout,
                          max(0.01, self._deadline - time.time()))
        return timeout

    def _barrier_met(self):
        # every expected worker is either parked here or fully done:
        # nothing more can join this round (caller holds the lock)
        return self._parked and \
            len(self._parked) + self._done >= self._expected

    # -- dispatching ---------------------------------------------------

    def _dispatch_alone(self, slot):
        fit = self._resolve_fit()
        self.n_dispatches += 1
        self._emit(1, slot.n)
        attrs = self._span_attrs([slot], slot.n)
        with metrics.timed(PHASE_HISTOGRAM, phase="dispatch",
                           bucket="-" if self.bucket is None
                           else "%dx%d" % self.bucket), \
                obs.span("dispatch", **attrs):
            return fit(*slot.args, **self._sized_kw(slot.kw, slot.n))

    def _span_attrs(self, slots, total):
        """Attrs for the dispatch span: fan-in is first-class — ONE
        span per device dispatch, carrying a span link to every member
        call's context (obs/tracing.py), so a combined dispatch is
        causally reachable from each of the K requests it served."""
        attrs = {"n_requests": len(slots), "batch": int(total),
                 "bucket": None if self.bucket is None
                 else "%dx%d" % self.bucket}
        links = [tracing.link(s.ctx) for s in slots
                 if s.ctx is not None]
        if links:
            attrs["links"] = links
        return attrs

    def _sized_kw(self, kw, total):
        """Recompute the batch-shaping knobs for the (possibly
        combined) batch size; per-call values were sized for solo
        dispatch."""
        from ..fit.portrait import auto_scan_size, bucket_batch_size

        out = dict(kw)
        scan = auto_scan_size(total)
        out["scan_size"] = scan
        out["pad_to"] = None if scan is not None \
            else bucket_batch_size(total)
        return out

    def _fire_locked(self):
        """Dispatch every parked call (caller holds the lock); the
        current thread is the leader.  The actual device work runs
        OUTSIDE the lock so late workers can park for the next round."""
        parked, self._parked = self._parked, []
        self._lock.release()
        try:
            groups = {}
            for slot in parked:
                groups.setdefault(_static_key(slot.kw),
                                  []).append(slot)
            for slots in groups.values():
                self._dispatch_group(slots)
        finally:
            self._lock.acquire()
        self._cond.notify_all()

    def _dispatch_group(self, slots):
        # micro-batch park: how long each call waited for its leader
        t_fire = time.perf_counter()
        blabel = "-" if self.bucket is None else "%dx%d" % self.bucket
        for slot in slots:
            park_s = max(0.0, t_fire - slot.t0)
            metrics.observe(PHASE_HISTOGRAM, park_s,
                            phase="park", bucket=blabel,
                            exemplar=slot.ctx[0] if slot.ctx else None)
            if slot.ctx is not None:
                # each member's wait-for-leader, in its own trace
                tracing.emit_span("park", park_s, ctx=slot.ctx,
                                  bucket=blabel)
        if len(slots) == 1:
            slot = slots[0]
            try:
                slot.result = self._dispatch_alone(slot)
            except BaseException as e:  # noqa: BLE001 — forwarded
                slot.error = e
            finally:
                slot.event.set()
            return
        try:
            self._dispatch_combined(slots)
        except BaseException as e:  # noqa: BLE001 — forwarded to all
            for slot in slots:
                slot.error = e
                slot.event.set()

    def _dispatch_combined(self, slots):
        from ..fit.portrait import model_kmax
        from ..utils.databunch import DataBunch

        fit = self._resolve_fit()
        total = sum(s.n for s in slots)

        def cat(pick):
            return np.concatenate([np.asarray(pick(s)) for s in slots],
                                  axis=0)

        # positional contract (pipelines/toas.py): data, models, init,
        # Ps, freqs; models may broadcast [B, nchan, nbin] per call
        data = cat(lambda s: s.args[0])
        models = np.concatenate(
            [np.broadcast_to(np.asarray(s.args[1]),
                             np.asarray(s.args[0]).shape)
             for s in slots], axis=0)
        init = cat(lambda s: s.args[2])
        Ps = np.concatenate(
            [np.broadcast_to(np.asarray(s.args[3]), (s.n,))
             for s in slots], axis=0)
        freqs = cat(lambda s: s.args[4])

        kw0 = self._sized_kw(slots[0].kw, total)
        for key in ("errs", "weights", "nu_fits"):
            if slots[0].kw.get(key) is not None:
                kw0[key] = cat(lambda s, k=key: s.kw[k])
        nu_outs0 = slots[0].kw.get("nu_outs")
        if nu_outs0 is not None:
            kw0["nu_outs"] = tuple(
                None if col is None else np.concatenate(
                    [np.asarray(s.kw["nu_outs"][i]) for s in slots])
                for i, col in enumerate(nu_outs0))
        # pin the harmonic cutoff to the most demanding member —
        # fit_portrait_full_batch would otherwise derive it from the
        # FIRST batch row only (fit/portrait.model_kmax)
        if kw0.get("kmax") is None:
            kmaxes = [model_kmax(np.asarray(s.args[1])) for s in slots]
            kmaxes = [k for k in kmaxes if k is not None]
            if kmaxes:
                kw0["kmax"] = max(kmaxes)

        self.n_dispatches += 1
        self.n_coalesced += len(slots)
        self._emit(len(slots), total)
        with metrics.timed(PHASE_HISTOGRAM, phase="dispatch",
                           bucket="-" if self.bucket is None
                           else "%dx%d" % self.bucket), \
                obs.span("dispatch", **self._span_attrs(slots, total)):
            out = fit(data, models, init, Ps, freqs, **kw0)
        out = {k: np.asarray(v) for k, v in dict(out).items()}
        off = 0
        for slot in slots:
            slot.result = DataBunch(**{
                k: (v[off:off + slot.n]
                    if getattr(v, "ndim", 0) >= 1
                    and v.shape[0] == total else v)
                for k, v in out.items()})
            off += slot.n
            slot.event.set()

    def _emit(self, n_requests, total):
        obs.event("microbatch_dispatch",
                  bucket=None if self.bucket is None
                  else "%dx%d" % self.bucket,
                  n_requests=n_requests, batch=int(total))
        obs.counter("service_dispatches")
        if n_requests > 1:
            obs.counter("service_coalesced_requests", n_requests)
