"""Global configuration and physical constants.

TPU-native re-design of the reference's module-header configuration block
(see /root/reference/pplib.py:44-83).  Unlike the reference, which is
configured by editing module constants, everything here is either a true
physical constant or a runtime-overridable setting carried explicitly
through function arguments; the module-level values are only *defaults*.

Numerics contract
-----------------
TOA parity at the ~1 ns level on a ~ms period requires ~1e-6 rotations of
phase precision coming out of a chi-squared whose sums run over up to
~1e6 (nchan x nharm) terms.  We therefore enable JAX x64 globally and keep
the *solver state* (phase, DM, GM, tau, alpha, chi-squared accumulators,
phasor arguments) in float64.  Bulk portrait data may be float32/bfloat16
where parity tests allow; each op takes dtype from its inputs rather than
hard-coding it.  ``phasor()`` reduces its argument mod 1 in float64 before
the complex exponential so harmonic index k ~ 2048 does not destroy
precision (cf. the reference's direct ``exp(2j*pi*outer(...))``,
/root/reference/pptoaslib.py:233-238, which relies on float64 throughout).
"""

import functools

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

# -- Dispersion constants [MHz**2 cm**3 pc**-1 s] ---------------------------
# Exact value of e**2/(2 pi m_e c) (used by PRESTO).
Dconst_exact = 4.148808e3
# "Traditional" value used by PSRCHIVE/TEMPO/PINT.  Fitted DM values depend
# on this choice (reference: pplib.py:44-51).
Dconst_trad = 0.000241 ** -1
Dconst = Dconst_trad

# Default power-law index for the scattering law tau(nu) = tau*(nu/nu_tau)**alpha
# (reference: pplib.py:53-54).
scattering_alpha = -4.0

# Default noise estimation method; see ops.noise (reference: pplib.py:56-62).
default_noise_method = "PS"

# Weight applied to the DC (k=0) harmonic in all Fourier-domain fits.
# 0 removes the baseline term from the fit (reference: pplib.py:64-66).
F0_fact = 0

# Upper bound on Gaussian component FWHM [rot] used to stabilize Gaussian
# fits (reference: pplib.py:68-70).
wid_max = 0.25

# Default Gaussian-portrait evolution code: one digit per (loc, wid, amp);
# '0' = power-law evolution, '1' = linear (reference: pplib.py:72-79).
default_model = "000"

# Scattering-function bin shift fudge factor; retained for format parity,
# currently has no effect (reference: pplib.py:81-83).
binshift = 1.0

# scipy.optimize.fmin_tnc return-code strings, kept verbatim for diagnostic
# parity (reference: pplib.py:109-119).  Our batched Newton solver maps its
# own termination reasons onto the closest codes: 0 = gradient converged,
# 1 = function converged, 2 = step converged, 3 = max iterations.
RCSTRINGS = {
    "-1": "INFEASIBLE: Infeasible (low > up).",
    "0": "LOCALMINIMUM: Local minima reach (|pg| ~= 0).",
    "1": "FCONVERGED: Converged (|f_n-f_(n-1)| ~= 0.)",
    "2": "XCONVERGED: Converged (|x_n-x_(n-1)| ~= 0.)",
    "3": "MAXFUN: Max. number of function evaluations reach.",
    "4": "LSFAIL: Linear search failed.",
    "5": "CONSTANT: All lower bounds are equal to the upper bounds.",
    "6": "NOPROGRESS: Unable to progress.",
    "7": "USERABORT: User requested end of minimization.",
}

# Default dtypes for the two precision domains of the numerics contract.
solver_dtype = jnp.float64
data_dtype = jnp.float64  # parity-first default; benches may drop to float32

# Chunked-scan engagement for batched fits (fit_portrait_full_batch
# scan_size): batches above *_scan_threshold run as a lax.scan over
# *_scan_size chunks inside one program, keeping the compile footprint
# bounded (the remote compile helper fails on the monolithic 200-subint
# 512x2048 program) while the whole batch stays one device dispatch.
subint_scan_threshold = 128
subint_scan_size = 100
profile_scan_threshold = 2048  # narrowband: single-channel profile rows
profile_scan_size = 1024


def default_float(x):
    """Cast a python/numpy scalar or array to the solver dtype."""
    return jnp.asarray(x, dtype=solver_dtype)


def complex_dtype_for(real_dtype):
    """Return the complex dtype matching a real dtype."""
    return jnp.result_type(real_dtype, jnp.complex64)


@functools.lru_cache(maxsize=None)
def backend_supports_complex128():
    """True when the default JAX backend can compile complex128.

    TPUs cannot ("Element type C128 is not supported"); CPUs and GPUs can.
    Cached per-process — the default backend does not change mid-run.
    """
    try:
        return jax.default_backend() != "tpu"
    except RuntimeError:  # pragma: no cover - backend init failure
        return True


def fft_real_dtype(dtype):
    """Widest real dtype whose complex counterpart compiles on the default
    backend: float64 stays float64 on CPU/GPU but becomes float32 on TPU.

    This is the device boundary of the numerics contract: *solver state*
    (phases, DMs, chi-squared sums, mod-1 phasor arguments) stays float64
    everywhere, while arrays that flow through rfft/lax.complex are clamped
    here so no f64 path ever materializes complex128 on TPU.
    """
    dtype = jnp.dtype(dtype)
    if not jnp.issubdtype(dtype, jnp.floating):
        dtype = jnp.dtype(solver_dtype)
    if dtype == jnp.float64 and not backend_supports_complex128():
        return jnp.dtype(jnp.float32)
    return dtype


def as_fft_operand(x):
    """Cast a real array for use in rfft/complex ops (see fft_real_dtype)."""
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        return x
    return x.astype(fft_real_dtype(x.dtype))


def host_stats_device():
    """Context manager placing small statistics on the local CPU backend.

    Per-archive load-time estimates (noise, S/N) are tiny computations;
    on a remote-tunnel TPU each one costs a full dispatch+transfer round
    trip (~150 ms here) that dwarfs the math.  Archive loading wraps
    them in this context so IO-side code never blocks on the
    accelerator; the batched fit pipelines are unaffected.  Falls back
    to a no-op when no CPU backend is registered.
    """
    import contextlib

    try:
        cpu = jax.devices("cpu")[0]
    except RuntimeError:
        return contextlib.nullcontext()
    return jax.default_device(cpu)


def host_array(x):
    """Device array -> numpy, transferring complex values as two real
    planes.

    Some TPU transports (the axon remote-compile tunnel here) cannot
    transfer complex buffers device->host at all ("UNIMPLEMENTED", and
    the failed transfer wedges the client) — every host materialization
    of a possibly-complex device array must go through this helper
    instead of np.asarray.
    """
    x = jnp.asarray(x)
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        return np.asarray(jnp.real(x)) + 1j * np.asarray(jnp.imag(x))
    return np.asarray(x)


def set_compile_cache_dir(cache_dir):
    """Point jax's persistent compilation cache at ``cache_dir``.

    Deployment policy, so it lives here with the rest of the global
    jax configuration (jaxlint J005).  Thresholds are dropped to zero
    so every program qualifies: the cache exists to save multi-minute
    survey/service compiles, but it must also prove itself on the tiny
    smoke-test programs (service/warm.py, docs/SERVICE.md).
    """
    jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    for knob, val in (("jax_persistent_cache_min_compile_time_secs",
                       0.0),
                      ("jax_persistent_cache_min_entry_size_bytes", 0)):
        try:
            jax.config.update(knob, val)
        except AttributeError:  # older jax: defaults still cache
            pass
    try:
        # the cache module latches a disabled state after the first
        # compile that ran without a directory configured; reset so a
        # mid-process enable (ppserve --compile-cache) takes effect
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass


__all__ = [
    "Dconst",
    "Dconst_exact",
    "Dconst_trad",
    "scattering_alpha",
    "default_noise_method",
    "F0_fact",
    "wid_max",
    "default_model",
    "binshift",
    "RCSTRINGS",
    "solver_dtype",
    "data_dtype",
    "default_float",
    "complex_dtype_for",
    "backend_supports_complex128",
    "fft_real_dtype",
    "as_fft_operand",
    "host_stats_device",
    "subint_scan_threshold",
    "subint_scan_size",
    "profile_scan_threshold",
    "profile_scan_size",
    "set_compile_cache_dir",
]
