"""Standalone channel-zapping heuristics + paz command emission.

Equivalent of the reference's ppzap module functions
(/root/reference/ppzap.py:18-95): the model-free median-noise cut
(``get_zap_channels``) and the paz shell-command writer
(``print_paz_cmds``).  The model-based path lives on
``GetTOAs.get_channels_to_zap`` (pipelines/toas.py), as in the
reference.

The median cut here is vectorized across a subintegration's channels
(boolean masks instead of the reference's list.pop loop) but iterates to
the same fixed point: a channel is zapped when its noise level exceeds
the median of the surviving channels by ``nstd`` standard deviations.
"""

import sys

import numpy as np

__all__ = ["get_zap_channels", "print_paz_cmds", "apply_zaps"]


def get_zap_channels(data, nstd=3):
    """Propose channels to zap via the iterated median-noise algorithm.

    data: DataBunch from load_data (or DataPortrait); uses
    data.ok_isubs / data.ok_ichans / data.noise_stds.
    Returns one sorted channel-index list per ARCHIVE subint (list
    position == absolute subint index; excluded subints get an empty
    list), so consumers that address subints by position — paz ``-w``
    emission and ``apply_zaps`` — stay aligned on archives with
    dead subints (ref /root/reference/ppzap.py:18-48).
    """
    zap_channels = [[] for _ in range(data.nsub)]
    for isub in data.ok_isubs:
        ichans = np.asarray(data.ok_ichans[isub], dtype=int)
        alive = np.ones(len(ichans), dtype=bool)
        noise = np.asarray(data.noise_stds[isub, 0, ichans])
        while alive.any():
            ns = noise[alive]
            bad = noise > np.median(ns) + nstd * np.std(ns)
            bad &= alive
            if not bad.any():
                break
            alive &= ~bad
        zap_channels[int(isub)] = sorted(ichans[~alive].tolist())
    return zap_channels


def print_paz_cmds(datafiles, zap_list, all_subs=False, modify=True,
                   outfile=None, quiet=False):
    """Emit paz shell commands for a zap list.

    zap_list[iarch][isub] -> channel indices to zap; all_subs applies a
    channel's zap to every subint (deduplicated); modify=True emits
    in-place ('-m') commands, else a '-e zap' copy first.  outfile
    appends to a file instead of stdout.  Returns the emitted lines
    (ref /root/reference/ppzap.py:50-95).
    """
    if not len(datafiles) or not len(zap_list):
        if not quiet:
            print("Nothing to zap.")
        return []
    lines = []
    for iarch, datafile in enumerate(datafiles):
        count = sum(len(z) for z in zap_list[iarch])
        if count:
            if modify:
                paz_outfile = datafile
            else:
                paz_outfile = _zap_outfile_name(datafile)
                lines.append("paz -e zap %s" % datafile)
        last_line = ""
        for isub, bad_ichans in enumerate(zap_list[iarch]):
            for bad_ichan in bad_ichans:
                if not all_subs:
                    lines.append("paz -m -I -z %d -w %d %s"
                                 % (bad_ichan, isub, paz_outfile))
                else:
                    line = "paz -m -z %d %s" % (bad_ichan, paz_outfile)
                    if line != last_line:
                        lines.append(line)
                    last_line = line
    out = open(outfile, "a") if outfile is not None else sys.stdout
    for line in lines:
        print(line, file=out)
    if outfile is not None:
        out.close()
        if not quiet:
            print("Wrote %s." % outfile)
    return lines


def _zap_outfile_name(datafile):
    """paz '-e zap' naming: replace the final extension with 'zap'
    (append '.zap' when the name has no extension) — the same names
    print_paz_cmds puts in its emitted commands."""
    ii = datafile[::-1].find(".")
    return datafile + ".zap" if ii < 0 else datafile[:-ii] + "zap"


def apply_zaps(datafiles, zap_list, all_subs=False, modify=True,
               quiet=False):
    """Natively apply a zap list: zero weights and rewrite the archives.

    The reference (and `print_paz_cmds`) can only *emit* paz shell
    commands, leaving the actual zapping to psrchive's C++ paz tool.
    This applies the same semantics with the in-repo PSRFITS writer
    (io/psrfits.py), so the zap path works end-to-end in a
    psrchive-free environment (ref /root/reference/ppzap.py:50-95 for
    the command set; /root/reference/pplib.py:3039-3075 for the
    unload-a-modified-archive pattern this replaces).

    zap_list[iarch][isub] -> channel indices to zap in that subint;
    all_subs zaps each listed channel in EVERY subint (paz ``-z`` vs
    ``-z -w``); modify=True rewrites the datafile in place (paz
    ``-m``), else writes a copy named like paz ``-e zap``.

    Returns [(outfile, n_weights_zeroed), ...] for the rewritten
    archives (archives with nothing to zap are left untouched).
    """
    from ..io.psrfits import read_archive

    if len(zap_list) != len(datafiles):
        # strict: a shifted pairing would silently zap the wrong
        # archives (and --modify rewrites them in place)
        raise ValueError(
            "apply_zaps got %d zap list(s) for %d datafile(s); the "
            "lists pair by index and must align exactly"
            % (len(zap_list), len(datafiles)))
    results = []
    for iarch, datafile in enumerate(datafiles):
        zaps = zap_list[iarch]
        if not sum(len(z) for z in zaps):
            continue
        arch = read_archive(datafile)
        weights = np.asarray(arch.weights, dtype=np.float64).copy()
        before = int(np.count_nonzero(weights))
        if all_subs:
            chans = sorted({c for z in zaps for c in z})
            weights[:, chans] = 0.0
        else:
            for isub, bad_ichans in enumerate(zaps):
                if isub >= weights.shape[0]:
                    raise IndexError(
                        "zap_list for %s names subint %d but the "
                        "archive has %d subints"
                        % (datafile, isub, weights.shape[0]))
                weights[isub, list(bad_ichans)] = 0.0
        arch.weights = weights
        outfile = datafile if modify else _zap_outfile_name(datafile)
        arch.unload(outfile, quiet=True)
        nzapped = before - int(np.count_nonzero(weights))
        results.append((outfile, nzapped))
        if not quiet:
            print("Zapped %d channel weight(s) -> %s."
                  % (nzapped, outfile))
    return results
