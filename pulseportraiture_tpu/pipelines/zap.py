"""Standalone channel-zapping heuristics + paz command emission.

Equivalent of the reference's ppzap module functions
(/root/reference/ppzap.py:18-95): the model-free median-noise cut
(``get_zap_channels``) and the paz shell-command writer
(``print_paz_cmds``).  The model-based path lives on
``GetTOAs.get_channels_to_zap`` (pipelines/toas.py), as in the
reference.

The median cut here is vectorized across a subintegration's channels
(boolean masks instead of the reference's list.pop loop) but iterates to
the same fixed point: a channel is zapped when its noise level exceeds
the median of the surviving channels by ``nstd`` standard deviations.
"""

import sys

import numpy as np

__all__ = ["get_zap_channels", "print_paz_cmds"]


def get_zap_channels(data, nstd=3):
    """Propose channels to zap via the iterated median-noise algorithm.

    data: DataBunch from load_data (or DataPortrait); uses
    data.ok_isubs / data.ok_ichans / data.noise_stds.
    Returns a per-subint list of sorted channel-index lists
    (ref /root/reference/ppzap.py:18-48).
    """
    zap_channels = []
    for isub in data.ok_isubs:
        ichans = np.asarray(data.ok_ichans[isub], dtype=int)
        alive = np.ones(len(ichans), dtype=bool)
        noise = np.asarray(data.noise_stds[isub, 0, ichans])
        while alive.any():
            ns = noise[alive]
            bad = noise > np.median(ns) + nstd * np.std(ns)
            bad &= alive
            if not bad.any():
                break
            alive &= ~bad
        zap_channels.append(sorted(ichans[~alive].tolist()))
    return zap_channels


def print_paz_cmds(datafiles, zap_list, all_subs=False, modify=True,
                   outfile=None, quiet=False):
    """Emit paz shell commands for a zap list.

    zap_list[iarch][isub] -> channel indices to zap; all_subs applies a
    channel's zap to every subint (deduplicated); modify=True emits
    in-place ('-m') commands, else a '-e zap' copy first.  outfile
    appends to a file instead of stdout.  Returns the emitted lines
    (ref /root/reference/ppzap.py:50-95).
    """
    if not len(datafiles) or not len(zap_list):
        if not quiet:
            print("Nothing to zap.")
        return []
    lines = []
    for iarch, datafile in enumerate(datafiles):
        count = sum(len(z) for z in zap_list[iarch])
        if count:
            if modify:
                paz_outfile = datafile
            else:
                ii = datafile[::-1].find(".")
                paz_outfile = datafile + ".zap" if ii < 0 \
                    else datafile[:-ii] + "zap"
                lines.append("paz -e zap %s" % datafile)
        last_line = ""
        for isub, bad_ichans in enumerate(zap_list[iarch]):
            for bad_ichan in bad_ichans:
                if not all_subs:
                    lines.append("paz -m -I -z %d -w %d %s"
                                 % (bad_ichan, isub, paz_outfile))
                else:
                    line = "paz -m -z %d %s" % (bad_ichan, paz_outfile)
                    if line != last_line:
                        lines.append(line)
                    last_line = line
    out = open(outfile, "a") if outfile is not None else sys.stdout
    for line in lines:
        print(line, file=out)
    if outfile is not None:
        out.close()
        if not quiet:
            print("Wrote %s." % outfile)
    return lines
