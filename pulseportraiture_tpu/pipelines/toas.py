"""Wideband TOA measurement pipeline (pptoas equivalent).

TPU-native re-design of the reference's ``GetTOAs``
(/root/reference/pptoas.py:75-738): per archive, all subintegrations are
fit *in one batched device call* (initial FFTFIT guesses and the
5-parameter portrait fits both vmapped over subints, ragged zapped
channels handled as dense weight masks) instead of the reference's
serial per-subint scipy loop.  Result attributes keep the reference's
names and per-archive list structure so downstream tooling (zap, plots,
tim writing) carries over.
"""

import os
import sys
import threading
import time

import jax
import numpy as np

from .. import obs
from ..config import host_array, host_stats_device, scattering_alpha
from ..obs import metrics, tracing
from ..obs.metrics import PHASE_HISTOGRAM
from ..fit.phase_shift import fit_phase_shift
from ..fit.portrait import (auto_scan_size, bucket_batch_size,
                            fit_portrait_full_batch)
from ..fit.transforms import guess_fit_freq, phase_transform
from ..io.archive import file_is_type, load_data, parse_metafile
from ..io.gmodel import read_model
from ..io.splmodel import read_spline_model
from ..io.timfile import TOA, filter_TOAs, format_toa_line, write_TOAs
from ..ops.fourier import rotate_data
from ..ops.instrumental import instrumental_response_port_FT
from ..ops.scattering import scattering_portrait_FT, scattering_times
from ..ops.stats import weighted_mean
from ..testing import faults
from ..utils.databunch import DataBunch

__all__ = ["GetTOAs", "drop_checkpoint_blocks", "checkpoint_traces",
           "load_archive_data"]

# Per-checkpoint-file locks: the TOA service (service/daemon.py) runs
# several requests of one tenant concurrently to micro-batch their
# device dispatches, and those fits share the tenant's .tim checkpoint.
# Block+marker appends, the entry-time resume validation (which may
# REWRITE the file) and reconcile-time block drops must not interleave.
# Single-threaded callers pay one uncontended lock acquire per archive.
_CKPT_LOCKS = {}
_CKPT_LOCKS_GUARD = threading.Lock()


def _checkpoint_lock(checkpoint):
    key = os.path.realpath(checkpoint)
    with _CKPT_LOCKS_GUARD:
        lock = _CKPT_LOCKS.get(key)
        if lock is None:
            lock = _CKPT_LOCKS[key] = threading.RLock()
    return lock


def _nonfinite_guard(ports, errs_b, weights_b):
    """Pre-jit non-finite guard over one archive's fit batch.

    The Fourier-domain estimator (Taylor 1992 FFTFIT, extended to 2-D
    portraits by Pennucci+14) has no intrinsic defense against NaN/Inf
    inputs: one poisoned channel NaNs its subint's FFTs, weighted
    reductions and ultimately the whole batched fit.  Weights alone do
    not protect — ``NaN * 0 == NaN`` — so bad channels must be
    *scrubbed* before anything reaches the device.

    Returns ``(ports, errs_b, weights_b, bad_chan, n_zap, n_live)``:
    copies with every live channel containing a non-finite data sample
    or noise estimate zero-weighted and its data/noise replaced by
    finite placeholders (excluded from the fit by the zero weight
    anyway), the [B, nchan] bad-channel mask, the count of channels
    zapped, and the count of channels that were live going in.  The
    caller decides whether ``n_zap / n_live`` crosses the quarantine
    threshold (``nonfinite_max_frac``).
    """
    wok = weights_b > 0.0
    bad = (~np.isfinite(ports).all(axis=-1)
           | ~np.isfinite(errs_b)) & wok
    n_zap = int(bad.sum())
    if n_zap == 0:
        return ports, errs_b, weights_b, bad, 0, int(wok.sum())
    ports = np.where(bad[..., None], 0.0, ports)
    errs_b = np.where(bad, 1.0, errs_b)
    weights_b = np.where(bad, 0.0, weights_b)
    return ports, errs_b, weights_b, bad, n_zap, int(wok.sum())


def _trace_marker():
    """`` trace=<id>`` suffix for the ``pp_done`` marker line when a
    trace context is ambient (obs/tracing.py) — the checkpoint block
    then names the trace that produced it, so a replayed or
    reconciled block is causally auditable.  Both marker parsers
    tolerate the extra token (``len(tok) >= 4``); pre-trace
    checkpoints parse unchanged."""
    tid = tracing.current_trace_id()
    return " trace=%s" % tid if tid else ""


def checkpoint_traces(checkpoint):
    """{realpath(archive): trace_id} for every marked block of a
    checkpoint that carries a ``trace=`` token (tools/obs_trace.py's
    takeover/replay audit)."""
    out = {}
    try:
        with open(checkpoint) as cf:
            for ln in cf:
                tok = ln.split()
                if len(tok) >= 5 and tok[0] == "C" \
                        and tok[1] == "pp_done" \
                        and tok[4].startswith("trace="):
                    out[os.path.realpath(tok[2])] = tok[4][6:]
    except OSError:
        pass
    return out


def _resume_checkpoint(checkpoint, quiet=True):
    """Validate a crash-resume .tim checkpoint; return completed archives.

    Each archive's TOA block is terminated by a ``C pp_done <archive>
    <nlines>`` marker written in the same append as the block, so a
    crash mid-write leaves an unterminated (or count-mismatched) block.
    Such partial blocks are dropped — the file is rewritten atomically
    without them — and their archives refit on resume; otherwise a
    partially-recorded archive would be silently skipped with its
    remaining subint TOAs lost, or refit with its lines duplicated.

    Checkpoints written before the marker format existed (no pp_done
    lines at all) are honored for backward compatibility: every block
    but the trailing one — the only one a crash can have truncated —
    is accepted, and the file is rewritten with markers added so the
    next resume sees the current format.

    Returns a set of os.path.realpath-normalized archive names, so a
    resumed run matches archives regardless of path spelling (relative
    vs absolute vs './'-prefixed).
    """
    with _checkpoint_lock(checkpoint):
        return _resume_checkpoint_locked(checkpoint, quiet)


def _resume_checkpoint_locked(checkpoint, quiet):
    with open(checkpoint) as cf:
        lines = cf.readlines()
    has_markers = any(len(t) >= 4 and t[0] == "C" and t[1] == "pp_done"
                      for t in (ln.split() for ln in lines))
    if not has_markers:
        return _resume_markerless_checkpoint(checkpoint, lines, quiet)
    done, kept = set(), []
    buf_arch, buf = None, []
    dirty = False
    for ln in lines:
        tok = ln.split()
        if len(tok) >= 4 and tok[0] == "C" and tok[1] == "pp_done":
            arch, n = tok[2], tok[3]
            # buf_arch is None for a zero-TOA archive (all its TOAs
            # culled): a 0-count marker is then valid, not partial
            if (arch == buf_arch or buf_arch is None) and \
                    n.isdigit() and len(buf) == int(n):
                kept.extend(buf)
                kept.append(ln)
                done.add(os.path.realpath(arch))
            else:  # marker without its (complete) block: drop both
                dirty = True
            buf_arch, buf = None, []
        elif not tok or tok[0] in ("FORMAT", "C", "#"):
            kept.append(ln)
        else:  # a TOA line; first token is the archive name
            if buf_arch is not None and tok[0] != buf_arch:
                dirty = True  # interleaved block: treat as partial
                buf = []
            buf_arch = tok[0]
            buf.append(ln)
    if buf:  # trailing block with no marker: crash mid-archive
        dirty = True
    if dirty:
        tmp = checkpoint + ".tmp"
        with open(tmp, "w") as tf:
            tf.writelines(kept)
        os.replace(tmp, checkpoint)
        if not quiet:
            print(f"checkpoint {checkpoint}: dropped partial archive "
                  "blocks; they will be refit.")
    return done


def _resume_markerless_checkpoint(checkpoint, lines, quiet=True):
    """Legacy (pre-marker) checkpoint: accept every archive block except
    the trailing one, which a crash may have truncated; rewrite the file
    with pp_done markers so subsequent resumes use the current format."""
    done, kept = set(), []
    buf_arch, buf = None, []

    def flush():
        if buf:
            kept.extend(buf)
            kept.append(f"C pp_done {buf_arch} {len(buf)}\n")
            done.add(os.path.realpath(buf_arch))

    for ln in lines:
        tok = ln.split()
        if not tok or tok[0] in ("FORMAT", "C", "#"):
            kept.append(ln)
        else:
            if buf_arch is not None and tok[0] != buf_arch:
                flush()
                buf = []
            buf_arch = tok[0]
            buf.append(ln)
    # the trailing block is dropped (not flushed): with no marker there
    # is no way to tell a complete block from a mid-write crash
    dropped = len(buf)
    tmp = checkpoint + ".tmp"
    with open(tmp, "w") as tf:
        tf.writelines(kept)
    os.replace(tmp, checkpoint)
    if not quiet:
        print(f"checkpoint {checkpoint}: no pp_done markers (legacy "
              f"file, or a crash before the first marker); accepted "
              f"{len(done)} archives, refitting the trailing block "
              f"({dropped} TOA lines).")
    return done


def drop_checkpoint_blocks(checkpoint, archives):
    """Remove the TOA blocks (and their ``pp_done`` markers) of the
    given archives from a checkpoint .tim file, atomically.

    The survey runner's ledger/checkpoint reconciliation uses this:
    when the runner ledger says an archive is *pending* but the
    checkpoint already carries its block (a crash landed between the
    two appends, or the ledger was reset), the block is dropped so the
    archive REFITS — never silently skipped with half-trusted TOAs,
    never duplicated.  Archives are matched by ``os.path.realpath``
    like :func:`_resume_checkpoint`.  Returns the number of dropped
    blocks.
    """
    targets = {os.path.realpath(a) for a in archives}
    if not targets or not os.path.isfile(checkpoint):
        return 0
    with _checkpoint_lock(checkpoint):
        # checkpoint IO is the critical section the per-path RLock serializes (jaxlint J006)
        with open(checkpoint) as cf:  # jaxlint: disable=J006
            lines = cf.readlines()
        kept, dropped = [], 0
        for ln in lines:
            tok = ln.split()
            if len(tok) >= 4 and tok[0] == "C" and tok[1] == "pp_done":
                if os.path.realpath(tok[2]) in targets:
                    dropped += 1
                    continue
            elif tok and tok[0] not in ("FORMAT", "C", "#") and \
                    os.path.realpath(tok[0]) in targets:
                continue
            kept.append(ln)
        if dropped or len(kept) != len(lines):
            tmp = checkpoint + ".tmp"
            with open(tmp, "w") as tf:  # jaxlint: disable=J006 — atomic rewrite under the lock
                tf.writelines(kept)
            os.replace(tmp, checkpoint)
        return dropped


def _detect_model_type(modelfile):
    """'FITS' | 'spline' | 'gmodel' for a model file path."""
    kind = file_is_type(modelfile)
    if kind == "FITS":
        return "FITS"
    if kind == "ASCII":
        return "gmodel"
    return "spline"  # npz or legacy pickle container


# preload-table miss sentinel (None is a valid load outcome, so a
# plain dict.get default cannot stand in for "nothing was prefetched")
_PRELOAD_MISS = object()


def load_archive_data(datafile, tscrunch=False, quiet=True):
    """The host-side archive load shared by :meth:`GetTOAs._load_archive`
    and the prefetch stage (runner/prefetch.py): load_data with the
    reference's dmc-reload fallback (pptoas.py:216-233).  Returns the
    DataBunch or None on failure.  Because both the serial fit loop and
    the prefetch threads run this exact function, a prefetched buffer
    is bit-identical to a serial load and the ``archive_read`` fault
    site (io/archive.py) fires wherever the load actually runs.
    """
    try:
        data = load_data(datafile, dedisperse=False,
                         dededisperse=False, tscrunch=tscrunch,
                         pscrunch=True, rm_baseline=True,
                         refresh_arch=False, return_arch=False,
                         quiet=quiet)
        if data.dmc:
            data = load_data(datafile, dedisperse=False,
                             dededisperse=True, tscrunch=tscrunch,
                             pscrunch=True, rm_baseline=True,
                             refresh_arch=False, return_arch=False,
                             quiet=quiet)
        if not len(data.ok_isubs):
            if not quiet:
                print(f"No subints to fit for {datafile}; "
                      f"skipping it.")
            return None
        return data
    except (RuntimeError, ValueError, OSError) as e:
        if not quiet:
            print(f"Cannot load_data({datafile}): {e}; skipping it.")
        return None


class GetTOAs:
    """Measure wideband TOAs/DMs (+GM, tau, alpha) from archives.

    datafiles: archive path, list of paths, or metafile; modelfile: a
    .gmodel, spline container, or FITS template.  API and result
    attributes follow /root/reference/pptoas.py:75-148.
    """

    # per-archive result lists (names per the reference); the TOA
    # service's fitter pool resets exactly these between requests so a
    # long-lived instance cannot accumulate unbounded result state
    # (service/daemon.py)
    RESULT_ATTRS = (
        "order", "obs", "doppler_fs", "nu0s", "nu_fits",
        "nu_refs", "ok_idatafiles", "ok_isubs", "epochs",
        "MJDs", "Ps", "phis", "phi_errs", "TOAs", "TOA_errs",
        "DM0s", "DMs", "DM_errs", "DeltaDM_means",
        "DeltaDM_errs", "GMs", "GM_errs", "taus", "tau_errs",
        "alphas", "alpha_errs", "scales", "scale_errs",
        "snrs", "channel_snrs", "profile_fluxes",
        "profile_flux_errs", "fluxes", "flux_errs",
        "flux_freqs", "covariances", "red_chi2s", "nfevals",
        "rcs", "fit_durations", "n_nonfinite_zapped")

    def __init__(self, datafiles, modelfile, quiet=True):
        if isinstance(datafiles, str):
            if file_is_type(datafiles) == "ASCII":
                self.datafiles = parse_metafile(datafiles)
            else:
                self.datafiles = [datafiles]
        else:
            self.datafiles = list(datafiles)
        self.modelfile = modelfile
        self.model_type = _detect_model_type(modelfile)
        self.is_FITS_model = self.model_type == "FITS"
        self.quiet = quiet
        self.instrumental_response_dict = self.ird = \
            {"DM": 0.0, "wids": [], "irf_types": []}
        # archives dropped by the degraded modes: (datafile, reason) —
        # load failures stay silent-but-skipped as before; device/
        # tunnel failures are recorded here
        self.failed_datafiles = []
        # archives the non-finite guard refused to fit (too many
        # NaN/Inf channels): (datafile, reason).  The survey runner
        # quarantines these directly — retrying poisoned data is
        # pointless (runner/execute.py)
        self.poisoned_datafiles = []
        # batched-fit entry override (None = module-level
        # fit_portrait_full_batch, resolved at call time so tests can
        # monkeypatch the module attribute); the survey runner installs
        # a mesh-sharded fitter here (runner/execute.py)
        self.fit_batch = None
        # prefetched load outcomes keyed by realpath, installed by
        # preload() and consumed (once) by _load_archive — the hand-off
        # end of the host prefetch stage (runner/prefetch.py)
        self._preloaded = {}
        for attr in self.RESULT_ATTRS:
            setattr(self, attr, [])
        self.TOA_list = []

    # -- model construction --------------------------------------------
    def _build_model(self, freqs, phases, P, fit_scat):
        """Model portrait [nchan, nbin] at the given channel freqs.

        For fit_scat with a gmodel, the model's own scattering is
        stripped (the fit measures it), per pptoas.py:355-374.
        """
        nbin = len(phases)
        if self.model_type == "gmodel":
            if not fit_scat:
                name, ngauss, model = read_model(self.modelfile, phases,
                                                 freqs, P, quiet=True)
                self.model_name, self.ngauss = name, ngauss
            else:
                (self.model_name, self.model_code, self.model_nu_ref,
                 self.ngauss, self.gparams, _, self.alpha, _) = \
                    read_model(self.modelfile, quiet=True)
                from ..ops.profiles import gen_gaussian_portrait
                unscat = np.copy(self.gparams)
                unscat[1] = 0.0
                model = gen_gaussian_portrait(self.model_code, unscat, 0.0,
                                              phases, freqs,
                                              self.model_nu_ref)
            return np.asarray(model)
        elif self.model_type == "spline":
            self.model_name, model = read_spline_model(self.modelfile,
                                                       freqs, nbin,
                                                       quiet=True)
            return np.asarray(model)
        else:  # FITS template archive
            model_data = load_data(self.modelfile, dedisperse=False,
                                   tscrunch=True, pscrunch=True,
                                   rm_baseline=True, quiet=True)
            self.model_name = model_data.source
            model = (model_data.masks * model_data.subints)[0, 0]
            if model_data.nchan == 1:
                model = np.tile(model[0], (len(freqs), 1))
            return np.asarray(model)

    # -- archive loading with the dmc-reload degraded mode --------------
    def _load_archive(self, datafile, tscrunch, quiet):
        """load_archive_data, with prefetched outcomes replayed
        verbatim (see preload)."""
        hit = self._take_preloaded(datafile)
        if hit is not _PRELOAD_MISS:
            kind, val = hit
            if kind == "raise":
                raise val
            return val
        return load_archive_data(datafile, tscrunch=tscrunch,
                                 quiet=quiet)

    # -- host prefetch hand-off (runner/prefetch.py) --------------------
    def preload(self, datafile, outcome):
        """Install a prefetched load outcome for ``datafile``:
        ``("data", DataBunch_or_None)`` or ``("raise", exc)``.  The next
        ``_load_archive(datafile)`` replays it instead of touching the
        filesystem — returning or raising exactly what the serial load
        path would have, from the same call site, so result values and
        failure chains are identical whether the load ran inline or on
        a prefetch thread (docs/RUNNER.md "Host pipeline")."""
        self._preloaded[os.path.realpath(datafile)] = tuple(outcome)

    def _take_preloaded(self, datafile):
        """Pop the prefetched outcome for ``datafile`` (consume-once),
        or the module sentinel _PRELOAD_MISS when none was installed."""
        if not self._preloaded:
            return _PRELOAD_MISS
        return self._preloaded.pop(os.path.realpath(datafile),
                                   _PRELOAD_MISS)

    def _prepare_models(self, d, ports, freqs_b, Ps_b, fit_scat,
                        add_instrumental_response, datafile):
        """Per-batch model portraits [B, nchan, nbin] for one archive,
        shared by the wideband and narrowband drivers: per-subint models
        when channel frequencies differ, the FITS-template nbin check,
        and the optional instrumental-response convolution.  Returns
        None when the archive must be skipped."""
        nbin = ports.shape[-1]
        same_freqs = np.allclose(freqs_b, freqs_b[0])
        if same_freqs:
            model = self._build_model(freqs_b[0], d.phases,
                                      float(Ps_b[0]), fit_scat)
            models_b = np.broadcast_to(model, ports.shape)
        else:
            models_b = np.stack([
                self._build_model(freqs_b[i], d.phases, float(Ps_b[i]),
                                  fit_scat)
                for i in range(len(ports))])
        if self.is_FITS_model and models_b.shape[-1] != nbin:
            print(f"Model nbin != data nbin for {datafile}; "
                  f"skipping it.")
            return None, same_freqs
        if add_instrumental_response and (self.ird["DM"]
                                          or len(self.ird["wids"])):
            irFT = host_array(instrumental_response_port_FT(
                nbin, freqs_b[0], self.ird["DM"], float(Ps_b[0]),
                self.ird["wids"], self.ird["irf_types"]))
            models_b = np.fft.irfft(irFT * np.fft.rfft(models_b, axis=-1),
                                    nbin, axis=-1)
        return models_b, same_freqs

    # -- the main driver -----------------------------------------------
    @obs.scoped_run("pptoas")
    def get_TOAs(self, datafile=None, tscrunch=False, nu_refs=None,
                 DM0=None, bary=True, fit_DM=True, fit_GM=False,
                 fit_scat=False, log10_tau=True, scat_guess=None,
                 fix_alpha=False, print_phase=False, print_flux=False,
                 print_parangle=False, add_instrumental_response=False,
                 addtnl_toa_flags=None, method="trust-ncg", bounds=None,
                 nu_fits=None, show_plot=False, quiet=None,
                 max_iter=50, checkpoint=None, polish_iter=None,
                 coarse_iter=None, coarse_kmax=None,
                 nonfinite_max_frac=0.5):
        """Measure TOAs; results accumulate on self (reference-named).

        Equivalent of /root/reference/pptoas.py:150-738; ``method`` is
        accepted for API parity (the batched Newton solver replaces the
        scipy method choices).

        ``checkpoint``: path to a .tim file appended after EVERY archive
        (the reference writes its .tim only at the end, so a crashed
        multi-archive run loses all TOAs — SURVEY.md §5.3).  On entry,
        archives already present in the checkpoint are skipped, so a
        killed run resumes where it stopped.

        ``polish_iter`` / ``coarse_iter`` / ``coarse_kmax``: optional
        speed knobs for the hybrid f32+f64 fit (cap the f64 polish /
        the f32 stage's iterations / its harmonics).  Defaults keep
        exact behavior; the sub-0.01-ns trade each knob buys on the
        bench configs is measured in PERF.md (bench ships 4/12/64).

        ``nonfinite_max_frac``: the non-finite guard zero-weights
        NaN/Inf-poisoned channels (counted as ``n_nonfinite_zapped``)
        and fits the rest; an archive whose bad-channel fraction
        exceeds this threshold is refused instead (recorded on
        ``poisoned_datafiles`` — the survey runner quarantines it,
        docs/RUNNER.md).
        """
        if quiet is None:
            quiet = self.quiet
        self.nfit = 1 + int(fit_DM) + int(fit_GM) + \
            (2 if fit_scat else 0) - int(fit_scat and fix_alpha)
        self.fit_flags = [1, int(fit_DM), int(fit_GM), int(fit_scat),
                          int(fit_scat and not fix_alpha)]
        if not fit_scat:
            log10_tau = False
        self.log10_tau = log10_tau
        self.scat_guess = scat_guess
        self.DM0 = DM0
        self.bary = bary
        self.tscrunch = tscrunch
        self.add_instrumental_response = add_instrumental_response
        nu_ref_tuple = nu_refs
        nu_fit_tuple = nu_fits
        start = time.time()

        datafiles = self.datafiles if datafile is None else [datafile]
        obs.configure(pipeline="get_TOAs", modelfile=self.modelfile,
                      model_type=self.model_type,
                      n_datafiles=len(datafiles),
                      fit_flags=list(self.fit_flags),
                      log10_tau=log10_tau, max_iter=max_iter,
                      bary=bary, tscrunch=tscrunch,
                      checkpoint=checkpoint)
        done_archives = set()
        if checkpoint is not None and os.path.isfile(checkpoint):
            done_archives = _resume_checkpoint(checkpoint, quiet)
        for iarch, datafile in enumerate(datafiles):
            if os.path.realpath(datafile) in done_archives:
                if not quiet:
                    print(f"{datafile} already in checkpoint "
                          f"{checkpoint}; skipping it.")
                continue
            # per-archive phase spans (docs/OBSERVABILITY.md): load /
            # guess / solve / polish / write — no-ops unless a run is
            # open (PPTPU_OBS_DIR + obs.run, see @obs.scoped_run above)
            n_toa0 = len(self.TOA_list)
            ph = obs.phases(archive=datafile)
            ph.enter("load")
            data = self._load_archive(datafile, tscrunch, quiet)
            if data is None:
                ph.done(skipped="load_failed")
                continue
            d = data
            nsub, nchan, nbin = d.nsub, d.nchan, d.nbin
            fit_start = time.time()
            ok = np.asarray(d.ok_isubs)
            B = len(ok)
            DM_stored = d.DM
            DM0_arch = DM_stored if self.DM0 is None else self.DM0

            # dense per-subint views over the fit batch
            ports = d.subints[ok, 0]                      # [B, nchan, nbin]
            freqs_b = d.freqs[ok]                         # [B, nchan]
            weights_b = d.weights[ok]
            errs_b = d.noise_stds[ok, 0]
            SNRs_b = d.SNRs[ok, 0]
            Ps_b = d.Ps[ok]

            # non-finite guard: scrub or refuse BEFORE anything reaches
            # a weighted reduction or the device (NaN * 0 == NaN, so
            # zero weights alone cannot contain poisoned channels)
            ports, errs_b, weights_b, bad_chan, n_zap, n_live = \
                _nonfinite_guard(ports, errs_b, weights_b)
            if n_zap:
                frac = n_zap / max(n_live, 1)
                obs.event("nonfinite_guard", datafile=datafile,
                          n_zapped=n_zap, n_live=n_live,
                          frac=round(frac, 4),
                          quarantined=bool(frac > nonfinite_max_frac))
                obs.counter("n_nonfinite_zapped", n_zap)
                if frac > nonfinite_max_frac:
                    reason = ("non-finite data: %d/%d live channels "
                              "NaN/Inf (> nonfinite_max_frac=%.2f)"
                              % (n_zap, n_live, nonfinite_max_frac))
                    self.poisoned_datafiles.append((datafile, reason))
                    ph.done(skipped="nonfinite_poison")
                    if not quiet:
                        print(f"{datafile}: {reason}; not fitting it.")
                    continue
                SNRs_b = np.where(bad_chan, 0.0, SNRs_b)
            wok = (weights_b > 0.0).astype(np.float64)
            if n_zap:
                keep = wok.sum(-1) > 0
                if not keep.all():  # subints with no live channel left
                    ok, ports, freqs_b, weights_b, errs_b, SNRs_b, \
                        Ps_b, wok = (a[keep] for a in (
                            ok, ports, freqs_b, weights_b, errs_b,
                            SNRs_b, Ps_b, wok))
                    B = len(ok)
                    if B == 0:
                        self.poisoned_datafiles.append(
                            (datafile, "non-finite data: every subint "
                                       "lost all live channels"))
                        ph.done(skipped="nonfinite_poison")
                        continue

            # transient device/tunnel failures (the remote-
            # compile tunnel here has died mid-run for hours at
            # a time) must not kill a many-archive survey run:
            # the archive is recorded on failed_datafiles and
            # skipped, like any other unreadable archive
            n_okid = len(self.ok_idatafiles)
            try:
                models_b, _ = self._prepare_models(
                    d, ports, freqs_b, Ps_b, fit_scat,
                    add_instrumental_response, datafile)
                if models_b is None:
                    ph.done(skipped="model_mismatch")
                    continue
                self.ok_idatafiles.append(iarch)
                obs.event("archive", datafile=datafile, nsub=int(nsub),
                          nchan=int(nchan), nbin=int(nbin), B=int(B),
                          dtype=str(ports.dtype))

                ph.enter("guess")
                # reference frequencies for fit and output
                nu_means = (freqs_b * wok).sum(-1) / wok.sum(-1)
                if nu_fit_tuple is None:
                    # tiny per-subint reductions: pinned to the host device —
                    # through a remote-dispatch tunnel each device call costs
                    # a ~150-400 ms round trip, which at B calls per archive
                    # dominated the warm per-archive wall of the mixed-shape
                    # bench stage
                    with host_stats_device():
                        nu_fit = np.array([
                            float(np.asarray(guess_fit_freq(
                                freqs_b[i][wok[i] > 0],
                                SNRs_b[i][wok[i] > 0])))
                            for i in range(B)])
                    nu_fits_b = np.stack([nu_fit, nu_fit, nu_fit], axis=1)
                else:
                    nu_fits_b = np.tile([nu_fit_tuple[0], nu_fit_tuple[0],
                                         nu_fit_tuple[-1]], (B, 1))
                if nu_ref_tuple is None:
                    nu_outs_b = None
                else:
                    nu_ref_DM = nu_ref_tuple[0]
                    nu_ref_tau = nu_ref_tuple[-1]
                    # bary: the requested (barycentric) tau reference maps to
                    # a per-subint topocentric one (pptoas.py:410-415)
                    if bary and nu_ref_tau:
                        taus_ref = nu_ref_tau / d.doppler_factors[ok]
                    else:
                        taus_ref = np.full(B, np.nan if nu_ref_tau is None
                                           else nu_ref_tau)
                    col = np.full(B, np.nan if nu_ref_DM is None
                                  else nu_ref_DM)
                    nu_outs_b = (
                        None if nu_ref_DM is None else col,
                        None if nu_ref_DM is None else col,
                        None if nu_ref_tau is None else taus_ref)

                # -- initial guesses (batched) ------------------------------
                DM_guess = DM_stored
                # per-subint nu_mean reference folded into the shift via
                # broadcasting (nu_ref [B, 1] against freqs [B, nchan]):
                # ONE batched device call for the whole archive — the
                # previous per-subint loop paid B dispatch round trips
                # through the remote tunnel, and the removed same-freqs
                # fast path referenced every row to nu_means[0] while the
                # downstream phase_transform assumed each row's own
                # nu_means[i]
                rot_ports = np.asarray(rotate_data(ports, 0.0, DM_guess,
                                                   Ps_b, freqs_b,
                                                   nu_means[:, None]))
                # weighted band-average profiles
                rot_profs = (rot_ports * wok[..., None]).sum(1) / \
                    wok.sum(-1)[:, None]
                model_profs = (models_b * wok[..., None]).sum(1) / \
                    wok.sum(-1)[:, None]
                tau_guess = np.zeros(B)
                alpha_guess = np.zeros(B)
                if fit_scat:
                    if self.scat_guess is not None:
                        tg_s, tg_ref, ag = self.scat_guess
                        tau_guess[:] = (tg_s / Ps_b) * \
                            (nu_fits_b[:, 2] / tg_ref) ** ag
                        alpha_guess[:] = ag
                    else:
                        alpha_guess[:] = getattr(self, "alpha",
                                                 scattering_alpha)
                        if hasattr(self, "gparams"):
                            tau_guess[:] = (self.gparams[1] / Ps_b) * \
                                (nu_fits_b[:, 2] / self.model_nu_ref) \
                                ** alpha_guess
                    # scatter the model mean profile for the phase guess
                    taus_g = np.asarray(scattering_times(
                        tau_guess, alpha_guess, nu_fits_b[:, 2],
                        nu_fits_b[:, 2]))
                    spFT = host_array(scattering_portrait_FT(taus_g, nbin))
                    model_profs = np.fft.irfft(
                        spFT * np.fft.rfft(model_profs, axis=-1), nbin,
                        axis=-1)
                    if log10_tau:
                        tau_guess = np.log10(np.where(tau_guess == 0.0,
                                                      1.0 / nbin, tau_guess))
                guess = fit_phase_shift(rot_profs, model_profs,
                                        noise=np.asarray(
                                            np.median(errs_b, axis=-1)),
                                        Ns=100)
                phi_guess = np.asarray(phase_transform(
                    np.asarray(guess.phase), DM_guess, nu_means,
                    nu_fits_b[:, 0], Ps_b, mod=True))
                init = np.stack([phi_guess, np.full(B, DM_guess),
                                 np.zeros(B), tau_guess, alpha_guess], axis=1)

                if bounds is None:
                    tau_lo = np.log10(1.0 / (10 * nbin)) if log10_tau else 0.0
                    bounds_eff = [(None, None), (None, None), (None, None),
                                  (tau_lo, None), (-10.0, 10.0)] \
                        if fit_scat else None
                else:
                    bounds_eff = bounds

                # -- degraded modes: group subints by effective fit flags ---
                nchanx = wok.sum(-1).astype(int)
                flags_groups = {}
                flags_used = [None] * B
                for i in range(B):
                    if nchanx[i] == 1:
                        fl = (1, 0, 0, 0, 0)
                    elif nchanx[i] == 2 and fit_DM and fit_GM:
                        fl = (1, 1, 0, self.fit_flags[3], self.fit_flags[4])
                    else:
                        fl = tuple(self.fit_flags)
                    flags_used[i] = fl
                    flags_groups.setdefault(fl, []).append(i)

                ph.enter("solve", batch=int(B))
                # chaos site: an injected dispatch fault/hang stands in
                # for a wedged device or dead compile tunnel right at
                # the jit boundary (testing/faults.py)
                faults.check("dispatch", key=datafile)
                results = [None] * B
                # opt-in device profile of the fit dispatches
                # (PPTPU_TRACE_DIR; a no-op context otherwise)
                with obs.trace_capture("pptoas_arch%03d" % iarch):
                    for fl, idxs in flags_groups.items():
                        sel = np.asarray(idxs)
                        # long observations (hundreds of subints) run as
                        # a chunked scan: the compile footprint stays
                        # that of a 100-subint program (bigger monolithic
                        # batches can exhaust the compiler) while the
                        # whole archive stays one device dispatch.  Small
                        # batches are padded to a power-of-two bucket
                        # instead so archives with different subint
                        # counts share compiled programs — a mixed-survey
                        # metafile otherwise pays one multi-minute remote
                        # compile per distinct nsub
                        scan = auto_scan_size(len(sel))
                        fit = self.fit_batch or fit_portrait_full_batch
                        out = fit(
                            ports[sel], models_b[sel], init[sel],
                            Ps_b[sel], freqs_b[sel], errs=errs_b[sel],
                            weights=weights_b[sel], fit_flags=fl,
                            nu_fits=nu_fits_b[sel],
                            nu_outs=None if nu_outs_b is None else tuple(
                                None if col is None else col[sel]
                                for col in nu_outs_b),
                            bounds=bounds_eff, log10_tau=log10_tau,
                            max_iter=max_iter, scan_size=scan,
                            pad_to=None if scan is not None
                            else bucket_batch_size(len(sel)),
                            polish_iter=polish_iter,
                            coarse_iter=coarse_iter,
                            coarse_kmax=coarse_kmax)
                        # ONE host transfer for the whole result tree —
                        # per-key np.asarray would issue ~24 sequential
                        # device->host round trips per archive (each
                        # ~150-400 ms through a remote-dispatch tunnel);
                        # the host read is also the solve phase's device
                        # boundary, so its span needs no extra block
                        out = jax.device_get(dict(out))
                        for j, i in enumerate(idxs):
                            results[i] = {key: np.asarray(val)[j]
                                          for key, val in out.items()}
                fit_duration = time.time() - fit_start
            except jax.errors.JaxRuntimeError as e:
                del self.ok_idatafiles[n_okid:]
                self.failed_datafiles.append((datafile, str(e)))
                obs.counter("device_errors")
                ph.done(error="JaxRuntimeError")
                print(f"Device error fitting {datafile}: {e}; "
                      "skipping it.", file=sys.stderr)
                continue

            # -- assemble per-archive outputs ---------------------------
            ph.enter("polish")
            nu_refs_arr = np.zeros([nsub, 3])
            nu_fits_arr = np.zeros([nsub, 3])
            phis = np.zeros(nsub)
            phi_errs = np.zeros(nsub)
            TOAs_arr = np.zeros(nsub, dtype=object)
            TOA_errs_arr = np.zeros(nsub, dtype=object)
            DMs = np.zeros(nsub)
            DM_errs = np.zeros(nsub)
            GMs = np.zeros(nsub)
            GM_errs = np.zeros(nsub)
            taus_a = np.zeros(nsub)
            tau_errs = np.zeros(nsub)
            alphas = np.zeros(nsub)
            alpha_errs = np.zeros(nsub)
            scales_a = np.zeros([nsub, nchan])
            scale_errs_a = np.zeros([nsub, nchan])
            snrs = np.zeros(nsub)
            channel_snrs = np.zeros([nsub, nchan])
            profile_fluxes = np.zeros([nsub, nchan])
            profile_flux_errs = np.zeros([nsub, nchan])
            fluxes = np.zeros(nsub)
            flux_errs = np.zeros(nsub)
            flux_freqs = np.zeros(nsub)
            red_chi2s = np.zeros(nsub)
            covariances = np.zeros([nsub, 5, 5])
            nfevals = np.zeros(nsub, dtype=int)
            rcs = np.zeros(nsub, dtype=int)
            MJDs = np.array([d.epochs[isub].mjd() for isub in range(nsub)])

            for j, isub in enumerate(ok):
                r = results[j]
                P = float(Ps_b[j])
                epoch = d.epochs[isub]
                TOA_epoch = epoch.add_seconds(
                    float(r["phi"]) * P + d.backend_delay)
                TOA_err_us = float(r["phi_err"]) * P * 1e6
                DM_fit = float(r["DM"])
                GM_fit = float(r["GM"])
                df = float(d.doppler_factors[isub]) if bary else 1.0
                fl = list(flags_used[j])
                if bary:
                    if fl[1]:
                        DM_fit *= df  # barycentric DM
                    if fl[2]:
                        GM_fit *= df ** 3

                if print_flux:
                    okc = wok[j] > 0
                    mx = models_b[j][okc]
                    tau_lin = 10 ** float(r["tau"]) if log10_tau \
                        else float(r["tau"])
                    if tau_lin != 0.0 and fit_scat:
                        tausx = np.asarray(scattering_times(
                            tau_lin, float(r["alpha"]), freqs_b[j][okc],
                            float(r["nu_tau"])))
                        spFT = host_array(scattering_portrait_FT(tausx,
                                                                 nbin))
                        scat_model = np.fft.irfft(
                            spFT * np.fft.rfft(mx, axis=-1), nbin, axis=-1)
                    else:
                        scat_model = mx
                    means = scat_model.mean(axis=-1)
                    pf = means * np.asarray(r["scales"])[okc]
                    pfe = np.abs(means) * np.asarray(r["scale_errs"])[okc]
                    profile_fluxes[isub][okc] = pf
                    profile_flux_errs[isub][okc] = pfe
                    flux, flux_err = weighted_mean(pf, pfe)
                    flux_freq, _ = weighted_mean(freqs_b[j][okc], pfe)
                    fluxes[isub] = float(np.asarray(flux))
                    flux_errs[isub] = float(np.asarray(flux_err))
                    flux_freqs[isub] = float(np.asarray(flux_freq))

                nu_refs_arr[isub] = [float(r["nu_DM"]), float(r["nu_GM"]),
                                     float(r["nu_tau"])]
                nu_fits_arr[isub] = nu_fits_b[j]
                phis[isub] = float(r["phi"])
                phi_errs[isub] = float(r["phi_err"])
                TOAs_arr[isub] = TOA_epoch
                TOA_errs_arr[isub] = TOA_err_us
                DMs[isub] = DM_fit
                DM_errs[isub] = float(r["DM_err"])
                GMs[isub] = GM_fit
                GM_errs[isub] = float(r["GM_err"])
                taus_a[isub] = float(r["tau"])
                tau_errs[isub] = float(r["tau_err"])
                alphas[isub] = float(r["alpha"])
                alpha_errs[isub] = float(r["alpha_err"])
                okc = wok[j] > 0
                scales_a[isub][okc] = np.asarray(r["scales"])[okc]
                scale_errs_a[isub][okc] = np.asarray(r["scale_errs"])[okc]
                snrs[isub] = float(r["snr"])
                channel_snrs[isub][okc] = np.asarray(
                    r["channel_snrs"])[okc]
                cov = np.asarray(r["covariance_matrix"])
                ifit = np.flatnonzero(fl)
                covariances[isub][np.ix_(ifit, ifit)] = \
                    cov[:len(ifit)][:, :len(ifit)]
                red_chi2s[isub] = float(r["red_chi2"])
                nfevals[isub] = int(r["nfeval"])
                rcs[isub] = int(r["return_code"])

                toa_flags = {}
                DM_out, DM_err_out = DM_fit, float(r["DM_err"])
                if not fl[1]:
                    DM_out = DM_err_out = None
                if fl[2]:
                    toa_flags["gm"] = GM_fit
                    toa_flags["gm_err"] = float(r["GM_err"])
                if fl[3]:
                    if log10_tau:
                        toa_flags["scat_time"] = \
                            10 ** float(r["tau"]) * P / df * 1e6
                        toa_flags["log10_scat_time"] = float(r["tau"]) + \
                            np.log10(P / df)
                        toa_flags["log10_scat_time_err"] = \
                            float(r["tau_err"])
                    else:
                        toa_flags["scat_time"] = \
                            float(r["tau"]) * P / df * 1e6
                        toa_flags["scat_time_err"] = \
                            float(r["tau_err"]) * P / df * 1e6
                    toa_flags["scat_ref_freq"] = float(r["nu_tau"]) * df
                    toa_flags["scat_ind"] = float(r["alpha"])
                if fl[4]:
                    toa_flags["scat_ind_err"] = float(r["alpha_err"])
                freqsx = freqs_b[j][okc]
                toa_flags.update(
                    be=d.backend, fe=d.frontend,
                    f=f"{d.frontend}_{d.backend}", nbin=nbin, nch=nchan,
                    nchx=int(nchanx[j]),
                    bw=float(freqsx.max() - freqsx.min()),
                    chbw=abs(d.bw) / nchan, subint=int(isub),
                    tobs=float(d.subtimes[isub]),
                    fratio=float(freqsx.max() / freqsx.min()),
                    tmplt=self.modelfile, snr=float(r["snr"]))
                if nu_ref_tuple is not None and fl[0] and fl[1]:
                    toa_flags["phi_DM_cov"] = float(cov[0, 1])
                if bary and getattr(d, "doppler_degraded", False):
                    # the unity-Doppler fallback silently made the
                    # requested barycentric quantities topocentric
                    # (io/psrfits.py); mark the TOA so downstream
                    # analysis can tell (VERDICT r02 weak #6)
                    toa_flags["pp_topo"] = 1
                toa_flags["gof"] = float(r["red_chi2"])
                if print_phase:
                    toa_flags["phs"] = float(r["phi"])
                    toa_flags["phs_err"] = float(r["phi_err"])
                if print_flux:
                    toa_flags["flux"] = fluxes[isub]
                    toa_flags["flux_err"] = flux_errs[isub]
                    toa_flags["flux_ref_freq"] = flux_freqs[isub]
                if print_parangle:
                    toa_flags["par_angle"] = \
                        float(d.parallactic_angles[isub])
                toa_flags.update(addtnl_toa_flags or {})
                self.TOA_list.append(TOA(
                    datafile, float(r["nu_DM"]), TOA_epoch, TOA_err_us,
                    d.telescope, d.telescope_code, DM_out, DM_err_out,
                    toa_flags))

            # per-archive weighted DeltaDM with red-chi2 error inflation
            DeltaDMs = DMs[ok] - DM0_arch
            dm_errs_ok = DM_errs[ok]
            if np.all(dm_errs_ok):
                DM_weights = dm_errs_ok ** -2
            else:
                DM_weights = np.ones(len(dm_errs_ok))
            DeltaDM_mean = np.average(DeltaDMs, weights=DM_weights)
            DeltaDM_var = 1.0 / DM_weights.sum()
            if len(ok) > 1:
                DeltaDM_var *= np.sum(
                    (DeltaDMs - DeltaDM_mean) ** 2 * DM_weights) / \
                    (len(DeltaDMs) - 1)
            self.order.append(datafile)
            self.obs.append(DataBunch(telescope=d.telescope,
                                      backend=d.backend,
                                      frontend=d.frontend))
            self.doppler_fs.append(d.doppler_factors)
            self.nu0s.append(d.nu0)
            self.nu_fits.append(nu_fits_arr)
            self.nu_refs.append(nu_refs_arr)
            self.ok_isubs.append(ok)
            self.epochs.append(d.epochs)
            self.MJDs.append(MJDs)
            self.Ps.append(d.Ps)
            self.phis.append(phis)
            self.phi_errs.append(phi_errs)
            self.TOAs.append(TOAs_arr)
            self.TOA_errs.append(TOA_errs_arr)
            self.DM0s.append(DM0_arch)
            self.DMs.append(DMs)
            self.DM_errs.append(DM_errs)
            self.DeltaDM_means.append(DeltaDM_mean)
            self.DeltaDM_errs.append(DeltaDM_var ** 0.5)
            self.GMs.append(GMs)
            self.GM_errs.append(GM_errs)
            self.taus.append(taus_a)
            self.tau_errs.append(tau_errs)
            self.alphas.append(alphas)
            self.alpha_errs.append(alpha_errs)
            self.scales.append(scales_a)
            self.scale_errs.append(scale_errs_a)
            self.snrs.append(snrs)
            self.channel_snrs.append(channel_snrs)
            self.profile_fluxes.append(profile_fluxes)
            self.profile_flux_errs.append(profile_flux_errs)
            self.fluxes.append(fluxes)
            self.flux_errs.append(flux_errs)
            self.flux_freqs.append(flux_freqs)
            self.covariances.append(covariances)
            self.red_chi2s.append(red_chi2s)
            self.nfevals.append(nfevals)
            self.rcs.append(rcs)
            self.fit_durations.append(fit_duration)
            self.n_nonfinite_zapped.append(n_zap)
            # fit-quality fingerprint (obs/quality.py): one record per
            # archive, from the same host-side arrays the TOA lines
            # were built from (strictly after the device_get boundary)
            obs.quality.record_archive(
                datafile, red_chi2s[ok], phi_errs[ok] * Ps_b * 1e6,
                snrs=snrs[ok], rcs=rcs[ok], phis=phis[ok],
                phi_errs=phi_errs[ok], n_zapped=int(n_zap), isubs=ok,
                nsub=int(nsub), nchan=int(nchan))
            if checkpoint is not None:
                ph.enter("write", checkpoint=checkpoint)
                # chaos site: a flush failure here (full disk, kill)
                # leaves the ledger not-done with no block — the
                # reconcile/retry path must refit without duplicating
                faults.check("checkpoint_flush", key=datafile)
                # block + its pp_done marker go down in ONE append, so a
                # crash leaves either a complete marked block or an
                # unmarked partial one that _resume_checkpoint drops.
                # Only THIS call's TOAs are eligible: a same-process
                # retry after a failed flush would otherwise write the
                # archive's lines twice in one "valid" block
                arch_toas = filter_TOAs(
                    [t for t in self.TOA_list[n_toa0:]
                     if t.archive == datafile],
                    "snr", 0.0, ">=", pass_unflagged=False)
                blk = [format_toa_line(t) for t in arch_toas]
                blk.append("C pp_done %s %d%s"
                           % (datafile, len(blk), _trace_marker()))
                with metrics.timed(PHASE_HISTOGRAM,
                                   phase="checkpoint"), \
                        obs.span("checkpoint", checkpoint=checkpoint), \
                        _checkpoint_lock(checkpoint):
                    # the checkpoint append IS the critical section (jaxlint J006)
                    with open(checkpoint, "a") as cf:  # jaxlint: disable=J006
                        cf.write("".join(line + "\n" for line in blk))
            ph.done(fit_duration_s=round(fit_duration, 6),
                    n_toas=len(ok), n_nonfinite_zapped=n_zap)
            if not quiet:
                print("--------------------------")
                print(datafile)
                print("~%.4f sec/TOA" % (fit_duration / len(ok)))
                print("Med. TOA error is %.3f us"
                      % np.median(phi_errs[ok] * d.Ps.mean() * 1e6))
        if not quiet and len(self.ok_isubs):
            tot = time.time() - start
            ntoa = sum(len(o) for o in self.ok_isubs)
            print("--------------------------")
            print("Total time: %.2f sec, ~%.4f sec/TOA"
                  % (tot, tot / max(ntoa, 1)))

    # -- narrowband (per-channel) TOAs ----------------------------------
    @obs.scoped_run("pptoas")
    def get_narrowband_TOAs(self, datafile=None, tscrunch=False,
                            fit_scat=False, log10_tau=True,
                            scat_guess=None, print_phase=False,
                            print_flux=False, print_parangle=False,
                            add_instrumental_response=False,
                            addtnl_toa_flags=None, method="trust-ncg",
                            bounds=None, show_plot=False, quiet=None,
                            max_iter=50, checkpoint=None,
                            polish_iter=None,
                            coarse_iter=None, coarse_kmax=None,
                            nonfinite_max_frac=0.5):
        """Measure per-channel (narrowband) TOAs.

        Equivalent of /root/reference/pptoas.py:740-1125, re-designed as
        one device call per archive: every live (subint, channel)
        profile is fit in a single batched FFTFIT (grid matmul + Newton
        polish) instead of the reference's per-channel host loop.

        fit_scat=True fits a per-channel scattering time jointly with
        the phase — the reference declares this mode not yet implemented
        and zeroes tau; here each channel becomes a single-channel
        portrait through the 5-parameter kernel with fit_flags
        (phi, tau) so the scattering fit is real.  alpha and DM/GM are
        unidentifiable from one channel and stay fixed.

        ``polish_iter`` / ``coarse_iter`` / ``coarse_kmax``: speed
        knobs for the 5-parameter kernel (see get_TOAs / PERF.md) —
        they apply ONLY to the fit_scat=True path; the default
        phase-only mode runs the FFTFIT kernel, which never sees them.

        ``checkpoint``: same crash-resume .tim protocol as
        :meth:`get_TOAs` (block + ``C pp_done`` marker in one append
        per archive; archives already present are skipped), so the
        survey runner drives narrowband surveys through the identical
        ledger/lease/checkpoint machinery (``run_survey``'s
        ``narrowband=True``, docs/RUNNER.md).
        """
        if quiet is None:
            quiet = self.quiet
        self.nfit = 1 + 2 * int(fit_scat)
        self.fit_phi = True
        self.fit_tau = fit_scat
        self.fit_flags = [1, int(fit_scat)]
        if not fit_scat:
            log10_tau = False
        self.log10_tau = log10_tau
        self.scat_guess = scat_guess
        self.tscrunch = tscrunch
        self.add_instrumental_response = add_instrumental_response
        start = time.time()

        datafiles = self.datafiles if datafile is None else [datafile]
        obs.configure(pipeline="get_narrowband_TOAs",
                      modelfile=self.modelfile,
                      n_datafiles=len(datafiles), fit_scat=fit_scat,
                      log10_tau=log10_tau, max_iter=max_iter,
                      checkpoint=checkpoint)
        done_archives = set()
        if checkpoint is not None and os.path.isfile(checkpoint):
            done_archives = _resume_checkpoint(checkpoint, quiet)
        for iarch, datafile in enumerate(datafiles):
            if os.path.realpath(datafile) in done_archives:
                if not quiet:
                    print(f"{datafile} already in checkpoint "
                          f"{checkpoint}; skipping it.")
                continue
            n_toa0 = len(self.TOA_list)
            ph = obs.phases(archive=datafile)
            ph.enter("load")
            data = self._load_archive(datafile, tscrunch, quiet)
            if data is None:
                ph.done(skipped="load_failed")
                continue
            d = data
            nsub, nchan, nbin = d.nsub, d.nchan, d.nbin
            fit_start = time.time()
            ok = np.asarray(d.ok_isubs)
            B = len(ok)
            ports = d.subints[ok, 0]                      # [B, nchan, nbin]
            freqs_b = d.freqs[ok]
            weights_b = d.weights[ok]
            errs_b = d.noise_stds[ok, 0]
            Ps_b = d.Ps[ok]

            # non-finite guard (see get_TOAs): scrub poisoned channels
            # or refuse the archive before the per-channel fit batch
            ports, errs_b, weights_b, _, n_zap, n_live = \
                _nonfinite_guard(ports, errs_b, weights_b)
            if n_zap:
                frac = n_zap / max(n_live, 1)
                obs.event("nonfinite_guard", datafile=datafile,
                          n_zapped=n_zap, n_live=n_live,
                          frac=round(frac, 4),
                          quarantined=bool(frac > nonfinite_max_frac),
                          narrowband=True)
                obs.counter("n_nonfinite_zapped", n_zap)
                if frac > nonfinite_max_frac:
                    reason = ("non-finite data: %d/%d live channels "
                              "NaN/Inf (> nonfinite_max_frac=%.2f)"
                              % (n_zap, n_live, nonfinite_max_frac))
                    self.poisoned_datafiles.append((datafile, reason))
                    ph.done(skipped="nonfinite_poison")
                    if not quiet:
                        print(f"{datafile}: {reason}; not fitting it.")
                    continue
            wok = (weights_b > 0.0).astype(np.float64)

            # transient device/tunnel failures (the remote-
            # compile tunnel here has died mid-run for hours at
            # a time) must not kill a many-archive survey run:
            # the archive is recorded on failed_datafiles and
            # skipped, like any other unreadable archive
            n_okid = len(self.ok_idatafiles)
            try:
                models_b, _ = self._prepare_models(
                    d, ports, freqs_b, Ps_b, fit_scat,
                    add_instrumental_response, datafile)
                if models_b is None:
                    ph.done(skipped="model_mismatch")
                    continue
                self.ok_idatafiles.append(iarch)
                obs.event("archive", datafile=datafile, nsub=int(nsub),
                          nchan=int(nchan), nbin=int(nbin), B=int(B),
                          dtype=str(ports.dtype), narrowband=True)

                # flatten live (subint, channel) pairs into one fit batch
                jj, cc = np.nonzero(wok)                      # [M], [M]
                sub_idx = ok[jj]                 # archive subint index per fit
                profs = ports[jj, cc]                         # [M, nbin]
                mods = np.ascontiguousarray(models_b[jj, cc])
                errsx = errs_b[jj, cc]
                nusx = freqs_b[jj, cc]
                Psx = Ps_b[jj]
                M = len(jj)
                if M == 0:  # the guard zapped every live channel
                    self.poisoned_datafiles.append(
                        (datafile, "non-finite data: every live "
                                   "channel zapped"))
                    del self.ok_idatafiles[n_okid:]
                    ph.done(skipped="nonfinite_poison")
                    continue

                taus_fit = np.zeros(M)
                tau_errs_fit = np.zeros(M)
                covariances = np.zeros([nsub, nchan, self.nfit, self.nfit])
                nfevals = np.zeros([nsub, nchan], dtype=int)
                rcs_a = np.zeros([nsub, nchan], dtype=int)
                # caller bounds follow the reference's [(phi), (tau)] contract
                phi_bounds = (-0.5, 0.5)
                if bounds is not None and bounds[0] is not None \
                        and None not in bounds[0]:
                    phi_bounds = tuple(bounds[0])
                ph.enter("solve", batch=int(M))
                # chaos site: same jit-boundary fault stand-in as the
                # wideband driver (testing/faults.py)
                faults.check("dispatch", key=datafile)
                # opt-in device profile of the narrowband fit dispatches
                # (PPTPU_TRACE_DIR; a no-op context otherwise) — the
                # devtime ingestion attributes the capture by pp_* scope
                with obs.trace_capture("ppnbtoas_arch%03d" % iarch):
                    if not fit_scat:
                        r = jax.device_get(dict(fit_phase_shift(
                            profs, mods, noise=errsx, bounds=phi_bounds,
                            Ns=100)))  # one host transfer for all fields
                        phis_fit = np.asarray(r["phase"])
                        phi_errs_fit = np.asarray(r["phase_err"])
                        scales_fit = np.asarray(r["scale"])
                        scale_errs_fit = np.asarray(r["scale_err"])
                        snrs_fit = np.asarray(r["snr"])
                        red_chi2s_fit = np.asarray(r["red_chi2"])
                    else:
                        # per-channel tau guess at each channel's frequency
                        alpha_guess = getattr(self, "alpha", scattering_alpha)
                        if self.scat_guess is not None:
                            tg_s, tg_ref, alpha_guess = self.scat_guess
                            tau_g = (tg_s / Psx) * (nusx / tg_ref) ** alpha_guess
                        elif hasattr(self, "gparams"):
                            tau_g = (self.gparams[1] / Psx) * \
                                (nusx / self.model_nu_ref) ** alpha_guess
                        else:
                            tau_g = np.zeros(M)
                        # phase guess vs the scattered model
                        taus_g = np.asarray(scattering_times(tau_g, alpha_guess,
                                                             nusx, nusx))
                        spFT = host_array(scattering_portrait_FT(taus_g, nbin))
                        mods_scat = np.fft.irfft(spFT * np.fft.rfft(mods, axis=-1),
                                                 nbin, axis=-1)
                        guess = fit_phase_shift(profs, mods_scat, noise=errsx,
                                                Ns=100)
                        if log10_tau:
                            tau_g = np.log10(np.where(tau_g == 0.0, 1.0 / nbin,
                                                      tau_g))
                        init = np.stack([np.asarray(guess.phase),
                                         np.full(M, d.DM), np.zeros(M), tau_g,
                                         np.full(M, alpha_guess)], axis=1)
                        if bounds is None:
                            tau_lo = np.log10(1.0 / (10 * nbin)) if log10_tau \
                                else 0.0
                            bounds_eff = [(None, None), (None, None),
                                          (None, None), (tau_lo, None),
                                          (-10.0, 10.0)]
                        else:
                            bounds_eff = [tuple(bounds[0]), (None, None),
                                          (None, None), tuple(bounds[1]),
                                          (-10.0, 10.0)]
                        nb_scan = auto_scan_size(len(profs), profiles=True)
                        fit = self.fit_batch or fit_portrait_full_batch
                        out = fit(
                            profs[:, None, :], mods[:, None, :], init, Psx,
                            nusx[:, None], errs=errsx[:, None],
                            fit_flags=(1, 0, 0, 1, 0),
                            nu_fits=np.stack([nusx] * 3, axis=1),
                            bounds=bounds_eff, log10_tau=log10_tau,
                            max_iter=max_iter, scan_size=nb_scan,
                            pad_to=None if nb_scan is not None
                            else bucket_batch_size(len(profs)),
                            polish_iter=polish_iter, coarse_iter=coarse_iter,
                            coarse_kmax=coarse_kmax)
                        # one host transfer for the whole result tree (see
                        # the wideband driver)
                        out = jax.device_get(dict(out))
                        phis_fit = np.asarray(out["phi"])
                        phi_errs_fit = np.asarray(out["phi_err"])
                        taus_fit = np.asarray(out["tau"])
                        tau_errs_fit = np.asarray(out["tau_err"])
                        scales_fit = np.asarray(out["scales"])[:, 0]
                        scale_errs_fit = np.asarray(out["scale_errs"])[:, 0]
                        snrs_fit = np.asarray(out["snr"])
                        red_chi2s_fit = np.asarray(out["red_chi2"])
                        # (phi, tau) covariance block from the 5-param kernel's
                        # packed [nfit, nfit] matrix (fit order: phi, tau)
                        cov = np.asarray(out["covariance_matrix"])
                        covariances[sub_idx, cc, 0, 0] = cov[:, 0, 0]
                        covariances[sub_idx, cc, 0, 1] = cov[:, 0, 1]
                        covariances[sub_idx, cc, 1, 0] = cov[:, 1, 0]
                        covariances[sub_idx, cc, 1, 1] = cov[:, 1, 1]
                        nfevals[sub_idx, cc] = np.asarray(out["nfeval"])
                        rcs_a[sub_idx, cc] = np.asarray(out["return_code"])
                fit_duration = time.time() - fit_start
            except jax.errors.JaxRuntimeError as e:
                del self.ok_idatafiles[n_okid:]
                self.failed_datafiles.append((datafile, str(e)))
                obs.counter("device_errors")
                ph.done(error="JaxRuntimeError")
                print(f"Device error fitting {datafile}: {e}; "
                      "skipping it.", file=sys.stderr)
                continue

            # -- assemble per-archive [nsub, nchan] outputs -------------
            ph.enter("polish")
            phis = np.zeros([nsub, nchan])
            phi_errs = np.zeros([nsub, nchan])
            TOAs_arr = np.zeros([nsub, nchan], dtype=object)
            TOA_errs_arr = np.zeros([nsub, nchan], dtype=object)
            taus_a = np.zeros([nsub, nchan])
            tau_errs = np.zeros([nsub, nchan])
            scales_a = np.zeros([nsub, nchan])
            scale_errs_a = np.zeros([nsub, nchan])
            channel_snrs = np.zeros([nsub, nchan])
            profile_fluxes = np.zeros([nsub, nchan])
            profile_flux_errs = np.zeros([nsub, nchan])
            channel_red_chi2s = np.zeros([nsub, nchan])
            MJDs = np.array([d.epochs[isub].mjd() for isub in range(nsub)])

            phis[sub_idx, cc] = phis_fit
            phi_errs[sub_idx, cc] = phi_errs_fit
            taus_a[sub_idx, cc] = taus_fit
            tau_errs[sub_idx, cc] = tau_errs_fit
            scales_a[sub_idx, cc] = scales_fit
            scale_errs_a[sub_idx, cc] = scale_errs_fit
            channel_snrs[sub_idx, cc] = snrs_fit
            channel_red_chi2s[sub_idx, cc] = red_chi2s_fit

            if print_flux:
                # per-channel flux of the (scattered) scaled template
                if fit_scat:
                    tau_lin = 10 ** taus_fit if log10_tau else taus_fit
                    tausx = np.asarray(scattering_times(
                        tau_lin, scattering_alpha, nusx, nusx))
                    spFT = host_array(scattering_portrait_FT(tausx, nbin))
                    scat_mods = np.fft.irfft(
                        spFT * np.fft.rfft(mods, axis=-1), nbin, axis=-1)
                else:
                    scat_mods = mods
                means = scat_mods.mean(axis=-1)
                profile_fluxes[sub_idx, cc] = means * scales_fit
                profile_flux_errs[sub_idx, cc] = np.abs(means) * \
                    scale_errs_fit

            for m in range(M):
                isub = int(sub_idx[m])
                ichan = int(cc[m])
                P = float(Psx[m])
                epoch = d.epochs[isub]
                TOA_epoch = epoch.add_seconds(
                    float(phis_fit[m]) * P + d.backend_delay)
                TOA_err_us = float(phi_errs_fit[m]) * P * 1e6
                TOAs_arr[isub, ichan] = TOA_epoch
                TOA_errs_arr[isub, ichan] = TOA_err_us

                toa_flags = {}
                if fit_scat:
                    df = float(d.doppler_factors[isub])
                    if log10_tau:
                        toa_flags["scat_time"] = \
                            10 ** float(taus_fit[m]) * P / df * 1e6
                        toa_flags["log10_scat_time"] = \
                            float(taus_fit[m]) + np.log10(P / df)
                        toa_flags["log10_scat_time_err"] = \
                            float(tau_errs_fit[m])
                    else:
                        toa_flags["scat_time"] = \
                            float(taus_fit[m]) * P / df * 1e6
                        toa_flags["scat_time_err"] = \
                            float(tau_errs_fit[m]) * P / df * 1e6
                    toa_flags["phi_tau_cov"] = \
                        float(covariances[isub, ichan, 0, 1])
                    if getattr(d, "doppler_degraded", False):
                        toa_flags["pp_topo"] = 1  # unity-Doppler fallback
                toa_flags.update(
                    be=d.backend, fe=d.frontend,
                    f=f"{d.frontend}_{d.backend}", nbin=nbin,
                    bw=abs(d.bw) / nchan, subint=isub, chan=ichan,
                    tobs=float(d.subtimes[isub]), tmplt=self.modelfile,
                    snr=float(snrs_fit[m]),
                    gof=float(red_chi2s_fit[m]))
                if print_phase:
                    toa_flags["phs"] = float(phis_fit[m])
                    toa_flags["phs_err"] = float(phi_errs_fit[m])
                if print_flux:
                    toa_flags["flux"] = float(profile_fluxes[isub, ichan])
                    toa_flags["flux_err"] = \
                        float(profile_flux_errs[isub, ichan])
                if print_parangle:
                    toa_flags["par_angle"] = \
                        float(d.parallactic_angles[isub])
                toa_flags.update(addtnl_toa_flags or {})
                self.TOA_list.append(TOA(
                    datafile, float(nusx[m]), TOA_epoch, TOA_err_us,
                    d.telescope, d.telescope_code, None, None, toa_flags))

            self.order.append(datafile)
            self.obs.append(DataBunch(telescope=d.telescope,
                                      backend=d.backend,
                                      frontend=d.frontend))
            self.doppler_fs.append(d.doppler_factors)
            self.ok_isubs.append(ok)
            self.epochs.append(d.epochs)
            self.MJDs.append(MJDs)
            self.Ps.append(d.Ps)
            self.phis.append(phis)
            self.phi_errs.append(phi_errs)
            self.TOAs.append(TOAs_arr)
            self.TOA_errs.append(TOA_errs_arr)
            self.taus.append(taus_a)
            self.tau_errs.append(tau_errs)
            self.scales.append(scales_a)
            self.scale_errs.append(scale_errs_a)
            self.channel_snrs.append(channel_snrs)
            self.profile_fluxes.append(profile_fluxes)
            self.profile_flux_errs.append(profile_flux_errs)
            self.covariances.append(covariances)
            if not hasattr(self, "channel_red_chi2s"):
                self.channel_red_chi2s = []
            self.channel_red_chi2s.append(channel_red_chi2s)
            self.nfevals.append(nfevals)
            self.rcs.append(rcs_a)
            self.fit_durations.append(fit_duration)
            self.n_nonfinite_zapped.append(n_zap)
            # fit-quality fingerprint (obs/quality.py): per-channel
            # fits count as the quality subunits here; isubs names the
            # archive subint each (subint, channel) fit belongs to
            obs.quality.record_archive(
                datafile, red_chi2s_fit, phi_errs_fit * Psx * 1e6,
                snrs=snrs_fit, rcs=rcs_a[sub_idx, cc], phis=phis_fit,
                phi_errs=phi_errs_fit, n_zapped=int(n_zap),
                isubs=sub_idx, narrowband=True, nsub=int(nsub),
                nchan=int(nchan))
            if checkpoint is not None:
                ph.enter("write", checkpoint=checkpoint)
                # same protocol as the wideband driver: block + its
                # pp_done marker in ONE append, sliced to THIS call's
                # TOAs so a retry after a failed flush cannot double
                # the block (see get_TOAs)
                faults.check("checkpoint_flush", key=datafile)
                arch_toas = filter_TOAs(
                    [t for t in self.TOA_list[n_toa0:]
                     if t.archive == datafile],
                    "snr", 0.0, ">=", pass_unflagged=False)
                blk = [format_toa_line(t) for t in arch_toas]
                blk.append("C pp_done %s %d%s"
                           % (datafile, len(blk), _trace_marker()))
                with metrics.timed(PHASE_HISTOGRAM,
                                   phase="checkpoint"), \
                        obs.span("checkpoint", checkpoint=checkpoint), \
                        _checkpoint_lock(checkpoint):
                    # the checkpoint append IS the critical section (jaxlint J006)
                    with open(checkpoint, "a") as cf:  # jaxlint: disable=J006
                        cf.write("".join(line + "\n" for line in blk))
            ph.done(fit_duration_s=round(fit_duration, 6), n_toas=M,
                    n_nonfinite_zapped=n_zap)
            if not quiet:
                print("--------------------------")
                print(datafile)
                print("~%.4f sec/TOA" % (fit_duration / max(M, 1)))
                print("Med. TOA error is %.3f us"
                      % np.median(phi_errs_fit * Psx * 1e6))
        if not quiet and len(self.ok_isubs):
            tot = time.time() - start
            print("--------------------------")
            print("Total time: %.2f sec, ~%.4f sec/TOA"
                  % (tot, tot / max(len(self.TOA_list), 1)))

    def get_psrchive_TOAs(self, datafile=None, tscrunch=False,
                          algorithm="PGS", toa_format="tempo2",
                          flags="IPTA", attributes=("chan", "subint"),
                          quiet=None):
        """Narrowband TOAs via the external PSRCHIVE 'pat' machinery —
        a cross-validation hook against an independent implementation
        (ref /root/reference/pptoas.py:1127-1199).  Requires the
        optional ``psrchive`` python bindings; raises a clear
        RuntimeError when they are not installed (they are not part of
        this framework — the native equivalent is
        ``get_narrowband_TOAs``).  Results accumulate (as TOA-line
        strings per archive) on self.psrchive_toas.

        NOTE: unexercised in this environment — no psrchive install
        exists here, so tests cover only the RuntimeError gate
        (tests/test_pipeline_toas.py); the pat-driving body has never
        run against real bindings.  The independent cross-validation
        this hook exists for is covered WITHOUT psrchive by
        tests/test_timing_crossval.py: a from-the-spec tim parser +
        GLS oracle (tests/timing_oracle.py, Decimal arithmetic + scipy
        lstsq) validates the written tim format and the wideband GLS
        against committed expected results.
        """
        try:
            import psrchive as pr
        except ImportError as e:
            raise RuntimeError(
                "get_psrchive_TOAs needs the external PSRCHIVE python "
                "bindings (the cross-check path); use "
                "get_narrowband_TOAs for the native equivalent.") from e
        self.psrchive_toas = []
        arrtim = pr.ArrivalTime()
        arrtim.set_shift_estimator(algorithm)
        arrtim.set_format(toa_format)
        arrtim.set_format_flags(flags)
        arrtim.set_attributes(list(attributes))
        datafiles = self.datafiles if datafile is None else [datafile]
        if self.is_FITS_model:
            model_arch = pr.Archive_load(self.modelfile)
            model_arch.pscrunch()
            arrtim.set_standard(model_arch)
        for datafile in datafiles:
            arch = pr.Archive_load(datafile)
            arch.pscrunch()
            if tscrunch:
                arch.tscrunch()
            arrtim.set_observation(arch)
            if not self.is_FITS_model:
                # fill a clone with the evaluated model as the standard
                from ..ops.fourier import get_bin_centers

                nchan, nbin = arch.get_nchan(), arch.get_nbin()
                freqs = np.array([arch.get_Integration(0)
                                  .get_centre_frequency(ic)
                                  for ic in range(nchan)])
                P = arch.get_Integration(0).get_folding_period()
                model = self._build_model(
                    freqs, np.asarray(get_bin_centers(nbin)), P,
                    fit_scat=False)
                model_arch = arch.clone()
                model_arch.tscrunch()
                sub = model_arch.get_Integration(0)
                for ipol in range(arch.get_npol()):
                    for ichan in range(nchan):
                        prof = sub.get_Profile(ipol, ichan)
                        prof.get_amps()[:] = model[ichan]
                        sub.set_weight(ichan, 1.0)
                arrtim.set_standard(model_arch)
            self.psrchive_toas.append(arrtim.get_toas())
        return self.psrchive_toas

    def write_TOAs(self, outfile=None, nu_ref=None, format="tempo2",
                   SNR_cutoff=0.0, append=True):
        """Write the accumulated TOA_list to a .tim file."""
        with obs.span("write", outfile=outfile,
                      n_toas=len(self.TOA_list)):
            write_TOAs(self.TOA_list, SNR_cutoff=SNR_cutoff,
                       outfile=outfile, append=append)

    def write_princeton_TOAs(self, outfile=None, one_DM=False,
                             dmerrfile=None):
        """Write the accumulated TOAs in Princeton/tempo format.

        Implements the method the reference CLI calls but never defines
        (pptoas.py:1589): one line per TOA via
        io.timfile.write_princeton_TOA, with the dDM column from the
        per-subint fit (or the per-archive mean when ``one_DM``);
        ``dmerrfile`` appends the matching DM uncertainties.
        """
        from ..io.timfile import write_princeton_TOA

        dm_err_lines = []
        for toa in self.TOA_list:
            ifile = self.order.index(toa.archive)
            DM0 = self.DM0s[ifile] if ifile < len(self.DM0s) else 0.0
            if one_DM and ifile < len(self.DeltaDM_means):
                dDM = float(self.DeltaDM_means[ifile])
                dDM_err = float(self.DeltaDM_errs[ifile])
            elif toa.DM is not None:
                dDM = float(toa.DM) - DM0
                dDM_err = float(toa.DM_error)
            else:  # narrowband TOAs carry no DM measurement
                dDM = dDM_err = 0.0
            write_princeton_TOA(toa.MJD.intday(), toa.MJD.fracday(),
                                toa.TOA_error, toa.frequency, dDM,
                                obs=toa.telescope_code, outfile=outfile)
            dm_err_lines.append("%.5e" % dDM_err)
        if dmerrfile is not None:
            with open(dmerrfile, "a") as f:
                f.write("\n".join(dm_err_lines) + "\n")

    # -- post-fit channel zapping (reference pptoas.py:1201-1278) -------
    def return_fit(self, ifile, isub):
        """(rotated port, scaled model, ok_ichans, freqs, noise_stds) for
        one fitted subint — the return_fit payload of the reference's
        show_fit (pptoas.py:1280-1412), used by zapping/diagnostics."""
        from ..ops.stats import get_red_chi2  # noqa: F401  (for callers)

        datafile = self.order[ifile]
        if not hasattr(self, "_data_cache"):
            self._data_cache = {}
        if datafile not in self._data_cache:
            d = load_data(datafile, dedisperse=False, dededisperse=False,
                          tscrunch=self.tscrunch, pscrunch=True,
                          rm_baseline=True, refresh_arch=False,
                          return_arch=False, quiet=True)
            if d.dmc:
                d = load_data(datafile, dedisperse=False,
                              dededisperse=True, tscrunch=self.tscrunch,
                              pscrunch=True, rm_baseline=True,
                              refresh_arch=False, return_arch=False,
                              quiet=True)
            self._data_cache[datafile] = d
        d = self._data_cache[datafile]
        P = float(d.Ps[isub])
        freqs = d.freqs[isub]
        ok_ichans = d.ok_ichans[isub]
        port = d.subints[isub, 0]
        model = self._build_model(freqs, d.phases, P,
                                  bool(self.fit_flags[3]))
        if self.fit_flags[3]:
            tau = self.taus[ifile][isub]
            tau_lin = 10 ** tau if self.log10_tau else tau
            taus = np.asarray(scattering_times(
                tau_lin, self.alphas[ifile][isub], freqs,
                self.nu_refs[ifile][isub][2]))
            spFT = host_array(scattering_portrait_FT(taus, d.nbin))
            model = np.fft.irfft(spFT * np.fft.rfft(model, axis=-1),
                                 d.nbin, axis=-1)
        if self.add_instrumental_response and (self.ird["DM"]
                                               or len(self.ird["wids"])):
            irFT = host_array(instrumental_response_port_FT(
                d.nbin, freqs, self.ird["DM"], P, self.ird["wids"],
                self.ird["irf_types"]))
            model = np.fft.irfft(irFT * np.fft.rfft(model, axis=-1),
                                 d.nbin, axis=-1)
        model = self.scales[ifile][isub][:, None] * model
        df = float(d.doppler_factors[isub]) if self.bary else 1.0
        DM_topo = self.DMs[ifile][isub] / df  # undo bary correction
        rot_port = np.asarray(rotate_data(
            port, self.phis[ifile][isub], DM_topo, P, freqs,
            self.nu_refs[ifile][isub][0]))
        return rot_port, model, ok_ichans, freqs, d.noise_stds[isub, 0]

    def show_subint(self, ifile=0, isub=0, rotate=0.0, **kwargs):
        """Plot one fitted subintegration (ref pptoas.py:1280-1308)."""
        from ..viz import show_subint
        return show_subint(self, ifile=ifile, isub=isub, rotate=rotate,
                           **kwargs)

    def show_fit(self, ifile=0, isub=0, rotate=0.0, **kwargs):
        """Plot one subint's data/model/residuals
        (ref pptoas.py:1310-1412)."""
        from ..viz import show_fit
        return show_fit(self, ifile=ifile, isub=isub, rotate=rotate,
                        **kwargs)

    def get_channels_to_zap(self, SNR_threshold=8.0, rchi2_threshold=1.3,
                            iterate=True, show=False):
        """Flag channels for zapping from post-fit per-channel reduced
        chi2 (> rchi2_threshold or NaN) and channel S/N below the
        effective per-channel threshold (SNR_threshold^2/nchx)^0.5,
        iterating the S/N cut to convergence.  Fills
        self.channel_red_chi2s and self.zap_channels — both hold one
        entry per ARCHIVE subint (position == absolute subint index,
        empty for subints the fit skipped) so paz ``-w`` emission and
        ``apply_zaps`` address the right subints.  Equivalent of
        /root/reference/pptoas.py:1201-1278."""
        from ..ops.stats import get_red_chi2

        self.channel_red_chi2s = []
        self.zap_channels = []
        for ifile in range(len(self.order)):
            nsub_arch = len(self.Ps[ifile])
            channel_red_chi2s = [[] for _ in range(nsub_arch)]
            zap_channels = [[] for _ in range(nsub_arch)]
            for j, isub in enumerate(self.ok_isubs[ifile]):
                port, model, ok_ichans, freqs, noise_stds = \
                    self.return_fit(ifile, isub)
                channel_snrs = self.channel_snrs[ifile][isub]
                thresh = (SNR_threshold ** 2.0 / len(ok_ichans)) ** 0.5
                red_chi2s = []
                bad_ichans = []
                for ok_ichan in ok_ichans:
                    rc2 = float(np.asarray(get_red_chi2(
                        port[ok_ichan], model[ok_ichan],
                        errs=noise_stds[ok_ichan],
                        dof=len(port[ok_ichan]) - 2)))
                    red_chi2s.append(rc2)
                    if rc2 > rchi2_threshold or np.isnan(rc2):
                        bad_ichans.append(ok_ichan)
                    elif SNR_threshold and \
                            channel_snrs[ok_ichan] < thresh:
                        bad_ichans.append(ok_ichan)
                if iterate and SNR_threshold and len(bad_ichans):
                    old_len = len(bad_ichans)
                    added_new = True
                    while added_new and (len(ok_ichans) - len(bad_ichans)):
                        thresh = (SNR_threshold ** 2.0 /
                                  (len(ok_ichans) - len(bad_ichans))) ** 0.5
                        for ok_ichan in ok_ichans:
                            if ok_ichan in bad_ichans:
                                continue
                            if channel_snrs[ok_ichan] < thresh:
                                bad_ichans.append(ok_ichan)
                        added_new = bool(len(bad_ichans) - old_len)
                        old_len = len(bad_ichans)
                channel_red_chi2s[int(isub)] = red_chi2s
                zap_channels[int(isub)] = bad_ichans
            self.channel_red_chi2s.append(channel_red_chi2s)
            self.zap_channels.append(zap_channels)
        return self.zap_channels
