"""Iterative align-and-average pipeline (ppalign equivalent).

TPU-native re-design of the reference's ``align_archives``
(/root/reference/ppalign.py:54-243): per iteration, each archive's
subintegrations are phase/DM-fit against the running template *in one
batched device call* and accumulated with scales/noise weighting; the
weighted average becomes the next iteration's template.  The subprocess
wrappers around PSRCHIVE's psradd/psrsmooth are replaced with native
equivalents (average_archives; ops.wavelet smoothing for psrsmooth -W).
"""

import numpy as np

from ..fit.phase_shift import fit_phase_shift
from ..fit.portrait import fit_portrait_full_batch
from ..fit.transforms import guess_fit_freq
from ..io.archive import load_data, parse_metafile
from ..ops.fourier import rotate_data
from ..ops.normalize import normalize_portrait
from ..ops.profiles import gaussian_profile

__all__ = ["align_archives", "average_archives", "make_constant_portrait",
           "psrsmooth_archive"]


def make_constant_portrait(archive, outfile, profile=None, DM=0.0,
                           dmc=False, weights=None, quiet=True):
    """Fill a copy of ``archive`` with one profile in every channel.

    Native equivalent of /root/reference/pplib.py:958-994 (no PSRCHIVE
    round trip): profile defaults to the archive's full-scrunch average.
    """
    from ..io.archive import unload_new_archive
    from ..io.psrfits import read_archive

    arch = read_archive(archive)
    nsub, npol, nchan, nbin = arch.data.shape
    if profile is None:
        sc = arch.copy()
        sc.tscrunch()
        sc.pscrunch()
        sc.dedisperse()
        sc.fscrunch()
        profile = sc.data[0, 0, 0]
    profile = np.asarray(profile)
    if len(profile) != nbin:
        raise ValueError("len(profile) != number of bins in dummy archive")
    if weights is None:
        weights = np.ones([nsub, nchan])
    data = np.broadcast_to(profile, (nsub, npol, nchan, nbin))
    unload_new_archive(data, arch, outfile, DM=DM, dmc=int(dmc),
                       weights=weights, quiet=quiet)
    return outfile


def psrsmooth_archive(archive, options="-W", outfile=None, quiet=True):
    """Wavelet-smooth an archive's profiles and write '<archive>.sm'.

    Native equivalent of the reference's psrsmooth subprocess wrapper
    (/root/reference/ppalign.py:40-52): '-W' applies per-channel
    wavelet denoising (ops.wavelet.smart_smooth) to every
    subintegration/polarization of the stored data.
    """
    from ..io.psrfits import read_archive
    from ..ops.wavelet import smart_smooth

    arch = read_archive(archive)
    sm = arch.copy()
    nsub, npol = sm.data.shape[:2]
    for isub in range(nsub):
        for ipol in range(npol):
            sm.data[isub, ipol] = smart_smooth(sm.data[isub, ipol],
                                               fallback="raw")
    if outfile is None:
        outfile = archive + ".sm"
    sm.unload(outfile, quiet=quiet)
    return outfile


def average_archives(datafiles, outfile, palign=False, tscrunch=True,
                     quiet=True):
    """Native psradd equivalent: load archives, optionally phase-align on
    their band-average profiles (psradd -P analog), and average them into
    one archive written to ``outfile``.

    Replaces the subprocess wrapper /root/reference/ppalign.py:21-38.
    """
    if isinstance(datafiles, str):
        datafiles = parse_metafile(datafiles)
    total = None
    template_arch = None
    nused = 0
    ref_prof = None
    for f in datafiles:
        try:
            d = load_data(f, dedisperse=True, tscrunch=True, pscrunch=True,
                          rm_baseline=True, quiet=True)
        except (OSError, ValueError, RuntimeError):
            continue
        port = (d.masks * d.subints)[0, 0]
        if palign:
            prof = port.mean(axis=0)
            if ref_prof is None:
                ref_prof = prof
            else:
                shift = float(np.asarray(
                    fit_phase_shift(prof, ref_prof, Ns=d.nbin).phase))
                port = np.asarray(rotate_data(port, shift))
        if total is None:
            total = np.zeros_like(port)
            template_arch = d.arch
        if port.shape == total.shape:
            total += port
            nused += 1
    if nused == 0:
        raise ValueError("No loadable archives to average.")
    avg = total / nused
    arch = template_arch.copy()
    arch.tscrunch()
    arch.pscrunch()
    arch.data = avg[None, None]
    arch.unload(outfile, quiet=quiet)
    return outfile


def align_archives(metafile, initial_guess, fit_dm=True, tscrunch=False,
                   pscrunch=True, SNR_cutoff=0.0, outfile=None, norm=None,
                   rot_phase=0.0, place=None, niter=1, quiet=True,
                   max_iter=30):
    """Iteratively align + average archives against a template.

    metafile: metafile path or list of archive paths; initial_guess: a
    PSRFITS archive giving the starting template.  Behavior follows
    /root/reference/ppalign.py:54-243: per subint, (phase, DM) is fit
    against the template, subints are rotated and accumulated weighted
    by scales/noise**2, the average becomes the next template; the
    output archive gets DM=0 and dmc=0.

    Returns (outfile, aligned_port [npol, nchan, nbin], total_weights).
    """
    if isinstance(metafile, str):
        datafiles = parse_metafile(metafile)
        if outfile is None:
            outfile = metafile + ".algnd.fits"
    else:
        datafiles = list(metafile)
        if outfile is None:
            outfile = "aligned.fits"
    state = "Intensity" if pscrunch else "Stokes"
    npol = 1 if pscrunch else 4

    model_data = load_data(initial_guess, state=state, dedisperse=True,
                           tscrunch=True, pscrunch=pscrunch,
                           rm_baseline=True, refresh_arch=True,
                           return_arch=True, quiet=True)
    nchan, nbin = model_data.nchan, model_data.nbin
    model_port = (model_data.masks * model_data.subints)[0, 0]

    skip_these = set()
    aligned_port = np.zeros((npol, nchan, nbin))
    total_weights = np.zeros((nchan, nbin))
    for count in range(1, niter + 1):
        if not quiet:
            print(f"Doing iteration {count}...")
        aligned_port[:] = 0.0
        total_weights[:] = 0.0
        use_files = [f for f in datafiles if f not in skip_these]
        for datafile in use_files:
            try:
                d = load_data(datafile, state=state, dedisperse=False,
                              tscrunch=tscrunch, pscrunch=pscrunch,
                              rm_baseline=True, refresh_arch=False,
                              return_arch=False, quiet=True)
            except (OSError, ValueError, RuntimeError):
                skip_these.add(datafile)
                continue
            if d.nbin != nbin:
                skip_these.add(datafile)
                continue
            if d.prof_SNR < SNR_cutoff:
                skip_these.add(datafile)
                continue
            same_freqs = d.freqs.shape[-1] == nchan and \
                np.allclose(d.freqs[0], model_data.freqs[0])
            ok = np.asarray(d.ok_isubs)
            if not len(ok):
                continue
            B = len(ok)
            wok = (d.weights[ok] > 0.0).astype(float)
            # mask channels missing from the template too
            model_mask = np.zeros(nchan)
            model_mask[model_data.ok_ichans[0]] = 1.0
            if same_freqs:
                model_b = np.broadcast_to(model_port,
                                          (B, nchan, nbin)).copy()
                wok = wok * model_mask[None, :]
                chan_map = None
            else:
                # nearest-frequency template channels (ppalign.py:165-172)
                chan_map = np.argmin(np.abs(
                    model_data.freqs[0][None, :]
                    - d.freqs[0][:, None]), axis=1)
                model_b = np.broadcast_to(model_port[chan_map],
                                          (B, d.nchan, nbin)).copy()
            ports = d.subints[ok, 0]
            freqs_b = d.freqs[ok]
            errs_b = d.noise_stds[ok, 0]
            SNRs_b = d.SNRs[ok, 0]
            Ps_b = d.Ps[ok]
            DM_guess = d.DM

            nu_fit = np.array([
                float(np.asarray(guess_fit_freq(freqs_b[i][wok[i] > 0],
                                                SNRs_b[i][wok[i] > 0])))
                for i in range(B)])
            rot = np.stack([
                np.asarray(rotate_data(ports[i], 0.0, DM_guess,
                                       float(Ps_b[i]), freqs_b[i],
                                       nu_fit[i])) for i in range(B)])
            rot_profs = (rot * wok[..., None]).sum(1) / \
                np.maximum(wok.sum(-1), 1.0)[:, None]
            model_profs = (model_b * wok[..., None]).sum(1) / \
                np.maximum(wok.sum(-1), 1.0)[:, None]
            g = fit_phase_shift(rot_profs, model_profs,
                                noise=np.median(errs_b, axis=-1), Ns=nbin)
            init = np.zeros((B, 5))
            init[:, 0] = np.asarray(g.phase)
            init[:, 1] = DM_guess
            out = fit_portrait_full_batch(
                ports, model_b, init, Ps_b, freqs_b, errs=errs_b,
                weights=wok, fit_flags=(1, int(bool(fit_dm)), 0, 0, 0),
                nu_fits=np.stack([nu_fit] * 3, axis=1),
                log10_tau=False, max_iter=max_iter)
            phases_f = np.asarray(out.phi)
            DMs_f = np.asarray(out.DM)
            nu_refs_f = np.asarray(out.nu_DM)
            scales_f = np.asarray(out.scales)

            full = d.subints[ok]  # [B, npol, nchan, nbin]
            for j in range(B):
                okc = wok[j] > 0
                w = np.outer(scales_f[j][okc] / errs_b[j][okc] ** 2,
                             np.ones(nbin))
                rotated = np.asarray(rotate_data(
                    full[j][:, okc], phases_f[j], DMs_f[j],
                    float(Ps_b[j]), freqs_b[j][okc], nu_refs_f[j]))
                tchan = np.flatnonzero(okc) if chan_map is None \
                    else chan_map[okc]
                for ipol in range(npol):
                    aligned_port[ipol, tchan] += w * rotated[ipol]
                total_weights[tchan] += w
        nz = total_weights > 0
        for ipol in range(npol):
            aligned_port[ipol][nz] /= total_weights[nz]
        model_port = aligned_port[0].copy()

    if norm in ("mean", "max", "prof", "rms", "abs"):
        for ipol in range(npol):
            aligned_port[ipol] = np.asarray(
                normalize_portrait(aligned_port[ipol], norm))
    if rot_phase:
        aligned_port = np.asarray(rotate_data(aligned_port, rot_phase))
    if place is not None:
        prof = aligned_port[0].mean(axis=0)
        delta = prof.max() * np.asarray(
            gaussian_profile(nbin, place, 0.0001))
        phase = float(np.asarray(fit_phase_shift(prof, delta,
                                                 Ns=nbin).phase))
        aligned_port = np.asarray(rotate_data(aligned_port, phase))

    arch = model_data.arch.copy()
    arch.tscrunch()
    if pscrunch:
        arch.pscrunch()
    arch.DM = 0.0
    arch.dedispersed = False
    arch.data = np.asarray(aligned_port)[None]
    arch.weights = np.where(total_weights.sum(axis=-1) > 0.0, 1.0,
                            0.0)[None, :]
    arch.unload(outfile, quiet=quiet)
    return outfile, aligned_port, total_weights
