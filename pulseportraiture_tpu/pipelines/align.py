"""Iterative align-and-average pipeline (ppalign equivalent).

TPU-native re-design of the reference's ``align_archives``
(/root/reference/ppalign.py:54-243): per iteration, each archive's
subintegrations are phase/DM-fit against the running template *in one
batched device call* and accumulated with scales/noise weighting; the
weighted average becomes the next iteration's template.  The subprocess
wrappers around PSRCHIVE's psradd/psrsmooth are replaced with native
equivalents (average_archives; ops.wavelet smoothing for psrsmooth -W).
"""

import sys

import numpy as np

import jax
import jax.numpy as jnp

from .. import obs
from ..config import as_fft_operand
from ..fit.phase_shift import fit_phase_shift
from ..fit.portrait import fit_portrait_full_batch
from ..io.archive import load_data, parse_metafile
from ..ops.fourier import apply_phasor, phase_shifts, rotate_data
from ..ops.normalize import normalize_portrait
from ..ops.profiles import gaussian_profile

__all__ = ["align_archives", "average_archives", "make_constant_portrait",
           "psrsmooth_archive"]


def make_constant_portrait(archive, outfile, profile=None, DM=0.0,
                           dmc=False, weights=None, quiet=True):
    """Fill a copy of ``archive`` with one profile in every channel.

    Native equivalent of /root/reference/pplib.py:958-994 (no PSRCHIVE
    round trip): profile defaults to the archive's full-scrunch average.
    """
    from ..io.archive import unload_new_archive
    from ..io.psrfits import read_archive

    arch = read_archive(archive)
    nsub, npol, nchan, nbin = arch.data.shape
    if profile is None:
        sc = arch.copy()
        sc.tscrunch()
        sc.pscrunch()
        sc.dedisperse()
        sc.fscrunch()
        profile = sc.data[0, 0, 0]
    profile = np.asarray(profile)
    if len(profile) != nbin:
        raise ValueError("len(profile) != number of bins in dummy archive")
    if weights is None:
        weights = np.ones([nsub, nchan])
    data = np.broadcast_to(profile, (nsub, npol, nchan, nbin))
    unload_new_archive(data, arch, outfile, DM=DM, dmc=int(dmc),
                       weights=weights, quiet=quiet)
    return outfile


def psrsmooth_archive(archive, options="-W", outfile=None, quiet=True):
    """Wavelet-smooth an archive's profiles and write '<archive>.sm'.

    Native equivalent of the reference's psrsmooth subprocess wrapper
    (/root/reference/ppalign.py:40-52): '-W' applies per-channel
    wavelet denoising (ops.wavelet.smart_smooth) to every
    subintegration/polarization of the stored data.
    """
    from ..io.psrfits import read_archive
    from ..ops.wavelet import smart_smooth

    arch = read_archive(archive)
    sm = arch.copy()
    nsub, npol = sm.data.shape[:2]
    for isub in range(nsub):
        for ipol in range(npol):
            sm.data[isub, ipol] = smart_smooth(sm.data[isub, ipol],
                                               fallback="raw")
    if outfile is None:
        outfile = archive + ".sm"
    sm.unload(outfile, quiet=quiet)
    return outfile


def average_archives(datafiles, outfile, palign=False, tscrunch=True,
                     pscrunch=True, quiet=True):
    """Native psradd equivalent: load archives, optionally phase-align on
    their band-average profiles (psradd -P analog), and average them into
    one archive written to ``outfile``.

    ``pscrunch=False`` keeps all four polarizations (ppalign -p's
    psradd call), averaging in the Stokes basis; the alignment shift is
    still measured on total intensity and applied to every pol.
    Replaces the subprocess wrapper /root/reference/ppalign.py:21-38.
    """
    if isinstance(datafiles, str):
        datafiles = parse_metafile(datafiles)
    state = "Intensity" if pscrunch else "Stokes"
    total = None
    template_arch = None
    nused = 0
    ref_prof = None
    for f in datafiles:
        try:
            d = load_data(f, state=state, dedisperse=True, tscrunch=True,
                          pscrunch=pscrunch, rm_baseline=True, quiet=True)
        except NotImplementedError as e:
            # e.g. -p on an already-pscrunched archive: skipped, like
            # the reference's ppalign ("converted or skipped")
            print(f"Skipping {f}: cannot convert to {state} ({e})",
                  file=sys.stderr)
            continue
        except (OSError, ValueError, RuntimeError):
            continue
        port = (d.masks * d.subints)[0]            # [npol, nchan, nbin]
        if palign:
            prof = port[0].mean(axis=0)            # Stokes I / intensity
            if ref_prof is None:
                ref_prof = prof
            else:
                shift = float(np.asarray(
                    fit_phase_shift(prof, ref_prof, Ns=d.nbin).phase))
                port = np.asarray(rotate_data(port, shift))
        if total is None:
            total = np.zeros_like(port)
            template_arch = d.arch
        if port.shape == total.shape:
            total += port
            nused += 1
    if nused == 0:
        raise ValueError("No loadable archives to average.")
    avg = total / nused
    arch = template_arch.copy()
    arch.tscrunch()
    if pscrunch:
        arch.pscrunch()
    # pscrunch=False: arch came through load_data(state="Stokes"), so
    # it is already Stokes (inconvertible files were skipped above)
    arch.data = avg[None]
    arch.unload(outfile, quiet=quiet)
    return outfile


@jax.jit
def _rotate_batch(data, phis, DMs, Ps, freqs, nu_refs):
    """Rotate [B, (npol,) nchan, nbin] by per-subint (phi, DM) in ONE
    device call — the latency-critical op of the align loop (each
    archive used to pay its own device round trips)."""
    data = jnp.asarray(data)
    shifts = phase_shifts(jnp.asarray(phis)[:, None],
                          jnp.asarray(DMs)[:, None], 0.0,
                          jnp.asarray(freqs),
                          jnp.asarray(nu_refs)[:, None], jnp.inf,
                          jnp.asarray(Ps)[:, None])        # [B, nchan]
    if data.ndim == 4:
        shifts = shifts[:, None, :]
    FT = jnp.fft.rfft(as_fft_operand(data), axis=-1)
    return jnp.fft.irfft(apply_phasor(FT, shifts), n=data.shape[-1],
                         axis=-1)


def _guess_fit_freqs_np(freqs, SNRs, mask):
    """Masked SNR*nu^-2-weighted frequency per subint (numpy batch of
    fit.transforms.guess_fit_freq; host-side — it feeds device calls).
    Rows with no valid channels fall back to the unmasked mean frequency
    (their weights are zero everywhere downstream)."""
    any_ok = (mask > 0).any(axis=-1)
    big = np.where(mask > 0, freqs, np.nan)
    with np.errstate(all="ignore"):
        nu0 = np.where(
            any_ok,
            0.5 * (np.nanmin(np.where(any_ok[:, None], big, 0.0), axis=-1)
                   + np.nanmax(np.where(any_ok[:, None], big, 0.0),
                               axis=-1)),
            freqs.mean(axis=-1))
    w = np.where(mask > 0, SNRs * freqs ** -2.0, 0.0)
    nu = nu0 + np.sum((freqs - nu0[:, None]) * w, axis=-1) / \
        np.maximum(w.sum(axis=-1), 1e-300)
    return np.where(any_ok, nu, freqs.mean(axis=-1))


def _pad_rows(nrows, chunk_max):
    """Block size for ``nrows`` live rows: the next power of two (>= 8),
    capped at chunk_max — a handful of compiled shapes total."""
    b = 8
    while b < nrows:
        b *= 2
    return min(b, chunk_max)


def _assemble_block(rows, model_port, dnchan, nchan, nbin, npol,
                    chunk_max):
    """One padded [B, ...] block from a list of (entry, j) subint rows.

    Padding rows carry zero data, zero weights, and the template as
    their model (so the fit stays finite); their zero weights drop them
    from the accumulation."""
    B = _pad_rows(len(rows), chunk_max)
    full = np.zeros((B, npol, dnchan, nbin))
    pad_model = model_port if dnchan == nchan \
        else model_port[np.arange(dnchan) % nchan]
    model_b = np.broadcast_to(pad_model, (B, dnchan, nbin)).copy()
    freqs_b = np.ones((B, dnchan))
    errs_b = np.ones((B, dnchan))
    SNRs_b = np.zeros((B, dnchan))
    Ps_b = np.ones(B)
    wok = np.zeros((B, dnchan))
    DMg = np.zeros(B)
    chan_maps = []
    owners = np.zeros(B, dtype=int)
    for r, (e, j) in enumerate(rows):
        full[r] = e["full"][j]
        cm = e["chan_map"]
        model_b[r] = model_port if cm is None else model_port[cm]
        freqs_b[r] = e["freqs"][j]
        errs_b[r] = e["errs"][j]
        SNRs_b[r] = e["SNRs"][j]
        Ps_b[r] = e["Ps"][j]
        wok[r] = e["wok"][j]
        DMg[r] = e["DM"]
        chan_maps.append(cm)
        owners[r] = r
    return (full, model_b, freqs_b, errs_b, SNRs_b, Ps_b, wok, DMg,
            owners), chan_maps


def _align_fit_accumulate(full, model_b, freqs_b, errs_b, SNRs_b, Ps_b,
                          wok, DMg, owners, chan_maps, fit_dm, max_iter,
                          nbin, npol, aligned_port, total_weights):
    """One batched align pass over a [B, npol, nchan, nbin] subint block:
    seed (dedisperse + profile FFTFIT), (phi, DM) portrait fit, rotate,
    and accumulate into aligned_port/total_weights (in place)."""
    ports = full[:, 0]
    nu_fit = _guess_fit_freqs_np(freqs_b, SNRs_b, wok)
    rot = np.asarray(_rotate_batch(ports, np.zeros(len(Ps_b)), DMg, Ps_b,
                                   freqs_b, nu_fit))
    denom = np.maximum(wok.sum(-1), 1.0)[:, None]
    rot_profs = (rot * wok[..., None]).sum(1) / denom
    model_profs = (model_b * wok[..., None]).sum(1) / denom
    g = fit_phase_shift(rot_profs, model_profs,
                        noise=np.median(errs_b, axis=-1), Ns=nbin)
    init = np.zeros((len(Ps_b), 5))
    init[:, 0] = np.nan_to_num(np.asarray(g.phase))
    init[:, 1] = DMg
    out = fit_portrait_full_batch(
        ports, model_b, init, Ps_b, freqs_b, errs=errs_b, weights=wok,
        fit_flags=(1, int(bool(fit_dm)), 0, 0, 0),
        nu_fits=np.stack([nu_fit] * 3, axis=1), log10_tau=False,
        max_iter=max_iter)
    scales_f = np.asarray(out.scales)
    # padded / fully-zapped rows can carry non-finite fit results; their
    # weights are zero, but 0*nan would still poison the accumulation
    phi_f = np.nan_to_num(np.asarray(out.phi))
    DM_f = np.nan_to_num(np.asarray(out.DM))
    nu_f = np.nan_to_num(np.asarray(out.nu_DM), nan=1.0)
    rotated = np.nan_to_num(np.asarray(_rotate_batch(
        full, phi_f, DM_f, Ps_b, freqs_b, nu_f)))
    errs_safe = np.where(wok > 0, errs_b, 1.0)  # dead channels: no 1/0
    w_bc = np.nan_to_num(
        np.where(wok > 0, scales_f / errs_safe ** 2, 0.0))  # [B, nchan]
    same = all(chan_maps[i] is None for i in set(owners.tolist()))
    if same:
        aligned_port += np.einsum("bc,bpcn->pcn", w_bc, rotated)
        total_weights += w_bc.sum(0)[:, None]
    else:
        for j in range(len(Ps_b)):
            cm = chan_maps[owners[j]]
            okc = wok[j] > 0
            tchan = np.flatnonzero(okc) if cm is None else cm[okc]
            wcol = w_bc[j][okc][:, None]
            for ipol in range(npol):
                np.add.at(aligned_port[ipol], tchan,
                          wcol * rotated[j, ipol, okc])
            np.add.at(total_weights, tchan,
                      np.broadcast_to(wcol, (len(tchan), nbin)))


@obs.scoped_run("ppalign")
def align_archives(metafile, initial_guess, fit_dm=True, tscrunch=False,
                   pscrunch=True, SNR_cutoff=0.0, outfile=None, norm=None,
                   rot_phase=0.0, place=None, niter=1, quiet=True,
                   max_iter=30):
    """Iteratively align + average archives against a template.

    metafile: metafile path or list of archive paths; initial_guess: a
    PSRFITS archive giving the starting template.  Behavior follows
    /root/reference/ppalign.py:54-243: per subint, (phase, DM) is fit
    against the template, subints are rotated and accumulated weighted
    by scales/noise**2, the average becomes the next template; the
    output archive gets DM=0 and dmc=0.

    Returns (outfile, aligned_port [npol, nchan, nbin], total_weights).
    """
    if isinstance(metafile, str):
        datafiles = parse_metafile(metafile)
        if outfile is None:
            outfile = metafile + ".algnd.fits"
    else:
        datafiles = list(metafile)
        if outfile is None:
            outfile = "aligned.fits"
    state = "Intensity" if pscrunch else "Stokes"
    npol = 1 if pscrunch else 4

    model_data = load_data(initial_guess, state=state, dedisperse=True,
                           tscrunch=True, pscrunch=pscrunch,
                           rm_baseline=True, refresh_arch=True,
                           return_arch=True, quiet=True)
    nchan, nbin = model_data.nchan, model_data.nbin
    model_port = (model_data.masks * model_data.subints)[0, 0]
    obs.configure(pipeline="align_archives", n_datafiles=len(datafiles),
                  nchan=int(nchan), nbin=int(nbin), niter=int(niter),
                  fit_dm=bool(fit_dm), outfile=outfile)

    skip_these = set()
    aligned_port = np.zeros((npol, nchan, nbin))
    total_weights = np.zeros((nchan, nbin))
    model_mask = np.zeros(nchan)
    model_mask[model_data.ok_ichans[0]] = 1.0
    # device-call budget: archives are loaded on the host, concatenated
    # into per-(nchan) groups, and every group runs the whole iteration
    # in a handful of batched device programs (rotate / seed / fit /
    # rotate) instead of several calls per archive — at 500 homogeneous
    # archives the difference is ~2000 tunnel round trips vs ~8
    chunk_max = 128
    for count in range(1, niter + 1):
        if not quiet:
            print(f"Doing iteration {count}...")
        aligned_port[:] = 0.0
        total_weights[:] = 0.0
        use_files = [f for f in datafiles if f not in skip_these]
        # streaming assembly: rows queue per channelization; full blocks
        # flush as soon as chunk_max rows are pending, so memory stays
        # bounded by ~chunk_max subints + the archive being loaded (the
        # 500-archive case never holds 500 archives at once)
        pending = {}

        def flush(dnchan, force=False):
            rows = pending.get(dnchan, [])
            while len(rows) >= chunk_max or (force and rows):
                take, rows = rows[:chunk_max], rows[chunk_max:]
                block, cmaps = _assemble_block(
                    take, model_port, dnchan, nchan, nbin, npol,
                    chunk_max)
                # the accumulate ends in host numpy ops, so the span's
                # device boundary is inherent — no explicit block needed
                with obs.span("solve", iteration=count, nchan=dnchan,
                              rows=len(take)):
                    _align_fit_accumulate(
                        *block, chan_maps=cmaps, fit_dm=fit_dm,
                        max_iter=max_iter, nbin=nbin, npol=npol,
                        aligned_port=aligned_port,
                        total_weights=total_weights)
            pending[dnchan] = rows

        for datafile in use_files:
            try:
                with obs.span("load", archive=datafile):
                    d = load_data(datafile, state=state,
                                  dedisperse=False, tscrunch=tscrunch,
                                  pscrunch=pscrunch, rm_baseline=True,
                                  refresh_arch=False, return_arch=False,
                                  quiet=True)
            except NotImplementedError as e:
                print(f"Skipping {datafile}: cannot convert to {state} "
                      f"({e})", file=sys.stderr)
                skip_these.add(datafile)
                continue
            except (OSError, ValueError, RuntimeError):
                skip_these.add(datafile)
                continue
            if d.nbin != nbin:
                skip_these.add(datafile)
                continue
            if d.prof_SNR < SNR_cutoff:
                skip_these.add(datafile)
                continue
            same_freqs = d.freqs.shape[-1] == nchan and \
                np.allclose(d.freqs[0], model_data.freqs[0])
            ok = np.asarray(d.ok_isubs)
            if not len(ok):
                continue
            wok = (d.weights[ok] > 0.0).astype(float)
            if same_freqs:
                wok = wok * model_mask[None, :]
                chan_map = None
            else:
                # nearest-frequency template channels (ppalign.py:165-172)
                chan_map = np.argmin(np.abs(
                    model_data.freqs[0][None, :]
                    - d.freqs[0][:, None]), axis=1)
            entry = dict(
                full=np.asarray(d.subints[ok]), freqs=np.asarray(d.freqs[ok]),
                errs=np.asarray(d.noise_stds[ok, 0]),
                SNRs=np.asarray(d.SNRs[ok, 0]), Ps=np.asarray(d.Ps[ok]),
                wok=wok, chan_map=chan_map, DM=float(d.DM))
            dnchan = d.freqs.shape[-1]
            pending.setdefault(dnchan, []).extend(
                (entry, j) for j in range(len(ok)))
            flush(dnchan)

        for dnchan in list(pending):
            flush(dnchan, force=True)
        nz = total_weights > 0
        for ipol in range(npol):
            aligned_port[ipol][nz] /= total_weights[nz]
        model_port = aligned_port[0].copy()

    if norm in ("mean", "max", "prof", "rms", "abs"):
        for ipol in range(npol):
            aligned_port[ipol] = np.asarray(
                normalize_portrait(aligned_port[ipol], norm))
    if rot_phase:
        aligned_port = np.asarray(rotate_data(aligned_port, rot_phase))
    if place is not None:
        prof = aligned_port[0].mean(axis=0)
        delta = prof.max() * np.asarray(
            gaussian_profile(nbin, place, 0.0001))
        phase = float(np.asarray(fit_phase_shift(prof, delta,
                                                 Ns=nbin).phase))
        aligned_port = np.asarray(rotate_data(aligned_port, phase))

    arch = model_data.arch.copy()
    arch.tscrunch()
    if pscrunch:
        arch.pscrunch()
    arch.DM = 0.0
    arch.dedispersed = False
    arch.data = np.asarray(aligned_port)[None]
    arch.weights = np.where(total_weights.sum(axis=-1) > 0.0, 1.0,
                            0.0)[None, :]
    with obs.span("write", outfile=outfile):
        arch.unload(outfile, quiet=quiet)
    return outfile, aligned_port, total_weights
