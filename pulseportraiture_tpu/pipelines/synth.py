"""Synthetic data factory: in-memory fake-pulsar portraits for tests/benches.

TPU-native equivalent of the reference's fixture generators
``make_fake_pulsar`` (/root/reference/pplib.py:3189-3384) and
``add_scintillation`` (/root/reference/pplib.py:1146-1174), minus the
PSRFITS unload (the PSRFITS-backed variant lives in io.archive once the
writer exists).  All stochastic pieces use explicit JAX PRNG keys so
fixtures are reproducible and jit-able.
"""

import jax
import jax.numpy as jnp

from ..config import Dconst, as_fft_operand, scattering_alpha
from ..ops.fourier import get_bin_centers, rotate_data
from ..ops.profiles import gen_gaussian_portrait
from ..ops.scattering import scattering_portrait_FT, scattering_times
from ..utils.databunch import DataBunch

__all__ = ["add_scintillation", "make_fake_portrait", "make_fake_dataset"]


def add_scintillation(port, params=None, key=None, nsin=2, amax=1.0,
                      wmax=3.0):
    """Multiply channels by a sum-of-sin^2 fake scintillation pattern.

    params: flat triplets (amp, freq [cycles], phase [cycles]); if None, a
    PRNG ``key`` draws nsin triplets (amp ~ U[0, amax], freq ~ chi2(wmax),
    phase ~ U[0, 1]).  Equivalent of /root/reference/pplib.py:1146-1174.
    """
    port = jnp.asarray(port)
    nchan = port.shape[-2]
    x = jnp.linspace(0.0, jnp.pi, nchan)
    if params is not None:
        trip = jnp.asarray(params).reshape(-1, 3)
        a, w, p = trip[:, 0], trip[:, 1], trip[:, 2]
    elif key is not None:
        ka, kw, kp = jax.random.split(key, 3)
        a = jax.random.uniform(ka, (nsin,), maxval=amax)
        w = 2.0 * jax.random.gamma(kw, 0.5 * wmax, (nsin,))  # chi2(wmax)
        p = jax.random.uniform(kp, (nsin,))
    else:
        return port
    pattern = jnp.sum(a[:, None] * jnp.sin(w[:, None] * x[None, :]
                                           + p[:, None] * jnp.pi) ** 2,
                      axis=0)
    return port * pattern[..., :, None]


def make_fake_portrait(model_params, nchan, nbin, freqs, P, *,
                       model_code="000", nu_ref=None,
                       scattering_index=scattering_alpha,
                       phase=0.0, DM=0.0, GM=0.0, t_scat=0.0,
                       scint=False, scint_params=None,
                       noise_std=0.0, scales=1.0, weights=None, key=None,
                       nu_dm=jnp.inf):
    """One synthetic [nchan, nbin] portrait with injected parameters.

    model_params: Gaussian portrait parameter vector (see
    gen_gaussian_portrait).  phase/DM/GM inject a rotation (phase in [rot]
    referenced to nu_dm); t_scat [sec] applies scattering when the model
    itself has none; noise_std adds white noise (scalar or [nchan]);
    scales multiplies channels (scalar or [nchan]).

    Mirrors the per-subint synthesis loop of the reference's
    make_fake_pulsar (/root/reference/pplib.py:3330-3384) as a pure
    function of a PRNG key.
    """
    freqs = jnp.asarray(freqs)
    phases = get_bin_centers(nbin)
    if nu_ref is None:
        nu_ref = float(jnp.mean(freqs))
    port = gen_gaussian_portrait(model_code, model_params, scattering_index,
                                 phases, freqs, nu_ref)
    # Inject rotation: negative phase/DM rotates to *later* phases, i.e.
    # simulates a delayed, dispersed pulse (reference uses
    # rotate_data(model, -phase, -dDM, ...)).
    port = rotate_data(port, -phase, -DM, P, freqs, nu_dm)
    if t_scat:
        taus = scattering_times(t_scat / P, scattering_index, freqs, nu_ref)
        sp_FT = scattering_portrait_FT(taus, nbin)
        port = jnp.fft.irfft(sp_FT * jnp.fft.rfft(as_fft_operand(port),
                                                  axis=-1),
                             n=nbin, axis=-1)
    if scint is not False:
        if scint is True:
            key, kscint = jax.random.split(key)
            port = add_scintillation(port, key=kscint, nsin=3, amax=1.0,
                                     wmax=5.0)
        else:
            port = add_scintillation(port, params=scint_params)
    port = port * jnp.broadcast_to(jnp.asarray(scales), (nchan,))[:, None]
    if key is not None:
        noise = jnp.broadcast_to(jnp.asarray(noise_std), (nchan,))
        port = port + noise[:, None] * jax.random.normal(key, (nchan, nbin),
                                                         dtype=port.dtype)
    if weights is not None:
        port = port * jnp.asarray(weights)[:, None]
    return port


def make_fake_dataset(key, model_params, *, nsub=10, nchan=64, nbin=512,
                      lofreq=1300.0, bw=800.0, P=0.005, model_code="000",
                      scattering_index=scattering_alpha, nu_ref=None,
                      phases=None, dDMs=None, DM0=30.0, noise_std=0.1,
                      t_scat=0.0, scint=False):
    """A batch of synthetic subints with known injected (phase, dDM).

    Returns a DataBunch patterned on load_data's schema
    (/root/reference/pplib.py:2809-2820) restricted to the fields the
    device pipeline consumes: subints [nsub, nchan, nbin], freqs [nchan],
    weights, noise_stds, Ps, plus the injected truth (phases_inj,
    dDMs_inj, DM0).  Frequencies are channel centers across [lofreq,
    lofreq+bw], matching the example workload geometry
    (/root/reference/examples/example.py:18-28).
    """
    chan_bw = bw / nchan
    freqs = lofreq + chan_bw * (jnp.arange(nchan) + 0.5)
    if nu_ref is None:
        nu_ref = float(jnp.mean(freqs))
    keys = jax.random.split(key, nsub + 2)
    if phases is None:
        phases = jax.random.uniform(keys[-1], (nsub,), minval=-0.4,
                                    maxval=0.4)
    else:
        phases = jnp.broadcast_to(jnp.asarray(phases), (nsub,))
    if dDMs is None:
        dDMs = jax.random.normal(keys[-2], (nsub,)) * \
            5e-4 * P / (Dconst * (freqs.min() ** -2 - freqs.max() ** -2))
    else:
        dDMs = jnp.broadcast_to(jnp.asarray(dDMs), (nsub,))

    def one(k, phi, ddm):
        return make_fake_portrait(
            model_params, nchan, nbin, freqs, P, model_code=model_code,
            nu_ref=nu_ref, scattering_index=scattering_index, phase=phi,
            DM=ddm, t_scat=t_scat, scint=scint, noise_std=noise_std, key=k,
            nu_dm=nu_ref)

    subints = jax.vmap(one)(keys[:nsub], phases, dDMs)
    return DataBunch(
        subints=subints, freqs=freqs,
        weights=jnp.ones((nsub, nchan)),
        noise_stds=jnp.full((nsub, nchan), noise_std),
        Ps=jnp.full((nsub,), P), nu_ref=nu_ref, nbin=nbin,
        phases_inj=phases, dDMs_inj=dDMs, DM0=DM0,
        model_code=model_code, model_params=jnp.asarray(model_params))
