"""Wideband timing: parse .tim files and run a GLS timing fit.

Closes the loop the reference's notebook closes with tempo
(/root/reference/examples/example_make_model_and_TOAs.ipynb cells 43-56:
GLS fit with ``DMDATA 1`` so wideband DM measurements enter the fit as
data) — in-repo, so the end-use proof that wideband TOAs+DMs feed a
timing fit does not depend on an external tempo install.  When a real
``tempo`` + ``tempo_utils`` environment is available the example script
can still hand the same files to it; the file formats are identical.

The model fit is the wideband set [offset, dF0, dF1, DM]: TOA phase
residuals and DM measurements are combined in one weighted
least-squares system, the wideband-GLS structure introduced by
Pennucci+ (2014):

  r_phase_i = off + dF0 * dt_i + dF1 * dt_i^2 / 2
              + (Dconst / nu_i^2 / P) * dDM_e(i) + noise
  DM_i      = DM0 + dDM_e(i) + noise_DM

where dDM_e is either one global correction or — with ``dmx=True`` or
DMX in the par, tempo's DMDATA+DMX configuration — an independent
correction per DMX epoch (TOAs grouped into fixed-length windows like
tempo's DMX ranges).  Par-file DMX_xxxx values themselves are assumed
zero in the prefit residuals; the fit estimates them from scratch.
"""

import numpy as np

from ..config import Dconst
from ..io.parfile import read_par
from ..utils.mjd import MJD

__all__ = ["parse_tim", "phase_residuals", "rescaled_errors",
           "wideband_gls_fit", "run_tempo_if_available"]


def parse_tim(timfile):
    """Parse an IPTA/tempo2 .tim file (as written by io.timfile).

    Returns a list of DataBunch-like dicts with archive, freq [MHz],
    mjd (two-part utils.mjd.MJD), err_us, and a flags dict (pp_dm /
    pp_dme parsed to float when present).
    """
    toas = []
    with open(timfile) as f:
        for ln in f:
            tok = ln.split()
            if not tok or tok[0] in ("FORMAT", "C", "#", "MODE"):
                continue
            arch, freq, mjd_s, err, site = tok[:5]
            day, _, frac = mjd_s.partition(".")
            flags = {}
            rest = tok[5:]
            for i in range(0, len(rest) - 1, 2):
                if rest[i].startswith("-"):
                    key = rest[i][1:]
                    try:
                        flags[key] = float(rest[i + 1])
                    except ValueError:
                        flags[key] = rest[i + 1]
            toas.append(dict(
                archive=arch, freq=float(freq),
                mjd=MJD(int(day), float("0." + frac) * 86400.0),
                err_us=float(err), site=site, flags=flags))
    return toas


def _selector_mask(toas, flag, flagval):
    """Boolean mask of TOAs whose ``-<flag> <value>`` matches a par
    selector (JUMP/T2EFAC/... lines).  parse_tim floats numeric flag
    values, so both string and numeric representations compare equal
    ('800' matches 800.0)."""
    out = np.zeros(len(toas), dtype=bool)
    for i, t in enumerate(toas):
        tv = t["flags"].get(flag)
        if tv is None:
            continue
        if str(tv) == str(flagval):
            out[i] = True
        else:
            try:
                out[i] = float(tv) == float(flagval)
            except (TypeError, ValueError):
                pass
    return out


def _jump_mask(toas, j):
    """TOA mask for one par JUMP entry, any of tempo's four forms:
    flag selector, MJD range, FREQ range [MHz], or TEL site."""
    if "lo" in j:  # JUMP MJD t1 t2 / JUMP FREQ f1 f2
        if j["flag"] == "MJD":
            vals = np.array([t["mjd"].day + t["mjd"].secs / 86400.0
                             for t in toas])
        else:
            vals = np.array([t["freq"] for t in toas])
        return (vals >= j["lo"]) & (vals <= j["hi"])
    if j["flag"] == "TEL":
        return np.array([t["site"] == j["flagval"] for t in toas],
                        dtype=bool)
    return _selector_mask(toas, j["flag"], j["flagval"])


def _jump_label(j):
    if "lo" in j:
        return "JUMP_%s_%g_%g" % (j["flag"], j["lo"], j["hi"])
    return "JUMP_%s_%s" % (j["flag"], j["flagval"])


def rescaled_errors(toas, par):
    """Per-TOA (err_us, dm_err) with par EFAC/EQUAD-style rescaling.

    tempo2 convention: sigma' = EFAC * sqrt(sigma^2 + EQUAD^2), with
    T2EFAC/T2EQUAD [us] selecting TOAs by flag and DMEFAC/DMEQUAD
    [pc cm^-3] doing the same for the wideband DM uncertainties.  A TOA
    matched by several lines of the same kind uses the first match.
    Flagless tempo1-style global lines ('EFAC 1.5') apply to every TOA
    a selector line did not match.
    Returns (err_us [ntoa], dm_err [ntoa; NaN where no -pp_dme]).
    """
    p = par if not isinstance(par, str) else read_par(par)
    err_us = np.array([t["err_us"] for t in toas], dtype=np.float64)
    dm_err = np.array([t["flags"].get("pp_dme", np.nan) for t in toas],
                      dtype=np.float64)

    def first_match(lines, global_key, default):
        # flagless global value (a plain par field) is the fallback
        # for TOAs no selector line matched
        fallback = p.get(global_key, default)
        fallback = float(fallback) if not isinstance(fallback, str) \
            else default
        vals = np.full(len(toas), np.nan)
        for ln in lines:
            m = _selector_mask(toas, ln["flag"], ln["flagval"])
            vals = np.where(np.isnan(vals) & m, ln["value"], vals)
        return np.where(np.isnan(vals), fallback, vals)

    equad = first_match(p.get("equads", []), "EQUAD", 0.0)
    efac = first_match(p.get("efacs", []), "EFAC", 1.0)
    err_us = efac * np.sqrt(err_us ** 2 + equad ** 2)
    dmequad = first_match(p.get("dmequads", []), "DMEQUAD", 0.0)
    dmefac = first_match(p.get("dmefacs", []), "DMEFAC", 1.0)
    dm_err = dmefac * np.sqrt(dm_err ** 2 + dmequad ** 2)
    return err_us, dm_err


def _dispersion_term(nu):
    """Dispersion delay per unit DM [s]; a TOA frequency of 0.0 encodes
    infinite frequency (no delay), as written by format_toa_line."""
    return np.where(nu > 0.0,
                    Dconst / np.where(nu > 0.0, nu, 1.0) ** 2.0, 0.0)


def phase_residuals(toas, par):
    """Pulse-phase residuals [rot] of TOAs against a (F0, F1, DM) par.

    A TOA is the arrival time *at its reference frequency*, so the
    ephemeris DM's dispersion delay at that frequency is removed before
    evaluating the spin phase (what tempo does with the par DM; a
    frequency of 0 encodes infinite frequency, i.e. no delay).
    Residuals are wrapped to (-0.5, 0.5].
    Returns (resid [rot], dt [s from PEPOCH], P [s]).
    """
    p = par if not isinstance(par, str) else read_par(par)
    F0 = float(p.F0)
    F1 = float(p.get("F1", 0.0))
    DM = float(p.get("DM", 0.0))
    PEPOCH = float(p.get("PEPOCH"))
    pe_day = int(PEPOCH)
    pe_sec = (PEPOCH - pe_day) * 86400.0
    nu = np.array([t["freq"] for t in toas])
    delay = DM * _dispersion_term(nu)
    dt = np.array([(t["mjd"].day - pe_day) * 86400.0
                   + (t["mjd"].secs - pe_sec) for t in toas]) - delay
    phase = F0 * dt + 0.5 * F1 * dt * dt
    resid = ((phase + 0.5) % 1.0) - 0.5
    return resid, dt, 1.0 / F0


def dmx_epochs(mjds, window_days=6.5):
    """Group TOA MJDs into DMX-style fixed-length ranges.

    Like tempo's DMX binning: sorted TOAs open a new range when they
    fall outside ``window_days`` of the current range's first TOA.
    Returns (epoch_index per TOA [int], list of (r1, r2) range bounds).
    """
    order = np.argsort(mjds)
    idx = np.empty(len(mjds), dtype=int)
    ranges = []
    start = None
    for i in order:
        if start is None or mjds[i] - start > window_days:
            start = mjds[i]
            ranges.append([mjds[i], mjds[i]])
        idx[i] = len(ranges) - 1
        ranges[-1][1] = mjds[i]
    return idx, [tuple(r) for r in ranges]


def wideband_gls_fit(toas, par, fit_dm=None, fit_f1=None, dmx=None,
                     dmx_window_days=None):
    """Weighted GLS of [phase offset, dF0, dF1, DM/DMX] on wideband TOAs.

    ``fit_dm`` defaults to True when the par has ``DMDATA 1`` (the
    notebook's convention): the per-TOA -pp_dm/-pp_dme measurements
    then enter the system as data alongside the TOA residuals.
    ``fit_f1`` defaults to the par's F1 fit flag (``F1 <val> 1``).
    ``dmx`` defaults to True when the par carries DMX (a range length
    or DMX_xxxx entries); per-epoch dDM corrections then replace the
    single global dDM, with TOAs binned into ``dmx_window_days``-long
    ranges (default: the par's DMX value, else 6.5 d, tempo's default).

    Par noise/offset extensions are honored (the reference defers these
    to tempo — notebook cells 43-56; this stage inlines them):

    - ``JUMP -flag val offset [fit]`` — a receiver/backend time offset
      [s] applied to TOAs matching ``-flag val``.  The par offset is
      removed from the prefit residuals; lines with a fit flag of 1 get
      a free column (the correction, in seconds).  Positive JUMP =
      matching TOAs arrive later.  Per-jump results land in ``jumps``.
    - ``DMJUMP -flag val offset [fit]`` — PINT's wideband per-receiver
      DM-measurement offset [pc cm^-3]: a bias of the matching TOAs'
      -pp_dm values (e.g. from template evolution misfit in one band),
      NOT a physical delay — it enters the DM data rows only.  Fixed
      offsets are subtracted from the measurements; fit=1 adds a free
      column.  Results land in ``dmjumps``.
    - ``T2EFAC/T2EQUAD`` (+ ``DMEFAC/DMEQUAD`` for the wideband DM
      uncertainties): sigma' = EFAC * sqrt(sigma^2 + EQUAD^2), tempo2's
      convention (see ``rescaled_errors``).

    Returns a dict with params, errors, per-epoch ``dmx`` results,
    per-jump ``jumps`` results, prefit/postfit weighted rms [us], chi2,
    and dof.
    """
    p = par if not isinstance(par, str) else read_par(par)
    if fit_dm is None:
        fit_dm = int(float(p.get("DMDATA", 0))) == 1
    if fit_f1 is None:
        fit_f1 = p.get("fit_flags", {}).get("F1", 0) == 1
    has_dmx = "DMX" in p or any(k.startswith("DMX_") for k in p)
    if dmx is None:
        # auto-DMX requires the wideband DM rows: per-epoch DM columns
        # constrained by phase residuals alone are rank-deficient for
        # single-frequency epochs (tempo pairs DMX with DMDATA here too)
        dmx = has_dmx and fit_dm
    if dmx_window_days is None:
        dmx_val = p.get("DMX", 6.5)
        dmx_window_days = float(dmx_val) \
            if isinstance(dmx_val, (int, float)) and dmx_val > 0 else 6.5
    DM0 = float(p.get("DM", 0.0))
    resid, dt, P = phase_residuals(toas, p)
    nu = np.array([t["freq"] for t in toas])
    err_us_r, dme_r = rescaled_errors(toas, p)
    err_rot = err_us_r * 1e-6 / P
    disp = _dispersion_term(nu) / P  # phase per unit DM

    # JUMPs: remove the par offsets from the prefit residuals (re-wrap
    # after — a jump can carry a residual across the +-0.5 boundary)
    jumps = list(p.get("jumps", []))
    jump_masks = [_jump_mask(toas, j) for j in jumps]
    for j, m in zip(jumps, jump_masks):
        if j["offset_s"]:
            resid = resid - m * (j["offset_s"] / P)
    resid = ((resid + 0.5) % 1.0) - 0.5

    # spin columns, in phase units
    cols = [np.ones_like(dt), dt]
    names = ["offset_rot", "dF0_hz"]
    if fit_f1:
        cols.append(0.5 * dt * dt)
        names.append("dF1_hz_s")
    nspin = len(cols)

    # DM columns: one global dDM, or one per DMX epoch
    if dmx:
        mjds = np.array([t["mjd"].day + t["mjd"].secs / 86400.0
                         for t in toas])
        eidx, ranges = dmx_epochs(mjds, dmx_window_days)
        nep = len(ranges)
        dm_cols = np.zeros((len(toas), nep))
        dm_cols[np.arange(len(toas)), eidx] = disp
        cols.extend(list(dm_cols.T))
        names.extend(f"DMX_{e + 1:04d}" for e in range(nep))
    else:
        eidx, ranges, nep = None, [], 0
        if fit_dm:
            cols.append(disp)
            names.append("dDM")
    # free JUMP columns (fit flag 1) go last so the DM-row indexing
    # below (columns nspin..nspin+nep) stays contiguous
    njump_start = len(cols)
    for j, m in zip(jumps, jump_masks):
        if j.get("fit", 0):
            if not m.any():
                raise ValueError(
                    "%s (fit) matches no TOAs — its design column "
                    "would be all-zero" % _jump_label(j))
            cols.append(m.astype(np.float64) / P)  # rot per second
            names.append(_jump_label(j))
    M = np.stack(cols, axis=1)
    y = resid.copy()
    w = err_rot ** -2.0

    dmjumps = list(p.get("dmjumps", []))
    dmjump_masks = [_selector_mask(toas, dj["flag"], dj["flagval"])
                    for dj in dmjumps]
    dmjump_start = M.shape[1]
    if fit_dm:
        # wideband DM measurements as data rows: DM_i - DM0 = dDM_e(i)
        dms = np.array([t["flags"].get("pp_dm", np.nan) for t in toas])
        # fixed DMJUMP offsets come off the measurements up front
        for dj, m in zip(dmjumps, dmjump_masks):
            if dj["offset_dm"]:
                dms = dms - np.where(m, dj["offset_dm"], 0.0)
        dmes = dme_r  # DMEFAC/DMEQUAD-rescaled
        okd = np.isfinite(dms) & np.isfinite(dmes) & (dmes > 0)
        Md = np.zeros((int(okd.sum()), M.shape[1]))
        if dmx:
            Md[np.arange(Md.shape[0]), nspin + eidx[okd]] = 1.0
        else:
            Md[:, nspin] = 1.0
        M = np.vstack([M, Md])
        y = np.concatenate([y, dms[okd] - DM0])
        w = np.concatenate([w, dmes[okd] ** -2.0])
        # free DMJUMP columns act on the DM rows alone
        dmjump_start = M.shape[1]
        for dj, m in zip(dmjumps, dmjump_masks):
            if dj.get("fit", 0):
                if not m[okd].any():
                    raise ValueError(
                        "DMJUMP -%s %s (fit) matches no wideband DM "
                        "rows — its design column would be all-zero"
                        % (dj["flag"], dj["flagval"]))
                col = np.concatenate([np.zeros(len(toas)),
                                      m[okd].astype(np.float64)])
                M = np.hstack([M, col[:, None]])
                names.append("DMJUMP_%s_%s" % (dj["flag"], dj["flagval"]))

    # weighted LSQ via column-scaled QR: the spin columns span ~16
    # decades (1, dt, dt^2/2 at dt~1e8 s), where forming the normal
    # equations squares an already-large condition number
    sw = np.sqrt(w)
    Aw = M * sw[:, None]
    scale = np.linalg.norm(Aw, axis=0)
    scale[scale == 0.0] = 1.0
    Q, R = np.linalg.qr(Aw / scale)
    rdiag = np.abs(np.diag(R))
    if R.shape[0] != R.shape[1] or rdiag.min() < 1e-12 * rdiag.max():
        raise ValueError(
            "singular wideband design matrix (%d rows x %d params): "
            "with dmx=True each epoch needs constraining data — DM "
            "measurement rows (DMDATA 1 + -pp_dm flags) or "
            "multi-frequency TOAs per epoch." % (M.shape[0], M.shape[1]))
    xs = np.linalg.solve(R, Q.T @ (y * sw))
    Rinv = np.linalg.solve(R, np.eye(R.shape[0]))
    cov = (Rinv @ Rinv.T) / np.outer(scale, scale)
    x = xs / scale
    errs = np.sqrt(np.diag(cov))
    post = y - M @ x
    ntoa = len(toas)
    wrms_us = np.sqrt(np.sum(w[:ntoa] * post[:ntoa] ** 2)
                      / np.sum(w[:ntoa])) * P * 1e6
    prefit_us = np.sqrt(np.sum(w[:ntoa] * resid ** 2)
                        / np.sum(w[:ntoa])) * P * 1e6
    chi2 = float(np.sum(w * post ** 2))
    dof = len(y) - M.shape[1]
    dmx_out = [dict(name=names[nspin + e], r1=ranges[e][0],
                    r2=ranges[e][1],
                    mjd_mid=0.5 * (ranges[e][0] + ranges[e][1]),
                    dDM=float(x[nspin + e]),
                    err=float(errs[nspin + e]),
                    ntoa=int(np.sum(eidx == e)))
               for e in range(nep)]
    jump_out = []
    k = njump_start
    for j, m in zip(jumps, jump_masks):
        jd = dict(flag=j["flag"], flagval=j.get("flagval"),
                  offset_s=float(j["offset_s"]),
                  fit=bool(j.get("fit", 0)), ntoa=int(m.sum()))
        if "lo" in j:
            jd["lo"], jd["hi"] = float(j["lo"]), float(j["hi"])
        if jd["fit"]:
            jd["delta_s"] = float(x[k])
            jd["err_s"] = float(errs[k])
            jd["total_s"] = jd["offset_s"] + jd["delta_s"]
            k += 1
        else:
            jd["total_s"] = jd["offset_s"]
        jump_out.append(jd)
    dmjump_out = []
    k = dmjump_start
    for dj, m in zip(dmjumps, dmjump_masks):
        dd = dict(flag=dj["flag"], flagval=dj["flagval"],
                  offset_dm=float(dj["offset_dm"]),
                  fit=bool(dj.get("fit", 0)) and fit_dm,
                  ntoa=int(m.sum()))
        if dd["fit"]:
            dd["delta_dm"] = float(x[k])
            dd["err_dm"] = float(errs[k])
            dd["total_dm"] = dd["offset_dm"] + dd["delta_dm"]
            k += 1
        else:
            dd["total_dm"] = dd["offset_dm"]
        dmjump_out.append(dd)
    return dict(params=dict(zip(names, x)),
                errors=dict(zip(names, errs)),
                dmx=dmx_out, jumps=jump_out, dmjumps=dmjump_out,
                prefit_wrms_us=float(prefit_us),
                postfit_wrms_us=float(wrms_us),
                chi2=chi2, red_chi2=chi2 / max(dof, 1), dof=dof,
                ntoa=ntoa, fit_dm=bool(fit_dm), fit_f1=bool(fit_f1))


def run_tempo_if_available(parfile, timfile, quiet=True):
    """Run the external tempo GLS fit when installed; None otherwise.

    The files are the same ones wideband_gls_fit consumes, so an
    environment with tempo/tempo_utils reproduces the reference
    notebook's end stage exactly.
    """
    import shutil
    import subprocess

    if shutil.which("tempo") is None:
        return None
    proc = subprocess.run(["tempo", "-G", "-f", parfile, timfile],
                          capture_output=True, text=True)
    if not quiet:
        print(proc.stdout)
    return proc.returncode
