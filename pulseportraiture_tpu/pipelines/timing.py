"""Wideband timing: parse .tim files and run a GLS timing fit.

Closes the loop the reference's notebook closes with tempo
(/root/reference/examples/example_make_model_and_TOAs.ipynb cells 43-56:
GLS fit with ``DMDATA 1`` so wideband DM measurements enter the fit as
data) — in-repo, so the end-use proof that wideband TOAs+DMs feed a
timing fit does not depend on an external tempo install.  When a real
``tempo`` + ``tempo_utils`` environment is available the example script
can still hand the same files to it; the file formats are identical.

The model fit here is the minimal wideband set: a constant phase offset,
a spin-frequency correction dF0, and a DM correction dDM.  TOA phase
residuals and DM measurements are combined in one weighted least-squares
system, the wideband-GLS structure introduced by Pennucci+ (2014):

  r_phase_i = off + dF0 * dt_i + (Dconst / nu_i^2 / P) * dDM + noise
  DM_i      = DM0 + dDM + noise_DM
"""

import numpy as np

from ..config import Dconst
from ..io.parfile import read_par
from ..utils.mjd import MJD

__all__ = ["parse_tim", "phase_residuals", "wideband_gls_fit",
           "run_tempo_if_available"]


def parse_tim(timfile):
    """Parse an IPTA/tempo2 .tim file (as written by io.timfile).

    Returns a list of DataBunch-like dicts with archive, freq [MHz],
    mjd (two-part utils.mjd.MJD), err_us, and a flags dict (pp_dm /
    pp_dme parsed to float when present).
    """
    toas = []
    with open(timfile) as f:
        for ln in f:
            tok = ln.split()
            if not tok or tok[0] in ("FORMAT", "C", "#", "MODE"):
                continue
            arch, freq, mjd_s, err, site = tok[:5]
            day, _, frac = mjd_s.partition(".")
            flags = {}
            rest = tok[5:]
            for i in range(0, len(rest) - 1, 2):
                if rest[i].startswith("-"):
                    key = rest[i][1:]
                    try:
                        flags[key] = float(rest[i + 1])
                    except ValueError:
                        flags[key] = rest[i + 1]
            toas.append(dict(
                archive=arch, freq=float(freq),
                mjd=MJD(int(day), float("0." + frac) * 86400.0),
                err_us=float(err), site=site, flags=flags))
    return toas


def _dispersion_term(nu):
    """Dispersion delay per unit DM [s]; a TOA frequency of 0.0 encodes
    infinite frequency (no delay), as written by format_toa_line."""
    return np.where(nu > 0.0,
                    Dconst / np.where(nu > 0.0, nu, 1.0) ** 2.0, 0.0)


def phase_residuals(toas, par):
    """Pulse-phase residuals [rot] of TOAs against a (F0, F1, DM) par.

    A TOA is the arrival time *at its reference frequency*, so the
    ephemeris DM's dispersion delay at that frequency is removed before
    evaluating the spin phase (what tempo does with the par DM; a
    frequency of 0 encodes infinite frequency, i.e. no delay).
    Residuals are wrapped to (-0.5, 0.5].
    Returns (resid [rot], dt [s from PEPOCH], P [s]).
    """
    p = par if not isinstance(par, str) else read_par(par)
    F0 = float(p.F0)
    F1 = float(p.get("F1", 0.0))
    DM = float(p.get("DM", 0.0))
    PEPOCH = float(p.get("PEPOCH"))
    pe_day = int(PEPOCH)
    pe_sec = (PEPOCH - pe_day) * 86400.0
    nu = np.array([t["freq"] for t in toas])
    delay = DM * _dispersion_term(nu)
    dt = np.array([(t["mjd"].day - pe_day) * 86400.0
                   + (t["mjd"].secs - pe_sec) for t in toas]) - delay
    phase = F0 * dt + 0.5 * F1 * dt * dt
    resid = ((phase + 0.5) % 1.0) - 0.5
    return resid, dt, 1.0 / F0


def wideband_gls_fit(toas, par, fit_dm=None):
    """Weighted LSQ of [phase offset, dF0, dDM] on wideband TOAs.

    ``fit_dm`` defaults to True when the par has ``DMDATA 1`` (the
    notebook's convention): the per-TOA -pp_dm/-pp_dme measurements then
    enter the system as data alongside the TOA residuals.  Returns a
    dict with params, errors, prefit/postfit weighted rms [us], chi2,
    and dof.
    """
    p = par if not isinstance(par, str) else read_par(par)
    if fit_dm is None:
        fit_dm = int(float(p.get("DMDATA", 0))) == 1
    DM0 = float(p.get("DM", 0.0))
    resid, dt, P = phase_residuals(toas, p)
    nu = np.array([t["freq"] for t in toas])
    err_rot = np.array([t["err_us"] for t in toas]) * 1e-6 / P

    # design matrix in phase units
    cols = [np.ones_like(dt), dt]
    if fit_dm:
        cols.append(_dispersion_term(nu) / P)
    M = np.stack(cols, axis=1)
    y = resid.copy()
    w = err_rot ** -2.0

    if fit_dm:
        dms = np.array([t["flags"].get("pp_dm", np.nan) for t in toas])
        dmes = np.array([t["flags"].get("pp_dme", np.nan) for t in toas])
        okd = np.isfinite(dms) & np.isfinite(dmes) & (dmes > 0)
        # DM rows: DM_i - DM0 = dDM
        Md = np.zeros((okd.sum(), M.shape[1]))
        Md[:, 2] = 1.0
        M = np.vstack([M, Md])
        y = np.concatenate([y, dms[okd] - DM0])
        w = np.concatenate([w, dmes[okd] ** -2.0])

    # weighted normal equations with errors from the covariance
    A = M * w[:, None]
    cov = np.linalg.inv(M.T @ A)
    x = cov @ (A.T @ y)
    post = y - M @ x
    ntoa = len(toas)
    wrms_us = np.sqrt(np.sum(w[:ntoa] * post[:ntoa] ** 2)
                      / np.sum(w[:ntoa])) * P * 1e6
    prefit_us = np.sqrt(np.sum(w[:ntoa] * resid ** 2)
                        / np.sum(w[:ntoa])) * P * 1e6
    chi2 = float(np.sum(w * post ** 2))
    dof = len(y) - M.shape[1]
    names = ["offset_rot", "dF0_hz"] + (["dDM"] if fit_dm else [])
    return dict(params=dict(zip(names, x)),
                errors=dict(zip(names, np.sqrt(np.diag(cov)))),
                prefit_wrms_us=float(prefit_us),
                postfit_wrms_us=float(wrms_us),
                chi2=chi2, red_chi2=chi2 / max(dof, 1), dof=dof,
                ntoa=ntoa, fit_dm=bool(fit_dm))


def run_tempo_if_available(parfile, timfile, quiet=True):
    """Run the external tempo GLS fit when installed; None otherwise.

    The files are the same ones wideband_gls_fit consumes, so an
    environment with tempo/tempo_utils reproduces the reference
    notebook's end stage exactly.
    """
    import shutil
    import subprocess

    if shutil.which("tempo") is None:
        return None
    proc = subprocess.run(["tempo", "-G", "-f", parfile, timfile],
                          capture_output=True, text=True)
    if not quiet:
        print(proc.stdout)
    return proc.returncode
