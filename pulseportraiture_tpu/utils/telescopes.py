"""Telescope name -> TEMPO/TEMPO2 observatory-code table.

Equivalent of /root/reference/telescope_codes.py: if $TEMPO2 is set, the
table is sourced from ``$TEMPO2/observatory/observatories.dat`` (+
``aliases``); otherwise a built-in table is used.  The mapping itself is
public observatory-catalog data (TEMPO2 distribution).  The first code
in each list is the one written on TOA lines (pplib.py:2676-2677).
"""

import os

__all__ = ["telescope_code_dict", "get_telescope_code"]

# name -> [primary code, aliases...]; compact (name, codes-string) pairs.
_BUILTIN = [
    ("ARECIBO", "ao 3 arecebo arecibo"), ("AXIS", "axi"),
    ("CAMBRIDGE", "cam"), ("COE", "coe"), ("DARNHALL", "l"),
    ("DE601", "EFlfr"), ("DE601HBA", "EFlfrhba"),
    ("DE601LBA", "EFlfrlba"), ("DE601LBH", "EFlfrlbh"),
    ("DE602", "UWlfr"), ("DE602HBA", "UWlfrhba"),
    ("DE602LBA", "UWlfrlba"), ("DE602LBH", "UWlfrlbh"),
    ("DE603", "TBlfr"), ("DE603HBA", "TBlfrhba"),
    ("DE603LBA", "TBlfrlba"), ("DE603LBH", "TBlfrlbh"),
    ("DE604", "POlfr"), ("DE604HBA", "POlfrhba"),
    ("DE604LBA", "POlfrlba"), ("DE604LBH", "POlfrlbh"),
    ("DE605", "JUlfr"), ("DE605HBA", "JUlfrhba"),
    ("DE605LBA", "JUlfrlba"), ("DE605LBH", "JUlfrlbh"),
    ("DE609", "NDlfr"), ("DE609HBA", "NDlfrhba"),
    ("DE609LBA", "NDlfrlba"), ("DE609LBH", "NDlfrlbh"),
    ("DEFFORD", "n"), ("DSS_43", "tid43 6"), ("EFFELSBERG", "eff g"),
    ("EFFELSBERG_ASTERIX", "effix"), ("FAST", "fast"),
    ("FI609", "Filfr"), ("FI609HBA", "Filfrhba"),
    ("FI609LBA", "Filfrlba"), ("FI609LBH", "Filfrlbh"),
    ("FR606", "FRlfr"), ("FR606HBA", "FRlfrhba"),
    ("FR606LBA", "FRlfrlba"), ("FR606LBH", "FRlfrlbh"),
    ("GB140", "gb140"), ("GB300", "gb300"), ("GB853", "gb853"),
    ("GBT", "gbt 1 gb"), ("GEO600", "geo600"), ("GMRT", "gmrt"),
    ("GOLDSTONE", "gs"), ("GRAO", "grao"), ("HAMBURG", "hamburg"),
    ("HANFORD", "lho"), ("HARTEBEESTHOEK", "hart"), ("HOBART", "hob"),
    ("JBOAFB", "jbafb"), ("JBODFB", "jbdfb q"), ("JBOROACH", "jbroach"),
    ("JB_42FT", "jb42"), ("JB_MKII", "jbmk2 h"),
    ("JB_MKII_DFB", "jbmk2dfb"), ("JB_MKII_RCH", "jbmk2roach"),
    ("JODRELL", "jb 8 y z"), ("JODRELL2", "q"), ("JODRELLM4", "jbm4"),
    ("KAGRA", "kagra"), ("KAT-7", "k7"), ("KNOCKIN", "m"),
    ("LA_PALMA", "p"), ("LIVINGSTON", "llo"), ("LOFAR", "lofar t"),
    ("LWA1", "lwa1 x"), ("MEERKAT", "meerkat m"), ("MKIII", "jbmk3 j"),
    ("MOST", "mo"), ("MWA", "mwa"), ("NANCAY", "ncy f"),
    ("NANSHAN", "NS"), ("NARRABRI", "atca 2"), ("NUPPI", "ncyobs w"),
    ("OP", "obspm"), ("PARKES", "pks 7"), ("PRINCETON", "princeton"),
    ("SE607", "ONlfr"), ("SE607HBA", "ONlfrhba"),
    ("SE607LBA", "ONlfrlba"), ("SE607LBH", "ONlfrlbh"),
    ("SRT", "srt z"), ("STL_BAT", "STL_BAT"), ("TABLEY", "k"),
    ("UAO", "NS"), ("UK608", "UKlfr"), ("UK608HBA", "UKlfrhba"),
    ("UK608LBA", "UKlfrlba"), ("UK608LBH", "UKlfrlbh"),
    ("UTR-2", "UTR2"), ("VIRGO", "virgo"), ("VLA", "vla c"),
    ("WARKWORTH_12M", "wark12m"), ("WARKWORTH_30M", "wark30m"),
    ("WSRT", "wsrt i"),
]


def _from_tempo2():
    """Source the table from $TEMPO2 observatory data, if available."""
    t2 = os.environ.get("TEMPO2")
    if not t2:
        return None
    path = os.path.join(t2, "observatory", "observatories.dat")
    if not os.path.isfile(path):
        return None
    table = {}
    with open(path) as f:
        for line in f:
            if line.startswith("#") or not line.strip():
                continue
            toks = line.split()
            table[toks[-2].upper()] = [toks[-1]]
    alias_path = os.path.join(t2, "observatory", "aliases")
    if os.path.isfile(alias_path):
        with open(alias_path) as f:
            for line in f:
                if line.startswith("#") or not line.strip():
                    continue
                toks = line.split()
                for telescope, codes in table.items():
                    if toks[0] == codes[0]:
                        codes.extend(toks[1:])
    return table


telescope_code_dict = _from_tempo2() or {
    name: codes.split() for name, codes in _BUILTIN}


def get_telescope_code(telescope, default=None):
    """Primary TOA-line code for a telescope name (case-insensitive)."""
    codes = telescope_code_dict.get(str(telescope).upper())
    if codes:
        return codes[0]
    if default is not None:
        return default
    raise KeyError(f"Unknown telescope '{telescope}'; add it to "
                   f"telescope_code_dict or set $TEMPO2.")
