"""Two-part MJD arithmetic with sub-nanosecond precision.

Replacement for the PSRCHIVE ``pr.MJD`` objects the reference leans on
(epochs from archives, TOA epochs: pplib.py:2634-2648, pptoas.py:527-530).
A single float64 MJD only resolves ~1 us at MJD ~ 55000; TOAs need ns, so
the day is kept as an integer and the in-day offset in seconds as a
float64 (resolution ~1e-11 s).
"""

__all__ = ["MJD"]


class MJD:
    """MJD as (integer day, seconds into the day)."""

    __slots__ = ("day", "secs")

    def __init__(self, day=0, secs=0.0):
        day = int(day)
        secs = float(secs)
        extra, secs = divmod(secs, 86400.0)
        self.day = day + int(extra)
        self.secs = secs

    @classmethod
    def from_mjd(cls, mjd):
        """Build from a float MJD (precision-limited; prefer two-part)."""
        day = int(mjd // 1)
        return cls(day, (mjd - day) * 86400.0)

    @classmethod
    def from_imjd_smjd(cls, imjd, smjd, offs=0.0):
        """From PSRFITS STT_IMJD / STT_SMJD / STT_OFFS fields."""
        return cls(int(imjd), float(smjd) + float(offs))

    def intday(self):
        return self.day

    def fracday(self):
        return self.secs / 86400.0

    def in_seconds(self):
        return self.day * 86400.0 + self.secs

    def mjd(self):
        return self.day + self.secs / 86400.0

    def add_seconds(self, secs):
        return MJD(self.day, self.secs + secs)

    def __add__(self, other):
        if isinstance(other, MJD):
            return MJD(self.day + other.day, self.secs + other.secs)
        return MJD(self.day, self.secs + float(other) * 86400.0)

    def __sub__(self, other):
        """Difference in seconds (MJD) or shifted MJD (scalar days)."""
        if isinstance(other, MJD):
            return (self.day - other.day) * 86400.0 + \
                (self.secs - other.secs)
        return MJD(self.day, self.secs - float(other) * 86400.0)

    def __eq__(self, other):
        return isinstance(other, MJD) and self.day == other.day and \
            self.secs == other.secs

    def __lt__(self, other):
        return (self.day, self.secs) < (other.day, other.secs)

    def __le__(self, other):
        return (self.day, self.secs) <= (other.day, other.secs)

    def __hash__(self):
        return hash((self.day, self.secs))

    def __repr__(self):
        return f"MJD({self.day}, {self.secs!r})"

    def format_parts(self, frac_digits=15):
        """(day, '.ddd...') strings with rounding carried into the day.

        Naive '%.15f' % fracday() prints a time within ~4e-12 day of
        midnight as '1.000...' next to the *old* integer day — a TOA
        early by a full day.  Rounding is applied first and the carry
        propagated.
        """
        frac = self.fracday()
        rounded = round(frac, frac_digits)
        day = self.day
        if rounded >= 1.0:
            day += 1
            rounded = 0.0
        return day, ("%.*f" % (frac_digits, rounded))[1:]

    def __str__(self):
        day, frac = self.format_parts(15)
        return f"{day}{frac}"
