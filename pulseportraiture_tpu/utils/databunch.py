"""DataBunch: attribute-accessible dict used as the universal result record.

Equivalent of the reference's ``DataBunch`` (/root/reference/pplib.py:125-136).
Registered as a JAX pytree so fit results can flow through jit/vmap
boundaries untouched.
"""

import jax


class DataBunch(dict):
    """dict with attribute access: ``db.a`` is ``db['a']``."""

    def __init__(self, **kwds):
        dict.__init__(self, kwds)
        self.__dict__ = self

    def __repr__(self):  # stable ordering for readable printing
        keys = ", ".join(sorted(self.keys()))
        return f"DataBunch({keys})"


def _flatten(db):
    keys = sorted(db.keys())
    return [db[k] for k in keys], keys


def _unflatten(keys, values):
    return DataBunch(**dict(zip(keys, values)))


jax.tree_util.register_pytree_node(DataBunch, _flatten, _unflatten)

__all__ = ["DataBunch"]
