"""Observatory geometry: Doppler factors and parallactic angles.

The reference takes both quantities per subintegration from PSRCHIVE
(/root/reference/pplib.py:2697-2708,
``Integration.get_doppler_factor``/``get_parallactic_angle``); this
module computes them natively from the telescope's ITRF position, the
source coordinates (RAJ/DECJ in the stored ephemeris), and the subint
epochs:

* Earth's barycentric velocity from the exact Keplerian velocity of an
  elliptical orbit with low-precision mean solar elements (Meeus-style),
  plus the diurnal rotation velocity of the site.  The velocity (and
  GMST) are mean-of-date quantities, so catalog J2000 directions are
  precessed to date before projecting.  Error budget: neglected
  lunar/planetary terms ~15 m/s and residual frame effects (nutation
  ~17 arcsec) give |dbeta| <~ 1e-7, three orders below the annual 1e-4
  signal.
* doppler_factor = nu_source / nu_observed = sqrt((1+beta)/(1-beta)),
  beta = v/c > 0 for increasing distance (the convention documented at
  pplib.py:2697-2703).
* Parallactic angle from the hour angle at the site's geodetic
  latitude, in radians on (-pi, pi].

The ITRF coordinate table is public observatory-catalog data (TEMPO2
``observatories.dat``); entries cover the telescopes in
utils.telescopes that time pulsars.
"""

import re
import warnings

import numpy as np

__all__ = ["OBSERVATORY_ITRF", "gmst_rad", "itrf_to_geodetic",
           "parse_ra_dec", "earth_velocity_kms", "site_velocity_kms",
           "doppler_factor", "parallactic_angle",
           "doppler_parangle_for_archive"]

C_KMS = 299792.458
OMEGA_EARTH = 7.2921150e-5          # rad/s, Earth rotation rate
AU_KM = 1.495978707e8

# name -> ITRF (X, Y, Z) [m]; public TEMPO2 observatory catalog data.
OBSERVATORY_ITRF = {
    "GBT": (882589.65, -4924872.32, 3943729.348),
    "ARECIBO": (2390490.0, -5564764.0, 1994727.0),
    "PARKES": (-4554231.5, 2816759.1, -3454036.3),
    "JODRELL": (3822626.04, -154105.65, 5086486.04),
    "JB_MKII": (3822846.76, -153802.28, 5086285.90),
    "NANCAY": (4324165.81, 165927.11, 4670132.83),
    "NUPPI": (4324165.81, 165927.11, 4670132.83),
    "EFFELSBERG": (4033949.5, 486989.4, 4900430.8),
    "WSRT": (3828445.659, 445223.600, 5064921.568),
    "MEERKAT": (5109360.133, 2006852.586, -3238948.127),
    "FAST": (-1668557.0, 5506838.0, 2744934.0),
    "GMRT": (1656342.30, 5797947.77, 2073243.16),
    "VLA": (-1601192.0, -5041981.4, 3554871.4),
    "LOFAR": (3826577.462, 461022.624, 5064892.526),
    "SRT": (4865182.766, 791922.689, 4035137.174),
    "HARTEBEESTHOEK": (5085442.780, 2668263.483, -2768697.034),
    "MOST": (-4483311.64, 2648815.92, -3671909.31),
    "HOBART": (-3950077.96, 2522377.31, -4311667.52),
    "NANSHAN": (228310.702, 4631922.905, 4367064.059),
    "UAO": (228310.702, 4631922.905, 4367064.059),
    "CHIME": (-2059166.313, -3621302.972, 4814304.113),
    "LWA1": (-1602196.60, -5042313.47, 3553971.51),
    "GB140": (882872.57, -4924552.73, 3944154.92),
    "EFFELSBERG_ASTERIX": (4033949.5, 486989.4, 4900430.8),
}


# common aliases / TEMPO site names -> canonical table keys
_OBS_ALIASES = {
    "GREEN BANK": "GBT", "GB": "GBT", "NRT": "NANCAY",
    "JODRELL BANK": "JODRELL", "JB": "JODRELL", "AO": "ARECIBO",
    "PKS": "PARKES", "EFF": "EFFELSBERG", "MK": "MEERKAT",
    "NCY": "NANCAY", "NCYOBS": "NUPPI", "SARDINIA": "SRT",
}


def _obs_itrf(telescope):
    name = str(telescope).strip().upper()
    name = _OBS_ALIASES.get(name, name)
    itrf = OBSERVATORY_ITRF.get(name)
    if itrf is not None:
        return itrf
    # fall back to the alias lists in the telescope-code table
    from .telescopes import telescope_code_dict

    low = str(telescope).strip().lower()
    for canon, codes in telescope_code_dict.items():
        if low in [c.lower() for c in codes]:
            return OBSERVATORY_ITRF.get(
                _OBS_ALIASES.get(canon.upper(), canon.upper()))
    return None


def gmst_rad(mjd_ut):
    """Greenwich mean sidereal time [rad] (ERA-based linear model,
    adequate to <0.1 s over decades)."""
    d = np.asarray(mjd_ut, dtype=np.float64) - 51544.5
    gmst_hours = 18.697374558 + 24.06570982441908 * d
    return (gmst_hours % 24.0) * (2.0 * np.pi / 24.0)


def itrf_to_geodetic(xyz):
    """(lat_rad, lon_rad, height_m) from ITRF meters (Bowring's
    one-iteration method, WGS84)."""
    x, y, z = xyz
    a, f = 6378137.0, 1.0 / 298.257223563
    b = a * (1.0 - f)
    e2 = 1.0 - (b / a) ** 2
    ep2 = (a / b) ** 2 - 1.0
    p = np.hypot(x, y)
    theta = np.arctan2(z * a, p * b)
    lat = np.arctan2(z + ep2 * b * np.sin(theta) ** 3,
                     p - e2 * a * np.cos(theta) ** 3)
    lon = np.arctan2(y, x)
    N = a / np.sqrt(1.0 - e2 * np.sin(lat) ** 2)
    h = p / np.cos(lat) - N
    return lat, lon, h


_RA_RE = re.compile(r"^\s*RAJ?\s+([\d:.+-]+)", re.MULTILINE)
_DEC_RE = re.compile(r"^\s*DECJ?\s+([\d:.+-]+)", re.MULTILINE)
_ELONG_RE = re.compile(r"^\s*(?:ELONG|LAMBDA)\s+([-+.\deE]+)",
                       re.MULTILINE)
_ELAT_RE = re.compile(r"^\s*(?:ELAT|BETA)\s+([-+.\deE]+)", re.MULTILINE)

# IAU 2006 obliquity at J2000, for ecliptic-coordinate ephemerides
_EPS0 = np.radians(84381.406 / 3600.0)


def _parse_sexagesimal(s):
    parts = [float(p) for p in s.split(":")]
    sign = -1.0 if s.strip().startswith("-") else 1.0
    mag = abs(parts[0]) + (parts[1] if len(parts) > 1 else 0.0) / 60.0 \
        + (parts[2] if len(parts) > 2 else 0.0) / 3600.0
    return sign * mag


def parse_ra_dec(ephemeris_text):
    """(ra_rad, dec_rad) J2000 from RAJ/DECJ — or ELONG/ELAT (ecliptic,
    the NANOGrav-style convention) — lines; None if neither present."""
    text = ephemeris_text or ""
    mra = _RA_RE.search(text)
    mdec = _DEC_RE.search(text)
    if mra and mdec:
        ra = _parse_sexagesimal(mra.group(1)) * (2.0 * np.pi / 24.0)
        dec = np.radians(_parse_sexagesimal(mdec.group(1)))
        return ra, dec
    mlon = _ELONG_RE.search(text)
    mlat = _ELAT_RE.search(text)
    if mlon and mlat:
        lam = np.radians(float(mlon.group(1)))
        bet = np.radians(float(mlat.group(1)))
        dec = np.arcsin(np.sin(bet) * np.cos(_EPS0)
                        + np.cos(bet) * np.sin(_EPS0) * np.sin(lam))
        ra = np.arctan2(np.sin(lam) * np.cos(_EPS0)
                        - np.tan(bet) * np.sin(_EPS0), np.cos(lam)) \
            % (2.0 * np.pi)
        return ra, dec
    return None


def precess_from_j2000(mjd, n_hat):
    """Rotate a J2000 unit vector to the mean equinox of date
    (IAU 1976 precession angles, first-order — arcsec-accurate over
    decades, ample for the 1e-4 Doppler signal)."""
    T = (np.asarray(mjd, dtype=np.float64).mean() - 51544.5) / 36525.0
    arcsec = np.pi / (180.0 * 3600.0)
    zeta = (2306.2181 * T + 0.30188 * T * T) * arcsec
    z = (2306.2181 * T + 1.09468 * T * T) * arcsec
    theta = (2004.3109 * T - 0.42665 * T * T) * arcsec

    def Rz(a):
        return np.array([[np.cos(a), np.sin(a), 0.0],
                         [-np.sin(a), np.cos(a), 0.0],
                         [0.0, 0.0, 1.0]])

    Ry = np.array([[np.cos(theta), 0.0, -np.sin(theta)],
                   [0.0, 1.0, 0.0],
                   [np.sin(theta), 0.0, np.cos(theta)]])
    return Rz(-z) @ Ry @ Rz(-zeta) @ np.asarray(n_hat)


def earth_velocity_kms(mjd):
    """Earth's barycentric velocity [km/s], equatorial J2000-of-date
    frame; exact Keplerian velocity on low-precision mean elements."""
    mjd = np.asarray(mjd, dtype=np.float64)
    T = (mjd - 51544.5) / 36525.0
    g = np.radians(357.52911 + 35999.05029 * T)       # solar mean anomaly
    L = np.radians(280.46646 + 36000.76983 * T)       # solar mean long.
    e = 0.016708634 - 0.000042037 * T
    C = np.radians((1.914602 - 0.004817 * T) * np.sin(g)
                   + (0.019993 - 0.000101 * T) * np.sin(2 * g)
                   + 0.000289 * np.sin(3 * g))        # equation of center
    lam_sun = L + C                                   # true solar long.
    pomega_sun = L - g                                # long. of perigee
    lam_e = lam_sun + np.pi                           # Earth helio long.
    pomega_e = pomega_sun + np.pi
    V = 2.0 * np.pi * AU_KM / (365.25636 * 86400.0) / np.sqrt(1.0 - e * e)
    vx_ecl = -V * (np.sin(lam_e) + e * np.sin(pomega_e))
    vy_ecl = V * (np.cos(lam_e) + e * np.cos(pomega_e))
    eps = np.radians(23.4392911 - 0.0130042 * T)
    return np.stack([vx_ecl,
                     vy_ecl * np.cos(eps),
                     vy_ecl * np.sin(eps)], axis=-1)


def site_velocity_kms(mjd, itrf_m):
    """Diurnal rotation velocity of an ITRF site [km/s], equatorial
    frame of date."""
    mjd = np.asarray(mjd, dtype=np.float64)
    theta = gmst_rad(mjd)
    x, y, z = np.asarray(itrf_m) / 1000.0
    # inertial position = Rz(theta) r; velocity = omega ez x position
    xi = x * np.cos(theta) - y * np.sin(theta)
    yi = x * np.sin(theta) + y * np.cos(theta)
    return OMEGA_EARTH * np.stack([-yi, xi, np.zeros_like(xi)], axis=-1)


def _n_hat_of_date(mjd, ra, dec):
    """Unit vector toward J2000 (ra, dec), precessed to the mean
    equinox of date (matching the of-date velocity/GMST frames)."""
    n_j2000 = np.array([np.cos(dec) * np.cos(ra),
                        np.cos(dec) * np.sin(ra), np.sin(dec)])
    return precess_from_j2000(mjd, n_j2000)


def doppler_factor(mjd, ra, dec, telescope="GBT"):
    """nu_source/nu_observed = sqrt((1+beta)/(1-beta)) toward J2000
    (ra, dec) [rad] at MJD(s); beta > 0 for increasing distance."""
    n_hat = _n_hat_of_date(mjd, ra, dec)
    v = earth_velocity_kms(mjd)
    itrf = _obs_itrf(telescope)
    if itrf is not None:
        v = v + site_velocity_kms(mjd, itrf)
    beta = -(v @ n_hat) / C_KMS           # receding -> beta > 0
    return np.sqrt((1.0 + beta) / (1.0 - beta))


def parallactic_angle(mjd, ra, dec, telescope="GBT"):
    """Parallactic angle [rad] at MJD(s) for a source at J2000
    (ra, dec)."""
    itrf = _obs_itrf(telescope)
    if itrf is None:
        return np.zeros_like(np.asarray(mjd, dtype=np.float64))
    nd = _n_hat_of_date(mjd, ra, dec)
    ra_d = np.arctan2(nd[1], nd[0])
    dec_d = np.arcsin(np.clip(nd[2], -1.0, 1.0))
    lat, lon, _ = itrf_to_geodetic(itrf)
    ha = gmst_rad(mjd) + lon - ra_d
    return np.arctan2(np.sin(ha),
                      np.tan(lat) * np.cos(dec_d)
                      - np.sin(dec_d) * np.cos(ha))


def doppler_parangle_for_archive(epochs, ephemeris_text, telescope,
                                 warn=True):
    """(doppler_factors [nsub], parallactic_angles [nsub]) for subint
    epochs, or (None, None) — with a loud warning, since downstream
    barycentric corrections silently degrade to topocentric — when the
    source coordinates or observatory position are unknown."""
    radec = parse_ra_dec(ephemeris_text)
    itrf_known = _obs_itrf(telescope) is not None
    if radec is None or not itrf_known:
        if warn and len(epochs):
            why = [] if radec is not None else \
                ["no RAJ/DECJ or ELONG/ELAT in the ephemeris"]
            if not itrf_known:
                why.append("telescope '%s' not in OBSERVATORY_ITRF"
                           % telescope)
            warnings.warn(
                "Cannot compute Doppler factors/parallactic angles (%s);"
                " falling back to unity/zero — barycentric (bary=True) "
                "DM/GM/tau outputs will actually be topocentric."
                % "; ".join(why), stacklevel=2)
        return None, None
    ra, dec = radec
    mjds = np.array([e.mjd() for e in epochs], dtype=np.float64)
    return (doppler_factor(mjds, ra, dec, telescope),
            parallactic_angle(mjds, ra, dec, telescope))
