"""Utility records and tables."""
