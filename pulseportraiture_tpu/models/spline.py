"""ppspline-equivalent model builder: PCA + B-spline profile evolution.

TPU-native equivalent of the reference's primary modern modeling path
(/root/reference/ppspline.py:34-274 ``make_spline_model``/
``write_model``): the portrait is decomposed into a weighted-mean
profile plus principal components (device ``eigh``), significant
eigenvectors are selected by smoothed Fourier S/N (batched wavelet
search, ops.pca/ops.wavelet), the per-channel projections are fit with a
parametric B-spline over frequency (host FITPACK ``splprep`` — runs once
per model), and the model is stored in the npz spline container that the
TOA pipeline evaluates on device with the de Boor kernel (ops.splines).
"""

import numpy as np
import scipy.interpolate as si

from ..dataportrait import DataPortrait
from ..io.splmodel import write_spline_model
from ..ops.pca import find_significant_eigvec, pca, reconstruct_portrait
from ..ops.splines import gen_spline_portrait
from ..ops.wavelet import smart_smooth
from ..utils.databunch import DataBunch

__all__ = ["make_spline_model", "SplineModelPortrait"]


def make_spline_model(dp, max_ncomp=10, smooth=True, snr_cutoff=150.0,
                      rchi2_tol=0.1, k=3, sfac=1.0, max_nbreak=None,
                      model_name=None, quiet=True, **kwargs):
    """Build a PCA/B-spline portrait model from a DataPortrait.

    dp: a DataPortrait (or path to an archive/metafile, loaded here).
    Behavioral equivalent of /root/reference/ppspline.py:34-204; returns
    a DataBunch with (model_name, source, datafile, mean_prof, eigvec
    [nbin, ncomp], tck, ieig, ncomp, eigval, proj_port, model, modelx,
    fp, ier) and stores the same attributes on ``dp``.
    Smoothing parameter: s = sfac * nprof * sum((SNR*sigma)**2)/sum(SNR)**2
    (the reference's formula, ppspline.py:135-146).
    """
    if isinstance(dp, str):
        dp = DataPortrait(dp, quiet=quiet)

    port = dp.portx
    pca_weights = dp.SNRsxs / np.sum(dp.SNRsxs)
    mean_prof = (port * pca_weights[:, None]).sum(axis=0) / \
        pca_weights.sum()
    freqs = dp.freqsxs[0]
    nu_lo, nu_hi = freqs.min(), freqs.max()
    nbin = port.shape[1]
    if nbin % 2 != 0:
        if not quiet:
            print("nbin = %d is odd; cannot wavelet-smooth." % nbin)
        smooth = False

    eigval, eigvec = (np.asarray(a) for a in
                      pca(port, mean_prof, pca_weights))
    return_max = 10 if max_ncomp is None else min(max_ncomp, 10)
    if smooth:
        ieig, smooth_eigvec = find_significant_eigvec(
            eigvec, check_max=10, return_max=return_max,
            snr_cutoff=snr_cutoff, return_smooth=True,
            rchi2_tol=rchi2_tol, **kwargs)
        smooth_mean_prof = np.asarray(smart_smooth(
            mean_prof, rchi2_tol=rchi2_tol, fallback="raw"))
        use_mean = smooth_mean_prof
        use_eigvec = smooth_eigvec
    else:
        ieig = find_significant_eigvec(
            eigvec, check_max=10, return_max=return_max,
            snr_cutoff=snr_cutoff, return_smooth=False,
            rchi2_tol=rchi2_tol, **kwargs)
        smooth_mean_prof = smooth_eigvec = None
        use_mean = mean_prof
        use_eigvec = eigvec
    ncomp = len(ieig)

    nchan_all = dp.freqs.shape[-1]
    if ncomp == 0:
        # constant-profile model
        proj_port = port[:, :0]
        modelx = np.tile(use_mean, (len(freqs), 1))
        model = np.tile(use_mean, (nchan_all, 1))
        tck = [np.array([]), np.array([]).reshape(0, 0), 0]
        u, fp, ier, msg = np.array([]), None, None, None
    else:
        delta_port = port - mean_prof
        proj_port = delta_port @ use_eigvec[:, ieig]     # [nchanx, ncomp]
        # FITPACK parametric spline of the projections over frequency
        spl_weights = pca_weights
        s = sfac * len(proj_port) * \
            np.sum((dp.SNRsxs * dp.noise_stdsxs) ** 2) / \
            np.sum(dp.SNRsxs) ** 2
        flip = -1 if dp.bw < 0 else 1   # u must be increasing
        (tck, u), fp, ier, msg = si.splprep(
            proj_port[::flip].T, w=spl_weights[::flip], u=freqs[::flip],
            ub=nu_lo, ue=nu_hi, k=min(k, len(freqs) - 1), task=0, s=s,
            t=None, full_output=1, nest=None, per=0, quiet=int(quiet))
        if max_nbreak is not None and \
                len(np.unique(tck[0])) > max_nbreak:
            max_nbreak = max(max_nbreak, 2)
            if max_nbreak == 2:
                s = np.inf
            (tck, u), fp, ier, msg = si.splprep(
                proj_port[::flip].T, w=spl_weights[::flip],
                u=freqs[::flip], ub=nu_lo, ue=nu_hi,
                k=min(k, len(freqs) - 1), task=0, s=s, t=None,
                full_output=1, nest=max_nbreak + 2 * k, per=0,
                quiet=int(quiet))
        if ier is not None and ier > 1 and not quiet:
            print("splprep trouble for %s:\n%s" % (dp.source, msg))
        tck = [np.asarray(tck[0]), np.asarray(tck[1]), tck[2]]
        modelx = np.asarray(gen_spline_portrait(
            use_mean, freqs, use_eigvec[:, ieig], tck))
        model = np.asarray(gen_spline_portrait(
            use_mean, dp.freqs[0], use_eigvec[:, ieig], tck))

    reconst_port = np.asarray(reconstruct_portrait(
        port, mean_prof, use_eigvec[:, ieig])) if ncomp else modelx.copy()

    if model_name is None:
        model_name = str(dp.datafile) + ".spl"
    # mirror the reference's attribute surface on the DataPortrait
    dp.ieig, dp.ncomp = ieig, ncomp
    dp.eigval, dp.eigvec = eigval, eigvec
    dp.mean_prof = mean_prof
    if smooth:
        dp.smooth_mean_prof = smooth_mean_prof
        dp.smooth_eigvec = smooth_eigvec
    dp.proj_port, dp.reconst_port = proj_port, reconst_port
    dp.tck, dp.u, dp.fp, dp.ier = tck, u, fp, ier
    dp.model_name = model_name
    dp.model, dp.modelx = model, modelx
    dp.model_masked = model * dp.masks[0, 0]

    if not quiet:
        if ncomp:
            print("B-spline model %s: %d components, %d breakpoints "
                  "(k=%d)." % (model_name, ncomp,
                               len(np.unique(tck[0])), tck[2]))
        else:
            print("B-spline model %s: 0 components (mean profile only)."
                  % model_name)
    return DataBunch(model_name=model_name, source=dp.source,
                     datafile=str(dp.datafile), mean_prof=use_mean,
                     eigvec=use_eigvec[:, ieig] if ncomp
                     else np.zeros((nbin, 0)),
                     tck=tck, ieig=ieig, ncomp=ncomp, eigval=eigval,
                     proj_port=proj_port, model=model, modelx=modelx,
                     fp=fp, ier=ier)


def write_model(outfile, built, quiet=True):
    """Write a built spline model (make_spline_model return) to the npz
    container (reference ppspline.py:206-230 pickles instead)."""
    write_spline_model(outfile, built.model_name, built.source,
                       built.datafile, built.mean_prof, built.eigvec,
                       built.tck, quiet=quiet)
    return outfile


class SplineModelPortrait(DataPortrait):
    """DataPortrait with spline-modeling methods, mirroring the
    reference's ppspline.DataPortrait subclass surface."""

    def make_spline_model(self, **kwargs):
        self.spline_model = make_spline_model(self, **kwargs)
        return self.spline_model

    def write_model(self, outfile, quiet=True):
        if not hasattr(self, "spline_model"):
            raise AttributeError("call make_spline_model first")
        return write_model(outfile, self.spline_model, quiet=quiet)
