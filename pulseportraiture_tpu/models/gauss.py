"""ppgauss-equivalent model builder: iterated evolving-Gaussian fits.

TPU-native equivalent of the reference's Gaussian modeling path
(/root/reference/ppgauss.py:55-372 ``make_gaussian_model``/
``model_iteration``/``check_convergence``/``write_model``/
``write_errfile``).  Seeding is non-interactive by default
(fit.gauss.auto_gauss_seed / peak_pick_seed) with the hand-fitting
GaussianSelector GUI available via ``interactive=True`` (viz.selector);
the lmfit portrait fit becomes the batched JAX Levenberg-Marquardt; the
convergence check reuses the 2-parameter device fit kernel.
"""

import numpy as np

from ..config import default_model, scattering_alpha
from ..dataportrait import DataPortrait
from ..fit.gauss import (auto_gauss_seed, fit_gaussian_portrait,
                         peak_pick_seed)
from ..fit.phase_shift import fit_phase_shift
from ..fit.portrait import fit_portrait
from ..fit.transforms import guess_fit_freq
from ..io.gmodel import read_model, write_model
from ..ops.fourier import rotate_data
from ..ops.profiles import gen_gaussian_portrait

__all__ = ["GaussianModelPortrait", "make_gaussian_model"]


class GaussianModelPortrait(DataPortrait):
    """DataPortrait with Gaussian-modeling methods, mirroring the
    reference's ppgauss.DataPortrait subclass surface."""

    def fit_profile(self, profile, errs=None, tau=0.0, fixscat=True,
                    auto_gauss=0.0, max_ngauss=6, interactive=False,
                    quiet=True):
        """Seed Gaussian components from an averaged profile.

        Replaces the interactive GaussianSelector launch
        (/root/reference/ppgauss.py:28-53): ``auto_gauss`` != 0 fits one
        component of that width guess; ``interactive`` opens the
        matplotlib picker (viz.selector); otherwise iterative
        peak-pick-fit-subtract finds up to ``max_ngauss`` components.
        """
        if errs is None:
            errs = float(np.median(self.noise_stdsxs))
        if interactive:
            from ..viz.selector import select_gaussians

            fit = select_gaussians(profile, errs, tau=tau,
                                   fixscat=fixscat, quiet=quiet)
        elif auto_gauss:
            fit = auto_gauss_seed(profile, errs, wid_guess=auto_gauss,
                                  tau=tau, fit_scattering=not fixscat)
        else:
            fit = peak_pick_seed(profile, errs, max_ngauss=max_ngauss,
                                 tau=tau, fit_scattering=not fixscat,
                                 quiet=quiet)
        self.init_params = list(fit.fitted_params)
        self.ngauss = (len(fit.fitted_params) - 2) // 3
        return fit

    def make_gaussian_model(self, modelfile=None, ref_prof=(None, None),
                            tau=0.0, fixloc=False, fixwid=False,
                            fixamp=False, fixscat=True, fixalpha=True,
                            scattering_index=scattering_alpha,
                            model_code=default_model, niter=0,
                            fiducial_gaussian=False, auto_gauss=0.0,
                            max_ngauss=6, interactive=False,
                            writemodel=False, outfile=None,
                            writeerrfile=False, errfile=None,
                            model_name=None, quiet=True):
        """Iterate evolving-Gaussian portrait fits to convergence.

        Behavioral equivalent of /root/reference/ppgauss.py:55-238: seed
        from a modelfile (improve mode) or a profile fit; then fit the
        full portrait, measure the residual (phase, DM) of the data
        against the fitted model, rotate the data by it, and repeat
        until the offsets are within their uncertainties or ``niter``
        runs out.  Writes the model each iteration when ``writemodel``.
        """
        if modelfile:
            if outfile is None:
                outfile = modelfile
            (self.model_name, self.model_code, self.nu_ref, self.ngauss,
             self.init_model_params, self.fit_flags,
             self.scattering_index, fitalpha) = read_model(modelfile)
            self.fixalpha = not fitalpha
            if model_name is not None:
                self.model_name = model_name
            # TAU in the file is seconds; the fit works in bins
            self.init_model_params[1] *= self.nbin / self.Ps[0]
        else:
            self.model_code = model_code
            self.scattering_index = scattering_index
            self.fixalpha = fixalpha
            self.model_name = model_name if model_name is not None \
                else self.source
            if not len(self.init_params):
                nu_ref, bw_ref = ref_prof
                self.nu_ref = self.nu0 if nu_ref is None else nu_ref
                bw_ref = abs(self.bw) if bw_ref is None else bw_ref
                inband = (self.freqs[0] > self.nu_ref - bw_ref / 2) & \
                    (self.freqs[0] < self.nu_ref + bw_ref / 2) & \
                    (self.masks[0, 0].mean(axis=1) > 0)
                # align the bands with the seed join parameters for the
                # profile used by automatic component seeding (the
                # reference leaves this to the interactive selector);
                # rotate a local copy — never the shared portrait state
                iband = np.flatnonzero(inband)
                band_port = np.array(self.port[iband])
                if self.njoin:
                    for ij in range(self.njoin):
                        phi_j = self.join_params[2 * ij]
                        DM_j = self.join_params[2 * ij + 1]
                        sel = np.isin(iband, self.join_ichans[ij])
                        if sel.any():
                            band_port[sel] = np.asarray(rotate_data(
                                band_port[sel], -phi_j, -DM_j, self.Ps[0],
                                self.freqs[0, iband[sel]], self.nu_ref))
                profile = band_port.mean(axis=0)
                self.fit_profile(profile, tau=tau, fixscat=fixscat,
                                 auto_gauss=auto_gauss,
                                 max_ngauss=max_ngauss,
                                 interactive=interactive, quiet=quiet)
            else:
                self.nu_ref = ref_prof[0] or self.nu0
                self.ngauss = (len(self.init_params) - 2) // 3
            # expand [dc, tau, (loc, wid, amp)*n] to the evolving form
            # with zero slopes/spectral indices
            mp = np.empty([self.ngauss, 6])
            for ig in range(self.ngauss):
                mp[ig] = [self.init_params[2::3][ig], 0.0,
                          self.init_params[3::3][ig], 0.0,
                          self.init_params[4::3][ig], 0.0]
            self.init_model_params = np.array(
                [self.init_params[0], self.init_params[1]]
                + list(mp.ravel()))
            self.fit_flags = np.ones(len(self.init_model_params))
            self.fit_flags[1] *= not fixscat
            self.fit_flags[3::6] *= not fixloc
            self.fit_flags[5::6] *= not fixwid
            self.fit_flags[7::6] *= not fixamp
            if fiducial_gaussian:
                # free every component's loc slope except the first: the
                # fiducial component does not drift with frequency
                # (ref ppgauss.py:155-159)
                self.fit_flags[3::6] = 1
                self.fit_flags[3] = 0
        if errfile is None and outfile is not None:
            errfile = outfile + "_errs"

        self.portx_noise = np.outer(self.noise_stdsxs, np.ones(self.nbin))
        self.nu_fit = float(np.asarray(guess_fit_freq(self.freqsxs[0],
                                                      self.SNRsxs)))
        niter = max(niter, 0)
        self.niter = self.itern = niter
        self.model_params = np.copy(self.init_model_params)

        self._model_iteration(quiet=quiet)
        self.cnvrgnc = self.check_convergence(quiet=quiet)
        if writemodel:
            self.write_model(outfile=outfile, quiet=quiet)
        if writeerrfile:
            self.write_errfile(errfile=errfile, quiet=quiet)
        while self.niter and not self.cnvrgnc:
            if not self.njoin:
                # rotate the data into the fitted frame and refit
                self.port = np.asarray(rotate_data(
                    self.port, self.phi, self.DM, self.Ps[0],
                    self.freqs[0], self.nu_fit))
                self.portx = np.asarray(rotate_data(
                    self.portx, self.phi, self.DM, self.Ps[0],
                    self.freqsxs[0], self.nu_fit))
            self._model_iteration(quiet=quiet)
            self.niter -= 1
            self.cnvrgnc = self.check_convergence(quiet=quiet)
            if writemodel:  # for safety, write after each iteration
                self.write_model(outfile=outfile, quiet=quiet)
            if writeerrfile:
                self.write_errfile(errfile=errfile, quiet=quiet)
        if self.njoin:
            # rotate the joined bands (and model) back to native frames
            for ii in range(self.njoin):
                phi = self.join_params[0::2][ii]
                DM = self.join_params[1::2][ii]
                jic = self.join_ichans[ii]
                self.port[jic] = np.asarray(rotate_data(
                    self.port[jic], -phi, -DM, self.Ps[0],
                    self.freqs[0, jic], self.nu_ref))
                jicx = self.join_ichanxs[ii]
                self.portx[jicx] = np.asarray(rotate_data(
                    self.portx[jicx], -phi, -DM, self.Ps[0],
                    self.freqsxs[0][jicx], self.nu_ref))
                self.model[jic] = np.asarray(rotate_data(
                    self.model[jic], -phi, -DM, self.Ps[0],
                    self.freqs[0, jic], self.nu_ref))
            self.model_masked = self.model * self.masks[0, 0]
            self.modelx = self.model[self.ok_ichans[0]]
        if not quiet:
            print("Residuals std: %.2e (data std %.2e)"
                  % ((self.portx - self.modelx).std(),
                     np.median(self.noise_stdsxs)))
        return self.model

    def _model_iteration(self, quiet=True):
        """One full-portrait Gaussian fit (ref ppgauss.py:240-276)."""
        fgp = fit_gaussian_portrait(
            self.model_code, self.portx, self.model_params,
            self.scattering_index, self.portx_noise, self.fit_flags,
            not self.fixalpha, self.phases, self.freqsxs[0], self.nu_ref,
            self.all_join_params, self.Ps[0], quiet=quiet)
        self.fgp = fgp
        self.chi2, self.dof = fgp.chi2, fgp.dof
        self.scattering_index = fgp.scattering_index
        self.scattering_index_err = fgp.scattering_index_err
        if self.njoin:
            self.model_params = fgp.fitted_params[:-self.njoin * 2]
            self.model_param_errs = fgp.fit_errs[:-self.njoin * 2]
            self.join_params = fgp.fitted_params[-self.njoin * 2:]
            self.join_param_errs = fgp.fit_errs[-self.njoin * 2:]
            self.all_join_params[1] = self.join_params
        else:
            self.model_params = fgp.fitted_params[:]
            self.model_param_errs = fgp.fit_errs[:]
        full_params = np.concatenate(
            [self.model_params,
             self.join_params if self.njoin else np.array([])])
        # np.array (writable copy): the join path rotates bands of the
        # model in place, and device-backed arrays are read-only
        self.model = np.array(gen_gaussian_portrait(
            self.model_code, full_params, self.scattering_index,
            self.phases, self.freqs[0], self.nu_ref,
            self.join_ichans, self.Ps[0]))
        self.model_masked = self.model * self.masks[0, 0]
        self.modelx = self.model[self.ok_ichans[0]]

    def check_convergence(self, efac=1.0, quiet=True):
        """(phase, DM) of the data vs the fitted model within errors?
        (ref ppgauss.py:278-334)"""
        if self.njoin:
            portx = np.zeros_like(self.portx)
            modelx = np.zeros_like(self.modelx)
            for ii in range(self.njoin):
                phi = self.join_params[0::2][ii]
                DM = self.join_params[1::2][ii]
                jicx = self.join_ichanxs[ii]
                portx[jicx] = np.asarray(rotate_data(
                    self.portx[jicx], -phi, -DM, self.Ps[0],
                    self.freqsxs[0][jicx], self.nu_ref))
                modelx[jicx] = np.asarray(rotate_data(
                    self.modelx[jicx], -phi, -DM, self.Ps[0],
                    self.freqsxs[0][jicx], self.nu_ref))
        else:
            portx, modelx = self.portx, self.modelx
        phase_guess = float(np.asarray(fit_phase_shift(
            portx.mean(axis=0), modelx.mean(axis=0)).phase))
        phase_guess = (phase_guess + 0.5) % 1.0 - 0.5
        fp = fit_portrait(portx, modelx, [phase_guess, 0.0], self.Ps[0],
                          self.freqsxs[0], nu_fit=self.nu_fit, quiet=True)
        self.fp_results = fp
        self.phi = float(np.asarray(fp.phase))
        self.phierr = float(np.asarray(fp.phase_err))
        self.DM = float(np.asarray(fp.DM))
        self.DMerr = float(np.asarray(fp.DM_err))
        self.red_chi2 = float(np.asarray(fp.red_chi2))
        if not quiet:
            print("Iter %d: phase %.2e +/- %.2e rot, DM %.6e +/- %.2e, "
                  "red chi2 %.2f" % (self.itern - self.niter, self.phi,
                                     self.phierr, self.DM, self.DMerr,
                                     self.red_chi2))
        converged = (min(abs(self.phi), abs(1 - self.phi))
                     < abs(self.phierr) * efac
                     and abs(self.DM) < abs(self.DMerr) * efac)
        return int(converged)

    def write_model(self, outfile=None, append=False, quiet=True):
        """Write the fitted model (TAU bins -> seconds)
        (ref ppgauss.py:336-352)."""
        if outfile is None:
            outfile = self.model_name + ".gmodel"
        params = np.copy(self.model_params)
        # wrap component locations back into [0, 1) (ref ppgauss.py:345)
        params[2::6] = np.where(params[2::6] >= 1.0, params[2::6] % 1.0,
                                params[2::6])
        params[1] *= self.Ps[0] / self.nbin
        write_model(outfile, self.model_name, self.model_code, self.nu_ref,
                    params, self.fit_flags.astype(int),
                    self.scattering_index, int(not self.fixalpha),
                    append=append, quiet=quiet)
        return outfile

    def write_errfile(self, errfile=None, quiet=True):
        """Write parameter uncertainties in model-file format
        (ref ppgauss.py:354-372)."""
        if errfile is None:
            errfile = self.model_name + ".gmodel_errs"
        errs = np.copy(self.model_param_errs)
        errs[1] *= self.Ps[0] / self.nbin
        write_model(errfile, self.model_name + "_errs", self.model_code,
                    self.nu_ref, errs, self.fit_flags.astype(int),
                    self.scattering_index_err, int(not self.fixalpha),
                    quiet=quiet)
        return errfile


def make_gaussian_model(datafile, quiet=True, **kwargs):
    """Convenience wrapper: datafile/metafile -> fitted
    GaussianModelPortrait (the ppgauss CLI's core path)."""
    dp = GaussianModelPortrait(datafile, quiet=quiet)
    dp.make_gaussian_model(quiet=quiet, **kwargs)
    return dp
