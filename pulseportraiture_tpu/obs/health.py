"""Live health plane: declarative alert rules over streaming metrics.

Every obs plane before this one (events, metrics, tracing, memory,
quality) is a passive recorder consumed *after* a run; this module is
the part of the system that can say "this run is unhealthy *right
now*" while it is still running — the probe surface the fleet router
and autoscaler consume (ROADMAP), and the trigger that makes the
flight recorder (:mod:`.flight`) dump a postmortem the moment things
go sideways.

**Rules** are declarative dicts evaluated over a sliding window of
registry snapshots (:class:`~.metrics.MetricsRegistry`).  Four kinds:

* ``threshold`` — a gauge's current value against a limit, optionally
  derived from a budget gauge (``budget_frac`` × ``budget_gauge``);
* ``rate`` — a windowed counter delta against a limit, optionally
  gated on a gauge (``guard_gauge``) and/or on another counter family
  staying quiet (``quiet``);
* ``ratio`` — windowed delta of ``num`` counters over ``den``
  counters, with a ``min_den`` sample floor;
* ``burn_rate`` — :func:`~.metrics.evaluate_slo` re-applied to the
  *window's* request deltas and latency-histogram delta, so an SLO
  breach is detected while it burns instead of at the end of the run.

**Lifecycle** per rule: ``ok`` → ``pending`` (predicate true) →
``firing`` (true for ``for_s`` continuously; emits ``alert_firing``,
bumps the ``alerts_fired`` manifest counter and the
``pps_alerts_total`` metric, raises the ``pps_alerts_firing`` gauges
and dumps a flight-recorder postmortem) → back to ``ok`` on recovery
(emits ``alert_resolved``, bumps ``alerts_resolved``).  The bare
``pps_alerts_firing`` gauge is the count of firing rules; the
rule-labeled series are 1/0 flags so watch views can name them.

**Cadence**: the metrics exporter calls :meth:`HealthState.evaluate`
on every snapshot tick, the survey runner on every claim cycle, and
the service ``health`` verb on demand.  Everything here is never
fatal, host-side only (jaxlint J002), and disabled at one attribute
read when no run is active — the standing obs contract.
``PPTPU_HEALTH=0`` turns the plane off; ``PPTPU_HEALTH_RULES``
overlays rule fields (JSON) or appends custom rules.
"""

import collections
import json
import os
import time

from . import core as _core
from .metrics import PHASE_HISTOGRAM, Histogram, evaluate_slo, \
    parse_series

__all__ = ["BUILTIN_RULES", "HealthState", "health_enabled",
           "health_rules", "evaluate", "firing"]

# gauge published by budget-aware hosts (service/daemon.py) that the
# memory_watermark rule prices device usage against; absent = the rule
# stays quiet (no budget, no watermark)
BUDGET_GAUGE = "pps_mem_budget_bytes"

# gauge set once warm-up finishes (runner/execute.py,
# service/daemon.py): the compile_cache_postwarm guard — a miss during
# warm-up is the expected cold compile, a miss after it is a leak
WARM_GAUGE = "pps_warm_complete"

BUILTIN_RULES = (
    {"name": "quarantine_spike", "kind": "rate", "severity": "critical",
     "signal": ("pps_quarantined_total",),
     "op": ">=", "threshold": 3, "window_s": 120.0, "for_s": 0.0,
     "summary": "archives/requests quarantined faster than the "
                "poison-pill baseline"},
    {"name": "retry_burn", "kind": "rate", "severity": "warning",
     "signal": ("pps_retries_total",),
     "op": ">=", "threshold": 10, "window_s": 120.0, "for_s": 0.0,
     "summary": "request retries burning through attempt budgets"},
    {"name": "lease_expiry_spike", "kind": "rate",
     "severity": "warning",
     "signal": ("pps_lease_expirations_total",),
     "op": ">=", "threshold": 3, "window_s": 120.0, "for_s": 0.0,
     "summary": "workers losing leases (stalls, kills, clock pressure)"},
    {"name": "memory_watermark", "kind": "threshold",
     "severity": "critical",
     "gauge": "pps_device_bytes_in_use",
     "budget_gauge": BUDGET_GAUGE, "budget_frac": 0.9,
     "op": ">=", "window_s": 60.0, "for_s": 0.0,
     "summary": "device memory above 90% of the configured budget"},
    {"name": "slo_burn", "kind": "burn_rate", "severity": "critical",
     "slo": {"max_error_rate": 0.5}, "min_requests": 4,
     "window_s": 120.0, "for_s": 0.0,
     "summary": "request error rate burning the SLO inside the window"},
    {"name": "bad_fit_drift", "kind": "ratio", "severity": "warning",
     "num": ("pps_quality_bad_subints_total",),
     "den": ("pps_quality_subints_total",), "min_den": 8,
     "op": ">=", "threshold": 0.5, "window_s": 300.0, "for_s": 0.0,
     "summary": "bad-fit rate drifting above half of recent subints"},
    {"name": "prefetch_stall", "kind": "rate", "severity": "warning",
     "signal": ("pps_prefetch_misses",),
     "quiet": ("pps_prefetch_hits",),
     "op": ">=", "threshold": 2, "window_s": 120.0, "for_s": 0.0,
     "summary": "prefetch missing with zero hits: the pipeline is "
                "IO-bound on a stalled prefetcher"},
    {"name": "compile_cache_postwarm", "kind": "rate",
     "severity": "warning",
     "signal": ("pps_compile_cache_misses_total",),
     "op": ">=", "threshold": 1, "window_s": 120.0, "for_s": 0.0,
     "guard_gauge": WARM_GAUGE, "guard_value": 1,
     "summary": "compile-cache misses after warm-up: the zero-cold-"
                "start contract is leaking compiles"},
    {"name": "daemon_churn", "kind": "rate", "severity": "warning",
     "signal": ("pps_respawns_total",),
     "op": ">=", "threshold": 2, "window_s": 300.0, "for_s": 0.0,
     "summary": "fleet daemons respawning repeatedly (crash-looping "
                "replica or poisoned bucket)"},
    {"name": "worker_churn", "kind": "rate", "severity": "warning",
     "signal": ("pps_supervisor_respawns_total",),
     "op": ">=", "threshold": 3, "window_s": 300.0, "for_s": 0.0,
     "summary": "survey workers respawning repeatedly under the "
                "supervisor (respawn storm; flapping slots park)"},
    # the quota plane (obs/usage.py) publishes pps_quota_burn as the
    # UNLABELED max used/limit fraction across budgeted tenants (the
    # per-tenant fractions live under a different name on purpose:
    # a threshold rule sums label variants); absent = no quotas, quiet
    {"name": "quota_burn", "kind": "threshold", "severity": "warning",
     "gauge": "pps_quota_burn",
     "op": ">=", "threshold": 0.85, "window_s": 60.0, "for_s": 0.0,
     "summary": "a tenant burned 85% of its usage quota: hard shed "
                "is imminent"},
)

_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

# window samples kept beyond the largest rule window (slack for jitter)
_PRUNE_SLACK_S = 60.0


def health_enabled():
    """False when PPTPU_HEALTH=0 turned the plane off."""
    return os.environ.get("PPTPU_HEALTH", "").strip() != "0"


def health_rules():
    """The effective rule list: built-ins with the
    ``PPTPU_HEALTH_RULES`` JSON overlay applied.  A dict overlay maps
    rule name → field overrides (``{"disabled": true}`` drops a rule);
    a list overlay appends full custom rules.  Unparsable overlays are
    ignored — never fatal."""
    rules = [dict(r) for r in BUILTIN_RULES]
    raw = os.environ.get("PPTPU_HEALTH_RULES", "").strip()
    if not raw:
        return rules
    try:
        overlay = json.loads(raw)
    except (json.JSONDecodeError, ValueError):
        return rules
    if isinstance(overlay, dict):
        out = []
        for r in rules:
            ov = overlay.get(r["name"])
            if isinstance(ov, dict):
                r.update(ov)
            if not r.get("disabled"):
                out.append(r)
        return out
    if isinstance(overlay, list):
        for r in overlay:
            if isinstance(r, dict) and r.get("name") and r.get("kind"):
                rules.append(dict(r))
    return rules


class _Sample:
    """One windowed registry snapshot (the health plane's unit of
    history)."""

    __slots__ = ("t", "counters", "gauges", "hists")

    def __init__(self, t, snap):
        self.t = t
        self.counters = snap.get("counters") or {}
        self.gauges = snap.get("gauges") or {}
        self.hists = snap.get("histograms") or {}


def _series_sum(store, specs):
    """Sum every series in ``store`` whose base name (merge prefixes
    stripped) matches one of ``specs``; a spec is a bare name or a
    ``(name, {label: value})`` filter.  None when no series matched —
    absent is not zero (a pre-plane snapshot must not fire rules)."""
    total = None
    for key, v in store.items():
        name, labels = parse_series(key.rsplit("/", 1)[-1])
        for spec in specs:
            if isinstance(spec, (tuple, list)) and len(spec) == 2:
                want, want_labels = spec
            else:
                want, want_labels = spec, None
            if name != want:
                continue
            if want_labels and any(labels.get(k) != str(val)
                                   for k, val in want_labels.items()):
                continue
            try:
                total = (total or 0.0) + float(v)
            except (TypeError, ValueError):
                pass
            break
    return total


class HealthState:
    """Windowed rule evaluation + alert lifecycle for one
    :class:`~.core.Recorder`."""

    def __init__(self, recorder, rules=None):
        self._rec = recorder
        self.rules = list(rules) if rules is not None else \
            health_rules()
        max_w = max([float(r.get("window_s", 0.0) or 0.0)
                     for r in self.rules] or [0.0])
        self._keep_s = max_w + _PRUNE_SLACK_S
        self._samples = collections.deque()
        # rule name -> {"state", "since", "fired_t", "measured"}
        self._states = {r["name"]: {"state": "ok", "since": None,
                                    "fired_t": None, "measured": None}
                        for r in self.rules}
        self._evaluating = False

    # -- window ---------------------------------------------------------

    def _baseline(self, now, window_s):
        """The newest sample at least ``window_s`` old, else the
        oldest available (a partial window on young runs — deltas
        start at zero, so a restart never back-fires a rate rule)."""
        cutoff = now - float(window_s)
        base = self._samples[0]
        for s in self._samples:
            if s.t <= cutoff:
                base = s
            else:
                break
        return base

    def _delta(self, store_attr, specs, now, window_s):
        cur = self._samples[-1]
        base = self._baseline(now, window_s)
        a = _series_sum(getattr(base, store_attr), specs)
        b = _series_sum(getattr(cur, store_attr), specs)
        if b is None:
            return None
        return b - (a or 0.0)

    # -- predicates -----------------------------------------------------

    def _predicate(self, rule, now):
        """(is_breaching, measured) for one rule against the current
        window; unknown kinds and absent signals read as healthy."""
        kind = rule.get("kind")
        op = _OPS.get(rule.get("op", ">="), _OPS[">="])
        window_s = float(rule.get("window_s", 120.0) or 120.0)
        if kind == "threshold":
            cur = self._samples[-1]
            val = _series_sum(cur.gauges, (rule["gauge"],))
            limit = rule.get("threshold")
            bg = rule.get("budget_gauge")
            if bg:
                budget = _series_sum(cur.gauges, (bg,))
                if not budget:
                    return False, {"value": val, "limit": None}
                limit = float(rule.get("budget_frac", 0.9)) * budget
            if val is None or limit is None:
                return False, {"value": val, "limit": limit}
            return op(val, float(limit)), {"value": val,
                                           "limit": float(limit)}
        if kind == "rate":
            delta = self._delta("counters", rule["signal"], now,
                                window_s)
            measured = {"delta": delta, "window_s": window_s,
                        "limit": rule.get("threshold")}
            if delta is None:
                return False, measured
            gg = rule.get("guard_gauge")
            if gg is not None:
                gv = _series_sum(self._samples[-1].gauges, (gg,))
                measured["guard"] = gv
                if gv != rule.get("guard_value", 1):
                    return False, measured
            quiet = rule.get("quiet")
            if quiet:
                qd = self._delta("counters", quiet, now, window_s)
                measured["quiet_delta"] = qd
                if qd:
                    return False, measured
            return op(delta, float(rule.get("threshold", 1))), measured
        if kind == "ratio":
            num = self._delta("counters", rule["num"], now, window_s)
            den = self._delta("counters", rule["den"], now, window_s)
            measured = {"num": num, "den": den,
                        "limit": rule.get("threshold"),
                        "window_s": window_s}
            if not den or den < float(rule.get("min_den", 1)):
                return False, measured
            ratio = (num or 0.0) / den
            measured["ratio"] = round(ratio, 6)
            return op(ratio, float(rule.get("threshold", 1.0))), \
                measured
        if kind == "burn_rate":
            return self._burn_rate(rule, now, window_s)
        return False, {}

    def _burn_rate(self, rule, now, window_s):
        cur = self._samples[-1]
        base = self._baseline(now, window_s)
        ok = err = 0
        for key, v in cur.counters.items():
            name, labels = parse_series(key.rsplit("/", 1)[-1])
            if name != "pps_requests_total":
                continue
            prev = base.counters.get(key, 0) or 0
            try:
                d = float(v) - float(prev)
            except (TypeError, ValueError):
                continue
            if labels.get("outcome") == "done":
                ok += d
            else:
                err += d
        span = max(1e-9, cur.t - base.t)
        measured = {"n_ok": int(ok), "n_err": int(err),
                    "window_s": window_s}
        total = ok + err
        if total < int(rule.get("min_requests", 1)):
            return False, measured
        hist = self._phase_hist_delta(cur, base,
                                      rule.get("phase", "total"))
        res = evaluate_slo(rule.get("slo") or {}, hist, ok, err, span)
        measured.update(res["measured"])
        measured["breaches"] = [b["slo"] for b in res["breaches"]]
        return (not res["ok"]), measured

    def _phase_hist_delta(self, cur, base, phase):
        """Window delta of the ``pps_phase_seconds{phase=...}``
        histograms as one snapshot dict (exact integer bucket
        subtraction — the same fixed-geometry property the shard merge
        relies on), or None when the phase has no series."""
        def collect(sample):
            h = None
            for key, snap in sample.hists.items():
                name, labels = parse_series(key.rsplit("/", 1)[-1])
                if name != PHASE_HISTOGRAM or \
                        labels.get("phase") != phase:
                    continue
                hh = Histogram.from_snapshot(snap)
                h = hh if h is None else h.merge(hh)
            return h
        cur_h = collect(cur)
        if cur_h is None:
            return None
        if cur is not base:
            old = collect(base)
            if old is not None:
                for i, c in old.counts.items():
                    cur_h.counts[i] = cur_h.counts.get(i, 0) - c
                cur_h.counts = {i: c for i, c in cur_h.counts.items()
                                if c > 0}
                cur_h.under -= old.under
                cur_h.over -= old.over
                cur_h.count -= old.count
                cur_h.sum -= old.sum
        return cur_h.to_snapshot()

    # -- lifecycle ------------------------------------------------------

    def evaluate(self, now=None):
        """Take one registry sample and advance every rule's
        lifecycle; returns the currently firing alerts.  Never raises
        — a broken rule reads as healthy, not as a crashed pipeline."""
        try:
            return self._evaluate(now)
        except Exception:
            return self.firing()

    def _evaluate(self, now):
        rec = self._rec
        reg = rec._metrics
        if reg is None or self._evaluating:
            return []
        now = float(now) if now is not None else time.time()
        # single-flight: the exporter tick, the claim cycle and the
        # health verb may race; one sampler at a time is plenty and
        # transitions stay single-threaded
        self._evaluating = True
        try:
            self._samples.append(_Sample(now, reg.snapshot()))
            while len(self._samples) > 1 and \
                    self._samples[0].t < now - self._keep_s:
                self._samples.popleft()
            transitions = []
            for rule in self.rules:
                st = self._states[rule["name"]]
                try:
                    breaching, measured = self._predicate(rule, now)
                except Exception as exc:
                    # per-rule isolation: one malformed rule must read
                    # as healthy without wedging the rules after it
                    breaching, measured = \
                        False, {"error": type(exc).__name__}
                st["measured"] = measured
                if breaching:
                    if st["state"] == "ok":
                        st["state"] = "pending"
                        st["since"] = now
                    if st["state"] == "pending" and \
                            now - st["since"] >= \
                            float(rule.get("for_s", 0.0) or 0.0):
                        st["state"] = "firing"
                        st["fired_t"] = now
                        transitions.append(("firing", rule, st))
                else:
                    if st["state"] == "firing":
                        transitions.append(("resolved", rule, st))
                    st["state"] = "ok"
                    st["since"] = None
            self._apply(transitions, reg, now)
            reg.set_gauge("pps_alerts_firing", sum(
                1 for s in self._states.values()
                if s["state"] == "firing"))
        finally:
            self._evaluating = False
        return self.firing()

    def _apply(self, transitions, reg, now):
        """Emit the lifecycle events/metrics for this pass's
        transitions, then trigger postmortems — the ``alert_firing``
        event lands in the ring before the bundle freezes it."""
        rec = self._rec
        for what, rule, st in transitions:
            name = rule["name"]
            if what == "firing":
                rec.event("alert_firing", rule=name,
                          severity=rule.get("severity", "warning"),
                          summary=rule.get("summary"),
                          measured=st["measured"])
                rec.counter("alerts_fired")
                reg.inc("pps_alerts_total", rule=name)
                reg.set_gauge("pps_alerts_firing", 1, rule=name)
            else:
                rec.event("alert_resolved", rule=name,
                          severity=rule.get("severity", "warning"),
                          firing_s=round(now - (st["fired_t"]
                                                or now), 6))
                rec.counter("alerts_resolved")
                reg.set_gauge("pps_alerts_firing", 0, rule=name)
        for what, rule, st in transitions:
            if what == "firing":
                rec.flight.dump("alert:%s" % rule["name"],
                                context={"rule": rule["name"],
                                         "severity": rule.get(
                                             "severity"),
                                         "measured": st["measured"]})

    def firing(self):
        """The currently firing alerts as JSON-ready dicts."""
        out = []
        for rule in self.rules:
            st = self._states[rule["name"]]
            if st["state"] != "firing":
                continue
            out.append({"rule": rule["name"],
                        "severity": rule.get("severity", "warning"),
                        "summary": rule.get("summary"),
                        "since": st["fired_t"],
                        "measured": st["measured"]})
        return out

    def states(self):
        """{rule name: lifecycle state} — the readiness surface."""
        return {name: dict(st) for name, st in self._states.items()}

    def stop(self):
        """Final evaluation at recorder close, so the last
        metrics.jsonl snapshot carries the closing alert gauges."""
        self.evaluate()


# -- module-level helpers (the instrumented-code API) -------------------


def evaluate(now=None):
    """Evaluate the active run's health rules (claim-cycle hook);
    returns the firing alerts, or None when no run is active /
    health is disabled — one attribute read on the disabled path."""
    rec = _core._active
    if rec is None:
        return None
    hs = rec.health_state()
    return hs.evaluate(now=now) if hs is not None else None


def firing():
    """The active run's firing alerts ([] when inactive/disabled)."""
    rec = _core._active
    if rec is None:
        return []
    hs = rec.health_state()
    return hs.firing() if hs is not None else []
