"""Distributed tracing: causal ids for the obs event stream.

The event stream (:mod:`.core`) records *what happened*; the metrics
plane (:mod:`.metrics`) records *how the distribution looks*.  Neither
answers *why this particular request was slow* — that needs
Dapper-style request-scoped tracing (Sigelman et al. 2010): every span
carries ``trace_id`` / ``span_id`` / ``parent_span_id``, so a p99
histogram bucket's exemplar trace id resolves to a concrete span tree
and a critical path (``tools/obs_trace.py``).

Design (docs/OBSERVABILITY.md "Distributed tracing"):

* **Context is ambient, per thread.**  A context is the pair
  ``(trace_id, span_id)`` — the trace this thread is working for and
  the span any new child should parent on.  It lives in the same
  thread-local the span stack uses (``core._tls``), so the read is ONE
  ``getattr`` — the disabled-path budget ``tools/span_overhead.py``
  prices.  :func:`activate` installs a context for a with-block (the
  cross-thread attach: a worker thread adopts its request's context).
* **Zero API churn for instrumented code.**  ``obs.span`` /
  ``obs.phases`` / ``obs.event`` stamp the ambient context onto the
  events they already emit and push the child context for their
  dynamic extent — the GetTOAs load/guess/solve/write phases become
  children of whatever request span is ambient without a single caller
  changing.  With no ambient context the events are exactly what they
  were before this module existed.
* **Explicit carriers across processes.**  :func:`inject` /
  :func:`extract` move a context through a dict using the W3C
  ``traceparent`` field (``00-<32hex trace>-<16hex span>-01``) — the
  socket protocol (service/server.py) forwards it verbatim, so
  ``pploadgen``'s client-side submit span becomes the root of the
  daemon-side request tree.
* **Fan-in is first-class.**  A batched dispatch serving K requests is
  ONE span carrying ``links`` — ``[{"trace_id", "span_id"}, ...]``
  references to every member request's context (OpenTelemetry span
  links) — instead of K copies or a lost edge (service/batcher.py).

Host-side only, like everything in ``obs``: jaxlint J002 statically
rejects ``tracing.*`` calls inside ``jax.jit``, and a trace id is a
host string — capturing one as a traced value burns the trace-time id
into every execution of the compiled program.
"""

import contextlib
import os
import re

from . import core as _core

__all__ = ["current", "current_trace_id", "current_span_id", "mint",
           "activate", "new_trace_id", "new_span_id", "inject",
           "extract", "format_traceparent", "parse_traceparent",
           "emit_span", "link", "TRACEPARENT_KEY"]

# the carrier field name (W3C Trace Context); the socket protocol and
# any future HTTP front reuse it unchanged
TRACEPARENT_KEY = "traceparent"

_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")


def new_trace_id():
    """A fresh 128-bit trace id (32 hex chars)."""
    return os.urandom(16).hex()


def new_span_id():
    """A fresh 64-bit span id (16 hex chars)."""
    return os.urandom(8).hex()


def mint():
    """A fresh root context: new trace, no parent span.  The first
    span opened under it becomes the trace's root."""
    return (new_trace_id(), None)


def current():
    """The ambient ``(trace_id, span_id)`` context of this thread, or
    None.  One thread-local lookup — safe on any hot path."""
    return getattr(_core._tls, "trace", None)


def current_trace_id():
    """Ambient trace id, or None (ledger/checkpoint stamping)."""
    ctx = getattr(_core._tls, "trace", None)
    return ctx[0] if ctx is not None else None


def current_span_id():
    """Ambient span id, or None."""
    ctx = getattr(_core._tls, "trace", None)
    return ctx[1] if ctx is not None else None


@contextlib.contextmanager
def activate(ctx):
    """Install ``ctx`` as this thread's ambient context for the
    with-block (and restore the previous one after).

    ``ctx`` is ``(trace_id, span_id)`` — typically a request's
    ``(trace_id, request_span_id)`` adopted by the worker thread that
    fits it, or :func:`mint` for a fresh root.  ``None`` deactivates
    tracing for the block.
    """
    tls = _core._tls
    prev = getattr(tls, "trace", None)
    tls.trace = tuple(ctx) if ctx is not None else None
    try:
        yield ctx
    finally:
        tls.trace = prev


def format_traceparent(ctx):
    """W3C traceparent string for a context (span id required — inject
    from inside a span, or allocate one first)."""
    trace_id, span_id = ctx
    if span_id is None:
        span_id = new_span_id()
    return "00-%s-%s-01" % (trace_id, span_id)


def parse_traceparent(value):
    """``(trace_id, span_id)`` from a traceparent string, or None when
    the value is absent/malformed (a bad carrier must degrade to an
    untraced request, never reject it)."""
    if not value or not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if not m:
        return None
    return (m.group(1), m.group(2))


def inject(carrier=None, ctx=None):
    """Write the (given or ambient) context into ``carrier`` as a
    ``traceparent`` field; returns the carrier (a fresh dict when
    None).  No-op returning the carrier unchanged when there is no
    context to propagate."""
    if carrier is None:
        carrier = {}
    if ctx is None:
        ctx = current()
    if ctx is not None:
        carrier[TRACEPARENT_KEY] = format_traceparent(ctx)
    return carrier


def extract(carrier):
    """Context from a carrier dict's ``traceparent`` field, or None."""
    if not isinstance(carrier, dict):
        return None
    return parse_traceparent(carrier.get(TRACEPARENT_KEY))


def link(ctx):
    """A span-link reference dict for ``ctx`` (JSON-ready)."""
    return {"trace_id": ctx[0], "span_id": ctx[1]}


def emit_span(name, dur_s, ctx=None, span_id=None, links=None,
              **attrs):
    """Record a span post-hoc (duration already measured).

    For intervals whose end is "now" but whose start predates any
    with-block — a request's queue wait measured at claim time, the
    request's own end-to-end span stamped at finalize.  Parents on the
    given ``ctx`` (or the ambient one); allocates ``span_id`` unless
    the caller pre-allocated it (a request span whose id children
    already reference).  ``links`` is a list of :func:`link` dicts.
    Returns the span id, or None when no run is active.
    """
    rec = _core._active
    if rec is None:
        return None
    if ctx is None:
        ctx = current()
    sid = span_id or new_span_id()
    fields = dict(attrs)
    if ctx is not None:
        fields["trace_id"] = ctx[0]
        if ctx[1] is not None:
            fields["parent_span_id"] = ctx[1]
    fields["span_id"] = sid
    if links:
        fields["links"] = list(links)
    rec.emit("span", name=name, path=name,
             dur_s=round(float(dur_s), 6), **fields)
    return sid
