"""Streaming metrics: counters, gauges, log-bucketed latency histograms.

The event stream (:mod:`.core`) answers "what happened"; this module
answers "how is it doing *right now*" — the live telemetry plane the
TOA service (docs/SERVICE.md) and the survey runner are operated and
SLO-gated on:

* **Counters / gauges with labels** — monotonically increasing totals
  (``pps_requests_total{outcome="done",tenant="alice"}``) and
  last-value-wins gauges (``pps_queue_depth{tenant="alice"}``), keyed
  by a Prometheus-style series string so snapshots render to both JSON
  and the Prometheus text exposition format without a schema change.
* **Log-bucketed latency histograms** (HDR-style) — bucket ``i``
  covers ``[lo·2^(i/per_octave), lo·2^((i+1)/per_octave))``; the
  boundaries are *fixed by construction* from ``(lo, hi,
  per_octave)``, so any two histograms of one series merge **exactly**
  by summing sparse bucket counts — across threads, snapshots, shards
  and processes, in any order, with the same result
  (:func:`merge_snapshots`, used by ``obs/merge.py``).  Quantiles are
  read from the bucket upper edge clamped to the exactly-tracked
  min/max, so ``quantile(h, q)`` is within one bucket's relative
  resolution (``2^(1/per_octave) - 1``) of the true percentile — the
  NumPy-oracle contract tests/test_metrics.py enforces.
* **Periodic snapshot exporter** — a daemon thread appends the full
  registry snapshot to ``<run-dir>/metrics.jsonl`` every
  ``PPTPU_METRICS_INTERVAL`` seconds (default 2.0; 0 disables the
  thread), plus one final snapshot at recorder close.  Each line is a
  complete cumulative snapshot, so readers (``tools/obs_report.py``,
  the ``--watch`` views, ``pploadgen``'s SLO gate) take the **last
  parseable line** — a crash mid-append leaves a torn tail that is
  simply skipped, never a corrupted series.

Activation follows the obs run lifecycle: the module-level helpers
(:func:`inc`, :func:`set_gauge`, :func:`observe`, :func:`timed`)
no-op at one attribute read + ``None`` check when no run is active —
the same "disabled = free" contract as ``obs.span`` (the <2% budget in
``tools/span_overhead.py`` now prices these too).  With a run active
they record into the run's lazily-created :class:`MetricsRegistry`
(one per :class:`~.core.Recorder`).

Host-side only, like everything in ``obs``: jaxlint J002 statically
rejects ``metrics.*`` calls inside ``jax.jit`` — under jit an
``observe`` would record the trace-time value once and never again.
"""

import bisect
import contextlib
import json
import math
import os
import re
import threading
import time

from . import core as _core

__all__ = ["Histogram", "MetricsRegistry", "MetricsExporter",
           "series_key", "parse_series", "quantile", "percentiles",
           "exemplar_for_quantile", "inc", "set_gauge", "observe",
           "timed", "snapshot", "metrics_interval",
           "render_prometheus", "merge_snapshots", "load_snapshots",
           "last_snapshot", "latest_run_dir", "evaluate_slo",
           "render_watch", "PHASE_HISTOGRAM", "SNAPSHOT_SCHEMA",
           "EXEMPLARS_PER_BUCKET"]

SNAPSHOT_SCHEMA = "pptpu-metrics-v1"

# the one histogram family the request/survey lifecycles share; phases
# are distinguished by the ``phase`` label (docs/OBSERVABILITY.md):
# service requests: queue_wait / checkout / park / dispatch / fit /
# checkpoint / total; survey archives: claim / fit / checkpoint /
# archive
PHASE_HISTOGRAM = "pps_phase_seconds"

# default bucket geometry: 1 us .. ~4096 s at 8 buckets per octave
# (~9% relative resolution, 256 buckets); chosen so a socket RTT and a
# cold multi-minute compile land in the same instrument
DEFAULT_LO = 1e-6
DEFAULT_HI = 4096.0
DEFAULT_PER_OCTAVE = 8

# per-bucket exemplar retention (last-K trace ids per bucket): enough
# to resolve "who was in this p99 bucket" without the snapshot growing
# with traffic (OpenMetrics exemplars carry one per rendered bucket)
EXEMPLARS_PER_BUCKET = 4


def metrics_interval():
    """$PPTPU_METRICS_INTERVAL: snapshot cadence in seconds (default
    2.0; 0 / unparsable-as-positive disables the periodic thread — the
    close-time final snapshot is always written)."""
    v = os.environ.get("PPTPU_METRICS_INTERVAL", "").strip()
    try:
        return max(0.0, float(v)) if v else 2.0
    except ValueError:
        return 2.0


def series_key(name, labels=None):
    """Prometheus-style series key: ``name{k="v",...}`` with labels
    sorted (deterministic across processes), or bare ``name``."""
    if not labels:
        return name
    inner = ",".join('%s="%s"' % (k, labels[k]) for k in sorted(labels))
    return "%s{%s}" % (name, inner)


_SERIES_RE = re.compile(r'^([^{]+)(?:\{(.*)\})?$')
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def parse_series(key):
    """Inverse of :func:`series_key`: ``(name, {label: value})``."""
    m = _SERIES_RE.match(key)
    if not m:
        return key, {}
    name, inner = m.group(1), m.group(2)
    if not inner:
        return name, {}
    return name, dict(_LABEL_RE.findall(inner))


class Histogram:
    """Log-bucketed latency histogram with exact deterministic merge.

    Bucket boundaries are a pure function of ``(lo, hi, per_octave)``
    — precomputed edges, indexed by bisection (no per-observation
    ``log`` call, no float-rounding ambiguity at the boundaries):
    ``edges[i] = lo * 2**(i / per_octave)``.  Values below ``lo`` land
    in ``under``, at/above ``hi`` in ``over``; exact ``count``,
    ``sum``, ``min`` and ``max`` ride along.
    """

    __slots__ = ("lo", "hi", "per_octave", "n_buckets", "edges",
                 "counts", "under", "over", "count", "sum", "min",
                 "max", "exemplars", "_lock")

    def __init__(self, lo=DEFAULT_LO, hi=DEFAULT_HI,
                 per_octave=DEFAULT_PER_OCTAVE):
        if not (lo > 0 and hi > lo and per_octave >= 1):
            raise ValueError("need 0 < lo < hi and per_octave >= 1")
        self.lo = float(lo)
        self.hi = float(hi)
        self.per_octave = int(per_octave)
        self.n_buckets = int(math.ceil(
            math.log(self.hi / self.lo, 2.0) * self.per_octave))
        self.edges = [self.lo * 2.0 ** (i / self.per_octave)
                      for i in range(self.n_buckets + 1)]
        self.counts = {}          # sparse: bucket index -> count
        self.under = 0
        self.over = 0
        self.count = 0
        self.sum = 0.0
        self.min = None
        self.max = None
        # sparse: bucket index -> last-K [{"trace_id", "value"}, ...]
        # (index n_buckets = the overflow bucket); the distributed-
        # tracing hook: a quantile's bucket resolves to concrete trace
        # ids (obs/tracing.py, tools/obs_trace.py)
        self.exemplars = {}
        self._lock = threading.Lock()

    def bucket_index(self, value):
        """Bucket index for ``value`` (-1 = under, n_buckets = over)."""
        v = float(value)
        if v < self.lo:
            return -1
        if v >= self.edges[-1]:
            return self.n_buckets
        return bisect.bisect_right(self.edges, v) - 1

    def observe(self, value, exemplar=None):
        """Record one observation; ``exemplar`` (a trace id string)
        attaches the observation's trace to its bucket, keeping the
        last ``EXEMPLARS_PER_BUCKET`` per bucket."""
        v = float(value)
        if v != v:          # NaN: drop rather than poison the stats
            return
        i = self.bucket_index(v)
        with self._lock:
            if i < 0:
                self.under += 1
            elif i >= self.n_buckets:
                self.over += 1
            else:
                self.counts[i] = self.counts.get(i, 0) + 1
            if exemplar:
                # clamp to [0, n_buckets]: an under-range value keeps
                # its exemplar on the first bucket (the rendered
                # cumulative bucket 0 already counts ``under``), the
                # symmetric move to the over bucket above — a traced
                # sub-resolution observation must stay traceable
                ex = self.exemplars.setdefault(
                    max(0, min(i, self.n_buckets)), [])
                ex.append({"trace_id": str(exemplar), "value": v})
                del ex[:-EXEMPLARS_PER_BUCKET]
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)

    def merge(self, other):
        """Fold ``other`` in; exact (integer bucket sums) and
        commutative, provided the geometries match."""
        if (self.lo, self.hi, self.per_octave) != \
                (other.lo, other.hi, other.per_octave):
            raise ValueError(
                "histogram geometry mismatch: (%g,%g,%d) vs (%g,%g,%d)"
                % (self.lo, self.hi, self.per_octave,
                   other.lo, other.hi, other.per_octave))
        with self._lock:
            for i, c in other.counts.items():
                i = int(i)
                self.counts[i] = self.counts.get(i, 0) + int(c)
            # exemplars survive the merge (the bucket-count merge stays
            # exact regardless): concatenate per bucket, dedupe by
            # trace id preserving order, keep the last K — shard order
            # is fixed by the callers (sorted proc), so the merged
            # exemplar set is deterministic
            for i, ex in other.exemplars.items():
                i = int(i)
                seen = {}
                for item in self.exemplars.get(i, []) + list(ex):
                    tid = item.get("trace_id")
                    if tid:
                        seen[tid] = item
                self.exemplars[i] = \
                    list(seen.values())[-EXEMPLARS_PER_BUCKET:]
            self.under += other.under
            self.over += other.over
            self.count += other.count
            self.sum += other.sum
            for attr, pick in (("min", min), ("max", max)):
                ov = getattr(other, attr)
                if ov is not None:
                    sv = getattr(self, attr)
                    setattr(self, attr,
                            ov if sv is None else pick(sv, ov))
        return self

    def to_snapshot(self):
        with self._lock:
            snap = {"lo": self.lo, "hi": self.hi,
                    "per_octave": self.per_octave,
                    "count": self.count,
                    "sum": round(self.sum, 9),
                    "min": self.min, "max": self.max,
                    "under": self.under, "over": self.over,
                    "counts": {str(i): c
                               for i, c in sorted(self.counts.items())}}
            if self.exemplars:
                snap["exemplars"] = {
                    str(i): [dict(x) for x in ex]
                    for i, ex in sorted(self.exemplars.items()) if ex}
            return snap

    @classmethod
    def from_snapshot(cls, snap):
        h = cls(lo=snap.get("lo", DEFAULT_LO),
                hi=snap.get("hi", DEFAULT_HI),
                per_octave=snap.get("per_octave", DEFAULT_PER_OCTAVE))
        h.counts = {int(i): int(c)
                    for i, c in (snap.get("counts") or {}).items()}
        h.under = int(snap.get("under", 0))
        h.over = int(snap.get("over", 0))
        h.count = int(snap.get("count", 0))
        h.sum = float(snap.get("sum", 0.0))
        h.min = snap.get("min")
        h.max = snap.get("max")
        h.exemplars = {int(i): [dict(x) for x in ex
                                if isinstance(x, dict)]
                       for i, ex in (snap.get("exemplars")
                                     or {}).items()}
        return h

    def quantile(self, q):
        """Value at quantile ``q`` in [0, 1], within one bucket's
        relative resolution; None on an empty histogram.

        Walks the cumulative counts to the covering bucket and returns
        its upper edge clamped into the exactly-tracked [min, max], so
        q=0 is the true min and q=1 the true max.
        """
        with self._lock:
            total = self.count
            if not total:
                return None
            if q <= 0.0:
                return self.min
            if q >= 1.0:
                return self.max
            rank = q * total
            cum = self.under
            if cum >= rank and cum:
                return self.min if self.min is not None else self.lo
            val = None
            for i in sorted(self.counts):
                cum += self.counts[i]
                if cum >= rank:
                    val = self.edges[i + 1]
                    break
            if val is None:      # rank beyond all buckets: overflow
                val = self.max if self.max is not None else self.hi
            if self.min is not None:
                val = max(val, self.min)
            if self.max is not None:
                val = min(val, self.max)
            return val


def quantile(hist_snapshot, q):
    """Quantile from a histogram *snapshot dict* (see
    :meth:`Histogram.to_snapshot`); None when empty/absent."""
    if not hist_snapshot:
        return None
    return Histogram.from_snapshot(hist_snapshot).quantile(q)


def percentiles(hist_snapshot, qs=(0.5, 0.9, 0.99)):
    """{q: value} for a snapshot dict (empty dict when no samples)."""
    if not hist_snapshot or not hist_snapshot.get("count"):
        return {}
    h = Histogram.from_snapshot(hist_snapshot)
    return {q: h.quantile(q) for q in qs}


def exemplar_for_quantile(hist_snapshot, q):
    """The exemplar whose bucket covers quantile ``q`` of a snapshot —
    the "resolve this p99 to a trace" hook (docs/OBSERVABILITY.md).

    Walks the cumulative counts to q's covering bucket and returns its
    newest exemplar as ``{"trace_id", "value", "bucket"}``; when that
    bucket recorded none (exemplars are sampled, counts are exact) the
    nearest exemplar-carrying bucket wins, preferring slower buckets —
    for a tail quantile the slower neighbor is the honest stand-in.
    None when the snapshot carries no exemplars at all.
    """
    if not hist_snapshot:
        return None
    h = Histogram.from_snapshot(hist_snapshot)
    if not h.exemplars or not h.count:
        return None
    rank = max(0.0, min(1.0, float(q))) * h.count
    cum = h.under
    covering = None
    for i in sorted(h.counts):
        cum += h.counts[i]
        if cum >= rank:
            covering = i
            break
    if covering is None:
        covering = h.n_buckets  # rank beyond all buckets: overflow
    have = sorted(h.exemplars)
    best = min(have, key=lambda i: (abs(i - covering), covering - i))
    ex = dict(h.exemplars[best][-1])
    ex["bucket"] = best
    return ex


class MetricsRegistry:
    """Label-keyed counters, gauges and histograms for one run.

    Series creation takes the registry lock once; increments take only
    the per-histogram lock (counters/gauges update under the registry
    lock — they are single dict stores, far from any hot path's
    budget).  ``snapshot()`` is safe against concurrent writers and
    returns plain JSON-ready dicts.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}
        self._hists = {}
        self._t0 = time.time()
        self._seq = 0

    # -- write side -----------------------------------------------------

    def inc(self, name, value=1, **labels):
        key = series_key(name, labels)
        with self._lock:
            self._counters[key] = self._counters.get(key, 0) + value

    def set_gauge(self, name, value, **labels):
        key = series_key(name, labels)
        with self._lock:
            self._gauges[key] = value

    def histogram(self, name, lo=DEFAULT_LO, hi=DEFAULT_HI,
                  per_octave=DEFAULT_PER_OCTAVE, **labels):
        key = series_key(name, labels)
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = Histogram(
                    lo=lo, hi=hi, per_octave=per_octave)
            return h

    def observe(self, name, value, exemplar=None, **labels):
        self.histogram(name, **labels).observe(value,
                                               exemplar=exemplar)

    # -- read side ------------------------------------------------------

    def snapshot(self):
        """One cumulative snapshot dict (a ``metrics.jsonl`` line)."""
        with self._lock:
            self._seq += 1
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = dict(self._hists)
            seq = self._seq
        return {"schema": SNAPSHOT_SCHEMA,
                "t": round(time.time(), 6),
                "uptime_s": round(time.time() - self._t0, 6),
                "seq": seq,
                "counters": counters,
                "gauges": gauges,
                "histograms": {k: h.to_snapshot()
                               for k, h in sorted(hists.items())}}


class MetricsExporter:
    """Periodic + final snapshot writer for one registry.

    Appends one snapshot line to ``<run_dir>/metrics.jsonl`` every
    ``interval_s`` (daemon thread; 0 disables it) and once at
    :meth:`stop`.  Write failures are dropped, never fatal — the
    ``obs`` "never fatal" contract.
    """

    def __init__(self, registry, run_dir, interval_s=None):
        self.registry = registry
        self.path = os.path.join(run_dir, "metrics.jsonl")
        self.interval_s = metrics_interval() if interval_s is None \
            else float(interval_s)
        self.dropped = 0
        # health-plane hook (obs/health.py): called before each
        # periodic snapshot so that tick's alert gauges land in the
        # metrics.jsonl line it writes; failures never kill the loop
        self.on_tick = None
        self._stop = threading.Event()
        self._thread = None
        if self.interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, name="pptpu-metrics-exporter",
                daemon=True)
            self._thread.start()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            cb = self.on_tick
            if cb is not None:
                try:
                    cb()
                except Exception:
                    pass
            self.write_snapshot()

    def write_snapshot(self):
        try:
            line = json.dumps(self.registry.snapshot(),
                              default=_core._json_default)
            with open(self.path, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")
                fh.flush()
        except (OSError, TypeError, ValueError):
            self.dropped += 1

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None
        self.write_snapshot()


# -- module-level helpers (the instrumented-code API) -------------------


def _registry():
    rec = _core._active
    if rec is None:
        return None
    return rec.metrics_registry()


def inc(name, value=1, **labels):
    """Bump a counter series; no-op when no obs run is active."""
    reg = _registry()
    if reg is not None:
        reg.inc(name, value, **labels)


def set_gauge(name, value, **labels):
    """Set a gauge series (last value wins); no-op when inactive."""
    reg = _registry()
    if reg is not None:
        reg.set_gauge(name, value, **labels)


def _ambient_exemplar():
    """Ambient trace id (obs/tracing.py) as the default exemplar: one
    thread-local read, so every observe made while serving a traced
    request links its bucket to that trace with zero caller churn."""
    ctx = getattr(_core._tls, "trace", None)
    return ctx[0] if ctx is not None else None


def observe(name, seconds, exemplar=None, **labels):
    """Record one latency observation; no-op when inactive.  The
    ambient trace context (if any) rides along as the bucket's
    exemplar unless the caller passes its own."""
    reg = _registry()
    if reg is not None:
        reg.observe(name, seconds,
                    exemplar=exemplar or _ambient_exemplar(), **labels)


@contextlib.contextmanager
def timed(name, **labels):
    """Time a with-block into a histogram series; no-op when
    inactive.  Records on every exit path (including raises) — a
    failed dispatch's latency is exactly the one an SLO cares about."""
    reg = _registry()
    if reg is None:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        reg.observe(name, time.perf_counter() - t0,
                    exemplar=_ambient_exemplar(), **labels)


def snapshot():
    """The active run's current snapshot, or None when inactive."""
    reg = _registry()
    return None if reg is None else reg.snapshot()


# -- rendering ----------------------------------------------------------


def _prom_name(key):
    name, labels = parse_series(key)
    return name, labels


def render_prometheus(snap):
    """Prometheus text exposition of one snapshot dict.

    Counters/gauges render directly; histograms render as cumulative
    ``_bucket{le=...}`` series (per-octave edges), ``_sum`` and
    ``_count`` — scrape-compatible with any Prometheus-style
    collector without this repo growing a dependency.
    """
    if not snap:
        return ""
    out = []
    typed = set()

    def type_line(name, kind):
        if name not in typed:
            typed.add(name)
            out.append("# TYPE %s %s" % (name, kind))

    for key in sorted(snap.get("counters") or {}):
        name, _ = _prom_name(key)
        type_line(name, "counter")
        out.append("%s %s" % (key, (snap["counters"][key])))
    for key in sorted(snap.get("gauges") or {}):
        name, _ = _prom_name(key)
        type_line(name, "gauge")
        out.append("%s %s" % (key, snap["gauges"][key]))
    for key in sorted(snap.get("histograms") or {}):
        h = snap["histograms"][key]
        name, labels = _prom_name(key)
        type_line(name, "histogram")
        edges = Histogram(lo=h.get("lo", DEFAULT_LO),
                          hi=h.get("hi", DEFAULT_HI),
                          per_octave=h.get("per_octave",
                                           DEFAULT_PER_OCTAVE)).edges
        cum = int(h.get("under", 0))
        counts = {int(i): int(c)
                  for i, c in (h.get("counts") or {}).items()}
        exemplars = {int(i): ex
                     for i, ex in (h.get("exemplars") or {}).items()
                     if ex}

        def exemplar_suffix(i):
            # OpenMetrics exemplar syntax on the bucket that recorded
            # it: `# {trace_id="..."} <observed value>` — a scraper
            # (or a human) jumps from the p99 bucket straight to the
            # trace (tools/obs_trace.py)
            ex = exemplars.get(i)
            if not ex:
                return ""
            last = ex[-1]
            return ' # {trace_id="%s"} %.9g' % (
                last.get("trace_id", ""), float(last.get("value", 0.0)))

        # only edges that close a non-empty bucket, to keep the
        # exposition proportional to the data, plus +Inf
        n_buckets = len(edges) - 1
        for i in sorted(counts):
            cum += counts[i]
            lab = dict(labels)
            lab["le"] = "%.9g" % edges[i + 1]
            out.append("%s %d%s" % (series_key(name + "_bucket", lab),
                                    cum, exemplar_suffix(i)))
        lab = dict(labels)
        lab["le"] = "+Inf"
        out.append("%s %d%s" % (series_key(name + "_bucket", lab),
                                int(h.get("count", 0)),
                                exemplar_suffix(n_buckets)))
        out.append("%s %s" % (series_key(name + "_sum", labels),
                              h.get("sum", 0.0)))
        out.append("%s %d" % (series_key(name + "_count", labels),
                              int(h.get("count", 0))))
    return "\n".join(out) + ("\n" if out else "")


# -- snapshot files -----------------------------------------------------


def load_snapshots(run_dir):
    """Every parseable snapshot of a run's ``metrics.jsonl``, oldest
    first.  Torn tail lines (crash mid-append) are skipped."""
    path = os.path.join(run_dir, "metrics.jsonl")
    out = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    snap = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(snap, dict):
                    out.append(snap)
    except OSError:
        pass
    return out


def last_snapshot(run_dir):
    """The newest parseable snapshot of a run, or None."""
    snaps = load_snapshots(run_dir)
    return snaps[-1] if snaps else None


def _run_dirs(base):
    """Every run directory under an obs base dir (or ``base`` itself
    when it already is one); [] when nothing qualifies."""
    if not base:
        return []
    for probe in ("metrics.jsonl", "events.jsonl", "manifest.json"):
        if os.path.isfile(os.path.join(base, probe)):
            return [base]
    try:
        names = os.listdir(base)
    except OSError:
        return []
    runs = []
    for name in names:
        d = os.path.join(base, name)
        if any(os.path.isfile(os.path.join(d, p))
               for p in ("metrics.jsonl", "events.jsonl",
                         "manifest.json")):
            runs.append(d)
    return runs


def latest_run_dir(base):
    """Newest run directory under an obs base dir (mtime order), or
    ``base`` itself when it already is a run dir; None when nothing
    qualifies.  The ``--watch`` views poll this instead of replaying
    ledgers."""
    runs = _run_dirs(base)
    return max(runs, key=os.path.getmtime) if runs else None


def overlay_supervisor(snap, base):
    """Fold the supervisor's ``pps_supervisor_*`` series into a watch
    snapshot.

    ``ppsurvey status --watch`` tails the *newest* run dir under the
    workdir's obs base — on a supervised survey that is almost always
    a worker's run (workers start after the supervisor, so their dirs
    are newer), which would make the supervisor's gauges invisible
    exactly when they matter.  This scans the run dirs newest-first
    for the supervisor's own series and copies them in.  Absent, not
    broken: an unsupervised run has no such series anywhere, and the
    snapshot is returned untouched (bit-identical frame)."""
    def _sup_series(s):
        out = {}
        for kind in ("gauges", "counters"):
            for key, v in (s.get(kind) or {}).items():
                if key.rsplit("/", 1)[-1].startswith(
                        "pps_supervisor_"):
                    out.setdefault(kind, {})[key] = v
        return out

    if snap and _sup_series(snap):
        return snap
    try:
        runs = sorted(_run_dirs(base), key=os.path.getmtime,
                      reverse=True)
    except OSError:
        runs = []
    for run_dir in runs:
        other = last_snapshot(run_dir)
        if not other:
            continue
        sup = _sup_series(other)
        if not sup:
            continue
        if snap is None:
            return other
        snap = dict(snap)
        for kind, series in sup.items():
            merged = dict(snap.get(kind) or {})
            merged.update(series)
            snap[kind] = merged
        return snap
    return snap


def merge_snapshots(snaps):
    """Merge per-process snapshots into one (``obs/merge.py`` path).

    ``snaps`` is ``{proc: snapshot}``.  Counters and histograms sum
    across shards **by identical series key** — histogram merges are
    integer bucket sums over identical edges, so the result is exact
    and independent of shard order; gauges keep a ``p<proc>/`` prefix
    (a queue depth summed across hosts would be a lie).
    """
    counters = {}
    gauges = {}
    hists = {}
    t = 0.0
    for proc in sorted(snaps):
        s = snaps[proc] or {}
        t = max(t, float(s.get("t", 0.0) or 0.0))
        for k, v in (s.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[k] = counters.get(k, 0) + v
        for k, v in (s.get("gauges") or {}).items():
            gauges["p%s/%s" % (proc, k)] = v
        for k, h in (s.get("histograms") or {}).items():
            cur = hists.get(k)
            if cur is None:
                hists[k] = Histogram.from_snapshot(h)
            else:
                cur.merge(Histogram.from_snapshot(h))
    return {"schema": SNAPSHOT_SCHEMA,
            "t": t,
            "seq": 1,
            "merged_from": sorted(snaps),
            "counters": counters,
            "gauges": gauges,
            "histograms": {k: h.to_snapshot()
                           for k, h in sorted(hists.items())}}


# -- SLO evaluation (pploadgen gate) ------------------------------------


def evaluate_slo(spec, hist_snapshot, n_ok, n_err, wall_s):
    """Evaluate an SLO spec against a latency-histogram snapshot plus
    outcome counts; returns ``{"ok", "breaches", "measured"}``.

    Spec fields (all optional — absent means not gated):

    * ``p50_s`` / ``p90_s`` / ``p99_s`` — latency ceilings [s]
    * ``max_error_rate``      — errors / (ok + errors) ceiling
    * ``min_throughput_rps``  — ok / wall floor [requests/s]
    * ``min_requests``        — sample-size floor (guards the gate
      against vacuously passing on an empty run)
    """
    n_ok = int(n_ok)
    n_err = int(n_err)
    total = n_ok + n_err
    wall_s = float(wall_s)
    measured = {
        "n_ok": n_ok, "n_err": n_err,
        "error_rate": round(n_err / total, 6) if total else None,
        "throughput_rps": round(n_ok / wall_s, 6)
        if wall_s > 0 else None,
        "wall_s": round(wall_s, 6),
    }
    for q in (0.5, 0.9, 0.99):
        v = quantile(hist_snapshot, q)
        measured["p%g_s" % (100 * q)] = None if v is None \
            else round(v, 6)
    if hist_snapshot:
        measured["max_s"] = hist_snapshot.get("max")
    breaches = []

    def breach(field, got, limit, cmp):
        breaches.append({"slo": field, "measured": got, "limit": limit,
                         "detail": "%s %s (limit %s)" % (field, got,
                                                         cmp + str(
                                                             limit))})

    for field, mkey in (("p50_s", "p50_s"), ("p90_s", "p90_s"),
                        ("p99_s", "p99_s")):
        limit = spec.get(field)
        if limit is None:
            continue
        got = measured.get(mkey)
        if got is None or got > float(limit):
            breach(field, got, limit, "<=")
    if spec.get("max_error_rate") is not None:
        got = measured["error_rate"]
        if got is None or got > float(spec["max_error_rate"]):
            breach("max_error_rate", got, spec["max_error_rate"], "<=")
    if spec.get("min_throughput_rps") is not None:
        got = measured["throughput_rps"]
        if got is None or got < float(spec["min_throughput_rps"]):
            breach("min_throughput_rps", got,
                   spec["min_throughput_rps"], ">=")
    if spec.get("min_requests") is not None \
            and total < int(spec["min_requests"]):
        breach("min_requests", total, spec["min_requests"], ">=")
    return {"ok": not breaches, "breaches": breaches,
            "measured": measured}


# -- watch rendering (pptop-style) --------------------------------------


def _fmt_lat(v):
    if v is None:
        return "-"
    if v < 1e-3:
        return "%.0fus" % (v * 1e6)
    if v < 1.0:
        return "%.1fms" % (v * 1e3)
    return "%.2fs" % v


def _fmt_bytes(n):
    try:
        n = float(n)
    except (TypeError, ValueError):
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024.0 or unit == "TiB":
            return ("%d%s" % (n, unit)) if unit == "B" \
                else "%.1f%s" % (n, unit)
        n /= 1024.0


def _memory_row(gauges):
    """The ``--watch`` memory line from the sampler's gauges
    (obs/memory.py), summed over any ``p<proc>/`` merge prefixes;
    None when the snapshot carries no memory gauges (pre-memory runs
    must keep their original frame)."""
    sums = {}
    for key, v in gauges.items():
        base = key.rsplit("/", 1)[-1]
        if base in ("pps_device_bytes_in_use", "pps_device_peak_bytes",
                    "pps_host_rss_bytes"):
            try:
                sums[base] = sums.get(base, 0.0) + float(v)
            except (TypeError, ValueError):
                continue
    if not sums:
        return None
    return "memory: device in-use %s  peak %s  host RSS %s" % (
        _fmt_bytes(sums.get("pps_device_bytes_in_use")),
        _fmt_bytes(sums.get("pps_device_peak_bytes")),
        _fmt_bytes(sums.get("pps_host_rss_bytes")))


def _quality_row(snap):
    """The ``--watch`` quality line (obs/quality.py): bad-fit rate
    from the exact ``pps_quality_*_total`` counters (summed across any
    ``p<proc>/`` merge prefixes — counters, never gauges: gauge merges
    keep per-process values, which cannot be combined into a rate) and
    the median reduced chi^2 from the merged fixed-geometry
    distribution series; None when the snapshot carries no quality
    series (pre-quality runs keep their original frame)."""
    from . import quality as _q     # lazy: quality imports metrics

    n = bad = 0
    for key, v in (snap.get("counters") or {}).items():
        base = key.rsplit("/", 1)[-1]
        try:
            if base == _q.CTR_SUBINTS:
                n += int(v)
            elif base == _q.CTR_BAD_SUBINTS:
                bad += int(v)
        except (TypeError, ValueError):
            continue
    if not n:
        return None
    chi2 = None
    for key, h in (snap.get("histograms") or {}).items():
        name, _labels = parse_series(key.rsplit("/", 1)[-1])
        if name != _q.HIST_RED_CHI2:
            continue
        hh = Histogram.from_snapshot(h)
        if chi2 is None:
            chi2 = hh
        else:
            chi2.merge(hh)
    med = chi2.quantile(0.5) if chi2 is not None else None
    return "quality: bad-fit %.2f%% (%d/%d)  med chi2=%s" % (
        100.0 * bad / n, bad, n,
        "%.3g" % med if med is not None else "-")


def _compile_cache_row(snap):
    """The ``--watch`` persistent-compile-cache line from the exact
    ``pps_compile_cache_*_total`` counters (summed across any
    ``p<proc>/`` merge prefixes); None when the snapshot carries no
    cache series (pre-warm runs keep their original frame)."""
    hits = misses = 0
    seen = False
    for key, v in (snap.get("counters") or {}).items():
        base = key.rsplit("/", 1)[-1]
        try:
            if base == "pps_compile_cache_hits_total":
                hits += int(v)
                seen = True
            elif base == "pps_compile_cache_misses_total":
                misses += int(v)
                seen = True
        except (TypeError, ValueError):
            continue
    if not seen:
        return None
    total = hits + misses
    rate = " (%.0f%% hit)" % (100.0 * hits / total) if total else ""
    return "compile-cache: %d hit(s) / %d miss(es)%s" % (hits, misses,
                                                         rate)


def _alerts_row(snap):
    """The ``--watch`` alerts line (obs/health.py): firing rules from
    the rule-labeled ``pps_alerts_firing`` gauges (a rule counts as
    firing when its flag is truthy on ANY ``p<proc>/`` merge prefix —
    gauges are never summed into rates) plus the fired total from the
    ``pps_alerts_total`` counters (summed across prefixes); None when
    the snapshot carries no alert series (pre-health runs keep their
    original frame)."""
    firing = set()
    seen = False
    for key, v in (snap.get("gauges") or {}).items():
        name, labels = parse_series(key.rsplit("/", 1)[-1])
        if name != "pps_alerts_firing":
            continue
        seen = True
        rule = labels.get("rule")
        try:
            if rule and float(v):
                firing.add(rule)
        except (TypeError, ValueError):
            continue
    fired = 0
    for key, v in (snap.get("counters") or {}).items():
        name, _labels = parse_series(key.rsplit("/", 1)[-1])
        if name != "pps_alerts_total":
            continue
        seen = True
        try:
            fired += int(v)
        except (TypeError, ValueError):
            continue
    if not seen:
        return None
    if firing:
        return "alerts: %d firing (%s)  %d fired total" % (
            len(firing), ", ".join(sorted(firing)), fired)
    return "alerts: none firing  %d fired total" % fired


def _usage_row(snap, prev=None, dt=None):
    """The ``--watch`` per-tenant usage line (obs/usage.py): metered
    device-seconds and request counts from the exact, tenant-labeled
    ``pps_usage_*_total`` counters (summed across any ``p<proc>/``
    merge prefixes — counters, never gauges), with a per-second
    request rate when ``prev``/``dt`` are available; None when the
    snapshot carries no usage series (pre-usage runs keep their
    original frame)."""
    def _fold(s):
        by_tenant = {}
        for key, v in (s.get("counters") or {}).items():
            name, labels = parse_series(key.rsplit("/", 1)[-1])
            if name not in ("pps_usage_records_total",
                            "pps_usage_device_seconds_total"):
                continue
            tenant = labels.get("tenant", "-")
            cur = by_tenant.setdefault(tenant, [0, 0.0])
            try:
                if name == "pps_usage_records_total":
                    cur[0] += int(v)
                else:
                    cur[1] += float(v)
            except (TypeError, ValueError):
                continue
        return by_tenant

    by_tenant = _fold(snap)
    if not by_tenant:
        return None
    prev_t = _fold(prev) if prev else {}
    parts = []
    for tenant in sorted(by_tenant):
        recs, dev = by_tenant[tenant]
        rate = ""
        if dt:
            rate = " (+%.2f/s)" % ((recs - prev_t.get(tenant,
                                                      [0, 0.0])[0]) / dt)
        parts.append("%s=%d rec%s %.2f dev-s" % (tenant, recs, rate,
                                                 dev))
    return "usage: " + "  ".join(parts)


def _supervisor_row(snap):
    """The ``--watch`` autoscaling-supervisor line
    (runner/supervisor.py): desired/live/parked worker counts from the
    state-labeled ``pps_supervisor_workers`` gauges (per-state values,
    never summed across ``p<proc>/`` merge prefixes — only the one
    supervisor process publishes them), respawn/scale totals from the
    ``pps_supervisor_*_total`` counters (summed across prefixes), and
    the last scale action from the ``pps_supervisor_last_scale``
    timestamp gauges; None when the snapshot carries no supervisor
    series (unsupervised runs keep their original frame)."""
    workers = {}
    last = None  # (t, action)
    for key, v in (snap.get("gauges") or {}).items():
        name, labels = parse_series(key.rsplit("/", 1)[-1])
        try:
            if name == "pps_supervisor_workers":
                workers[labels.get("state", "?")] = int(float(v))
            elif name == "pps_supervisor_last_scale":
                t = float(v)
                if last is None or t > last[0]:
                    last = (t, labels.get("action", "?"))
        except (TypeError, ValueError):
            continue
    if not workers:
        return None
    respawns = scales = 0
    for key, v in (snap.get("counters") or {}).items():
        name, _labels = parse_series(key.rsplit("/", 1)[-1])
        try:
            if name == "pps_supervisor_respawns_total":
                respawns += int(v)
            elif name == "pps_supervisor_scale_events_total":
                scales += int(v)
        except (TypeError, ValueError):
            continue
    scale_txt = "-"
    if last is not None:
        ago = ""
        try:
            dt = float(snap.get("t", 0.0)) - last[0]
            if dt >= 0:
                ago = " (%.0fs ago)" % dt
        except (TypeError, ValueError):
            pass
        scale_txt = "%s%s" % (last[1], ago)
    return ("supervisor: desired %d  live %d  parked %d  "
            "respawns %d  scale-events %d  last scale %s" % (
                workers.get("desired", 0), workers.get("live", 0),
                workers.get("parked", 0), respawns, scales,
                scale_txt))


def render_watch(snap, prev=None, title=""):
    """A terminal dashboard frame from one snapshot (pptop-style).

    ``prev`` (the previous tick's snapshot) turns cumulative counters
    and histogram counts into per-second rates; per-phase latency
    p50/p90/p99/max come from the cumulative histograms.  Pure
    string-building: the ``--watch`` loops own the screen control.
    """
    if not snap:
        return "(no metrics snapshot yet)"
    lines = []
    head = "%s  t=%s  seq=%s  uptime=%.1fs" % (
        title or "metrics", time.strftime(
            "%H:%M:%S", time.localtime(snap.get("t", 0.0))),
        snap.get("seq"), float(snap.get("uptime_s", 0.0) or 0.0))
    lines.append(head.strip())
    dt = None
    if prev and snap.get("t") and prev.get("t"):
        dt = max(1e-9, float(snap["t"]) - float(prev["t"]))

    hists = snap.get("histograms") or {}
    by_phase = {}
    for key, h in hists.items():
        name, labels = parse_series(key)
        if name != PHASE_HISTOGRAM:
            continue
        phase = labels.get("phase", "?")
        cur = by_phase.get(phase)
        if cur is None:
            by_phase[phase] = Histogram.from_snapshot(h)
        else:
            cur.merge(Histogram.from_snapshot(h))
    if by_phase:
        lines.append("")
        lines.append("%-12s %8s %8s %9s %9s %9s %9s" %
                     ("phase", "n", "n/s", "p50", "p90", "p99", "max"))
        prev_counts = {}
        if prev:
            for key, h in (prev.get("histograms") or {}).items():
                name, labels = parse_series(key)
                if name == PHASE_HISTOGRAM:
                    ph = labels.get("phase", "?")
                    prev_counts[ph] = prev_counts.get(ph, 0) \
                        + int(h.get("count", 0))
        for phase in sorted(by_phase):
            h = by_phase[phase]
            rate = "-"
            if dt:
                rate = "%.2f" % ((h.count - prev_counts.get(phase, 0))
                                 / dt)
            lines.append("%-12s %8d %8s %9s %9s %9s %9s" % (
                phase, h.count, rate,
                _fmt_lat(h.quantile(0.5)), _fmt_lat(h.quantile(0.9)),
                _fmt_lat(h.quantile(0.99)), _fmt_lat(h.max)))

    # per-workload breakdown (the survey engine labels every phase
    # sample with its workload): only shown when the snapshot carries
    # more than the default single workload, so plain TOA surveys and
    # the service keep their original frame
    by_wl = {}
    for key, h in hists.items():
        name, labels = parse_series(key)
        if name != PHASE_HISTOGRAM or "workload" not in labels:
            continue
        k2 = (labels["workload"], labels.get("phase", "?"))
        cur = by_wl.get(k2)
        if cur is None:
            by_wl[k2] = Histogram.from_snapshot(h)
        else:
            cur.merge(Histogram.from_snapshot(h))
    if len({wl for wl, _ in by_wl}) > 1:
        lines.append("")
        lines.append("%-12s %-10s %8s %9s %9s %9s" %
                     ("workload", "phase", "n", "p50", "p99", "max"))
        for wl, phase in sorted(by_wl):
            h = by_wl[(wl, phase)]
            lines.append("%-12s %-10s %8d %9s %9s %9s" % (
                wl, phase, h.count,
                _fmt_lat(h.quantile(0.5)),
                _fmt_lat(h.quantile(0.99)), _fmt_lat(h.max)))

    gauges = snap.get("gauges") or {}
    mem = _memory_row(gauges)
    if mem:
        lines.append("")
        lines.append(mem)
    qual = _quality_row(snap)
    if qual:
        if not mem:
            lines.append("")
        lines.append(qual)
    cache = _compile_cache_row(snap)
    if cache:
        if not mem and not qual:
            lines.append("")
        lines.append(cache)
    alerts = _alerts_row(snap)
    if alerts:
        if not mem and not qual and not cache:
            lines.append("")
        lines.append(alerts)
    used = _usage_row(snap, prev, dt)
    if used:
        if not mem and not qual and not cache and not alerts:
            lines.append("")
        lines.append(used)
    sup = _supervisor_row(snap)
    if sup:
        if not mem and not qual and not cache and not alerts \
                and not used:
            lines.append("")
        lines.append(sup)
    if gauges:
        lines.append("")
        lines.append("gauges: " + "  ".join(
            "%s=%s" % (k, v) for k, v in sorted(gauges.items())))
    counters = snap.get("counters") or {}
    if counters:
        prev_c = (prev or {}).get("counters") or {}
        lines.append("")
        lines.append("counters:")
        for k in sorted(counters):
            rate = ""
            if dt:
                rate = "  (+%.2f/s)" % ((counters[k]
                                         - prev_c.get(k, 0)) / dt)
            lines.append("  %s: %s%s" % (k, counters[k], rate))
    return "\n".join(lines)
