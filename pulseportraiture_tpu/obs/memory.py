"""Live memory observability: device/host watermarks, per-span peaks.

The obs plane measured only *time* until PR 12; this module makes
memory a first-class observable with the same activation contract as
everything else in ``obs``:

* **Live sampler** — one daemon thread per run (gated exactly like the
  ``metrics.jsonl`` exporter: created lazily on the first span, cadence
  ``PPTPU_MEMORY_INTERVAL`` seconds, default the metrics interval, 0
  disables the thread) polls ``device.memory_stats()`` plus host RSS
  and publishes the ``pps_device_bytes_in_use`` /
  ``pps_device_peak_bytes`` / ``pps_host_rss_bytes`` gauges into the
  run's streaming-metrics registry — the ``--watch`` views and the
  Prometheus rendering get a memory row for free.
* **Per-span peak watermarks** — :meth:`MemoryState.mark` /
  :meth:`MemoryState.peak` bracket every ``obs.span`` /
  ``obs.phases`` extent (wired in ``obs/core.py``), so each span event
  carries a ``peak_bytes`` field: the maximum *footprint* observed
  between entry and exit (every sample — boundary or periodic — folds
  into all open marks, so a peak reached mid-phase by the sampler
  thread is attributed to the phase that was open).
* **Footprint semantics** — ``footprint_bytes`` is device
  ``bytes_in_use`` summed over local devices when the backend exposes
  allocator stats (TPU/GPU), else host RSS (CPU: XLA buffers live in
  the process heap, so RSS is the honest watermark).  Which one a
  sample used is recorded (``source``: ``device`` / ``host``).
* **OOM forensics** — :func:`device_memory_dump` wraps
  ``jax.profiler.device_memory_profile()`` into a run-dir file; the
  runner/service OOM handlers attach the path plus the last sampled
  watermarks to their ``oom`` events (docs/OBSERVABILITY.md).

Never fatal, host-side only (jaxlint J002 rejects ``memory.*`` calls
inside jit), and disabled = free: with no run active every module-level
helper is one attribute read + ``None`` check.
"""

import itertools
import os
import sys
import threading

from . import core as _core
from . import metrics as _metrics

__all__ = ["GAUGE_IN_USE", "GAUGE_PEAK", "GAUGE_HOST_RSS",
           "memory_interval", "host_rss_bytes", "sample",
           "watermarks", "last", "is_oom", "record_oom",
           "device_memory_dump", "MemoryState"]

# the streaming-metrics gauge names the sampler publishes (and the
# --watch memory row / obs_report read back)
GAUGE_IN_USE = "pps_device_bytes_in_use"
GAUGE_PEAK = "pps_device_peak_bytes"
GAUGE_HOST_RSS = "pps_host_rss_bytes"


def memory_interval():
    """$PPTPU_MEMORY_INTERVAL: sampler cadence in seconds (default:
    the metrics snapshot interval; 0 disables the thread — boundary
    samples at span entry/exit still run)."""
    v = os.environ.get("PPTPU_MEMORY_INTERVAL", "").strip()
    try:
        return max(0.0, float(v)) if v else _metrics.metrics_interval()
    except ValueError:
        return _metrics.metrics_interval()


_page_size = None


def host_rss_bytes():
    """Resident set size of this process in bytes (0 when /proc is
    unavailable — never fatal)."""
    global _page_size
    try:
        if _page_size is None:
            _page_size = os.sysconf("SC_PAGE_SIZE")
        with open("/proc/self/statm", "rb") as fh:
            return int(fh.read().split()[1]) * _page_size
    except (OSError, ValueError, IndexError):
        return 0


# device-allocator probe cache: None = unprobed, () = backend exposes
# no allocator stats (CPU), tuple = the devices to poll.  Probing once
# keeps the steady-state sample at one /proc read on CPU backends.
_dev_lock = threading.Lock()
_dev_cache = None


def _devices_with_stats():
    global _dev_cache
    devs = _dev_cache
    if devs is not None:
        return devs
    if "jax" not in sys.modules:
        # the sampler must never be the thing that imports jax and
        # initializes a backend; probe again once the pipeline has
        return ()
    with _dev_lock:
        if _dev_cache is None:
            try:
                import jax

                _dev_cache = tuple(
                    d for d in jax.local_devices()
                    if (d.memory_stats() or {}).get("bytes_in_use")
                    is not None)
            except Exception:
                _dev_cache = ()
        return _dev_cache


def _reset_device_cache():
    """Test hook: force the allocator-stats probe to rerun."""
    global _dev_cache
    with _dev_lock:
        _dev_cache = None


def sample():
    """One point-in-time watermark sample.

    Returns ``{"host_rss_bytes", "footprint_bytes", "source"}`` plus,
    when the backend exposes allocator stats,
    ``device_bytes_in_use`` / ``device_peak_bytes`` (summed over local
    devices).  ``footprint_bytes`` is the number per-span peaks track:
    device in-use when available, else host RSS.
    """
    out = {"host_rss_bytes": host_rss_bytes()}
    devs = _devices_with_stats()
    if devs:
        in_use = peak = 0
        for d in devs:
            try:
                st = d.memory_stats() or {}
            except Exception:
                st = {}
            bi = int(st.get("bytes_in_use", 0) or 0)
            in_use += bi
            peak += int(st.get("peak_bytes_in_use", bi) or bi)
        out["device_bytes_in_use"] = in_use
        out["device_peak_bytes"] = max(peak, in_use)
        out["footprint_bytes"] = in_use
        out["source"] = "device"
    else:
        out["footprint_bytes"] = out["host_rss_bytes"]
        out["source"] = "host"
    return out


class _Mark:
    """One open watermark bracket (a span's extent)."""

    __slots__ = ("peak",)

    def __init__(self, peak):
        self.peak = peak


class MemoryState:
    """Per-recorder sampler thread + watermark bookkeeping.

    Created lazily by :meth:`~.core.Recorder.memory_state` on the first
    span boundary (a run that never opens a span costs nothing), and
    stopped by ``Recorder.close()`` *before* the metrics exporter so
    the final gauges land in the final ``metrics.jsonl`` snapshot.
    """

    def __init__(self, recorder, interval_s=None):
        self._recorder = recorder
        self.interval_s = memory_interval() if interval_s is None \
            else float(interval_s)
        self._lock = threading.Lock()
        self._marks = {}
        self._mark_seq = itertools.count(1)
        self._last = None
        self.run_peak_bytes = 0
        self.n_samples = 0
        self._stop = threading.Event()
        self._thread = None
        self.sample_now(publish=False)
        # the footprint when sampling began: on CPU backends the
        # estimator compares against peak GROWTH over this baseline
        # (the interpreter + jax runtime dominate absolute RSS)
        self.baseline_footprint_bytes = self._last["footprint_bytes"]
        if self.interval_s > 0:
            self._thread = threading.Thread(
                target=self._loop, name="pptpu-memory-sampler",
                daemon=True)
            self._thread.start()

    # -- sampling -------------------------------------------------------

    def sample_now(self, publish=True):
        """Take one sample, fold it into every open mark, optionally
        publish the gauges; returns the sample dict."""
        s = sample()
        fp = s["footprint_bytes"]
        with self._lock:
            self._last = s
            self.n_samples += 1
            if fp > self.run_peak_bytes:
                self.run_peak_bytes = fp
            for m in self._marks.values():
                if fp > m.peak:
                    m.peak = fp
        if publish:
            self._publish(s)
        return s

    def last(self):
        """The most recent sample (never None after construction)."""
        with self._lock:
            return dict(self._last) if self._last else None

    def _publish(self, s):
        # gauges go through the run's streaming-metrics registry (the
        # --watch / Prometheus surface); creating it here is exactly
        # the metrics-exporter activation the sampler is gated like
        try:
            reg = self._recorder.metrics_registry()
        except Exception:
            return
        reg.set_gauge(GAUGE_HOST_RSS, s["host_rss_bytes"])
        if "device_bytes_in_use" in s:
            reg.set_gauge(GAUGE_IN_USE, s["device_bytes_in_use"])
            reg.set_gauge(GAUGE_PEAK, s["device_peak_bytes"])
        else:
            # CPU backend: the footprint gauges mirror RSS so the
            # watch row / regression gates read one schema everywhere
            reg.set_gauge(GAUGE_IN_USE, s["footprint_bytes"])
            with self._lock:
                peak = self.run_peak_bytes
            reg.set_gauge(GAUGE_PEAK, peak)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            self.sample_now()

    # -- span watermarks ------------------------------------------------

    def mark(self):
        """Open a watermark bracket (span entry); returns a token."""
        self.sample_now(publish=False)
        with self._lock:
            tok = next(self._mark_seq)
            self._marks[tok] = _Mark(self._last["footprint_bytes"])
        return tok

    def peak(self, tok):
        """Close a bracket (span exit); returns its peak footprint in
        bytes, or None for an unknown token."""
        self.sample_now(publish=False)
        with self._lock:
            m = self._marks.pop(tok, None)
        return None if m is None else m.peak

    # -- lifecycle ------------------------------------------------------

    def stop(self):
        """Stop the thread, take a final sample, publish final gauges
        (only when a metrics registry already exists — stopping must
        not create one), and record the run-level peak gauges."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None
        self.sample_now(publish=False)
        s = self.last() or {}
        rec = self._recorder
        if rec._metrics is not None:
            self._publish(s)
        # manifest gauges: the run-level summary obs_report / bench
        # read back without parsing metrics.jsonl
        rec.set_gauge("peak_footprint_bytes", self.run_peak_bytes)
        rec.set_gauge("baseline_footprint_bytes",
                      self.baseline_footprint_bytes)
        rec.set_gauge("host_rss_bytes", s.get("host_rss_bytes", 0))
        if "device_peak_bytes" in s:
            rec.set_gauge("device_peak_bytes", s["device_peak_bytes"])


# -- module-level helpers (the instrumented-code API) -------------------


def _state():
    rec = _core._active
    if rec is None:
        return None
    return rec.memory_state()


def watermarks():
    """A fresh watermark sample of the active run (fed into the run's
    open marks), or None when no run is active."""
    st = _state()
    return None if st is None else st.sample_now(publish=False)


def last():
    """The active run's most recent sample without taking a new one
    (the OOM-forensics read), or None when no run is active."""
    st = _state()
    return None if st is None else st.last()


def is_oom(err):
    """True when ``err`` (an exception or its message string) looks
    like a device out-of-memory failure.  XLA surfaces allocator
    exhaustion as ``XlaRuntimeError: RESOURCE_EXHAUSTED: ...`` (often
    with an "Out of memory" detail line); both markers are matched so
    the string form recorded in ``failed_datafiles`` classifies the
    same as the live exception."""
    text = str(err)
    return ("RESOURCE_EXHAUSTED" in text
            or "out of memory" in text.lower())


def record_oom(where, err, **fields):
    """OOM forensics: emit an ``oom`` event into the active run.

    The event carries the error text, a final watermark sample (plus
    the run peak so far), the per-scope HBM attribution from the most
    recent profiler capture when one ran (``parse_xplane_memory`` via
    ``record_devtime``), and the path of a fresh
    ``jax.profiler.device_memory_profile()`` dump.  Returns the event
    fields, or None when no run is active.  Never fatal — forensics
    must not mask the failure being recorded.
    """
    rec = _core._active
    if rec is None:
        return None
    try:
        ev = dict(fields)
        ev["where"] = where
        ev["error"] = str(err)[:500]
        st = rec.memory_state()
        if st is not None:
            ev["watermarks"] = st.sample_now(publish=False)
            ev["run_peak_bytes"] = st.run_peak_bytes
        scopes = getattr(rec, "memory_scopes", None)
        if scopes:
            ev["scopes"] = scopes
        dump = device_memory_dump(rec.dir)
        if dump:
            ev["memory_profile"] = dump
        rec.emit("oom", **ev)
        rec.bump("oom_events")
        return ev
    except Exception:
        return None


def device_memory_dump(run_dir, tag="oom"):
    """Write ``jax.profiler.device_memory_profile()`` (a gzipped pprof
    protobuf) into ``run_dir``; returns the path, or None when the
    profiler/dir is unavailable.  Never fatal."""
    try:
        import jax.profiler

        blob = jax.profiler.device_memory_profile()
    except Exception:
        return None
    path = os.path.join(run_dir, "%s_memory.prof" % tag)
    try:
        with open(path, "wb") as fh:
            fh.write(blob)
    except (OSError, TypeError):
        return None
    return path
