"""Fit-quality observability: scientific correctness as a diffable
observable.

The obs plane measured wall time, device seconds, request latency,
causality and memory before PR 13 — but the *product* of a wideband
timing run (Pennucci 2019's per-subint measurement statistics: reduced
chi^2, TOA error, S/N, convergence) was invisible to ``obs_diff``, so
a silently-wrong fit passed every gate.  This module turns the
per-subint quantities ``GetTOAs`` already computes into deterministic
per-run **quality fingerprints**:

* **Distributions** — reduced chi^2, TOA error [us] and S/N go into
  log-bucketed :class:`~.metrics.Histogram` series with FIXED
  geometries (the ``CHI2_*`` / ``ERR_*`` / ``SNR_*`` schema constants
  below; a geometry change is a schema change): shard merges stay
  exact integer bucket sums, and two runs' distributions are
  comparable bucket by bucket — the ``obs_diff --quality-rel``
  total-variation gate.
* **Exact counters** — subints fitted, bad fits (``red_chi2`` above
  ``$PPTPU_QUALITY_CHI2_BAD``, non-converged return codes, non-finite
  results), error-inflated subints (``red_chi2`` above
  ``$PPTPU_QUALITY_CHI2_INFLATED`` — the regime where quoted TOA
  errors understate the scatter), zapped channels — Recorder manifest
  counters plus ``pps_quality_*_total`` metrics counters, so merged
  runs sum exactly and the ``--watch`` views get a quality row.
* **Per-archive events** — one ``quality`` event per archive carrying
  the exact medians, the offending subint indices and a
  residual-whiteness statistic (lag-1 autocorrelation of the
  standardized phase residuals; Taylor 1992's FFTFIT goodness-of-fit
  intuition — a faithful template leaves white residuals), stamped
  with bucket/workload attribution from the ambient :func:`context`.

Never fatal, host-side only (jaxlint J002 rejects ``quality.*`` calls
inside jit — call it after the ``device_get`` boundary), and
disabled = free: with no run active every module-level helper is one
attribute read + ``None`` check.
"""

import contextlib
import math
import os
import sys
import threading

from . import core as _core
from . import metrics as _metrics

__all__ = ["HIST_RED_CHI2", "HIST_TOA_ERR", "HIST_SNR",
           "CTR_SUBINTS", "CTR_BAD_SUBINTS",
           "chi2_bad_threshold", "error_inflation_threshold",
           "whiteness_r1", "summarize", "record_archive", "context",
           "fingerprint", "group_fingerprints", "gt_fingerprint",
           "QualityState"]

# -- schema constants ----------------------------------------------------
# Histogram series names + FIXED geometries.  Histogram.merge is exact
# only over identical (lo, hi, per_octave); every process must build
# these series with exactly these constants, so they live here, not at
# call sites.  per_octave=8 gives ~9% relative bucket resolution.
HIST_RED_CHI2 = "pps_fit_red_chi2"
CHI2_LO, CHI2_HI, CHI2_PER_OCTAVE = 1.0 / 64, 1024.0, 8
HIST_TOA_ERR = "pps_toa_err_us"
ERR_LO, ERR_HI, ERR_PER_OCTAVE = 1e-3, 16384.0, 8
HIST_SNR = "pps_fit_snr"
SNR_LO, SNR_HI, SNR_PER_OCTAVE = 0.25, 16384.0, 8

# metrics counters (summable across shard prefixes — the --watch row)
CTR_SUBINTS = "pps_quality_subints_total"
CTR_BAD_SUBINTS = "pps_quality_bad_subints_total"

# cap on offending-subint indices carried per quality event
MAX_BAD_ISUBS = 16


def chi2_bad_threshold():
    """$PPTPU_QUALITY_CHI2_BAD: reduced-chi^2 above which a subint
    counts as a bad fit (default 3.0)."""
    v = os.environ.get("PPTPU_QUALITY_CHI2_BAD", "").strip()
    try:
        return float(v) if v else 3.0
    except ValueError:
        return 3.0


def error_inflation_threshold():
    """$PPTPU_QUALITY_CHI2_INFLATED: reduced-chi^2 above which the
    quoted TOA error understates the residual scatter (default 1.5)."""
    v = os.environ.get("PPTPU_QUALITY_CHI2_INFLATED", "").strip()
    try:
        return float(v) if v else 1.5
    except ValueError:
        return 1.5


def _converged_rcs():
    # the solver's converged return codes (obs/core.py fit_telemetry
    # owns the authoritative tuple; rc 3 = iteration budget exhausted,
    # rc 4 = damping stuck)
    return getattr(_core, "_CONVERGED_RCS", (0, 1, 2))


def _has_tracer(*values):
    """True when any input is a jax tracer — the J002 runtime
    contract: quality probes inside jit degrade to no-ops rather than
    forcing a device sync (without importing jax themselves)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return False
    try:
        return any(isinstance(v, jax.core.Tracer) for v in values
                   if v is not None)
    except Exception:
        return False


def whiteness_r1(phis, phi_errs=None):
    """Lag-1 autocorrelation of the standardized phase residuals of one
    archive (subint/time order): r1 = sum z_t z_{t+1} / sum z_t^2 with
    z = (phi - weighted mean) / phi_err.  A faithful template leaves
    white residuals (|r1| small); a drifting or mis-rotated one leaves
    correlated structure.  None for < 3 finite points or zero variance
    — whiteness of two subints is not a statement.
    """
    try:
        import numpy as np

        phis = np.asarray(phis, dtype=float).ravel()
        if phi_errs is None:
            errs = np.ones_like(phis)
        else:
            errs = np.asarray(phi_errs, dtype=float).ravel()
        okm = np.isfinite(phis) & np.isfinite(errs) & (errs > 0.0)
        phis, errs = phis[okm], errs[okm]
        if len(phis) < 3:
            return None
        w = errs ** -2.0
        mean = float(np.sum(w * phis) / np.sum(w))
        z = (phis - mean) / errs
        denom = float(np.sum(z * z))
        if denom <= 0.0:
            return None
        return float(np.sum(z[:-1] * z[1:]) / denom)
    except Exception:
        return None


def _median(values):
    try:
        import numpy as np

        v = np.asarray(values, dtype=float).ravel()
        v = v[np.isfinite(v)]
        return float(np.median(v)) if len(v) else None
    except Exception:
        return None


def summarize(red_chi2s, toa_errs_us, snrs=None, rcs=None, phis=None,
              phi_errs=None, n_zapped=0, isubs=None):
    """One archive's quality fingerprint from host-side per-subint
    arrays (pure computation, no recorder): exact medians, bad-fit
    breakdown (chi^2 / return code / non-finite), error-inflated
    count, residual whiteness.  Callers pass the *fitted* subints only
    (``isubs`` optionally names their archive indices for
    attribution).
    """
    import numpy as np

    chi2 = np.asarray(red_chi2s, dtype=float).ravel()
    errs = np.asarray(toa_errs_us, dtype=float).ravel()
    n = len(chi2)
    thr_bad = chi2_bad_threshold()
    thr_infl = error_inflation_threshold()
    finite = np.isfinite(chi2) & np.isfinite(errs)
    bad_chi2 = finite & (chi2 > thr_bad)
    if rcs is None:
        bad_rc = np.zeros(n, dtype=bool)
    else:
        rc = np.asarray(rcs).ravel().astype(int)
        bad_rc = ~np.isin(rc, np.asarray(_converged_rcs(), dtype=int))
    bad = bad_chi2 | bad_rc | ~finite
    inflated = finite & (chi2 > thr_infl)
    fp = {
        "n_subints": int(n),
        "n_bad": int(bad.sum()),
        "n_bad_chi2": int(bad_chi2.sum()),
        "n_bad_rc": int(bad_rc.sum()),
        "n_nonfinite": int((~finite).sum()),
        "n_error_inflated": int(inflated.sum()),
        "n_zapped": int(n_zapped),
        "bad_fit_rate": round(float(bad.sum()) / n, 6) if n else None,
        "median_red_chi2": _median(chi2),
        "max_red_chi2": float(np.max(chi2[finite]))
        if finite.any() else None,
        "median_toa_err_us": _median(errs),
        "chi2_bad_threshold": thr_bad,
    }
    if snrs is not None:
        fp["median_snr"] = _median(snrs)
    if phis is not None:
        fp["whiteness_r1"] = whiteness_r1(phis, phi_errs)
    if bad.any():
        where = np.flatnonzero(bad)
        if isubs is not None:
            idx = np.asarray(isubs).ravel()
            where = idx[where[where < len(idx)]]
        fp["bad_isubs"] = [int(i) for i in where[:MAX_BAD_ISUBS]]
    for k in ("median_red_chi2", "max_red_chi2", "median_toa_err_us",
              "median_snr", "whiteness_r1"):
        if fp.get(k) is not None:
            fp[k] = round(fp[k], 6)
    return fp


def gt_fingerprint(gt):
    """Fingerprint of the LAST archive fitted by a GetTOAs-style
    result object (the service daemon's per-request stamp: each request
    fits one archive).  Handles both the wideband per-subint arrays and
    the narrowband per-channel grids; None when nothing was fitted.
    Never fatal."""
    try:
        import numpy as np

        if not getattr(gt, "ok_isubs", None):
            return None
        ok = np.asarray(gt.ok_isubs[-1])
        chi2 = np.asarray(gt.red_chi2s[-1]) if getattr(
            gt, "red_chi2s", None) else None
        phi_errs = np.asarray(gt.phi_errs[-1])
        Ps = np.asarray(gt.Ps[-1])
        if chi2 is not None and chi2.ndim == 1:        # wideband
            rcs = np.asarray(gt.rcs[-1])[ok] if getattr(
                gt, "rcs", None) else None
            return summarize(
                chi2[ok], phi_errs[ok] * Ps[ok] * 1e6,
                snrs=np.asarray(gt.snrs[-1])[ok] if getattr(
                    gt, "snrs", None) else None,
                rcs=rcs, phis=np.asarray(gt.phis[-1])[ok],
                phi_errs=phi_errs[ok],
                n_zapped=int(gt.n_nonfinite_zapped[-1]) if getattr(
                    gt, "n_nonfinite_zapped", None) else 0,
                isubs=ok)
        if getattr(gt, "channel_red_chi2s", None):     # narrowband
            chi2 = np.asarray(gt.channel_red_chi2s[-1])
            snrs = np.asarray(gt.channel_snrs[-1])
            live = np.zeros(chi2.shape, dtype=bool)
            live[ok] = snrs[ok] > 0.0
            errs = phi_errs * Ps[:, None] * 1e6
            return summarize(chi2[live], errs[live], snrs=snrs[live],
                             phis=np.asarray(gt.phis[-1])[live],
                             phi_errs=phi_errs[live])
        return None
    except Exception:
        return None


# -- ambient attribution context (runner: bucket/workload) --------------

_tls = threading.local()


@contextlib.contextmanager
def context(bucket=None, workload=None, tenant=None):
    """Stamp quality records emitted in this thread's dynamic extent
    with runner attribution (shape bucket, workload pass, tenant) —
    the survey engine wraps each archive's fit so per-bucket and
    per-workload fingerprints come out of one shared emission point in
    the pipelines."""
    prev = getattr(_tls, "labels", None)
    _tls.labels = {k: v for k, v in (("bucket", bucket),
                                     ("workload", workload),
                                     ("tenant", tenant)) if v is not None}
    try:
        yield
    finally:
        _tls.labels = prev


def _labels():
    return getattr(_tls, "labels", None) or {}


# -- per-run aggregation -------------------------------------------------


class _Group:
    """Per-(bucket, workload) aggregate: exact counts + local fixed-
    geometry histograms for group medians (these never cross process
    boundaries — cross-shard merging happens on the registry series)."""

    __slots__ = ("n_subints", "n_bad", "n_zapped", "chi2", "err")

    def __init__(self):
        self.n_subints = 0
        self.n_bad = 0
        self.n_zapped = 0
        self.chi2 = _metrics.Histogram(CHI2_LO, CHI2_HI,
                                       CHI2_PER_OCTAVE)
        self.err = _metrics.Histogram(ERR_LO, ERR_HI, ERR_PER_OCTAVE)

    def fingerprint(self):
        n = self.n_subints
        return {"n_subints": n, "n_bad": self.n_bad,
                "n_zapped": self.n_zapped,
                "bad_fit_rate": round(self.n_bad / n, 6) if n else None,
                "median_red_chi2": self.chi2.quantile(0.5),
                "median_toa_err_us": self.err.quantile(0.5)}


class QualityState:
    """Per-recorder quality aggregation.

    Created lazily by :meth:`~.core.Recorder.quality_state` on the
    first quality record (a run that fits nothing costs nothing) and
    stopped by ``Recorder.close()``, which writes the run-level
    fingerprint gauges into the manifest.  The histogram series live
    in the run's streaming-metrics registry (creating it here is the
    same activation the memory sampler's gauges ride), so rotation,
    torn-tail discipline and exact shard merge are inherited, not
    reimplemented.
    """

    def __init__(self, recorder):
        self._recorder = recorder
        self._lock = threading.Lock()
        self.n_archives = 0
        self.n_subints = 0
        self.n_bad = 0
        self.n_zapped = 0
        self.n_error_inflated = 0
        self._groups = {}
        reg = recorder.metrics_registry()
        self._chi2 = reg.histogram(HIST_RED_CHI2, CHI2_LO, CHI2_HI,
                                   CHI2_PER_OCTAVE)
        self._err = reg.histogram(HIST_TOA_ERR, ERR_LO, ERR_HI,
                                  ERR_PER_OCTAVE)
        self._snr = reg.histogram(HIST_SNR, SNR_LO, SNR_HI,
                                  SNR_PER_OCTAVE)

    def record(self, fp, red_chi2s, toa_errs_us, snrs=None,
               labels=None):
        """Fold one archive's fingerprint + raw per-subint arrays into
        the run aggregate and the registry distributions."""
        import numpy as np

        rec = self._recorder
        for v in np.asarray(red_chi2s, dtype=float).ravel():
            self._chi2.observe(v)
        for v in np.asarray(toa_errs_us, dtype=float).ravel():
            self._err.observe(v)
        if snrs is not None:
            for v in np.asarray(snrs, dtype=float).ravel():
                self._snr.observe(v)
        reg = rec.metrics_registry()
        reg.inc(CTR_SUBINTS, fp["n_subints"])
        if fp["n_bad"]:
            reg.inc(CTR_BAD_SUBINTS, fp["n_bad"])
        rec.bump("quality_subints", fp["n_subints"])
        for ctr, key in (("quality_bad_subints", "n_bad"),
                         ("quality_bad_chi2", "n_bad_chi2"),
                         ("quality_bad_rc", "n_bad_rc"),
                         ("quality_nonfinite", "n_nonfinite"),
                         ("quality_error_inflated", "n_error_inflated"),
                         ("quality_zapped", "n_zapped")):
            if fp.get(key):
                rec.bump(ctr, fp[key])
        labels = labels or {}
        gkey = (labels.get("bucket") or "-",
                labels.get("workload") or "-")
        with self._lock:
            self.n_archives += 1
            self.n_subints += fp["n_subints"]
            self.n_bad += fp["n_bad"]
            self.n_zapped += fp["n_zapped"]
            self.n_error_inflated += fp["n_error_inflated"]
            g = self._groups.get(gkey)
            if g is None:
                g = self._groups[gkey] = _Group()
            g.n_subints += fp["n_subints"]
            g.n_bad += fp["n_bad"]
            g.n_zapped += fp["n_zapped"]
        for v in np.asarray(red_chi2s, dtype=float).ravel():
            g.chi2.observe(v)
        for v in np.asarray(toa_errs_us, dtype=float).ravel():
            g.err.observe(v)

    def fingerprint(self):
        """The run-level fingerprint (medians at histogram resolution,
        ~9% — per-archive events carry the exact ones)."""
        with self._lock:
            n = self.n_subints
            out = {"n_archives": self.n_archives, "n_subints": n,
                   "n_bad": self.n_bad, "n_zapped": self.n_zapped,
                   "n_error_inflated": self.n_error_inflated,
                   "bad_fit_rate": round(self.n_bad / n, 6)
                   if n else None}
        out["median_red_chi2"] = self._chi2.quantile(0.5)
        out["median_toa_err_us"] = self._err.quantile(0.5)
        return out

    def group_fingerprints(self):
        """{"<bucket>|<workload>": fingerprint} for every attribution
        group seen (the survey-summary breakdown)."""
        with self._lock:
            groups = dict(self._groups)
        return {"%s|%s" % k: g.fingerprint()
                for k, g in sorted(groups.items())}

    def stop(self):
        """Run end: record the run-level fingerprint as manifest
        gauges (the summary obs_report / obs_diff / bench read back
        without parsing metrics.jsonl)."""
        if not self.n_subints:
            return
        rec = self._recorder
        fp = self.fingerprint()
        for key in ("median_red_chi2", "median_toa_err_us",
                    "bad_fit_rate"):
            if fp.get(key) is not None:
                rec.set_gauge("quality_%s" % key, fp[key])


# -- module-level helpers (the instrumented-code API) -------------------


def _state():
    rec = _core._active
    if rec is None:
        return None
    return rec.quality_state()


def record_archive(archive, red_chi2s, toa_errs_us, snrs=None,
                   rcs=None, phis=None, phi_errs=None, n_zapped=0,
                   isubs=None, **extra):
    """Record one archive's fit quality into the active run (the
    single emission point both GetTOAs drivers and the narrowband path
    call after the device_get boundary).

    Emits a ``quality`` event (exact medians, bad-fit breakdown,
    whiteness, ambient bucket/workload attribution), feeds the fixed-
    geometry distribution series and bumps the exact counters.
    Returns the fingerprint dict, or None when no run is active /
    inputs are tracers.  Never fatal — a quality probe must not kill
    a fit that just succeeded.
    """
    rec = _core._active
    if rec is None:
        return None
    if _has_tracer(red_chi2s, toa_errs_us, snrs, rcs, phis, phi_errs):
        return None
    try:
        fp = summarize(red_chi2s, toa_errs_us, snrs=snrs, rcs=rcs,
                       phis=phis, phi_errs=phi_errs,
                       n_zapped=n_zapped, isubs=isubs)
        labels = _labels()
        st = rec.quality_state()
        if st is not None:
            st.record(fp, red_chi2s, toa_errs_us, snrs=snrs,
                      labels=labels)
        ev = dict(fp)
        ev["archive"] = archive
        ev.update(labels)
        ev.update(extra)
        rec.emit("quality", **ev)
        return fp
    except Exception:
        return None


def fingerprint():
    """The active run's run-level quality fingerprint, or None when no
    run is active or nothing was recorded (bench / runner summary
    read)."""
    rec = _core._active
    if rec is None or rec._quality is None:
        return None
    st = rec.quality_state()
    if st is None or not st.n_subints:
        return None
    return st.fingerprint()


def group_fingerprints():
    """Per-(bucket, workload) fingerprints of the active run, or None
    (the survey-summary breakdown)."""
    rec = _core._active
    if rec is None or rec._quality is None:
        return None
    st = rec.quality_state()
    if st is None or not st.n_subints:
        return None
    return st.group_fingerprints()
