"""Structured observability core: runs, spans, events, fit telemetry.

One :class:`Recorder` per run writes two files under
``$PPTPU_OBS_DIR/<run-id>/``:

* ``events.jsonl`` — an append-only stream of timestamped JSON events
  (spans, one-off events, compile/trace notifications from the
  jax.monitoring bridge, per-batch fit telemetry);
* ``manifest.json`` — the run's static context (shapes, config,
  platform, git SHA; see :mod:`.manifest`), rewritten at close with
  the aggregated counters, wall time, and jit cache sizes merged in.

Design rules (the contract the tests enforce):

* **Disabled = free.**  With ``PPTPU_OBS_DIR`` unset (the default),
  every entry point short-circuits on ``_active is None`` — no files,
  no imports of jax, no measurable overhead on the tier-1 lane.
* **Host-side only.**  Nothing here may run inside traced code:
  :func:`fit_telemetry` returns immediately when it sees a tracer, and
  jaxlint J002 statically rejects ``obs.*`` calls inside ``jax.jit``
  (docs/LINTING.md).  The device→host transfer fit telemetry performs
  on *concrete* results is the feature's documented cost, exactly like
  the PPTPU_SANITIZE NaN hooks.
* **Explicit device boundaries.**  A span that times device work must
  mark its result with ``sp.block(value)`` so ``block_until_ready``
  runs before the duration is taken — otherwise async dispatch
  attributes the device time to whichever span happens to synchronize
  later.
* **Never fatal.**  Telemetry IO failures degrade to dropped events,
  not pipeline crashes.
"""

import contextlib
import functools
import json
import os
import threading
import time

from ..testing import faults
from . import monitor
from .manifest import build_manifest

__all__ = ["obs_dir", "enabled", "current", "run", "scoped_run",
           "configure", "span", "phases", "event", "counter", "gauge",
           "fit_telemetry", "Recorder", "list_event_files",
           "obs_max_bytes"]

_state_lock = threading.Lock()
_active = None           # the process's active Recorder, or None
_run_seq = 0             # uniquifies run dirs within one process

_tls = threading.local()  # per-thread span path stack + trace context


def _trace_child():
    """Allocate a child span under the thread's ambient trace context
    (obs/tracing.py) and install it; returns ``(saved_ctx, fields)`` —
    ``fields`` is None when no context is ambient.  The caller MUST
    restore ``_tls.trace = saved_ctx`` on exit.  Kept inline here (not
    in tracing.py) so the no-context cost is one thread-local read."""
    ctx = getattr(_tls, "trace", None)
    if ctx is None:
        return None, None
    sid = os.urandom(8).hex()
    _tls.trace = (ctx[0], sid)
    fields = {"trace_id": ctx[0], "span_id": sid}
    if ctx[1] is not None:
        fields["parent_span_id"] = ctx[1]
    return ctx, fields


def obs_dir():
    """$PPTPU_OBS_DIR, or None when observability is disabled."""
    v = os.environ.get("PPTPU_OBS_DIR", "").strip()
    return v or None


def obs_max_bytes():
    """$PPTPU_OBS_MAX_BYTES: events.jsonl rotation threshold in bytes
    (0 / unset / unparsable = no rotation)."""
    v = os.environ.get("PPTPU_OBS_MAX_BYTES", "").strip()
    try:
        return max(0, int(v)) if v else 0
    except ValueError:
        return 0


def list_event_files(run_dir):
    """Every event file of a run, oldest first: the rotated set
    (``events.jsonl.1``, ``events.jsonl.2``, ...) then the live
    ``events.jsonl``.  Readers (tools/obs_report.py, obs/merge.py) use
    this so survey-scale rotated runs read back as one stream."""
    out = []
    try:
        names = os.listdir(run_dir)
    except OSError:
        return out
    rotated = []
    for name in names:
        if name.startswith("events.jsonl."):
            suffix = name.rsplit(".", 1)[-1]
            if suffix.isdigit():
                rotated.append((int(suffix), name))
    out = [os.path.join(run_dir, name) for _, name in sorted(rotated)]
    live = os.path.join(run_dir, "events.jsonl")
    if os.path.isfile(live):
        out.append(live)
    return out


def enabled():
    """True when a run is active or PPTPU_OBS_DIR would enable one."""
    return _active is not None or obs_dir() is not None


def current():
    """The active Recorder, or None."""
    return _active


def _span_stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _json_default(x):
    # numpy scalars/arrays and other non-JSON leaves degrade to
    # something readable instead of raising mid-pipeline
    try:
        import numpy as np

        if isinstance(x, np.ndarray):
            return x.tolist()
        if isinstance(x, np.generic):
            return x.item()
    except Exception:
        pass
    return repr(x)


class Recorder:
    """JSONL event sink + manifest writer for one run."""

    def __init__(self, name, base_dir, config=None):
        global _run_seq
        with _state_lock:
            _run_seq += 1
            seq = _run_seq
        stamp = time.strftime("%Y%m%dT%H%M%S")
        self.run_id = "%s-%s-p%d-%02d" % (name, stamp, os.getpid(), seq)
        self.name = name
        self.dir = os.path.join(base_dir, self.run_id)
        os.makedirs(self.dir, exist_ok=True)
        self.events_path = os.path.join(self.dir, "events.jsonl")
        self.manifest_path = os.path.join(self.dir, "manifest.json")
        self._lock = threading.Lock()
        # always-on flight ring (obs/flight.py): every emitted event
        # also lands in a bounded in-memory deque, so a postmortem can
        # show the last few seconds even when the sink itself is dead
        from .flight import FlightRecorder

        self.flight = FlightRecorder(self)
        self._fh = open(self.events_path, "a", encoding="utf-8")
        # size-based sink rotation (PPTPU_OBS_MAX_BYTES): survey-scale
        # runs emit one fit event per archive batch and must not grow
        # one unbounded file
        self._max_bytes = obs_max_bytes()
        try:
            self._bytes = os.path.getsize(self.events_path)
        except OSError:
            self._bytes = 0
        self._rot_seq = 0
        self._t0 = time.time()
        self._perf0 = time.perf_counter()
        self.counters = {}
        self.gauges = {}
        self.n_events = 0
        self.dropped_events = 0  # sink-write failures (never fatal)
        self.compile_total_s = 0.0
        self.manifest = build_manifest(name, self.run_id, config=config)
        self._write_manifest()
        self._mon_cb = monitor.subscribe(self._on_monitoring)
        # streaming metrics (obs/metrics.py): registry + snapshot
        # exporter created lazily on the first metrics.* call, so a
        # run that records no metrics costs neither a thread nor a
        # metrics.jsonl
        self._metrics = None
        self._metrics_exporter = None
        # memory watermark sampler (obs/memory.py): created lazily on
        # the first span boundary, same gating as the exporter above
        self._memory = None
        # fit-quality aggregation (obs/quality.py): created lazily on
        # the first quality record — a run that fits nothing pays
        # nothing
        self._quality = None
        # alert-rule engine (obs/health.py): created lazily on the
        # first health evaluation (runner claim cycle, service health
        # verb), same gating as the states above
        self._health = None
        # usage-accounting plane (obs/usage.py): created lazily on the
        # first metered unit — a run that serves nothing bills nothing
        self._usage = None
        self._closed = False

    def metrics_registry(self):
        """The run's MetricsRegistry (created on first use, together
        with the periodic ``metrics.jsonl`` exporter)."""
        reg = self._metrics
        if reg is not None:
            return reg
        from .metrics import MetricsExporter, MetricsRegistry

        with self._lock:
            if self._metrics is None:
                self._metrics = MetricsRegistry()
                self._metrics_exporter = MetricsExporter(
                    self._metrics, self.dir)
            return self._metrics

    def memory_state(self):
        """The run's memory watermark sampler (obs/memory.py), created
        on first use; None when creation failed — never fatal."""
        st = self._memory
        if st is not None:
            return st
        from .memory import MemoryState

        with self._lock:
            if self._memory is None and not self._closed:
                try:
                    # publish=False init skips the registry re-entry path (jaxlint J007)
                    self._memory = MemoryState(self)  # jaxlint: disable=J007
                except Exception:
                    return None
            return self._memory

    def quality_state(self):
        """The run's fit-quality aggregator (obs/quality.py), created
        on first use; None when creation failed — never fatal."""
        st = self._quality
        if st is not None:
            return st
        from .quality import QualityState

        # materialize the registry first: QualityState.__init__ reads
        # it, and self._lock is not reentrant
        self.metrics_registry()
        with self._lock:
            if self._quality is None and not self._closed:
                try:
                    # registry materialized above: no re-entry (jaxlint J007)
                    self._quality = QualityState(self)  # jaxlint: disable=J007
                except Exception:
                    return None
            return self._quality

    def usage_state(self):
        """The run's usage-accounting plane (obs/usage.py), created on
        first use; None when creation failed — never fatal."""
        st = self._usage
        if st is not None:
            return st
        from .usage import UsageState

        # materialize the registry first: UsageState.__init__ reads
        # it, and self._lock is not reentrant
        self.metrics_registry()
        with self._lock:
            if self._usage is None and not self._closed:
                try:
                    # registry materialized above: no re-entry (jaxlint J007)
                    self._usage = UsageState(self)  # jaxlint: disable=J007
                except Exception:
                    return None
            return self._usage

    def health_state(self):
        """The run's alert-rule engine (obs/health.py), created on
        first use; None when PPTPU_HEALTH=0 or creation failed —
        never fatal."""
        st = self._health
        if st is not None:
            return st
        from .health import HealthState, health_enabled

        if not health_enabled():
            return None
        # materialize the registry first: HealthState samples it, and
        # self._lock is not reentrant
        self.metrics_registry()
        with self._lock:
            if self._health is None and not self._closed:
                try:
                    # registry materialized above: no re-entry (jaxlint J007)
                    self._health = HealthState(self)  # jaxlint: disable=J007
                except Exception:
                    return None
        exporter = self._metrics_exporter
        if exporter is not None and self._health is not None:
            # evaluate on the exporter cadence, just before each
            # periodic snapshot, so the alert gauges land in the
            # metrics.jsonl line that tick writes
            exporter.on_tick = self._health.evaluate
        return self._health

    # -- event stream ---------------------------------------------------

    def emit(self, kind, **fields):
        """Append one timestamped JSON event; never raises."""
        rec = {"t": round(time.time(), 6), "kind": kind}
        rec.update(fields)
        # flight ring first (obs/flight.py): the in-memory trail must
        # survive a sink-write failure — that failure is exactly what
        # a postmortem needs to explain
        self.flight.record(rec)
        try:
            line = json.dumps(rec, default=_json_default)
        except Exception:
            return
        with self._lock:
            if self._closed:
                return
            try:
                # chaos site: an injected sink-write failure (full
                # disk, dead NFS) must DROP the event, never crash the
                # pipeline — the "never fatal" contract above
                # _tls.emitting guards re-entry; hang= is test-only (jaxlint J006, J007)
                faults.check("obs_write")  # jaxlint: disable=J006, J007
                if self._max_bytes and self._bytes and \
                        self._bytes + len(line) + 1 > self._max_bytes:
                    self._rotate()
                # the sink write IS the critical section (jaxlint J006)
                self._fh.write(line + "\n")  # jaxlint: disable=J006
                self._fh.flush()  # jaxlint: disable=J006 — bounded flush of one line
                self.n_events += 1
                self._bytes += len(line) + 1
            except (OSError, faults.InjectedFault):
                self.dropped_events += 1

    def _rotate(self):
        """Move the live events file aside as ``events.jsonl.<n>`` and
        start a fresh one (caller holds the lock).  ``.1`` is the
        oldest; ``list_event_files`` reads the set back in order.
        Failures degrade to continuing on the current file."""
        self._rot_seq += 1
        try:
            self._fh.close()
            os.replace(self.events_path,
                       "%s.%d" % (self.events_path, self._rot_seq))
        except OSError:
            pass
        self._fh = open(self.events_path, "a", encoding="utf-8")
        try:
            self._bytes = os.path.getsize(self.events_path)
        except OSError:
            self._bytes = 0

    def bump(self, name, inc=1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + inc

    def counter(self, name, inc=1):
        """Per-recorder form of the module-level :func:`counter` (the
        health/flight planes bump their own recorder's counters)."""
        self.bump(name, inc)

    def event(self, name, **fields):
        """Per-recorder form of the module-level :func:`event`: a
        one-off JSON event on THIS recorder, ambient-trace-stamped
        the same way (the health/flight planes emit their lifecycle
        events through the recorder they observe)."""
        ctx = getattr(_tls, "trace", None)
        if ctx is not None:
            fields.setdefault("trace_id", ctx[0])
            if ctx[1] is not None:
                fields.setdefault("span_id", ctx[1])
        self.emit("event", name=name, **fields)

    def set_gauge(self, name, value):
        with self._lock:
            self.gauges[name] = value

    def merge_config(self, config):
        """Fold extra config into the manifest (reentrant runs)."""
        self.manifest.setdefault("config", {}).update(config or {})
        self._write_manifest()

    # -- jax.monitoring bridge ------------------------------------------

    def _on_monitoring(self, evt, duration):
        if evt == monitor.TRACE_EVENT:
            self.bump("jaxpr_traces")
        elif evt == monitor.COMPILE_EVENT:
            self.bump("backend_compiles")
            with self._lock:
                self.compile_total_s += duration
            stack = _span_stack()
            self.emit("compile", dur_s=round(duration, 6),
                      span="/".join(s.name for s in stack) or None)
        elif evt == monitor.CACHE_HIT_EVENT:
            # persistent-compile-cache outcome counters: a warm-started
            # process proves its cold compiles were saved here
            # (docs/SERVICE.md zero-cold-start).  Mirrored into the
            # metrics plane so live --watch views show the hit/miss
            # ratio without waiting for the run manifest.
            self.bump("compile_cache_hits")
            try:
                self.metrics_registry().inc(
                    "pps_compile_cache_hits_total")
            except Exception:
                pass
        elif evt == monitor.CACHE_MISS_EVENT:
            self.bump("compile_cache_misses")
            try:
                self.metrics_registry().inc(
                    "pps_compile_cache_misses_total")
            except Exception:
                pass

    # -- manifest -------------------------------------------------------

    def _write_manifest(self):
        try:
            tmp = self.manifest_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(self.manifest, fh, indent=1,
                          default=_json_default)
                fh.write("\n")
            os.replace(tmp, self.manifest_path)
        except OSError:
            pass

    def _jit_cache_sizes(self):
        """Cache sizes of the retrace-budgeted hot jit boundaries —
        the gauges PPTPU_SANITIZE's budgets bound at runtime."""
        sizes = {}
        try:
            from ..fit import portrait as fp

            for attr in ("_solve", "_batch_impl"):
                fn = getattr(fp, attr, None)
                try:
                    sizes["fit.portrait.%s" % attr] = int(fn._cache_size())
                except Exception:
                    pass
        except Exception:
            pass
        return sizes

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
        monitor.unsubscribe(self._mon_cb)
        if self._memory is not None:
            # stop the sampler BEFORE the exporter: the final memory
            # gauges must land in the final metrics.jsonl snapshot
            self._memory.stop()
        if self._quality is not None:
            # same ordering: the run-level quality fingerprint gauges
            # must make the manifest written below
            try:
                self._quality.stop()
            except Exception:
                pass
        if self._health is not None:
            # final rule pass BEFORE the exporter stop: the closing
            # alert gauges must land in the final metrics.jsonl
            # snapshot
            try:
                self._health.stop()
            except Exception:
                pass
        if self._usage is not None:
            # same ordering: the run-total usage gauges must make the
            # manifest written below (bench/obs_diff read them back)
            try:
                self._usage.stop()
            except Exception:
                pass
        if self._metrics_exporter is not None:
            # final cumulative snapshot: even a run closed before the
            # first periodic tick leaves one metrics.jsonl line
            self._metrics_exporter.stop()
        self.manifest.update(
            t_end=time.time(),
            wall_s=round(time.perf_counter() - self._perf0, 6),
            counters=dict(self.counters),
            gauges=dict(self.gauges),
            n_events=self.n_events,
            dropped_events=self.dropped_events,
            compile_total_s=round(self.compile_total_s, 6),
            jit_cache_sizes=self._jit_cache_sizes(),
        )
        self._write_manifest()
        try:
            self._fh.close()
        except OSError:
            pass


@contextlib.contextmanager
def run(name, config=None, base_dir=None):
    """Open a run (Recorder) for the dynamic extent of the context.

    Reentrant: when a run is already active (a CLI opened one and a
    pipeline opens another), the existing recorder is reused — its
    manifest absorbs the inner ``config`` and the inner context's exit
    does NOT close it.  A no-op yielding None when PPTPU_OBS_DIR is
    unset — unless ``base_dir`` is given, which opens the run there
    regardless of the environment (callers whose *output* is the obs
    run: the survey runner's per-process shards, bench's result
    read-back).
    """
    global _active
    with _state_lock:
        existing = _active
    if existing is not None:
        if config:
            existing.merge_config(config)
        yield existing
        return
    base = base_dir or obs_dir()
    if base is None:
        yield None
        return
    try:
        rec = Recorder(name, base, config=config)
    except OSError:
        yield None  # an unwritable obs dir must not kill the pipeline
        return
    with _state_lock:
        _active = rec
    try:
        yield rec
    finally:
        with _state_lock:
            _active = None
        rec.close()


def scoped_run(name):
    """Decorator form of :func:`run` for pipeline entry points.

    ``@obs.scoped_run("pptoas")`` opens (or, reentrantly, joins) a run
    for the duration of each call; call :func:`configure` inside the
    body to fold runtime config into the manifest once it is known.
    """
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with run(name):
                return fn(*args, **kwargs)
        return wrapper
    return deco


def configure(**config):
    """Merge fields into the active run's manifest config (no-op when
    no run is active)."""
    rec = _active
    if rec is not None:
        rec.merge_config(config)


class _Span:
    """Handle yielded by :func:`span`; ``block(x)`` marks the device
    value whose completion bounds the span."""

    __slots__ = ("name", "_block")

    def __init__(self, name):
        self.name = name
        self._block = None

    def block(self, value):
        """Mark ``value`` for block_until_ready at span exit; returns
        ``value`` unchanged so it nests in expressions."""
        self._block = value
        return value


class _NullSpan:
    __slots__ = ()
    name = None

    def block(self, value):
        return value


_NULL_SPAN = _NullSpan()


@contextlib.contextmanager
def span(name, **attrs):
    """Record a nested wall-clock span event.

    Usage::

        with obs.span("solve", archive=path, batch=B) as sp:
            out = fit_portrait_full_batch(...)
            sp.block(out.params)     # device boundary: block before t1

    Emits ``{"kind": "span", "name": ..., "path": "a/b/solve",
    "dur_s": ..., ...attrs}``.  When no run is active this is a no-op
    yielding a shared null handle.  Must never be called inside traced
    code (jaxlint J002): under jit the body would be timed at trace
    time once and never again.
    """
    rec = _active
    if rec is None:
        yield _NULL_SPAN
        return
    sp = _Span(name)
    stack = _span_stack()
    stack.append(sp)
    # ambient trace context (obs/tracing.py): the span becomes a child
    # of whatever request/archive trace this thread is working for,
    # and its own id is ambient for nested spans — zero caller churn
    saved_ctx, trace_fields = _trace_child()
    # memory watermark bracket (obs/memory.py): peak footprint over
    # the span's extent rides along as the event's ``peak_bytes``
    mem = rec.memory_state()
    mtok = mem.mark() if mem is not None else None
    t0 = time.perf_counter()
    err = None
    try:
        yield sp
    except BaseException as e:
        err = type(e).__name__
        raise
    finally:
        if sp._block is not None:
            try:
                import jax

                jax.block_until_ready(sp._block)
            except Exception:
                pass
        dur = time.perf_counter() - t0
        if trace_fields is not None:
            _tls.trace = saved_ctx
        if stack and stack[-1] is sp:
            stack.pop()
        path = "/".join(s.name for s in stack + [sp])
        fields = dict(attrs)
        if trace_fields is not None:
            fields.update(trace_fields)
        if err is not None:
            fields["error"] = err
        if mtok is not None:
            pk = mem.peak(mtok)
            if pk:
                fields["peak_bytes"] = pk
        rec.emit("span", name=name, path=path, dur_s=round(dur, 6),
                 **fields)


class phases:
    """Sequential phase spans for long pipeline bodies.

    A with-block per phase would force re-indenting hundred-line
    pipeline sections; this timer instead closes the previous phase
    whenever the next one is entered::

        ph = obs.phases(archive=path)
        ph.enter("load");  data = load(...)
        ph.enter("solve"); out = fit(...); ph.block(out.params)
        ph.enter("write"); write(...)
        ph.done()

    Each phase is emitted as a normal span event (same schema and path
    rules) and participates in the thread's span stack, so compile
    events are attributed to the phase they occurred in.  ``done()``
    must run on every exit path of the instrumented region — a missed
    one drops that phase's event and cleans the stack lazily, it never
    corrupts later spans.  All methods are no-ops when no run is
    active at ``enter`` time.
    """

    def __init__(self, **attrs):
        self._attrs = attrs
        self._sp = None
        self._t0 = 0.0
        self._extra = {}
        self._block = None
        self._saved_ctx = None
        self._trace_fields = None
        self._mem = None
        self._mtok = None

    def enter(self, name, **attrs):
        """Close the current phase (if any) and open ``name``."""
        self._finish()
        rec = _active
        if rec is None:
            return
        self._sp = _Span(name)
        self._extra = dict(attrs)
        _span_stack().append(self._sp)
        # each phase is a child span of the ambient trace context, and
        # ambient for its own extent (same contract as obs.span)
        self._saved_ctx, self._trace_fields = _trace_child()
        self._mem = rec.memory_state()
        self._mtok = self._mem.mark() if self._mem is not None else None
        self._t0 = time.perf_counter()

    def block(self, value):
        """Device value bounding the CURRENT phase: block_until_ready
        runs before its duration is taken.  Returns ``value``."""
        self._block = value
        return value

    def done(self, **attrs):
        """Close the current phase, folding ``attrs`` into its event."""
        self._extra.update(attrs)
        self._finish()

    def _finish(self):
        sp, self._sp = self._sp, None
        if sp is None:
            self._block = None
            return
        if self._block is not None:
            try:
                import jax

                jax.block_until_ready(self._block)
            except Exception:
                pass
            self._block = None
        dur = time.perf_counter() - self._t0
        trace_fields, self._trace_fields = self._trace_fields, None
        if trace_fields is not None:
            _tls.trace = self._saved_ctx
            self._saved_ctx = None
        stack = _span_stack()
        if sp in stack:
            path = "/".join(s.name for s in stack[:stack.index(sp) + 1])
            stack.remove(sp)
        else:
            path = sp.name
        mem, self._mem = self._mem, None
        mtok, self._mtok = self._mtok, None
        pk = mem.peak(mtok) if mem is not None and mtok is not None \
            else None
        rec = _active
        if rec is not None:
            fields = dict(self._attrs)
            fields.update(self._extra)
            if trace_fields is not None:
                fields.update(trace_fields)
            if pk:
                fields["peak_bytes"] = pk
            rec.emit("span", name=sp.name, path=path,
                     dur_s=round(dur, 6), **fields)
        self._extra = {}


def event(name, **fields):
    """One-off JSON event (no duration); no-op when no run is active.

    When a trace context is ambient (obs/tracing.py) the event is
    stamped with ``trace_id`` (+ the enclosing ``span_id``), so the
    lease/robustness audit events become causally searchable without
    any caller change.  Explicit fields win over the ambient stamp.
    """
    rec = _active
    if rec is not None:
        ctx = getattr(_tls, "trace", None)
        if ctx is not None:
            fields.setdefault("trace_id", ctx[0])
            if ctx[1] is not None:
                fields.setdefault("span_id", ctx[1])
        rec.emit("event", name=name, **fields)


def counter(name, inc=1):
    """Bump an aggregate counter (written into the manifest at close)."""
    rec = _active
    if rec is not None:
        rec.bump(name, inc)


def gauge(name, value):
    """Set a gauge (last value wins; manifest at close + JSONL event)."""
    rec = _active
    if rec is not None:
        rec.set_gauge(name, value)
        rec.emit("gauge", name=name, value=value)


# fields of a batched fit result that carry per-subint fit quality
_FIT_FIELDS = ("nfeval", "chi2", "red_chi2", "return_code")

# solver return codes that mean "converged" (config.RCSTRINGS): 0/1/2;
# 3 = iteration budget exhausted, 4 = damping blew past mu_max (stuck)
_CONVERGED_RCS = (0, 1, 2)


def fit_telemetry(result, where="fit", **attrs):
    """Log per-batch fit-quality telemetry from a *concrete* result.

    ``result`` is a fit DataBunch/dict carrying per-subint ``nfeval``,
    ``chi2``/``red_chi2`` and ``return_code`` (the auxiliary outputs
    the batched solvers in fit/portrait.py return).  Emits one ``fit``
    event with summary statistics, the return-code histogram, and the
    per-subint vectors.  Returns ``result`` unchanged.

    Host-side only: traced inputs pass through untouched (so a caller
    accidentally inside jit cannot sync or crash — though jaxlint J002
    flags that caller), and nothing happens when no run is active.
    The device→host transfer of the small per-subint vectors is the
    documented cost when enabled.
    """
    rec = _active
    if rec is None:
        return result
    try:
        fields = {k: result[k] for k in _FIT_FIELDS
                  if isinstance(result, dict) and k in result}
    except Exception:
        return result
    if not fields:
        return result
    import jax

    if any(isinstance(v, jax.core.Tracer) for v in fields.values()):
        return result  # inside traced code: never sync (J002 contract)
    import numpy as np

    try:
        host = jax.device_get(fields)
    except Exception:
        return result
    ev = {"where": where}
    ev.update(attrs)
    nfev = np.atleast_1d(np.asarray(host.get("nfeval", [])))
    rc = np.atleast_1d(np.asarray(host.get("return_code", [])))
    chi2 = np.atleast_1d(np.asarray(
        host.get("red_chi2", host.get("chi2", []))), )
    ev["batch"] = int(nfev.size) if nfev.size else int(rc.size)
    if nfev.size:
        ev["nfeval"] = {"min": int(nfev.min()),
                        "median": float(np.median(nfev)),
                        "max": int(nfev.max())}
        ev["nfeval_per_subint"] = nfev.astype(int).tolist()
    if chi2.size:
        finite = np.isfinite(chi2)
        ev["chi2"] = {"median": float(np.median(chi2[finite]))
                      if finite.any() else None,
                      "max": float(chi2[finite].max())
                      if finite.any() else None,
                      "n_nonfinite": int((~finite).sum())}
        ev["red_chi2_per_subint"] = [round(float(x), 6) for x in chi2]
    if rc.size:
        hist = {}
        for code in rc.astype(int):
            hist[str(code)] = hist.get(str(code), 0) + 1
        ev["rc_hist"] = hist
        converged = np.isin(rc.astype(int), _CONVERGED_RCS)
        bad = ~converged
        if chi2.size == rc.size:
            bad = bad | ~np.isfinite(chi2)
        ev["n_bad"] = int(bad.sum())
        ev["bad_isubs"] = np.flatnonzero(bad).tolist()
    rec.emit("fit", **ev)
    rec.bump("fit_batches")
    rec.bump("fit_subints", ev.get("batch", 0))
    if "n_bad" in ev:
        rec.bump("fit_bad_subints", ev["n_bad"])
    return result
