"""Single jax.monitoring bridge shared by every telemetry consumer.

jax.monitoring has no unregister API, so naive per-consumer
registration leaks one permanent listener per consumer (the hazard the
ad-hoc listener in the pre-obs ``debug.py`` worked around privately).
This module owns ONE permanent listener and fans events out to
whatever subscribers are currently registered: ``debug.trace_counter``
subscribes a counter for the duration of its context, an active
:class:`pulseportraiture_tpu.obs.core.Recorder` subscribes for the
duration of a run, and both see the same stream.

Subscribers are callables ``cb(event, duration)`` where ``event`` is
the jax.monitoring event key and ``duration`` its reported seconds
(0.0 for events without one).  Subscription is thread-safe; callbacks
run on whatever thread jax emits from and must be cheap and
exception-free (a raising subscriber is dropped rather than allowed to
poison the shared listener).
"""

import threading

__all__ = ["TRACE_EVENT", "COMPILE_EVENT", "CACHE_HIT_EVENT",
           "CACHE_MISS_EVENT", "subscribe", "unsubscribe"]

# the two duration events the repo's telemetry is built on: one fires
# per jaxpr trace, one per backend (XLA) compile
TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

# persistent-compilation-cache outcomes (plain events, no duration):
# with jax_compilation_cache_dir configured every backend compile is
# preceded by exactly one of these, so hit/miss counters answer "did
# the warm stage actually save this process a cold compile?"
# (docs/SERVICE.md zero-cold-start)
CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

_lock = threading.Lock()
_subscribers = []
_listener_installed = False


def _fan_out(event, duration):
    if not _subscribers:
        return
    with _lock:
        subs = list(_subscribers)
    for cb in subs:
        try:
            cb(event, float(duration))
        except Exception:
            # a broken subscriber must not take down the process's
            # only listener; drop it
            with _lock:
                if cb in _subscribers:
                    _subscribers.remove(cb)


def _install_listener():
    global _listener_installed
    if _listener_installed:
        return
    import jax.monitoring

    def _on_duration(event, duration=0.0, **kwargs):
        _fan_out(event, duration)

    def _on_event(event, **kwargs):
        _fan_out(event, 0.0)

    jax.monitoring.register_event_duration_secs_listener(_on_duration)
    try:
        # plain (durationless) events carry the compilation-cache
        # hit/miss stream; older jax without the API just loses those
        # counters, never the duration telemetry
        jax.monitoring.register_event_listener(_on_event)
    except AttributeError:
        pass
    _listener_installed = True


def subscribe(cb):
    """Register ``cb(event, duration)`` on the shared listener."""
    _install_listener()
    with _lock:
        _subscribers.append(cb)
    return cb


def unsubscribe(cb):
    """Remove a subscriber registered with :func:`subscribe`."""
    with _lock:
        if cb in _subscribers:
            _subscribers.remove(cb)
