"""Flight recorder: an always-on ring of recent events + postmortems.

The event stream (:mod:`.core`) is complete but append-only on disk —
when a run goes sideways (an OOM quarantine, a watchdog kill, a firing
alert) the question is "what happened in the last few seconds", and
answering it from a multi-megabyte ``events.jsonl`` after the fact is
exactly the forensics lag this module removes.  Every
:class:`~.core.Recorder` owns one :class:`FlightRecorder`:

* **Always-on ring.**  ``record()`` appends every emitted event dict
  into a bounded in-memory deque (``PPTPU_FLIGHT_CAPACITY``, default
  256) *before* the sink write, so the ring still holds the trail when
  the sink itself is the failure (full disk, dead NFS — the
  ``obs_write`` chaos site).  The append is one ``deque.append`` of an
  already-built dict; ``tools/span_overhead.py`` prices it inside the
  obs plane's existing <2% budget.
* **Postmortem bundles.**  ``dump(trigger)`` freezes the ring together
  with the last metrics snapshot, the firing alerts (:mod:`.health`)
  and a manifest excerpt into
  ``<run-dir>/postmortem/<seq>-<trigger>.json``.  Dumps are capped per
  run (``PPTPU_FLIGHT_MAX_DUMPS``, default 8) so a flapping alert
  cannot fill a disk, and every failure degrades to a dropped bundle —
  the obs "never fatal" contract.

Triggers are wired where the failures live: the survey runner dumps on
OOM/watchdog/quarantine (runner/execute.py), the TOA service on
request quarantine (service/daemon.py), and the health plane the
moment any alert transitions to firing (obs/health.py).

Host-side only, like everything in ``obs`` (jaxlint J002).
"""

import collections
import json
import os
import re
import threading
import time

from . import core as _core

__all__ = ["FLIGHT_SCHEMA", "flight_capacity", "flight_max_dumps",
           "FlightRecorder", "dump", "load_postmortems"]

FLIGHT_SCHEMA = "pptpu-postmortem-v1"

# manifest keys worth carrying into a bundle: enough context to read a
# postmortem without the run directory (the full manifest stays there)
_MANIFEST_EXCERPT_KEYS = ("schema", "run_id", "name", "t_start",
                          "config", "platform", "git")

_TRIGGER_SAFE_RE = re.compile(r"[^A-Za-z0-9_.-]+")


def flight_capacity():
    """$PPTPU_FLIGHT_CAPACITY: ring size in events (default 256; 0
    disables the ring — and with it, postmortem dumps)."""
    v = os.environ.get("PPTPU_FLIGHT_CAPACITY", "").strip()
    try:
        return max(0, int(v)) if v else 256
    except ValueError:
        return 256


def flight_max_dumps():
    """$PPTPU_FLIGHT_MAX_DUMPS: postmortem bundles per run (default 8;
    a flapping trigger must not fill the disk)."""
    v = os.environ.get("PPTPU_FLIGHT_MAX_DUMPS", "").strip()
    try:
        return max(0, int(v)) if v else 8
    except ValueError:
        return 8


class FlightRecorder:
    """Bounded ring of recent event dicts + postmortem bundle writer
    for one :class:`~.core.Recorder`."""

    def __init__(self, recorder):
        self._recorder = recorder
        cap = flight_capacity()
        # None (capacity 0) keeps record() at one attribute read
        self._ring = collections.deque(maxlen=cap) if cap else None
        self._max_dumps = flight_max_dumps()
        self._seq = 0
        self._lock = threading.Lock()

    @property
    def capacity(self):
        return self._ring.maxlen if self._ring is not None else 0

    def record(self, rec):
        """Append one event dict (a ``deque.append`` — the whole
        always-on cost; the deque's maxlen bounds memory)."""
        ring = self._ring
        if ring is not None:
            ring.append(rec)

    def snapshot_ring(self):
        """The ring's current contents, oldest first."""
        ring = self._ring
        return list(ring) if ring is not None else []

    def dump(self, trigger, context=None):
        """Write one postmortem bundle; returns its path, or None when
        disabled, capped or failed — never raises."""
        rec = self._recorder
        if self._ring is None or rec is None:
            return None
        try:
            with self._lock:
                if self._seq >= self._max_dumps:
                    return None
                self._seq += 1
                seq = self._seq
            bundle = self._build_bundle(trigger, context)
            pm_dir = os.path.join(rec.dir, "postmortem")
            os.makedirs(pm_dir, exist_ok=True)
            fname = "%03d-%s.json" % (
                seq, _TRIGGER_SAFE_RE.sub("-", str(trigger)) or "dump")
            path = os.path.join(pm_dir, fname)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(bundle, fh, indent=1,
                          default=_core._json_default)
                fh.write("\n")
            os.replace(tmp, path)
        except Exception:
            return None
        # the bundle itself lands first, then its audit trail: a sink
        # failure here loses the event, never the postmortem
        rec.event("postmortem_written", trigger=str(trigger),
                  path=path,
                  n_ring=len(bundle.get("ring") or ()))
        rec.counter("postmortems_written")
        return path

    def _build_bundle(self, trigger, context):
        rec = self._recorder
        bundle = {"schema": FLIGHT_SCHEMA,
                  "t": round(time.time(), 6),
                  "trigger": str(trigger)}
        if context:
            bundle["context"] = dict(context)
        bundle["ring"] = self.snapshot_ring()
        # already-materialized sub-states only: a postmortem must not
        # spin up the exporter thread of a run that never used metrics
        reg = rec._metrics
        bundle["metrics"] = reg.snapshot() if reg is not None else None
        hs = rec._health
        bundle["alerts_firing"] = hs.firing() if hs is not None else []
        bundle["manifest"] = {k: rec.manifest.get(k)
                              for k in _MANIFEST_EXCERPT_KEYS
                              if k in rec.manifest}
        bundle["counters"] = dict(rec.counters)
        return bundle


def dump(trigger, **context):
    """Dump a postmortem from the active run's flight recorder;
    returns the bundle path, or None when no run is active (no-op at
    one attribute read — the disabled-obs contract)."""
    rec = _core._active
    if rec is None:
        return None
    return rec.flight.dump(trigger, context=context or None)


def load_postmortems(run_dir):
    """Every parseable postmortem bundle of a run, oldest first, each
    with its ``file`` name injected.  Torn or partial bundles (a
    sigkilled worker mid-dump) are skipped — a dead shard's ring must
    never corrupt a survivor's forensics."""
    pm_dir = os.path.join(run_dir, "postmortem")
    try:
        names = sorted(os.listdir(pm_dir))
    except OSError:
        return []
    out = []
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(pm_dir, name),
                      encoding="utf-8") as fh:
                bundle = json.load(fh)
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            continue
        if isinstance(bundle, dict):
            bundle["file"] = name
            out.append(bundle)
    return out
