"""Structured observability for the TOA pipelines (docs/OBSERVABILITY.md).

Gated on ``PPTPU_OBS_DIR``: when unset (the default) every entry point
is a cheap no-op; when set, pipelines write a per-run directory holding
``events.jsonl`` (spans, compiles, fit telemetry) and ``manifest.json``
(platform, shapes, config, git SHA).  ``tools/obs_report.py``
summarizes a run into the tables PERF.md used to maintain by hand.

Layout:

* :mod:`.core`     — runs, spans, events, counters, fit telemetry,
  size-based sink rotation (``PPTPU_OBS_MAX_BYTES``)
* :mod:`.monitor`  — the single jax.monitoring fan-out bridge (shared
  with the PPTPU_SANITIZE trace counters in ``debug.py``)
* :mod:`.manifest` — run-manifest assembly (git SHA, device, env)
* :mod:`.trace`    — opt-in jax.profiler capture (``PPTPU_TRACE_DIR``),
  reentrancy-safe (a nested capture degrades to a ``trace_skipped``
  event; the profiler is a process-wide singleton)
* :mod:`.devtime`  — profiler-capture ingestion: Chrome-trace/xplane
  parsing, self-time reduction, ``jax.named_scope`` (``pp_*``) stage
  attribution, the per-region ``devtime`` events the phase table's
  device column is built from
* :mod:`.memory`   — memory observability: live device/host watermark
  sampler (``pps_device_bytes_in_use`` / ``pps_device_peak_bytes`` /
  ``pps_host_rss_bytes`` gauges), per-span ``peak_bytes`` watermarks,
  ``device_memory_profile`` OOM dumps
* :mod:`.quality`  — fit-quality observability: per-run quality
  fingerprints from the per-subint fit statistics (reduced chi^2 /
  TOA-error / S/N distributions with fixed histogram geometry, exact
  bad-fit counters, per-archive ``quality`` events with residual
  whiteness) — the ``obs_diff --quality-rel`` drift gate's data plane
* :mod:`.metrics`  — live telemetry plane: label-keyed counters/
  gauges + log-bucketed latency histograms with exact deterministic
  merge, periodic ``metrics.jsonl`` snapshots, Prometheus text
  rendering, SLO evaluation (``pploadgen``), the ``--watch`` frames
* :mod:`.health`   — live health plane: declarative alert rules
  (threshold / rate / ratio / SLO burn-rate) over windowed registry
  snapshots with a pending→firing→resolved lifecycle
  (``alert_firing`` / ``alert_resolved`` events, the
  ``pps_alerts_firing`` / ``pps_alerts_total`` series), evaluated on
  the exporter cadence and each claim cycle
* :mod:`.usage`    — per-tenant usage metering: every unit of work
  (service request, fleet forward, survey archive) becomes one
  ``usage.jsonl`` record with (tenant, bucket, workload) attribution
  and additive measures (wall/device seconds, bytes, archives), plus
  quota enforcement (``PPTPU_QUOTAS``) and the ``pps_usage_*`` /
  ``pps_quota_*`` series the fleet merges per tenant
* :mod:`.flight`   — flight recorder: always-on bounded in-memory
  ring of recent events that freezes into postmortem bundles
  (``<run>/postmortem/``) on OOM/watchdog/quarantine/alert triggers
* :mod:`.tracing`  — distributed tracing: ``trace_id`` / ``span_id``
  / ``parent_span_id`` on every span and event via a thread-ambient
  context, ``traceparent`` carriers across processes, span links for
  batched fan-in; ``tools/obs_trace.py`` rebuilds the span trees and
  critical paths
* :mod:`.merge`    — multihost shard merge: per-process
  ``events.<proc>.jsonl`` + ``manifest.<proc>.json`` shards into one
  run (span paths prefixed by process, counters summed)

Never call any of this inside ``jax.jit`` — telemetry is host-side by
contract (jaxlint J002 enforces it statically; ``fit_telemetry``
additionally passes tracers through untouched at runtime).
"""

from . import (devtime, flight, health, memory, metrics,  # noqa: F401
               monitor, quality, tracing, usage)
from .core import (Recorder, configure, counter, current, enabled,
                   event, fit_telemetry, gauge, list_event_files,
                   obs_dir, obs_max_bytes, phases, run, scoped_run,
                   span)
from .merge import merge_obs_shards
from .trace import trace_capture, trace_dir

__all__ = ["Recorder", "configure", "counter", "current", "devtime",
           "enabled", "event", "fit_telemetry", "flight", "gauge",
           "health", "list_event_files", "memory", "merge_obs_shards",
           "metrics", "obs_dir", "obs_max_bytes", "phases", "quality",
           "run", "scoped_run", "span", "trace_capture", "trace_dir",
           "monitor", "tracing", "usage"]
