"""Multihost obs-shard merge: per-process runs into one report.

A multi-process job (a pod-slice sweep, the survey runner) gives every
process its own recorder — per-process run directories whose contents
are copied into a shared shard directory as::

    <shards>/events.<proc>.jsonl[.N]   # rotated sets kept
    <shards>/manifest.<proc>.json

Process 0 then merges the shards into ONE run directory that
``tools/obs_report.py`` reads like any other (the ROADMAP multihost
metric-aggregation item):

* events are concatenated in timestamp order, each tagged with
  ``proc``; span/compile paths are prefixed ``p<proc>/`` so the phase
  table distinguishes hosts while aggregating names;
* fit telemetry passes through untouched — the report's per-subint
  convergence stats sum over every shard's fit events;
* ``devtime`` events (ingested profiler captures, obs/devtime.py) get
  their ``region`` prefixed ``p<proc>/`` like span paths; the phase
  and scope aggregations still sum across hosts by name;
* manifest counters/gauges are summed (numeric) or kept per-process,
  ``wall_s`` is the max (processes run concurrently), configs merged.
"""

import json
import os
import re

from .core import list_event_files

__all__ = ["write_shard", "merge_obs_shards", "list_shards"]

_SHARD_RE = re.compile(r"^events\.(\d+)\.jsonl(?:\.(\d+))?$")


def write_shard(run_dir, shards_dir, proc):
    """Copy a closed per-process run into the shared shard layout.

    Rotated event files keep their rotation index; the manifest is
    copied as ``manifest.<proc>.json``.  Returns the list of files
    written.
    """
    os.makedirs(shards_dir, exist_ok=True)
    written = []
    for src in list_event_files(run_dir):
        base = os.path.basename(src)          # events.jsonl[.N]
        suffix = base[len("events.jsonl"):]   # "" or ".N"
        dst = os.path.join(shards_dir,
                           "events.%d.jsonl%s" % (proc, suffix))
        with open(src, "rb") as sf, open(dst, "wb") as df:
            df.write(sf.read())
        written.append(dst)
    for base, pattern in (("manifest.json", "manifest.%d.json"),
                          ("metrics.jsonl", "metrics.%d.jsonl")):
        src = os.path.join(run_dir, base)
        if os.path.isfile(src):
            dst = os.path.join(shards_dir, pattern % proc)
            with open(src, "rb") as sf, open(dst, "wb") as df:
                df.write(sf.read())
            written.append(dst)
    return written


def list_shards(shards_dir):
    """{proc: [event files oldest-first]} found under ``shards_dir``."""
    shards = {}
    try:
        names = os.listdir(shards_dir)
    except OSError:
        return shards
    for name in names:
        m = _SHARD_RE.match(name)
        if not m:
            continue
        proc = int(m.group(1))
        rot = int(m.group(2)) if m.group(2) else None
        shards.setdefault(proc, []).append((rot, name))
    out = {}
    for proc, files in shards.items():
        # rotated files (oldest = .1) before the live (unsuffixed) file
        rotated = sorted((r, n) for r, n in files if r is not None)
        live = [n for r, n in files if r is None]
        out[proc] = [os.path.join(shards_dir, n)
                     for _, n in rotated] + \
                    [os.path.join(shards_dir, n) for n in live]
    return out


def _read_events(path):
    events = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # torn tail line from a crashed shard
    except OSError:
        pass
    return events


def merge_obs_shards(shards_dir, out_dir):
    """Merge every ``events.<proc>.jsonl`` shard set (+ manifests)
    under ``shards_dir`` into one obs run at ``out_dir``.

    Returns ``out_dir``; raises FileNotFoundError when no shards
    exist.  Idempotent: re-merging overwrites the previous merge.
    """
    shards = list_shards(shards_dir)
    if not shards:
        raise FileNotFoundError(f"no obs shards under {shards_dir}")
    os.makedirs(out_dir, exist_ok=True)

    merged = []
    for proc in sorted(shards):
        for path in shards[proc]:
            for ev in _read_events(path):
                ev["proc"] = proc
                if ev.get("kind") in ("span", "compile"):
                    for field in ("path", "span"):
                        if ev.get(field):
                            ev[field] = "p%d/%s" % (proc, ev[field])
                elif ev.get("kind") == "devtime" and ev.get("region"):
                    # keep per-host capture regions distinguishable;
                    # the phase/scope aggregations (obs_report's
                    # device column) still sum across hosts by name
                    ev["region"] = "p%d/%s" % (proc, ev["region"])
                merged.append(ev)
    merged.sort(key=lambda e: e.get("t", 0.0))
    with open(os.path.join(out_dir, "events.jsonl"), "w",
              encoding="utf-8") as fh:
        for ev in merged:
            fh.write(json.dumps(ev) + "\n")

    # metrics snapshots (obs/metrics.py): the LAST parseable snapshot
    # of every shard's metrics.<proc>.jsonl merges exactly — integer
    # bucket sums over identical log-bucket edges, shard-order
    # independent — into one metrics.jsonl line the report's latency
    # section reads like any single-process run's
    from . import metrics as _metrics

    shard_snaps = {}
    for proc in sorted(shards):
        mpath = os.path.join(shards_dir, "metrics.%d.jsonl" % proc)
        snaps = [s for s in _read_events(mpath)
                 if isinstance(s, dict)
                 and (s.get("histograms") is not None
                      or s.get("counters") is not None)]
        if snaps:
            shard_snaps[proc] = snaps[-1]
    if shard_snaps:
        merged_snap = _metrics.merge_snapshots(shard_snaps)
        with open(os.path.join(out_dir, "metrics.jsonl"), "w",
                  encoding="utf-8") as fh:
            fh.write(json.dumps(merged_snap) + "\n")

    manifests = {}
    for proc in sorted(shards):
        mpath = os.path.join(shards_dir, "manifest.%d.json" % proc)
        if os.path.isfile(mpath):
            try:
                with open(mpath, encoding="utf-8") as fh:
                    manifests[proc] = json.load(fh)
            except (OSError, json.JSONDecodeError):
                pass

    counters = {}
    gauges = {}
    config = {}
    wall = 0.0
    compile_total = 0.0
    for proc in sorted(manifests):
        m = manifests[proc]
        for k, v in (m.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[k] = counters.get(k, 0) + v
        for k, v in (m.get("gauges") or {}).items():
            gauges["p%d/%s" % (proc, k)] = v
        config.update(m.get("config") or {})
        wall = max(wall, float(m.get("wall_s", 0.0) or 0.0))
        compile_total += float(m.get("compile_total_s", 0.0) or 0.0)
    base = manifests.get(min(manifests), {}) if manifests else {}
    out_manifest = {
        "schema": "pptpu-obs-v1",
        "name": str(base.get("name", "merged")) + "-merged",
        "run_id": os.path.basename(os.path.normpath(out_dir)),
        "merged_from": sorted(shards),
        "n_processes": len(shards),
        "platform": base.get("platform"),
        "device_count": base.get("device_count"),
        "jax_version": base.get("jax_version"),
        "git_sha": base.get("git_sha"),
        "t_start": min((m.get("t_start", 0.0) for m in
                        manifests.values()), default=0.0),
        "config": config,
        "counters": counters,
        "gauges": gauges,
        "wall_s": wall,
        "compile_total_s": round(compile_total, 6),
        "n_events": len(merged),
    }
    tmp = os.path.join(out_dir, "manifest.json.tmp")
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(out_manifest, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, os.path.join(out_dir, "manifest.json"))
    return out_dir
