"""Multihost obs-shard merge: per-process runs into one report.

A multi-process job (a pod-slice sweep, the survey runner) gives every
process its own recorder — per-process run directories whose contents
are copied into a shared shard directory as::

    <shards>/events.<proc>.jsonl[.N]   # rotated sets kept
    <shards>/manifest.<proc>.json

Process 0 then merges the shards into ONE run directory that
``tools/obs_report.py`` reads like any other (the ROADMAP multihost
metric-aggregation item):

* events are concatenated in timestamp order, each tagged with
  ``proc``; span/compile paths are prefixed ``p<proc>/`` so the phase
  table distinguishes hosts while aggregating names;
* fit telemetry passes through untouched — the report's per-subint
  convergence stats sum over every shard's fit events;
* ``devtime`` events (ingested profiler captures, obs/devtime.py) get
  their ``region`` prefixed ``p<proc>/`` like span paths; the phase
  and scope aggregations still sum across hosts by name;
* manifest counters/gauges are summed (numeric) or kept per-process,
  ``wall_s`` is the max (processes run concurrently), configs merged;
* usage ledgers (``usage.<proc>.jsonl``, obs/usage.py) concatenate —
  records are self-contained and rollups are pure sums, so the merged
  per-tenant totals are exact and order-independent.
"""

import json
import os
import re

from .core import list_event_files

__all__ = ["write_shard", "merge_obs_shards", "list_shards"]

_SHARD_RE = re.compile(r"^events\.(\d+)\.jsonl(?:\.(\d+))?$")


def _advance_shard(shards_dir, proc):
    """Rotate an existing shard set aside before publishing a new run.

    A workdir that chains sequential runs (the workload engine's
    zap→align→toas surveys share one workdir) calls ``write_shard``
    once per run with the same ``proc``; without rotation each call
    would overwrite the previous run's shard and the merged report
    would only show the last workload.  The previous live files move
    to the next free rotation index (rotation order is oldest-first,
    matching in-run size rotation), and the caller offsets the new
    run's own rotated files past them.  Returns the base index for
    the new run's rotated files.
    """
    live = os.path.join(shards_dir, "events.%d.jsonl" % proc)
    max_rot = 0
    try:
        for name in os.listdir(shards_dir):
            m = _SHARD_RE.match(name)
            if m and int(m.group(1)) == proc and m.group(2):
                max_rot = max(max_rot, int(m.group(2)))
    except OSError:
        pass
    if not os.path.isfile(live):
        return max_rot
    base = max_rot + 1
    os.replace(live, live + ".%d" % base)
    for name in ("manifest.%d.json" % proc,
                 "metrics.%d.jsonl" % proc,
                 "usage.%d.jsonl" % proc):
        src = os.path.join(shards_dir, name)
        if os.path.isfile(src):
            os.replace(src, src + ".%d" % base)
    return base


def _rotated_paths(shards_dir, base_name):
    """[oldest-first rotated copies..., live] for a shard-side file
    (``manifest.<proc>.json``, ``metrics.<proc>.jsonl``)."""
    out = []
    try:
        names = os.listdir(shards_dir)
    except OSError:
        names = []
    rot = []
    for name in names:
        if name.startswith(base_name + "."):
            tail = name[len(base_name) + 1:]
            if tail.isdigit():
                rot.append((int(tail), name))
    out.extend(os.path.join(shards_dir, n) for _, n in sorted(rot))
    live = os.path.join(shards_dir, base_name)
    if os.path.isfile(live):
        out.append(live)
    return out


def write_shard(run_dir, shards_dir, proc):
    """Copy a closed per-process run into the shared shard layout.

    Rotated event files keep their rotation index; the manifest is
    copied as ``manifest.<proc>.json``.  A shard already present for
    ``proc`` (an earlier sequential run in the same workdir, e.g. a
    prior workload pass) is advanced to rotated copies first, so
    chained runs accumulate instead of overwriting.  Returns the list
    of files written.
    """
    os.makedirs(shards_dir, exist_ok=True)
    base = _advance_shard(shards_dir, proc)
    written = []
    for src in list_event_files(run_dir):
        name = os.path.basename(src)          # events.jsonl[.N]
        suffix = name[len("events.jsonl"):]   # "" or ".N"
        if suffix and base:
            # keep global oldest-first order: this run's own rotation
            # indices shift past the previous runs' copies
            suffix = ".%d" % (base + int(suffix[1:]))
        dst = os.path.join(shards_dir,
                           "events.%d.jsonl%s" % (proc, suffix))
        with open(src, "rb") as sf, open(dst, "wb") as df:
            df.write(sf.read())
        written.append(dst)
    for name, pattern in (("manifest.json", "manifest.%d.json"),
                          ("metrics.jsonl", "metrics.%d.jsonl")):
        src = os.path.join(run_dir, name)
        if os.path.isfile(src):
            dst = os.path.join(shards_dir, pattern % proc)
            with open(src, "rb") as sf, open(dst, "wb") as df:
                df.write(sf.read())
            written.append(dst)
    # the usage ledger (obs/usage.py): records are order-independent,
    # so the run's rotated chain concatenates into ONE shard file —
    # no rotation-index bookkeeping to collide with the event set
    from .usage import usage_files

    srcs = usage_files(run_dir)
    if srcs:
        dst = os.path.join(shards_dir, "usage.%d.jsonl" % proc)
        with open(dst, "wb") as df:
            for src in srcs:
                with open(src, "rb") as sf:
                    df.write(sf.read())
        written.append(dst)
    return written


def list_shards(shards_dir):
    """{proc: [event files oldest-first]} found under ``shards_dir``."""
    shards = {}
    try:
        names = os.listdir(shards_dir)
    except OSError:
        return shards
    for name in names:
        m = _SHARD_RE.match(name)
        if not m:
            continue
        proc = int(m.group(1))
        rot = int(m.group(2)) if m.group(2) else None
        shards.setdefault(proc, []).append((rot, name))
    out = {}
    for proc, files in shards.items():
        # rotated files (oldest = .1) before the live (unsuffixed) file
        rotated = sorted((r, n) for r, n in files if r is not None)
        live = [n for r, n in files if r is None]
        out[proc] = [os.path.join(shards_dir, n)
                     for _, n in rotated] + \
                    [os.path.join(shards_dir, n) for n in live]
    return out


def _fold_snapshots(snaps):
    """Fold ONE process's sequential-run metrics snapshots into one:
    counters and histograms sum by identical series key (exact integer
    bucket sums), gauges last-write-wins with NO process prefix —
    unlike :func:`..metrics.merge_snapshots`, which is for DIFFERENT
    processes."""
    from .metrics import Histogram

    if len(snaps) == 1:
        return snaps[0]
    counters = {}
    gauges = {}
    hists = {}
    t = 0.0
    for s in snaps:
        t = max(t, float(s.get("t", 0.0) or 0.0))
        for k, v in (s.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[k] = counters.get(k, 0) + v
        gauges.update(s.get("gauges") or {})
        for k, h in (s.get("histograms") or {}).items():
            cur = hists.get(k)
            if cur is None:
                hists[k] = Histogram.from_snapshot(h)
            else:
                cur.merge(Histogram.from_snapshot(h))
    out = dict(snaps[-1])
    out.update(t=t, counters=counters, gauges=gauges,
               histograms={k: h.to_snapshot()
                           for k, h in sorted(hists.items())})
    return out


def _fold_manifests(docs):
    """Fold ONE process's sequential-run manifests (oldest-first) into
    one: counters/walls/compile totals sum, configs and gauges update
    newest-wins, identity fields (platform, git SHA, name) come from
    the newest run, ``t_start`` from the oldest."""
    if len(docs) == 1:
        return docs[0]
    out = dict(docs[-1])
    counters = {}
    gauges = {}
    config = {}
    wall = 0.0
    compile_total = 0.0
    t_start = None
    for m in docs:
        for k, v in (m.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[k] = counters.get(k, 0) + v
        gauges.update(m.get("gauges") or {})
        config.update(m.get("config") or {})
        wall += float(m.get("wall_s", 0.0) or 0.0)
        compile_total += float(m.get("compile_total_s", 0.0) or 0.0)
        ts = m.get("t_start")
        if ts is not None:
            t_start = ts if t_start is None else min(t_start, ts)
    out.update(counters=counters, gauges=gauges, config=config,
               wall_s=wall, compile_total_s=round(compile_total, 6))
    if t_start is not None:
        out["t_start"] = t_start
    return out


def _read_events(path):
    events = []
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # torn tail line from a crashed shard
    except OSError:
        pass
    return events


def merge_obs_shards(shards_dir, out_dir):
    """Merge every ``events.<proc>.jsonl`` shard set (+ manifests)
    under ``shards_dir`` into one obs run at ``out_dir``.

    Returns ``out_dir``; raises FileNotFoundError when no shards
    exist.  Idempotent: re-merging overwrites the previous merge.
    """
    shards = list_shards(shards_dir)
    if not shards:
        raise FileNotFoundError(f"no obs shards under {shards_dir}")
    os.makedirs(out_dir, exist_ok=True)

    merged = []
    for proc in sorted(shards):
        for path in shards[proc]:
            for ev in _read_events(path):
                ev["proc"] = proc
                if ev.get("kind") in ("span", "compile"):
                    for field in ("path", "span"):
                        if ev.get(field):
                            ev[field] = "p%d/%s" % (proc, ev[field])
                elif ev.get("kind") == "devtime" and ev.get("region"):
                    # keep per-host capture regions distinguishable;
                    # the phase/scope aggregations (obs_report's
                    # device column) still sum across hosts by name
                    ev["region"] = "p%d/%s" % (proc, ev["region"])
                merged.append(ev)
    merged.sort(key=lambda e: e.get("t", 0.0))
    with open(os.path.join(out_dir, "events.jsonl"), "w",
              encoding="utf-8") as fh:
        for ev in merged:
            fh.write(json.dumps(ev) + "\n")

    # metrics snapshots (obs/metrics.py): the LAST parseable snapshot
    # of every shard's metrics.<proc>.jsonl merges exactly — integer
    # bucket sums over identical log-bucket edges, shard-order
    # independent — into one metrics.jsonl line the report's latency
    # section reads like any single-process run's
    from . import metrics as _metrics

    shard_snaps = {}
    for proc in sorted(shards):
        # one last-snapshot per metrics file: the live copy plus any
        # rotated copies from earlier chained runs, folded WITHOUT the
        # per-process gauge prefix (they are all this proc's)
        per_run = []
        for mpath in _rotated_paths(shards_dir,
                                    "metrics.%d.jsonl" % proc):
            snaps = [s for s in _read_events(mpath)
                     if isinstance(s, dict)
                     and (s.get("histograms") is not None
                          or s.get("counters") is not None)]
            if snaps:
                per_run.append(snaps[-1])
        if per_run:
            shard_snaps[proc] = _fold_snapshots(per_run)
    if shard_snaps:
        merged_snap = _metrics.merge_snapshots(shard_snaps)
        with open(os.path.join(out_dir, "metrics.jsonl"), "w",
                  encoding="utf-8") as fh:
            fh.write(json.dumps(merged_snap) + "\n")

    # usage ledgers (obs/usage.py): records are self-contained and
    # rollups are pure sums, so the merge is concatenation — tagged
    # with ``proc`` and time-sorted for readability, exact either way
    usage = []
    for proc in sorted(shards):
        for upath in _rotated_paths(shards_dir,
                                    "usage.%d.jsonl" % proc):
            for rec in _read_events(upath):
                if isinstance(rec, dict):
                    rec["proc"] = proc
                    usage.append(rec)
    if usage:
        usage.sort(key=lambda r: r.get("t", 0.0))
        with open(os.path.join(out_dir, "usage.jsonl"), "w",
                  encoding="utf-8") as fh:
            for rec in usage:
                fh.write(json.dumps(rec) + "\n")

    manifests = {}
    for proc in sorted(shards):
        # fold this proc's manifests oldest-first: rotated copies from
        # earlier chained runs, then the live one.  Counters and walls
        # sum (the runs were sequential), configs/gauges update so the
        # newest run wins, identity fields come from the newest run.
        docs = []
        for mpath in _rotated_paths(shards_dir,
                                    "manifest.%d.json" % proc):
            try:
                with open(mpath, encoding="utf-8") as fh:
                    docs.append(json.load(fh))
            except (OSError, json.JSONDecodeError):
                pass
        if docs:
            manifests[proc] = _fold_manifests(docs)

    counters = {}
    gauges = {}
    config = {}
    wall = 0.0
    compile_total = 0.0
    for proc in sorted(manifests):
        m = manifests[proc]
        for k, v in (m.get("counters") or {}).items():
            if isinstance(v, (int, float)):
                counters[k] = counters.get(k, 0) + v
        for k, v in (m.get("gauges") or {}).items():
            gauges["p%d/%s" % (proc, k)] = v
        config.update(m.get("config") or {})
        wall = max(wall, float(m.get("wall_s", 0.0) or 0.0))
        compile_total += float(m.get("compile_total_s", 0.0) or 0.0)
    base = manifests.get(min(manifests), {}) if manifests else {}
    out_manifest = {
        "schema": "pptpu-obs-v1",
        "name": str(base.get("name", "merged")) + "-merged",
        "run_id": os.path.basename(os.path.normpath(out_dir)),
        "merged_from": sorted(shards),
        "n_processes": len(shards),
        "platform": base.get("platform"),
        "device_count": base.get("device_count"),
        "jax_version": base.get("jax_version"),
        "git_sha": base.get("git_sha"),
        "t_start": min((m.get("t_start", 0.0) for m in
                        manifests.values()), default=0.0),
        "config": config,
        "counters": counters,
        "gauges": gauges,
        "wall_s": wall,
        "compile_total_s": round(compile_total, 6),
        "n_events": len(merged),
    }
    tmp = os.path.join(out_dir, "manifest.json.tmp")
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(out_manifest, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, os.path.join(out_dir, "manifest.json"))
    return out_dir
