"""Device-time attribution: profiler captures -> per-stage device seconds.

The wall-clock spans of :mod:`.core` stop at the jit boundary: the
hybrid fit's coarse-vs-polish split (and the scattering kernel inside
each) lives inside ONE compiled program, so the phase table could only
show "solve took N s" without saying where the device spent it.  This
module closes that gap by parsing the artifacts ``jax.profiler`` drops
under ``$PPTPU_TRACE_DIR/<region>/plugins/profile/<session>/``:

* ``*.xplane.pb`` — the raw profiler protobuf, the PRIMARY source.
  Its op planes carry every executed op as an XEvent with
  ``hlo_module``/``hlo_op`` stats and picosecond timing, and its
  ``/host:metadata`` plane embeds each executed program's HloProto,
  whose per-instruction ``metadata.op_name`` carries the
  ``jax.named_scope`` path
  (``jit(fit)/.../pp_coarse/while/body/dot_general``).  A ~100-line
  protobuf wire reader extracts exactly that — no
  tensorflow/tensorboard dependency, and the python-tracer lines
  (hundreds of thousands of host frames when a compile happens inside
  the capture) are skipped whole at the line level, which the
  length-delimited wire format makes free.
* ``*.trace.json.gz`` — the Chrome-trace event stream, the FALLBACK
  when no xplane sits next to it.  Same op rows via
  ``args.hlo_module``/``args.hlo_op``, but jax caps the conversion at
  ~1e6 events and host frames count against the cap, so a capture
  containing a compile can silently lose its op rows there (exactly
  how this parser's xplane-first policy was motivated).

Container rows (``jit_*`` program rows, ``while``-loop rows) CONTAIN
their children in both formats, so durations are reduced to SELF time
via per-track interval nesting before they are summed — rows then
partition device time exactly (the double-count the legacy
tools/trace_summary.py could only warn about).

Attribution contract: the solver annotates its stages with
``jax.named_scope`` names starting with ``pp_`` (fit/portrait.py:
``pp_seed``/``pp_coarse``/``pp_solve``/``pp_polish``;
ops/scattering.py: ``pp_scatter``).  An op's scope path is the ordered
list of ``pp_*`` segments in its ``op_name``; its pipeline *phase* is
the :data:`SCOPE_PHASES` entry of the outermost scope.  Ops without a
``pp_*`` scope (data prep, padding, transfers) count toward the device
total as ``unattributed``.  ``device <= wall`` need not hold per phase
on a multi-threaded backend (device-seconds sum over parallel
executors); see docs/OBSERVABILITY.md for the full semantics.

Everything here is host-side file parsing — never call it inside
traced code (jaxlint J002 flags ``obs.devtime.*`` in jit).
"""

import glob
import gzip
import json
import os
import re
import struct

from . import core

__all__ = ["SCOPE_PREFIX", "SCOPE_PHASES", "find_capture",
           "parse_chrome_trace", "self_times", "parse_xplane",
           "parse_xplane_scopes", "parse_xplane_memory", "scopes_of",
           "summarize_region", "summarize_trace_dir", "record_devtime"]

# named-scope convention: any scope segment starting with this prefix
# is an attribution scope (everything else in the op_name path —
# jit(...)/while/body/transpose machinery — is ignored)
SCOPE_PREFIX = "pp_"

# outermost scope -> pipeline phase (the span names GetTOAs emits), so
# the phase table can carry a device column next to the wall column
SCOPE_PHASES = {
    "pp_seed": "guess",      # in-graph FFTFIT phase seeding
    "pp_coarse": "solve",    # hybrid f32 coarse-search stage
    "pp_solve": "solve",     # single-stage (non-hybrid) solve
    "pp_polish": "polish",   # f64 polish + covariance/nu-zero finish
    "pp_scatter": "solve",   # scattering kernel reached outside a stage
}


# -- capture discovery ----------------------------------------------------

def find_capture(region_dir):
    """(trace_json_gz_path, xplane_pb_path) of the NEWEST profiler
    session under ``region_dir`` (either may be None).

    ``jax.profiler`` writes each start/stop pair into a fresh
    ``plugins/profile/<timestamp>/`` session directory; re-capturing a
    region appends sessions, and the newest is the one the enclosing
    span just timed.
    """
    sessions = {}
    for path in glob.glob(os.path.join(
            region_dir, "**", "*.trace.json.gz"), recursive=True):
        sessions.setdefault(os.path.dirname(path), {})["trace"] = path
    for path in glob.glob(os.path.join(
            region_dir, "**", "*.xplane.pb"), recursive=True):
        sessions.setdefault(os.path.dirname(path), {})["xplane"] = path
    if not sessions:
        return None, None
    newest = max(sessions)  # timestamped dir names sort chronologically
    return sessions[newest].get("trace"), sessions[newest].get("xplane")


# -- Chrome-trace side ----------------------------------------------------

def parse_chrome_trace(path):
    """Complete (``ph == "X"``) events of a ``*.trace.json[.gz]``.

    Returns dicts with ``pid``/``tid``/``ts``/``dur`` (microseconds)
    /``name`` plus ``module``/``op`` when the row is an XLA op
    (``args.hlo_module``/``args.hlo_op``); rows without an ``hlo_op``
    are host frames or executor scaffolding.
    """
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8") as fh:
        doc = json.load(fh)
    out = []
    for e in doc.get("traceEvents", []):
        if e.get("ph") != "X":
            continue
        try:
            ts = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        args = e.get("args") or {}
        out.append({"pid": e.get("pid"), "tid": e.get("tid"),
                    "ts": ts, "dur": dur,
                    "name": e.get("name", ""),
                    "module": _strip_program_id(args.get("hlo_module")),
                    "op": args.get("hlo_op")})
    return out


def self_times(events):
    """Annotate each event with ``self`` = dur minus nested children.

    Chrome-trace rows nest on a (pid, tid) track: a program row spans
    its ops, a ``while`` row spans every iteration's body ops.  Summing
    raw ``dur`` double-counts those containers; self time partitions
    each track's busy time exactly.  Mutates and returns ``events``.
    """
    tracks = {}
    for e in events:
        tracks.setdefault((e["pid"], e["tid"]), []).append(e)
    for track in tracks.values():
        # parents first at equal start times (longer duration first)
        track.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []  # open events, innermost last
        for e in track:
            e["self"] = e["dur"]
            while stack and e["ts"] >= stack[-1]["ts"] + stack[-1]["dur"]:
                stack.pop()
            if stack:
                stack[-1]["self"] -= e["dur"]
            stack.append(e)
    return events


def _strip_program_id(name):
    """'jit_fit(5)' -> 'jit_fit' (the Chrome trace and the xplane
    metadata plane disagree about the program-id suffix)."""
    if not name:
        return name
    if name.endswith(")") and "(" in name:
        return name[:name.rindex("(")]
    return name


# -- xplane side: a minimal protobuf wire reader --------------------------
#
# Only length-delimited traversal is needed.  Field numbers follow
# xplane.proto (XSpace.planes=1; XPlane.name=2/lines=3/
# event_metadata=4/stat_metadata=5; XLine.name=2/timestamp_ns=3/
# events=4; XEvent.metadata_id=1/offset_ps=2/duration_ps=3/stats=4;
# XStat.metadata_id=1/str_value=5/bytes_value=6/ref_value=7) and
# hlo.proto (HloProto.hlo_module=1; module.computations=3;
# computation.instructions=2; instruction.name=1/metadata=7;
# OpMetadata.op_name=2).  Unknown fields are skipped by wire type, so
# schema additions degrade gracefully.

def _fields(buf):
    """(field_number, wire_type, value) triples of one message."""
    i, n, out = 0, len(buf), []
    while i < n:
        tag, i = _varint(buf, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(buf, i)
        elif wt == 2:
            ln, i = _varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = buf[i:i + 4]
            i += 4
        elif wt == 1:
            v = buf[i:i + 8]
            i += 8
        else:  # groups (3/4): not produced by these schemas
            raise ValueError("unsupported wire type %d" % wt)
        out.append((fn, wt, v))
    return out


def _varint(buf, i):
    x = s = 0
    while True:
        b = buf[i]
        i += 1
        x |= (b & 0x7F) << s
        if not b & 0x80:
            return x, i
        s += 7


def _sub(fields, n):
    return [v for f, _, v in fields if f == n]


def _hlo_op_names(hlo_proto):
    """{instruction name: metadata.op_name} across every computation of
    one embedded HloProto (instruction names are module-unique)."""
    out = {}
    for module in _sub(_fields(hlo_proto), 1):        # HloProto.hlo_module
        for comp in _sub(_fields(module), 3):         # .computations
            for inst in _sub(_fields(comp), 2):       # .instructions
                inf = _fields(inst)
                names = _sub(inf, 1)                  # .name
                metas = _sub(inf, 7)                  # .metadata
                if not names or not metas:
                    continue
                op_names = _sub(_fields(metas[0]), 2)  # OpMetadata.op_name
                if op_names:
                    try:
                        out[names[0].decode()] = op_names[0].decode()
                    except UnicodeDecodeError:
                        pass
    return out


def _plane_scopes(pf, out):
    """Fold a metadata plane's embedded HloProtos into the
    {(module, instruction): op_name} scope map ``out``."""
    for entry in _sub(pf, 4):                     # .event_metadata{}
        for em in _sub(_fields(entry), 2):        # map value
            emf = _fields(em)
            mod_names = _sub(emf, 2)              # XEventMetadata.name
            if not mod_names:
                continue
            module = _strip_program_id(mod_names[0].decode())
            for stat in _sub(emf, 5):             # .stats
                for blob in _sub(_fields(stat), 6):  # bytes_value
                    for inst, op_name in _hlo_op_names(blob).items():
                        out[(module, inst)] = op_name


def _plane_op_events(pf, plane_name, out):
    """Append one op plane's XEvents (those carrying hlo stats) to
    ``out`` as parse_chrome_trace-shaped dicts (times in us)."""
    stat_names = {}                               # stat metadata id->name
    for entry in _sub(pf, 5):                     # .stat_metadata{}
        for sm in _sub(_fields(entry), 2):
            smf = _fields(sm)
            ids, names = _sub(smf, 1), _sub(smf, 2)
            if ids and names:
                try:
                    stat_names[ids[0]] = names[0].decode()
                except UnicodeDecodeError:
                    pass
    hlo_op_ids = {i for i, n in stat_names.items() if n == "hlo_op"}
    hlo_mod_ids = {i for i, n in stat_names.items()
                   if n == "hlo_module"}
    if not hlo_op_ids:
        return  # no XLA ops on this plane (python tracer, task env)
    event_names = {}                              # event metadata id->name
    for entry in _sub(pf, 4):                     # .event_metadata{}
        for em in _sub(_fields(entry), 2):
            emf = _fields(em)
            ids, names = _sub(emf, 1), _sub(emf, 2)
            if ids and names:
                try:
                    event_names[ids[0]] = names[0].decode()
                except UnicodeDecodeError:
                    pass
    for line_buf in _sub(pf, 3):                  # XPlane.lines
        lf = _fields(line_buf)
        lnames = _sub(lf, 2)                      # XLine.name
        lname = ""
        if lnames and isinstance(lnames[0], bytes):
            try:
                lname = lnames[0].decode()
            except UnicodeDecodeError:
                pass
        if lname == "python":
            continue  # host python tracer: no ops, possibly 1e6 rows
        ts0_ns = 0
        for v in _sub(lf, 3):                     # .timestamp_ns
            if isinstance(v, int):
                ts0_ns = v
        line_id = _sub(lf, 1)
        tid = "%s/%s" % (line_id[0] if line_id else 0, lname)
        for ev_buf in _sub(lf, 4):                # .events
            ef = _fields(ev_buf)
            op = module = None
            for stat_buf in _sub(ef, 4):          # XEvent.stats
                sf = _fields(stat_buf)
                mids = _sub(sf, 1)
                if not mids:
                    continue
                val = None
                strs = _sub(sf, 5)                # str_value
                refs = _sub(sf, 7)                # ref_value
                if strs and isinstance(strs[0], bytes):
                    try:
                        val = strs[0].decode()
                    except UnicodeDecodeError:
                        val = None
                elif refs:
                    val = stat_names.get(refs[0])
                if val is None:
                    continue
                if mids[0] in hlo_op_ids:
                    op = val
                elif mids[0] in hlo_mod_ids:
                    module = val
            if op is None:
                continue
            mid = _sub(ef, 1)                     # .metadata_id
            name = event_names.get(mid[0], "") if mid else ""
            off_ps = _sub(ef, 2)                  # .offset_ps
            dur_ps = _sub(ef, 3)                  # .duration_ps
            ts_us = ts0_ns / 1e3 + (off_ps[0] / 1e6 if off_ps else 0.0)
            out.append({"pid": plane_name, "tid": tid, "ts": ts_us,
                        "dur": (dur_ps[0] / 1e6 if dur_ps else 0.0),
                        "name": name,
                        "module": _strip_program_id(module),
                        "op": op})


def parse_xplane(path):
    """(op_events, scope_map) of one ``*.xplane.pb``.

    ``op_events`` are parse_chrome_trace-shaped dicts for every XEvent
    carrying an ``hlo_op`` stat — unlike the Chrome-trace conversion
    these are NOT subject to jax's ~1e6-event cap, so a capture whose
    JSON drowned in python-tracer frames still attributes fully.
    ``scope_map`` maps (module, instruction) to the named-scope
    ``op_name``.  Tolerates a missing/corrupt file by returning empty
    results.
    """
    try:
        with open(path, "rb") as fh:
            buf = fh.read()
    except OSError:
        return [], {}
    events = []
    scopes = {}
    try:
        for plane_buf in _sub(_fields(buf), 1):   # XSpace.planes
            pf = _fields(plane_buf)
            names = _sub(pf, 2)                   # XPlane.name
            pname = names[0].decode() if names else ""
            if pname.endswith(":metadata"):
                _plane_scopes(pf, scopes)
            else:
                _plane_op_events(pf, pname, events)
    except (ValueError, IndexError, UnicodeDecodeError):
        pass  # torn/foreign protobuf: degrade to what was parsed
    return events, scopes


def parse_xplane_scopes(path):
    """{(module, instruction): op_name} — the named-scope source of
    truth (see :func:`parse_xplane`)."""
    return parse_xplane(path)[1]


# -- xplane memory ingestion ----------------------------------------------
#
# Allocator activity lands in the xplane as XEvents whose stats carry
# the BFC/TPU allocator gauges (watermark stats below) plus, on
# allocation rows, the requesting op ("tf_op" — a named-scope path on
# jax programs).  CPU captures typically carry none of these; the
# parser then returns None and every consumer degrades to absent.

# point-in-time watermark stats: a capture's memory peak is their max
_MEM_WATERMARK_STATS = frozenset((
    "peak_bytes_in_use", "bytes_in_use", "bytes_reserved",
    "heap_allocated_bytes", "stack_reserved_bytes"))
# per-allocation size stats: summed per pp_* scope for attribution
_MEM_ALLOC_STATS = frozenset((
    "allocation_bytes", "requested_bytes", "bytes_allocated"))
_MEM_STAT_NAMES = _MEM_WATERMARK_STATS | _MEM_ALLOC_STATS


def _stat_scalar(wt, v):
    """Numeric value of one XStat payload field (int64/uint64 varints
    arrive decoded; double_value is 8 raw bytes), or None."""
    if wt == 0 and isinstance(v, int):
        return v
    if wt == 1 and isinstance(v, bytes) and len(v) == 8:
        return struct.unpack("<d", v)[0]
    return None


def _plane_memory(pf, agg):
    """Fold one plane's memory-carrying XEvents into ``agg``."""
    stat_names = {}                               # stat metadata id->name
    for entry in _sub(pf, 5):                     # .stat_metadata{}
        for sm in _sub(_fields(entry), 2):
            smf = _fields(sm)
            ids, names = _sub(smf, 1), _sub(smf, 2)
            if ids and names:
                try:
                    stat_names[ids[0]] = names[0].decode()
                except UnicodeDecodeError:
                    pass
    mem_ids = {i: n for i, n in stat_names.items()
               if n in _MEM_STAT_NAMES}
    if not mem_ids:
        return  # plane carries no allocator stats (CPU, python tracer)
    op_ids = {i for i, n in stat_names.items() if n == "tf_op"}
    for line_buf in _sub(pf, 3):                  # XPlane.lines
        lf = _fields(line_buf)
        for ev_buf in _sub(lf, 4):                # XLine.events
            ef = _fields(ev_buf)
            vals = {}
            op_name = None
            for stat_buf in _sub(ef, 4):          # XEvent.stats
                for fn, wt, v in _fields(stat_buf):
                    if fn == 1 and wt == 0:       # XStat.metadata_id
                        sid = v
                        break
                else:
                    continue
                name = mem_ids.get(sid)
                for fn, wt, v in _fields(stat_buf):
                    if name and fn in (2, 3, 4):  # int64/uint64/double
                        num = _stat_scalar(0 if fn != 4 else 1, v)
                        if num is not None:
                            vals[name] = int(num)
                    elif sid in op_ids and fn == 5 \
                            and isinstance(v, bytes):  # str_value
                        try:
                            op_name = v.decode()
                        except UnicodeDecodeError:
                            pass
            if not vals:
                continue
            agg["n_events"] += 1
            for name in _MEM_WATERMARK_STATS:
                got = vals.get(name)
                if got is not None and got > agg["watermarks"].get(
                        name, 0):
                    agg["watermarks"][name] = got
            alloc = max((vals.get(n, 0) for n in _MEM_ALLOC_STATS),
                        default=0)
            if alloc:
                key = "/".join(scopes_of(op_name)) or "unattributed"
                agg["scopes"][key] = agg["scopes"].get(key, 0) + alloc


def parse_xplane_memory(path):
    """Allocator-memory summary of one ``*.xplane.pb``, or None.

    Returns ``{"peak_bytes_in_use", "watermarks": {stat: max},
    "scopes": {pp-scope-path: allocated bytes}, "n_events"}`` when the
    capture carries allocator stats (TPU/GPU backends); None when it
    carries none (CPU captures) or the file is missing/corrupt — the
    same degrade-to-absent contract as :func:`parse_xplane`.
    """
    try:
        with open(path, "rb") as fh:
            buf = fh.read()
    except OSError:
        return None
    agg = {"watermarks": {}, "scopes": {}, "n_events": 0}
    try:
        for plane_buf in _sub(_fields(buf), 1):   # XSpace.planes
            pf = _fields(plane_buf)
            names = _sub(pf, 2)                   # XPlane.name
            pname = names[0].decode() if names else ""
            if not pname.endswith(":metadata"):
                _plane_memory(pf, agg)
    except (ValueError, IndexError, UnicodeDecodeError):
        pass  # torn/foreign protobuf: degrade to what was parsed
    if not agg["n_events"]:
        return None
    wm = agg["watermarks"]
    peak = max([wm.get("peak_bytes_in_use", 0),
                wm.get("bytes_in_use", 0)] or [0])
    return {"peak_bytes_in_use": peak,
            "watermarks": wm,
            "scopes": dict(sorted(agg["scopes"].items(),
                                  key=lambda kv: -kv[1])),
            "n_events": agg["n_events"]}


# a pp_* scope possibly wrapped in transform decorations the lowering
# applies per segment: "pp_coarse", "vmap(pp_coarse)", "jit(pp_x)" ...
_SCOPE_SEG_RE = re.compile(r"\b(%s[A-Za-z0-9_]+)" % SCOPE_PREFIX)


def scopes_of(op_name):
    """Ordered ``pp_*`` scopes of a named-scope path; transform
    decorations are stripped
    ('jit(f)/vmap(pp_coarse)/while/body/pp_scatter/mul' ->
    ['pp_coarse', 'pp_scatter'])."""
    if not op_name:
        return []
    out = []
    for seg in op_name.split("/"):
        m = _SCOPE_SEG_RE.search(seg)
        if m:
            out.append(m.group(1))
    return out


# -- aggregation ----------------------------------------------------------

def summarize_region(region_dir, top=10):
    """Aggregate the newest capture under one region directory.

    Returns None when no capture exists, else a JSON-ready dict::

        {"trace": ..., "device_total_s": ..., "unattributed_s": ...,
         "phases": {"solve": ..., "polish": ...},     # device seconds
         "scopes": {"pp_coarse": ..., "pp_coarse/pp_scatter": ...},
         "top_ops": {...}, "n_ops": ...}

    ``phases`` maps the outermost scope through :data:`SCOPE_PHASES`;
    ``scopes`` keeps the full nested scope path.  All values are
    self-time sums — rows partition device time, so ``scopes`` +
    ``unattributed_s`` == ``device_total_s`` (up to rounding).
    """
    trace_path, xplane_path = find_capture(region_dir)
    if trace_path is None and xplane_path is None:
        return None
    events, scope_map = [], {}
    if xplane_path:
        events, scope_map = parse_xplane(xplane_path)
    if not events and trace_path:
        # xplane absent/unreadable: the (event-capped) Chrome trace
        events = parse_chrome_trace(trace_path)
    events = self_times(events)

    total_us = 0.0
    unattr_us = 0.0
    scopes = {}
    phases = {}
    top_ops = {}
    n_ops = 0
    for e in events:
        if not e["op"]:
            continue  # host frame / executor scaffolding
        n_ops += 1
        dt = e["self"]
        total_us += dt
        op_name = scope_map.get((e["module"], e["op"]), "")
        path = scopes_of(op_name)
        if path:
            key = "/".join(path)
            scopes[key] = scopes.get(key, 0.0) + dt
            phase = SCOPE_PHASES.get(path[0])
            if phase:
                phases[phase] = phases.get(phase, 0.0) + dt
        else:
            unattr_us += dt
        top_ops[e["op"]] = top_ops.get(e["op"], 0.0) + dt

    def s(us):
        return round(us / 1e6, 6)

    top = dict(sorted(top_ops.items(), key=lambda kv: -kv[1])[:top])
    out = {
        "trace": trace_path or xplane_path,
        "device_total_s": s(total_us),
        "unattributed_s": s(unattr_us),
        "phases": {k: s(v) for k, v in sorted(phases.items())},
        "scopes": {k: s(v) for k, v in sorted(scopes.items())},
        "top_ops": {k: s(v) for k, v in top.items()},
        "n_ops": n_ops,
    }
    if xplane_path:
        # allocator-memory ingestion (PR 12): peak HBM + per-scope
        # allocation attribution next to the device seconds; absent
        # (not null) when the capture carries no allocator stats (CPU)
        mem = parse_xplane_memory(xplane_path)
        if mem is not None:
            out["memory"] = mem
    return out


def summarize_trace_dir(trace_root, top=10):
    """{region: summary} for every region directory under a
    ``PPTPU_TRACE_DIR`` root (regions with no capture are skipped)."""
    out = {}
    try:
        names = sorted(os.listdir(trace_root))
    except OSError:
        return out
    for name in names:
        region_dir = os.path.join(trace_root, name)
        if not os.path.isdir(region_dir):
            continue
        summary = summarize_region(region_dir, top=top)
        if summary is not None:
            out[name] = summary
    return out


def record_devtime(region, region_dir):
    """Ingest a just-closed capture and emit one ``devtime`` event into
    the active obs run (:mod:`.trace` calls this after ``stop_trace``).

    Never raises and never emits when no run is active or the capture
    is unreadable — telemetry must not kill the run it observes.  The
    per-run ``device_seconds_total`` counter sums ``device_total_s``
    across regions so the runner can gauge device utilization without
    re-reading its own event stream.
    """
    rec = core.current()
    if rec is None:
        return None
    try:
        summary = summarize_region(region_dir)
    except Exception as e:  # parsing must never be fatal
        rec.emit("event", name="devtime_error", region=region,
                 error=str(e)[:500])
        return None
    if summary is None:
        return None
    rec.emit("devtime", region=region, **summary)
    rec.bump("devtime_regions")
    rec.bump("device_seconds_total", summary["device_total_s"])
    mem = summary.get("memory")
    if mem:
        # run-level capture watermark: the max peak any ingested
        # capture observed (manifest gauge, next to the sampler's)
        prev = rec.gauges.get("capture_peak_bytes_in_use", 0)
        rec.set_gauge("capture_peak_bytes_in_use",
                      max(int(prev or 0), mem["peak_bytes_in_use"]))
        # latest per-scope attribution, kept for OOM forensics
        # (obs.memory.record_oom attaches it to the ``oom`` event)
        rec.memory_scopes = mem.get("scopes") or None
    return summary
