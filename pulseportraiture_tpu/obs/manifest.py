"""Run-manifest assembly: the who/what/where record of one run.

A manifest is the JSON sidecar of a run's event stream: enough static
context (device platform, shapes/dtypes/config the caller passes, git
SHA, argv, relevant PPTPU_* environment) that a committed
``manifest.json`` + ``events.jsonl`` pair is self-describing evidence
— the reader never has to reconstruct "what was this run?" from shell
history, which is exactly how the hand-maintained PERF.md tables used
to decay.

Everything here is best-effort and exception-free: telemetry must
never be the thing that kills a pipeline, so unavailable fields are
recorded as a short error string instead of raised.
"""

import os
import subprocess
import sys
import time

__all__ = ["build_manifest", "git_sha"]

_ENV_KEYS_PREFIX = "PPTPU_"
_ENV_KEYS_EXTRA = ("JAX_PLATFORMS", "XLA_FLAGS")


_GIT_SHA_CACHE = []  # [sha-or-None] once resolved


def git_sha():
    """HEAD commit of the repo this package lives in, or None.

    Memoized after the first lookup: the TOA service opens one run
    (and hence one manifest) per request, and a git subprocess per
    request would dominate small-request latency.  HEAD moving under a
    live process is not a case worth a stale-cache defense.
    """
    if _GIT_SHA_CACHE:
        return _GIT_SHA_CACHE[0]
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    sha = None
    try:
        out = subprocess.run(
            ["git", "-C", root, "rev-parse", "HEAD"],
            capture_output=True, text=True, timeout=10)
        if out.returncode == 0:
            sha = out.stdout.strip()
    except (OSError, subprocess.SubprocessError):
        pass
    _GIT_SHA_CACHE.append(sha)
    return sha


def _device_info():
    """Platform/device facts without *forcing* a backend to initialize
    successfully: a dead accelerator tunnel is itself a fact worth
    recording (cf. bench_common.resolve_devices)."""
    info = {}
    try:
        import jax

        info["jax_version"] = jax.__version__
        try:
            devs = jax.devices()
            info["platform"] = devs[0].platform
            info["device_count"] = len(devs)
            info["device_kind"] = getattr(devs[0], "device_kind", None)
        except RuntimeError as e:  # backend init failure
            info["platform"] = "unavailable"
            info["backend_error"] = str(e).splitlines()[0][:500]
    except Exception as e:  # jax itself unimportable: still record why
        info["jax_error"] = str(e)[:500]
    return info


def build_manifest(name, run_id, config=None):
    """The open-time manifest dict for a run (the recorder rewrites it
    at close with counters/durations merged in)."""
    env = {k: v for k, v in os.environ.items()
           if k.startswith(_ENV_KEYS_PREFIX) or k in _ENV_KEYS_EXTRA}
    m = {
        "schema": "pptpu-obs-v1",
        "name": name,
        "run_id": run_id,
        "t_start": time.time(),
        "argv": list(sys.argv),
        "cwd": os.getcwd(),
        "pid": os.getpid(),
        "git_sha": git_sha(),
        "env": env,
        "config": dict(config or {}),
    }
    m.update(_device_info())
    return m
