"""Opt-in jax.profiler trace capture (PPTPU_TRACE_DIR).

``trace_capture(name)`` wraps a region in a device profiler trace when
the ``PPTPU_TRACE_DIR`` environment variable names a directory, and is
a no-op otherwise.  Profiling through a remote-device tunnel is not
always supported (tools/perf_probe.py records the same caveat), so a
failing profiler start degrades to "no trace, one event recorded"
rather than an exception: telemetry must never kill the run it is
observing.
"""

import contextlib
import os

from . import core

__all__ = ["trace_dir", "trace_capture"]


def trace_dir():
    """$PPTPU_TRACE_DIR, or None when profiler capture is disabled."""
    v = os.environ.get("PPTPU_TRACE_DIR", "").strip()
    return v or None


@contextlib.contextmanager
def trace_capture(name):
    """Capture a jax.profiler trace of the region into
    ``$PPTPU_TRACE_DIR/<name>``; yields the trace path or None.

    Composes with :func:`pulseportraiture_tpu.obs.core.span`: the span
    carries the wall clock, the profiler trace carries the device
    timeline, and the emitted ``trace`` event links the two.
    """
    base = trace_dir()
    if base is None:
        yield None
        return
    path = os.path.join(base, name)
    import jax

    started = False
    try:
        jax.profiler.start_trace(path)
        started = True
    except Exception as e:
        core.event("trace_error", region=name, error=str(e)[:500])
    try:
        yield path if started else None
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                core.event("trace", region=name, path=path)
            except Exception as e:
                core.event("trace_error", region=name,
                           error=str(e)[:500])
