"""Opt-in jax.profiler trace capture (PPTPU_TRACE_DIR).

``trace_capture(name)`` wraps a region in a device profiler trace when
the ``PPTPU_TRACE_DIR`` environment variable names a directory (or an
explicit ``base_dir`` is given), and is a no-op otherwise.  Profiling
through a remote-device tunnel is not always supported
(tools/perf_probe.py records the same caveat), so a failing profiler
start degrades to "no trace, one event recorded" rather than an
exception: telemetry must never kill the run it is observing.

The profiler is a PROCESS-WIDE singleton: ``jax.profiler.start_trace``
raises when a trace is already active.  A nested ``trace_capture``
(the survey runner's per-bucket capture around ``GetTOAs``'s
per-archive capture) therefore degrades to a no-op that yields None
and records one ``trace_skipped`` event naming the owning region —
the outer capture keeps the device timeline.

On a successful stop the capture is immediately ingested by
:mod:`.devtime`: one ``devtime`` event (per-stage device seconds,
named-scope attribution) lands in the active obs run next to the
``trace`` event that links the span wall clock to the trace path.
"""

import contextlib
import os
import threading

from . import core, devtime

__all__ = ["trace_dir", "trace_capture"]

_lock = threading.Lock()
_active_region = None  # region name owning the process-wide profiler


def trace_dir():
    """$PPTPU_TRACE_DIR, or None when profiler capture is disabled."""
    v = os.environ.get("PPTPU_TRACE_DIR", "").strip()
    return v or None


@contextlib.contextmanager
def trace_capture(name, base_dir=None):
    """Capture a jax.profiler trace of the region into
    ``<base>/<name>`` (``base_dir`` or ``$PPTPU_TRACE_DIR``); yields
    the trace path or None.

    Composes with :func:`pulseportraiture_tpu.obs.core.span`: the span
    carries the wall clock, the profiler trace carries the device
    timeline, the emitted ``trace`` event links the two, and the
    ``devtime`` event :func:`.devtime.record_devtime` ingests carries
    the per-stage device-second attribution.
    """
    global _active_region
    base = base_dir if base_dir is not None else trace_dir()
    if base is None:
        yield None
        return
    path = os.path.join(base, name)
    with _lock:
        owner = _active_region
        if owner is None:
            _active_region = name
    if owner is not None:
        # profiler already running: degrade, don't raise mid-pipeline
        core.event("trace_skipped", region=name, active_region=owner)
        yield None
        return
    import jax

    started = False
    try:
        jax.profiler.start_trace(path)
        started = True
    except Exception as e:
        core.event("trace_error", region=name, error=str(e)[:500])
    try:
        yield path if started else None
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
                core.event("trace", region=name, path=path)
                devtime.record_devtime(name, path)
            except Exception as e:
                core.event("trace_error", region=name,
                           error=str(e)[:500])
        with _lock:
            _active_region = None
