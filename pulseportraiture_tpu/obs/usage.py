"""Per-tenant usage metering, cost attribution and quotas.

PR 18 made multi-tenant fleet serving real, but tenant attribution
lived only in transient metric labels — nothing durably answered
"what did tenant X consume, and when do we cut them off?".  This
module completes the plane series (seconds → spans, bytes → memory,
correctness → quality, liveness → health) with the observable
operators bill and budget on: **resource-seconds per tenant**.

* **Usage ledger** — every unit of work (a service request, a fleet
  forward, a survey archive) is metered by :func:`meter` into one
  JSON record per unit, appended to ``<run>/usage.jsonl``.  Records
  carry ``(tenant, bucket, workload)`` attribution plus additive
  measures (wall-seconds, device-seconds from the fit-phase spans,
  peak bytes from the memory plane, archives fitted, compiles
  triggered, bytes decoded).  The ledger shares the obs sinks'
  discipline: size rotation (``PPTPU_OBS_MAX_BYTES``), torn-tail
  tolerant read-back (:func:`read_usage` skips the unparsable last
  line a SIGKILL leaves), never fatal (a failed append drops the
  record, bills the in-memory aggregate anyway), and exact shard
  merge — records are order-independent and rollups are pure sums,
  so fleet-merged and multi-process totals are integer/float-exact.
* **Live counters** — ``pps_usage_records_total{tenant=}`` /
  ``pps_usage_device_seconds_total{tenant=}`` /
  ``pps_usage_wall_seconds_total{tenant=}`` /
  ``pps_usage_bytes_decoded_total{tenant=}`` ride the streaming
  metrics registry, so the fleet ``metrics`` verb merges per-tenant
  usage across daemons for free and ``--watch`` gets a usage row.
* **Quotas** — per-tenant budgets (``PPTPU_QUOTAS`` JSON /
  ``--quotas``) over the :data:`RESOURCES` measures.  Enforcement
  points (daemon submit, router admission) call :func:`check` against
  the *local* metered totals; exhaustion surfaces first as the
  ``quota_burn`` health rule (the ``pps_quota_burn`` gauge crosses
  its threshold → pending → firing) and then as a hard shed.  With
  no run active :func:`check` admits — quotas are an observability
  feature and obey "disabled = free".

Host-side only (jaxlint J002), never fatal, disabled = free: with no
run active every module-level helper is one attribute read + ``None``
check.
"""

import json
import os
import threading
import time

from ..testing import faults
from . import core as _core

__all__ = ["SCHEMA", "RESOURCES", "UsageState", "meter", "check",
           "configure_quotas", "parse_quotas", "quotas_from_env",
           "totals", "usage_files", "read_usage", "rollup",
           "quota_burn_fraction"]

# every usage.jsonl line carries this schema tag; a field change is a
# schema change (readers key on it to skip foreign lines)
SCHEMA = "pptpu-usage-v1"

# quota-able resources: keys of a PPTPU_QUOTAS per-tenant budget dict.
# Each maps onto one additive measure of the tenant rollup.
RESOURCES = ("device_seconds", "wall_seconds", "requests", "archives",
             "bytes_decoded")

# rollup measure each quota resource is charged against
_RESOURCE_KEY = {"device_seconds": "device_s",
                 "wall_seconds": "wall_s",
                 "requests": "requests",
                 "archives": "archives",
                 "bytes_decoded": "bytes_decoded"}

# the additive measures of one usage record (rollups sum exactly these)
_MEASURES = ("wall_s", "device_s", "peak_bytes", "archives",
             "compiles", "bytes_decoded")

# tenant attribution for un-attributed work (local survey runs)
LOCAL_TENANT = "_local"


def parse_quotas(spec):
    """Parse a quota spec into ``{tenant: {resource: float}}``.

    ``spec`` is a dict or a JSON object text: tenant → budget, where a
    budget is either a scalar (shorthand for ``device_seconds``) or a
    dict over :data:`RESOURCES`.  Raises ValueError on malformed JSON
    or unknown resource names — a quota typo must fail the daemon at
    start, not silently admit forever.
    """
    if spec is None:
        return {}
    if isinstance(spec, str):
        spec = spec.strip()
        if not spec:
            return {}
        try:
            spec = json.loads(spec)
        except json.JSONDecodeError as e:
            raise ValueError("quotas: not valid JSON: %s" % e)
    if not isinstance(spec, dict):
        raise ValueError("quotas: expected an object "
                         "{tenant: budget}, got %r" % type(spec).__name__)
    out = {}
    for tenant, budget in spec.items():
        if isinstance(budget, (int, float)):
            budget = {"device_seconds": budget}
        if not isinstance(budget, dict):
            raise ValueError("quotas[%r]: budget must be a number or "
                             "an object over %s" % (tenant, ", ".join(
                                 RESOURCES)))
        limits = {}
        for res, lim in budget.items():
            if res not in RESOURCES:
                raise ValueError("quotas[%r]: unknown resource %r "
                                 "(known: %s)" % (tenant, res,
                                                  ", ".join(RESOURCES)))
            limits[res] = float(lim)
        if limits:
            out[str(tenant)] = limits
    return out


def quotas_from_env():
    """``$PPTPU_QUOTAS`` parsed, or ``{}`` when unset/unparsable (a
    broken env var must not kill a daemon that never opted in)."""
    try:
        return parse_quotas(os.environ.get("PPTPU_QUOTAS", ""))
    except ValueError:
        return {}


class UsageState:
    """Per-recorder usage accounting.

    Created lazily by :meth:`~.core.Recorder.usage_state` on the first
    metered unit (a run that serves nothing costs nothing) and stopped
    by ``Recorder.close()``, which writes the run totals into the
    manifest gauges bench and obs_diff read back.  The ledger file
    inherits the recorder's rotation threshold; the per-tenant
    counters live in the run's streaming-metrics registry, so fleet
    merge and ``--watch`` rendering are inherited, not reimplemented.
    """

    def __init__(self, recorder):
        self._recorder = recorder
        self._lock = threading.Lock()
        self.path = os.path.join(recorder.dir, "usage.jsonl")
        self._fh = open(self.path, "a", encoding="utf-8")
        self._max_bytes = recorder._max_bytes
        try:
            self._bytes = os.path.getsize(self.path)
        except OSError:
            self._bytes = 0
        self._rot_seq = 0
        self.n_records = 0
        self.dropped_records = 0
        # (tenant, bucket, workload) → {measure: sum, "records": n}
        self._groups = {}
        # tenant → {measure: sum, "records": n, "requests": n}
        self._tenants = {}
        # parsed quota table (configure_quotas); {} = no enforcement
        self.quotas = {}
        self._reg = recorder.metrics_registry()

    # -- metering -------------------------------------------------------

    def record(self, kind, tenant, bucket="-", workload="-",
               wall_s=0.0, device_s=0.0, peak_bytes=0, archives=0,
               compiles=0, bytes_decoded=0, **extra):
        """Meter one unit of work: append the ledger record, fold the
        in-memory rollup, bump the per-tenant counters.  Never raises;
        a failed append drops the *record* but still bills the
        aggregate (the quota plane must not lose billing to a full
        disk)."""
        tenant = str(tenant or LOCAL_TENANT)
        rec = {"t": round(time.time(), 6), "schema": SCHEMA,
               "kind": kind, "tenant": tenant,
               "bucket": bucket or "-", "workload": workload or "-",
               "wall_s": round(float(wall_s), 6),
               "device_s": round(float(device_s), 6),
               "peak_bytes": int(peak_bytes or 0),
               "archives": int(archives or 0),
               "compiles": int(compiles or 0),
               "bytes_decoded": int(bytes_decoded or 0)}
        rec.update(extra)
        try:
            line = json.dumps(rec, default=_core._json_default)
        except Exception:
            return None
        with self._lock:
            try:
                # chaos site shared with the event sink: a full disk
                # fails the usage ledger the same way (key "usage"
                # lets a spec target just this sink)
                faults.check("obs_write", key="usage")  # jaxlint: disable=J006, J007
                if self._max_bytes and self._bytes and \
                        self._bytes + len(line) + 1 > self._max_bytes:
                    self._rotate()
                # the ledger append IS the critical section (jaxlint J006)
                self._fh.write(line + "\n")  # jaxlint: disable=J006
                self._fh.flush()  # jaxlint: disable=J006 — bounded flush of one line
                self._bytes += len(line) + 1
            except (OSError, ValueError, faults.InjectedFault):
                self.dropped_records += 1
            self.n_records += 1
            gkey = (tenant, rec["bucket"], rec["workload"])
            g = self._groups.get(gkey)
            if g is None:
                g = self._groups[gkey] = dict.fromkeys(_MEASURES, 0)
                g["records"] = 0
            t = self._tenants.get(tenant)
            if t is None:
                t = self._tenants[tenant] = dict.fromkeys(_MEASURES, 0)
                t["records"] = t["requests"] = 0
            for m in _MEASURES:
                g[m] += rec[m]
                t[m] += rec[m]
            g["records"] += 1
            t["records"] += 1
            if kind in ("request", "forward"):
                t["requests"] += 1
        reg = self._reg
        reg.inc("pps_usage_records_total", tenant=tenant)
        if rec["wall_s"]:
            reg.inc("pps_usage_wall_seconds_total", rec["wall_s"],
                    tenant=tenant)
        if rec["device_s"]:
            reg.inc("pps_usage_device_seconds_total", rec["device_s"],
                    tenant=tenant)
        if rec["bytes_decoded"]:
            reg.inc("pps_usage_bytes_decoded_total",
                    rec["bytes_decoded"], tenant=tenant)
        self._recorder.bump("usage_records")
        if self.quotas:
            self._publish_burn()
        return rec

    def _rotate(self):
        """Move the live ledger aside as ``usage.jsonl.<n>`` (caller
        holds the lock); same convention as the event sink so
        :func:`usage_files` reads the set back oldest-first."""
        self._rot_seq += 1
        try:
            self._fh.close()
            os.replace(self.path, "%s.%d" % (self.path, self._rot_seq))
        except OSError:
            pass
        self._fh = open(self.path, "a", encoding="utf-8")
        try:
            self._bytes = os.path.getsize(self.path)
        except OSError:
            self._bytes = 0

    # -- quotas ---------------------------------------------------------

    def set_quotas(self, quotas):
        with self._lock:
            self.quotas = dict(quotas or {})
        if self.quotas:
            self._publish_burn()

    def _tenant_used(self, tenant, resource):
        # caller holds the lock
        t = self._tenants.get(tenant)
        if t is None:
            return 0.0
        return float(t.get(_RESOURCE_KEY[resource], 0) or 0)

    def check(self, tenant, quotas=None):
        """The first exhausted ``{"quota", "limit", "used"}`` breach
        for ``tenant`` against the LOCAL metered totals, or None to
        admit.  A tenant with no budget row is unlimited."""
        tenant = str(tenant or LOCAL_TENANT)
        with self._lock:
            limits = (quotas if quotas is not None else
                      self.quotas).get(tenant)
            if not limits:
                return None
            for res in RESOURCES:
                lim = limits.get(res)
                if lim is None:
                    continue
                used = self._tenant_used(tenant, res)
                if used >= lim:
                    return {"quota": res, "limit": lim,
                            "used": round(used, 6)}
        return None

    def burn_fraction(self, tenant=None):
        """Max used/limit fraction over every budgeted resource — of
        one tenant, or (``tenant=None``) across all budgeted tenants.
        0.0 when nothing is budgeted."""
        with self._lock:
            tenants = [tenant] if tenant is not None else \
                list(self.quotas)
            frac = 0.0
            for ten in tenants:
                limits = self.quotas.get(ten)
                if not limits:
                    continue
                for res, lim in limits.items():
                    if lim <= 0:
                        return 1.0
                    frac = max(frac,
                               self._tenant_used(ten, res) / lim)
        return frac

    def _publish_burn(self):
        """Quota-burn gauges: the UNLABELED ``pps_quota_burn`` (max
        fraction across tenants — the ``quota_burn`` health rule's
        input; per-tenant fractions must not share its name or the
        rule's label-summing would add them) plus the per-tenant
        ``pps_quota_used_frac{tenant=}`` diagnostics."""
        reg = self._reg
        burn = 0.0
        with self._lock:
            quotas = dict(self.quotas)
        for tenant in quotas:
            frac = self.burn_fraction(tenant)
            burn = max(burn, frac)
            reg.set_gauge("pps_quota_used_frac", round(frac, 6),
                          tenant=tenant)
        reg.set_gauge("pps_quota_burn", round(burn, 6))

    # -- read side ------------------------------------------------------

    def totals(self):
        """``{"records", "tenants": {tenant: sums}}`` — the run's
        in-memory rollup (runner summary extras, quota introspection).
        """
        with self._lock:
            return {"records": self.n_records,
                    "dropped_records": self.dropped_records,
                    "tenants": {t: dict(v) for t, v in
                                sorted(self._tenants.items())}}

    def stop(self):
        """Run end: totals become manifest gauges (the summary bench /
        obs_diff / obs_report read back without parsing the ledger)."""
        if self.n_records:
            rec = self._recorder
            dev = wall = 0.0
            with self._lock:
                for t in self._tenants.values():
                    dev += t["device_s"]
                    wall += t["wall_s"]
            rec.set_gauge("usage_records_total", self.n_records)
            rec.set_gauge("usage_device_seconds_total", round(dev, 6))
            rec.set_gauge("usage_wall_seconds_total", round(wall, 6))
        with self._lock:
            try:
                self._fh.close()
            except OSError:
                pass


# -- module-level helpers (the instrumented-code API) -------------------


def _state():
    rec = _core._active
    if rec is None:
        return None
    return rec.usage_state()


def meter(kind, tenant=None, bucket=None, workload=None, wall_s=0.0,
          device_s=0.0, peak_bytes=0, archives=0, compiles=0,
          bytes_decoded=0, **extra):
    """Meter one unit of work into the active run's usage ledger.

    ``kind`` names the unit (``request`` — one service fit,
    ``forward`` — one router forward, ``archive`` — one survey
    archive).  Returns the ledger record, or None when no run is
    active.  Never fatal."""
    st = _state()
    if st is None:
        return None
    try:
        return st.record(kind, tenant, bucket=bucket or "-",
                         workload=workload or "-", wall_s=wall_s,
                         device_s=device_s, peak_bytes=peak_bytes,
                         archives=archives, compiles=compiles,
                         bytes_decoded=bytes_decoded, **extra)
    except Exception:
        return None


def configure_quotas(quotas):
    """Install a parsed/parsable quota table on the active run (the
    daemon/router start path).  Returns the parsed table (callers keep
    it for explicit :func:`check` calls); no-op → parsed table when no
    run is active."""
    parsed = quotas if isinstance(quotas, dict) and all(
        isinstance(v, dict) for v in quotas.values()) \
        else parse_quotas(quotas)
    st = _state()
    if st is not None and parsed:
        st.set_quotas(parsed)
    return parsed


def check(tenant, quotas=None):
    """Quota admission: the breach dict for ``tenant`` or None to
    admit.  No run active → None (disabled = free admits)."""
    rec = _core._active
    if rec is None or (quotas is None and rec._usage is None):
        return None
    st = _state()
    if st is None:
        return None
    try:
        return st.check(tenant, quotas=quotas)
    except Exception:
        return None


def totals():
    """The active run's usage rollup, or None when no run is active or
    nothing was metered (bench / runner summary read)."""
    rec = _core._active
    if rec is None or rec._usage is None:
        return None
    st = rec.usage_state()
    if st is None or not st.n_records:
        return None
    return st.totals()


def quota_burn_fraction():
    """The active run's max quota-burn fraction, or None when no run /
    no quotas (the health probe surface)."""
    rec = _core._active
    if rec is None or rec._usage is None:
        return None
    st = rec.usage_state()
    if st is None or not st.quotas:
        return None
    return st.burn_fraction()


# -- ledger read-back (CLI / diff / report / merge) ---------------------


def usage_files(run_dir):
    """Every usage-ledger file of a run or shard dir, oldest first:
    per-run rotated sets (``usage.jsonl.1``, ..., then the live
    ``usage.jsonl``) and per-process shard sets (``usage.<proc>.jsonl``
    with their rotated chains)."""
    try:
        names = os.listdir(run_dir)
    except OSError:
        return []
    groups = {}   # (proc or None) → [(rot or None, name)]
    for name in names:
        if not name.startswith("usage."):
            continue
        parts = name.split(".")
        # usage.jsonl | usage.jsonl.N | usage.P.jsonl | usage.P.jsonl.N
        if parts[1] == "jsonl":
            proc, rest = None, parts[2:]
        elif len(parts) > 2 and parts[1].isdigit() \
                and parts[2] == "jsonl":
            proc, rest = int(parts[1]), parts[3:]
        else:
            continue
        if not rest:
            rot = None
        elif len(rest) == 1 and rest[0].isdigit():
            rot = int(rest[0])
        else:
            continue
        groups.setdefault(proc, []).append((rot, name))
    out = []
    for proc in sorted(groups, key=lambda p: (p is not None, p)):
        files = groups[proc]
        rotated = sorted((r, n) for r, n in files if r is not None)
        live = [n for r, n in files if r is None]
        out.extend(os.path.join(run_dir, n) for _, n in rotated)
        out.extend(os.path.join(run_dir, n) for n in live)
    return out


def read_usage(path):
    """Usage records of ``path`` (a run/shard dir, or one ledger
    file), torn-tail tolerant: the unparsable line a SIGKILL tears is
    skipped, every completed record survives.  Lines without the
    :data:`SCHEMA` tag are skipped — a ledger is only ever appended
    by this module."""
    files = [path] if os.path.isfile(path) else usage_files(path)
    records = []
    for fpath in files:
        try:
            with open(fpath, encoding="utf-8") as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn tail from a crashed writer
                    if isinstance(rec, dict) \
                            and rec.get("schema") == SCHEMA:
                        records.append(rec)
        except OSError:
            continue
    return records


def rollup(records):
    """Aggregate usage records into exact, order-independent sums:
    ``{"records", "wall_s", "device_s", ..., "tenants": {tenant:
    sums}, "groups": {"tenant|bucket|workload": sums}}``.  Pure sums
    over :data:`_MEASURES` — merging two rollups equals rolling up the
    concatenation, which is what makes shard/fleet totals exact."""
    out = {"records": 0}
    for m in _MEASURES:
        out[m] = 0
    tenants = {}
    groups = {}
    for rec in records:
        out["records"] += 1
        gkey = "%s|%s|%s" % (rec.get("tenant") or LOCAL_TENANT,
                             rec.get("bucket") or "-",
                             rec.get("workload") or "-")
        tkey = rec.get("tenant") or LOCAL_TENANT
        t = tenants.get(tkey)
        if t is None:
            t = tenants[tkey] = dict.fromkeys(_MEASURES, 0)
            t["records"] = t["requests"] = 0
        g = groups.get(gkey)
        if g is None:
            g = groups[gkey] = dict.fromkeys(_MEASURES, 0)
            g["records"] = 0
        for m in _MEASURES:
            v = rec.get(m)
            if isinstance(v, (int, float)):
                out[m] += v
                t[m] += v
                g[m] += v
        t["records"] += 1
        g["records"] += 1
        if rec.get("kind") in ("request", "forward"):
            t["requests"] += 1
    for m in ("wall_s", "device_s"):
        out[m] = round(out[m], 6)
        for d in list(tenants.values()) + list(groups.values()):
            d[m] = round(d[m], 6)
    out["tenants"] = {k: tenants[k] for k in sorted(tenants)}
    out["groups"] = {k: groups[k] for k in sorted(groups)}
    return out
