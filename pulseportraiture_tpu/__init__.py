"""pulseportraiture_tpu: TPU-native wideband pulsar timing framework.

A ground-up JAX/XLA re-design with the capabilities of the reference
PulsePortraiture package (wideband TOA/DM measurement by Fourier-domain
portrait fitting; Gaussian and PCA/spline portrait modeling; alignment,
averaging and RFI zapping pipelines; PSRFITS I/O) — batched, jit-compiled,
and sharded over device meshes instead of per-profile host loops.

Layering (bottom-up):
  io/        PSRFITS + model-file + TOA-file I/O (host)
  ops/       portrait array math (device, batched)
  fit/       Fourier-domain fit kernels + batched solvers (device)
  models/    Gaussian & spline model builders
  pipelines/ pptoas/ppalign/ppspline/ppgauss/ppzap equivalents
  parallel/  mesh + sharding of batched fits over TPU slices
  utils/     records, telescope codes
  viz/       matplotlib diagnostics (host, optional)
"""

from . import config  # noqa: F401  (enables x64 on import)
from .utils.databunch import DataBunch  # noqa: F401

__version__ = "0.1.0"
