"""Test-support machinery that ships with the package.

``testing.faults`` is the chaos harness: deterministic, env-gated
fault injection at named host-side sites threaded through the survey
pipeline (docs/RUNNER.md).  It lives in the package (not tests/)
because production code calls its ``check()`` hooks — with
``PPTPU_FAULTS`` unset every hook is a near-free no-op.
"""

from . import faults
from .faults import InjectedFault

__all__ = ["faults", "InjectedFault"]
