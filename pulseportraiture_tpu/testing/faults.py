"""Deterministic, env-gated fault injection: the chaos harness.

The survey service's failure menu is much wider than "corrupt PSRFITS":
SIGTERM preemption mid-bucket, a wedged device dispatch, a hung
multihost barrier, a sink write hitting a full disk.  None of those
paths can be trusted untested, and none can be provoked on demand
without an injection layer — this module is that layer.

Named **sites** are threaded through the host-side pipeline; each is a
single ``faults.check(site, key=...)`` call that is a near-free no-op
unless a matching fault spec is active:

=================  ====================================================
site               where it fires
=================  ====================================================
``archive_read``   ``io/archive.load_data`` (per archive load; under
                   ``--prefetch`` it fires on the prefetch thread and
                   is replayed at the fit's load call site —
                   runner/prefetch.py outcome replay — so quarantine/
                   retry/backoff semantics are unchanged)
``header_scan``    ``runner/plan.scan_archive_header`` (plan-time scan)
``archive_pad``    ``runner/plan.pad_databunch`` (bucket padding; on
                   the prefetch thread under ``--prefetch``, replayed
                   like ``archive_read``)
``dispatch``       ``pipelines/toas.py`` just before the batched device
                   fit (wideband and narrowband drivers)
``ledger_append``  ``runner/queue.WorkQueue._append`` (every ledger
                   state transition)
``ledger_scan``    ``runner/queue.WorkQueue.refresh`` (per union-shard
                   tail read; a failure degrades to a stale view)
``lease_renew``    ``runner/queue.WorkQueue.renew`` (lease heartbeat;
                   a failure lets the lease run out — takeover fodder)
``checkpoint_flush``  the per-archive ``.tim`` checkpoint append
``obs_write``      ``obs/core.Recorder.emit`` (event-sink writes; the
                   injected failure must DROP the event, never crash)
``barrier``        ``parallel/multihost.barrier`` (simulates a
                   straggler for the timeout path)
``compile_cache``  ``runner/warm.enable_persistent_cache`` (persistent
                   compile-cache enable; a failure degrades to normal
                   first-use JIT compiles — warm is never fatal)
``supervisor_spawn``  ``runner/supervisor.Supervisor._spawn`` just
                   before the worker Popen (key ``w<slot>``); an
                   injected failure counts as an instant worker death,
                   so the crash-loop backoff and flap-park paths are
                   testable without burning real subprocesses
=================  ====================================================

Spec grammar (``PPTPU_FAULTS`` or :func:`configure`)::

    spec    := clause (";" clause)*
    clause  := "site:"NAME "@" param ("," param)*
             | ("sigterm" | "sigint" | "sigkill") "@" param
               ("," param)*
    param   := FLOAT          probability per check, decided by a
                              stable hash of (seed, site, key) — a
                              given key either always faults or never
                              (persistent corruption), keys you never
                              pass decide per check count (transients)
             | "nth="K        fire exactly on the K-th check of the site
                              (check *order* dependent — for targeting
                              a load that may run on a prefetch thread
                              prefer a probability clause, whose per-key
                              hash is order independent)
             | "every="K      fire on every K-th check
             | "after="K      sites: fire on every check past the K-th;
                              signals: deliver ONCE when the counting
                              site's check counter reaches K.
                              ``sigkill`` is a REAL hard kill — no
                              handler, no drain, the check never
                              returns — so lease-expiry recovery is
                              testable without any cooperation from
                              the victim (docs/RUNNER.md elasticity;
                              use it on a subprocess, never in-process
                              in a test runner)
             | "at="NAME      signal clauses: the counting site
                              (default "dispatch")
             | "hang="SECS    on fire, sleep SECS first — watchdog
                              fodder; the hang then *releases as the
                              fault* so an abandoned watchdogged
                              thread terminates instead of leaking
             | "latency="SECS on fire, sleep SECS then PROCEED — the
                              check returns normally, no fault is
                              raised.  Slow-storage simulation (an
                              NFS/Lustre archive mount) for the host
                              pipeline: inject on ``archive_read`` to
                              measure IO-wait overlap under
                              ``--prefetch`` (PERF.md §8)
             | "times="M      cap total fires of this clause
             | "seed="N       probability-hash seed (default 0)

Example — the ISSUE's chaos run::

    PPTPU_FAULTS="site:archive_read@0.1;site:dispatch@nth=3;sigterm@after=5"

Contract:

* **Deterministic.**  No wall-clock or global randomness decides a
  fire: probabilities hash (seed, site, key), everything else counts
  checks.  The same spec over the same run fires identically.
* **Env-gated and near-free.**  With no spec active, ``check`` is one
  dict lookup.  The spec is re-read from the environment whenever the
  variable changes, so a resumed in-process run can drop its faults.
* **Auditable.**  Every fire appends to :func:`fired` and emits an obs
  ``fault_injected`` event (+ ``faults_injected`` counter), so a chaos
  run's report shows exactly what was injected where — except the
  ``obs_write`` site, whose whole point is failing the sink itself.
* **Host-only.**  Sites live outside every jit boundary by
  construction; jaxlint J002 flags any ``faults.*`` call inside jit
  (fixture: ``tests/data/jaxlint_fixtures/j002_faults.py``).
"""

import hashlib
import os
import signal as _signal
import threading
import time

__all__ = ["InjectedFault", "SITES", "check", "active", "configure",
           "reset", "fired", "spec_string"]

SITES = ("archive_read", "header_scan", "archive_pad", "dispatch",
         "ledger_append", "ledger_scan", "lease_renew",
         "checkpoint_flush", "obs_write", "barrier", "compile_cache",
         "supervisor_spawn")

_SIGNALS = {"sigterm": _signal.SIGTERM, "sigint": _signal.SIGINT,
            "sigkill": _signal.SIGKILL}

# injected hangs sleep in slices this long, so a process exit (or the
# hang deadline) is never more than one slice away
HANG_SLICE_S = 0.05


class InjectedFault(RuntimeError):
    """Raised at a firing injection site.

    Subclasses RuntimeError so it travels exactly the except paths a
    real IO/runtime failure would (``_load_archive`` swallows it like
    a truncated payload; the runner's fault isolation records it like
    a dead tunnel) — the harness tests the *handlers*, not a bespoke
    error channel.
    """


class _Clause:
    __slots__ = ("raw", "site", "signal", "p", "nth", "every", "after",
                 "at", "hang_s", "latency_s", "times", "seed",
                 "n_fired")

    def __init__(self, raw, site=None, sig=None):
        self.raw = raw
        self.site = site
        self.signal = sig
        self.p = None
        self.nth = None
        self.every = None
        self.after = None
        self.at = "dispatch"
        self.hang_s = None
        self.latency_s = None
        self.times = None
        self.seed = 0
        self.n_fired = 0


def _parse(spec):
    """List of _Clause from a spec string; raises ValueError on typos
    (an unknown site silently never firing would defeat the harness)."""
    clauses = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        head, _, params = part.partition("@")
        head = head.strip()
        if head.startswith("site:"):
            site = head[len("site:"):].strip()
            if site not in SITES:
                raise ValueError(
                    "PPTPU_FAULTS: unknown site %r (known: %s)"
                    % (site, ", ".join(SITES)))
            c = _Clause(part, site=site)
        elif head in _SIGNALS:
            c = _Clause(part, sig=head)
        else:
            raise ValueError(
                "PPTPU_FAULTS: clause %r must start with 'site:<name>'"
                ", 'sigterm' or 'sigint'" % part)
        for tok in params.split(","):
            tok = tok.strip()
            if not tok:
                continue
            key, _, val = tok.partition("=")
            try:
                if not _:
                    c.p = float(tok)
                elif key == "nth":
                    c.nth = int(val)
                elif key == "every":
                    c.every = int(val)
                elif key == "after":
                    c.after = int(val)
                elif key == "at":
                    if val not in SITES:
                        raise ValueError("unknown counting site %r"
                                         % val)
                    c.at = val
                elif key == "hang":
                    c.hang_s = float(val)
                elif key == "latency":
                    c.latency_s = float(val)
                elif key == "times":
                    c.times = int(val)
                elif key == "seed":
                    c.seed = int(val)
                else:
                    raise ValueError("unknown param %r" % tok)
            except ValueError as e:
                raise ValueError("PPTPU_FAULTS: bad clause %r: %s"
                                 % (part, e))
        if c.signal is not None:
            if c.after is None:
                raise ValueError("PPTPU_FAULTS: signal clause %r needs "
                                 "after=<n>" % part)
        elif c.p is None and c.nth is None and c.every is None \
                and c.after is None:
            raise ValueError("PPTPU_FAULTS: clause %r has no trigger "
                             "(probability, nth=, every= or after=)"
                             % part)
        clauses.append(c)
    return clauses


class _Harness:
    """Parsed spec + per-site check counters + the fired log."""

    def __init__(self, clauses, spec):
        self.clauses = clauses
        self.spec = spec
        self.counts = {}
        self.fired = []
        self._lock = threading.Lock()
        self._tls = threading.local()

    # -- trigger evaluation --------------------------------------------

    @staticmethod
    def _hash_fires(clause, site, key, n):
        ident = "%d|%s|%s" % (clause.seed, site,
                              key if key is not None else n)
        h = hashlib.sha1(ident.encode("utf-8", "replace")).digest()
        return int.from_bytes(h[:8], "big") / 2.0 ** 64 < clause.p

    def _matches(self, c, site, key, n):
        if c.nth is not None:
            return n == c.nth
        if c.every is not None:
            return n % c.every == 0
        if c.after is not None:
            return n > c.after
        return self._hash_fires(c, site, key, n)

    # -- firing --------------------------------------------------------

    def _record(self, c, site, n, key, action):
        c.n_fired += 1
        rec = {"site": site, "n": n, "key": key, "action": action,
               "clause": c.raw}
        with self._lock:
            self.fired.append(rec)
        self._emit(rec)
        return rec

    def _emit(self, rec):
        # the obs_write site fails the sink itself: logging it through
        # the sink would be circular (it stays visible via fired())
        if rec["site"] == "obs_write":
            return
        self._tls.emitting = True
        try:
            from .. import obs

            obs.event("fault_injected", **rec)
            obs.counter("faults_injected")
        except Exception:
            pass
        finally:
            self._tls.emitting = False

    def check(self, site, key=None):
        if getattr(self._tls, "emitting", False):
            return  # our own obs emission re-entering a site
        with self._lock:
            n = self.counts.get(site, 0) + 1
            self.counts[site] = n
        for c in self.clauses:
            if c.times is not None and c.n_fired >= c.times:
                continue
            if c.signal is not None:
                # deliver ONCE, exactly when the counting site's
                # counter reaches after=N (preemption at a defined
                # progress point); the check itself then proceeds —
                # except sigkill, which never returns (hard death)
                if site == c.at and n == c.after:
                    self._record(c, site, n, key, c.signal)
                    os.kill(os.getpid(), _SIGNALS[c.signal])
                continue
            if c.site != site or not self._matches(c, site, key, n):
                continue
            if c.latency_s:
                # pure success-path delay (slow-storage simulation):
                # sleep, record, and keep checking — never raises
                self._record(c, site, n, key, "latency")
                deadline = time.monotonic() + c.latency_s
                while True:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    time.sleep(min(HANG_SLICE_S, left))
                continue
            action = "hang" if c.hang_s else "fail"
            self._record(c, site, n, key, action)
            if c.hang_s:
                deadline = time.monotonic() + c.hang_s
                while True:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    time.sleep(min(HANG_SLICE_S, left))
            raise InjectedFault(
                "injected fault at site %r (check #%d%s%s)"
                % (site, n,
                   "" if key is None else ", key=%r" % key,
                   ", after %.3gs hang" % c.hang_s if c.hang_s else ""))


_lock = threading.Lock()
_harness = None     # active _Harness (env- or configure()-driven)
_env_spec = None    # the env string _harness was parsed from
_override = False   # True when configure() owns _harness


def _current():
    """The active harness, re-synced with $PPTPU_FAULTS on change."""
    global _harness, _env_spec
    if _override:
        return _harness
    env = os.environ.get("PPTPU_FAULTS", "").strip()
    if not env:
        if _env_spec is not None:
            with _lock:
                _harness, _env_spec = None, None
        return None
    if env != _env_spec:
        with _lock:
            if env != _env_spec:
                _harness = _Harness(_parse(env), env)
                _env_spec = env
    return _harness


def check(site, key=None):
    """Fault-injection hook: no-op unless an active spec matches.

    ``key`` identifies the work item (archive path, barrier name) so
    probability clauses can decide per item and the fired log reads
    usefully.  May raise :class:`InjectedFault`, sleep (``hang=``) or
    deliver a signal to this process — exactly what the instrumented
    code must survive.  Host-side only (jaxlint J002).
    """
    h = _current()
    if h is not None:
        h.check(site, key)


def active():
    """True when a fault spec is currently active."""
    return _current() is not None


def spec_string():
    """The active spec string, or None."""
    h = _current()
    return h.spec if h is not None else None


def configure(spec):
    """Activate ``spec`` programmatically (tests), overriding the
    environment until :func:`reset`.  Parses eagerly: a bad spec fails
    here, not silently at the first check."""
    global _harness, _override
    with _lock:
        _harness = _Harness(_parse(spec), spec)
        _override = True


def reset():
    """Drop any active spec and all counters; the environment is
    re-read (and re-parsed) on the next :func:`check`."""
    global _harness, _env_spec, _override
    with _lock:
        _harness, _env_spec, _override = None, None, False


def fired():
    """Copy of the fired log: [{"site", "n", "key", "action",
    "clause"}] in firing order."""
    h = _harness
    return list(h.fired) if h is not None else []
