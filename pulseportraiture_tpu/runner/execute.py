"""Survey execution: bucketed batches, lease-based claiming, obs shards.

``run_survey`` drives a :class:`~.plan.SurveyPlan` to completion for
ONE process of a (possibly multi-process) job:

* work ownership is **lease-based over the union of ledger shards**
  (:class:`~.queue.WorkQueue` union mode), not a static partition: a
  claim is a ``running`` record carrying ``owner`` + an expiring
  lease, renewed by a heartbeat thread while the fit is in flight.
  The plan's bucket-major order round-robined by process index is only
  a *preference* (it minimizes claim conflicts and keeps bucket
  batching intact); any process may claim any ready archive, so a
  resumed survey can run with fewer or more processes than the run
  that was preempted, and a dead straggler's archives expire back into
  the pool instead of staying stranded (docs/RUNNER.md "Elasticity");
* archives are fit bucket by bucket through the normal ``GetTOAs``
  pipeline (or ``get_narrowband_TOAs`` with ``narrowband=True``), each
  archive padded to its bucket's canonical shape at load time
  (:func:`~.plan.pad_databunch`) so the whole survey compiles
  O(#buckets) program sets instead of O(#shapes);
* per-archive state lives in this process's ledger shard
  (``ledger.<pid>.jsonl`` — each process appends only to its own
  file): transient failures retry with backoff, poison archives are
  quarantined with a reason, and a killed run resumes exactly where it
  stopped — reconciled against the ``.tim`` checkpoints so a
  disagreement (or a lease takeover) refits rather than silently
  skipping or double-writing a block (``_reconcile``);
* each process records its own obs run and publishes it as a shard
  (``obs_shards/events.<proc>.jsonl``); process 0 merges the shards
  into one report (``obs/merge.py``) after a barrier on real
  multihost runs, and a barrier straggler's leases are revoked from
  its ``BarrierTimeout.missing`` ids;
* the loop itself is workload-agnostic (``runner/workloads.py``):
  ``workload=`` selects what a claimed archive *means* — ``toas``
  (the default, bit-identical to the engine's original behavior),
  ``zap``, ``align`` (multi-pass, with a per-iteration reduce), or
  ``modelfit`` — while the ledger/lease/checkpoint/reconcile/obs
  machinery stays exactly the same.  Every ledger record, lease row,
  metric sample, and span carries the ``workload`` label, so one
  workdir can chain zap→align→toas with exactly-once semantics per
  (archive, workload).

With more than one local device, each bucket's batched fit is sharded
over a ('subint', 'chan') mesh via :func:`make_mesh_fitter`
(``use_mesh=True``) — the same GSPMD path as
``parallel.sharded_fit.sharded_fit_portrait_batch``, adapted to the
pipeline's per-archive fit configuration.
"""

import collections
import contextlib
import functools
import itertools
import json
import os
import signal
import sys
import threading
import time

import numpy as np

from .. import obs
from ..obs import flight, health, memory, metrics, quality, tracing, \
    usage
from ..obs.merge import merge_obs_shards, write_shard
from ..obs.metrics import PHASE_HISTOGRAM
from ..pipelines.toas import _PRELOAD_MISS, GetTOAs, \
    drop_checkpoint_blocks
from .plan import SurveyPlan, load_bucketed_databunch
from .prefetch import HostPrefetcher
from .queue import DEFAULT_WORKLOAD, DONE, FAILED, QUARANTINED, \
    RUNNING, WorkQueue, owner_pid

__all__ = ["run_survey", "make_mesh_fitter", "survey_status",
           "abandoned_workers"]

# workers the dispatch watchdog abandoned (may be wedged inside native
# code forever); see abandoned_workers()
_ABANDONED = []

# run-epoch counter: owner strings must differ across run_survey calls
# in one interpreter (simulated multi-process tests) AND across OS
# processes, so an owner is "p<pid>@<ospid>.<n>"
_RUN_SEQ = itertools.count(1)


def abandoned_workers(grace_s=0.0):
    """Watchdog-abandoned worker threads that are still alive, after
    giving them ``grace_s`` (total) to finish.

    A worker the watchdog gave up on may be wedged inside native
    XLA/device code.  Exiting the interpreter with such a thread live
    aborts hard in C++ teardown (``terminate called without an active
    exception``) — so a process that used the watchdog should check
    this before returning from main and ``os._exit`` past teardown
    when any remain (cli/ppsurvey.py does).
    """
    global _ABANDONED
    _ABANDONED = [t for t in _ABANDONED if t.is_alive()]
    deadline = time.monotonic() + max(0.0, grace_s)
    for t in list(_ABANDONED):
        t.join(max(0.0, deadline - time.monotonic()))
    _ABANDONED = [t for t in _ABANDONED if t.is_alive()]
    return list(_ABANDONED)


class _BucketedGetTOAs(GetTOAs):
    """GetTOAs whose loaded archives are padded to one canonical
    (nchan, nbin) bucket shape, so every archive of the bucket reuses
    the same compiled programs."""

    def __init__(self, datafiles, modelfile, bucket_shape, quiet=True):
        super().__init__(datafiles, modelfile, quiet=quiet)
        self._bucket_shape = tuple(bucket_shape)

    def _load_archive(self, datafile, tscrunch, quiet):
        # a prefetched buffer is already bucket-padded: replay its
        # outcome (or exception) from this exact call site, so a
        # prefetch-thread read/pad fault propagates like a serial one
        hit = self._take_preloaded(datafile)
        if hit is not _PRELOAD_MISS:
            kind, val = hit
            if kind == "raise":
                raise val
            return val
        return load_bucketed_databunch(datafile, self._bucket_shape,
                                       tscrunch=tscrunch, quiet=quiet)


def make_mesh_fitter(mesh):
    """A ``fit_portrait_full_batch`` drop-in that shards each bucket
    batch over ``mesh`` ('subint' data-parallel, 'chan' model-parallel,
    GSPMD-partitioned like parallel/sharded_fit.py).

    The batch is padded to a multiple of the mesh's subint axis with
    copies of its last subint (live weights — all-dead rows would NaN
    the weighted reductions) and the padding is sliced off the
    outputs.  ``scan_size``/``pad_to`` are dropped: a GSPMD-sharded
    batch axis must not be reshaped into scan chunks
    (fit/portrait.py's auto_scan_size contract).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..fit.portrait import fit_portrait_full_batch
    from ..parallel.mesh import batch_sharding
    from ..utils.databunch import DataBunch

    n_sub = mesh.shape["subint"]
    sh3 = batch_sharding(mesh)
    sh2 = NamedSharding(mesh, P("subint", "chan"))
    sh1 = NamedSharding(mesh, P("subint"))
    sh1x = NamedSharding(mesh, P("subint", None))

    def fitter(data, models, init, Ps, freqs, errs=None, weights=None,
               nu_fits=None, nu_outs=None, **kw):
        kw.pop("scan_size", None)
        kw.pop("pad_to", None)
        data = np.asarray(data)
        B = data.shape[0]
        Bp = -(-B // n_sub) * n_sub

        def padrow(x):
            x = np.asarray(x)
            if Bp == B:
                return x
            return np.concatenate(
                [x, np.repeat(x[-1:], Bp - B, axis=0)], axis=0)

        models = np.broadcast_to(np.asarray(models), data.shape)
        if weights is None:
            weights = np.ones(data.shape[:-1])
        else:
            weights = np.broadcast_to(np.asarray(weights),
                                      data.shape[:-1])
        put = jax.device_put
        args = [put(padrow(data), sh3), put(padrow(models), sh3),
                put(padrow(np.broadcast_to(
                    np.asarray(init, np.float64), (B, 5))), sh1x),
                put(padrow(np.broadcast_to(np.asarray(Ps), (B,))), sh1),
                put(padrow(np.broadcast_to(np.asarray(freqs),
                                           data.shape[:-1])), sh2)]
        if errs is not None:
            errs = put(padrow(np.broadcast_to(np.asarray(errs),
                                              data.shape[:-1])), sh2)
        weights = put(padrow(weights), sh2)
        if nu_fits is not None and not isinstance(nu_fits, tuple):
            nu_fits = put(padrow(np.asarray(nu_fits)), sh1x)
        if nu_outs is not None and isinstance(nu_outs, tuple):
            nu_outs = tuple(
                None if col is None else put(padrow(np.asarray(col)),
                                             sh1)
                for col in nu_outs)
        with mesh:
            out = fit_portrait_full_batch(
                *args, errs=errs, weights=weights, nu_fits=nu_fits,
                nu_outs=nu_outs, **kw)
        if Bp == B:
            return out
        return DataBunch(**{
            k: (v[:B] if getattr(v, "ndim", 0) >= 1
                and v.shape[0] == Bp else v)
            for k, v in out.items()})

    return fitter


def _resolve_process(process_index, process_count):
    """(pid, nproc, simulated): explicit args win (simulated
    multi-process in one interpreter); defaults ask the jax runtime."""
    if process_index is None and process_count is None:
        from ..parallel import multihost

        return multihost.process_index(), multihost.process_count(), \
            False
    return int(process_index or 0), int(process_count or 1), True


def _paths(workdir, pid):
    return {
        "ledger": os.path.join(workdir, "ledger.%d.jsonl" % pid),
        "checkpoint": os.path.join(workdir, "toas.%d.tim" % pid),
        "obs": os.path.join(workdir, "obs"),
        "shards": os.path.join(workdir, "obs_shards"),
        "merged": os.path.join(workdir, "obs_merged"),
        "survey": os.path.join(workdir, "survey.%d.json" % pid),
        "survey_merged": os.path.join(workdir, "survey.json"),
    }


def _ckpt_path(workdir, pid):
    return os.path.join(workdir, "toas.%d.tim" % pid)


class _LeaseHeartbeat:
    """Daemon thread renewing the leases of in-flight archives.

    The fit loop (and the dispatch watchdog's worker) can block inside
    a device dispatch for longer than a lease, so renewal cannot live
    on the fitting thread: :meth:`hold` (or an :meth:`acquire` /
    :meth:`release` pair) marks archives whose leases the thread keeps
    alive with ``queue.renew`` heartbeat appends (``lease_renewed``
    events).  The claim-ahead prefetch window holds SEVERAL leases at
    once — one per claimed-but-not-yet-fit archive — hence a key set
    rather than a single slot; the set is idempotent, not refcounted.
    A renewal that fails — injected ``lease_renew`` fault, NFS blip —
    is dropped and counted; the lease then simply runs out and the
    fit's completion guard abandons without a transition if someone
    took over.
    """

    def __init__(self, queue, interval_s):
        self.queue = queue
        self.interval_s = max(0.05, float(interval_s))
        self._keys = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        # the heartbeat renews MANY archives' leases; no one trace to adopt (jaxlint J008)
        self._t = threading.Thread(target=self._run, daemon=True,  # jaxlint: disable=J008
                                   name="pptpu-lease-heartbeat")
        self._t.start()

    def _run(self):
        while not self._stop.wait(self.interval_s):
            with self._lock:
                keys = sorted(self._keys)
            for key in keys:
                try:
                    rec = self.queue.renew(key)
                except Exception:
                    obs.counter("lease_renew_failures")
                    continue
                if rec is not None:
                    obs.event("lease_renewed", archive=key,
                              owner=self.queue.owner,
                              lease_expires_at=rec.get(
                                  "lease_expires_at"),
                              renewals=rec.get("renewals"))
                    obs.counter("leases_renewed")

    def acquire(self, path):
        """Start renewing ``path``'s lease."""
        with self._lock:
            self._keys.add(self.queue.key_for(path))

    def release(self, path):
        """Stop renewing ``path``'s lease."""
        with self._lock:
            self._keys.discard(self.queue.key_for(path))

    @contextlib.contextmanager
    def hold(self, path):
        self.acquire(path)
        try:
            yield
        finally:
            self.release(path)

    def stop(self):
        self._stop.set()
        self._t.join(2.0)


def _reconcile(wl, queue, checkpoint, pid, assigned_paths, quiet=True):
    """Make the union ledger and MY checkpoint agree before fitting.
    Disagreements REFIT rather than silently skip (docs/RUNNER.md):

    * ledger ``done`` with the block recorded in MY checkpoint
      (``ckpt == pid``) but no complete block there -> the results are
      lost (crash between fit and append) -> reset to pending;
    * block present in MY checkpoint but the ledger does not confirm
      it as mine -> half-trusted (crash between the two appends, or a
      lease takeover refit it elsewhere) -> drop the block, never
      skip, never duplicate.

    ``done`` records owned by OTHER processes are trusted as-is: their
    blocks live in their own checkpoint (the final survey results are
    the union of all checkpoints), and a takeover additionally scrubs
    the previous owner's block at claim time.  The checkpoint protocol
    (block read/drop) is the workload's: ``.tim`` block+marker for
    ``toas``, one-JSONL-line-per-archive for the rest
    (runner/workloads.py).
    """
    done_ckpt = wl.resume_done(checkpoint, quiet)
    to_drop = []
    for path in assigned_paths:
        key = queue.key_for(path)
        rec = queue.entries.get(key)
        state = rec["state"] if rec else None
        in_ckpt = key in done_ckpt
        ck = None
        if rec is not None and state == DONE:
            ck = rec.get("ckpt")
            if ck is None:
                ck = queue.shard_of(path)
            if ck is None:
                ck = pid  # legacy single-shard ledger
        if state == DONE and ck == pid and not in_ckpt:
            queue.reset(path, "checkpoint_missing_block")
            obs.event("runner_reconcile", archive=path,
                      action="refit", cause="checkpoint_missing_block")
        elif state == DONE and ck != pid and in_ckpt:
            # the confirmed block lives in another process's
            # checkpoint; mine is a stale partial from a lost lease
            to_drop.append(path)
            obs.event("runner_reconcile", archive=path,
                      action="drop_block", cause="done_elsewhere")
        elif state not in (DONE, QUARANTINED) and in_ckpt:
            to_drop.append(path)
            obs.event("runner_reconcile", archive=path,
                      action="refit", cause="ledger_not_done")
    if to_drop:
        wl.drop_blocks(checkpoint, to_drop)
        if not quiet:
            print(f"reconcile: dropped {len(to_drop)} checkpoint "
                  "block(s) the ledger does not confirm as this "
                  "process's; refitting where needed.")


def _lease_lost(queue, info, checkpoint, wrote_block,
                drop=drop_checkpoint_blocks):
    """The lease was taken over mid-fit: abandon with NO ledger
    transition (the taker owns the archive's state now) and drop any
    block this fit just wrote so a re-claimed archive never
    double-writes a checkpoint block.  ``drop`` is the workload's
    block-drop protocol (the ``.tim`` one by default)."""
    if wrote_block:
        drop(checkpoint, [info.path])
    cur = queue.record(info.path) or {}
    obs.event("lease_lost", archive=info.path, owner=queue.owner,
              new_owner=cur.get("owner"),
              block_dropped=bool(wrote_block))
    obs.counter("leases_lost")


class _ClaimedItem:
    """One claimed archive in flight between claim and fit — either
    fitting immediately (serial path) or riding the claim-ahead window
    with its load on the prefetch pool (``ticket``)."""

    __slots__ = ("info", "bucket", "ctx", "t0", "ticket")

    def __init__(self, info, bucket, ctx, t0, ticket=None):
        self.info = info
        self.bucket = bucket
        self.ctx = ctx
        self.t0 = t0
        self.ticket = ticket


def _try_claim(queue, wl, info, owner, workdir, ipass, pid, t_arch0,
               blabel, wlabel):
    """The union-replay lease-claim protocol for one archive; must run
    under the archive's activated trace context.

    Sync the union view first (a sibling may have claimed or even
    completed this archive since the last refresh, and a claim layered
    on top of an unseen ``done`` would win the (t, owner) order and
    refit it), then claim, then re-sync to run the deterministic
    double-claim election; a lost election abandons with NO ledger
    transition.  A takeover additionally scrubs the previous owner's
    checkpoint block.  Returns the claim record, or None when the
    archive turned out not to be ours to fit.
    """
    queue.refresh()
    if queue.state(info.path) in (DONE, QUARANTINED) \
            or not queue.ready(info.path):
        return None
    prev_rec = queue.record(info.path) or {}
    was_held = prev_rec.get("state") == RUNNING
    claim = queue.claim(info.path, **wl.claim_fields(queue, info))
    queue.refresh()
    if not queue.owns(info.path):
        # double-claim lost: the deterministic (t, owner) union order
        # elected the other claimant — abandon with NO transition
        obs.event("lease_claim_lost", archive=info.path, owner=owner,
                  winner=(queue.record(info.path) or {}).get("owner"))
        obs.counter("lease_claims_lost")
        return None
    if was_held:
        obs.event("lease_expired", archive=info.path,
                  prev_owner=prev_rec.get("owner"),
                  lease_expires_at=prev_rec.get("lease_expires_at"))
        obs.counter("leases_expired")
        # health-rule signal (obs/health.py lease_expiry_spike): the
        # metrics twin of the manifest counter, windowable live
        metrics.inc("pps_lease_expirations_total")
    takeover = claim.get("takeover_from")
    n_scrubbed = 0
    if takeover:
        ppid = owner_pid(takeover)
        if ppid is not None and ppid != pid:
            # the previous owner may have died between its checkpoint
            # flush and the ledger append: scrub its block so the
            # refit cannot double-write
            n_scrubbed = wl.drop_blocks(
                wl.checkpoint_path(workdir, ppid, ipass), [info.path])
        obs.counter("lease_takeovers")
    obs.event("lease_claimed", archive=info.path, owner=owner,
              lease_expires_at=claim.get("lease_expires_at"),
              takeover_from=takeover,
              blocks_scrubbed=n_scrubbed or None,
              attempts=claim.get("attempts", 0))
    obs.counter("leases_claimed")
    # claim latency: union refresh + ledger append + takeover scrub
    claim_s = time.perf_counter() - t_arch0
    metrics.observe(PHASE_HISTOGRAM, claim_s, phase="claim",
                    bucket=blabel, workload=wlabel)
    tracing.emit_span("claim", claim_s, archive=info.path)
    return claim


def _fit_one(gt, queue, info, checkpoint, padded, get_toas_kw, quiet,
             cancelled=None, narrowband=False):
    """Fit one (already claimed) archive with full fault isolation;
    returns its final state.  Only BaseExceptions (kill signals)
    propagate.

    ``cancelled`` (a threading.Event) is set by the dispatch watchdog
    once it has settled this archive from outside; a late-finishing
    abandoned worker must then make NO ledger transition — the
    watchdog's ``fail`` record already owns the archive's state.  The
    same no-transition discipline applies when the union ledger shows
    the lease was taken over mid-fit (:func:`_lease_lost`).
    """
    n_fail0 = len(gt.failed_datafiles)
    n_poison0 = len(gt.poisoned_datafiles)
    n_ord0 = len(gt.order)
    n_toa0 = len(gt.TOA_list)
    kw = dict(get_toas_kw)
    if padded:
        flags = dict(kw.get("addtnl_toa_flags") or {})
        flags.setdefault("pp_grid", "%dx%d" % gt._bucket_shape)
        kw["addtnl_toa_flags"] = flags
    fit = gt.get_narrowband_TOAs if narrowband else gt.get_TOAs
    try:
        fit(datafile=info.path, checkpoint=checkpoint, quiet=quiet,
            **kw)
    except Exception as e:  # fault isolation: one archive, not the run
        if cancelled is not None and cancelled.is_set():
            return None
        if not queue.owns(info.path, refresh=True):
            _lease_lost(queue, info, checkpoint, wrote_block=False)
            return None
        reason = "%s: %s" % (type(e).__name__, e)
        if memory.is_oom(e):
            # allocator exhaustion is deterministic for the shape that
            # caused it — burning retries repeats the OOM; quarantine
            # with forensics (watermarks + dump) instead
            memory.record_oom("fit_one", e, archive=info.path,
                              workload=queue.workload)
            rec = queue.quarantine(info.path, "oom: %s" % reason[:400])
        else:
            rec = queue.fail(info.path, reason)
    else:
        if cancelled is not None and cancelled.is_set():
            return None
        if not queue.owns(info.path, refresh=True):
            # success, but someone else holds the archive now — the
            # block we just appended would duplicate the taker's
            _lease_lost(queue, info, checkpoint,
                        wrote_block=len(gt.order) > n_ord0)
            return None
        if len(gt.failed_datafiles) > n_fail0:
            reason = gt.failed_datafiles[-1][1]
            if memory.is_oom(reason):
                # GetTOAs isolated a device OOM into failed_datafiles;
                # same quarantine-not-retry policy as the except path
                memory.record_oom("fit_one", reason, archive=info.path,
                                  workload=queue.workload)
                rec = queue.quarantine(info.path,
                                       "oom: %s" % str(reason)[:400])
            else:
                # transient device/tunnel failure GetTOAs already
                # isolated
                rec = queue.fail(info.path, reason)
        elif len(gt.poisoned_datafiles) > n_poison0:
            # non-finite guard refusal: retrying poisoned data is
            # pointless — quarantine directly with the guard's reason
            rec = queue.quarantine(info.path,
                                   gt.poisoned_datafiles[-1][1])
        elif len(gt.order) == n_ord0:
            # loaded-but-unusable (corrupt payload, model mismatch,
            # no subints): deterministic-looking, but a flaky
            # filesystem produces the same signature — bounded
            # retries settle it, then quarantine
            rec = queue.fail(info.path, "load_failed_or_model_mismatch")
        else:
            rec = queue.complete(info.path,
                                 n_toas=int(len(gt.TOA_list) - n_toa0))
    obs.event("runner_archive", archive=info.path,
              workload=queue.workload,
              state=rec["state"], attempts=rec.get("attempts", 0),
              reason=rec.get("reason"))
    if rec["state"] == QUARANTINED:
        # every quarantine path (OOM, poison, retries exhausted) feeds
        # the quarantine_spike health rule and freezes a postmortem of
        # the events that led here — the runner_archive record above
        # is already in the flight ring when the bundle is cut
        reason = str(rec.get("reason") or "")
        metrics.inc("pps_quarantined_total", workload=queue.workload)
        flight.dump("oom" if reason.startswith("oom") else "quarantine",
                    archive=info.path, workload=queue.workload,
                    reason=reason[:200])
    return rec["state"]


def _fit_one_guarded(wl, state, queue, info, checkpoint, padded, quiet,
                     watchdog_s):
    """The workload's ``fit_one``, bounded by a dispatch watchdog.

    With ``watchdog_s`` unset this is a plain call.  Otherwise the fit
    runs in a daemon worker thread joined with the timeout, so a hang
    (wedged device dispatch, stuck first compile through a dead
    tunnel) becomes a bounded ``fail()``+requeue instead of wedging
    the whole survey.  On timeout the worker is cancelled
    cooperatively — it skips its ledger transitions once the watchdog
    has settled the archive; injected hangs release themselves as
    :class:`~..testing.faults.InjectedFault` (testing/faults.py), and
    a genuinely wedged dispatch never returns and dies with the
    process.  Returns ``(final_state, state_poisoned)``:
    ``state_poisoned`` means the bucket's warm state (e.g. the toas
    GetTOAs instance) may still be touched by the abandoned worker and
    must be discarded by the caller.
    """
    if not watchdog_s:
        return wl.fit_one(state, queue, info, checkpoint, padded,
                          quiet), False
    cancelled = threading.Event()
    box = {}
    # the watchdog worker is a fresh thread: adopt this archive's
    # ambient trace context so its spans/ledger records stay stamped
    ctx = tracing.current()

    def _work():
        try:
            with tracing.activate(ctx):
                box["state"] = wl.fit_one(state, queue, info,
                                          checkpoint, padded, quiet,
                                          cancelled=cancelled)
        except BaseException as e:
            box["err"] = e

    t = threading.Thread(
        target=_work, daemon=True,
        name="pptpu-fit-%s" % os.path.basename(info.path))
    t.start()
    t.join(watchdog_s)
    if t.is_alive():
        cancelled.set()
        _ABANDONED.append(t)
        obs.event("watchdog_fired", archive=info.path,
                  timeout_s=watchdog_s)
        obs.counter("watchdog_fired")
        # freeze the trail while it is hot: the ring still holds the
        # spans/events of the dispatch that just wedged
        flight.dump("watchdog", archive=info.path,
                    timeout_s=watchdog_s)
        if not queue.owns(info.path, refresh=True):
            # the hang outlived the lease and someone took over: the
            # taker's record stands, the watchdog records nothing
            _lease_lost(queue, info, checkpoint, wrote_block=False)
            return None, True
        rec = queue.fail(
            info.path,
            "watchdog: dispatch exceeded %.1fs" % watchdog_s)
        obs.event("runner_archive", archive=info.path,
                  workload=queue.workload,
                  state=rec["state"], attempts=rec.get("attempts", 0),
                  reason=rec.get("reason"))
        return rec["state"], True
    if "err" in box:
        raise box["err"]
    return box.get("state"), False


# per-archive record fields surfaced in survey manifests/status: the
# engine's own state plus every workload's result fields
_MANIFEST_FIELDS = ("state", "attempts", "reason", "n_toas", "owner",
                    "lease_expires_at", "ckpt", "takeover_from",
                    "prev_owner", "workload", "pre_fit", "n_zapped",
                    "n_proposed", "n_rows", "part", "skipped", "model",
                    "kind")


def _write_survey_manifest(path, pid, nproc, queue, plan, extra=None):
    doc = {
        "schema": "pptpu-survey-run-v1",
        "process": pid,
        "n_processes": nproc,
        "owner": queue.owner,
        "t": time.time(),
        "workload": queue.workload,
        "counts": queue.counts(),
        "workloads": queue.counts_by_workload(),
        "n_buckets": len(plan.buckets),
        "quarantined": [{"archive": a, "reason": r}
                        for a, r in queue.quarantined()],
        "archives": {k: {f: v for f, v in rec.items()
                         if f in _MANIFEST_FIELDS}
                     for k, rec in queue.entries.items()},
    }
    doc.update(extra or {})
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, path)
    return doc


def _merge_survey_manifests(workdir, out_path,
                            workload=DEFAULT_WORKLOAD):
    """Fold the per-process survey manifests into one survey.json.

    Counts/states come from a readonly union replay of every ledger
    shard (the single source of truth) — summing per-shard counts
    would double-count archives that several shards have seen.
    ``workload`` picks whose per-archive records ``counts``/
    ``archives`` describe (the workload just run); ``workloads``
    always breaks the whole workdir down.
    """
    n_shards = 0
    for name in sorted(os.listdir(workdir)):
        if name.startswith("survey.") and name.endswith(".json") \
                and name != os.path.basename(out_path):
            stem = name[len("survey."):-len(".json")]
            if stem.isdigit():
                n_shards += 1
    q = WorkQueue(None, readonly=True, union_dir=workdir,
                  workload=workload)
    try:
        doc = {"schema": "pptpu-survey-run-v1",
               "n_processes": n_shards,
               "t": time.time(),
               "workload": q.workload,
               "counts": q.counts(),
               "workloads": q.counts_by_workload(),
               "quarantined": [{"archive": a, "reason": r}
                               for a, r in q.quarantined()],
               "archives": {k: {f: v for f, v in rec.items()
                                if f in _MANIFEST_FIELDS}
                            for k, rec in q.entries.items()}}
    finally:
        q.close()
    tmp = out_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1)
        fh.write("\n")
    os.replace(tmp, out_path)
    return doc


def run_survey(plan, workdir, modelfile=None, process_index=None,
               process_count=None, max_attempts=3, backoff_s=0.0,
               use_mesh=False, mesh=None, merge=True, max_archives=None,
               trace_bucket=False, watchdog_s=None,
               barrier_timeout_s=600.0, lease_s=600.0,
               narrowband=False, workload=None, workload_opts=None,
               tenant=None, warm=None, compile_cache=None,
               prefetch=0, quiet=True, **get_toas_kw):
    """Execute (or resume) one process's share of a survey plan.

    ``plan`` is a SurveyPlan or a path to a saved plan.json.  All
    state lives under ``workdir``; calling again with the same workdir
    resumes.  Returns the process's survey-manifest dict (counts,
    quarantined archives with reasons, per-archive states).  Counts
    reflect the UNION of all ledger shards (the whole survey as this
    process last saw it), not just this process's own fits.

    **Elastic ownership** (docs/RUNNER.md "Elasticity"): work is
    claimed from the union ledger under expiring leases
    (``lease_s``, renewed by a heartbeat thread while each fit is in
    flight), with the process's round-robin slice of the plan as a
    claim-order *preference* only.  Resuming with a different
    ``process_count`` than the interrupted run is therefore fully
    supported, and a process that outlives a dead sibling takes over
    its expired leases in the same run (visible ``lease_expired`` /
    ``takeover_from`` records in the ledger, ``lease_*`` obs events).
    Pick ``lease_s`` well above the worst per-archive fit+compile time
    divided by three (the heartbeat renews every ``lease_s/3``).

    ``max_archives`` bounds how many fit attempts this call makes
    (incremental surveys, deterministic kill/resume tests); archives
    left over stay pending in the ledger.  ``merge`` lets process 0
    fold the per-process obs shards + survey manifests into
    ``obs_merged/`` + ``survey.json`` once its own share is written.

    ``narrowband=True`` routes ``get_narrowband_TOAs`` through the
    same bucket/ledger/lease/checkpoint machinery (``get_toas_kw``
    must then hold narrowband-driver keywords only).

    **Graceful preemption** (docs/RUNNER.md): SIGTERM/SIGINT are
    converted into a *drain* — the in-flight archive finishes, the
    ledger/checkpoint/obs shard are flushed as usual, a
    ``sigterm_drain`` event is recorded, and the call returns its
    partial summary with ``"drained"`` set; ``ppsurvey resume`` then
    refits nothing already done.  A second signal aborts hard
    (KeyboardInterrupt).  A hard kill (SIGKILL, OOM) needs no
    cooperation at all: the stranded lease expires and any process —
    of any later topology — reclaims the archive.

    ``watchdog_s`` arms a per-archive dispatch watchdog: each fit runs
    in a worker thread joined with the timeout, so a wedged device
    dispatch or hung first compile becomes a bounded ``fail``+requeue
    (``watchdog_fired`` event) instead of wedging the run.  Pick it
    above the worst first-compile time of a bucket.

    ``barrier_timeout_s`` bounds the pre-merge multihost barrier; a
    straggler process yields a recorded ``barrier_timeout`` in the
    summary, its named leases are revoked back into the pool
    (``lease_revoked`` ledger records), and the merge proceeds over
    the shards that exist (the straggler's shard folds in on the next
    resume/report).

    ``trace_bucket`` (``ppsurvey run --trace-bucket``) captures one
    jax.profiler trace per shape bucket into ``$PPTPU_TRACE_DIR`` (or
    ``<workdir>/traces`` when unset); each capture is ingested into a
    ``devtime`` event (obs/devtime.py) and the run closes with
    ``device_total_s``/``device_utilization`` gauges, so the merged
    report answers whether the survey was fit-bound or IO-bound and
    where the device time went.  ``GetTOAs``'s own per-archive capture
    degrades to ``trace_skipped`` events inside the bucket capture
    (the profiler is a process-wide singleton).

    ``workload`` selects what each claimed archive means
    (runner/workloads.py): ``None``/"toas" (the default TOA survey),
    "zap", "align", "modelfit", a registered name, or a ``Workload``
    instance; ``workload_opts`` are constructor keywords for named
    workloads.  A multi-pass workload (align with ``niter > 1``) runs
    its passes sequentially under per-pass ledger workload labels
    ("align", "align.i2", ...) inside this one call, each pass ending
    with its reduce once the union ledger shows every archive settled
    — the reduce is idempotent, so any process of any topology may
    perform it.  ``**get_toas_kw`` is accepted only for ``toas``.

    ``tenant`` attributes the survey's usage records (obs/usage.py):
    every fitted archive is metered under it — per-archive wall and
    fit-phase device seconds, decoded bytes — into the run's
    ``usage.jsonl`` ledger; ``None`` bills the local pseudo-tenant
    ``_local``.  The summary gains a ``usage`` rollup when anything
    was metered.

    ``prefetch`` (``ppsurvey run --prefetch N``) enables the streaming
    host pipeline (runner/prefetch.py, docs/RUNNER.md "Host
    pipeline"): the loop claims up to N archives ahead and decodes +
    pads them on a prefetch thread while the current archive fits, so
    a warm survey runs fit-bound instead of IO-bound.  ``0`` (the
    default) is the serial path; results are bit-identical either way
    — the prefetched buffer (or its load failure/exception) is
    replayed through the fit's own load call site.  Window archives
    hold real claims whose leases the heartbeat renews; on drain/stop
    they are handed back (``prefetch_abandoned`` reset) and a lease
    lost while queued discards the buffer with NO ledger transition.
    Ignored for workloads without a prefetchable load phase
    (``supports_prefetch`` is False).

    ``warm`` (``ppsurvey run --warm[=auto]``) runs the shared warm
    pass (runner/warm.py) at worker start: every program the plan's
    buckets will dispatch for this workload is compiled/primed before
    the first claim, against the persistent compile cache when
    ``compile_cache`` names one (``--compile-cache`` /
    ``$PPTPU_COMPILE_CACHE_DIR``), so a resumed/rescheduled worker
    starts fit-bound.  Under ``--prefetch`` the warm pass OVERLAPS the
    host pipeline: the first window of this process's preferred slice
    is decoded speculatively on the prefetch workers while the main
    thread warms, and the claim loop adopts those buffers after a
    fresh claim (lease semantics unchanged — no claim is taken before
    warm finishes).  ``"always"``/True warms unconditionally;
    ``"auto"`` warms only when it can pay for itself (a persistent
    cache is active, or prefetch overlap hides the wall time).  Warm
    is never fatal: failures degrade to normal first-use compiles
    (``warm_failed`` / ``compile_cache_degraded`` events).  When warm
    ran, the summary/manifest gain ``warm_s``,
    ``time_to_first_fit_s`` and a ``warm_summary`` compile/cache
    digest; without ``--warm`` the manifest is bit-identical to the
    pre-warm behavior.
    """
    if isinstance(plan, str):
        plan = SurveyPlan.load(plan)
    modelfile = modelfile or plan.modelfile
    from .workloads import resolve_workload

    wl = resolve_workload(workload, modelfile=modelfile,
                          narrowband=narrowband,
                          get_toas_kw=get_toas_kw, opts=workload_opts)
    n_passes = max(1, int(wl.n_passes(plan)))
    pid, nproc, simulated = _resolve_process(process_index,
                                             process_count)
    os.makedirs(workdir, exist_ok=True)
    paths = _paths(workdir, pid)
    owner = "p%d@%d.%d" % (pid, os.getpid(), next(_RUN_SEQ))

    from ..parallel.multihost import (BarrierTimeout, barrier,
                                      partition_indices,
                                      straggler_ids)

    ordered = list(plan.archives())
    # round-robin slice as claim-order PREFERENCE only: it keeps claim
    # conflicts rare and bucket batching intact, but any process may
    # scavenge any other ready archive afterwards (elastic ownership)
    pref = partition_indices(len(ordered), process_id=pid,
                             num_processes=nproc)
    in_pref = set(pref)
    order_idx = pref + [i for i in range(len(ordered))
                        if i not in in_pref]

    fitter = None
    if use_mesh:
        if mesh is None:
            from ..parallel.mesh import make_mesh

            mesh = make_mesh()
        fitter = make_mesh_fitter(mesh)

    # per-bucket profiler capture (--trace-bucket): region directories
    # named by bucket shape; a capture spans every consecutive archive
    # of its bucket (the plan orders bucket-major) and is ingested to
    # a devtime event at each bucket boundary
    trace_base = None
    if trace_bucket:
        from ..obs.trace import trace_dir

        trace_base = trace_dir() or os.path.join(workdir, "traces")

    # SIGTERM/SIGINT drain handler: preemption must not tear state.
    # The handler only flips a flag — the in-flight archive finishes
    # (every store flushes per write), the loop then stops cleanly.
    drain = {"sig": None}

    def _drain_handler(signum, frame):
        if drain["sig"] is not None:
            raise KeyboardInterrupt  # second signal: abort hard
        try:
            drain["sig"] = signal.Signals(signum).name
        except ValueError:
            drain["sig"] = str(signum)

    prev_handlers = {}
    try:
        for s in (signal.SIGTERM, signal.SIGINT):
            prev_handlers[s] = signal.signal(s, _drain_handler)
    except ValueError:
        prev_handlers = {}  # not the main thread: no graceful drain

    # claim-ahead depth of the streaming host pipeline; 0 (or a
    # workload without a prefetchable load phase) = the serial path
    prefetch_depth = max(0, int(prefetch or 0))
    if prefetch_depth and not getattr(wl, "supports_prefetch", False):
        prefetch_depth = 0
    pf_tscrunch = bool(get_toas_kw.get("tscrunch", False))

    queue = None
    hb = None
    checkpoint = None
    prefetcher = None
    revoked = []
    # zero-cold-start state (--warm): wall time of the warm pass, its
    # compile/cache digest, when the first fit completed, and the
    # speculative prefetch tickets warm overlapped with
    warm_mode = warm if isinstance(warm, str) else \
        ("always" if warm else None)
    warm_s = None
    warm_summary = None
    first_fit = {"t": None}
    speculative = {}
    try:
        with obs.run("ppsurvey", base_dir=paths["obs"],
                     config={"process": pid, "n_processes": nproc,
                             "owner": owner,
                             "workload": wl.name,
                             "n_passes": n_passes,
                             "n_archives": len(ordered),
                             "n_buckets": len(plan.buckets),
                             "modelfile": modelfile,
                             "use_mesh": bool(use_mesh),
                             "watchdog_s": watchdog_s,
                             "lease_s": lease_s,
                             "narrowband": bool(narrowband),
                             "prefetch": prefetch_depth,
                             "trace_bucket": bool(trace_bucket)}) as rec:
            t0 = time.perf_counter()
            if prefetch_depth:
                prefetcher = HostPrefetcher(depth=prefetch_depth)
            # persistent compile cache + warm-overlapped startup
            # (docs/RUNNER.md "Warm start"): enable the cache first
            # (degraded-not-fatal), kick the first window of loads
            # onto the prefetch workers, then warm the plan's program
            # set on the main thread while those decodes run
            from .warm import (WARM_WORKLOADS, enable_persistent_cache,
                               warm_plan)

            cache_ok = None
            if compile_cache:
                cache_ok = enable_persistent_cache(compile_cache)
            do_warm = wl.name in WARM_WORKLOADS and (
                warm_mode == "always"
                or (warm_mode == "auto"
                    and (bool(cache_ok) or prefetch_depth > 0)))
            if warm_mode is not None and not do_warm:
                obs.event("warm_skipped", mode=warm_mode,
                          workload=wl.name,
                          compile_cache=bool(cache_ok))
            if do_warm:
                if prefetcher is not None:
                    for idx in order_idx[:prefetch_depth]:
                        sinfo, sbucket = ordered[idx]
                        speculative[sinfo.path] = prefetcher.submit(
                            sinfo.path,
                            functools.partial(
                                load_bucketed_databunch, sinfo.path,
                                sbucket.key, tscrunch=pf_tscrunch,
                                quiet=quiet),
                            est_bytes=sbucket.est_bytes())
                tw0 = time.perf_counter()
                try:
                    with obs.span("warm", workload=wl.name):
                        warm_summary = warm_plan(
                            plan, modelfile, get_toas_kw=get_toas_kw,
                            narrowband=narrowband, quiet=quiet,
                            workloads=(wl.name,))
                    # compile-cache misses after this point are a
                    # zero-cold-start leak, not a cold start: arm the
                    # compile_cache_postwarm health rule's guard
                    metrics.set_gauge("pps_warm_complete", 1)
                except Exception as e:
                    # never fatal: the run proceeds with first-use
                    # compiles
                    obs.event("warm_failed", error="%s: %s"
                              % (type(e).__name__, e))
                warm_s = time.perf_counter() - tw0
            if rec is not None and plan.buckets:
                # analytical footprint ceiling (runner/plan.py): the
                # largest per-bucket estimate the plan will dispatch;
                # obs_report / memory_smoke compare it to measured peak
                obs.gauge("plan_est_bytes",
                          max(b.est_bytes() for b in plan.buckets))
            n_fit = 0
            stop = False
            pass_complete = True
            for ipass in range(n_passes):
                wlabel = wl.pass_label(ipass)
                checkpoint = wl.checkpoint_path(workdir, pid, ipass)
                # each pass gets its own ledger view (same shard
                # files, per-pass ``workload`` label — pass k's
                # records never contend with pass k-1's) and a
                # heartbeat bound to that view
                if hb is not None:
                    hb.stop()
                if queue is not None:
                    queue.close()
                queue = WorkQueue(paths["ledger"],
                                  max_attempts=max_attempts,
                                  backoff_s=backoff_s,
                                  union_dir=workdir, owner=owner,
                                  lease_s=lease_s, process_index=pid,
                                  workload=wlabel)
                hb = _LeaseHeartbeat(queue, lease_s / 3.0) \
                    if lease_s else None
                queue.add([info.path for info, _ in ordered])
                for path, reason in plan.unreadable:
                    # any process may quarantine plan-time unreadables
                    # (a survey resumed without process 0 must still
                    # record them)
                    if queue.state(path) != QUARANTINED:
                        queue.quarantine(
                            path, "unreadable at plan time: %s"
                            % reason)
                _reconcile(wl, queue, checkpoint, pid,
                           [info.path for info, _ in ordered], quiet)
                wl.begin_pass(ipass, plan, workdir, quiet=quiet)
                states = {}
                stalled = 0
                tracer = contextlib.ExitStack()
                cur_bucket = None
                # claim-ahead window (--prefetch): claimed archives
                # whose loads are in flight on the prefetch pool,
                # consumed (fit) in claim order; empty on the serial
                # path and whenever the wait/backoff loop runs
                window = collections.deque()

                def _fit_item(item):
                    """Fit one claimed archive under its trace —
                    shared by the serial path and the window consumer
                    (which installs the prefetched buffer first)."""
                    nonlocal tracer, cur_bucket
                    info, bucket = item.info, item.bucket
                    blabel = "%dx%d" % bucket.key
                    with tracing.activate(item.ctx):
                        # warm per-bucket state (the toas GetTOAs +
                        # fitter; None for stateless workloads) — at
                        # most one compiled program set per (workload,
                        # shape bucket)
                        if bucket.key not in states:
                            states[bucket.key] = wl.make_bucket_state(
                                bucket, ordered, fitter, quiet=quiet)
                        if trace_base is not None \
                                and bucket.key != cur_bucket:
                            tracer.close()  # stop+ingest prev
                            tracer = contextlib.ExitStack()
                            tracer.enter_context(obs.trace_capture(
                                "bucket_%dx%d" % bucket.key,
                                base_dir=trace_base))
                            cur_bucket = bucket.key
                        if item.ticket is not None:
                            # hand-off: the fit's own _load_archive
                            # call site replays the prefetched outcome
                            # (buffer, None, or raised fault)
                            states[bucket.key].preload(
                                info.path,
                                prefetcher.consume(item.ticket))
                        padded = (info.nchan, info.nbin) != bucket.key
                        hold = hb.hold(info.path) if hb is not None \
                            else contextlib.nullcontext()
                        tfit = time.perf_counter()
                        with hold:
                            with metrics.timed(
                                    PHASE_HISTOGRAM, phase="fit",
                                    bucket=blabel, workload=wlabel), \
                                    obs.span("fit", archive=info.path,
                                             bucket=blabel,
                                             workload=wlabel), \
                                    quality.context(bucket=blabel,
                                                    workload=wlabel):
                                _, st_poisoned = _fit_one_guarded(
                                    wl, states[bucket.key], queue,
                                    info, checkpoint, padded, quiet,
                                    watchdog_s)
                        fit_s = time.perf_counter() - tfit
                        arch_s = time.perf_counter() - item.t0
                        metrics.observe(PHASE_HISTOGRAM, arch_s,
                                        phase="archive", bucket=blabel,
                                        workload=wlabel)
                        # meter the archive (obs/usage.py) under the
                        # submitting tenant (or _local): the survey's
                        # cost attribution in the same ledger currency
                        # the service daemon bills requests in
                        try:
                            nbytes = os.path.getsize(info.path)
                        except OSError:
                            nbytes = 0
                        usage.meter("archive", tenant=tenant,
                                    bucket=blabel, workload=wlabel,
                                    wall_s=arch_s, device_s=fit_s,
                                    archives=1, bytes_decoded=nbytes,
                                    archive=info.path, owner=owner)
                        # the root span of this archive's trace:
                        # children (claim/prefetch_load/fit/...)
                        # reference its pre-allocated id
                        tracing.emit_span(
                            "archive", arch_s, ctx=(item.ctx[0], None),
                            span_id=item.ctx[1], archive=info.path,
                            bucket=blabel, workload=wlabel,
                            owner=owner)
                        if first_fit["t"] is None:
                            # time-to-first-fit: worker start -> first
                            # completed fit attempt (includes any
                            # compile the warm pass did not absorb)
                            first_fit["t"] = \
                                time.perf_counter() - t0
                    if st_poisoned:
                        # the abandoned worker may still touch this
                        # state; retries get a fresh one
                        states.pop(bucket.key, None)

                def _consume_one():
                    """Pop the oldest window item and fit it — unless
                    its lease was lost while it queued, in which case
                    the buffer is discarded with NO ledger transition
                    (the taker owns the archive's state now).  Returns
                    True when a fit attempt actually ran."""
                    item = window.popleft()
                    with tracing.activate(item.ctx):
                        if not queue.owns(item.info.path,
                                          refresh=True):
                            prefetcher.discard(item.ticket,
                                               "lease_lost")
                            if hb is not None:
                                hb.release(item.info.path)
                            _lease_lost(queue, item.info, checkpoint,
                                        wrote_block=False)
                            return False
                    _fit_item(item)
                    # hold() inside _fit_item already released the
                    # claim-time acquire (the key set is idempotent)
                    return True

                def _abandon_item(item, cause):
                    """Flush a window item without fitting it (drain,
                    stop): discard the buffer, and hand the claim back
                    with an explicit reset when we still own it — we
                    claimed ahead and never fit, so waiting out the
                    lease would strand the archive for a resume."""
                    prefetcher.discard(item.ticket, cause)
                    if hb is not None:
                        hb.release(item.info.path)
                    with tracing.activate(item.ctx):
                        if queue.owns(item.info.path, refresh=True):
                            queue.reset(item.info.path,
                                        "prefetch_abandoned: %s"
                                        % cause)
                            obs.event("prefetch_abandoned",
                                      archive=item.info.path,
                                      cause=cause)
                        else:
                            _lease_lost(queue, item.info, checkpoint,
                                        wrote_block=False)

                try:
                    while True:
                        ran = 0
                        for idx in order_idx:
                            info, bucket = ordered[idx]
                            if drain["sig"]:
                                stop = True
                            if stop or queue.state(info.path) in \
                                    (DONE, QUARANTINED):
                                continue
                            if not queue.ready(info.path):
                                continue
                            blabel = "%dx%d" % bucket.key
                            t_arch0 = time.perf_counter()
                            # each archive's claim->fit->checkpoint
                            # runs under its own trace
                            # (obs/tracing.py): the ledger transitions
                            # and the checkpoint block carry the trace
                            # id, and the fit's phase spans become
                            # children of the root "archive" span
                            trace_ctx = (tracing.new_trace_id(),
                                         tracing.new_span_id())
                            item = _ClaimedItem(info, bucket,
                                                trace_ctx, t_arch0)
                            with tracing.activate(trace_ctx):
                                claim = _try_claim(
                                    queue, wl, info, owner, workdir,
                                    ipass, pid, t_arch0, blabel,
                                    wlabel)
                                if claim is None:
                                    continue
                                if prefetcher is not None:
                                    # claim first, THEN prefetch: the
                                    # heartbeat renews this lease
                                    # while the load runs on the
                                    # worker and the item waits in
                                    # the window
                                    if hb is not None:
                                        hb.acquire(info.path)
                                    # adopt the warm-overlapped
                                    # speculative decode when one is
                                    # in flight for this archive (the
                                    # claim above owns the lease; the
                                    # buffer is claim-independent)
                                    item.ticket = speculative.pop(
                                        info.path, None)
                                    if item.ticket is None:
                                        item.ticket = prefetcher.submit(
                                            info.path,
                                            functools.partial(
                                                load_bucketed_databunch,
                                                info.path, bucket.key,
                                                tscrunch=pf_tscrunch,
                                                quiet=quiet),
                                            est_bytes=bucket.est_bytes(),
                                            ctx=trace_ctx)
                            if prefetcher is None:
                                _fit_item(item)
                            else:
                                window.append(item)
                                if len(window) < prefetch_depth:
                                    continue  # top up the window
                                if not _consume_one():
                                    continue  # discarded, no fit ran
                            ran += 1
                            n_fit += 1
                            if max_archives is not None \
                                    and n_fit >= max_archives:
                                stop = True
                        # flush the claim-ahead window: fit what is
                        # still ours, or on stop/drain hand the
                        # claims back (SIGTERM drain must not strand
                        # in-flight prefetches behind live leases)
                        while window:
                            if stop or drain["sig"]:
                                _abandon_item(window.popleft(),
                                              drain["sig"] or
                                              "stopped")
                                continue
                            if not _consume_one():
                                continue
                            ran += 1
                            n_fit += 1
                            if max_archives is not None \
                                    and n_fit >= max_archives:
                                stop = True
                        outstanding = queue.outstanding()
                        metrics.set_gauge("pps_outstanding",
                                          len(outstanding))
                        # live health pass on the claim cadence, so
                        # alert rules advance even when the exporter
                        # thread is disabled (PPTPU_METRICS_INTERVAL=0)
                        health.evaluate()
                        if stop or drain["sig"] or not outstanding:
                            break
                        if ran:
                            stalled = 0
                            continue
                        # everything left is backing off or leased to
                        # another process; wait for the earliest retry
                        # or lease expiry (so a survivor takes over a
                        # dead sibling's work IN this run), unless
                        # nothing will ever become ready.  Sleep in
                        # slices so a drain signal is honored
                        # promptly.
                        now = time.time()
                        waits = []
                        for k in outstanding:
                            entry = queue.entries[k]
                            if entry["state"] == FAILED:
                                waits.append(entry.get("retry_at", 0.0)
                                             - now)
                            elif entry["state"] == RUNNING \
                                    and entry.get("owner") != owner:
                                exp = entry.get("lease_expires_at")
                                waits.append(0.0 if exp is None
                                             else exp - now)
                        if not waits:
                            break
                        # sleep to the earliest deadline, but poll the
                        # union view on the way: a live sibling
                        # completing its claims must wake this process
                        # immediately, not after the sibling's full
                        # lease runs out (a --warm worker that lost
                        # the claim race would otherwise idle for
                        # minutes behind a finished survey)
                        deadline = now + max(0.0, min(waits))
                        woke = 0
                        while time.time() < deadline \
                                and not drain["sig"]:
                            time.sleep(min(0.2,
                                           deadline - time.time()))
                            woke = queue.refresh()
                            if woke:
                                break
                        n_new = woke or queue.refresh()
                        # a live sibling renewing or completing IS
                        # progress; only a dead-still union view
                        # counts toward the stall cap (a backstop
                        # against claim ping-pong, never hit in
                        # healthy runs)
                        stalled = 0 if n_new else stalled + 1
                        if stalled > max(8,
                                         2 * queue.max_attempts + 4):
                            obs.event("runner_stalled",
                                      outstanding=len(outstanding))
                            break
                finally:
                    tracer.close()  # stop + ingest last bucket capture
                # -- end of pass: the reduce --------------------------
                # a pass is settled once the union ledger shows no
                # pending/running/failed archive for its workload
                # label; only then may the (idempotent) reduce run —
                # every process that observes completion performs it,
                # so the output exists regardless of which processes
                # survive.  An unsettled pass (drain, max_archives,
                # stall) stops the pass chain; resume continues it.
                queue.refresh()
                pcounts = queue.counts()
                pass_complete = not (pcounts.get("pending", 0)
                                     or pcounts.get("running", 0)
                                     or pcounts.get("failed", 0))
                if pass_complete:
                    if wl.has_reduce:
                        with metrics.timed(PHASE_HISTOGRAM,
                                           phase="reduce",
                                           workload=wlabel), \
                                obs.span("reduce", workload=wlabel,
                                         iteration=ipass + 1):
                            wl.end_pass(ipass, plan, workdir, queue,
                                        pid, quiet=quiet)
                    else:
                        wl.end_pass(ipass, plan, workdir, queue, pid,
                                    quiet=quiet)
                if stop or drain["sig"] or not pass_complete:
                    break
            if drain["sig"]:
                obs.event("sigterm_drain", signal=drain["sig"],
                          n_fit_attempts=n_fit, **queue.counts())
                obs.counter("sigterm_drain")
                if not quiet:
                    print("ppsurvey: %s received — drained after %d "
                          "fit attempt(s); resume continues the rest."
                          % (drain["sig"], n_fit), file=sys.stderr)
            if prefetcher is not None:
                # host-pipeline memory plane: the high-water mark of
                # live prefetch buffers (bounded by depth ×
                # ShapeBucket.est_bytes)
                obs.gauge("prefetch_buffer_peak_bytes",
                          prefetcher.peak_bytes)
            if warm_s is not None:
                obs.gauge("warm_s", round(warm_s, 6))
                if first_fit["t"] is not None:
                    obs.gauge("time_to_first_fit_s",
                              round(first_fit["t"], 6))
            if rec is not None and trace_base is not None:
                # was this run fit-bound or IO-bound?  devtime
                # ingestion sums attributed device seconds into a run
                # counter; the gauge compares them to this process's
                # survey wall
                dev_s = float(rec.counters.get("device_seconds_total",
                                               0.0))
                wall = time.perf_counter() - t0
                obs.gauge("device_total_s", round(dev_s, 6))
                obs.gauge("device_utilization",
                          round(dev_s / wall, 4) if wall > 0 else 0.0)
            if rec is not None:
                # run-level memory peak, recorded while the run is
                # still open (close() re-records the final value; this
                # one makes it visible to the runner_summary consumers)
                st = rec.memory_state()
                if st is not None:
                    st.sample_now(publish=False)
                    obs.gauge("peak_footprint_bytes",
                              st.run_peak_bytes)
            # per-process quality fingerprint (obs/quality.py): the
            # run-level aggregate plus the per-(bucket, workload)
            # breakdown the fit-context labels built up
            qfp = quality.fingerprint()
            qgroups = quality.group_fingerprints()
            if qfp is not None:
                obs.event("quality_summary", process=pid,
                          workload=wl.name, fingerprint=qfp,
                          groups=qgroups)
            # per-process usage rollup (obs/usage.py): what this
            # worker billed, in summary form
            ufp = usage.totals()
            if ufp is not None:
                obs.event("usage_summary", process=pid,
                          workload=wl.name, **ufp)
            obs.event("runner_summary", process=pid, owner=owner,
                      workload=wl.name, **queue.counts())
            run_dir = rec.dir if rec is not None else None

        if run_dir is not None:
            write_shard(run_dir, paths["shards"], pid)

        barrier_timeout = None
        if merge and not simulated and nproc > 1:
            # ALL processes arrive (a barrier only 0 joins would wedge
            # it); a straggler is bounded and recorded, its named
            # leases are revoked back into the pool, and the merge
            # proceeds over the shards that exist
            try:
                barrier("pptpu_runner_merge",
                        timeout_s=barrier_timeout_s)
            except BarrierTimeout as e:
                barrier_timeout = {
                    "barrier": e.name, "timeout_s": e.timeout_s,
                    "missing": e.missing}
                for mpid in straggler_ids(e.missing):
                    revoked.extend(queue.revoke_owner(
                        mpid, "lease_revoked: barrier straggler "
                        "p%d" % mpid))
                print("ppsurvey: %s — revoked %d lease(s), merging "
                      "available shards" % (e, len(revoked)),
                      file=sys.stderr)

        extra = {"checkpoint": checkpoint,
                 "obs_run": run_dir, "n_fit_attempts": n_fit}
        if warm_s is not None:
            # only when --warm ran: a plain run's manifest stays
            # bit-identical to pre-warm behavior
            extra["warm_s"] = round(warm_s, 6)
            if first_fit["t"] is not None:
                extra["time_to_first_fit_s"] = round(first_fit["t"], 6)
            if warm_summary is not None:
                extra["warm_summary"] = {
                    k: warm_summary[k]
                    for k in ("n_programs", "wall_s",
                              "backend_compiles", "compile_cache_hits",
                              "compile_cache_misses")}
        if n_passes > 1:
            extra["n_passes"] = n_passes
            extra["pass_complete"] = pass_complete
        extra.update(wl.summary_extra())
        if qfp is not None:
            extra["quality"] = qfp
            if qgroups:
                extra["quality_groups"] = qgroups
        if ufp is not None:
            extra["usage"] = ufp
        if drain["sig"]:
            extra["drained"] = drain["sig"]
        if barrier_timeout:
            extra["barrier_timeout"] = barrier_timeout
        if revoked:
            extra["leases_revoked"] = [
                {"archive": r["archive"],
                 "prev_owner": r.get("prev_owner")} for r in revoked]
        summary = _write_survey_manifest(
            paths["survey"], pid, nproc, queue, plan, extra=extra)
        queue.close()

        if pid == 0 and merge:
            try:
                merge_obs_shards(paths["shards"], paths["merged"])
                summary["obs_merged"] = paths["merged"]
            except FileNotFoundError:
                pass
            merged = _merge_survey_manifests(workdir,
                                             paths["survey_merged"],
                                             workload=queue.workload)
            summary["merged_counts"] = merged["counts"]
        return summary
    finally:
        if prefetcher is not None:
            # speculative decodes never adopted by a claim (sibling
            # took the archive, drain, quarantine): drop the buffers
            for tkt in speculative.values():
                prefetcher.discard(tkt, "warm_unused")
            prefetcher.stop()
        if hb is not None:
            hb.stop()
        for s, h in prev_handlers.items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError):
                pass


def survey_status(workdir, now=None):
    """Union-replay status across every ledger shard under ``workdir``
    (the ``ppsurvey status`` payload): merged {counts, quarantined,
    per-archive states}, per-owner state counts, the lease table for
    every ``running`` entry, and the expired-but-unreclaimed leases a
    resume (of any process count) would take over.  Readonly — a live
    run may own the shards.

    ``counts`` aggregates across every workload the workdir has seen
    (identical to the toas counts for a plain TOA survey);
    ``workloads`` breaks them down per workload, and lease rows carry
    their workload.  ``archives`` keeps its original shape: the toas
    records (back-compat for toas-only consumers)."""
    q = WorkQueue(None, readonly=True, union_dir=workdir)
    try:
        if not q.shards_seen:
            raise FileNotFoundError(f"no ledger shards under {workdir}")
        now = time.time() if now is None else now
        per_wl = q.counts_by_workload()
        counts = {}
        for wl_counts in per_wl.values():
            for state, n in wl_counts.items():
                counts[state] = counts.get(state, 0) + n
        for state in q.counts():  # keep every state key present
            counts.setdefault(state, 0)
        owners = {}
        for rec in q.all_entries.values():
            o = rec.get("owner") or "(unowned)"
            per = owners.setdefault(o, {})
            per[rec["state"]] = per.get(rec["state"], 0) + 1
        leases = q.leases(now=now, all_workloads=True)
        return {"counts": counts,
                "workloads": per_wl,
                "quarantined": q.quarantined(),
                "archives": dict(q.entries),
                "owners": owners,
                "leases": leases,
                "expired_unreclaimed": [x for x in leases
                                        if x["expired"]]}
    finally:
        q.close()
