"""Bounded double-buffered host prefetch (docs/RUNNER.md "Host
pipeline").

The survey fit loop (execute.py) and the TOA service's intake
(service/daemon.py) are fit-bound on device but were IO-bound on host:
FITS decode + ``pad_databunch`` ran serially *between* fits — 21 ms
p50 / 27 ms p99 on the warmed daemon's critical path (PERF.md §5).
:class:`HostPrefetcher` moves that load off the fit timeline: a small
worker pool runs :func:`~.plan.load_bucketed_databunch` for the *next*
archives while the current one fits, handing each finished buffer back
through a :class:`PrefetchTicket`.

Hand-off protocol (every invariant the serial path proves is preserved
by construction):

* **claim first, prefetch second** — callers submit a ticket only for
  an archive they have already claimed (and whose lease a heartbeat is
  renewing); a prefetch NEVER touches the ledger.
* **outcome replay** — the worker runs the exact serial load function
  and captures ``("data", bunch_or_None)`` or ``("raise", exc)``.  The
  consumer installs it via ``GetTOAs.preload``, so the fit's own
  ``_load_archive`` call site returns or raises precisely what it
  would have inline: ``archive_read`` / ``archive_pad`` injected
  faults (testing/faults.py) keep their quarantine/retry/backoff
  semantics unchanged, they merely fire on the prefetch thread.
* **discard without transition** — a lease lost (or drain/stop) while
  a ticket is queued discards the buffer (:meth:`~HostPrefetcher.
  discard`); whether the ledger then gets a ``reset`` (we still own
  the claim and hand it back) or nothing at all (a sibling took it) is
  the *caller's* decision, same as serial.
* **bounded memory** — live ticket bytes are capped by ``depth ×
  ShapeBucket.est_bytes()`` (the runner bounds its claim-ahead window
  at ``depth``; the daemon uses :meth:`~HostPrefetcher.try_submit`,
  which refuses past the cap) and surfaced in the memory plane as the
  ``pps_prefetch_buffer_bytes`` gauge.
* **trace adoption** — the worker activates the archive's trace
  context for the whole load, so decode spans, fault events, and the
  ``prefetch_load`` span stay attributed to their request while
  visibly moving OFF the request's critical path (tools/obs_trace.py).

The pool defaults to ONE worker: hand-off order then equals submission
(claim) order, so ``nth=``/``every=`` fault-site counting stays
deterministic, and the overlap that matters — load vs *fit* — needs no
load-vs-load parallelism.  The buffers stay host-side numpy: the fit
path mutates its arrays in place (``_nonfinite_guard``) and the
batched fit's ``device_put`` is a zero-copy donation on the CPU
backend, so eagerly pushing to device here would *break* bit-identical
replay for no measured win.
"""

import contextlib
import queue as queue_mod
import threading
import time

from .. import obs
from ..obs import metrics, tracing
from ..obs.metrics import PHASE_HISTOGRAM

__all__ = ["HostPrefetcher", "PrefetchTicket", "DEPTH_GAUGE",
           "BYTES_GAUGE", "HITS_COUNTER", "MISSES_COUNTER",
           "DISCARDED_COUNTER"]

# host-pipeline metric names (docs/OBSERVABILITY.md)
DEPTH_GAUGE = "pps_prefetch_depth"
BYTES_GAUGE = "pps_prefetch_buffer_bytes"
HITS_COUNTER = "pps_prefetch_hits"
MISSES_COUNTER = "pps_prefetch_misses"
DISCARDED_COUNTER = "pps_prefetch_discarded"


class PrefetchTicket:
    """Hand-off slot for one submitted load.

    The worker publishes exactly one outcome — ``("data", bunch)`` or
    ``("raise", exc)`` — and sets the event; the consumer side either
    waits for it (:meth:`HostPrefetcher.consume`) or abandons it
    (:meth:`HostPrefetcher.discard`).
    """

    __slots__ = ("path", "est_bytes", "ctx", "load_s", "_evt",
                 "_outcome", "_cancelled")

    def __init__(self, path, est_bytes=0, ctx=None):
        self.path = path
        self.est_bytes = int(est_bytes or 0)
        self.ctx = tuple(ctx) if ctx is not None else None
        self.load_s = None
        self._evt = threading.Event()
        self._outcome = ("data", None)
        self._cancelled = False

    def done(self):
        """True when the load outcome is published (no wait)."""
        return self._evt.is_set()

    def cancel(self):
        """Ask the worker to skip this load if it has not started."""
        self._cancelled = True

    def wait(self, timeout=None):
        """Block until the outcome is published; returns it (or the
        null outcome on timeout — callers that can time out must check
        :meth:`done`)."""
        self._evt.wait(timeout)
        return self._outcome


class HostPrefetcher:
    """A small thread pool decoding + padding upcoming archives.

    ``depth`` bounds the live (submitted, not yet consumed/discarded)
    tickets a *bounded* submitter may hold — the memory cap is
    ``depth × est_bytes`` of the costliest bucket, reported live on the
    ``pps_prefetch_buffer_bytes`` gauge.  ``workers`` defaults to 1
    (module docstring: deterministic hand-off order).
    """

    def __init__(self, depth=2, workers=1, name="pptpu-prefetch"):
        self.depth = max(1, int(depth))
        self.name = name
        self._jobs = queue_mod.SimpleQueue()
        self._lock = threading.Lock()
        self._stopped = False
        self._n_live = 0
        self._live_bytes = 0
        self.peak_bytes = 0
        self.n_hits = 0
        self.n_misses = 0
        self.n_discarded = 0
        metrics.set_gauge(DEPTH_GAUGE, self.depth)
        metrics.set_gauge(BYTES_GAUGE, 0)
        self._threads = []
        for i in range(max(1, int(workers))):
            t = threading.Thread(target=self._run, daemon=True,
                                 name="%s-%d" % (name, i))
            t.start()
            self._threads.append(t)

    # -- submit side ----------------------------------------------------
    def submit(self, path, loader, est_bytes=0, ctx=None):
        """Queue ``loader()`` (a zero-arg callable returning the loaded
        buffer) for ``path``; returns the :class:`PrefetchTicket`.

        The caller is responsible for bounding its live tickets at
        ``depth`` (the runner's claim-ahead window does) and for
        holding the archive's claim+lease for the ticket's lifetime.
        """
        ticket = PrefetchTicket(path, est_bytes=est_bytes, ctx=ctx)
        with self._lock:
            self._n_live += 1
            self._live_bytes += ticket.est_bytes
            self.peak_bytes = max(self.peak_bytes, self._live_bytes)
            live = self._live_bytes
        metrics.set_gauge(BYTES_GAUGE, live)
        self._jobs.put((ticket, loader))
        return ticket

    def try_submit(self, path, loader, est_bytes=0, ctx=None):
        """Like :meth:`submit`, but returns None instead of exceeding
        ``depth`` live tickets — the unbounded-submitter guard (the
        daemon's intake may admit more parked requests than the window;
        the overflow simply decodes inline at fit time, as before)."""
        with self._lock:
            if self._stopped or self._n_live >= self.depth:
                return None
        return self.submit(path, loader, est_bytes=est_bytes, ctx=ctx)

    # -- worker side ----------------------------------------------------
    def _run(self):
        while True:
            job = self._jobs.get()
            if job is None:
                return
            ticket, loader = job
            if ticket._cancelled or self._stopped:
                # discarded before the load started: publish the null
                # outcome so a racing wait() can never hang
                ticket._evt.set()
                continue
            # adopt the archive's trace context for the WHOLE load:
            # decode spans and injected-fault events attribute to the
            # archive's trace exactly as they would inline, and the
            # prefetch_load span shows the load moved off the fit
            # timeline
            ctx = contextlib.nullcontext() if ticket.ctx is None \
                else tracing.activate(ticket.ctx)
            t0 = time.perf_counter()
            with ctx:
                try:
                    outcome = ("data", loader())
                except BaseException as e:  # replayed at the consumer
                    outcome = ("raise", e)
                dt = time.perf_counter() - t0
                tracing.emit_span("prefetch_load", dt,
                                  archive=ticket.path,
                                  outcome=outcome[0])
                metrics.observe(PHASE_HISTOGRAM, dt,
                                phase="prefetch_load")
            ticket.load_s = dt
            ticket._outcome = outcome
            ticket._evt.set()

    # -- consume side ---------------------------------------------------
    def consume(self, ticket):
        """The load outcome for ``ticket``, waiting if it is still in
        flight; counts a *hit* (buffer ready before the fit needed it)
        or a *miss* (the fit had to wait)."""
        if ticket.done():
            self.n_hits += 1
            metrics.inc(HITS_COUNTER)
            obs.counter(HITS_COUNTER)
        else:
            self.n_misses += 1
            metrics.inc(MISSES_COUNTER)
            obs.counter(MISSES_COUNTER)
        outcome = ticket.wait()
        self._release(ticket)
        return outcome

    def discard(self, ticket, cause):
        """Drop ``ticket`` without consuming it (lease lost, drain,
        shutdown).  Only the buffer is released — any ledger transition
        (or deliberate absence of one) is the caller's move."""
        ticket.cancel()
        self.n_discarded += 1
        metrics.inc(DISCARDED_COUNTER)
        obs.counter(DISCARDED_COUNTER)
        obs.event("prefetch_discarded", archive=ticket.path,
                  cause=cause)
        self._release(ticket)

    def _release(self, ticket):
        with self._lock:
            self._n_live = max(0, self._n_live - 1)
            self._live_bytes = max(0,
                                   self._live_bytes - ticket.est_bytes)
            live = self._live_bytes
        metrics.set_gauge(BYTES_GAUGE, live)

    # -- lifecycle ------------------------------------------------------
    def stop(self, wait=True, timeout=10.0):
        """Stop the workers (each finishes its current load first —
        a drain is a *flush*, never a mid-decode abort)."""
        self._stopped = True
        for _ in self._threads:
            self._jobs.put(None)
        if wait:
            deadline = time.monotonic() + timeout
            for t in self._threads:
                t.join(max(0.0, deadline - time.monotonic()))
