"""Persistent survey work queue: a crash-safe JSONL state ledger.

One line is appended per state transition, so the ledger is crash-safe
by construction (a torn tail line is dropped on replay) and the full
history of every archive — attempts, failure reasons, timestamps — is
preserved for the final survey report.  Replaying the file left to
right reconstructs current state: the **last** record per archive
wins.

States::

    pending -> running -> done
                       -> failed (transient; bounded retries with
                                  exponential backoff) -> pending
                       -> quarantined (poison: corrupt file, model
                                       mismatch, retries exhausted)

``running`` entries found at load time are crash leftovers (the fit
never completed) and are reverted to ``pending``, mirroring how the
``.tim`` checkpoint drops unterminated archive blocks
(pipelines/toas.py).  Quarantined archives are terminal: they are
reported with their reason, never silently retried — one corrupt
PSRFITS file must not be able to wedge a week-long run in a retry
loop.

Union replay & leases (elastic multihost, docs/RUNNER.md)
---------------------------------------------------------

With ``union_dir`` set, a queue still appends **only to its own shard**
(``ledger.<pid>.jsonl`` — never multi-writer files) but replays the
union of every shard under the directory, so the merged ledger — not a
static partition — is the single source of truth for work ownership:

* ``claim()`` appends a ``running`` record carrying ``owner`` (process
  index + run epoch, e.g. ``p1@8812.2``) and ``lease_expires_at``;
  ``renew()`` heartbeats extend the lease with further appends.
* Merge order is deterministic and independent of shard read order:
  records sort by ``(t, owner, seq)`` and the **max** record per
  archive wins.  A double-claim therefore resolves identically on
  every process; the loser abandons with *no* ledger transition (the
  same discipline the dispatch watchdog uses for late finishers).
* ``ready()`` treats an expired-lease ``running`` entry as claimable:
  a dead straggler's archives expire back into the pool instead of
  staying stranded until a full restart.  The claimant first appends a
  visible ``pending`` revocation (``reason="lease_expired"``,
  ``prev_owner=...``) so every takeover is auditable from the ledger
  alone; ``revoke()`` does the same for barrier-named stragglers.
* ``refresh()`` tails every shard incrementally (byte offsets, torn
  tails never consumed) so a live process observes other processes'
  claims/completions without rereading whole files.

Workload dimension (runner/workloads.py)
----------------------------------------

A workdir may host several sequential workload passes (zap -> align ->
toas) sharing the same shard files.  Every record written by this
queue carries ``workload``; records **without** the field (ledgers
written before the workload engine existed) replay as ``"toas"``, so
old workdirs resume unchanged.  ``entries``/``ready``/``claim``/
``counts`` and every other single-workload API are filtered to this
queue's own workload — two workloads never contend for the same
archive — while ``all_entries`` keeps the cross-workload union for
``record_for``/``counts_by_workload``/``workloads_seen`` (the status
and pre-fit-chain views).
"""

import hashlib
import json
import os
import re
import threading
import time

from ..obs import tracing
from ..testing import faults

__all__ = ["WorkQueue", "PENDING", "RUNNING", "DONE", "FAILED",
           "QUARANTINED", "owner_pid", "DEFAULT_WORKLOAD",
           "record_workload"]

PENDING = "pending"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
QUARANTINED = "quarantined"

_STATES = (PENDING, RUNNING, DONE, FAILED, QUARANTINED)

_LEDGER_RE = re.compile(r"^ledger\.(\d+)\.jsonl$")
_OWNER_RE = re.compile(r"^p(\d+)@")

# records written before the workload engine existed have no
# ``workload`` field — they are TOA surveys by construction
DEFAULT_WORKLOAD = "toas"


def record_workload(rec):
    """Workload a ledger record belongs to (back-compat default)."""
    return str(rec.get("workload") or DEFAULT_WORKLOAD)


def owner_pid(owner):
    """Process index encoded in an owner string (``p<idx>@<epoch>``),
    or None for legacy/unparseable owners."""
    if not owner:
        return None
    m = _OWNER_RE.match(str(owner))
    return int(m.group(1)) if m else None


def _jitter_factor(key, attempts):
    """Deterministic backoff jitter in [0.5, 1.0), seeded from the
    archive path + attempt number.

    A bare ``backoff_s * 2**(attempts-1)`` is identical across every
    process of a multihost run, so one shared transient (tunnel blip,
    NFS hiccup) produces a synchronized retry stampede.  Hashing the
    key decorrelates the retry times across archives and processes
    while keeping every individual schedule exactly reproducible —
    no global randomness, so tests (and reruns) see the same ledger.
    """
    h = hashlib.sha1(("%s|%d" % (key, int(attempts)))
                     .encode("utf-8", "replace")).digest()
    return 0.5 + int.from_bytes(h[:8], "big") / 2.0 ** 65


def _rec_key(rec):
    """Total order for union replay: ``(t, owner, seq)`` primary (seq
    breaks same-owner microsecond ties causally), then state + the
    canonical JSON as a final deterministic tie-break so the merged
    winner is identical regardless of shard read order."""
    try:
        seq = int(rec.get("seq") or 0)
    except (TypeError, ValueError):
        seq = 0
    try:
        t = float(rec.get("t") or 0.0)
    except (TypeError, ValueError):
        t = 0.0
    return (t, str(rec.get("owner") or ""), seq,
            str(rec.get("state") or ""),
            json.dumps(rec, sort_keys=True, default=str))


class WorkQueue:
    """On-disk per-archive state machine for one survey.

    Archives are keyed by ``os.path.realpath`` so resumed runs match
    regardless of path spelling, exactly like the checkpoint resume in
    pipelines/toas.py.  All writes are appends flushed per line, and
    always to ``path`` (this process's own shard) only; with
    ``union_dir`` set the *read* side replays every ``ledger.*.jsonl``
    under it (module docstring).  ``owner``/``lease_s`` arm lease-based
    claiming; ``process_index`` identifies which stale ``running``
    records are this process's own crash leftovers.
    """

    def __init__(self, path, max_attempts=3, backoff_s=1.0,
                 readonly=False, union_dir=None, owner=None,
                 lease_s=600.0, process_index=None,
                 workload=DEFAULT_WORKLOAD):
        self.path = path
        self.max_attempts = int(max_attempts)
        self.backoff_s = float(backoff_s)
        self.readonly = bool(readonly)
        self.union_dir = union_dir
        self.owner = owner
        self.lease_s = float(lease_s)
        self.workload = str(workload or DEFAULT_WORKLOAD)
        if process_index is None:
            process_index = owner_pid(owner)
        self.process_index = process_index
        self.entries = {}      # realpath -> latest record, own workload
        self.all_entries = {}  # (workload, realpath) -> latest record
        self._order = []       # insertion order of first sighting
        self._seq = 0          # per-process record sequence (union tie-break)
        self._offsets = {}     # shard path -> bytes consumed
        self._shard_of = {}    # realpath -> shard pid of winning record
        self.shards_seen = 0   # shard files found by the last refresh
        self.scan_errors = 0   # unreadable shards tolerated by refresh
        # appends may race between the survey loop, its dispatch
        # watchdog settling an abandoned archive, and the lease
        # heartbeat thread (runner/execute.py)
        self._iolock = threading.Lock()
        if self.union_dir is not None:
            self.refresh(include_own=True)
        elif path is not None and os.path.isfile(path):
            self._replay()
        if self.readonly:
            # inspection only (ppsurvey status): no appends, and no
            # crash recovery — a live run may own the file
            self._fh = None
            return
        if path is None:
            raise ValueError("WorkQueue needs a shard path unless "
                             "readonly")
        # a torn tail (kill mid-append) must not glue the next append
        # onto the partial line — both records would then be lost
        if os.path.isfile(path) and os.path.getsize(path):
            with open(path, "rb") as fh:
                fh.seek(-1, os.SEEK_END)
                torn = fh.read(1) != b"\n"
            if torn:
                with open(path, "ab") as fh:
                    fh.write(b"\n")
        self._fh = open(path, "a", encoding="utf-8")
        self._recover()

    # -- persistence ----------------------------------------------------

    def _replay(self):
        """Single-shard replay: file order IS the causal order."""
        with open(self.path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail line from a crash
                key = rec.get("archive")
                if key is None or rec.get("state") not in _STATES:
                    continue
                wl = record_workload(rec)
                self.all_entries[(wl, key)] = rec
                if wl == self.workload:
                    if key not in self.entries:
                        self._order.append(key)
                    self.entries[key] = rec
                self._seq = max(self._seq, int(rec.get("seq") or 0))

    def _apply(self, rec, shard):
        """Merge one replayed record: max ``_rec_key`` per (workload,
        archive) wins (idempotent, shard-read-order independent).
        Only this queue's own workload feeds ``entries``/``_order`` —
        other workloads' records are visible through ``all_entries``
        but never contend for claims."""
        key = rec.get("archive")
        if key is None or rec.get("state") not in _STATES:
            return
        wl = record_workload(rec)
        wkey = (wl, key)
        prev = self.all_entries.get(wkey)
        if prev is not None and _rec_key(rec) < _rec_key(prev):
            return
        self.all_entries[wkey] = rec
        if wl != self.workload:
            return
        if key not in self.entries:
            self._order.append(key)
        self.entries[key] = rec
        self._shard_of[key] = shard

    def _read_shard(self, path, shard):
        """Tail one shard from its consumed offset; never consume an
        unterminated tail line (it may still be mid-append — or torn
        forever, in which case it stays ignored)."""
        off = self._offsets.get(path, 0)
        with open(path, "rb") as fh:
            fh.seek(off)
            data = fh.read()
        if not data:
            return 0
        lines = data.split(b"\n")
        tail = lines.pop()  # b"" when data ends on a newline
        self._offsets[path] = off + len(data) - len(tail)
        n = 0
        for raw in lines:
            raw = raw.strip()
            if not raw:
                continue
            try:
                rec = json.loads(raw.decode("utf-8", "replace"))
            except json.JSONDecodeError:
                continue  # torn line mid-file (pre-fix glue): skip it
            self._apply(rec, shard)
            n += 1
        return n

    def refresh(self, include_own=False):
        """Union mode: fold in every shard's new records; returns how
        many records were read.  A shard that cannot be read right now
        (NFS blip, injected ``ledger_scan`` fault) is skipped and
        retried on the next refresh — the view is then momentarily
        stale, which the claim protocol tolerates (ownership is
        re-checked against the union before every transition)."""
        if self.union_dir is None:
            return 0
        try:
            names = os.listdir(self.union_dir)
        except OSError:
            return 0
        own = os.path.basename(self.path) if self.path else None
        n = 0
        self.shards_seen = 0
        for name in sorted(names):
            m = _LEDGER_RE.match(name)
            if not m:
                continue
            self.shards_seen += 1
            if not include_own and name == own:
                continue  # own appends are applied at write time
            spath = os.path.join(self.union_dir, name)
            try:
                # chaos site: a failed shard scan must degrade to a
                # stale view, never crash the claim loop (checked
                # outside the lock — injected hangs must not block
                # the heartbeat thread's appends)
                faults.check("ledger_scan", key=spath)
                with self._iolock:
                    n += self._read_shard(spath, int(m.group(1)))
            except (faults.InjectedFault, OSError):
                self.scan_errors += 1
                continue
        for rec in self.all_entries.values():
            # seq must be monotone across EVERY workload sharing the
            # shard files, or a later pass's records would lose the
            # union tie-break to an earlier pass's
            self._seq = max(self._seq, int(rec.get("seq") or 0))
        return n

    def _append(self, key, state, **fields):
        if self._fh is None:
            raise RuntimeError("WorkQueue opened readonly")
        # chaos site: an injected append fault is a hard crash (full
        # disk, killed process) — nothing is recorded, and the resume
        # path must reconstruct from what IS on disk
        faults.check("ledger_append", key=key)
        # ambient trace context (obs/tracing.py): ledger transitions
        # made while serving a traced request/archive carry the trace
        # id, so lease takeovers and replays are causally auditable
        trace_id = tracing.current_trace_id()
        with self._iolock:
            self._seq += 1
            rec = {"t": round(time.time(), 6), "archive": key,
                   "state": state, "seq": self._seq,
                   "workload": self.workload}
            if trace_id is not None:
                rec["trace"] = trace_id
            if self.owner is not None:
                rec["owner"] = self.owner
            prev = self.entries.get(key)
            rec["attempts"] = int(fields.pop(
                "attempts", (prev or {}).get("attempts", 0)))
            rec.update(fields)
            if self.union_dir is not None:
                self._apply(rec, self.process_index)
            else:
                if key not in self.entries:
                    self._order.append(key)
                self.entries[key] = rec
                self.all_entries[(self.workload, key)] = rec
            # the ledger append IS _iolock's critical section (docs/RUNNER.md) (jaxlint J006)
            self._fh.write(json.dumps(rec) + "\n")  # jaxlint: disable=J006
            # flushed before the lease becomes visible to peers (jaxlint J006)
            self._fh.flush()  # jaxlint: disable=J006
        return rec

    def _recover(self):
        """Crash recovery: running -> pending (the fit never finished).

        In union mode only THIS process's own stale claims are
        recovered (an older epoch of the same process index); other
        owners' claims are left to lease expiry — their process may be
        alive and mid-fit.
        """
        for key, rec in list(self.entries.items()):
            if rec["state"] != RUNNING:
                continue
            own = rec.get("owner")
            if self.union_dir is not None:
                if own == self.owner:
                    continue  # cannot happen on open, but be safe
                if owner_pid(own) != self.process_index:
                    continue  # someone else's lease: expiry handles it
                self._append(key, PENDING, reason="recovered_from_crash",
                             prev_owner=own)
            else:
                self._append(key, PENDING, reason="recovered_from_crash")

    def close(self):
        if self._fh is None:
            return
        try:
            self._fh.close()
        except OSError:
            pass

    # -- transitions ----------------------------------------------------

    @staticmethod
    def key_for(path):
        return os.path.realpath(path)

    def add(self, paths):
        """Register archives as pending; known archives (in ANY shard
        of a union) keep their state (idempotent across resumes)."""
        for path in paths:
            key = self.key_for(path)
            if key not in self.entries:
                self._append(key, PENDING, path=path)

    def claim(self, path, lease_s=None, **extra_fields):
        """Claim an archive for this owner.

        Without an owner this is the legacy bare ``running`` append.
        With one, the record carries ``owner`` + ``lease_expires_at``;
        taking over another owner's expired (or revoked) claim first
        appends a visible ``pending`` revocation and tags the new claim
        with ``takeover_from``, so the ledger narrates every takeover.
        The caller must re-check :meth:`owns` after a
        :meth:`refresh` — a concurrent double-claim is resolved by the
        deterministic ``(t, owner)`` union order and the loser must
        abandon with no further transition.  ``extra_fields`` ride on
        the claim record (the toas workload stamps the upstream zap
        decision chain here — runner/workloads.py).
        """
        key = self.key_for(path)
        if self.owner is None:
            return self._append(key, RUNNING, **extra_fields)
        prev = self.entries.get(key)
        fields = {"lease_expires_at": round(
            time.time() + (self.lease_s if lease_s is None
                           else float(lease_s)), 6)}
        if prev is not None:
            if prev.get("state") == RUNNING \
                    and prev.get("owner") != self.owner:
                # visible revocation: the dead owner's lease expires
                # into the pool as an explicit ledger transition
                self._append(key, PENDING, reason="lease_expired",
                             prev_owner=prev.get("owner"),
                             attempts=prev.get("attempts", 0))
                fields["takeover_from"] = prev.get("owner")
            elif prev.get("prev_owner") \
                    and prev.get("prev_owner") != self.owner:
                # claimed straight off a revocation/recovery record
                fields["takeover_from"] = prev.get("prev_owner")
        fields.update(extra_fields)
        return self._append(key, RUNNING, **fields)

    def renew(self, path):
        """Heartbeat: extend this owner's lease with a fresh append.
        No-op (returns None) once the archive is no longer this
        owner's — ownership is verified against a *refreshed* union
        first, because a renewal appended over an unseen takeover
        would steal the archive back and double-fit it."""
        key = self.key_for(path)
        self.refresh()
        rec = self.entries.get(key)
        if self.owner is None or rec is None \
                or rec.get("state") != RUNNING \
                or rec.get("owner") != self.owner:
            return None
        # chaos site: a failed renewal lets the lease run out — the
        # fit's completion guard must then abandon without transitions
        faults.check("lease_renew", key=key)
        return self._append(
            key, RUNNING,
            lease_expires_at=round(time.time() + self.lease_s, 6),
            renewals=int(rec.get("renewals", 0)) + 1)

    def owns(self, path, refresh=False):
        """True when this owner holds the archive's current ``running``
        record in the union view (always True without lease mode)."""
        if self.owner is None:
            return True
        if refresh:
            self.refresh()
        rec = self.entries.get(self.key_for(path))
        return rec is not None and rec.get("state") == RUNNING \
            and rec.get("owner") == self.owner

    def revoke(self, path, reason):
        """Force another owner's ``running`` claim back to pending
        (barrier-named straggler, operator action).  Returns the
        revocation record, or None when there is nothing to revoke."""
        key = self.key_for(path)
        rec = self.entries.get(key)
        if rec is None or rec.get("state") != RUNNING \
                or rec.get("owner") == self.owner:
            return None
        return self._append(key, PENDING, reason=str(reason),
                            prev_owner=rec.get("owner"),
                            attempts=rec.get("attempts", 0))

    def revoke_owner(self, process_index, reason):
        """Revoke every ``running`` lease held by a process index (the
        ``BarrierTimeout.missing`` straggler path).  Returns the
        revocation records."""
        out = []
        for key, rec in list(self.entries.items()):
            if rec.get("state") == RUNNING \
                    and rec.get("owner") != self.owner \
                    and owner_pid(rec.get("owner")) == process_index:
                out.append(self._append(
                    key, PENDING, reason=str(reason),
                    prev_owner=rec.get("owner"),
                    attempts=rec.get("attempts", 0)))
        return out

    def complete(self, path, **info):
        if self.process_index is not None:
            # which process's .tim checkpoint holds this archive's
            # block (reconcile + elastic resume need to know)
            info.setdefault("ckpt", int(self.process_index))
        return self._append(self.key_for(path), DONE, **info)

    def fail(self, path, reason):
        """Transient failure: retry with exponential backoff until
        ``max_attempts``, then quarantine with the chain recorded."""
        key = self.key_for(path)
        attempts = self.entries.get(key, {}).get("attempts", 0) + 1
        if attempts >= self.max_attempts:
            return self._append(
                key, QUARANTINED, attempts=attempts,
                reason=f"retries exhausted ({attempts}): {reason}")
        span = self.backoff_s * 2 ** (attempts - 1)
        retry_at = time.time() + span * _jitter_factor(key, attempts)
        return self._append(key, FAILED, attempts=attempts,
                            reason=str(reason),
                            retry_at=round(retry_at, 6))

    def quarantine(self, path, reason):
        """Poison archive: terminal, with the reason on record."""
        return self._append(self.key_for(path), QUARANTINED,
                            reason=str(reason))

    def reset(self, path, reason):
        """Force an archive back to pending (ledger/checkpoint
        reconciliation — see execute.py)."""
        return self._append(self.key_for(path), PENDING,
                            reason=str(reason))

    # -- queries --------------------------------------------------------

    def state(self, path):
        rec = self.entries.get(self.key_for(path))
        return rec["state"] if rec else None

    def record(self, path):
        return self.entries.get(self.key_for(path))

    def shard_of(self, path):
        """Shard pid whose record currently wins for this archive
        (union mode; None single-shard)."""
        return self._shard_of.get(self.key_for(path))

    def ready(self, path, now=None):
        """True when the archive should be (re)fit now: pending,
        failed with its backoff elapsed, or — in union/lease mode —
        ``running`` under another owner's *expired* lease (a lease no
        one can renew counts as expired immediately)."""
        rec = self.entries.get(self.key_for(path))
        if rec is None:
            return False
        if rec["state"] == PENDING:
            return True
        if rec["state"] == FAILED:
            now = time.time() if now is None else now
            return now >= rec.get("retry_at", 0.0)
        if rec["state"] == RUNNING and self.union_dir is not None \
                and self.owner is not None \
                and rec.get("owner") != self.owner:
            exp = rec.get("lease_expires_at")
            if exp is None:
                return True  # unrenewable legacy claim: claimable
            now = time.time() if now is None else now
            return now >= exp
        return False

    def outstanding(self):
        """Archives not yet done or quarantined (pending, failed
        awaiting backoff, or running), in first-seen order."""
        return [k for k in self._order
                if self.entries[k]["state"] in (PENDING, RUNNING, FAILED)]

    def done(self):
        return {k for k in self._order
                if self.entries[k]["state"] == DONE}

    def quarantined(self):
        """[(archive, reason)] for every quarantined archive."""
        return [(k, self.entries[k].get("reason", ""))
                for k in self._order
                if self.entries[k]["state"] == QUARANTINED]

    def counts(self):
        out = {s: 0 for s in _STATES}
        for rec in self.entries.values():
            out[rec["state"]] += 1
        return out

    def leases(self, now=None, all_workloads=False):
        """[{archive, workload, owner, lease_expires_at, expires_in,
        expired}] for every ``running`` entry — the ``ppsurvey
        status`` lease table.  ``all_workloads`` widens the scan to
        every workload sharing the workdir."""
        now = time.time() if now is None else now
        if all_workloads:
            recs = [(k, self.all_entries[(wl, k)])
                    for wl, k in sorted(self.all_entries)]
        else:
            recs = [(k, self.entries[k]) for k in self._order]
        out = []
        for k, rec in recs:
            if rec["state"] != RUNNING:
                continue
            exp = rec.get("lease_expires_at")
            out.append({
                "archive": k,
                "workload": record_workload(rec),
                "owner": rec.get("owner"),
                "lease_expires_at": exp,
                "expires_in": None if exp is None
                else round(exp - now, 3),
                "expired": exp is None or now >= exp})
        return out

    # -- cross-workload queries (runner/workloads.py, status views) -----

    def workloads_seen(self):
        """Sorted workload names present anywhere in the union view."""
        return sorted({wl for wl, _ in self.all_entries})

    def record_for(self, workload, path):
        """Latest record for an archive under ANY workload (the toas
        pass reads the zap pass's decisions through this)."""
        return self.all_entries.get(
            (str(workload), self.key_for(path)))

    def entries_for(self, workload):
        """{realpath: record} snapshot of one workload's entries."""
        workload = str(workload)
        return {k: rec for (wl, k), rec in self.all_entries.items()
                if wl == workload}

    def counts_by_workload(self):
        """{workload: {state: n}} across the whole union view."""
        out = {}
        for (wl, _), rec in self.all_entries.items():
            per = out.setdefault(wl, {s: 0 for s in _STATES})
            per[rec["state"]] += 1
        return out
