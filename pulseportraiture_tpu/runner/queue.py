"""Persistent survey work queue: a crash-safe JSONL state ledger.

One line is appended per state transition, so the ledger is crash-safe
by construction (a torn tail line is dropped on replay) and the full
history of every archive — attempts, failure reasons, timestamps — is
preserved for the final survey report.  Replaying the file left to
right reconstructs current state: the **last** record per archive
wins.

States::

    pending -> running -> done
                       -> failed (transient; bounded retries with
                                  exponential backoff) -> pending
                       -> quarantined (poison: corrupt file, model
                                       mismatch, retries exhausted)

``running`` entries found at load time are crash leftovers (the fit
never completed) and are reverted to ``pending``, mirroring how the
``.tim`` checkpoint drops unterminated archive blocks
(pipelines/toas.py).  Quarantined archives are terminal: they are
reported with their reason, never silently retried — one corrupt
PSRFITS file must not be able to wedge a week-long run in a retry
loop.
"""

import hashlib
import json
import os
import threading
import time

from ..testing import faults

__all__ = ["WorkQueue", "PENDING", "RUNNING", "DONE", "FAILED",
           "QUARANTINED"]

PENDING = "pending"
RUNNING = "running"
DONE = "done"
FAILED = "failed"
QUARANTINED = "quarantined"

_STATES = (PENDING, RUNNING, DONE, FAILED, QUARANTINED)


def _jitter_factor(key, attempts):
    """Deterministic backoff jitter in [0.5, 1.0), seeded from the
    archive path + attempt number.

    A bare ``backoff_s * 2**(attempts-1)`` is identical across every
    process of a multihost run, so one shared transient (tunnel blip,
    NFS hiccup) produces a synchronized retry stampede.  Hashing the
    key decorrelates the retry times across archives and processes
    while keeping every individual schedule exactly reproducible —
    no global randomness, so tests (and reruns) see the same ledger.
    """
    h = hashlib.sha1(("%s|%d" % (key, int(attempts)))
                     .encode("utf-8", "replace")).digest()
    return 0.5 + int.from_bytes(h[:8], "big") / 2.0 ** 65


class WorkQueue:
    """On-disk per-archive state machine for one survey (one process).

    Archives are keyed by ``os.path.realpath`` so resumed runs match
    regardless of path spelling, exactly like the checkpoint resume in
    pipelines/toas.py.  All writes are appends flushed per line.
    """

    def __init__(self, path, max_attempts=3, backoff_s=1.0,
                 readonly=False):
        self.path = path
        self.max_attempts = int(max_attempts)
        self.backoff_s = float(backoff_s)
        self.readonly = bool(readonly)
        self.entries = {}      # realpath -> latest record (dict)
        self._order = []       # insertion order of first sighting
        # appends may race between the survey loop and its dispatch
        # watchdog settling an abandoned archive (runner/execute.py)
        self._iolock = threading.Lock()
        if os.path.isfile(path):
            self._replay()
        if self.readonly:
            # inspection only (ppsurvey status): no appends, and no
            # crash recovery — a live run may own the file
            self._fh = None
            return
        self._fh = open(path, "a", encoding="utf-8")
        self._recover()

    # -- persistence ----------------------------------------------------

    def _replay(self):
        with open(self.path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail line from a crash
                key = rec.get("archive")
                if key is None or rec.get("state") not in _STATES:
                    continue
                if key not in self.entries:
                    self._order.append(key)
                self.entries[key] = rec

    def _append(self, key, state, **fields):
        if self._fh is None:
            raise RuntimeError("WorkQueue opened readonly")
        # chaos site: an injected append fault is a hard crash (full
        # disk, killed process) — nothing is recorded, and the resume
        # path must reconstruct from what IS on disk
        faults.check("ledger_append", key=key)
        with self._iolock:
            rec = {"t": round(time.time(), 6), "archive": key,
                   "state": state}
            prev = self.entries.get(key)
            rec["attempts"] = int(fields.pop(
                "attempts", (prev or {}).get("attempts", 0)))
            rec.update(fields)
            if key not in self.entries:
                self._order.append(key)
            self.entries[key] = rec
            self._fh.write(json.dumps(rec) + "\n")
            self._fh.flush()
        return rec

    def _recover(self):
        """Crash recovery: running -> pending (the fit never finished)."""
        for key, rec in list(self.entries.items()):
            if rec["state"] == RUNNING:
                self._append(key, PENDING, reason="recovered_from_crash")

    def close(self):
        if self._fh is None:
            return
        try:
            self._fh.close()
        except OSError:
            pass

    # -- transitions ----------------------------------------------------

    @staticmethod
    def key_for(path):
        return os.path.realpath(path)

    def add(self, paths):
        """Register archives as pending; known archives keep their
        state (idempotent across resumes)."""
        for path in paths:
            key = self.key_for(path)
            if key not in self.entries:
                self._append(key, PENDING, path=path)

    def claim(self, path):
        return self._append(self.key_for(path), RUNNING)

    def complete(self, path, **info):
        return self._append(self.key_for(path), DONE, **info)

    def fail(self, path, reason):
        """Transient failure: retry with exponential backoff until
        ``max_attempts``, then quarantine with the chain recorded."""
        key = self.key_for(path)
        attempts = self.entries.get(key, {}).get("attempts", 0) + 1
        if attempts >= self.max_attempts:
            return self._append(
                key, QUARANTINED, attempts=attempts,
                reason=f"retries exhausted ({attempts}): {reason}")
        span = self.backoff_s * 2 ** (attempts - 1)
        retry_at = time.time() + span * _jitter_factor(key, attempts)
        return self._append(key, FAILED, attempts=attempts,
                            reason=str(reason),
                            retry_at=round(retry_at, 6))

    def quarantine(self, path, reason):
        """Poison archive: terminal, with the reason on record."""
        return self._append(self.key_for(path), QUARANTINED,
                            reason=str(reason))

    def reset(self, path, reason):
        """Force an archive back to pending (ledger/checkpoint
        reconciliation — see execute.py)."""
        return self._append(self.key_for(path), PENDING,
                            reason=str(reason))

    # -- queries --------------------------------------------------------

    def state(self, path):
        rec = self.entries.get(self.key_for(path))
        return rec["state"] if rec else None

    def record(self, path):
        return self.entries.get(self.key_for(path))

    def ready(self, path, now=None):
        """True when the archive should be (re)fit now: pending, or
        failed with its backoff elapsed."""
        rec = self.entries.get(self.key_for(path))
        if rec is None:
            return False
        if rec["state"] == PENDING:
            return True
        if rec["state"] == FAILED:
            now = time.time() if now is None else now
            return now >= rec.get("retry_at", 0.0)
        return False

    def outstanding(self):
        """Archives not yet done or quarantined (pending, failed
        awaiting backoff, or running), in first-seen order."""
        return [k for k in self._order
                if self.entries[k]["state"] in (PENDING, RUNNING, FAILED)]

    def done(self):
        return {k for k in self._order
                if self.entries[k]["state"] == DONE}

    def quarantined(self):
        """[(archive, reason)] for every quarantined archive."""
        return [(k, self.entries[k].get("reason", ""))
                for k in self._order
                if self.entries[k]["state"] == QUARANTINED]

    def counts(self):
        out = {s: 0 for s in _STATES}
        for rec in self.entries.values():
            out[rec["state"]] += 1
        return out
