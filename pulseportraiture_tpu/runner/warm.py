"""Bucket warm-up: AOT compile + prime every planned fit program.

BENCH_r05 measured a three-shape survey at 336.4 s cold vs 42.5 s warm
— ~294 s of pure compile churn neither a resident service nor a
rescheduled survey worker should pay on the request/fit path.  This
module is the ONE warm implementation shared by the daemon
(``ppserve warm``, service/warm.py re-exports it) and the batch engine
(``ppsurvey warm`` / ``ppsurvey run --warm``): it turns a
:class:`~.plan.SurveyPlan` bucket enumeration into the set of
*programs* the pipeline will actually dispatch and makes each one warm
before the first real archive:

* **Program enumeration** (:func:`program_specs`): archives group by
  ``(bucket shape, nsub)`` — the batched solver's program identity is
  the padded batch shape (``fit/portrait.bucket_batch_size`` /
  ``auto_scan_size``), and the guess-stage programs (rotate, FFTFIT
  seed, per-subint reductions) key on the raw ``nsub``.  ``coalesce``
  multipliers add the combined-batch solver programs the micro-batcher
  (service/batcher.py) will dispatch when several requests share a
  cycle.  ``workloads`` extends the enumeration beyond GetTOAs: the
  workload engine's align/zap/modelfit program sets key on the same
  bucket classes and get one spec per ``(bucket, nsub)`` each.
* **AOT stage** (``aot=True``): each solver program is compiled ahead
  of time via ``jit(...).lower().compile()``
  (``fit_portrait_full_batch(..., aot=True)``) so the XLA result
  lands in the **persistent compilation cache** when one is configured
  (:func:`enable_persistent_cache`) — a restarted/rescheduled daemon
  or survey worker retrieves it instead of recompiling (the obs
  ``compile_cache_hits``/``compile_cache_misses`` counters audit
  exactly that, docs/OBSERVABILITY.md).
* **Execution stage**: each ``(bucket, nsub)`` class then runs ONE
  synthetic archive end-to-end through the real driver (``GetTOAs``
  for toas; the align block math, the zap proposal walk, or a gaussian
  model fit for the workload-engine variants) — this is what fills the
  *in-process* jit caches for the whole program set, so a post-warm
  archive on a planned bucket triggers **zero** new XLA compiles (the
  ISSUE 7/15 warm-path acceptance; asserted via the obs
  ``backend_compiles`` counter).

Synthetic archives are built in memory from the caller's own model
when one is given (no FITS round trip, any model type) or from a
canonical gaussian pulse for the model-free workloads: data = model +
noise at exactly the bucket's canonical shape, so shapes and dtypes
match what a padded real archive produces.  Every warmed program
emits a ``warm_program`` obs event carrying its compile delta and
persistent-cache hit/miss delta; a failing program records its error
and the warm pass continues — warm is never fatal.
"""

import os
import time

import numpy as np

from .. import obs
from ..testing import faults
from ..utils.databunch import DataBunch
from ..utils.mjd import MJD
from .plan import SurveyPlan

__all__ = ["WarmSpec", "program_specs", "warm_plan",
           "enable_persistent_cache", "synth_databunch",
           "solver_program", "write_warm_archive"]

#: workloads the warm pass knows how to prime (runner/workloads.py
#: names); anything else enumerates no specs
WARM_WORKLOADS = ("toas", "zap", "align", "modelfit")


def enable_persistent_cache(cache_dir):
    """Point jax's persistent compilation cache at ``cache_dir``
    (delegates to ``config.set_compile_cache_dir`` — global jax policy
    lives in config.py, jaxlint J005).

    Degrades, never fails: a corrupt/unwritable cache dir (or an
    injected ``compile_cache`` fault) emits a ``compile_cache_degraded``
    obs event and returns False — the run proceeds with normal
    first-use JIT compiles.  Returns True when the cache is active.
    """
    try:
        faults.check("compile_cache", key=str(cache_dir))
        cache_dir = os.path.abspath(str(cache_dir))
        os.makedirs(cache_dir, exist_ok=True)
        if not os.access(cache_dir, os.W_OK):
            raise OSError("compile-cache dir not writable: %s"
                          % cache_dir)
        from ..config import set_compile_cache_dir

        set_compile_cache_dir(cache_dir)
        return True
    except Exception as e:
        obs.event("compile_cache_degraded", cache_dir=str(cache_dir),
                  error="%s: %s" % (type(e).__name__, e))
        obs.counter("compile_cache_degraded")
        return False


def solver_program(nsub):
    """(scan_size, padded_batch) identity of the batched-solver program
    a ``nsub``-row fit dispatches — must mirror the pipeline exactly
    (pipelines/toas.py + fit_portrait_full_batch's target logic)."""
    from ..fit.portrait import auto_scan_size, bucket_batch_size

    scan = auto_scan_size(nsub)
    if scan is None:
        return None, max(nsub, bucket_batch_size(nsub))
    if nsub <= scan:
        return None, nsub
    return scan, -(-nsub // scan) * scan


class WarmSpec:
    """One program class to warm."""

    __slots__ = ("bucket", "native", "nsub", "n_archives", "kind",
                 "batch", "scan_size", "nu0", "bw", "workload")

    def __init__(self, bucket, nsub, n_archives=1, kind="archive",
                 native=None, nu0=1500.0, bw=800.0, workload="toas"):
        self.bucket = tuple(bucket)
        self.native = tuple(native) if native else self.bucket
        self.nsub = int(nsub)
        self.n_archives = int(n_archives)
        self.kind = kind  # "archive" (full pipeline) | "coalesced"
        self.workload = str(workload)
        self.scan_size, self.batch = solver_program(self.nsub)
        self.nu0 = float(nu0) or 1500.0
        self.bw = float(bw) or 800.0

    def to_dict(self):
        return {"bucket": "%dx%d" % self.bucket,
                "native": "%dx%d" % self.native, "nsub": self.nsub,
                "n_archives": self.n_archives, "kind": self.kind,
                "batch": self.batch, "scan_size": self.scan_size,
                "workload": self.workload}


def program_specs(plan, coalesce=(), workloads=("toas",)):
    """Enumerate the programs a plan's buckets will dispatch.

    Archive specs group by ``(bucket, native shape, nsub)``: the
    solver programs key on the padded bucket+batch shape, but the
    load-path estimates (io/archive.load_data) run at the archive's
    *native* shape before padding, so each native class warms its own
    end-to-end walk.

    ``coalesce``: extra batch multipliers K — for each bucket, the
    combined-batch solver program of K modal-``nsub`` archives sharing
    one micro-batch cycle.  Combined programs that pad to a batch
    already covered by a per-archive spec are skipped (power-of-two
    bucketing makes that the common case).  Coalescing only applies to
    the toas workload (the micro-batcher serves GetTOAs requests).

    ``workloads``: which engines' program sets to enumerate — any of
    ``("toas", "zap", "align", "modelfit")``; each non-toas workload
    adds one spec per ``(bucket, native, nsub)`` class with
    ``spec.workload`` set, warmed by that workload's own executor.
    """
    if isinstance(plan, str):
        plan = SurveyPlan.load(plan)
    groups = {}
    for info, bucket in plan.archives():
        key = (bucket.key, (info.nchan, info.nbin), info.nsub)
        if key not in groups:
            groups[key] = WarmSpec(bucket.key, info.nsub, 0,
                                   native=(info.nchan, info.nbin),
                                   nu0=info.nu0, bw=info.bw)
        groups[key].n_archives += 1
    specs = sorted(groups.values(),
                   key=lambda s: (s.bucket, s.native, s.nsub))
    out = []
    if "toas" in workloads:
        out.extend(specs)
        # coalesced specs dedupe only among themselves: even when the
        # PADDED solver program matches an archive spec's, the
        # batch-glue programs (broadcasts/stacks in
        # fit_portrait_full_batch) key on the raw combined batch size,
        # so each distinct total must run
        covered = set()
        for spec in specs:
            for k in coalesce:
                if k <= 1:
                    continue
                c = WarmSpec(spec.bucket, spec.nsub * int(k),
                             spec.n_archives, kind="coalesced",
                             nu0=spec.nu0, bw=spec.bw)
                ident = (c.bucket, c.nsub)
                if c.nsub != spec.nsub and ident not in covered:
                    covered.add(ident)
                    out.append(c)
    for wl in workloads:
        if wl == "toas" or wl not in WARM_WORKLOADS:
            continue
        for spec in specs:
            out.append(WarmSpec(spec.bucket, spec.nsub,
                                spec.n_archives, native=spec.native,
                                nu0=spec.nu0, bw=spec.bw, workload=wl))
    return out


def _bucket_freqs(spec, native=False):
    """Per-channel frequencies for the spec's native or bucket grid
    (shapes are what matter; the values only steer the model
    evaluation)."""
    nchan = spec.native[0] if native else spec.bucket[0]
    step = spec.bw / nchan
    return spec.nu0 + step * (np.arange(nchan) + 0.5) - spec.bw / 2.0


def _synth_model(nchan, nbin):
    """Canonical gaussian pulse portrait for the model-free workloads
    (zap/align/modelfit warm only needs data of the right *shape* with
    one resolvable component)."""
    phases = (np.arange(nbin) + 0.5) / nbin
    prof = np.exp(-0.5 * ((phases - 0.5) / 0.05) ** 2)
    return np.broadcast_to(prof, (nchan, nbin)).copy()


def synth_databunch(model, freqs, nsub, P=0.005, noise_frac=0.02,
                    seed=0, name="warm"):
    """In-memory DataBunch shaped like a loaded+padded archive: data is
    the model plus ``noise_frac`` noise, all channels live."""
    rng = np.random.default_rng(seed)
    model = np.asarray(model, dtype=np.float64)
    nchan, nbin = model.shape
    sigma = noise_frac * max(float(np.abs(model).max()), 1e-12)
    subints = np.broadcast_to(model, (nsub, 1, nchan, nbin)) \
        + rng.normal(0.0, sigma, (nsub, 1, nchan, nbin))
    freqs_b = np.broadcast_to(np.asarray(freqs, dtype=np.float64),
                              (nsub, nchan)).copy()
    noise_stds = np.full((nsub, 1, nchan), sigma)
    snr = np.abs(model).mean(-1) / sigma
    return DataBunch(
        arch=None, backend="warm", backend_delay=0.0,
        bw=float(freqs[-1] - freqs[0]) if nchan > 1 else 1.0,
        doppler_factors=np.ones(nsub), doppler_degraded=False,
        DM=0.0, dmc=False,
        epochs=[MJD.from_mjd(56000.0 + 1e-5 * i) for i in range(nsub)],
        filename=name, flux_prof=None, freqs=freqs_b, frontend="warm",
        integration_length=nsub * 1.0,
        masks=np.ones((nsub, 1, nchan, nbin)), nbin=nbin, nchan=nchan,
        noise_stds=noise_stds, npol=1, nsub=nsub,
        nu0=float(np.mean(freqs)),
        ok_ichans=[np.arange(nchan)] * nsub,
        ok_isubs=np.arange(nsub),
        parallactic_angles=np.zeros(nsub),
        phases=(np.arange(nbin) + 0.5) / nbin,
        prof=model.mean(0), prof_noise=sigma / np.sqrt(nchan),
        prof_SNR=float(snr.mean()) * nchan,
        Ps=np.full(nsub, float(P)),
        SNRs=np.broadcast_to(snr, (nsub, 1, nchan)).copy(),
        source=name, state="warm", subints=subints,
        subtimes=np.full(nsub, 60.0), telescope="warm",
        telescope_code="0", weights=np.ones((nsub, nchan)))


def _fit_kwargs(get_toas_kw):
    """The fit-configuration subset of the driver kwargs (the statics
    that shape compiled programs)."""
    kw = dict(get_toas_kw or {})
    out = {}
    for key in ("tscrunch", "fit_DM", "fit_GM", "fit_scat",
                "log10_tau", "fix_alpha", "max_iter", "bary",
                "polish_iter", "coarse_iter", "coarse_kmax",
                "nonfinite_max_frac"):
        if key in kw:
            out[key] = kw[key]
    return out


class _CompileWatch:
    """Compile / persistent-cache counter deltas around a warm step,
    read from the active obs recorder (0s when obs is off)."""

    KEYS = ("backend_compiles", "compile_cache_hits",
            "compile_cache_misses")

    def __init__(self):
        self._rec = obs.current()
        self._base = self._snap()

    def _snap(self):
        if self._rec is None:
            return {k: 0 for k in self.KEYS}
        return {k: int(self._rec.counters.get(k, 0)) for k in self.KEYS}

    def delta(self):
        now = self._snap()
        return {k: now[k] - self._base[k] for k in self.KEYS}


_WARM_EPHEMERIS = ("PSR WARM\nRAJ 00:00:00\nDECJ 00:00:00\n"
                   "F0 200.0\nPEPOCH 56000.0\nDM 0.0\n")


def write_warm_archive(spec, model, outfile, seed=0):
    """Unload a synthetic PSRFITS archive of the spec's *native* shape
    (data = ``model`` + noise) — model-agnostic, unlike
    ``io.archive.make_fake_pulsar`` (which needs a .gmodel)."""
    from ..io.psrfits import Archive

    nchan, nbin = spec.native
    rng = np.random.default_rng(seed)
    model = np.asarray(model, dtype=np.float64)
    sigma = 0.02 * max(float(np.abs(model).max()), 1e-12)
    data = np.broadcast_to(model, (spec.nsub, 1, nchan, nbin)) \
        + rng.normal(0.0, sigma, (spec.nsub, 1, nchan, nbin))
    freqs = _bucket_freqs(spec, native=True)
    epochs = [MJD.from_mjd(56000.0 + 1e-3 * i)
              for i in range(spec.nsub)]
    arch = Archive(data, freqs, np.ones((spec.nsub, nchan)),
                   np.full(spec.nsub, 0.005), epochs,
                   np.full(spec.nsub, 60.0), DM=0.0,
                   dedispersed=False, source="WARM",
                   nu0=spec.nu0, bw=spec.bw,
                   ephemeris_text=_WARM_EPHEMERIS,
                   doppler_factors=np.ones(spec.nsub),
                   parallactic_angles=np.zeros(spec.nsub))
    arch.unload(outfile, quiet=True)
    return outfile


def _warm_archive_spec(spec, modelfile, get_toas_kw, aot, narrowband,
                       quiet, workdir=None):
    """Run one synthetic archive of the spec's class end-to-end —
    PSRFITS write, real ``load_data``, bucket padding, guess, fit —
    AOT-compiling the solver program first.  The real load path
    matters: its estimate programs are part of a request's compile
    footprint too."""
    import shutil
    import tempfile

    from ..fit.portrait import fit_portrait_full_batch
    from .execute import _BucketedGetTOAs

    tmp = tempfile.mkdtemp(prefix="ppwarm_", dir=workdir)
    try:
        gt0 = _BucketedGetTOAs([], modelfile, spec.bucket, quiet=True)
        nchan, nbin = spec.native
        model = gt0._build_model(
            _bucket_freqs(spec, native=True),
            (np.arange(nbin) + 0.5) / nbin, 0.005,
            fit_scat=bool((get_toas_kw or {}).get("fit_scat")))
        path = write_warm_archive(
            spec, model, os.path.join(tmp, "warm_%dx%d_n%d.fits"
                                      % (spec.native + (spec.nsub,))))

        gt = _BucketedGetTOAs([path], modelfile, spec.bucket,
                              quiet=True)
        aot_state = {"done": False}

        def warm_fit(*args, **kw):
            if aot and not aot_state["done"]:
                # jit(...).lower().compile() with the exact argument
                # set the execution below will use: the XLA result
                # lands in the persistent compile cache for the NEXT
                # process
                fit_portrait_full_batch(*args, aot=True, **kw)
                aot_state["done"] = True
            return fit_portrait_full_batch(*args, **kw)

        gt.fit_batch = warm_fit
        fit_kw = _fit_kwargs(get_toas_kw)
        if narrowband:
            for key in ("bary", "fit_DM", "fit_GM", "fix_alpha"):
                fit_kw.pop(key, None)
            gt.get_narrowband_TOAs(datafile=path, quiet=True, **fit_kw)
        else:
            gt.get_TOAs(datafile=path, quiet=True, **fit_kw)
        if not gt.order and not quiet:
            print("warm: %s produced no fit (model/config mismatch?)"
                  % path)
        return len(gt.order) > 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _warm_coalesced_spec(spec, modelfile, get_toas_kw, aot):
    """Warm a combined-batch solver program directly (the micro-batch
    dispatch shape; the guess stage stays per-archive and is covered
    by the archive specs)."""
    from ..fit.portrait import (bucket_batch_size, fit_portrait_full_batch,
                                model_kmax)
    from .execute import _BucketedGetTOAs

    gt = _BucketedGetTOAs([], modelfile, spec.bucket, quiet=True)
    freqs = _bucket_freqs(spec)
    fit_kw = _fit_kwargs(get_toas_kw)
    fit_scat = bool(fit_kw.get("fit_scat"))
    model = np.asarray(gt._build_model(
        freqs, (np.arange(spec.bucket[1]) + 0.5) / spec.bucket[1],
        0.005, fit_scat=fit_scat))
    d = synth_databunch(model, freqs, spec.nsub)
    B = spec.nsub
    ports = d.subints[:, 0]
    models_b = np.broadcast_to(model, ports.shape)
    nu_mean = float(np.mean(freqs))
    init = np.stack([np.zeros(B), np.full(B, d.DM), np.zeros(B),
                     np.zeros(B), np.zeros(B)], axis=1)
    flags = (1, int(fit_kw.get("fit_DM", True)),
             int(fit_kw.get("fit_GM", False)), 0, 0)
    kw = dict(errs=d.noise_stds[:, 0], weights=d.weights,
              fit_flags=flags, nu_fits=np.full((B, 3), nu_mean),
              nu_outs=None, bounds=None, log10_tau=False,
              max_iter=int(fit_kw.get("max_iter", 50)),
              scan_size=spec.scan_size,
              pad_to=None if spec.scan_size is not None
              else bucket_batch_size(B),
              polish_iter=fit_kw.get("polish_iter"),
              coarse_iter=fit_kw.get("coarse_iter"),
              coarse_kmax=fit_kw.get("coarse_kmax"),
              kmax=model_kmax(model))
    if aot:
        fit_portrait_full_batch(ports, models_b, init, d.Ps, d.freqs,
                                aot=True, **kw)
    fit_portrait_full_batch(ports, models_b, init, d.Ps, d.freqs, **kw)
    return True


def _warm_zap_spec(spec):
    """Prime the zap proposal walk at the spec's native shape.

    ``pipelines/zap.get_zap_channels`` is pure numpy — this spec
    honestly records zero backend compiles; it exists so the warm
    report enumerates the workload's program set (and stays correct if
    the proposal stage ever moves on-device)."""
    from ..pipelines.zap import get_zap_channels

    freqs = _bucket_freqs(spec, native=True)
    d = synth_databunch(_synth_model(*spec.native), freqs, spec.nsub)
    get_zap_channels(d, nstd=3)
    return True


def _warm_align_spec(spec):
    """Prime the align block programs for the spec's native shape: one
    padded subint block through seed (``_rotate_batch`` at [B, nchan,
    nbin] and [B, npol, nchan, nbin], ``fit_phase_shift``), the
    batched (phi, DM) portrait fit, and the rotate-accumulate — the
    exact per-row math of ``AlignWorkload._accumulate``.

    Best-effort: at run time the template's (nchan, nbin) comes from
    the initial-guess archive; the plan's native shape is the right
    warm target for the self-aligned survey case (template built from
    the survey's own archives)."""
    from ..pipelines.align import _align_fit_accumulate, _assemble_block

    nchan, nbin = spec.native
    model_port = _synth_model(nchan, nbin)
    freqs = _bucket_freqs(spec, native=True)
    d = synth_databunch(model_port, freqs, spec.nsub)
    ok = np.asarray(d.ok_isubs)
    entry = dict(
        full=np.asarray(d.subints[ok]),
        freqs=np.asarray(d.freqs[ok]),
        errs=np.asarray(d.noise_stds[ok, 0]),
        SNRs=np.asarray(d.SNRs[ok, 0]),
        Ps=np.asarray(d.Ps[ok]),
        wok=(d.weights[ok] > 0.0).astype(float),
        chan_map=None, DM=float(d.DM))
    rows = [(entry, j) for j in range(len(ok))]
    aligned = np.zeros((1, nchan, nbin))
    weights = np.zeros((nchan, nbin))
    chunk_max = 128
    for i0 in range(0, len(rows), chunk_max):
        take = rows[i0:i0 + chunk_max]
        block, cmaps = _assemble_block(take, model_port, nchan, nchan,
                                       nbin, 1, chunk_max)
        _align_fit_accumulate(*block, chan_maps=cmaps, fit_dm=True,
                              max_iter=30, nbin=nbin, npol=1,
                              aligned_port=aligned,
                              total_weights=weights)
    return True


def _warm_modelfit_spec(spec, workdir=None):
    """Prime the gaussian model-fit programs (``lm_solve`` via
    ``make_gaussian_model``) against a synthetic archive of the spec's
    native shape.

    Best-effort: the LM program set keys on the seeded component count,
    which for real data depends on the profile — the canonical
    single-gaussian warm covers the dominant programs."""
    import shutil
    import tempfile

    from ..models.gauss import GaussianModelPortrait

    tmp = tempfile.mkdtemp(prefix="ppwarm_", dir=workdir)
    try:
        path = write_warm_archive(
            spec, _synth_model(*spec.native),
            os.path.join(tmp, "warm_%dx%d_n%d.fits"
                         % (spec.native + (spec.nsub,))))
        dp = GaussianModelPortrait(path, quiet=True)
        dp.make_gaussian_model(quiet=True)
        return True
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _warm_one(spec, modelfile, get_toas_kw, aot, narrowband, quiet):
    if spec.kind == "coalesced":
        return _warm_coalesced_spec(spec, modelfile, get_toas_kw, aot)
    if spec.workload == "zap":
        return _warm_zap_spec(spec)
    if spec.workload == "align":
        return _warm_align_spec(spec)
    if spec.workload == "modelfit":
        return _warm_modelfit_spec(spec)
    return _warm_archive_spec(spec, modelfile, get_toas_kw, aot,
                              narrowband, quiet)


def warm_plan(plan, modelfile=None, get_toas_kw=None, coalesce=(),
              aot=True, narrowband=False, quiet=True,
              workloads=("toas",)):
    """Warm every program a plan enumerates; returns the summary dict.

    Emits one ``warm_program`` obs event per spec (compile +
    persistent-cache deltas) and ``warm_programs``/``warm_compiles``
    counters.  Programs that were already warm in this process report
    ``compiles == 0`` — the idempotence a resumed daemon or survey
    worker relies on.  A failing program records its error in the
    event/summary (``ok=False``) and the pass continues: warm is
    best-effort by contract, never fatal.
    """
    specs = program_specs(plan, coalesce=coalesce, workloads=workloads)
    t0 = time.perf_counter()
    total = _CompileWatch()
    done = []
    for spec in specs:
        watch = _CompileWatch()
        ts = time.perf_counter()
        err = None
        try:
            ok = _warm_one(spec, modelfile, get_toas_kw, aot,
                           narrowband, quiet)
        except Exception as e:
            ok, err = False, "%s: %s" % (type(e).__name__, e)
        d = watch.delta()
        entry = dict(spec.to_dict(), ok=bool(ok),
                     dur_s=round(time.perf_counter() - ts, 6), **d)
        if err is not None:
            entry["error"] = err
        done.append(entry)
        # "kind" collides with the event sink's own field name
        obs.event("warm_program", **{
            ("program_kind" if k == "kind" else k): v
            for k, v in entry.items()})
        obs.counter("warm_programs")
        if d["backend_compiles"]:
            obs.counter("warm_compiles", d["backend_compiles"])
        if not quiet:
            print("warm: %(bucket)s nsub=%(nsub)d batch=%(batch)s "
                  "kind=%(kind)s workload=%(workload)s "
                  "compiles=%(backend_compiles)d "
                  "cache_hits=%(compile_cache_hits)d "
                  "cache_misses=%(compile_cache_misses)d "
                  "(%(dur_s).1fs)" % entry)
    summary = {"n_programs": len(done), "programs": done,
               "wall_s": round(time.perf_counter() - t0, 6)}
    summary.update(total.delta())
    obs.event("warm_done", n_programs=len(done),
              wall_s=summary["wall_s"],
              backend_compiles=summary["backend_compiles"],
              compile_cache_hits=summary["compile_cache_hits"],
              compile_cache_misses=summary["compile_cache_misses"])
    return summary
