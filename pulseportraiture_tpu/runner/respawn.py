"""Crash-loop backoff + flap detection shared by every respawn path.

Two respawn loops exist in the tree — the fleet router's daemon
respawn (service/router.py) and the survey supervisor's worker
respawn (runner/supervisor.py) — and both face the same failure
shape: a child that dies the instant it starts.  Respawning it
unconditionally burns CPU, floods ``pps_respawns_total`` and, in the
supervisor's case, can starve the healthy workers of the ledger lock.
This module is the one policy for that shape:

* **Exponential backoff** between consecutive deaths: the n-th strike
  waits ``backoff_s * 2**(n-1)`` seconds (capped at
  ``backoff_max_s``), decorrelated with the same deterministic jitter
  the work queue uses for retry stampedes (queue._jitter_factor).
  ``backoff_s=0`` disables the delay entirely — the router uses that
  to keep its below-threshold behavior exactly what it was before
  this module existed (immediate in-place respawn).
* **Flap quarantine**: ``flap_count`` deaths inside a sliding
  ``flap_window_s`` window parks the slot — ``record_death`` returns
  ``{"action": "park"}`` and every later call keeps returning it.  A
  parked slot is never respawned again; the caller emits its
  ``*_flap`` event and the survey/fleet degrades gracefully onto the
  survivors.

A child that stays up longer than the window prunes its old strikes
by construction (the window is evaluated against death timestamps),
so a slow leak that dies once an hour never escalates past strike 1.

Trackers are pure bookkeeping over caller-supplied clocks: nothing
here spawns, sleeps, or reads the wall clock, which is what makes the
supervisor's ``decide()`` table-testable.
"""

from .queue import _jitter_factor

__all__ = ["RespawnPolicy", "RespawnTracker", "RESPAWN", "PARK"]

RESPAWN = "respawn"
PARK = "park"


class RespawnPolicy(object):
    """Tunables for one family of slots (all daemons, all workers)."""

    __slots__ = ("backoff_s", "backoff_max_s", "flap_count",
                 "flap_window_s")

    def __init__(self, backoff_s=1.0, backoff_max_s=60.0, flap_count=5,
                 flap_window_s=60.0):
        if flap_count < 1:
            raise ValueError("flap_count must be >= 1")
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.flap_count = int(flap_count)
        self.flap_window_s = float(flap_window_s)

    def delay_s(self, key, strikes):
        """Backoff before the respawn that follows strike #strikes."""
        if self.backoff_s <= 0.0 or strikes <= 0:
            return 0.0
        raw = min(self.backoff_s * 2.0 ** (strikes - 1), self.backoff_max_s)
        return raw * _jitter_factor(str(key), strikes)


class RespawnTracker(object):
    """Per-slot death ledger: feed it deaths, obey its verdicts.

    ``record_death(now)`` returns either

    * ``{"action": "respawn", "delay_s": float, "not_before": now+delay,
       "strikes": n}`` — respawn after the backoff, or
    * ``{"action": "park", "deaths": k, "window_s": w, "strikes": n}``
      — the slot flapped; park it forever.

    ``due(now)`` answers "has the last verdict's backoff elapsed" so a
    polling loop can defer the actual spawn without sleeping.
    """

    __slots__ = ("policy", "key", "deaths", "strikes", "parked",
                 "not_before", "total_deaths")

    def __init__(self, policy, key):
        self.policy = policy
        self.key = str(key)
        self.deaths = []        # death timestamps inside the flap window
        self.strikes = 0        # consecutive fast deaths (backoff exponent)
        self.parked = False
        self.not_before = 0.0   # earliest time the next respawn may run
        self.total_deaths = 0

    def record_death(self, now):
        self.total_deaths += 1
        win = self.policy.flap_window_s
        self.deaths = [t for t in self.deaths if now - t < win]
        self.deaths.append(now)
        if self.parked or len(self.deaths) >= self.policy.flap_count:
            self.parked = True
            return {"action": PARK, "deaths": len(self.deaths),
                    "window_s": win, "strikes": self.strikes}
        # strikes reset when the child outlived the flap window: only
        # deaths still inside the window count toward the exponent.
        self.strikes = len(self.deaths)
        delay = self.policy.delay_s(self.key, self.strikes)
        self.not_before = now + delay
        return {"action": RESPAWN, "delay_s": delay,
                "not_before": self.not_before, "strikes": self.strikes}

    def due(self, now):
        """True when a pending respawn's backoff has elapsed."""
        return (not self.parked) and now >= self.not_before

    def state(self):
        return {"key": self.key, "parked": self.parked,
                "strikes": self.strikes, "deaths": self.total_deaths,
                "not_before": self.not_before}
