"""Pluggable survey workloads for the claim→fit→checkpoint engine.

``runner/execute.py``'s loop — lease-based claiming over the union
ledger, per-archive fault isolation, checkpoint/ledger reconciliation,
obs shards, elastic resume — was built for GetTOAs but is not specific
to it.  This module factors the GetTOAs-specific shape into a
:class:`Workload` interface and registers four implementations, so
every pipeline of the paper's workflow (PAPER.md; SURVEY.md §0) runs
behind the same engine:

``toas``
    Wideband (+ narrowband) TOA measurement — the engine's original
    workload, bit-identical to the pre-workload behavior.  Checkpoint:
    the ``toas.<pid>.tim`` block+marker protocol (pipelines/toas.py).
``zap``
    Per-archive RFI excision: ``pipelines/zap.get_zap_channels``
    proposals applied in place with ``apply_zaps``.  Decisions land in
    the ledger (``n_zapped`` on the done record) where a later
    ``toas`` pass over the same workdir surfaces them as a ``pre_fit``
    stage on its claim records.
``align``
    Survey-scale iterative template building: ``pipelines/align.py``'s
    per-iteration batched fit becomes claimable per-archive accumulate
    units (each writes a weighted partial sum to
    ``align_parts/<pass>/``), with an idempotent weighted-average
    reduce per iteration that any process may perform once the pass's
    union ledger shows every archive settled.
``modelfit``
    ppgauss/ppspline model construction over averaged portraits, one
    model file per archive under ``<workdir>/models/``.

Checkpoint protocol: the non-toas workloads checkpoint one JSONL line
per archive (a *complete block* — torn tails are dropped on replay,
exactly the ``.tim`` discipline), written in one locked append behind
the same ``checkpoint_flush`` chaos site as ``get_TOAs``, so the
fault matrix (testing/faults.py) behaves identically under every
workload.  Ledger records carry ``workload`` (runner/queue.py); old
ledgers without the field replay as ``toas``.
"""

# every checkpoint open/write/readline below happens under _ckpt_lock
# BY DESIGN: the per-path lock exists to serialize exactly that IO
# (atomic append / read-rewrite), mirroring pipelines/toas.py
# jaxlint: disable-file=J006

import hashlib
import json
import os
import time

import numpy as np

from .. import obs
from ..obs import tracing
from ..testing import faults
from .queue import DEFAULT_WORKLOAD, DONE

__all__ = ["Workload", "ToasWorkload", "ZapWorkload", "AlignWorkload",
           "ModelFitWorkload", "register_workload", "get_workload",
           "workload_names", "resolve_workload",
           "read_jsonl_checkpoint", "append_jsonl_checkpoint",
           "drop_jsonl_checkpoint_blocks"]


# -- JSONL workload checkpoints ----------------------------------------
# One line per archive == one complete block.  Appends go through the
# same per-file lock as the .tim protocol (the service may run several
# fits of one tenant concurrently) and the same checkpoint_flush chaos
# site, so kill/resume and injected-fault behavior match get_TOAs'.

def _ckpt_lock(path):
    from ..pipelines.toas import _checkpoint_lock

    return _checkpoint_lock(path)


def read_jsonl_checkpoint(path):
    """{realpath(archive): record} for every complete line of a JSONL
    workload checkpoint; torn tail lines (kill mid-append) and
    unparseable lines are dropped, mirroring ``_resume_checkpoint``."""
    out = {}
    if not path or not os.path.isfile(path):
        return out
    try:
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                key = rec.get("archive") if isinstance(rec, dict) \
                    else None
                if key:
                    out[key] = rec
    except OSError:
        return {}
    return out


def append_jsonl_checkpoint(path, rec, key=None):
    """Append one archive's block in ONE locked, flushed write.

    The ``checkpoint_flush`` chaos site fires here exactly like inside
    ``get_TOAs``' block+marker append: an injected fault means nothing
    of this archive lands in the checkpoint, and the reconcile path
    refits it.  An ambient trace id is stamped on the record so
    replayed blocks stay causally auditable (cf. ``_trace_marker``)."""
    faults.check("checkpoint_flush", key=key or rec.get("archive"))
    tid = tracing.current_trace_id()
    if tid and "trace" not in rec:
        rec = dict(rec, trace=tid)
    line = json.dumps(rec, default=str) + "\n"
    with _ckpt_lock(path):
        with open(path, "a", encoding="utf-8") as fh:
            fh.write(line)
            fh.flush()
    return rec


def drop_jsonl_checkpoint_blocks(path, archives):
    """Atomically rewrite a JSONL checkpoint without the given
    archives' blocks; returns the number dropped
    (``drop_checkpoint_blocks`` for JSONL workload checkpoints)."""
    targets = {os.path.realpath(a) for a in archives}
    if not targets or not path or not os.path.isfile(path):
        return 0
    with _ckpt_lock(path):
        with open(path, encoding="utf-8") as fh:
            lines = fh.readlines()
        kept, dropped = [], 0
        for ln in lines:
            try:
                rec = json.loads(ln)
                key = rec.get("archive") if isinstance(rec, dict) \
                    else None
            except json.JSONDecodeError:
                kept.append(ln)  # torn tail: replay ignores it anyway
                continue
            if key in targets:
                dropped += 1
                continue
            kept.append(ln)
        if dropped:
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.writelines(kept)
            os.replace(tmp, path)
    return dropped


def settle_fit(queue, info, checkpoint, drop_blocks, cancelled,
               wrote_block, outcome):
    """Common completion guard for workload ``fit_one``s — the exact
    discipline of the toas path (execute.py ``_fit_one``):

    * watchdog-cancelled -> NO ledger transition (the watchdog's
      ``fail`` record owns the archive's state);
    * lease taken over mid-fit -> abandon with no transition and drop
      any block this fit just wrote (never double-write);
    * otherwise ``outcome()`` performs the workload's transition and
      the ``runner_archive`` event is emitted with the workload tag.
    """
    if cancelled is not None and cancelled.is_set():
        return None
    if not queue.owns(info.path, refresh=True):
        from .execute import _lease_lost

        _lease_lost(queue, info, checkpoint, wrote_block=wrote_block,
                    drop=drop_blocks)
        return None
    rec = outcome()
    obs.event("runner_archive", archive=info.path,
              workload=queue.workload, state=rec["state"],
              attempts=rec.get("attempts", 0),
              reason=rec.get("reason"))
    return rec["state"]


# -- the interface -----------------------------------------------------

class Workload:
    """One pluggable work-unit type for the survey engine.

    The engine (execute.py ``run_survey``) supplies the loop — plan
    order, lease claiming, heartbeats, watchdog, reconcile, obs —
    and delegates everything workload-specific here:

    * ``n_passes``/``pass_label``: how many sequential passes over the
      archive set (align iterates), and the ledger ``workload`` label
      of each (pass k's records never contend with pass k-1's);
    * ``checkpoint_path``/``resume_done``/``drop_blocks``: the
      per-process checkpoint protocol reconcile and takeover-scrub
      run against;
    * ``begin_pass``: per-pass setup (align loads the pass template);
    * ``make_bucket_state``: warm per-shape-bucket state (the toas
      bucketed GetTOAs + fitter) — return None when unused;
    * ``claim_fields``: extra fields stamped on the claim record (the
      toas workload surfaces the upstream zap decision chain);
    * ``fit_one``: process one claimed archive end to end — load, fit,
      checkpoint, then exactly one ledger transition guarded by
      :func:`settle_fit`;
    * ``end_pass``: the per-pass reduce (align's weighted average);
      must be idempotent and safe for ANY process to run once the
      pass's union ledger shows every archive settled.
    """

    name = None
    #: end_pass does real work (engine records a ``reduce`` phase)
    has_reduce = False
    #: archives are padded to their bucket's canonical shape
    uses_buckets = True
    #: fit_one's load phase goes through GetTOAs._load_archive, so the
    #: claim-ahead host prefetch stage (runner/prefetch.py) can run it
    #: on a worker thread and replay the outcome via preload()
    supports_prefetch = False

    def n_passes(self, plan):
        return 1

    def pass_label(self, ipass=0):
        return self.name if ipass == 0 \
            else "%s.i%d" % (self.name, ipass + 1)

    def checkpoint_path(self, workdir, pid, ipass=0):
        return os.path.join(workdir, "%s.%d.jsonl"
                            % (self.pass_label(ipass), pid))

    def resume_done(self, checkpoint, quiet=True):
        """Archives (realpaths) with a complete block in this
        checkpoint."""
        return set(read_jsonl_checkpoint(checkpoint))

    def drop_blocks(self, checkpoint, archives):
        return drop_jsonl_checkpoint_blocks(checkpoint, archives)

    def begin_pass(self, ipass, plan, workdir, quiet=True):
        pass

    def end_pass(self, ipass, plan, workdir, queue, pid, quiet=True):
        return None

    def make_bucket_state(self, bucket, ordered, fitter, quiet=True):
        return None

    def claim_fields(self, queue, info):
        return {}

    def fit_one(self, state, queue, info, checkpoint, padded, quiet,
                cancelled=None):
        raise NotImplementedError

    def summary_extra(self):
        """Workload-specific fields merged into the survey manifest."""
        return {}


# -- toas: the original workload, bit-identical ------------------------

class ToasWorkload(Workload):
    """Wideband/narrowband TOA measurement through bucketed GetTOAs —
    exactly the engine's pre-workload behavior (same checkpoint files,
    same ledger transitions, same compiled-program reuse)."""

    name = DEFAULT_WORKLOAD
    supports_prefetch = True

    def __init__(self, modelfile=None, narrowband=False,
                 get_toas_kw=None):
        if modelfile is None:
            raise ValueError("run_survey needs a modelfile (argument "
                             "or recorded on the plan)")
        self.modelfile = modelfile
        self.narrowband = bool(narrowband)
        self.get_toas_kw = dict(get_toas_kw or {})

    def checkpoint_path(self, workdir, pid, ipass=0):
        return os.path.join(workdir, "toas.%d.tim" % pid)

    def resume_done(self, checkpoint, quiet=True):
        from ..pipelines.toas import _resume_checkpoint

        if not os.path.isfile(checkpoint):
            return set()
        return _resume_checkpoint(checkpoint, quiet)

    def drop_blocks(self, checkpoint, archives):
        from ..pipelines.toas import drop_checkpoint_blocks

        return drop_checkpoint_blocks(checkpoint, archives)

    def make_bucket_state(self, bucket, ordered, fitter, quiet=True):
        from .execute import _BucketedGetTOAs

        gt = _BucketedGetTOAs(
            [i.path for i, b in ordered if b.key == bucket.key],
            self.modelfile, bucket.key, quiet=quiet)
        gt.fit_batch = fitter
        return gt

    def claim_fields(self, queue, info):
        # pre-fit chain: a zap pass over this workdir recorded its
        # decisions in the union ledger — surface them in this claim's
        # reason chain so the toas ledger narrates what preceded the
        # fit (ISSUE 11 acceptance)
        zrec = queue.record_for(ZapWorkload.name, info.path)
        if zrec is None or zrec.get("state") != DONE:
            return {}
        nz = int(zrec.get("n_zapped") or 0)
        return {"pre_fit": {"zap": {"n_zapped": nz,
                                    "owner": zrec.get("owner")}},
                "reason": "pre_fit zap: %d channel-weight(s) zeroed"
                          % nz}

    def fit_one(self, state, queue, info, checkpoint, padded, quiet,
                cancelled=None):
        from .execute import _fit_one

        return _fit_one(state, queue, info, checkpoint, padded,
                        self.get_toas_kw, quiet, cancelled=cancelled,
                        narrowband=self.narrowband)


# -- zap: per-archive RFI excision -------------------------------------

class ZapWorkload(Workload):
    """Model-free median-noise channel zapping applied in place.

    Per archive: ``load_data`` (the ``archive_read`` chaos site fires
    inside it, so zap inherits the toas fault surface),
    ``get_zap_channels`` proposals, ``apply_zaps`` zeroing the flagged
    channel weights via the in-repo PSRFITS writer.  The checkpoint
    block records the full proposal; the ledger done record carries
    ``n_zapped``/``n_proposed`` for the downstream toas pass's
    ``pre_fit`` chain.  Re-zapping an already-zapped archive is
    idempotent (the weights are already zero), so a takeover refit
    cannot corrupt data."""

    name = "zap"
    uses_buckets = False

    def __init__(self, nstd=3.0, tscrunch=False, all_subs=None):
        self.nstd = float(nstd)
        self.tscrunch = bool(tscrunch)
        # ppzap semantics: tscrunched examination applies zaps to all
        # subints (paz -z vs -z -w)
        self.all_subs = self.tscrunch if all_subs is None \
            else bool(all_subs)

    def fit_one(self, state, queue, info, checkpoint, padded, quiet,
                cancelled=None):
        from ..io.archive import load_data
        from ..pipelines.zap import apply_zaps, get_zap_channels

        wrote = False
        try:
            # same load flags as ppzap's model-free path
            d = load_data(info.path, dedisperse=False,
                          dededisperse=False, tscrunch=self.tscrunch,
                          pscrunch=True, rm_baseline=True,
                          refresh_arch=False, return_arch=False,
                          quiet=True)
            zaps = get_zap_channels(d, nstd=self.nstd)
            n_prop = sum(len(z) for z in zaps)
            n_zapped = 0
            if n_prop:
                results = apply_zaps([info.path], [zaps],
                                     all_subs=self.all_subs,
                                     modify=True, quiet=True)
                n_zapped = sum(n for _, n in results)
            append_jsonl_checkpoint(checkpoint, {
                "archive": os.path.realpath(info.path),
                "t": round(time.time(), 6),
                "nstd": self.nstd,
                "n_proposed": n_prop,
                "n_zapped": n_zapped,
                "zap_channels": [[int(c) for c in z] for z in zaps],
            }, key=info.path)
            wrote = True
        except Exception as e:
            err = "%s: %s" % (type(e).__name__, e)
            return settle_fit(queue, info, checkpoint,
                              self.drop_blocks, cancelled, wrote,
                              lambda: queue.fail(info.path, err))
        return settle_fit(
            queue, info, checkpoint, self.drop_blocks, cancelled,
            wrote,
            lambda: queue.complete(info.path, n_zapped=n_zapped,
                                   n_proposed=n_prop))


# -- align: claimable accumulate units + per-pass reduce ---------------

class AlignWorkload(Workload):
    """Iterative align-and-average (``pipelines/align.align_archives``)
    as claimable per-archive units.

    Pass k fits every archive's subints against the pass template (the
    initial guess for pass 0, the previous reduce's output after) and
    writes its weighted partial sums — the exact per-row math of
    ``_align_fit_accumulate``, whose rows are independent, so summing
    per-archive parts equals the reference's cross-archive batches up
    to float associativity — atomically to
    ``align_parts/<pass>/*.npz``.  ``end_pass`` is the reduce: sum
    every done archive's part, normalize by total weights, write the
    next pass template (or the final aligned archive + an
    ``align.result.npz`` with the raw portrait/weights).  The reduce
    is deterministic and idempotent (atomic rename), so ANY process
    that observes pass completion may perform it and kill/resume
    replays no archive already accumulated."""

    name = "align"
    has_reduce = True
    uses_buckets = False

    def __init__(self, initial_guess=None, fit_dm=True, tscrunch=False,
                 pscrunch=True, SNR_cutoff=0.0, niter=1, norm=None,
                 rot_phase=0.0, place=None, max_iter=30, outfile=None,
                 chunk_max=128):
        if initial_guess is None:
            raise ValueError(
                "align workload needs an initial_guess template "
                "archive (ppsurvey run -m / workload_opts"
                "={'initial_guess': ...})")
        self.initial_guess = initial_guess
        self.fit_dm = bool(fit_dm)
        self.tscrunch = bool(tscrunch)
        self.pscrunch = bool(pscrunch)
        self.SNR_cutoff = float(SNR_cutoff)
        self.niter = max(1, int(niter))
        self.norm = norm
        self.rot_phase = float(rot_phase)
        self.place = place
        self.max_iter = int(max_iter)
        self.outfile = outfile
        self.chunk_max = int(chunk_max)
        self._outputs = {}

    def n_passes(self, plan):
        return self.niter

    def _state(self):
        return "Intensity" if self.pscrunch else "Stokes"

    def _pass_template(self, workdir, ipass):
        """Template consumed by pass ``ipass`` (0-based)."""
        if ipass == 0:
            return self.initial_guess
        return os.path.join(workdir,
                            "align.template.%d.fits" % (ipass + 1))

    def _final_out(self, workdir):
        return self.outfile or os.path.join(workdir, "aligned.fits")

    def _result_path(self, workdir):
        return os.path.join(workdir, "align.result.npz")

    def begin_pass(self, ipass, plan, workdir, quiet=True):
        from ..io.archive import load_data

        src = self._pass_template(workdir, ipass)
        md = load_data(src, state=self._state(), dedisperse=True,
                       tscrunch=True, pscrunch=self.pscrunch,
                       rm_baseline=True, refresh_arch=True,
                       return_arch=True, quiet=True)
        self.model_data = md
        self.nchan, self.nbin = int(md.nchan), int(md.nbin)
        self.npol = 1 if self.pscrunch else 4
        self.model_port = (md.masks * md.subints)[0, 0]
        self.model_mask = np.zeros(self.nchan)
        self.model_mask[md.ok_ichans[0]] = 1.0
        self._parts_dir = os.path.join(workdir, "align_parts",
                                       self.pass_label(ipass))
        os.makedirs(self._parts_dir, exist_ok=True)

    def _part_path(self, path):
        key = os.path.realpath(path)
        h = hashlib.sha1(key.encode("utf-8", "replace")).hexdigest()
        return os.path.join(self._parts_dir, "%s.%s.npz"
                            % (os.path.basename(key), h[:12]))

    def resume_done(self, checkpoint, quiet=True):
        # a checkpointed block is only trustworthy while its part file
        # exists — a lost part must refit, never silently drop its
        # archive from the average
        recs = read_jsonl_checkpoint(checkpoint)
        return {k for k, r in recs.items()
                if not r.get("part") or os.path.isfile(r["part"])}

    def fit_one(self, state, queue, info, checkpoint, padded, quiet,
                cancelled=None):
        from ..io.archive import load_data

        wrote = False
        try:
            with obs.span("load", archive=info.path):
                d = load_data(info.path, state=self._state(),
                              dedisperse=False, tscrunch=self.tscrunch,
                              pscrunch=self.pscrunch, rm_baseline=True,
                              refresh_arch=False, return_arch=False,
                              quiet=True)
        except NotImplementedError as e:
            # inconvertible state: deterministic, like align_archives'
            # permanent skip — quarantine with the reason on record
            err = "cannot convert to %s: %s" % (self._state(), e)
            return settle_fit(queue, info, checkpoint,
                              self.drop_blocks, cancelled, wrote,
                              lambda: queue.quarantine(info.path, err))
        except Exception as e:
            # possibly transient (injected archive_read fault, NFS
            # blip): bounded retries, then quarantine — the engine's
            # standard fault isolation
            err = "%s: %s" % (type(e).__name__, e)
            return settle_fit(queue, info, checkpoint,
                              self.drop_blocks, cancelled, wrote,
                              lambda: queue.fail(info.path, err))
        skip = None
        if d.nbin != self.nbin:
            err = "nbin mismatch: %d != template %d" % (d.nbin,
                                                        self.nbin)
            return settle_fit(queue, info, checkpoint,
                              self.drop_blocks, cancelled, wrote,
                              lambda: queue.quarantine(info.path, err))
        if d.prof_SNR < self.SNR_cutoff:
            skip = "prof_SNR %.1f < cutoff %.1f" % (d.prof_SNR,
                                                    self.SNR_cutoff)
        ok = np.asarray(d.ok_isubs)
        if skip is None and not len(ok):
            skip = "no usable subints"
        try:
            part = None
            n_rows = 0
            if skip is None:
                aligned, weights, n_rows = self._accumulate(d, ok,
                                                            info.path)
                part = self._part_path(info.path)
                tmp = part + ".tmp.npz"
                np.savez(tmp, aligned=aligned, weights=weights)
                os.replace(tmp, part)
            append_jsonl_checkpoint(checkpoint, {
                "archive": os.path.realpath(info.path),
                "t": round(time.time(), 6),
                "part": part,
                "n_rows": int(n_rows),
                "skipped": skip,
            }, key=info.path)
            wrote = True
        except Exception as e:
            err = "%s: %s" % (type(e).__name__, e)
            return settle_fit(queue, info, checkpoint,
                              self.drop_blocks, cancelled, wrote,
                              lambda: queue.fail(info.path, err))
        return settle_fit(
            queue, info, checkpoint, self.drop_blocks, cancelled,
            wrote,
            lambda: queue.complete(info.path, n_rows=int(n_rows),
                                   part=part, skipped=skip))

    def _accumulate(self, d, ok, path):
        """This archive's weighted partial sums against the pass
        template — the exact entry construction + batched
        seed/fit/rotate/accumulate of ``align_archives``, restricted
        to one archive's rows."""
        from ..pipelines.align import (_align_fit_accumulate,
                                       _assemble_block)

        aligned = np.zeros((self.npol, self.nchan, self.nbin))
        weights = np.zeros((self.nchan, self.nbin))
        md = self.model_data
        same_freqs = d.freqs.shape[-1] == self.nchan and \
            np.allclose(d.freqs[0], md.freqs[0])
        wok = (d.weights[ok] > 0.0).astype(float)
        if same_freqs:
            wok = wok * self.model_mask[None, :]
            chan_map = None
        else:
            chan_map = np.argmin(np.abs(
                md.freqs[0][None, :] - d.freqs[0][:, None]), axis=1)
        entry = dict(
            full=np.asarray(d.subints[ok]),
            freqs=np.asarray(d.freqs[ok]),
            errs=np.asarray(d.noise_stds[ok, 0]),
            SNRs=np.asarray(d.SNRs[ok, 0]),
            Ps=np.asarray(d.Ps[ok]),
            wok=wok, chan_map=chan_map, DM=float(d.DM))
        rows = [(entry, j) for j in range(len(ok))]
        dnchan = d.freqs.shape[-1]
        for i0 in range(0, len(rows), self.chunk_max):
            take = rows[i0:i0 + self.chunk_max]
            block, cmaps = _assemble_block(
                take, self.model_port, dnchan, self.nchan, self.nbin,
                self.npol, self.chunk_max)
            with obs.span("solve", archive=path, rows=len(take)):
                _align_fit_accumulate(
                    *block, chan_maps=cmaps, fit_dm=self.fit_dm,
                    max_iter=self.max_iter, nbin=self.nbin,
                    npol=self.npol, aligned_port=aligned,
                    total_weights=weights)
        return aligned, weights, len(rows)

    def end_pass(self, ipass, plan, workdir, queue, pid, quiet=True):
        final = ipass == self.niter - 1
        out = self._final_out(workdir) if final \
            else self._pass_template(workdir, ipass + 1)
        result = self._result_path(workdir)
        if final:
            self._outputs = {"aligned": out, "result": result}
        if os.path.isfile(out) and (not final
                                    or os.path.isfile(result)):
            return out  # another process already reduced this pass
        aligned = np.zeros((self.npol, self.nchan, self.nbin))
        weights = np.zeros((self.nchan, self.nbin))
        n_parts = 0
        for key in sorted(queue.entries):
            rec = queue.entries[key]
            if rec.get("state") != DONE:
                continue
            part = rec.get("part")
            if not part or not os.path.isfile(part):
                continue
            with np.load(part) as z:
                aligned += z["aligned"]
                weights += z["weights"]
            n_parts += 1
        nz = weights > 0
        for ipol in range(self.npol):
            aligned[ipol][nz] /= weights[nz]
        if final:
            aligned = self._finalize_port(aligned)
        arch = self.model_data.arch.copy()
        arch.tscrunch()
        if self.pscrunch:
            arch.pscrunch()
        arch.DM = 0.0
        arch.dedispersed = False
        arch.data = np.asarray(aligned)[None]
        arch.weights = np.where(weights.sum(axis=-1) > 0.0, 1.0,
                                0.0)[None, :]
        tmp = out + ".tmp.fits"
        arch.unload(tmp, quiet=True)
        os.replace(tmp, out)
        if final:
            tmpr = result + ".tmp.npz"
            np.savez(tmpr, aligned_port=aligned, total_weights=weights)
            os.replace(tmpr, result)
        obs.event("align_reduce", iteration=ipass + 1,
                  n_parts=n_parts, outfile=out, final=final)
        return out

    def _finalize_port(self, aligned):
        """Final-pass cosmetics, matching align_archives: optional
        normalization, rotation, and fiducial-point placement."""
        from ..fit.phase_shift import fit_phase_shift
        from ..ops.fourier import rotate_data
        from ..ops.normalize import normalize_portrait
        from ..ops.profiles import gaussian_profile

        if self.norm in ("mean", "max", "prof", "rms", "abs"):
            for ipol in range(self.npol):
                aligned[ipol] = np.asarray(
                    normalize_portrait(aligned[ipol], self.norm))
        if self.rot_phase:
            aligned = np.asarray(rotate_data(aligned, self.rot_phase))
        if self.place is not None:
            prof = aligned[0].mean(axis=0)
            delta = prof.max() * np.asarray(
                gaussian_profile(self.nbin, self.place, 0.0001))
            phase = float(np.asarray(
                fit_phase_shift(prof, delta, Ns=self.nbin).phase))
            aligned = np.asarray(rotate_data(aligned, phase))
        return aligned

    def summary_extra(self):
        return dict(self._outputs)


# -- modelfit: ppgauss/ppspline over averaged portraits ----------------

class ModelFitWorkload(Workload):
    """Gaussian or spline portrait-model construction, one model per
    archive, written under ``<workdir>/models/``.  The heavy per-model
    optimization gets the engine's fault isolation, retries, leases
    and resume for free — a survey's worth of template archives models
    itself overnight and a preempted run continues where it stopped."""

    name = "modelfit"
    uses_buckets = False

    def __init__(self, kind="gauss", outdir=None, model_kw=None):
        if kind not in ("gauss", "spline"):
            raise ValueError("modelfit kind must be 'gauss' or "
                             "'spline', not %r" % (kind,))
        self.kind = kind
        self.outdir = outdir
        self.model_kw = dict(model_kw or {})

    def begin_pass(self, ipass, plan, workdir, quiet=True):
        if self.outdir is None:
            self.outdir = os.path.join(workdir, "models")
        os.makedirs(self.outdir, exist_ok=True)

    def _model_out(self, path):
        base = os.path.basename(path)
        stem = base.rsplit(".", 1)[0] or base
        ext = ".gmodel" if self.kind == "gauss" else ".spl.npz"
        return os.path.join(self.outdir, stem + ext)

    def fit_one(self, state, queue, info, checkpoint, padded, quiet,
                cancelled=None):
        wrote = False
        try:
            outfile = self._model_out(info.path)
            if self.kind == "gauss":
                from ..models.gauss import GaussianModelPortrait

                dp = GaussianModelPortrait(info.path, quiet=True)
                dp.make_gaussian_model(quiet=True, **self.model_kw)
                out = dp.write_model(outfile, quiet=True)
            else:
                from ..models.spline import SplineModelPortrait

                sp = SplineModelPortrait(info.path, quiet=True)
                sp.make_spline_model(**self.model_kw)
                out = sp.write_model(outfile, quiet=True)
            append_jsonl_checkpoint(checkpoint, {
                "archive": os.path.realpath(info.path),
                "t": round(time.time(), 6),
                "kind": self.kind,
                "model": out,
            }, key=info.path)
            wrote = True
        except Exception as e:
            err = "%s: %s" % (type(e).__name__, e)
            return settle_fit(queue, info, checkpoint,
                              self.drop_blocks, cancelled, wrote,
                              lambda: queue.fail(info.path, err))
        return settle_fit(
            queue, info, checkpoint, self.drop_blocks, cancelled,
            wrote,
            lambda: queue.complete(info.path, model=out,
                                   kind=self.kind))


# -- registry ----------------------------------------------------------

_REGISTRY = {}


def register_workload(name, factory):
    """Register a workload factory under a name (``ppsurvey run
    --workload <name>`` resolves here)."""
    _REGISTRY[str(name)] = factory


def workload_names():
    return sorted(_REGISTRY)


def get_workload(name, **opts):
    try:
        factory = _REGISTRY[str(name)]
    except KeyError:
        raise ValueError("unknown workload %r (registered: %s)"
                         % (name, ", ".join(workload_names())))
    return factory(**opts)


def resolve_workload(spec, modelfile=None, narrowband=False,
                     get_toas_kw=None, opts=None):
    """``run_survey``'s ``workload`` argument -> a Workload instance.

    ``None``/"toas" keeps the original TOA-survey behavior (including
    the modelfile requirement); other names resolve through the
    registry with ``opts`` as constructor keywords.  For ``align``,
    ``modelfile`` doubles as the default ``initial_guess`` (the CLI's
    ``-m`` flag).  A Workload instance passes through untouched."""
    if isinstance(spec, Workload):
        return spec
    name = str(spec) if spec else ToasWorkload.name
    if name == ToasWorkload.name:
        return ToasWorkload(modelfile=modelfile,
                            narrowband=narrowband,
                            get_toas_kw=get_toas_kw)
    if get_toas_kw:
        raise TypeError(
            "unexpected get_toas keyword(s) for workload %r: %s"
            % (name, ", ".join(sorted(get_toas_kw))))
    opts = dict(opts or {})
    if name == AlignWorkload.name and modelfile is not None:
        opts.setdefault("initial_guess", modelfile)
    return get_workload(name, **opts)


register_workload(ToasWorkload.name, ToasWorkload)
register_workload(ZapWorkload.name, ZapWorkload)
register_workload(AlignWorkload.name, AlignWorkload)
register_workload(ModelFitWorkload.name, ModelFitWorkload)
