"""Survey runner: shape-bucketed batch scheduling with fault isolation.

The paper's workload is embarrassingly parallel — every archive's
subintegrations are fit independently — but a survey of thousands of
heterogeneous archives needs an execution *plan*: group archives into
shape buckets so the whole survey compiles O(#buckets) programs
(:mod:`.plan`), track per-archive state in a crash-safe on-disk ledger
so one poison archive cannot kill a week-long run (:mod:`.queue`), and
drive the bucketed batches across processes with per-host obs shards
merged into one report (:mod:`.execute`).  The CLI front-end is
``python -m pulseportraiture_tpu.cli.ppsurvey``; the full contract
lives in docs/RUNNER.md.

Everything in this package is host-side orchestration (file IO, ledger
writes, process partitioning) and must never be reachable inside a jit
trace — jaxlint J002 enforces this statically, exactly as it does for
the obs API.
"""

from .plan import (ArchiveInfo, ShapeBucket, SurveyPlan, canonical_shape,
                   load_bucketed_databunch, pad_databunch, plan_survey,
                   scan_archive_header)
from .queue import DEFAULT_WORKLOAD, WorkQueue
from .execute import run_survey, survey_status
from .prefetch import HostPrefetcher, PrefetchTicket
from .respawn import RespawnPolicy, RespawnTracker
from .supervisor import Supervisor, decide, supervise
from .warm import (WarmSpec, enable_persistent_cache, program_specs,
                   synth_databunch, warm_plan)
from .workloads import (AlignWorkload, ModelFitWorkload, ToasWorkload,
                        Workload, ZapWorkload, get_workload,
                        register_workload, resolve_workload,
                        workload_names)

__all__ = ["ArchiveInfo", "ShapeBucket", "SurveyPlan", "canonical_shape",
           "load_bucketed_databunch", "pad_databunch", "plan_survey",
           "scan_archive_header", "HostPrefetcher", "PrefetchTicket",
           "WorkQueue", "DEFAULT_WORKLOAD", "run_survey",
           "survey_status", "Workload", "ToasWorkload", "ZapWorkload",
           "AlignWorkload", "ModelFitWorkload", "register_workload",
           "get_workload", "workload_names", "resolve_workload",
           "WarmSpec", "program_specs", "warm_plan",
           "enable_persistent_cache", "synth_databunch",
           "RespawnPolicy", "RespawnTracker", "Supervisor", "decide",
           "supervise"]
