"""Self-healing autoscaling supervisor for elastic survey workers.

``ppsurvey supervise`` closes the loop from observability back into
actuation: instead of a human picking ``--processes`` and re-running
``ppsurvey resume`` after every crash, one control loop owns the
survey end-to-end.  It spawns ``ppsurvey run`` worker subprocesses
(one per *slot*, slot index == worker ``--process`` index, so every
replacement inherits its predecessor's ledger shard, checkpoint
reconcile and crash-recovery semantics for free) and reconciles
desired vs. actual worker count every tick from the live planes the
runner already maintains:

* **queue depth + leases** (runner/queue.py): a readonly union replay
  gives ready-work backlog, outstanding totals, and expired leases —
  the same view ``ppsurvey status`` renders;
* **memory admission** (obs/memory.py + the plan's per-bucket
  ``est_bytes``): a worker-count cap of ``mem_budget_bytes //
  est_worker_bytes`` when a budget is configured;
* **health alerts** (obs/health.py): a firing ``memory_watermark``
  blocks scale-up, and supervisor respawn churn feeds the
  ``worker_churn`` rule.

Policy — all of it inside the pure, table-testable
:func:`decide(observed) -> actions`:

* scale **up** when ready backlog per live worker exceeds
  ``backlog_per_worker`` (and memory headroom allows, and no blocking
  alert fires), bounded by ``max_workers``;
* scale **down** by SIGTERM drain (the PR-5 preemption semantics: the
  in-flight archive finishes, the worker exits 0) when the live set
  outnumbers the remaining work;
* **replace** any worker that exits nonzero or whose leases expire,
  through per-slot crash-loop exponential backoff
  (runner/respawn.py); a slot that dies ``flap_count`` times inside
  ``flap_window_s`` is **parked** with a ``supervisor_flap`` event
  instead of respawning forever — the survey finishes on the
  survivors.

Every action is audited: ``supervisor_*`` events,
``pps_supervisor_workers{state}`` gauges and
``pps_supervisor_respawns_total`` / ``pps_supervisor_scale_events_total``
counters, all merged into the survey report via the supervisor's own
obs shard.  Killing the supervisor never loses work: the workers are
plain ``ppsurvey run`` processes that drain standalone, and a plain
``ppsurvey resume`` afterwards continues from the union ledger.
"""

import math
import os
import signal
import subprocess
import sys
import threading
import time

from .. import obs
from ..obs import health as obs_health
from ..obs import memory as obs_memory
from ..obs import metrics
from ..obs.merge import merge_obs_shards, write_shard
from ..testing import faults
from .plan import SurveyPlan
from .queue import DEFAULT_WORKLOAD, WorkQueue, owner_pid
from .respawn import PARK, RespawnPolicy, RespawnTracker

__all__ = ["Supervisor", "decide", "GAUGE_WORKERS", "GAUGE_LAST_SCALE",
           "COUNTER_RESPAWNS", "COUNTER_SCALE_EVENTS", "BLOCKING_ALERTS"]

GAUGE_WORKERS = "pps_supervisor_workers"
GAUGE_LAST_SCALE = "pps_supervisor_last_scale"
COUNTER_RESPAWNS = "pps_supervisor_respawns_total"
COUNTER_SCALE_EVENTS = "pps_supervisor_scale_events_total"

# alerts that veto scale-up (replacements still happen: a survey that
# is already over budget should not *grow*, but keeping the configured
# floor alive is what drains the pressure)
BLOCKING_ALERTS = frozenset(["memory_watermark"])

# slot states
EMPTY = "empty"        # spawnable: never spawned, or exited clean
LIVE = "live"          # subprocess running
DEAD = "dead"          # died dirty; respawn pending its backoff
PARKED = "parked"      # flapped; never respawned again


def decide(observed):
    """Pure reconciliation policy: one observation in, actions out.

    ``observed`` (plain dict, every key optional):

    * ``ready`` — archives claimable right now (pending, retry-backoff
      elapsed, or under an expired lease);
    * ``outstanding`` — archives not yet done/quarantined;
    * ``live`` / ``draining`` / ``parked`` / ``empty`` — slot-index
      lists by state (``draining`` ⊆ ``live``);
    * ``dead`` — ``[{"slot", "action": "respawn"|"park", "due"}]``
      verdicts from each dead slot's RespawnTracker;
    * ``expired`` — live slots whose ledger leases have expired (a
      wedged worker: alive to the OS, dead to the survey);
    * ``min_workers`` / ``max_workers`` / ``backlog_per_worker`` —
      the scaling knobs;
    * ``mem_budget_bytes`` / ``est_worker_bytes`` — admission inputs
      (0 = unconstrained);
    * ``alerts`` — names of firing health rules.

    Returns ``[{"op", "slot", "cause"}]`` with op one of ``spawn``
    (cause ``scale_up``/``replace``), ``drain`` (``scale_down``/
    ``complete``), ``respawn`` (``lease_expired``: kill + backoff +
    re-spawn) or ``park`` (``flap``).  Deterministic: scale-up fills
    the lowest empty slots, scale-down drains the highest live ones.
    """
    acts = []
    live = sorted(observed.get("live") or ())
    draining = set(observed.get("draining") or ())
    min_w = int(observed.get("min_workers", 1))
    max_w = int(observed.get("max_workers", 1))
    per = float(observed.get("backlog_per_worker", 2.0))
    ready = int(observed.get("ready", 0))
    outstanding = int(observed.get("outstanding", 0))
    alerts = set(observed.get("alerts") or ())
    budget = int(observed.get("mem_budget_bytes") or 0)
    est = int(observed.get("est_worker_bytes") or 0)

    # 1. dead slots: obey each tracker's verdict
    for d in observed.get("dead") or ():
        if d.get("action") == PARK:
            acts.append({"op": "park", "slot": d["slot"],
                         "cause": "flap"})
        elif d.get("due") and outstanding > 0:
            acts.append({"op": "spawn", "slot": d["slot"],
                         "cause": "replace"})
    replacing = set(a["slot"] for a in acts if a["op"] == "spawn")

    # 2. wedged workers: live to the OS but their leases expired
    for slot in observed.get("expired") or ():
        if slot in live and slot not in draining:
            acts.append({"op": "respawn", "slot": slot,
                         "cause": "lease_expired"})

    # 3. survey complete: drain everything (below min_workers too)
    if outstanding <= 0:
        for slot in live:
            if slot not in draining:
                acts.append({"op": "drain", "slot": slot,
                             "cause": "complete"})
        return acts

    # 4. scale down: the live set outnumbers the remaining work
    if len(live) > outstanding:
        surplus = len(live) - max(outstanding, min_w)
        for slot in sorted(live, reverse=True)[:max(0, surplus)]:
            if slot not in draining:
                acts.append({"op": "drain", "slot": slot,
                             "cause": "scale_down"})
        return acts

    # 5. scale up: backlog per live worker exceeds the threshold
    want = math.ceil(ready / per) if per > 0 else max_w
    want = min(max_w, max(min_w, want))
    if budget > 0 and est > 0:
        want = min(want, max(budget // est, min_w))
    add = want - (len(live) + len(replacing))
    if add > 0 and not (alerts & BLOCKING_ALERTS):
        pool = [s for s in sorted(observed.get("empty") or ())
                if s not in replacing]
        for slot in pool[:add]:
            acts.append({"op": "spawn", "slot": slot,
                         "cause": "scale_up"})
    return acts


class _Slot(object):
    """One worker slot: a fixed ``--process`` index plus its current
    subprocess (if any) and respawn bookkeeping."""

    __slots__ = ("index", "state", "proc", "pid", "spawned_at",
                 "tracker", "draining", "spawn_count")

    def __init__(self, index, policy):
        self.index = index
        self.state = EMPTY
        self.proc = None
        self.pid = None
        self.spawned_at = None
        self.tracker = RespawnTracker(policy, key="w%d" % index)
        self.draining = False
        self.spawn_count = 0


class Supervisor(object):
    """Own a planned survey end-to-end: spawn, scale, replace, drain.

    ``run()`` blocks until the survey has no outstanding work (or
    every slot is parked), then merges the obs shards — including the
    supervisor's own audit shard — and returns a summary dict.
    """

    def __init__(self, workdir, modelfile=None, min_workers=1,
                 max_workers=4, backlog_per_worker=2.0, interval_s=1.0,
                 lease_s=600.0, mem_budget_bytes=0,
                 est_worker_bytes=None, workload=DEFAULT_WORKLOAD,
                 warm=None, compile_cache=None, respawn_policy=None,
                 worker_args=(), worker_env=None, drain_grace_s=60.0,
                 max_ticks=None, quiet=False):
        if max_workers < 1 or min_workers < 0 \
                or min_workers > max_workers:
            raise ValueError("need 0 <= min_workers <= max_workers, "
                             "max_workers >= 1")
        self.workdir = workdir
        self.modelfile = modelfile
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.backlog_per_worker = float(backlog_per_worker)
        self.interval_s = float(interval_s)
        self.lease_s = float(lease_s)
        self.mem_budget_bytes = int(mem_budget_bytes or 0)
        self.workload = str(workload or DEFAULT_WORKLOAD)
        self.warm = warm
        self.compile_cache = compile_cache
        self.worker_args = list(worker_args or ())
        self.worker_env = dict(worker_env or {})  # slot -> {K: V}
        self.drain_grace_s = float(drain_grace_s)
        self.max_ticks = max_ticks
        self.quiet = bool(quiet)

        plan_path = os.path.join(workdir, "plan.json")
        if not os.path.isfile(plan_path):
            raise FileNotFoundError(
                "no plan at %s — run 'ppsurvey plan' first" % plan_path)
        self.plan = SurveyPlan.load(plan_path)
        self.planned = [WorkQueue.key_for(info.path)
                        for info, _ in self.plan.archives()]
        self.planned_total = len(self.planned) + len(self.plan.unreadable)
        if est_worker_bytes is None:
            est_worker_bytes = max(
                (b.est_bytes() for b in self.plan.buckets), default=0)
        self.est_worker_bytes = int(est_worker_bytes or 0)

        policy = respawn_policy or RespawnPolicy(
            backoff_s=1.0, backoff_max_s=30.0, flap_count=3,
            flap_window_s=60.0)
        self.policy = policy
        self.slots = [_Slot(i, policy) for i in range(self.max_workers)]
        self._stop = False
        self._desired = self.min_workers
        self._last_scale = None      # (action, t)
        self.totals = {"spawned": 0, "respawns": 0, "parked": 0,
                       "scale_ups": 0, "scale_downs": 0}

    # -- observation ----------------------------------------------------

    def observe_survey(self, now=None):
        """One reconciliation input for :func:`decide`: slot states
        from the process table, work states from a readonly union
        replay (the same file-tail-tolerant view ``ppsurvey status``
        uses — no locks taken, safe against live workers)."""
        now = time.time() if now is None else now
        q = WorkQueue(None, readonly=True, union_dir=self.workdir,
                      workload=self.workload)
        counts = q.counts()
        settled = counts.get("done", 0) + counts.get("quarantined", 0)
        outstanding = max(0, self.planned_total - settled)
        ready = sum(1 for p in self.planned
                    if p not in q.entries or q.ready(p, now))
        expired_idx = set()
        for row in q.leases(now):
            if row.get("expired"):
                idx = owner_pid(row.get("owner"))
                if idx is not None:
                    expired_idx.add(idx)
        alerts = [a.get("rule") for a in obs_health.firing()]
        obsd = {
            "now": now,
            "ready": ready,
            "outstanding": outstanding,
            "counts": counts,
            "live": [s.index for s in self.slots if s.state == LIVE],
            "draining": [s.index for s in self.slots if s.draining],
            "parked": [s.index for s in self.slots
                       if s.state == PARKED],
            "empty": [s.index for s in self.slots if s.state == EMPTY],
            "dead": [{"slot": s.index,
                      "action": PARK if s.tracker.parked else "respawn",
                      "due": s.tracker.due(now)}
                     for s in self.slots if s.state == DEAD],
            "expired": sorted(
                i for i in expired_idx
                if i < len(self.slots)
                and self.slots[i].state == LIVE),
            "min_workers": self.min_workers,
            "max_workers": self.max_workers,
            "backlog_per_worker": self.backlog_per_worker,
            "mem_budget_bytes": self.mem_budget_bytes,
            "est_worker_bytes": self.est_worker_bytes,
            "alerts": alerts,
        }
        return obsd

    # -- actuation ------------------------------------------------------

    def _worker_cmd(self, slot):
        cmd = [sys.executable, "-m",
               "pulseportraiture_tpu.cli.ppsurvey", "run",
               "-w", self.workdir,
               "--process", str(slot.index),
               "--processes", str(self.max_workers),
               "--lease", str(self.lease_s),
               "--no_merge"]
        if self.modelfile:
            cmd += ["-m", self.modelfile]
        if self.workload != DEFAULT_WORKLOAD:
            cmd += ["--workload", self.workload]
        if self.warm:
            cmd += ["--warm", self.warm]
        if self.compile_cache:
            cmd += ["--compile-cache", self.compile_cache]
        if self.quiet:
            cmd += ["--quiet"]
        cmd += self.worker_args
        return cmd

    def _spawn(self, slot, cause, now):
        """Launch one worker into ``slot``; an injected spawn fault
        counts as an instant death (backoff/flap chain), so the
        crash-loop machinery is testable without burning subprocesses."""
        env = dict(os.environ)
        if slot.spawn_count == 0:
            env.update(self.worker_env.get(slot.index, {}))
        else:
            # a respawn must come back clean: one-shot chaos clauses
            # (sigkill specs) died with the process they killed
            env.pop("PPTPU_FAULTS", None)
        slot.spawn_count += 1
        logdir = os.path.join(self.workdir, "supervisor")
        os.makedirs(logdir, exist_ok=True)
        log = open(os.path.join(logdir, "worker.%d.log" % slot.index),
                   "ab")
        try:
            faults.check("supervisor_spawn", key="w%d" % slot.index)
            slot.proc = subprocess.Popen(
                self._worker_cmd(slot), stdout=log,
                stderr=subprocess.STDOUT, env=env)
        except (faults.InjectedFault, OSError) as e:
            self._record_death(slot, now, returncode=None,
                               reason="spawn_failed: %s" % e)
            return False
        finally:
            log.close()
        slot.pid = slot.proc.pid
        slot.state = LIVE
        slot.draining = False
        slot.spawned_at = now
        self.totals["spawned"] += 1
        obs.event("supervisor_spawn", slot=slot.index, pid=slot.pid,
                  cause=cause, spawn_count=slot.spawn_count)
        if cause != "scale_up":
            self.totals["respawns"] += 1
            obs.counter("supervisor_respawns")
            metrics.inc(COUNTER_RESPAWNS, cause=cause)
        return True

    def _record_death(self, slot, now, returncode, reason):
        verdict = slot.tracker.record_death(now)
        obs.event("supervisor_worker_exit", slot=slot.index,
                  returncode=returncode, reason=reason,
                  strikes=verdict.get("strikes"),
                  verdict=verdict["action"])
        slot.proc = None
        slot.pid = None
        slot.draining = False
        if verdict["action"] == PARK:
            self._park(slot, verdict)
        else:
            slot.state = DEAD
        return verdict

    def _park(self, slot, verdict):
        slot.state = PARKED
        slot.draining = False
        self.totals["parked"] += 1
        obs.event("supervisor_flap", slot=slot.index,
                  deaths=verdict.get("deaths"),
                  window_s=verdict.get("window_s"))

    def _drain(self, slot, cause):
        if slot.proc is not None and slot.proc.poll() is None:
            try:
                slot.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
        slot.draining = True
        obs.event("supervisor_drain", slot=slot.index, cause=cause)

    def _kill(self, slot):
        if slot.proc is not None and slot.proc.poll() is None:
            try:
                slot.proc.kill()
                slot.proc.wait(timeout=10.0)
            except (OSError, subprocess.TimeoutExpired):
                pass

    def apply(self, actions, observed):
        """Actuate one decide() output; emits the scale events and
        counters that make the decision auditable."""
        now = observed.get("now") or time.time()
        ups = downs = 0
        for a in actions:
            slot = self.slots[a["slot"]]
            op, cause = a["op"], a.get("cause", "")
            if op == "park":
                self._park(slot, slot.tracker.state())
            elif op == "spawn":
                if self._spawn(slot, cause, now) \
                        and cause == "scale_up":
                    ups += 1
            elif op == "respawn":
                self._kill(slot)
                self._record_death(slot, now,
                                   returncode=slot.proc.returncode
                                   if slot.proc else None,
                                   reason=cause)
            elif op == "drain":
                self._drain(slot, cause)
                if cause == "scale_down":
                    downs += 1
        if ups:
            self.totals["scale_ups"] += 1
            obs.counter("supervisor_scale_events")
            metrics.inc(COUNTER_SCALE_EVENTS, direction="up")
            obs.event("supervisor_scale_up", n=ups,
                      live=len(observed.get("live") or ()) + ups,
                      ready=observed.get("ready"))
            self._last_scale = ("up", now)
            metrics.set_gauge(GAUGE_LAST_SCALE, now, action="up")
        if downs:
            self.totals["scale_downs"] += 1
            obs.counter("supervisor_scale_events")
            metrics.inc(COUNTER_SCALE_EVENTS, direction="down")
            obs.event("supervisor_scale_down", n=downs,
                      live=len(observed.get("live") or ()),
                      outstanding=observed.get("outstanding"))
            self._last_scale = ("down", now)
            metrics.set_gauge(GAUGE_LAST_SCALE, now, action="down")

    # -- the control loop -----------------------------------------------

    def _reap(self, now):
        """Fold exited subprocesses back into slot state.  A clean
        exit (rc 0, or any exit while draining) frees the slot; a
        dirty one feeds the crash-loop tracker."""
        for slot in self.slots:
            if slot.state != LIVE or slot.proc is None:
                continue
            rc = slot.proc.poll()
            if rc is None:
                continue
            uptime = now - (slot.spawned_at or now)
            if slot.draining or rc == 0:
                obs.event("supervisor_worker_exit", slot=slot.index,
                          returncode=rc, reason="clean",
                          uptime_s=round(uptime, 3),
                          drained=slot.draining)
                slot.state = EMPTY
                slot.proc = None
                slot.pid = None
                slot.draining = False
            else:
                self._record_death(slot, now, returncode=rc,
                                   reason="exit")

    def _publish_gauges(self):
        by_state = {LIVE: 0, PARKED: 0, DEAD: 0}
        for s in self.slots:
            if s.state in by_state:
                by_state[s.state] += 1
        metrics.set_gauge(GAUGE_WORKERS, self._desired, state="desired")
        metrics.set_gauge(GAUGE_WORKERS, by_state[LIVE], state="live")
        metrics.set_gauge(GAUGE_WORKERS, by_state[PARKED],
                          state="parked")
        metrics.set_gauge(GAUGE_WORKERS, by_state[DEAD], state="dead")

    def _request_stop(self, signum, frame):
        self._stop = True

    def run(self):
        """Supervise until the survey settles.  Returns the summary
        (also printed by ``ppsurvey supervise``)."""
        t0 = time.time()
        stopped_by = None
        old_term = old_int = None
        if threading.current_thread() is threading.main_thread():
            old_term = signal.signal(signal.SIGTERM, self._request_stop)
            old_int = signal.signal(signal.SIGINT, self._request_stop)
        shards_dir = os.path.join(self.workdir, "obs_shards")
        run_dir = None
        try:
            with obs.run("ppsupervisor",
                         base_dir=os.path.join(self.workdir, "obs"),
                         config={"min_workers": self.min_workers,
                                 "max_workers": self.max_workers,
                                 "backlog_per_worker":
                                     self.backlog_per_worker,
                                 "lease_s": self.lease_s,
                                 "mem_budget_bytes":
                                     self.mem_budget_bytes,
                                 "est_worker_bytes":
                                     self.est_worker_bytes,
                                 "workload": self.workload}) as rec:
                run_dir = rec.dir if rec is not None else None
                obs.event("supervisor_started", workdir=self.workdir,
                          planned=self.planned_total,
                          min_workers=self.min_workers,
                          max_workers=self.max_workers)
                ticks = 0
                observed = self.observe_survey()
                while True:
                    now = time.time()
                    self._reap(now)
                    if self._stop:
                        stopped_by = "signal"
                        break
                    observed = self.observe_survey(now)
                    actions = decide(observed)
                    self._desired = max(0, (
                        len(observed["live"])
                        + sum(1 for a in actions
                              if a["op"] == "spawn")
                        - sum(1 for a in actions
                              if a["op"] == "drain")))
                    self.apply(actions, observed)
                    self._publish_gauges()
                    obs_memory.watermarks()
                    obs_health.evaluate(now)
                    live = [s for s in self.slots if s.state == LIVE]
                    if observed["outstanding"] <= 0 and not live:
                        break
                    if not live and all(s.state == PARKED
                                        for s in self.slots):
                        # every slot flapped out: degrade honestly
                        # instead of spinning on an unwinnable survey
                        stopped_by = "all_parked"
                        break
                    ticks += 1
                    if self.max_ticks is not None \
                            and ticks >= self.max_ticks:
                        stopped_by = "max_ticks"
                        break
                    time.sleep(self.interval_s)
                if stopped_by in ("signal", "max_ticks"):
                    # hand the survey back intact: drain the workers
                    # (their in-flight archives finish), then leave —
                    # a plain `ppsurvey resume` continues from here
                    for slot in self.slots:
                        if slot.state == LIVE:
                            self._drain(slot, cause="supervisor_stop")
                self._wait_drain()
                self._publish_gauges()
                observed = self.observe_survey()
                obs.event("supervisor_stopped",
                          stopped_by=stopped_by or "complete",
                          outstanding=observed["outstanding"],
                          wall_s=round(time.time() - t0, 3),
                          **self.totals)
        finally:
            if old_term is not None:
                signal.signal(signal.SIGTERM, old_term)
            if old_int is not None:
                signal.signal(signal.SIGINT, old_int)
        if run_dir is not None:
            # publish the audit trail as one more obs shard (one slot
            # past the worker indices) and merge, so `ppsurvey report`
            # shows the supervisor's decisions next to the fits
            write_shard(run_dir, shards_dir, self.max_workers)
            try:
                merge_obs_shards(shards_dir,
                                 os.path.join(self.workdir,
                                              "obs_merged"))
            except FileNotFoundError:
                pass
        counts = observed.get("counts", {})
        return {"stopped_by": stopped_by or "complete",
                "counts": counts,
                "outstanding": observed["outstanding"],
                "workers": dict(self.totals),
                "parked_slots": [s.index for s in self.slots
                                 if s.state == PARKED],
                "wall_s": round(time.time() - t0, 3)}

    def _wait_drain(self):
        """Bounded wait for draining/live workers to exit; anything
        still alive past the grace window is left running (it keeps
        the survey safe — the ledger protects against double fits)."""
        deadline = time.time() + self.drain_grace_s
        for slot in self.slots:
            if slot.proc is None or slot.state != LIVE:
                continue
            left = deadline - time.time()
            try:
                slot.proc.wait(timeout=max(0.1, left))
            except subprocess.TimeoutExpired:
                obs.event("supervisor_drain_timeout", slot=slot.index,
                          pid=slot.pid)
                continue
            slot.state = EMPTY
            slot.proc = None


def supervise(workdir, **kw):
    """Convenience wrapper: build a Supervisor and run it."""
    return Supervisor(workdir, **kw).run()
