"""Survey planning: cheap header scans + shape-bucketed batch grouping.

A heterogeneous survey metafile compiles one program set per distinct
``(nchan, nbin)`` archive shape (bench.py's hetero stage prices this at
minutes per shape through a remote-compile tunnel).  The planner reads
each archive's shape from its FITS *headers only* — no DATA payload is
decoded, so planning a thousand-archive survey costs file-open + seek,
not gigabytes of IO — and groups archives into **shape buckets**: the
canonical grid pads ``nchan``/``nbin`` up to the next power of two, so
every archive in a bucket runs through the same compiled programs.

Padding semantics (docs/RUNNER.md):

* ``nchan`` — appended channels carry **zero weight** (excluded from
  every weighted reduction and from the fit), frequencies extrapolated
  on the native channel spacing, noise padded with the per-subint
  median so the guess stage's median-noise estimate is unbiased.
* ``nbin`` — the profile is **Fourier-resampled** (harmonic zero-pad)
  to the canonical bin count: an exact bandlimited representation of
  the same periodic signal, so fitted phases (in rotations) are
  unchanged.  Per-bin noise is rescaled by sqrt(nbin/nbin_pad) to keep
  the harmonic-domain noise level — and hence reduced chi-squared —
  consistent.

Archives whose headers cannot be read (truncated, not FITS, no SUBINT
HDU) are recorded on the plan as *unreadable* with the reason, and the
work queue quarantines them up front instead of crashing mid-survey.
"""

import json
import os

import numpy as np

from ..io.fits import BLOCK, CARD, Header
from ..testing import faults

__all__ = ["ArchiveInfo", "ShapeBucket", "SurveyPlan", "canonical_shape",
           "estimate_archive_bytes", "load_bucketed_databunch",
           "pad_databunch", "plan_survey", "scan_archive_header"]

PLAN_SCHEMA = "pptpu-survey-plan-v1"

# canonical-grid floors: padding below these wastes more in padded rows
# than a tiny program is worth saving in compiles
MIN_NCHAN = 8
MIN_NBIN = 64


def _next_pow2(n, lo):
    n = int(n)
    if n <= lo:
        return lo
    return 1 << (n - 1).bit_length()


def canonical_shape(nchan, nbin):
    """(nchan_pad, nbin_pad): the shape bucket an archive lands in."""
    return _next_pow2(nchan, MIN_NCHAN), _next_pow2(nbin, MIN_NBIN)


class ArchiveInfo:
    """Header-derived facts about one archive (no data decoded)."""

    __slots__ = ("path", "nsub", "npol", "nchan", "nbin", "source",
                 "nu0", "bw")

    def __init__(self, path, nsub, npol, nchan, nbin, source="unknown",
                 nu0=0.0, bw=0.0):
        self.path = path
        self.nsub = int(nsub)
        self.npol = int(npol)
        self.nchan = int(nchan)
        self.nbin = int(nbin)
        self.source = source
        self.nu0 = float(nu0)
        self.bw = float(bw)

    @property
    def bucket(self):
        return canonical_shape(self.nchan, self.nbin)

    def to_dict(self):
        return {k: getattr(self, k) for k in self.__slots__}

    @classmethod
    def from_dict(cls, d):
        return cls(**d)


def _has_end_card(block):
    for i in range(0, BLOCK, CARD):
        if block[i:i + 8].rstrip() == b"END":
            return True
    return False


def _iter_headers(f, path):
    """Yield FITS HDU headers from an open file, seeking past every
    data payload (the whole point: shapes come from headers alone)."""
    first = True
    while True:
        buf = b""
        while True:
            block = f.read(BLOCK)
            if first and block and not block.startswith(b"SIMPLE"):
                raise ValueError(f"{path}: not a FITS file")
            if len(block) < BLOCK:
                if buf or (first and block):
                    raise ValueError(
                        f"{path}: truncated FITS header "
                        f"({len(buf) + len(block)} bytes)")
                return
            buf += block
            if _has_end_card(block):
                break
        first = False
        hdr, _ = Header.from_bytes(buf)
        yield hdr
        if str(hdr.get("XTENSION", "")).strip() == "BINTABLE":
            nbytes = int(hdr["NAXIS1"]) * int(hdr["NAXIS2"]) \
                + int(hdr.get("PCOUNT", 0))
        elif hdr.get("NAXIS", 0) > 0:
            nbytes = abs(int(hdr.get("BITPIX", 8))) // 8
            for i in range(1, int(hdr["NAXIS"]) + 1):
                nbytes *= int(hdr[f"NAXIS{i}"])
        else:
            nbytes = 0
        f.seek(((nbytes + BLOCK - 1) // BLOCK) * BLOCK, os.SEEK_CUR)


def scan_archive_header(path):
    """ArchiveInfo from FITS headers only; raises ValueError when the
    file is not a readable PSRFITS archive (the quarantine trigger)."""
    faults.check("header_scan", key=path)
    primary = None
    with open(path, "rb") as f:
        for hdr in _iter_headers(f, path):
            if primary is None:
                primary = hdr
                continue
            if str(hdr.get("EXTNAME", "")).strip() != "SUBINT":
                continue
            nsub = int(hdr["NAXIS2"])
            npol = int(hdr.get("NPOL", 1))
            nchan = int(hdr.get("NCHAN", primary.get("OBSNCHAN", 0)))
            nbin = int(hdr.get("NBIN", 0))
            if nsub <= 0 or nchan <= 0 or nbin <= 0:
                raise ValueError(
                    f"{path}: SUBINT HDU with degenerate shape "
                    f"nsub={nsub} nchan={nchan} nbin={nbin}")
            return ArchiveInfo(
                path, nsub, npol, nchan, nbin,
                source=str(primary.get("SRC_NAME", "unknown")).strip(),
                nu0=float(primary.get("OBSFREQ", 0.0)),
                bw=float(primary.get("OBSBW", 0.0)))
    raise ValueError(f"{path}: no SUBINT HDU found")


# -- analytical footprint model (obs/memory.py regression gates) ----------
#
# Per-archive device bytes of one bucketed fit, from shapes and dtypes
# alone: the data-domain arrays (subints, masks, model portrait, noise
# working copy) are f64 [nsub, npol, nchan, nbin]; the harmonic-domain
# arrays (data FT, model FT, residual) are c128 [nsub, nchan,
# nbin//2+1]; the solver multiplies that by a temporaries factor
# (jacobian rows, line-search copies).  It is a *planning* estimate —
# checked against measured peaks by tools/memory_smoke.py (within 2x),
# not a buffer-assignment readback.
_DTYPE_BYTES = 8        # f64 data-domain arrays
_COMPLEX_BYTES = 16     # c128 harmonic-domain arrays
_DATA_ARRAYS = 4        # subints, masks, model, noise working copy
_HARMONIC_ARRAYS = 3    # data FT, model FT, solver residual
_SOLVER_OVERHEAD = 1.5  # solver temporaries (jacobian, line search)


def estimate_archive_bytes(nchan, nbin, nsub=1, npol=1):
    """Estimated peak device bytes to fit one archive at the canonical
    shape its ``(nchan, nbin)`` pads up to."""
    nchan, nbin = canonical_shape(nchan, nbin)
    nsub = max(1, int(nsub))
    npol = max(1, int(npol))
    data = nsub * npol * nchan * nbin * _DTYPE_BYTES * _DATA_ARRAYS
    harm = nsub * nchan * (nbin // 2 + 1) * _COMPLEX_BYTES \
        * _HARMONIC_ARRAYS
    return int(_SOLVER_OVERHEAD * (data + harm))


class ShapeBucket:
    """One canonical (nchan_pad, nbin_pad) group of archives."""

    def __init__(self, nchan, nbin, archives=None):
        self.nchan = int(nchan)
        self.nbin = int(nbin)
        self.archives = list(archives or [])

    @property
    def key(self):
        return (self.nchan, self.nbin)

    def est_bytes(self):
        """Estimated peak device bytes of this bucket's costliest
        archive (the admission/regression-gate number)."""
        nsub = max((a.nsub for a in self.archives), default=1)
        npol = max((a.npol for a in self.archives), default=1)
        return estimate_archive_bytes(self.nchan, self.nbin,
                                      nsub=nsub, npol=npol)

    def to_dict(self):
        return {"nchan": self.nchan, "nbin": self.nbin,
                "est_bytes": self.est_bytes(),
                "archives": [a.to_dict() for a in self.archives]}

    @classmethod
    def from_dict(cls, d):
        # tolerate pre-PR-12 plans: ``est_bytes`` is recomputed from
        # shapes, so its absence (or staleness) never breaks a load
        return cls(d["nchan"], d["nbin"],
                   [ArchiveInfo.from_dict(a) for a in d["archives"]])


class SurveyPlan:
    """Buckets + unreadable archives + the model the survey fits with.

    ``archives()`` yields (info, bucket) in a deterministic order —
    bucket-major, then metafile order within a bucket — which is also
    the order processes partition over (execute.py), so every process
    of a multihost run derives the same assignment from the same plan.
    """

    def __init__(self, buckets, unreadable, modelfile=None):
        self.buckets = sorted(buckets, key=lambda b: b.key)
        self.unreadable = list(unreadable)  # (path, reason)
        self.modelfile = modelfile

    def archives(self):
        for bucket in self.buckets:
            for info in bucket.archives:
                yield info, bucket

    @property
    def n_archives(self):
        return sum(len(b.archives) for b in self.buckets)

    def to_dict(self):
        return {"schema": PLAN_SCHEMA,
                "modelfile": self.modelfile,
                "n_archives": self.n_archives,
                "buckets": [b.to_dict() for b in self.buckets],
                "unreadable": [{"path": p, "reason": r}
                               for p, r in self.unreadable]}

    @classmethod
    def from_dict(cls, d):
        if d.get("schema") != PLAN_SCHEMA:
            raise ValueError(f"not a survey plan: schema="
                             f"{d.get('schema')!r}")
        return cls([ShapeBucket.from_dict(b) for b in d["buckets"]],
                   [(u["path"], u["reason"]) for u in d["unreadable"]],
                   modelfile=d.get("modelfile"))

    def save(self, path):
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(self.to_dict(), fh, indent=1)
            fh.write("\n")
        os.replace(tmp, path)

    @classmethod
    def load(cls, path):
        with open(path, encoding="utf-8") as fh:
            return cls.from_dict(json.load(fh))


def plan_survey(datafiles, modelfile=None, quiet=True):
    """Scan archives (list of paths or a metafile path) into a
    SurveyPlan: shape buckets + unreadable files with reasons."""
    if isinstance(datafiles, str):
        from ..io.archive import file_is_type, parse_metafile

        try:
            kind = file_is_type(datafiles)
        except OSError as e:
            raise ValueError(f"cannot read {datafiles}: {e}")
        paths = parse_metafile(datafiles) if kind == "ASCII" \
            else [datafiles]
    else:
        paths = list(datafiles)
    buckets = {}
    unreadable = []
    for path in paths:
        try:
            info = scan_archive_header(path)
        except (OSError, ValueError, KeyError,
                faults.InjectedFault) as e:
            unreadable.append((path, str(e)))
            if not quiet:
                print(f"plan: unreadable archive {path}: {e}")
            continue
        key = info.bucket
        if key not in buckets:
            buckets[key] = ShapeBucket(*key)
        buckets[key].archives.append(info)
    plan = SurveyPlan(buckets.values(), unreadable, modelfile=modelfile)
    if not quiet:
        print(f"plan: {plan.n_archives} archives in "
              f"{len(plan.buckets)} shape buckets, "
              f"{len(unreadable)} unreadable")
    return plan


def _resample_nbin(x, nbin_pad):
    """Bandlimited (harmonic zero-pad) resample of [..., nbin] profiles
    to nbin_pad bins; amplitude-preserving.

    Samples live at BIN CENTERS ((k+0.5)/nbin, ops.fourier.
    get_bin_centers), not at the DFT's k/nbin grid — a naive zero-pad
    would therefore shift every profile by half the bin-width
    difference (0.5/nbin - 0.5/nbin_pad rotations; exactly 1/768 rot
    for 96->128, ~40x a typical TOA error).  The harmonic phase ramp
    below re-centers the resampled samples on the new grid's bin
    centers.
    """
    nbin = x.shape[-1]
    if nbin == nbin_pad:
        return x
    FT = np.fft.rfft(x, axis=-1)
    k = np.arange(FT.shape[-1])
    delta = 0.5 / nbin - 0.5 / nbin_pad
    FT = FT * np.exp(-2j * np.pi * k * delta)
    return np.fft.irfft(FT, nbin_pad, axis=-1) * (nbin_pad / nbin)


def pad_databunch(d, nchan_pad, nbin_pad):
    """Pad a loaded archive DataBunch to the bucket's canonical shape.

    Mutates and returns ``d``: subints [nsub, npol, nchan_pad,
    nbin_pad], padded channels zero-weight (median-noise, zero-SNR),
    profiles Fourier-resampled along the bin axis with noise rescaled
    (module docstring).  Native shape is recorded as ``nchan_native``/
    ``nbin_native``; bw scales with nchan so the per-channel bandwidth
    stays the native value.  No-op when already canonical.
    """
    faults.check("archive_pad", key=getattr(d, "filename", None))
    nsub, npol, nchan, nbin = d.subints.shape
    if nchan == nchan_pad and nbin == nbin_pad:
        return d
    if nchan_pad < nchan or nbin_pad < nbin:
        raise ValueError(f"pad {nchan}x{nbin} -> {nchan_pad}x{nbin_pad}"
                         " shrinks the archive")
    d.nchan_native, d.nbin_native = nchan, nbin
    if nbin != nbin_pad:
        d.subints = _resample_nbin(d.subints, nbin_pad)
        d.prof = _resample_nbin(d.prof, nbin_pad)
        # keep the harmonic-domain noise (and red chi2) consistent:
        # the resampled profile carries the same harmonic amplitudes
        # over more bins
        scale = np.sqrt(nbin / nbin_pad)
        d.noise_stds = d.noise_stds * scale
        d.prof_noise = d.prof_noise * scale
        d.nbin = nbin_pad
        d.phases = (np.arange(nbin_pad) + 0.5) / nbin_pad
    if nchan != nchan_pad:
        extra = nchan_pad - nchan
        # extrapolate channel frequencies on the native spacing (sign
        # preserved for descending bands)
        step = (d.freqs[:, -1] - d.freqs[:, 0]) / max(nchan - 1, 1)
        step = np.where(step == 0.0, 1.0, step)
        pad_freqs = d.freqs[:, -1:] + step[:, None] * \
            np.arange(1, extra + 1)
        d.freqs = np.concatenate([d.freqs, pad_freqs], axis=1)
        d.subints = np.concatenate(
            [d.subints, np.zeros((nsub, npol, extra, d.nbin))], axis=2)
        d.weights = np.concatenate(
            [d.weights, np.zeros((nsub, extra))], axis=1)
        # median-noise padding keeps the guess stage's median-over-
        # channels noise estimate unbiased (zero would divide, and a
        # constant could dominate the median when extra ~ nchan)
        med = np.median(d.noise_stds, axis=2, keepdims=True)
        med = np.where(med > 0.0, med, 1.0)
        d.noise_stds = np.concatenate(
            [d.noise_stds, np.broadcast_to(med, (nsub, npol, extra))],
            axis=2)
        d.SNRs = np.concatenate(
            [d.SNRs, np.zeros((nsub, npol, extra))], axis=2)
        d.bw = d.bw * nchan_pad / nchan
        d.nchan = nchan_pad
        # ok_isubs is weight-derived and unchanged (padded channels are
        # dead); ok_ichans stays the native live set per subint
    weights_norm = np.where(d.weights == 0.0, 0.0, 1.0)
    d.masks = np.broadcast_to(
        weights_norm[:, None, :, None],
        (nsub, npol, d.nchan, d.nbin)).copy()
    return d


def load_bucketed_databunch(datafile, bucket_shape, tscrunch=False,
                            quiet=True):
    """The complete host-side load of one bucketed archive: FITS decode
    with the dmc-reload fallback (pipelines.toas.load_archive_data) +
    pad to the bucket's canonical shape.

    This is THE load path of the bucketed fit loop
    (execute._BucketedGetTOAs) and of the host prefetch stage
    (runner/prefetch.py) — one implementation, so a prefetched buffer
    is bit-identical to a serial load and the ``archive_read`` /
    ``archive_pad`` fault sites fire on whichever thread actually runs
    the load.  Returns the padded DataBunch, or None when the archive
    is unloadable or its header lied about the shape (bucket smaller
    than the decoded data); anything pad_databunch raises beyond
    ValueError (e.g. an injected RuntimeError) propagates so it travels
    the fit loop's fault-isolation path unchanged.
    """
    from ..pipelines.toas import load_archive_data

    bucket_shape = tuple(bucket_shape)
    data = load_archive_data(datafile, tscrunch=tscrunch, quiet=quiet)
    if data is None:
        return None
    try:
        return pad_databunch(data, *bucket_shape)
    except ValueError as e:
        if not quiet:
            print(f"Cannot pad {datafile} to bucket "
                  f"{bucket_shape}: {e}; skipping it.")
        return None
