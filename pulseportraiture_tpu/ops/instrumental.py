"""Instrumental response Fourier kernels (smearing / binning / averaging).

TPU-native equivalent of /root/reference/pptoaslib.py:112-179
(``instrumental_response_FT`` / ``instrumental_response_port_FT``).
"""

import jax.numpy as jnp

from ..config import complex_dtype_for, fft_real_dtype
from .profiles import gaussian_profile_FT

__all__ = ["instrumental_response_FT", "instrumental_response_port_FT"]


def instrumental_response_FT(nbin, wid=0.0, irf_type="rect"):
    """rFFT of a unit-area instrumental response of width ``wid`` [rot].

    'rect' gives sinc(k*wid); 'gauss' a unit-peak-normalized Gaussian FT.
    wid=0 returns ones (no effect).  Equivalent of
    /root/reference/pptoaslib.py:112-143.
    """
    nharm = nbin // 2 + 1
    k = jnp.arange(nharm, dtype=fft_real_dtype(jnp.float64))
    if irf_type == "rect":
        resp = jnp.sinc(k * jnp.asarray(wid, k.dtype))
    elif irf_type == "gauss":
        gp_FT = gaussian_profile_FT(nbin, 0.0, wid, 1.0)
        resp = gp_FT / gp_FT[0]
    else:
        raise ValueError(f"Unrecognized instrumental response type "
                         f"'{irf_type}'.")
    return jnp.where(wid == 0.0, jnp.ones(nharm, resp.dtype), resp)


def instrumental_response_port_FT(nbin, freqs, DM=0.0, P=1.0, wids=(),
                                  irf_types=()):
    """Combined per-channel instrumental response FT: [nchan, nharm].

    Multiplies the constant-width responses in ``wids``/``irf_types`` with
    the per-channel DM-smearing rectangle of width
    8.3e-6 * chan_bw * (nu/GHz)**-3 / P [rot] when DM != 0 (Bhat et al.
    2003).  Equivalent of /root/reference/pptoaslib.py:145-179.

    Parity note: the reference's smearing width omits the factor of DM
    from the Bhat et al. formula (8.3 us * DM * chbw_MHz * nu_GHz**-3) —
    DM acts only as an on/off gate there.  We reproduce that behavior
    bit-for-bit; callers wanting the physical width can fold DM into
    ``wids`` explicitly.
    """
    freqs = jnp.asarray(freqs)
    nchan = freqs.shape[0]
    nharm = nbin // 2 + 1
    out = jnp.ones([nchan, nharm],
                   dtype=complex_dtype_for(fft_real_dtype(freqs.dtype)))
    for wid, irf_type in zip(wids, irf_types):
        out = out * instrumental_response_FT(nbin, wid, irf_type)[None, :]
    if DM:
        chan_bw = jnp.abs(freqs[1] - freqs[0])
        smear_wids = 8.3e-6 * chan_bw / (freqs / 1e3) ** 3 / P  # [nchan]
        fft_dt = fft_real_dtype(jnp.float64)
        k = jnp.arange(nharm, dtype=fft_dt)
        out = out * jnp.sinc(k[None, :]
                             * smear_wids.astype(fft_dt)[:, None])
    return out
