"""Off-pulse noise and S/N estimators.

TPU-native equivalent of /root/reference/pplib.py:2206-2308 (``get_noise``,
``get_noise_PS``, ``get_noise_fit``, ``get_SNR``) and the noise-floor
cutoff fit ``find_kc`` (/root/reference/pplib.py:1436-1495).

The "PS" estimator — sqrt of the mean of the top 1/frac of the power
spectrum — is the hot default and is fully batched: one rFFT over
[..., nbin] and a static slice, vmappable over (subint, channel).  The
"fit" estimator brute-fits a half-triangle to the log power spectrum to
locate the noise-floor harmonic; its grid search is expressed as a dense
masked scan over all (cutoff, height) candidates, which XLA turns into a
single reduction instead of the reference's host-side ``opt.brute``.
"""

import jax
import jax.numpy as jnp

from ..config import as_fft_operand

__all__ = ["get_noise", "get_noise_PS", "get_noise_fit", "get_SNR",
           "find_kc", "half_triangle_function", "wiener_filter",
           "brickwall_filter", "fit_brickwall", "wiener_smooth"]


def get_noise(data, method="PS", **kwargs):
    """Dispatch noise estimation (reference pplib.py:2206-2225).

    data: [..., nbin]; returns scalar for 1-D input, [...] otherwise
    (the reference's ``chans=True`` flag is subsumed by batch shape).
    """
    if method == "PS":
        return get_noise_PS(data, **kwargs)
    elif method == "fit":
        return get_noise_fit(data, **kwargs)
    raise ValueError(f"Unknown get_noise method '{method}'.")


def get_noise_PS(data, frac=4):
    """Noise from the mean of the top 1/frac of the power spectrum.

    Equivalent of /root/reference/pplib.py:2227-2253 with chans handled by
    broadcasting: the estimate is per leading-batch element.
    """
    data = jnp.asarray(data)
    nbin = data.shape[-1]
    FFT = jnp.fft.rfft(as_fft_operand(data), axis=-1)
    pows = jnp.real(FFT * jnp.conj(FFT)) / nbin
    npow = pows.shape[-1]
    kc = int((1 - 1.0 / frac) * npow)
    return jnp.sqrt(jnp.mean(pows[..., kc:], axis=-1))


def half_triangle_function(a, b, dc, N):
    """Half-triangle of base floor(a), height b, on a dc baseline.

    Equivalent of /root/reference/pplib.py:1436-1446.
    """
    a = jnp.floor(a)
    k = jnp.arange(N, dtype=jnp.result_type(a, b, dc))
    return dc + jnp.where(k < a, b - (b / a) * k, 0.0)


def find_kc(pows, fn="exp_dc", Ns=20):
    """Noise-floor cutoff harmonic from a brute fit to log10 power.

    Matches the reference's opt.brute(Ns=20, finish=None) grid fit of
    (a, b, dc) (/root/reference/pplib.py:1448-1495) as one dense masked
    reduction on device:

    * 'exp_dc' (reference default): model = b*exp(-a*k) + dc with
      a in [1/N, 1], b in [0, range], dc in [min, max]; the cutoff is
      the first k with exp(-a*k) < 0.005 (else N-1).
    * 'half_tri': model = half_triangle(a, b, dc); cutoff = floor(a),
      a in [1, N].
    """
    pows = jnp.asarray(pows)
    N = pows.shape[-1]
    logp = jnp.log10(pows)
    rdt = logp.dtype  # grids track the (possibly TPU-clamped) spectrum
    lmin, lmax = logp.min(), logp.max()
    # scipy.optimize.brute with Ns points spans [lo, hi) like mgrid slices
    # with complex step: inclusive endpoints.
    b_grid = jnp.linspace(0.0, lmax - lmin, Ns, dtype=rdt)
    dc_grid = jnp.linspace(lmin, lmax, Ns, dtype=rdt)
    k = jnp.arange(N, dtype=rdt)
    if fn == "exp_dc":
        a_grid = jnp.linspace(1.0 / N, 1.0, Ns, dtype=rdt)
        shape_ak = jnp.exp(-a_grid[:, None] * k[None, :])      # [Ns, N]
    elif fn == "half_tri":
        a_grid = jnp.linspace(1.0, float(N), Ns, dtype=rdt)
        fa = jnp.floor(a_grid)[:, None]
        shape_ak = jnp.where(k[None, :] < fa, 1.0 - k[None, :] / fa, 0.0)
    else:
        raise ValueError(f"Unknown find_kc fn '{fn}'.")
    model = b_grid[None, :, None, None] * shape_ak[:, None, None, :] \
        + dc_grid[None, None, :, None]                  # [Ns, Ns, Ns, N]
    chi2 = jnp.sum((logp[None, None, None, :] - model) ** 2, axis=-1)
    ia = jnp.argmin(chi2) // (Ns * Ns)
    a = a_grid[ia]
    if fn == "exp_dc":
        below = jnp.exp(-a * k) < 0.005
        return jnp.where(jnp.any(below),
                         jnp.argmax(below).astype(jnp.int32), N - 1)
    return jnp.int32(jnp.floor(a))


def get_noise_fit(data, fact=1.1, fn="exp_dc"):
    """Noise from harmonics above a fitted noise-floor cutoff.

    Equivalent of /root/reference/pplib.py:2255-2287 (k_crit =
    fact * find_kc(pows), clipped to 0.99*npow), vmapped over channels.
    """
    data = jnp.asarray(data)
    nbin = data.shape[-1]
    FFT = jnp.fft.rfft(as_fft_operand(data), axis=-1)
    pows = jnp.real(FFT * jnp.conj(FFT)) / nbin
    npow = pows.shape[-1]

    def one(p):
        k_crit = jnp.minimum(fact * find_kc(p, fn=fn), int(0.99 * npow))
        mask = jnp.arange(npow, dtype=jnp.int32) >= k_crit
        return jnp.sqrt(jnp.sum(jnp.where(mask, p, 0.0)) / jnp.sum(mask))

    if data.ndim == 1:
        return one(pows)
    flat = jax.vmap(one)(pows.reshape(-1, npow))
    return flat.reshape(data.shape[:-1])


def _profile_spectrum(prof):
    """rFFT and |rfft|^2/nbin power of a profile (batched)."""
    prof = jnp.asarray(prof)
    FFT = jnp.fft.rfft(as_fft_operand(prof), axis=-1)
    pows = jnp.real(FFT * jnp.conj(FFT)) / prof.shape[-1]
    return FFT, pows


def _wiener_from_pows(pows, noise):
    sig = jnp.maximum(pows - noise ** 2, 0.0)
    return sig / (sig + noise ** 2)


def wiener_filter(prof, noise):
    """Per-harmonic Wiener filter H_k = S_k / (S_k + N_k) for a noisy
    profile.

    A *working* version of the reference's under-construction filter
    (/root/reference/pplib.py:1393-1408, marked "#FIX does not work"):
    in the |rfft|^2/nbin convention the white-noise floor per harmonic
    is noise^2, and the *signal* power is the measured power minus that
    floor (clipped at zero) — the reference used the total power as S,
    which biases H toward 1 everywhere.  Batched over leading dims.
    """
    return _wiener_from_pows(_profile_spectrum(prof)[1], noise)


def brickwall_filter(N, kc):
    """Binary low-pass filter: ones below harmonic kc, zeros above
    (equivalent of /root/reference/pplib.py:1410-1418; jit-safe for
    traced kc, batched over kc's leading dims)."""
    return jnp.where(jnp.arange(N, dtype=jnp.int32)
                     < jnp.asarray(kc)[..., None], 1.0, 0.0)


def fit_brickwall(prof, noise):
    """Best-fit brickwall cutoff kc to the profile's Wiener filter.

    Minimizes ||wiener_filter - brickwall(kc)||^2 over kc, evaluated in
    closed form with cumulative sums (the L2-optimal binary approximation
    of the filter) instead of the reference's O(N^2) host loop
    (/root/reference/pplib.py:1420-1434, "#FIX this is obviously
    wrong" — its objective was right, but it compared against the broken
    wiener_filter).  Returns the harmonic index kc.
    """
    return _fit_brickwall_from_wf(wiener_filter(prof, noise))


def _fit_brickwall_from_wf(wf):
    # X2(kc) = sum_{i<kc} (wf_i - 1)^2 + sum_{i>=kc} wf_i^2
    ones_cost = jnp.concatenate([jnp.zeros(wf.shape[:-1] + (1,),
                                           dtype=wf.dtype),
                                 jnp.cumsum((wf - 1.0) ** 2, axis=-1)],
                                axis=-1)
    tot = jnp.sum(wf ** 2, axis=-1, keepdims=True)
    zeros_cost = tot - jnp.concatenate(
        [jnp.zeros(wf.shape[:-1] + (1,), dtype=wf.dtype),
         jnp.cumsum(wf ** 2, axis=-1)], axis=-1)
    return jnp.argmin(ones_cost + zeros_cost, axis=-1).astype(jnp.int32)


def wiener_smooth(prof, noise, brickwall=False):
    """Denoise a profile by its Wiener (or best-fit brickwall) filter —
    the application the reference's under-construction filters were
    building toward.  Returns the filtered profile."""
    prof = jnp.asarray(prof)
    nbin = prof.shape[-1]
    FFT, pows = _profile_spectrum(prof)
    H = _wiener_from_pows(pows, noise)
    if brickwall:
        H = brickwall_filter(nbin // 2 + 1, _fit_brickwall_from_wf(H))
    return jnp.fft.irfft(FFT * H, nbin, axis=-1).astype(prof.dtype)


def get_SNR(prof, fudge=3.25, noise_method="PS"):
    """Lorimer & Kramer S/N with the reference's PSRCHIVE-matching fudge.

    Assumes the baseline has been removed.  Batched over leading dims.
    Equivalent of /root/reference/pplib.py:2289-2308.
    """
    prof = jnp.asarray(prof)
    noise = get_noise(prof, method=noise_method)
    Weq = prof.sum(axis=-1) / prof.max(axis=-1)
    mask = jnp.where(Weq <= 0.0, 0.0, 1.0)
    Weq = jnp.where(Weq <= 0.0, 1.0, Weq)
    SNR = prof.sum(axis=-1) / (noise * Weq ** 0.5)
    return (SNR * mask) / fudge
