"""Stationary wavelet denoising (Daubechies), FFT-domain and batched.

TPU-native equivalent of the reference's wavelet smoothing layer
(/root/reference/pplib.py:1621-1761 ``wavelet_smooth``/``smart_smooth``/
``fit_wavelet_smooth_function``), which drives PyWavelets' ``swt/iswt``
inside a per-profile, per-level ``opt.brute`` host loop.

Design (not a translation):

* PyWavelets is replaced by an in-repo implementation.  The undecimated
  (a trous) SWT with periodic boundaries is a circular convolution per
  level, so both the transform and its exact inverse are expressed as
  FFT multiplies with the level-j filter response H(2^j w) — batched
  rFFT-style ops that vectorize over (channel, threshold-candidate) and
  compile to one XLA program, instead of pywt's per-profile C loops.
* Daubechies scaling filters are generated numerically by spectral
  factorization (roots of the binomial polynomial), not hard-coded
  tables; ``daubechies_dec_lo(2)`` reproduces the textbook db2 values to
  1e-12 (tested).
* The reference's ``opt.brute`` over the threshold factor, run per
  profile per level on the host, becomes a dense [nlevel, nfact]
  candidate grid evaluated in one vmapped computation with an argmax
  selection — ``smart_smooth`` of a whole portrait is a single device
  call.
* Thresholding: universal threshold sigma*sqrt(2 ln nbin) with sigma
  from the median absolute *finest-detail* coefficient (Donoho-Johnstone
  estimator).  The reference medians over its library's first-returned
  coefficient pair instead; the smart_smooth factor search absorbs the
  scale difference.  Only detail bands are thresholded (the
  approximation band carries the profile baseline).
"""

import functools
from math import comb

import jax
import jax.numpy as jnp
import numpy as np

from ..config import as_fft_operand, complex_dtype_for, fft_real_dtype
from .noise import get_noise

__all__ = ["daubechies_dec_lo", "swt", "iswt", "wavelet_smooth",
           "smart_smooth", "threshold"]


@functools.lru_cache(maxsize=None)
def daubechies_dec_lo(N):
    """Daubechies scaling (lowpass analysis) filter with N vanishing
    moments (2N taps, 'db{N}'), by spectral factorization.

    H(z) = sqrt(2) ((1+z)/2)^N Q(z) with |Q(e^{iw})|^2 = P(sin^2(w/2)),
    P(y) = sum_{k<N} C(N-1+k, k) y^k; Q keeps the minimum-phase roots.
    """
    if N < 1:
        raise ValueError("N >= 1 required")
    if N == 1:  # Haar
        return np.array([1.0, 1.0]) / np.sqrt(2.0)
    p = np.array([comb(N - 1 + k, k) for k in range(N)], dtype=np.float64)
    yroots = np.roots(p[::-1])
    zroots = []
    for y in yroots:
        # y = (2 - z - 1/z)/4  =>  z^2 - (2 - 4y) z + 1 = 0
        b = 2.0 - 4.0 * y
        disc = np.sqrt(b * b - 4.0 + 0j)
        for z in ((b + disc) / 2.0, (b - disc) / 2.0):
            if abs(z) < 1.0:
                zroots.append(z)
    q = np.array([1.0 + 0j])
    for z in zroots:
        q = np.convolve(q, np.array([1.0, -z]))
    h = np.array([1.0])
    for _ in range(N):
        h = np.convolve(h, np.array([1.0, 1.0]))
    h = np.convolve(h, q.real)
    return h * (np.sqrt(2.0) / h.sum())


def _filter_responses(wavelet, nbin, dtype):
    """(H, G): full-FFT frequency responses of the analysis lo/hi filters
    on an nbin-point circle.  g_n = (-1)^n h_{L-1-n} (QMF)."""
    if isinstance(wavelet, str):
        if not wavelet.startswith("db"):
            raise ValueError(f"unsupported wavelet '{wavelet}'")
        h = daubechies_dec_lo(int(wavelet[2:]))
    else:
        h = np.asarray(wavelet, dtype=np.float64)
    L = len(h)
    g = ((-1.0) ** np.arange(L)) * h[::-1]
    cdt = complex_dtype_for(fft_real_dtype(dtype))
    H = jnp.asarray(np.fft.fft(h, nbin), dtype=cdt)
    G = jnp.asarray(np.fft.fft(g, nbin), dtype=cdt)
    return H, G


def _level_response(H, j):
    """Response of the level-j a-trous-upsampled filter: H(2^j w)."""
    nbin = H.shape[0]
    idx = (np.arange(nbin) * (2 ** j)) % nbin
    return H[idx]


def swt(x, nlevel, wavelet="db8"):
    """Undecimated wavelet transform of [..., nbin] with periodic
    boundaries; returns (cA [..., nbin], cDs list of nlevel arrays,
    finest first).  Perfect-reconstruction partner of ``iswt``."""
    x = jnp.asarray(x)
    nbin = x.shape[-1]
    H, G = _filter_responses(wavelet, nbin, x.dtype)
    A = jnp.fft.fft(as_fft_operand(x), axis=-1)
    cDs = []
    for j in range(nlevel):
        Hj, Gj = _level_response(H, j), _level_response(G, j)
        cDs.append(jnp.real(jnp.fft.ifft(jnp.conj(Gj) * A, axis=-1)))
        A = jnp.conj(Hj) * A
    cA = jnp.real(jnp.fft.ifft(A, axis=-1))
    return cA, cDs


def iswt(cA, cDs, wavelet="db8"):
    """Inverse of ``swt``: exact reconstruction via the synthesis
    responses (|H|^2 + |G|^2 = 2 for orthonormal filters)."""
    cA = jnp.asarray(cA)
    nbin = cA.shape[-1]
    H, G = _filter_responses(wavelet, nbin, cA.dtype)
    A = jnp.fft.fft(as_fft_operand(cA), axis=-1)
    for j in reversed(range(len(cDs))):
        Hj, Gj = _level_response(H, j), _level_response(G, j)
        D = jnp.fft.fft(as_fft_operand(cDs[j]), axis=-1)
        A = 0.5 * (Hj * A + Gj * D)
    return jnp.real(jnp.fft.ifft(A, axis=-1))


def threshold(c, value, mode="hard"):
    """Hard/soft wavelet thresholding (pywt.threshold semantics)."""
    c = jnp.asarray(c)
    value = jnp.asarray(value)
    if mode == "hard":
        return jnp.where(jnp.abs(c) < value, 0.0, c)
    if mode == "soft":
        return jnp.sign(c) * jnp.maximum(jnp.abs(c) - value, 0.0)
    raise ValueError(f"unknown threshold mode '{mode}'")


def wavelet_smooth(port, wavelet="db8", nlevel=5, threshtype="hard",
                   fact=1.0):
    """Wavelet-denoised portrait or profile (universal threshold).

    port: [nbin] or [..., nbin]; ``fact`` scales the threshold and may
    carry extra leading batch dims (e.g. a candidate grid) that
    broadcast against port's batch shape.  Behavioral equivalent of
    /root/reference/pplib.py:1621-1666, batched.
    """
    port = jnp.asarray(port)
    nbin = port.shape[-1]
    cA, cDs = swt(port, nlevel, wavelet)
    sigma = jnp.median(jnp.abs(cDs[0]), axis=-1) / 0.6745
    lopt = jnp.asarray(fact) * sigma * jnp.sqrt(2.0 * jnp.log(float(nbin)))
    cA = jnp.broadcast_to(cA, lopt.shape + cA.shape[-1:])
    cDs = [threshold(D, lopt[..., None], threshtype) for D in cDs]
    return iswt(cA, cDs, wavelet)


def _pseudo_snr(smooth_prof):
    """Fourier-domain pseudo-S/N used by the smoothing-factor search
    (reference pplib.py:1737-1761)."""
    sig = jnp.sum(
        jnp.abs(jnp.fft.rfft(as_fft_operand(smooth_prof),
                             axis=-1)[..., 1:]) ** 2, axis=-1)
    noise = get_noise(smooth_prof) * jnp.sqrt(smooth_prof.shape[-1] / 2.0)
    return jnp.where(noise > 0.0, sig / jnp.where(noise > 0.0, noise, 1.0),
                     jnp.where(sig > 0.0, jnp.inf, 0.0))


@functools.partial(jax.jit, static_argnames=("try_nlevels", "nfact",
                                             "wavelet", "threshtype"))
def _smart_smooth_grid(port, try_nlevels, nfact, rchi2_tol, wavelet,
                       threshtype):
    """Dense (nlevel x fact) candidate search, one XLA program.

    Returns the per-profile best smooth [..., nbin] (zeros where no
    candidate satisfies |red_chi2 - 1| <= rchi2_tol).
    """
    port = jnp.asarray(port)
    nbin = port.shape[-1]
    errs = get_noise(port)                      # [...] per profile
    facts = jnp.linspace(0.0, 3.0, nfact, dtype=port.dtype)

    # reduced chi2 of smooth-vs-raw with dof = nbin.  The gate is
    # one-sided, chi2 <= 1 + tol: over-distortion (removing more than
    # the noise) is rejected, while chi2 < 1 - tol (under-smoothing, or
    # a biased-high noise estimate making chi2 = (sigma/sigma_est)^2 < 1
    # even at perfect denoising) stays eligible — the pseudo-S/N argmax
    # then drives toward the most aggressive admissible smoothing.  The
    # reference's two-sided |chi2 - 1| <= tol gate silently zeroes
    # profiles whenever its noise estimator runs a few percent hot.
    def chi2_of(sm):
        r = (port - sm) / jnp.where(errs > 0.0, errs, 1.0)[..., None]
        return jnp.sum(r * r, axis=-1) / nbin

    best = jnp.zeros_like(port)
    best_snr = jnp.full(port.shape[:-1], -jnp.inf, dtype=port.dtype)
    for ilevel in range(try_nlevels):
        # [nfact, ..., nbin] candidates for this decomposition depth
        fgrid = facts.reshape((nfact,) + (1,) * (port.ndim - 1))
        sm = wavelet_smooth(port, wavelet, ilevel + 1, threshtype, fgrid)
        snr = _pseudo_snr(sm)                   # [nfact, ...]
        ok = chi2_of(sm) - 1.0 <= rchi2_tol
        snr = jnp.where(ok, snr, 0.0)
        ibest = jnp.argmax(snr, axis=0)         # [...]
        sm_best = jnp.take_along_axis(
            sm, ibest[None, ..., None], axis=0)[0]
        snr_best = jnp.take_along_axis(snr, ibest[None], axis=0)[0]
        improve = snr_best > best_snr
        best = jnp.where(improve[..., None], sm_best, best)
        best_snr = jnp.maximum(best_snr, snr_best)
    final_ok = (best_snr > 0.0) & (chi2_of(best) - 1.0 <= rchi2_tol)
    return jnp.where(final_ok[..., None], best, 0.0)


def smart_smooth(port, try_nlevels=None, rchi2_tol=0.1, wavelet="db8",
                 threshtype="hard", nfact=30, fallback="zero"):
    """Automated wavelet smoothing: maximize pseudo-S/N over
    (nlevel, fact) subject to red-chi2 within ``rchi2_tol`` of 1.

    port: [nbin] or [nchan, nbin].  Equivalent of
    /root/reference/pplib.py:1668-1735 with the per-profile
    ``opt.brute`` replaced by the dense on-device grid search.
    ``fallback`` controls profiles where no candidate satisfies the
    chi2 gate: 'zero' zeroes them (the reference's behavior — correct
    for eigenvector *significance* screening), 'raw' returns them
    unsmoothed (correct when the caller needs a usable profile, e.g.
    the model mean profile of nearly noiseless data).
    """
    port_in = np.asarray(port)
    nbin = port_in.shape[-1]
    if try_nlevels == 0 or nbin % 2 != 0:
        return port_in
    if np.modf(np.log2(nbin))[1] != np.log2(nbin):
        try_nlevels = 1
    elif try_nlevels is None:
        try_nlevels = int(np.log2(nbin))
    out = np.array(_smart_smooth_grid(
        jnp.asarray(port_in), int(try_nlevels), int(nfact),
        float(rchi2_tol), wavelet, threshtype))
    if fallback == "raw":
        failed = ~np.any(out, axis=-1)
        if port_in.ndim > 1:
            out[failed] = port_in[failed]
        elif failed:
            out = port_in.copy()
    elif port_in.ndim > 1:  # all-zero profiles stay zero (reference skips)
        out[~np.any(port_in, axis=-1)] = 0.0
    return out
