"""Power-law spectrum utilities.

TPU-native equivalent of /root/reference/pplib.py:1048-1096 (``powlaw``,
``powlaw_integral``, ``powlaw_freqs``) and the ISM helpers
/root/reference/pplib.py:1176-1202 (``mean_C2N``, ``dDM``).
"""

import jax.numpy as jnp

__all__ = ["powlaw", "powlaw_integral", "powlaw_freqs", "mean_C2N", "dDM"]


def powlaw(nu, nu_ref, A, alpha):
    """F(nu) = A*(nu/nu_ref)**alpha (reference pplib.py:1048-1052)."""
    return A * (nu / nu_ref) ** alpha


def powlaw_integral(nu2, nu1, nu_ref, A, alpha):
    """Definite integral of A*(nu/nu_ref)**alpha from nu1 to nu2.

    Equivalent of /root/reference/pplib.py:1054-1066.
    """
    alpha = jnp.asarray(alpha, dtype=jnp.float64)
    log_case = A * nu_ref * jnp.log(nu2 / nu1)
    safe_alpha = jnp.where(alpha == -1.0, 0.0, alpha)
    C = A * (nu_ref ** -safe_alpha) / (1 + safe_alpha)
    gen_case = C * ((nu2 ** (1 + safe_alpha)) - (nu1 ** (1 + safe_alpha)))
    return jnp.where(alpha == -1.0, log_case, gen_case)


def powlaw_freqs(lo, hi, N, alpha, mid=False):
    """Channel edges (or centers) with equal flux per channel for a
    power-law spectrum of index alpha.

    Equivalent of /root/reference/pplib.py:1068-1096.
    """
    alpha = jnp.asarray(alpha, dtype=jnp.float64)
    log_nus = jnp.exp(jnp.linspace(jnp.log(lo), jnp.log(hi), N + 1,
                                   dtype=jnp.float64))
    safe_alpha = jnp.where(alpha == -1.0, 0.0, alpha)
    gen_nus = jnp.power(
        jnp.linspace(lo ** (1 + safe_alpha), hi ** (1 + safe_alpha), N + 1,
                     dtype=jnp.float64),
        (1 + safe_alpha) ** -1)
    nus = jnp.where(alpha == -1.0, log_nus, gen_nus)
    if mid:
        nus = 0.5 * (nus[:-1] + nus[1:])
    return nus


def mean_C2N(nu, D, bw_scint):
    """Mean turbulence strength C2N [m**-20/3] (Foster, Fairhead & Backer
    1991); nu [MHz], D [kpc], scintillation bandwidth bw_scint [MHz].

    Equivalent of /root/reference/pplib.py:1176-1187.
    """
    return 2e-14 * nu ** (11 / 3.0) * D ** (-11 / 6.0) * \
        bw_scint ** (-5 / 6.0)


def dDM(D, D_screen, nu, bw_scint):
    """delta-DM [cm**-3 pc] predicted for a frequency-dependent DM.

    D = pulsar distance [kpc], D_screen = Earth-screen distance [kpc],
    nu [MHz], bw_scint = scintillation bandwidth at nu [MHz].
    References: Cordes & Shannon (2010); Foster, Fairhead & Backer (1991).
    Equivalent of /root/reference/pplib.py:1189-1202.
    """
    SM = mean_C2N(nu, D, bw_scint) * D  # scattering measure [m**-20/3 kpc]
    return 10 ** 4.45 * SM * D_screen ** (5 / 6.0) * nu ** (-11 / 6.0)
