"""Scattering model: tau(nu) power law and analytic Fourier-domain kernels.

TPU-native equivalent of the reference's scattering machinery
(/root/reference/pplib.py:4053-4101 ``scattering_times``/
``scattering_profile_FT``/``scattering_portrait_FT``; time-domain legacy
kernels pplib.py:1098-1174; derivative chain
/root/reference/pptoaslib.py:246-388).

All kernels are expressed directly in the harmonic domain: convolution with
the one-sided exponential of timescale tau [rot] is multiplication by
B_k = 1 / (1 + 2*pi*i*k*tau).  Derivatives with respect to (tau, alpha) use
the identity dB/dtau = B*(B-1)/tau, which the reference also exploits; we
evaluate it in the algebraically-safe form -2*pi*i*k*B**2 so tau -> 0 is
finite and the whole chain stays differentiable under jit (no data-dependent
branches on tau, unlike the reference's ``if taus.sum()`` host branches).
"""

import jax
import jax.numpy as jnp

from ..config import as_fft_operand, fft_real_dtype

__all__ = [
    "scattering_times",
    "scattering_times_deriv",
    "scattering_times_2deriv",
    "scattering_profile_FT",
    "scattering_portrait_FT",
    "scattering_portrait_FT_deriv",
    "scattering_portrait_FT_2deriv",
    "abs_scattering_portrait_FT",
    "abs_scattering_portrait_FT_deriv",
    "abs_scattering_portrait_FT_2deriv",
    "scattering_kernel",
    "add_scattering",
]


def scattering_times(tau, alpha, freqs, nu_tau):
    """tau(nu) = tau * (nu/nu_tau)**alpha (reference pplib.py:4053-4059)."""
    freqs = jnp.asarray(freqs)
    return tau * (freqs / nu_tau) ** alpha


def scattering_times_deriv(tau, freqs, nu_tau, log10_tau, taus):
    """d taus / d(tau or log10 tau, alpha): shape [2, nchan].

    When ``log10_tau`` the tau parameter is log10(tau) and
    d taus/d log10(tau) = ln(10)*taus.  Equivalent of
    /root/reference/pptoaslib.py:246-257, with the tau==0 branch expressed
    arithmetically ((freqs/nu_tau)**alpha is used directly for dtau).
    """
    freqs = jnp.asarray(freqs)
    if log10_tau:
        dtau = jnp.log(10.0) * taus
    else:
        dtau = jnp.where(tau != 0.0, taus / jnp.where(tau != 0.0, tau, 1.0),
                         0.0)
    dalpha = jnp.log(freqs / nu_tau) * taus
    return jnp.stack([dtau, dalpha])


def scattering_times_2deriv(tau, freqs, nu_tau, log10_tau, taus, taus_deriv):
    """Second derivatives of taus wrt (tau, alpha): shape [2, 2, nchan].

    Equivalent of /root/reference/pptoaslib.py:259-274.
    """
    freqs = jnp.asarray(freqs)
    dtau, dalpha = taus_deriv
    if log10_tau:
        d2tau = jnp.log(10.0) * dtau
        dtaudalpha = jnp.log(10.0) * dalpha
    else:
        d2tau = jnp.zeros_like(dtau)
        dtaudalpha = jnp.where(tau != 0.0,
                               dalpha / jnp.where(tau != 0.0, tau, 1.0), 0.0)
    d2alpha = jnp.log(freqs / nu_tau) * dalpha
    return jnp.stack([jnp.stack([d2tau, dtaudalpha]),
                      jnp.stack([dtaudalpha, d2alpha])])


def scattering_profile_FT(tau, nbin):
    """Analytic rFFT of the one-sided exponential scattering kernel.

    B_k = (1 + 2*pi*i*k*tau)**-1 with tau in [rot]; tau=0 gives ones.
    Equivalent of /root/reference/pplib.py:4061-4084.
    """
    nharm = nbin // 2 + 1
    tau = as_fft_operand(tau)
    k = jnp.arange(nharm, dtype=tau.dtype)
    # 1/(1+ix) = (1-ix)/(1+x^2), expressed in real ops + lax.complex so
    # no complex128 reaches a backend that lacks it (TPU-safe)
    x = 2.0 * jnp.pi * k * tau
    denom = 1.0 + x * x
    return jax.lax.complex(1.0 / denom, -x / denom)


def scattering_portrait_FT(taus, nbin, nharm=None):
    """Per-channel scattering FT: [..., nchan, nharm].

    Equivalent of /root/reference/pplib.py:4086-4101 without the host-side
    ``np.any(taus)`` branch (tau=0 channels already yield ones).
    ``nharm`` builds only the lowest harmonics (for callers working on a
    model_kmax-truncated spectrum).
    """
    # pp_scatter: device-time attribution scope (obs/devtime.py) — op
    # names of the kernel carry it into profiler captures
    with jax.named_scope("pp_scatter"):
        taus = as_fft_operand(taus)
        if nharm is None:
            nharm = nbin // 2 + 1
        k = jnp.arange(nharm, dtype=taus.dtype)
        x = 2.0 * jnp.pi * k * taus[..., None]
        denom = 1.0 + x * x
        return jax.lax.complex(1.0 / denom, -x / denom)


def scattering_portrait_FT_deriv(taus, taus_deriv, scat_port_FT):
    """d scat_FT / d(tau, alpha): shape [2, ..., nchan, nharm].

    Uses dB/dtaus = B*(B-1)/taus = -2*pi*i*k*B**2 (finite at taus=0),
    then the chain rule with taus_deriv.  Math equivalent of
    /root/reference/pptoaslib.py:318-330.
    """
    with jax.named_scope("pp_scatter"):
        nharm = scat_port_FT.shape[-1]
        k = jnp.arange(nharm,
                       dtype=fft_real_dtype(jnp.asarray(taus).dtype))
        # -2*pi*i*k as a same-dtype complex array (no weak c128 scalars)
        mjk = jax.lax.complex(jnp.zeros_like(k), -2.0 * jnp.pi * k)
        dB_dtaus = mjk * scat_port_FT ** 2
        dtau, dalpha = taus_deriv
        return jnp.stack([dB_dtaus * dtau[..., None],
                          dB_dtaus * dalpha[..., None]])


def scattering_portrait_FT_2deriv(taus, taus_deriv, taus_2deriv,
                                  scat_port_FT):
    """d2 scat_FT / d(tau, alpha)2: shape [2, 2, ..., nchan, nharm].

    With u = -2*pi*i*k: dB/dtaus = u*B**2, d2B/dtaus2 = 2*u**2*B**3, so
    d2B/dp_i dp_j = 2*u**2*B**3 * dtaus_i*dtaus_j + u*B**2 * d2taus_ij.
    All terms finite at taus=0.  Math equivalent of
    /root/reference/pptoaslib.py:332-356.
    """
    with jax.named_scope("pp_scatter"):
        nharm = scat_port_FT.shape[-1]
        k = jnp.arange(nharm,
                       dtype=fft_real_dtype(jnp.asarray(taus).dtype))
        u = jax.lax.complex(jnp.zeros_like(k), -2.0 * jnp.pi * k)
        B = scat_port_FT
        dB = u * B ** 2
        d2B = 2.0 * (u ** 2) * B ** 3
        dti = taus_deriv[:, None, ..., None]   # [2, 1, ..., nchan, 1]
        dtj = taus_deriv[None, :, ..., None]   # [1, 2, ..., nchan, 1]
        d2t = taus_2deriv[..., None]           # [2, 2, ..., nchan, 1]
        return d2B * dti * dtj + dB * d2t


def abs_scattering_portrait_FT(scat_port_FT):
    """|B|**2 (reference pptoaslib.py:358-363)."""
    return jnp.abs(scat_port_FT) ** 2


def abs_scattering_portrait_FT_deriv(scat_port_FT, scat_port_FT_deriv):
    """d|B|**2/dp = 2*Re(B * conj(dB/dp)) (reference pptoaslib.py:365-372)."""
    return 2.0 * jnp.real(scat_port_FT * jnp.conj(scat_port_FT_deriv))


def abs_scattering_portrait_FT_2deriv(scat_port_FT, scat_port_FT_deriv,
                                      scat_port_FT_2deriv):
    """d2|B|**2/dp_i dp_j = 2*Re(dB_i conj(dB_j) + B conj(d2B_ij)).

    Reference pptoaslib.py:374-388 (which evaluates the same formula
    entrywise for the 2x2 case).
    """
    dBi = scat_port_FT_deriv[:, None]
    dBj = scat_port_FT_deriv[None, :]
    return 2.0 * jnp.real(dBi * jnp.conj(dBj)
                          + scat_port_FT * jnp.conj(scat_port_FT_2deriv))


def scattering_kernel(tau, nu_ref, freqs, nbin, P=1.0, alpha=-4.0):
    """Time-domain one-sided exponential kernels, one per channel.

    tau [sec] at nu_ref; returns [nchan, nbin] kernels normalized to unit
    sum.  Legacy-path equivalent of /root/reference/pplib.py:1098-1119.
    """
    freqs = jnp.asarray(freqs)
    ts = jnp.arange(nbin, dtype=jnp.float64) * (P / nbin)
    taus = scattering_times(tau, alpha, freqs, nu_ref)  # [nchan], in sec
    taus = jnp.where(taus == 0.0, jnp.finfo(ts.dtype).tiny, taus)
    kern = jnp.exp(-ts[None, :] / taus[:, None])
    return kern / kern.sum(axis=-1, keepdims=True)


def add_scattering(port, kernel, repeat=3):
    """Convolve a portrait with a unit-sum time-domain scattering kernel.

    Both port and kernel are tiled ``repeat`` times, the tiled kernel is
    normalized to unit sum per channel, they are circularly convolved,
    and the central copy is returned — area-preserving, like the
    reference (/root/reference/pplib.py:1121-1144).
    """
    port = jnp.asarray(port)
    squeeze = port.ndim == 1
    port2 = jnp.atleast_2d(port)
    kernel2 = jnp.broadcast_to(jnp.atleast_2d(jnp.asarray(kernel)),
                               port2.shape)
    nbin = port2.shape[-1]
    mid = repeat // 2
    tiled_d = jnp.tile(port2, (1, repeat))
    tiled_k = jnp.tile(kernel2, (1, repeat))
    tiled_k = tiled_k / tiled_k.sum(axis=-1, keepdims=True)
    conv = jnp.fft.irfft(jnp.fft.rfft(as_fft_operand(tiled_d), axis=-1)
                         * jnp.fft.rfft(as_fft_operand(tiled_k), axis=-1),
                         n=repeat * nbin, axis=-1)
    out = conv[..., mid * nbin:(mid + 1) * nbin]
    return out[0] if squeeze else out
