"""Core batched portrait operations (device layer)."""
