"""Gaussian profile/portrait generation with frequency evolution laws.

TPU-native equivalent of the reference's model generation layer
(/root/reference/pplib.py:752-1046 ``gaussian_profile``/
``gen_gaussian_profile``/``gen_gaussian_portrait``/evolution laws and
/root/reference/pptoaslib.py:14-50 ``gaussian_profile_FT``).

Design: the portrait generator is fully vectorized over (channel, component)
— no per-channel Python loop as in the reference (pplib.py:905-908) — so a
whole Gaussian portrait is one fused XLA computation, and vmap over
parameter sets batches model evaluation inside the Levenberg-Marquardt
model fitter.
"""

import math

import jax
import jax.numpy as jnp

from ..config import as_fft_operand, fft_real_dtype
from .fourier import get_bin_centers
from .scattering import (scattering_portrait_FT, scattering_profile_FT,
                         scattering_times)

__all__ = [
    "FWHM_FACT",
    "gaussian_function",
    "gaussian_profile",
    "gen_gaussian_profile",
    "gen_gaussian_portrait",
    "gaussian_profile_FT",
    "gaussian_portrait_FT",
    "power_law_evolution",
    "linear_evolution",
    "evolve_parameter",
]

# FWHM = 2*sqrt(2*ln 2) * sigma — a plain float, NOT a jnp constant:
# module-level jnp ops dispatch to the default backend at import time,
# which must never happen (import must be device-free).
FWHM_FACT = 2.0 * math.sqrt(2.0 * math.log(2.0))


def gaussian_function(xs, loc, wid, norm=False):
    """Gaussian with FWHM ``wid`` at ``loc`` evaluated at xs.

    Equivalent of /root/reference/pplib.py:752-768.
    """
    sigma = wid / FWHM_FACT
    zs = (xs - loc) / sigma
    ys = jnp.exp(-0.5 * zs ** 2)
    if norm:
        ys = ys * (sigma ** 2 * 2.0 * jnp.pi) ** -0.5
    return ys


def gaussian_profile(nbin, loc, wid, norm=False):
    """Circularly-wrapped Gaussian profile with peak amplitude 1 (or unit area).

    The reference (pplib.py:770-825) recenters bin values within +-0.5 of
    the mean and zeroes |z| > 20; here the wrap is the same recentering
    expressed branch-free, and the <=0 width guard returns zeros.  Peak
    normalization matches the reference's exact-peak rescaling: the profile
    is scaled so its maximum sampled value is exp(-0.5*z_peak^2) for the
    bin nearest loc.
    """
    locval = get_bin_centers(nbin).astype(
        jnp.result_type(jnp.asarray(loc).dtype, jnp.float32))
    mean = loc % 1.0
    # wrap bin coordinates to within half a rotation of the mean
    locval = jnp.where(locval - mean > 0.5, locval - 1.0, locval)
    locval = jnp.where(locval - mean < -0.5, locval + 1.0, locval)
    sigma = wid / FWHM_FACT
    safe_sigma = jnp.where(wid > 0.0, sigma, 1.0)
    zs = (locval - mean) / safe_sigma
    zs = jnp.where(jnp.abs(zs) < 20.0, zs, 20.0)
    dens = jnp.exp(-0.5 * zs ** 2) / (safe_sigma * jnp.sqrt(2.0 * jnp.pi))
    if norm:
        prof = dens
    else:
        imax = jnp.argmax(dens)
        z_peak = (locval[imax] - loc) / safe_sigma
        fact = jnp.exp(-0.5 * z_peak ** 2) / jnp.maximum(
            dens[imax], jnp.finfo(dens.dtype).tiny)
        prof = fact * dens
    return jnp.where(wid > 0.0, prof, jnp.zeros(nbin, dens.dtype))


def gen_gaussian_profile(params, nbin):
    """Multi-Gaussian profile: params = [dc, tau_bins, (loc, wid, amp)*n].

    tau (params[1]) is the scattering timescale in [bin]; nonzero tau
    convolves via the analytic scattering FT.  Equivalent of
    /root/reference/pplib.py:827-851.
    """
    params = jnp.asarray(params)
    dc, tau = params[0], params[1]
    comps = params[2:].reshape(-1, 3)
    profs = jnp.stack([gaussian_profile(nbin, loc, wid) * amp
                       for loc, wid, amp in comps])
    model = dc + profs.sum(axis=0)
    sp_FT = scattering_profile_FT(tau / nbin, nbin)
    scattered = jnp.fft.irfft(sp_FT * jnp.fft.rfft(as_fft_operand(model)),
                              n=nbin)
    return jnp.where(tau != 0.0, scattered, model)


def power_law_evolution(freqs, nu_ref, parameter, index):
    """parameter * (freqs/nu_ref)**index, broadcast [nchan, ngauss].

    Equivalent of /root/reference/pplib.py:996-1011.
    """
    freqs = jnp.asarray(freqs)
    logf = jnp.log(freqs) - jnp.log(nu_ref)
    return jnp.exp(jnp.outer(logf, index)
                   + jnp.log(parameter)[None, :])


def linear_evolution(freqs, nu_ref, parameter, slope):
    """parameter + slope*(freqs - nu_ref), broadcast [nchan, ngauss].

    Equivalent of /root/reference/pplib.py:1013-1028.
    """
    freqs = jnp.asarray(freqs)
    return jnp.outer(freqs - nu_ref, slope) + parameter[None, :]


_EVOLUTION_FUNCTIONS = {"0": power_law_evolution, "1": linear_evolution}


def evolve_parameter(freqs, nu_ref, parameter, evol_parameter, code):
    """Evolve a per-component parameter across frequency per code digit.

    '0' = power law, '1' = linear (reference pplib.py:1030-1046).  ``code``
    is a static python string (model codes are trace-time constants).
    """
    return _EVOLUTION_FUNCTIONS[code](freqs, nu_ref, jnp.asarray(parameter),
                                      jnp.asarray(evol_parameter))


def gen_gaussian_portrait(model_code, params, scattering_index, phases,
                          freqs, nu_ref, join_ichans=(), P=None):
    """Gaussian-component model portrait [nchan, nbin].

    params = [dc, tau_bins, (loc0, d_loc, wid0, d_wid, amp0, d_amp)*ngauss]
    (+ 2 join params per join group appended).  Each component's (loc, wid,
    amp) evolves over frequency per the corresponding model_code digit.
    Scattering (tau in [bin] at nu_ref, power law ``scattering_index``) is
    applied via the analytic FT.  Equivalent of
    /root/reference/pplib.py:853-994.

    join_ichans/P: optional per-receiver rotation of channel groups by
    (phase, DM) pairs taken from the tail of params (used by the joined
    multi-archive Gaussian fit, reference pplib.py:977-993).
    """
    from .fourier import rotate_data  # local import to avoid cycle at init

    params = jnp.asarray(params)
    njoin = len(join_ichans)
    if njoin:
        join_params = params[-njoin * 2:]
        params = params[:-njoin * 2]
    dc, tau = params[0], params[1]
    comps = params[2:].reshape(-1, 6)  # [ngauss, (loc,dloc,wid,dwid,amp,damp)]
    freqs = jnp.asarray(freqs)
    nbin = len(phases)

    locs = evolve_parameter(freqs, nu_ref, comps[:, 0], comps[:, 1],
                            model_code[0])          # [nchan, ngauss]
    wids = evolve_parameter(freqs, nu_ref, comps[:, 2], comps[:, 3],
                            model_code[1])
    amps = evolve_parameter(freqs, nu_ref, comps[:, 4], comps[:, 5],
                            model_code[2])

    # Vectorized wrapped-Gaussian evaluation over [nchan, ngauss, nbin];
    # bin centers follow the parameter dtype so an f32 call stays
    # complex128-free through the scattering FFT (TPU-safe)
    locval = get_bin_centers(nbin).astype(params.dtype)
    mean = locs % 1.0
    x = locval[None, None, :] - mean[..., None]
    x = jnp.where(x > 0.5, x - 1.0, x)
    x = jnp.where(x < -0.5, x + 1.0, x)
    sigma = wids / FWHM_FACT
    safe_sigma = jnp.where(wids > 0.0, sigma, 1.0)[..., None]
    zs = jnp.clip(x / safe_sigma, -20.0, 20.0)
    comps_prof = jnp.exp(-0.5 * zs ** 2)
    comps_prof = jnp.where((wids > 0.0)[..., None], comps_prof, 0.0)
    gport = dc + jnp.sum(amps[..., None] * comps_prof, axis=1)

    taus = scattering_times(tau / nbin, scattering_index, freqs,
                            nu_ref).astype(fft_real_dtype(params.dtype))
    sp_FT = scattering_portrait_FT(taus, nbin)
    scattered = jnp.fft.irfft(sp_FT * jnp.fft.rfft(as_fft_operand(gport),
                                                   axis=-1),
                              n=nbin, axis=-1)
    gport = jnp.where(tau != 0.0, scattered, gport)

    if njoin:
        for ij, ichans in enumerate(join_ichans):
            phi = join_params[2 * ij]
            DM = join_params[2 * ij + 1]
            gport = gport.at[ichans].set(
                rotate_data(gport[ichans], phi, DM, P, freqs[ichans], nu_ref))
    return gport


def gaussian_profile_FT(nbin, loc, wid, amp):
    """rFFT of an amp-scaled Gaussian profile of FWHM ``wid`` at ``loc``.

    The reference (/root/reference/pptoaslib.py:14-50) approximates this
    with an analytic Gaussian-sinc erf formula ("is still an
    approximation"); we return the exact DFT of the wrapped, bin-sampled
    Gaussian that the formula approximates — one batched rFFT, which on
    TPU is cheaper than evaluating complex erf and exact for the sampled
    profile.  Normalization matches the reference: ``amp`` scales the
    peak-amplitude-1 Gaussian (the reference's k=0 value is
    amp*sigma*sqrt(2*pi)*nbin, i.e. nbin times the integral of the
    peak-1 Gaussian).  The half-bin phase factor converts from
    bin-center sampling to the reference's t=0-anchored continuous-FT
    convention.
    """
    prof = as_fft_operand(amp * gaussian_profile(nbin, loc, wid, norm=False))
    k = jnp.arange(nbin // 2 + 1, dtype=prof.dtype)
    ang = jnp.pi * k / nbin
    return jnp.fft.rfft(prof) * jax.lax.complex(jnp.cos(ang),
                                                -jnp.sin(ang))


def gaussian_portrait_FT(model_code, params, scattering_index, nbin, freqs,
                         nu_ref):
    """rFFT of a Gaussian portrait: [nchan, nharm].

    Fourier-domain companion of gen_gaussian_portrait (no join support);
    keeps model evaluation in the harmonic domain inside fit loops.
    """
    phases = get_bin_centers(nbin)
    port = gen_gaussian_portrait(model_code, params, scattering_index,
                                 phases, freqs, nu_ref)
    return jnp.fft.rfft(as_fft_operand(port), axis=-1)
