"""B-spline evaluation (de Boor) in JAX + spline portrait generation.

The reference evaluates its PCA/B-spline portrait models with FITPACK's
``si.splev`` inside ``gen_spline_portrait`` (/root/reference/pplib.py:
932-956) — a host-side Fortran call in the middle of the TOA hot path.
Here spline *construction* stays on the host (scipy, model-build time,
see models/spline), but *evaluation* is a vmappable de Boor recursion so
model generation inside fit loops runs on device.
"""

import jax.numpy as jnp
import numpy as np

from ..config import as_fft_operand

__all__ = ["splev", "gen_spline_portrait", "fft_resample"]


def _deboor(x, t, c, k):
    """de Boor evaluation of a 1-D B-spline at points x.

    t: knots [n+k+1], c: coefficients [n], k: degree (static int).
    Outside [t[k], t[n]] the end polynomial is extrapolated (matching
    splev's ext=0 default).
    """
    t = jnp.asarray(t)
    c = jnp.asarray(c)
    # FITPACK zero-pads c to len(t); only len(t)-k-1 coefficients are real
    n = t.shape[0] - k - 1
    # interval index i: t[i] <= x < t[i+1], clamped to [k, n-1]
    i = jnp.clip(jnp.searchsorted(t, x, side="right") - 1, k, n - 1)

    # d[j] = c[i - k + j] for j = 0..k
    def gather(j):
        return c[i - k + j]

    d = [gather(j) for j in range(k + 1)]
    for r in range(1, k + 1):
        for j in range(k, r - 1, -1):
            denom = t[i + j - r + 1] - t[i - k + j]
            alpha = jnp.where(denom != 0.0, (x - t[i - k + j])
                              / jnp.where(denom != 0.0, denom, 1.0), 0.0)
            d[j] = (1.0 - alpha) * d[j - 1] + alpha * d[j]
    return d[k]


def splev(x, tck):
    """Evaluate a (possibly parametric) spline like scipy's si.splev.

    tck = (t, c, k) with c either a single coefficient array (scalar
    spline) or a list/2-D array of per-dimension coefficient arrays
    (parametric curve, as produced by si.splprep).  Returns an array
    shaped [ndim, len(x)] for parametric input, else [len(x)].
    """
    t, c, k = tck
    x = jnp.atleast_1d(jnp.asarray(x))
    if isinstance(c, (list, tuple)) or (hasattr(c, "ndim")
                                        and np.ndim(c) == 2):
        return jnp.stack([_deboor(x, t, jnp.asarray(ci), int(k))
                          for ci in c])
    return _deboor(x, t, jnp.asarray(c), int(k))


def fft_resample(port, nbin):
    """Fourier resampling along the last axis (scipy.signal.resample
    semantics for real input)."""
    port = jnp.asarray(port)
    n = port.shape[-1]
    X = jnp.fft.rfft(as_fft_operand(port), axis=-1)
    nh_out = nbin // 2 + 1
    if nbin < n:
        Xr = X[..., :nh_out]
        # halve the new Nyquist bin if it aliases (even nbin)
        if nbin % 2 == 0:
            Xr = Xr.at[..., -1].set(jnp.real(Xr[..., -1]))
    else:
        pad = [(0, 0)] * (port.ndim - 1) + [(0, nh_out - X.shape[-1])]
        Xr = jnp.pad(X, pad)
    return jnp.fft.irfft(Xr, n=nbin, axis=-1) * (nbin / n)


def gen_spline_portrait(mean_prof, freqs, eigvec, tck, nbin=None):
    """Portrait from mean profile + eigenprofiles + B-spline coefficients.

    proj = splev(freqs, tck) gives the eigenbasis coordinates vs
    frequency; port = proj . eigvec^T + mean_prof.  Optional nbin
    resampling applies the half-bin shift correction the reference notes
    for ss.resample (/root/reference/pplib.py:932-956).
    """
    from .fourier import rotate_data  # local import to avoid cycle

    mean_prof = jnp.asarray(mean_prof)
    freqs = jnp.atleast_1d(jnp.asarray(freqs))
    eigvec = jnp.asarray(eigvec)
    if eigvec.shape[1] == 0:
        port = jnp.tile(mean_prof, (freqs.shape[0], 1))
    else:
        proj_port = splev(freqs, tck).T          # [nchan, neig]
        port = proj_port @ eigvec.T + mean_prof
    if nbin is not None and nbin != mean_prof.shape[-1]:
        shift = 0.5 * (1.0 / nbin - 1.0 / mean_prof.shape[-1])
        port = fft_resample(port, nbin)
        port = rotate_data(port, shift)
    return port
