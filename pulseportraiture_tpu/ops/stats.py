"""Weighted statistics helpers.

TPU-native equivalent of /root/reference/pplib.py:686-750
(``count_crossings``, ``weighted_mean``, ``get_WRMS``, ``get_red_chi2``).
All functions are mask-based (errs <= 0 excludes a point) so they stay
dense and vmappable instead of using index compression.
"""

import jax.numpy as jnp

from .noise import get_noise

__all__ = ["count_crossings", "weighted_mean", "get_WRMS", "get_red_chi2"]


def count_crossings(x, x0):
    """Number of crossings of 1-D array x across threshold x0.

    Equivalent of /root/reference/pplib.py:686-694.
    """
    x = jnp.asarray(x)
    d = x - x0
    return (jnp.diff(jnp.sign(d)) != 0).sum() - (d == 0).sum()


def weighted_mean(data, errs=1.0):
    """Weighted mean and its standard error; weights are errs**-2.

    Points with errs <= 0 are excluded.  Equivalent of
    /root/reference/pplib.py:696-709.
    """
    data = jnp.asarray(data)
    errs = jnp.broadcast_to(jnp.asarray(errs, dtype=data.dtype), data.shape)
    ok = errs > 0.0
    w = jnp.where(ok, jnp.where(ok, errs, 1.0) ** -2.0, 0.0)
    wsum = w.sum()
    mean = (data * w).sum() / wsum
    return mean, wsum ** -0.5


def get_WRMS(data, errs=1.0):
    """Weighted root-mean-square (reference pplib.py:711-725)."""
    data = jnp.asarray(data)
    errs = jnp.broadcast_to(jnp.asarray(errs, dtype=data.dtype), data.shape)
    ok = errs > 0.0
    w = jnp.where(ok, jnp.where(ok, errs, 1.0) ** -2.0, 0.0)
    mean = (data * w).sum() / w.sum()
    return jnp.sqrt(((data - mean) ** 2 * w).sum() / w.sum())


def get_red_chi2(data, model, errs=None, dof=None):
    """Reduced chi-squared of data vs model.

    data/model: [..., nbin] (1- or 2-D); errs broadcast per channel; if
    None, estimated with get_noise.  dof defaults to sum(data.shape),
    matching the reference (pplib.py:727-750).
    """
    data = jnp.asarray(data)
    resids = data - model
    if errs is None:
        errs = get_noise(data)  # already an array of data's dtype
    else:
        errs = jnp.asarray(errs)
    if dof is None:
        dof = sum(data.shape)
    if data.ndim == 1:
        return jnp.sum((resids / errs) ** 2) / dof
    return jnp.sum((resids / errs[..., None]) ** 2) / dof
