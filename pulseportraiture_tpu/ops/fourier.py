"""Fourier-domain portrait primitives: rFFT conventions, phasors, rotation.

TPU-native equivalents of the reference's rotation/dedispersion machinery
(/root/reference/pplib.py:2338-2575 ``rotate_data``/``rotate_portrait``/
``add_DM_nu``/``rotate_profile``/``fft_rotate`` and
/root/reference/pptoaslib.py:181-238 ``phase_shifts``/``phasor``/
``rotate_portrait_full``).

Design notes (TPU-first, not a translation):

* All functions are pure, shape-polymorphic in leading batch dims, and
  jit/vmap-safe.  The reference's 1/2/4-D dispatch in ``rotate_data``
  becomes a single broadcasting rule: data ``[..., nchan, nbin]`` and
  per-channel phase shifts ``[..., nchan]``.
* The phasor argument ``phi_n * k`` is reduced mod 1 in float64 *before*
  the complex exponential.  With nharm ~ 2048 and DM phases of many
  thousands of rotations, the unreduced argument costs ~1e-10 rot of
  precision in f64 and is catastrophic in f32; after reduction the
  exponential is exact to ulp and can even run in f32 on the MXU-friendly
  path without losing phase accuracy.
* The sign/direction convention matches the reference: positive phi/DM
  rotate data to *earlier* phases for freqs < nu_ref ("dedisperses").
  In the Fourier domain that is multiplication by exp(+2j*pi*k*phi_n).
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..config import Dconst, F0_fact, as_fft_operand, fft_real_dtype

__all__ = [
    "nharm_for",
    "truncate_mantissa",
    "data_operand_hook",
    "rfft_portrait",
    "rfft_pair",
    "irfft_portrait",
    "phase_shifts",
    "phase_shifts_deriv",
    "phasor",
    "apply_phasor",
    "rotate_portrait_full",
    "rotate_data",
    "rotate_profile",
    "add_DM_nu",
    "fft_rotate",
    "get_bin_centers",
]


def nharm_for(nbin):
    """Number of rFFT harmonics for an nbin-bin profile (nbin//2 + 1)."""
    return nbin // 2 + 1


def truncate_mantissa(x, bits):
    """Round ``x`` to ``bits`` mantissa bits (jit/vmap-safe, exponent
    preserved): frexp -> round the mantissa on a 2**bits grid ->
    ldexp.  ``bits=23`` reproduces float32 rounding semantics on f64
    values; smaller values inject a controlled, deterministic
    quantization error of ~2**-(bits+1) relative."""
    m, e = jnp.frexp(x)
    scale = 2.0 ** int(bits)
    return jnp.ldexp(jnp.round(m * scale) / scale, e)


def data_operand_hook(x):
    """Test hook for the quality drift gate (tools/quality_smoke.py):
    when ``$PPTPU_FOURIER_TRUNC_BITS`` is set, truncate the *data-side*
    spectral operand to that many mantissa bits before the fit's
    spectra are formed — a stand-in for the precision loss a future
    reduced-precision data-side DFT kernel (ROADMAP: split-f32/Pallas)
    would introduce.  Identity (and entirely free) when unset.

    Read at trace time: a changed value needs a fresh process, not
    just a fresh call — in-process jit caches bake the old value in.
    """
    v = os.environ.get("PPTPU_FOURIER_TRUNC_BITS", "").strip()
    if not v:
        return x
    return truncate_mantissa(x, int(v))


def rfft_portrait(port, zap_f0=True):
    """rFFT along the phase axis with the reference's DC-harmonic policy.

    The k=0 harmonic is scaled by ``F0_fact`` (default 0: the baseline term
    is excluded from Fourier fits; reference pplib.py:64-66 and
    pptoaslib.py:976-979).
    """
    port_FT = jnp.fft.rfft(as_fft_operand(port), axis=-1)
    if zap_f0:
        port_FT = port_FT.at[..., 0].multiply(F0_fact)
    return port_FT


def irfft_portrait(port_FT, nbin=None):
    """Inverse rFFT along the phase axis."""
    if nbin is None:
        nbin = 2 * (port_FT.shape[-1] - 1)
    return jnp.fft.irfft(port_FT, n=nbin, axis=-1)


@functools.lru_cache(maxsize=8)
def _dft_tables(nbin):
    """(cos, sin) [nharm, nbin] f64 DFT tables; angles formed from
    (k*n) mod nbin so they are exact to f64 ulp at any size."""
    k = np.arange(nbin // 2 + 1)
    n = np.arange(nbin)
    ang = 2.0 * np.pi * ((k[:, None] * n[None, :]) % nbin) / nbin
    return np.cos(ang), np.sin(ang)


def rfft_pair(x, zap_f0=True, kmax=None):
    """Float64 rFFT as a (re, im) real pair via a DFT matmul.

    The TPU-safe full-precision spectral path: complex128 does not
    compile on TPU at all, but f64 matmuls do (XLA lowers them to
    f32-pair arithmetic on the MXU), so an explicit [nharm, nbin] DFT
    contraction delivers f64-accurate spectra where jnp.fft.rfft cannot.
    Used by the fit kernel's f64 pair path (fit/portrait.py) that backs
    the <1 ns TOA-parity requirement on device.

    x: [..., nbin] real; returns (re, im) [..., nharm] float64 with the
    rFFT sign convention (X_k = sum_n x_n e^{-2 pi i k n / N}) and the
    usual F0_fact DC policy.  ``kmax`` computes only the lowest kmax
    harmonics (the model-support truncation of fit.portrait.model_kmax),
    cutting the contraction cost proportionally.
    """
    x = jnp.asarray(x, jnp.float64)
    nbin = x.shape[-1]
    C, S = _dft_tables(nbin)
    if kmax is not None:
        C, S = C[:kmax], S[:kmax]
    re = jnp.einsum("...n,kn->...k", x, jnp.asarray(C))
    im = -jnp.einsum("...n,kn->...k", x, jnp.asarray(S))
    if zap_f0:
        re = re.at[..., 0].multiply(F0_fact)
        im = im.at[..., 0].multiply(F0_fact)
    return re, im


def phase_shifts(phi, DM, GM, freqs, nu_DM=jnp.inf, nu_GM=jnp.inf, P=None,
                 mod=False):
    """Per-frequency phase delays [rot] for (phi, DM, GM).

    delays = phi + Dconst*DM*(nu^-2 - nu_DM^-2)/P
                 + Dconst^2*GM*(nu^-4 - nu_GM^-4)/P

    phi [rot] (or [sec] if P is None), DM [cm**-3 pc],
    GM [cm**-6 pc**2 s**-1], freqs/nu_DM/nu_GM [MHz], P [sec].
    ``mod=True`` wraps results with |delay| >= 0.5 onto [-0.5, 0.5) —
    only meaningful (and only honored) when P is given, since
    seconds-valued delays have no 1-rotation period.

    Math equivalent of /root/reference/pptoaslib.py:181-214.
    """
    if P is None:
        P = 1.0
        mod = False
    freqs = jnp.asarray(freqs)
    dispersive = Dconst * DM * (freqs ** -2 - nu_DM ** -2) / P
    refractive = (Dconst ** 2) * GM * (freqs ** -4 - nu_GM ** -4) / P
    delays = phi + dispersive + refractive
    if mod:
        delays = jnp.where(jnp.abs(delays) >= 0.5, delays % 1, delays)
        delays = jnp.where(delays >= 0.5, delays - 1.0, delays)
    return delays


def phase_shifts_deriv(freqs, nu_DM=jnp.inf, nu_GM=jnp.inf, P=1.0):
    """Gradient of phase_shifts wrt (phi, DM, GM): shape [3, nchan].

    Math equivalent of /root/reference/pptoaslib.py:216-225; the Hessian is
    identically zero (pptoaslib.py:227-231).
    """
    freqs = jnp.asarray(freqs)
    dphi = jnp.ones_like(freqs)
    dDM = Dconst * (freqs ** -2 - nu_DM ** -2) / P
    dGM = (Dconst ** 2) * (freqs ** -4 - nu_GM ** -4) / P
    return jnp.stack([dphi, dDM, dGM])


def phasor(shifts, nharm, sign=+1.0, dtype=None):
    """exp(sign * 2j*pi * shifts[..., None] * k) for k = 0..nharm-1.

    The product ``shifts * k`` is reduced mod 1 in float64 before
    exponentiation (see module docstring), then the trig runs in the
    real dtype matching ``dtype`` (complex64/complex128; default from
    shifts).  TPUs have no complex128 — f64 reduction + f32 trig + c64
    arithmetic preserves ~1e-8 rot phase accuracy on device.
    """
    shifts = jnp.asarray(shifts, dtype=jnp.float64)
    k = jnp.arange(nharm, dtype=shifts.dtype)
    frac = (shifts[..., None] * k) % 1.0
    if dtype is not None:
        real_dtype = jnp.finfo(dtype).dtype
    else:
        real_dtype = jnp.float64
    # clamp so the complex result compiles on the backend (c64 on TPU)
    frac = frac.astype(fft_real_dtype(real_dtype))
    ang = (2.0 * jnp.pi * sign) * frac
    return jax.lax.complex(jnp.cos(ang), jnp.sin(ang))


def apply_phasor(port_FT, shifts):
    """Multiply an rFFT'd portrait by the rotation phasor for ``shifts``.

    port_FT: [..., nchan, nharm]; shifts: [..., nchan] in rotations.
    Positive shifts rotate to earlier phase (dedisperse), matching the
    reference convention (pptoaslib.py:52-81).
    """
    return port_FT * phasor(shifts, port_FT.shape[-1],
                            dtype=port_FT.dtype)


def rotate_portrait_full(port, phi, DM, GM, freqs, nu_DM=jnp.inf,
                         nu_GM=jnp.inf, P=None):
    """Rotate/dedisperse a portrait by phi + DM*nu^-2 + GM*nu^-4 phasors.

    port: [..., nchan, nbin]; freqs: [..., nchan].  Behavioral equivalent
    of /root/reference/pptoaslib.py:52-81.
    """
    if P is None:
        P = 1.0
    port = jnp.asarray(port)
    port_FT = jnp.fft.rfft(as_fft_operand(port), axis=-1)
    shifts = phase_shifts(phi, DM, GM, freqs, nu_DM, nu_GM, P, mod=False)
    return jnp.fft.irfft(apply_phasor(port_FT, shifts), n=port.shape[-1],
                         axis=-1)


def rotate_data(data, phase=0.0, DM=0.0, Ps=None, freqs=None,
                nu_ref=jnp.inf):
    """Rotate and/or dedisperse data of shape [..., nchan, nbin] or [nbin].

    Generalizes the reference's 1/2/4-D dispatch (pplib.py:2338-2426) by
    broadcasting: ``Ps`` may be scalar or [...], ``freqs`` [nchan] or
    [..., nchan].  Positive phase/DM rotate to earlier phases.
    """
    data = jnp.asarray(data)
    if data.ndim == 1:
        if freqs is None:
            return rotate_profile(data, phase)
        # single profile at a scalar frequency: dispersive term applies
        P = 1.0 if Ps is None else Ps
        shift = phase + (Dconst * DM / P) * (jnp.asarray(freqs) ** -2
                                             - nu_ref ** -2)
        return rotate_profile(data, shift)
    if freqs is None:
        shifts = jnp.broadcast_to(jnp.asarray(phase), data.shape[:-1])
    else:
        freqs = jnp.asarray(freqs)
        P = 1.0 if Ps is None else jnp.asarray(Ps)
        if data.ndim > 2 and jnp.ndim(P) > 0:
            P = P.reshape(P.shape + (1,) * (data.ndim - 1 - P.ndim))
        D = Dconst * DM / P
        shifts = phase + D * (freqs ** -2 - nu_ref ** -2)
        shifts = jnp.broadcast_to(shifts, data.shape[:-1])
    data_FT = jnp.fft.rfft(as_fft_operand(data), axis=-1)
    return jnp.fft.irfft(apply_phasor(data_FT, shifts), n=data.shape[-1],
                         axis=-1)


def rotate_profile(profile, phase=0.0):
    """Rotate a 1-D profile by phase [rot]; positive = earlier phase.

    Equivalent of /root/reference/pplib.py:2548-2559.
    """
    profile = jnp.asarray(profile)
    prof_FT = jnp.fft.rfft(as_fft_operand(profile))
    prof_FT = prof_FT * phasor(jnp.asarray(phase), prof_FT.shape[-1],
                               dtype=prof_FT.dtype)[..., :]
    return jnp.fft.irfft(prof_FT, n=profile.shape[-1])


def fft_rotate(arr, bins):
    """Rotate an array *left* by (possibly fractional) ``bins`` places.

    PRESTO-style rotation retained as an independent cross-check of
    rotate_profile (cf. /root/reference/pplib.py:2561-2575, kept there
    "for testing"); ``fft_rotate(arr, b) == rotate_profile(arr, b/len(arr))``.
    """
    arr = jnp.asarray(arr)
    nbin = arr.shape[-1]
    return rotate_profile(arr, jnp.asarray(bins, dtype=jnp.result_type(
        arr.dtype, jnp.float64)) / nbin)


def add_DM_nu(port, phase=0.0, DM=None, P=None, freqs=None, xs=(-2.0,),
              Cs=(1.0,), nu_ref=jnp.inf):
    """Rotate a portrait with an arbitrary power-law dispersion law.

    delays = phase + (Dconst*DM/P) * sum_i C_i*(nu^x_i - nu_ref^x_i);
    with xs=(-2,), Cs=(1,) this is identical to plain dedispersion.
    Equivalent of /root/reference/pplib.py:2509-2546.
    """
    port = jnp.asarray(port)
    if DM is None or freqs is None:
        shifts = jnp.broadcast_to(jnp.asarray(phase), port.shape[:-1])
    else:
        freqs = jnp.asarray(freqs)
        exps = jnp.atleast_1d(jnp.asarray(xs, dtype=jnp.float64))
        coefs = jnp.atleast_1d(jnp.asarray(Cs, dtype=jnp.float64))
        coefs = jnp.concatenate(
            [coefs, jnp.ones(exps.shape[0] - coefs.shape[0], coefs.dtype)])
        freq_term = jnp.sum(
            coefs[:, None] * (freqs[None, :] ** exps[:, None]
                              - nu_ref ** exps[:, None]), axis=0)
        shifts = phase + (Dconst * DM / P) * freq_term
    port_FT = jnp.fft.rfft(as_fft_operand(port), axis=-1)
    return jnp.fft.irfft(apply_phasor(port_FT, shifts), n=port.shape[-1],
                         axis=-1)


def get_bin_centers(nbin, lo=0.0, hi=1.0):
    """nbin bin centers with bin edges spanning [lo, hi].

    Equivalent of /root/reference/pplib.py:671-684.
    """
    diff = hi - lo
    return jnp.linspace(lo + diff / (2 * nbin), hi - diff / (2 * nbin),
                        nbin, dtype=jnp.float64)
