"""Weighted PCA and eigenprofile significance selection.

TPU-native equivalent of /root/reference/pplib.py:1497-1619 (``pca``,
``reconstruct_portrait``, ``find_significant_eigvec``).  The weighted
covariance + symmetric eigensolve run on device (jnp.linalg.eigh maps to
XLA's batched eigensolver); the significance scan reuses the batched
wavelet ``smart_smooth`` so all candidate eigenvectors smooth in one
device call instead of a per-vector host loop.
"""

import jax.numpy as jnp
import numpy as np

from .noise import get_noise
from .stats import count_crossings
from .wavelet import smart_smooth

__all__ = ["pca", "reconstruct_portrait", "find_significant_eigvec"]


def pca(port, mean_prof=None, weights=None):
    """Principal components of port [nchan, nbin] (channels = samples).

    Returns (eigval [nbin], eigvec [nbin, nbin]) sorted by decreasing
    eigenvalue; eigenvectors are column vectors.  The covariance is the
    unbiased weighted covariance (np.cov aweights semantics).
    Equivalent of /root/reference/pplib.py:1497-1535.
    """
    port = jnp.asarray(port)
    nmes = port.shape[0]
    if weights is None:
        weights = jnp.ones(nmes, dtype=port.dtype)
    else:
        weights = jnp.asarray(weights, dtype=port.dtype)
    if mean_prof is None:
        mean_prof = (port * weights[:, None]).sum(axis=0) / weights.sum()
    delta = port - mean_prof
    # np.cov(delta.T, aweights=w, ddof=1): weighted mean removed, then
    # normalization sum(w) - sum(w^2)/sum(w)
    w = weights
    wsum = w.sum()
    dmean = (delta * w[:, None]).sum(axis=0) / wsum
    d = delta - dmean
    cov = jnp.einsum("i,ij,ik->jk", w, d, d) / (wsum - (w ** 2).sum() / wsum)
    eigval, eigvec = jnp.linalg.eigh(cov)
    return eigval[::-1], eigvec[:, ::-1]


def reconstruct_portrait(port, mean_prof, eigvec):
    """Project port onto the eigvec basis and reconstruct.

    Equivalent of /root/reference/pplib.py:1536-1553.
    """
    port = jnp.asarray(port)
    mean_prof = jnp.asarray(mean_prof)
    eigvec = jnp.asarray(eigvec)
    delta = port - mean_prof
    return (delta @ eigvec) @ eigvec.T + mean_prof


def find_significant_eigvec(eigvec, check_max=10, return_max=10,
                            snr_cutoff=150.0, check_crossings=True,
                            check_acorr=True, return_smooth=True,
                            **kwargs):
    """Indices of "significant" eigenvectors by smoothed Fourier S/N.

    eigvec: [nbin, ncomp] column eigenvectors.  An eigenvector is
    significant when its smoothed version's Fourier-power S/N passes
    ``snr_cutoff``; borderline cases (< 3x cutoff) additionally pass a
    crossings-count sanity check (and optionally an autocorrelation
    width check) to weed out RFI-like vectors.  Behavioral equivalent of
    /root/reference/pplib.py:1555-1619; the candidate smoothing runs
    batched (one call for all check_max vectors).
    """
    eigvec = np.asarray(eigvec)
    nbin = eigvec.shape[0]
    ncheck = min(max(check_max, return_max), eigvec.shape[1])
    cand = eigvec[:, :ncheck].T                       # [ncheck, nbin]
    smooth_cand = np.asarray(smart_smooth(cand, **kwargs))
    noise = np.asarray(get_noise(cand)) * np.sqrt(nbin / 2.0)
    sig = np.sum(np.abs(np.fft.rfft(smooth_cand, axis=-1)[:, 1:]) ** 2,
                 axis=-1)
    snrs = np.divide(sig, noise, out=np.zeros_like(sig),
                     where=noise > 0.0)

    smooth_eigvec = np.zeros(eigvec.shape)
    ieig = []
    for ivec in range(ncheck):
        ev = smooth_cand[ivec]
        ev_snr = snrs[ivec]
        add = False
        if ev_snr >= snr_cutoff:
            if check_crossings and ev_snr < 3 * snr_cutoff:
                # borderline: many crossings -> rejected.  NB: the
                # reference's autocorrelation rescue (check_acorr,
                # pplib.py:1655-1663) is dead code there — its elif
                # requires add_eigvec already True — so for parity a
                # crossings failure is final and check_acorr is accepted
                # but unused.
                ncross = int(np.asarray(count_crossings(
                    np.abs(ev), 0.1 * np.abs(ev).max())))
                add = ncross < int(0.02 * nbin)
            else:
                add = True
        if add:
            ieig.append(ivec)
            smooth_eigvec[:, ivec] = ev
        if ivec + 1 == check_max or len(ieig) == return_max:
            break
    ieig = np.array(ieig, dtype=int)
    if return_smooth:
        return ieig, smooth_eigvec
    return ieig
