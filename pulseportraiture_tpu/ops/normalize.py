"""Per-channel portrait normalization.

TPU-native equivalent of /root/reference/pplib.py:2462-2507
(``normalize_portrait``): methods 'mean', 'max', 'prof', 'rms', 'abs'.
Zero (all-zero) channels pass through unscaled with norm 1, matching the
reference's ``port[ichan].any()`` guard, expressed as a mask so the whole
portrait normalizes in one fused computation.
"""

import jax.numpy as jnp

__all__ = ["normalize_portrait", "unnormalize_portrait"]


def normalize_portrait(port, method="rms", weights=None, return_norms=False,
                       noise_method="PS"):
    """Normalize each channel profile of port [..., nchan, nbin].

    'mean': by profile mean; 'max': by maximum; 'prof': by the fitted
    scale against the (weighted) mean profile; 'rms': by the noise level
    (get_noise(profile) == 1 after); 'abs': by the vector 2-norm.
    """
    from ..fit.phase_shift import fit_phase_shift  # avoid import cycle
    from .noise import get_noise

    port = jnp.asarray(port)
    if method == "mean":
        norms = port.mean(axis=-1)
    elif method == "max":
        norms = port.max(axis=-1)
    elif method == "rms":
        norms = get_noise(port, method=noise_method)
    elif method == "abs":
        norms = jnp.sqrt((port ** 2).sum(axis=-1))
    elif method == "prof":
        nonzero = jnp.any(port != 0.0, axis=-1)                  # [..., nchan]
        if weights is None:
            w = nonzero.astype(port.dtype)
        else:
            w = jnp.asarray(weights) * nonzero
        wsum = w.sum(axis=-1)
        mean_prof = ((port * w[..., None]).sum(axis=-2)
                     / jnp.where(wsum > 0.0, wsum, 1.0)[..., None])
        norms = fit_phase_shift(port, mean_prof[..., None, :]).scale
    else:
        raise ValueError(f"Unknown normalize_portrait method '{method}'.")
    ok = jnp.any(port != 0.0, axis=-1) & (norms != 0.0)
    safe = jnp.where(ok, norms, 1.0)
    norm_port = port / safe[..., None]
    norm_vals = jnp.where(ok, norms, 1.0)
    if return_norms:
        return norm_port, norm_vals
    return norm_port


def unnormalize_portrait(norm_port, norm_vals):
    """Invert normalize_portrait given the returned norms.

    Equivalent of DataPortrait.unnormalize_portrait
    (/root/reference/pplib.py:384-398).
    """
    # norm_port is normalize_portrait's own (already-converted) output in
    # every caller; one conversion of the norms suffices — the multiply
    # promotes array-likes itself
    return norm_port * jnp.asarray(norm_vals)[..., None]
