"""Minimal pulsar-ephemeris (.par) reader.

Replacement for the optional external ``parfile`` module the reference
uses (/root/reference/pplib.py:3271-3302 falls back to manual parsing of
PSR/PSRJ, RAJ, DECJ, F0/P0, PEPOCH, DM).  All fields are kept; values
are typed as float where they parse, with fit-flag and uncertainty
columns preserved.
"""

import numpy as np

from ..utils.databunch import DataBunch

__all__ = ["read_par", "write_par"]

_STRING_FIELDS = {"PSR", "PSRJ", "PSRB", "RAJ", "DECJ", "RA", "DEC",
                  "EPHEM", "CLK", "CLOCK", "UNITS", "TZRSITE", "BINARY",
                  "TIMEEPH", "T2CMETHOD", "CORRECT_TROPOSPHERE", "PLANET_SHAPIRO",
                  "DILATEFREQ", "INFO", "NITS", "IBOOT", "DMDATA"}

# repeatable flag-selector lines: "<KEY> -<flag> <flagval> <value> ..."
# (tempo2/PINT noise+offset extensions).  Stored as lists, not fields:
#   JUMP     -> par.jumps    [{flag, flagval, offset_s, fit}] for the
#       flag form; tempo's non-flag forms parse too, as
#       {flag: "MJD"|"FREQ", lo, hi, offset_s, fit} and
#       {flag: "TEL", flagval: site, offset_s, fit}
#   DMJUMP   -> par.dmjumps  [{flag, flagval, offset_dm, fit}]  (PINT's
#       wideband per-receiver DM-measurement offset, pc cm^-3)
#   T2EFAC / EFAC   -> par.efacs    [{flag, flagval, value}]
#   T2EQUAD / EQUAD -> par.equads   [{flag, flagval, value}]  (us)
#   DMEFAC   -> par.dmefacs  |  DMEQUAD -> par.dmequads  (pc cm^-3)
_SELECTOR_KEYS = {"JUMP": "jumps", "DMJUMP": "dmjumps",
                  "T2EFAC": "efacs", "EFAC": "efacs",
                  "T2EQUAD": "equads", "EQUAD": "equads",
                  "DMEFAC": "dmefacs", "DMEQUAD": "dmequads"}
_OFFSET_FIELD = {"JUMP": "offset_s", "DMJUMP": "offset_dm"}


def _float_ftn(tok):
    return float(tok.replace("D", "E").replace("d", "e"))


def _fit_flag(toks, i):
    return int(toks[i]) if len(toks) > i \
        and toks[i].lstrip("+-").isdigit() else 0


def _parse_value(key, value):
    if key in _STRING_FIELDS:
        return value
    try:
        return float(value.replace("D", "E").replace("d", "e"))
    except ValueError:
        return value


def read_par(parfile):
    """Parse a .par file into a DataBunch.

    Returns fields by name (e.g. par.PSR, par.DM, par.F0), plus derived
    ``P0`` (from F0 if absent), ``fit_flags`` and ``uncertainties``
    dicts for lines carrying extra columns.
    """
    fields = {}
    fit_flags = {}
    uncertainties = {}
    selectors = {name: [] for name in set(_SELECTOR_KEYS.values())}
    with open(parfile) as f:
        for line in f:
            toks = line.split()
            if not toks or toks[0].startswith("#"):
                continue
            key = toks[0]
            if len(toks) < 2:
                continue
            if key in _SELECTOR_KEYS and len(toks) >= 4 \
                    and toks[1].startswith("-"):
                entry = DataBunch(flag=toks[1][1:], flagval=toks[2],
                                  value=_float_ftn(toks[3]))
                if key in _OFFSET_FIELD:
                    entry[_OFFSET_FIELD[key]] = entry.pop("value")
                    entry["fit"] = _fit_flag(toks, 4)
                selectors[_SELECTOR_KEYS[key]].append(entry)
                continue
            if key == "JUMP" and toks[1].upper() in ("MJD", "FREQ") \
                    and len(toks) >= 5:
                # tempo's range forms: JUMP MJD t1 t2 off [fit]
                selectors["jumps"].append(DataBunch(
                    flag=toks[1].upper(), lo=_float_ftn(toks[2]),
                    hi=_float_ftn(toks[3]),
                    offset_s=_float_ftn(toks[4]),
                    fit=_fit_flag(toks, 5)))
                continue
            if key == "JUMP" and toks[1].upper() == "TEL" \
                    and len(toks) >= 4:
                selectors["jumps"].append(DataBunch(
                    flag="TEL", flagval=toks[2],
                    offset_s=_float_ftn(toks[3]),
                    fit=_fit_flag(toks, 4)))
                continue
            fields[key] = _parse_value(key, toks[1])
            if len(toks) >= 3:
                try:
                    fit_flags[key] = int(toks[2])
                except ValueError:
                    pass
            if len(toks) >= 4:
                try:
                    uncertainties[key] = float(toks[3])
                except ValueError:
                    pass
    if "P0" not in fields and "F0" in fields:
        fields["P0"] = 1.0 / np.float64(fields["F0"])
    if "F0" not in fields and "P0" in fields:
        fields["F0"] = 1.0 / np.float64(fields["P0"])
    if "PSR" not in fields and "PSRJ" in fields:
        fields["PSR"] = fields["PSRJ"]
    return DataBunch(fit_flags=fit_flags, uncertainties=uncertainties,
                     **selectors, **fields)


_SELECTOR_WRITE_KEYS = {"jumps": "JUMP", "dmjumps": "DMJUMP",
                        "efacs": "T2EFAC", "equads": "T2EQUAD",
                        "dmefacs": "DMEFAC", "dmequads": "DMEQUAD"}


def write_par(parfile, fields, fit_flags=None, quiet=True):
    """Write a simple .par file from a mapping of field -> value."""
    fit_flags = fit_flags or {}
    with open(parfile, "w") as f:
        for key, value in fields.items():
            if key in ("fit_flags", "uncertainties"):
                continue
            if key in _SELECTOR_WRITE_KEYS:
                for s in value:
                    if key == "jumps" and "lo" in s:
                        line = "%-12s %s %.15g %.15g %.15g %d" % (
                            "JUMP", s["flag"], s["lo"], s["hi"],
                            s["offset_s"], s.get("fit", 0))
                    elif key == "jumps" and s["flag"] == "TEL":
                        line = "%-12s TEL %s %.15g %d" % (
                            "JUMP", s["flagval"], s["offset_s"],
                            s.get("fit", 0))
                    else:
                        val = s.get("offset_s",
                                    s.get("offset_dm", s.get("value")))
                        line = "%-12s -%s %s %.15g" % (
                            _SELECTOR_WRITE_KEYS[key], s["flag"],
                            s["flagval"], val)
                        if key in ("jumps", "dmjumps"):
                            line += " %d" % s.get("fit", 0)
                    f.write(line + "\n")
                continue
            if isinstance(value, float):
                line = "%-12s %.15g" % (key, value)
            else:
                line = "%-12s %s" % (key, value)
            if key in fit_flags:
                line += " %d" % fit_flags[key]
            f.write(line + "\n")
    if not quiet:
        print("%s written." % parfile)
