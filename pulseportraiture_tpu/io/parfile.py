"""Minimal pulsar-ephemeris (.par) reader.

Replacement for the optional external ``parfile`` module the reference
uses (/root/reference/pplib.py:3271-3302 falls back to manual parsing of
PSR/PSRJ, RAJ, DECJ, F0/P0, PEPOCH, DM).  All fields are kept; values
are typed as float where they parse, with fit-flag and uncertainty
columns preserved.
"""

import numpy as np

from ..utils.databunch import DataBunch

__all__ = ["read_par", "write_par"]

_STRING_FIELDS = {"PSR", "PSRJ", "PSRB", "RAJ", "DECJ", "RA", "DEC",
                  "EPHEM", "CLK", "CLOCK", "UNITS", "TZRSITE", "BINARY",
                  "TIMEEPH", "T2CMETHOD", "CORRECT_TROPOSPHERE", "PLANET_SHAPIRO",
                  "DILATEFREQ", "INFO", "NITS", "IBOOT", "DMDATA"}


def _parse_value(key, value):
    if key in _STRING_FIELDS:
        return value
    try:
        return float(value.replace("D", "E").replace("d", "e"))
    except ValueError:
        return value


def read_par(parfile):
    """Parse a .par file into a DataBunch.

    Returns fields by name (e.g. par.PSR, par.DM, par.F0), plus derived
    ``P0`` (from F0 if absent), ``fit_flags`` and ``uncertainties``
    dicts for lines carrying extra columns.
    """
    fields = {}
    fit_flags = {}
    uncertainties = {}
    with open(parfile) as f:
        for line in f:
            toks = line.split()
            if not toks or toks[0].startswith("#"):
                continue
            key = toks[0]
            if len(toks) < 2:
                continue
            fields[key] = _parse_value(key, toks[1])
            if len(toks) >= 3:
                try:
                    fit_flags[key] = int(toks[2])
                except ValueError:
                    pass
            if len(toks) >= 4:
                try:
                    uncertainties[key] = float(toks[3])
                except ValueError:
                    pass
    if "P0" not in fields and "F0" in fields:
        fields["P0"] = 1.0 / np.float64(fields["F0"])
    if "F0" not in fields and "P0" in fields:
        fields["F0"] = 1.0 / np.float64(fields["P0"])
    if "PSR" not in fields and "PSRJ" in fields:
        fields["PSR"] = fields["PSRJ"]
    return DataBunch(fit_flags=fit_flags, uncertainties=uncertainties,
                     **fields)


def write_par(parfile, fields, fit_flags=None, quiet=True):
    """Write a simple .par file from a mapping of field -> value."""
    fit_flags = fit_flags or {}
    with open(parfile, "w") as f:
        for key, value in fields.items():
            if key in ("fit_flags", "uncertainties"):
                continue
            if isinstance(value, float):
                line = "%-12s %.15g" % (key, value)
            else:
                line = "%-12s %s" % (key, value)
            if key in fit_flags:
                line += " %d" % fit_flags[key]
            f.write(line + "\n")
    if not quiet:
        print("%s written." % parfile)
