"""Archive loading with the reference's load_data schema + fixtures.

TPU-native equivalent of /root/reference/pplib.py:2650-2820 (load_data),
:3039-3075 (unload_new_archive) and :3189-3384 (make_fake_pulsar), with
the PSRCHIVE dependency replaced by io.psrfits.  The returned DataBunch
carries the same field names the reference's pipelines consume
(pplib.py:2809-2820), with ``arch`` holding the in-memory Archive.
"""

import os
import time

import numpy as np

from ..config import host_stats_device
from ..obs import metrics
from ..obs.metrics import PHASE_HISTOGRAM
from ..ops.fourier import get_bin_centers
from ..testing import faults
from ..ops.noise import get_SNR, get_noise
from ..utils.databunch import DataBunch
from ..utils.mjd import MJD
from ..utils.telescopes import telescope_code_dict
from .gmodel import read_model
from .polyco import polyco_from_spin
from .psrfits import Archive, read_archive

__all__ = ["load_data", "unload_new_archive", "make_fake_pulsar",
           "file_is_type"]


def file_is_type(filename):
    """'FITS' | 'ASCII' | 'data' dispatch without shelling out to `file`.

    Replaces the reference's ``os.popen4('file -L ...')`` sniffing
    (/root/reference/pplib.py:3021-3037): FITS files start with
    'SIMPLE  ='; metafiles are small text lists.
    """
    with open(filename, "rb") as f:
        head = f.read(160)
    if head.startswith(b"SIMPLE"):
        return "FITS"
    try:
        head.decode("ascii")
        return "ASCII"
    except UnicodeDecodeError:
        return "data"


def load_data(filename, state=None, dedisperse=False, dededisperse=False,
              tscrunch=False, pscrunch=False, fscrunch=False,
              rm_baseline=True, flux_prof=False, refresh_arch=True,
              return_arch=True, quiet=True, get_SNRs=True,
              noise_method="PS"):
    """Load a PSRFITS archive into the canonical DataBunch schema.

    Field-for-field equivalent of the reference's load_data
    (/root/reference/pplib.py:2650-2820): subints
    [nsub, npol, nchan, nbin], freqs [nsub, nchan], weights, masks,
    noise_stds [nsub, npol, nchan], SNRs, ok_isubs, ok_ichans, Ps,
    epochs, phases, prof, flux_prof, plus observation metadata.
    """
    # chaos site: an injected read fault surfaces exactly like a
    # truncated payload or NFS blip (testing/faults.py)
    faults.check("archive_read", key=getattr(filename, "filename",
                                             None) or str(filename))
    t_decode0 = time.perf_counter()
    arch = filename if isinstance(filename, Archive) \
        else read_archive(filename)
    if refresh_arch:
        arch = arch.copy()  # manipulations below stay local
    source = arch.source
    telescope = arch.telescope
    try:
        telescope_code = telescope_code_dict[telescope.upper()][0]
    except KeyError:
        telescope_code = telescope

    if state is not None and state != arch.state:
        arch.convert_state(state)
    if dedisperse:
        arch.dedisperse()
    if dededisperse:
        arch.dededisperse()
    DM = arch.DM
    dmc = arch.dedispersed
    if rm_baseline:
        arch.remove_baseline()
    if tscrunch:
        arch.tscrunch()
    nsub = arch.nsub
    integration_length = float(arch.durations.sum())
    doppler_factors = arch.doppler_factors.copy()
    parallactic_angles = arch.parallactic_angles.copy()
    if pscrunch:
        arch.pscrunch()
    state = arch.state
    npol = arch.npol
    if fscrunch:
        arch.fscrunch()
    nu0 = arch.nu0
    bw = arch.bw
    nchan = arch.nchan
    freqs = arch.freqs.copy()
    nbin = arch.nbin
    with host_stats_device():
        phases = np.asarray(get_bin_centers(nbin))
    subints = arch.data.copy()
    Ps = arch.Ps.copy()
    if len(Ps) < nsub:  # tscrunch keeps one
        Ps = np.resize(Ps, nsub)
    epochs = list(arch.epochs)
    subtimes = list(arch.durations)
    weights = arch.weights.copy()
    weights_norm = np.where(weights == 0.0, 0.0, 1.0)

    # per-archive noise/SNR estimates run on the local CPU backend: each
    # is a tiny computation whose remote-device round trip would
    # dominate archive loading (cf. the reference's own load-time SNR
    # complaint, pplib.py:2763-2772)
    with host_stats_device():
        noise_stds = np.asarray(get_noise(subints, method=noise_method))
    ok_isubs = np.compress(weights_norm.mean(axis=1),
                           range(arch.nsub))
    ok_ichans = [np.compress(weights_norm[isub], range(nchan))
                 for isub in range(arch.nsub)]
    masks = np.einsum("ij,k->ijk", weights_norm, np.ones(nbin))
    masks = np.einsum("j,ikl->ijkl", np.ones(npol), masks)
    if get_SNRs:
        with host_stats_device():
            SNRs = np.asarray(get_SNR(subints))
    else:
        SNRs = np.zeros([arch.nsub, npol, nchan])

    work = arch.copy()
    work.pscrunch()
    if flux_prof:
        fa = work.copy()
        fa.dedisperse()
        fa.tscrunch()
        flux_profile = fa.data.mean(axis=3)[0][0]
    else:
        flux_profile = np.array([])
    work.dedisperse()
    work.tscrunch()
    work.fscrunch()
    prof = work.data[0, 0, 0]
    with host_stats_device():
        prof_noise = float(np.asarray(get_noise(prof)))
        prof_SNR = float(np.asarray(get_SNR(prof)))

    # the host-pipeline accounting unit: where this time lands — on the
    # fit timeline (serial) or on a prefetch thread (--prefetch) — is
    # the whole point of docs/RUNNER.md "Host pipeline"
    metrics.observe(PHASE_HISTOGRAM, time.perf_counter() - t_decode0,
                    phase="decode")
    return DataBunch(
        arch=arch if return_arch else None, backend=arch.backend,
        backend_delay=arch.backend_delay, bw=bw,
        doppler_factors=doppler_factors,
        doppler_degraded=getattr(arch, "doppler_degraded", False),
        DM=DM, dmc=dmc, epochs=epochs,
        filename=getattr(arch, "filename", str(filename)),
        flux_prof=flux_profile, freqs=freqs, frontend=arch.frontend,
        integration_length=integration_length, masks=masks, nbin=nbin,
        nchan=nchan, noise_stds=noise_stds, npol=npol, nsub=arch.nsub,
        nu0=nu0, ok_ichans=ok_ichans, ok_isubs=ok_isubs,
        parallactic_angles=parallactic_angles, phases=phases, prof=prof,
        prof_noise=prof_noise, prof_SNR=prof_SNR, Ps=Ps, SNRs=SNRs,
        source=source, state=state, subints=subints, subtimes=subtimes,
        telescope=telescope, telescope_code=telescope_code,
        weights=weights)


def unload_new_archive(data, arch, outfile, DM=None, dmc=0, weights=None,
                       quiet=True):
    """Write ``data`` into a copy of an existing Archive and unload it.

    Equivalent of /root/reference/pplib.py:3039-3075.
    ``dmc=0`` stores the archive dedispersed=False (dispersed state).
    """
    new = arch.copy() if isinstance(arch, Archive) else \
        read_archive(arch).copy()
    new.data = np.asarray(data, dtype=np.float64).reshape(new.data.shape)
    if DM is not None:
        new.DM = float(DM)
    new.dedispersed = bool(dmc)
    if weights is not None:
        new.weights = np.asarray(weights, dtype=np.float64)
    new.unload(outfile, quiet=quiet)
    return new


def make_fake_pulsar(modelfile, ephemeris, outfile="fake_pulsar.fits",
                     nsub=1, npol=1, nchan=512, nbin=2048, nu0=1500.0,
                     bw=800.0, tsub=300.0, phase=0.0, dDM=0.0,
                     start_MJD=None, weights=None, noise_stds=1.0,
                     scales=1.0, dedispersed=False, t_scat=0.0,
                     alpha=-4.0, scint=False, xs=None, Cs=None,
                     nu_DM=np.inf, state="Stokes", telescope="GBT",
                     frontend="unknown", seed=0, quiet=True):
    """Generate a fake-pulsar PSRFITS archive from a .gmodel file.

    File-producing equivalent of /root/reference/pplib.py:3189-3384 —
    the array math lives in pipelines.synth; this wraps it with the
    ephemeris, epochs and PSRFITS unload.  ``seed`` replaces global
    numpy randomness with an explicit PRNG.
    """
    # fixture generation is host-side territory: the per-subint model
    # builds and noise draws are tiny device ops that would each pay a
    # full dispatch round trip through a remote-device tunnel (~150 ms
    # here), dominating archive synthesis ~10x over the math
    with host_stats_device():
        return _make_fake_pulsar_impl(
            modelfile=modelfile, ephemeris=ephemeris, outfile=outfile,
            nsub=nsub, npol=npol, nchan=nchan, nbin=nbin, nu0=nu0, bw=bw,
            tsub=tsub, phase=phase, dDM=dDM, start_MJD=start_MJD,
            weights=weights, noise_stds=noise_stds, scales=scales,
            dedispersed=dedispersed, t_scat=t_scat, alpha=alpha,
            scint=scint, xs=xs, Cs=Cs, nu_DM=nu_DM, state=state,
            telescope=telescope, frontend=frontend, seed=seed,
            quiet=quiet)


def _make_fake_pulsar_impl(*, modelfile, ephemeris, outfile, nsub, npol,
                           nchan, nbin, nu0, bw, tsub, phase, dDM,
                           start_MJD, weights, noise_stds, scales,
                           dedispersed, t_scat, alpha, scint, xs, Cs,
                           nu_DM, state, telescope, frontend, seed,
                           quiet):
    import jax

    from ..config import Dconst, host_array
    from ..ops.fourier import add_DM_nu, rotate_data
    from ..ops.scattering import scattering_portrait_FT, scattering_times
    from ..pipelines.synth import add_scintillation
    from .parfile import read_par

    chanwidth = bw / nchan
    lofreq = nu0 - bw / 2
    freqs = np.linspace(lofreq + chanwidth / 2, lofreq + bw - chanwidth / 2,
                        nchan)
    phases_arr = np.asarray(get_bin_centers(nbin))
    noise_stds = np.broadcast_to(np.asarray(noise_stds, dtype=np.float64),
                                 (nchan,))
    scales = np.broadcast_to(np.asarray(scales, dtype=np.float64),
                             (nchan,))
    par = read_par(ephemeris)
    P0 = float(par.P0)
    F0 = float(par.F0)
    F1 = float(par.get("F1", 0.0))
    DM = float(par.get("DM", 0.0))
    PEPOCH = float(par.get("PEPOCH", 56000.0))
    if start_MJD is None:
        start_MJD = MJD.from_mjd(PEPOCH)
    epochs = [start_MJD.add_seconds(tsub / 2.0 + isub * tsub)
              for isub in range(nsub)]
    # per-subint folding periods from the (F0, F1) spin model at each
    # epoch — matching the reference's per-Integration
    # get_folding_period() (/root/reference/pplib.py:2733, :3343); a
    # matching POLYCO predictor is attached so the period drift
    # round-trips through the PSRFITS layer
    if F1 != 0.0:
        polyco = polyco_from_spin(F0, F1, PEPOCH, psr=str(
            par.get("PSR", par.get("PSRJ", "FAKE"))))
        Ps_sub = polyco.periods([ep.mjd() for ep in epochs])
    else:
        polyco = None
        Ps_sub = np.full(nsub, P0)
    # Phase-align each subint epoch to the spin model, as folding with a
    # predictor does (PSRCHIVE archives are phase-connected: bin 0 of
    # every subint corresponds to predictor pulse-phase zero near its
    # epoch).  Without this the synthetic TOAs cannot time coherently
    # across epochs (the notebook's tempo GLS stage would see uniform
    # junk residuals).
    pe_day = int(PEPOCH)
    pe_sec = (PEPOCH - pe_day) * 86400.0
    dts = np.array([(ep.day - pe_day) * 86400.0 + (ep.secs - pe_sec)
                    for ep in epochs])
    spin_phase = F0 * dts + 0.5 * F1 * dts * dts
    epochs = [ep.add_seconds(-float((spin_phase[i] % 1.0) * Ps_sub[i]))
              for i, ep in enumerate(epochs)]
    if polyco is not None:  # periods exactly at the (shifted) epochs
        Ps_sub = polyco.periods([ep.mjd() for ep in epochs])
    if weights is None:
        weights = np.ones([nsub, nchan])

    key = jax.random.key(seed)
    data = np.zeros([nsub, npol, nchan, nbin])
    for isub in range(nsub):
        P = float(Ps_sub[isub])
        _, _, model = read_model(modelfile, phases_arr, freqs, P,
                                 quiet=True)
        model = np.asarray(model)
        if xs is None:
            rotmodel = model
        else:
            ph = phase + Dconst * (DM + dDM) * \
                (nu_DM ** -2 - nu0 ** -2) / P
            rotmodel = np.asarray(add_DM_nu(model, -ph, -dDM, P, freqs,
                                            xs=xs, Cs=Cs, nu_ref=nu_DM))
        if t_scat:
            taus = np.asarray(scattering_times(t_scat / P, alpha, freqs,
                                               nu0))
            sp_FT = host_array(scattering_portrait_FT(taus, nbin))
            rotmodel = np.fft.irfft(sp_FT * np.fft.rfft(rotmodel, axis=-1),
                                    nbin, axis=-1)
        if scint is not False:
            if scint is True:
                key, sk = jax.random.split(key)
                rotmodel = np.asarray(add_scintillation(rotmodel, key=sk,
                                                        nsin=3, amax=1.0,
                                                        wmax=5.0))
            else:
                rotmodel = np.asarray(add_scintillation(rotmodel,
                                                        params=scint))
        key, nk = jax.random.split(key)
        noise = np.asarray(jax.random.normal(nk, (npol, nchan, nbin)))
        data[isub] = scales[:, None] * rotmodel[None] + \
            noise * noise_stds[:, None]

    ephem_text = open(ephemeris).read()
    arch = Archive(data, freqs, weights, Ps_sub, epochs,
                   np.full(nsub, tsub), DM=DM,
                   state=("Intensity" if npol == 1 else state),
                   dedispersed=True, source=str(par.get("PSR", "FAKE")),
                   telescope=telescope, frontend=frontend, nu0=nu0,
                   bw=bw, ephemeris_text=ephem_text, polyco=polyco)
    # The model is built at its intrinsic (aligned) phases = the
    # dedispersed frame; inject the (phase, dDM) rotation, then store
    # dispersed or dedispersed as requested.
    if phase != 0.0 or dDM != 0.0:
        if xs is None:
            arch.data = np.asarray(
                rotate_data(arch.data, -phase, -dDM,
                            Ps_sub, freqs, nu0))
    if not dedispersed:
        arch.dededisperse()
    arch.unload(outfile, quiet=quiet)
    if not quiet:
        print("Unloaded %s." % outfile)
    return outfile


def parse_metafile(metafile):
    """List of archive paths from a newline-separated metafile
    (reference pptoas.py:92-96)."""
    with open(metafile) as f:
        return [line.strip() for line in f
                if line.strip() and not line.startswith("#")
                and os.path.basename(line.strip()) != ""]
