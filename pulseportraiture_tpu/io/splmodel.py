"""Spline portrait model container: npz-based, with legacy pickle compat.

The reference pickles ``[modelname, source, datafile, mean_prof, eigvec,
tck]`` into a ``.spl`` file (/root/reference/ppspline.py:206-230,
pplib.py:2961-3019).  Pickle is fragile and unsafe as an interchange
format; the native container here is a plain ``.npz`` holding the same
contents, while ``read_spline_model`` transparently loads either format
(legacy pickles read-only).
"""

import pickle

import numpy as np

from ..ops.splines import gen_spline_portrait, splev

__all__ = ["write_spline_model", "read_spline_model",
           "get_spline_model_coords"]


def write_spline_model(modelfile, modelname, source, datafile, mean_prof,
                       eigvec, tck, quiet=True):
    """Write a spline model as .npz (tck = (t, c, k); c [ndim, ncoef])."""
    t, c, k = tck
    # np.savez appends '.npz' to bare paths; write through a file object
    # so the model lands at exactly ``modelfile`` (.spl convention kept).
    with open(modelfile, "wb") as f:
        np.savez(
            f,
            modelname=np.str_(modelname), source=np.str_(source),
            datafile=np.str_(datafile),
            mean_prof=np.asarray(mean_prof, dtype=np.float64),
            eigvec=np.asarray(eigvec, dtype=np.float64),
            tck_t=np.asarray(t, dtype=np.float64),
            tck_c=np.asarray(c, dtype=np.float64),
            tck_k=np.int64(k))
    if not quiet:
        print("%s written." % modelfile)


def _load_container(modelfile):
    """Return (modelname, source, datafile, mean_prof, eigvec, tck) from
    either the npz container or a legacy reference pickle."""
    try:
        with np.load(modelfile, allow_pickle=False) as z:
            return (str(z["modelname"]), str(z["source"]),
                    str(z["datafile"]), z["mean_prof"], z["eigvec"],
                    (z["tck_t"], z["tck_c"], int(z["tck_k"])))
    except (ValueError, OSError, KeyError):
        with open(modelfile, "rb") as f:
            modelname, source, datafile, mean_prof, eigvec, tck = \
                pickle.load(f, encoding="latin1")
        t, c, k = tck
        return (modelname, source, datafile, np.asarray(mean_prof),
                np.asarray(eigvec), (np.asarray(t), np.asarray(c), int(k)))


def read_spline_model(modelfile, freqs=None, nbin=None, quiet=True):
    """Read a spline model; optionally build the portrait at ``freqs``.

    Read-only call returns the 6-tuple contents; otherwise returns
    (modelname, port [nchan, nbin]).  Equivalent of
    /root/reference/pplib.py:2961-2993.
    """
    contents = _load_container(modelfile)
    if freqs is None:
        return contents
    modelname, _, _, mean_prof, eigvec, tck = contents
    port = gen_spline_portrait(mean_prof, np.asarray(freqs), eigvec, tck,
                               nbin)
    return (modelname, port)


def get_spline_model_coords(modelfile, nfreq=1000, lo_freq=None,
                            hi_freq=None):
    """Spline-curve coordinates sampled over frequency.

    Equivalent of /root/reference/pplib.py:2995-3019 (without the pickle
    side-dump; callers can np.savez the return).
    """
    _, _, _, _, _, tck = _load_container(modelfile)
    t = np.asarray(tck[0])
    lo = t.min() if lo_freq is None else lo_freq
    hi = t.max() if hi_freq is None else hi_freq
    model_freqs = np.linspace(lo, hi, nfreq)
    proj_port = np.asarray(splev(model_freqs, tck)).T
    return model_freqs, proj_port
