"""TOA records and TOA-file writers (IPTA/tempo2 and Princeton formats).

Equivalent of the reference's TOA class (/root/reference/pptoas.py:31-73)
and ``filter_TOAs``/``write_princeton_TOA``/``write_TOAs``
(/root/reference/pplib.py:3386-3509), minus the Py2 ``exec``-based
attribute plumbing (SURVEY.md §7.4 calls that out as an artifact not to
reproduce) — flags live in a plain dict with operator-based filtering.
"""

import operator

import numpy as np

__all__ = ["TOA", "filter_TOAs", "write_TOAs", "write_princeton_TOA",
           "format_toa_line"]

_OPS = {">": operator.gt, ">=": operator.ge, "<": operator.lt,
        "<=": operator.le, "==": operator.eq, "!=": operator.ne}


class TOA:
    """One time-of-arrival measurement with metadata flags.

    archive: source file name; frequency: reference frequency [MHz] (may
    be inf); MJD: utils.mjd.MJD epoch; TOA_error [us]; telescope /
    telescope_code; DM/DM_error [cm**-3 pc] for wideband TOAs; flags: a
    dict of arbitrary '-flag value' pairs for the .tim line.
    """

    def __init__(self, archive, frequency, MJD, TOA_error, telescope,
                 telescope_code, DM=None, DM_error=None, flags=None):
        self.archive = archive
        self.frequency = frequency
        self.MJD = MJD
        self.TOA_error = TOA_error
        self.telescope = telescope
        self.telescope_code = telescope_code
        self.DM = DM
        self.DM_error = DM_error
        self.flags = dict(flags or {})

    def get(self, flag, default=None):
        """Flag value, falling back to real attributes (snr, gof, ...)."""
        if flag in self.flags:
            return self.flags[flag]
        return getattr(self, flag, default)

    def __repr__(self):
        return (f"TOA({self.archive}, {self.frequency} MHz, "
                f"{self.MJD}, +/-{self.TOA_error} us)")

    def write_TOA(self, inf_is_zero=True, outfile=None):
        write_TOAs(self, inf_is_zero=inf_is_zero, outfile=outfile,
                   append=True)


def filter_TOAs(TOAs, flag, cutoff, criterion=">=", pass_unflagged=False,
                return_culled=False):
    """Filter TOAs on a flag/attribute against a cutoff.

    Equivalent of /root/reference/pplib.py:3386-3413 with the exec-based
    comparison replaced by operator dispatch.
    """
    comp = _OPS[criterion]
    new_toas, culled = [], []
    for toa in TOAs:
        val = toa.get(flag)
        if val is not None:
            (new_toas if comp(val, cutoff) else culled).append(toa)
        else:
            (new_toas if pass_unflagged else culled).append(toa)
    if return_culled:
        return new_toas, culled
    return new_toas


def _format_flag_value(flag, value):
    if isinstance(value, str):
        return value
    if isinstance(value, (bool, np.bool_)):
        return "%d" % int(value)
    if isinstance(value, (int, np.integer)):
        return "%d" % value
    if "_cov" in flag:
        return "%.1e" % value
    if "phs" in flag:
        return "%.8f" % value
    if "flux" in flag:
        return "%.5f" % value
    return "%.3f" % value


def format_toa_line(toa, inf_is_zero=True):
    """One loosely-IPTA/tempo2 .tim line, with -pp_dm/-pp_dme wideband
    flags (format per /root/reference/pplib.py:3478-3503)."""
    freq = toa.frequency
    if freq == np.inf and inf_is_zero:
        freq = 0.0
    day, frac = toa.MJD.format_parts(15)
    line = "%s %.8f %d%s   %.3f  %s" % (toa.archive, freq, day, frac,
                                        toa.TOA_error,
                                        toa.telescope_code)
    if toa.DM is not None:
        line += " -pp_dm %.7f" % toa.DM
    if toa.DM_error is not None:
        line += " -pp_dme %.7f" % toa.DM_error
    for flag, value in toa.flags.items():
        if value is not None:
            line += " -%s %s" % (flag, _format_flag_value(flag, value))
    return line


def write_TOAs(TOAs, inf_is_zero=True, SNR_cutoff=0.0, outfile=None,
               append=True):
    """Write .tim lines to outfile (append by default) or stdout.

    Equivalent of /root/reference/pplib.py:3451-3509, plus the
    ``FORMAT 1`` header tempo2/PINT expect at the top of an IPTA-format
    tim file — emitted whenever this call starts a fresh file (the
    reference leaves it to the user's editor).
    """
    import os

    toas = TOAs if isinstance(TOAs, (list, tuple)) else [TOAs]
    toas = filter_TOAs(toas, "snr", SNR_cutoff, ">=", pass_unflagged=False)
    lines = [format_toa_line(t, inf_is_zero) for t in toas]
    if outfile is None:
        for line in lines:
            print(line)
    elif lines:
        fresh = not append or not os.path.exists(outfile) \
            or os.path.getsize(outfile) == 0
        with open(outfile, "a" if append else "w") as of:
            if fresh:
                of.write("FORMAT 1\n")
            of.write("".join(line + "\n" for line in lines))
    elif not append and os.path.exists(outfile):
        # all TOAs culled: an overwrite call must still truncate (stale
        # TOAs from a previous run would otherwise survive), but leave
        # no header-only file behind and create nothing new
        open(outfile, "w").close()


def write_princeton_TOA(TOA_MJDi, TOA_MJDf, TOA_err, nu_ref, dDM, obs="@",
                        name=" " * 13, outfile=None):
    """Princeton-format TOA line (columns per tempo documentation).

    Equivalent of /root/reference/pplib.py:3415-3449 — and usable from
    the TOA pipeline, fixing the reference's dangling
    ``write_princeton_TOAs`` call (pptoas.py:1589).
    """
    if nu_ref == np.inf:
        nu_ref = 0.0
    toa = "%5d" % int(TOA_MJDi) + ("%.13f" % TOA_MJDf)[1:]
    line = obs + " %13s %8.3f %s %8.3f              %9.5f" % \
        (name, nu_ref, toa, TOA_err, dDM)
    if outfile is None:
        print(line)
    else:
        with open(outfile, "a") as of:
            of.write(line + "\n")
    return line
