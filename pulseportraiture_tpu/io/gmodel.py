"""`.gmodel` Gaussian-model text files, bit-compatible with the reference.

Format (reference ``write_model``/``read_model``,
/root/reference/pplib.py:2834-2959; documented example at
/root/reference/examples/example.gmodel):

    MODEL   <name>
    CODE    <3-digit evolution code>
    FREQ    <nu_ref MHz>
    DC      <value> <fit>
    TAU     <value sec> <fit>
    ALPHA   <value>      <fit>
    COMPnn  <loc> <fit>  <dloc> <fit>  <wid> <fit>  <dwid> <fit> \
            <amp> <fit>  <damp> <fit>

TAU is stored in seconds; ``read_model`` converts to bins (tau *= nbin/P)
when building a portrait.
"""

import numpy as np

from ..ops.profiles import gen_gaussian_portrait

__all__ = ["write_model", "read_model"]


def write_model(filename, name, model_code, nu_ref, model_params, fit_flags,
                alpha, fit_alpha, append=False, quiet=False):
    """Write a Gaussian-component model file (pplib.py:2834-2872)."""
    mode = "a" if append else "w"
    model_params = np.asarray(model_params, dtype=np.float64)
    fit_flags = np.asarray(fit_flags, dtype=int)
    with open(filename, mode) as outfile:
        outfile.write("MODEL   %s\n" % name)
        outfile.write("CODE    %s\n" % model_code)
        outfile.write("FREQ    %.5f\n" % nu_ref)
        outfile.write("DC     % .8f %d\n" % (model_params[0], fit_flags[0]))
        outfile.write("TAU    % .8f %d\n" % (model_params[1], fit_flags[1]))
        outfile.write("ALPHA  % .3f      %d\n" % (alpha, fit_alpha))
        ngauss = (len(model_params) - 2) // 6
        for igauss in range(ngauss):
            comp = model_params[2 + igauss * 6: 8 + igauss * 6]
            fit_comp = fit_flags[2 + igauss * 6: 8 + igauss * 6]
            pairs = tuple(np.stack([comp, fit_comp], axis=1).ravel())
            outfile.write(
                "COMP%02d % .8f %d  % .8f %d  % .8f %d  % .8f %d  "
                "% .8f %d  % .8f %d\n"
                % ((igauss + 1,) + pairs))
    if not quiet:
        print("%s written." % filename)


def read_model(modelfile, phases=None, freqs=None, P=None, quiet=True):
    """Read a `.gmodel` file; optionally build the portrait.

    Read-only call (phases/freqs None) returns (name, model_code, nu_ref,
    ngauss, params, fit_flags, alpha, fit_alpha); otherwise returns
    (name, ngauss, model [nchan, nbin]) with TAU converted from seconds
    to bins.  Equivalent of /root/reference/pplib.py:2873-2959.
    """
    read_only = phases is None and freqs is None
    comps = []
    modelname = model_code = None
    nu_ref = dc = tau = alpha = 0.0
    fit_dc = fit_tau = fit_alpha = 0
    with open(modelfile) as f:
        for line in f:
            info = line.split()
            if not info:
                continue
            key = info[0]
            try:
                if key == "MODEL":
                    modelname = info[1]
                elif key == "CODE":
                    model_code = info[1]
                elif key == "FREQ":
                    nu_ref = float(info[1])
                elif key == "DC":
                    dc, fit_dc = float(info[1]), int(info[2])
                elif key == "TAU":
                    tau, fit_tau = float(info[1]), int(info[2])
                elif key == "ALPHA":
                    alpha, fit_alpha = float(info[1]), int(info[2])
                elif key.startswith("COMP"):
                    comps.append(line)
            except IndexError:
                pass
    ngauss = len(comps)
    params = np.zeros(ngauss * 6 + 2)
    fit_flags = np.zeros(len(params), dtype=int)
    params[0], params[1] = dc, tau
    fit_flags[0], fit_flags[1] = fit_dc, fit_tau
    for igauss, comp_line in enumerate(comps):
        toks = comp_line.split()
        params[2 + igauss * 6: 8 + igauss * 6] = \
            [float(v) for v in toks[1::2]]
        fit_flags[2 + igauss * 6: 8 + igauss * 6] = \
            [int(v) for v in toks[2::2]]
    if read_only:
        return (modelname, model_code, nu_ref, ngauss, params, fit_flags,
                alpha, fit_alpha)
    nbin = len(phases)
    if params[1] != 0.0:
        if P is None:
            raise ValueError("Need period P for non-zero scattering TAU.")
        params = params.copy()
        params[1] *= nbin / P
    model = gen_gaussian_portrait(model_code, params, alpha,
                                  np.asarray(phases), np.asarray(freqs),
                                  nu_ref)
    if not quiet:
        print("Model Name: %s" % modelname)
        print("Made %d component model with %d profile bins."
              % (ngauss, nbin))
    return (modelname, ngauss, model)
