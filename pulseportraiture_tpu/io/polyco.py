"""Pulse-phase predictors: tempo POLYCO and tempo2 T2PREDICT (Chebyshev).

Real fold-mode PSRFITS archives carry the folding ephemeris as a POLYCO
or T2PREDICT HDU, and the folding period drifts across subintegrations;
the reference reads ``get_folding_period()`` from every Integration via
PSRCHIVE (/root/reference/pplib.py:2733, :3343).  This module is the
in-repo equivalent: evaluate pulse phase/spin frequency at arbitrary
epochs so the PSRFITS layer (io/psrfits.py) can assign every subint its
own folding period.

Conventions implemented:

* tempo polyco segments (tempo "polyco.dat"):
    DT = (T - TMID) [min],
    phase(T) = RPHASE + 60 * DT * F0ref + sum_k COEF[k] * DT**k,
    f(T) [Hz] = F0ref + (1/60) * sum_k k * COEF[k] * DT**(k-1).
* tempo2 ChebyModelSet (T2PREDICT HDU text):
    phase(T, nu) = DISPERSION_CONSTANT / nu**2 + Cheb2D(x(T), y(nu))
  with x, y the ranges mapped to [-1, 1] and the i=0 / j=0 coefficients
  taken at half weight (tempo2's summation convention); the spin
  frequency is the analytic d(phase)/dT via Chebyshev differentiation.
"""

import numpy as np

__all__ = ["PolycoSegment", "Polyco", "ChebyModel", "ChebyModelSet",
           "parse_polyco_text", "parse_t2predict_text",
           "polyco_from_spin"]


class PolycoSegment:
    """One tempo polyco block: valid for ``nspan`` minutes around tmid."""

    def __init__(self, tmid, rphase, f0ref, coeffs, nspan=1440,
                 ref_freq=0.0, site="@", log10_fit_err=0.0):
        self.tmid = float(tmid)              # MJD (TDB)
        self.rphase = float(rphase)          # reference phase [rot]
        self.f0ref = float(f0ref)            # reference spin freq [Hz]
        self.coeffs = np.asarray(coeffs, dtype=np.float64)
        self.nspan = float(nspan)            # validity span [min]
        self.ref_freq = float(ref_freq)      # observing freq [MHz]
        self.site = site
        self.log10_fit_err = float(log10_fit_err)

    def contains(self, mjd):
        return abs(mjd - self.tmid) * 1440.0 <= self.nspan / 2.0

    def phase(self, mjd):
        dt = (np.asarray(mjd, dtype=np.float64) - self.tmid) * 1440.0
        poly = np.polynomial.polynomial.polyval(dt, self.coeffs)
        return self.rphase + 60.0 * dt * self.f0ref + poly

    def freq(self, mjd):
        """Spin frequency [Hz] at mjd."""
        dt = (np.asarray(mjd, dtype=np.float64) - self.tmid) * 1440.0
        dcoef = np.polynomial.polynomial.polyder(self.coeffs) \
            if len(self.coeffs) > 1 else np.zeros(1)
        return self.f0ref + np.polynomial.polynomial.polyval(dt,
                                                             dcoef) / 60.0


class Polyco:
    """A set of polyco segments with nearest-segment dispatch."""

    def __init__(self, segments, psr=""):
        if not segments:
            raise ValueError("Polyco needs at least one segment.")
        self.segments = sorted(segments, key=lambda s: s.tmid)
        self.psr = psr

    def _segment_for(self, mjd):
        best, bestd = None, np.inf
        for seg in self.segments:
            d = abs(mjd - seg.tmid)
            if d < bestd:
                best, bestd = seg, d
        return best

    def phase(self, mjd):
        return self._segment_for(float(mjd)).phase(float(mjd))

    def freq(self, mjd):
        return self._segment_for(float(mjd)).freq(float(mjd))

    def period(self, mjd):
        """Folding period [s] at mjd (1 / spin frequency)."""
        return 1.0 / self.freq(mjd)

    def periods(self, mjds):
        return np.asarray([self.period(m) for m in np.atleast_1d(mjds)])


def polyco_from_spin(F0, F1, pepoch, tmid=None, nspan=1440, ncoef=3,
                     site="@", psr=""):
    """Exact single-segment polyco for a (F0, F1) spin-down model.

    phase(t) = F0*dt + F1/2 dt**2 (dt in s from ``pepoch``) is quadratic,
    so with F0ref = F0 + F1*dts (dts = seconds from pepoch to tmid) and
    COEF[2] = 1800*F1 the polyco reproduces it to machine precision —
    the generator-side predictor for make_fake_pulsar's drifting-period
    archives.
    """
    tmid = float(pepoch if tmid is None else tmid)
    dts = (tmid - pepoch) * 86400.0
    f0ref = F0 + F1 * dts
    rphase = F0 * dts + 0.5 * F1 * dts ** 2
    coeffs = np.zeros(max(int(ncoef), 3))
    coeffs[2] = 1800.0 * F1  # (60 s/min)^2 * F1/2
    return Polyco([PolycoSegment(tmid, rphase, f0ref, coeffs,
                                 nspan=nspan, site=site)], psr=psr)


def parse_polyco_text(text):
    """Parse tempo 'polyco.dat' blocks.

    Block layout (tempo polyco format): line 1 = name, date, utc, tmid,
    dm, doppler, log10(fit rms); line 2 = rphase, f0, site, span, ncoef,
    obs freq [, binary phase...]; then ncoef coefficients, 3 per line.
    """
    lines = [ln for ln in text.splitlines() if ln.strip()]
    segments, psr = [], ""
    i = 0
    while i + 1 < len(lines):
        head1 = lines[i].split()
        head2 = lines[i + 1].split()
        psr = head1[0]
        tmid = float(head1[3])
        log10rms = float(head1[6]) if len(head1) > 6 else 0.0
        rphase = float(head2[0])
        f0ref = float(head2[1])
        site = head2[2]
        nspan = float(head2[3])
        ncoef = int(head2[4])
        ref_freq = float(head2[5]) if len(head2) > 5 else 0.0
        coeffs = []
        i += 2
        while len(coeffs) < ncoef:
            coeffs.extend(float(tok.replace("D", "E").replace("d", "e"))
                          for tok in lines[i].split())
            i += 1
        segments.append(PolycoSegment(tmid, rphase, f0ref, coeffs[:ncoef],
                                      nspan=nspan, ref_freq=ref_freq,
                                      site=site,
                                      log10_fit_err=log10rms))
    return Polyco(segments, psr=psr)


def _cheby2d_eval(coeffs, x, y):
    """sum_ij c_ij T_i(x) T_j(y), i=0/j=0 rows at half weight.

    Returns a true Python float for scalar (x, y) inputs — chebvander
    promotes 0-d inputs to shape (1,), which would otherwise leak out
    as a size-1 array (a hard error to float() under future NumPy).
    """
    c = np.array(coeffs, dtype=np.float64)
    c[0, :] *= 0.5
    c[:, 0] *= 0.5
    Tx = np.polynomial.chebyshev.chebvander(np.asarray(x), c.shape[0] - 1)
    Ty = np.polynomial.chebyshev.chebvander(np.asarray(y), c.shape[1] - 1)
    out = np.einsum("...i,ij,...j->...", Tx, c, Ty)
    if np.ndim(x) == 0 and np.ndim(y) == 0:
        return out.reshape(()).item()
    return out.reshape(np.broadcast_shapes(np.shape(x), np.shape(y)))


class ChebyModel:
    """One tempo2 ChebyModel segment (2-D Chebyshev phase predictor)."""

    def __init__(self, mjd_start, mjd_end, freq_start, freq_end, coeffs,
                 dispersion_constant=0.0, psrname="", sitename=""):
        self.mjd_start = float(mjd_start)
        self.mjd_end = float(mjd_end)
        self.freq_start = float(freq_start)
        self.freq_end = float(freq_end)
        self.coeffs = np.asarray(coeffs, dtype=np.float64)
        self.dispersion_constant = float(dispersion_constant)
        self.psrname = psrname
        self.sitename = sitename

    def _xy(self, mjd, freq):
        x = 2.0 * (np.asarray(mjd) - self.mjd_start) \
            / (self.mjd_end - self.mjd_start) - 1.0
        y = 2.0 * (np.asarray(freq) - self.freq_start) \
            / (self.freq_end - self.freq_start) - 1.0
        return x, y

    def contains(self, mjd):
        return self.mjd_start <= mjd <= self.mjd_end

    def phase(self, mjd, freq):
        x, y = self._xy(mjd, freq)
        ph = _cheby2d_eval(self.coeffs, x, y)
        if self.dispersion_constant:
            ph = ph + self.dispersion_constant / np.asarray(freq) ** 2
        return ph

    def freq_spin(self, mjd, freq):
        """Spin frequency [Hz] = d(phase)/dt via Chebyshev derivative."""
        x, y = self._xy(mjd, freq)
        c = np.array(self.coeffs, dtype=np.float64)
        c[0, :] *= 0.5
        c[:, 0] *= 0.5
        # half-weights are folded into c, so the derivative series dc
        # evaluates with plain (unweighted) Chebyshev summation
        dc = np.polynomial.chebyshev.chebder(c, axis=0)
        Tx = np.polynomial.chebyshev.chebvander(np.asarray(x),
                                                dc.shape[0] - 1)
        Ty = np.polynomial.chebyshev.chebvander(np.asarray(y),
                                                dc.shape[1] - 1)
        dphase_dx = np.einsum("...i,ij,...j->...", Tx, dc, Ty)
        dx_dmjd = 2.0 / (self.mjd_end - self.mjd_start)
        out = dphase_dx * dx_dmjd / 86400.0
        # chebvander promotes 0-d inputs to (1,); hand scalars back as
        # true scalars so float(period(...)) stays legal under future
        # NumPy (see _cheby2d_eval)
        if np.ndim(mjd) == 0 and np.ndim(freq) == 0:
            return out.reshape(()).item()
        return out.reshape(np.broadcast_shapes(np.shape(mjd),
                                               np.shape(freq)))


class ChebyModelSet:
    """tempo2 predictor: a set of ChebyModel segments."""

    def __init__(self, models):
        if not models:
            raise ValueError("ChebyModelSet needs at least one segment.")
        self.models = models

    def _model_for(self, mjd):
        for m in self.models:
            if m.contains(mjd):
                return m
        # nearest by midpoint outside all ranges
        return min(self.models,
                   key=lambda m: abs(mjd - 0.5 * (m.mjd_start
                                                  + m.mjd_end)))

    def phase(self, mjd, freq):
        return self._model_for(float(mjd)).phase(float(mjd), freq)

    def freq(self, mjd, freq):
        return self._model_for(float(mjd)).freq_spin(float(mjd), freq)

    def period(self, mjd, freq):
        return 1.0 / self.freq(mjd, freq)

    def periods(self, mjds, freq):
        return np.asarray([self.period(m, freq)
                           for m in np.atleast_1d(mjds)])


def parse_t2predict_text(text):
    """Parse a tempo2 ChebyModelSet (T2PREDICT HDU text payload)."""
    models = []
    cur = None
    coeff_rows = []
    ncoeff_time = ncoeff_freq = None
    for ln in text.splitlines():
        tok = ln.split()
        if not tok:
            continue
        key = tok[0].upper()
        if key == "CHEBYMODELSET":
            continue
        if key == "CHEBYMODEL":
            if tok[1].upper() == "BEGIN":
                cur = {}
                coeff_rows = []
                ncoeff_time = ncoeff_freq = None
            elif tok[1].upper() == "END" and cur is not None:
                coeffs = np.asarray(coeff_rows, dtype=np.float64)
                if ncoeff_time is not None and ncoeff_freq is not None:
                    coeffs = coeffs.reshape(ncoeff_time, ncoeff_freq)
                models.append(ChebyModel(
                    cur["time0"], cur["time1"], cur["freq0"], cur["freq1"],
                    coeffs,
                    dispersion_constant=cur.get("disp", 0.0),
                    psrname=cur.get("psrname", ""),
                    sitename=cur.get("sitename", "")))
                cur = None
        elif cur is None:
            continue
        elif key == "PSRNAME":
            cur["psrname"] = tok[1]
        elif key == "SITENAME":
            cur["sitename"] = tok[1]
        elif key == "TIME_RANGE":
            cur["time0"], cur["time1"] = float(tok[1]), float(tok[2])
        elif key == "FREQ_RANGE":
            cur["freq0"], cur["freq1"] = float(tok[1]), float(tok[2])
        elif key == "DISPERSION_CONSTANT":
            cur["disp"] = float(tok[1])
        elif key == "NCOEFF_TIME":
            ncoeff_time = int(tok[1])
        elif key == "NCOEFF_FREQ":
            ncoeff_freq = int(tok[1])
        elif key == "COEFFS":
            coeff_rows.append([float(t) for t in tok[1:]])
    return ChebyModelSet(models)
