"""PSRFITS fold-mode archives: in-memory model + read/write.

In-repo replacement for the PSRCHIVE L0 boundary (SURVEY.md §1 L0): the
``Archive`` class holds the folded data cube and metadata and provides
the manipulations ``load_data`` needs (state conversion, de/dedispersion,
scrunches, baseline removal, unload), implemented on the framework's own
batched ops instead of C++ calls.

File layout written/read: primary HDU with PSRFITS observation keywords;
a PSRPARAM table carrying the ephemeris text; a SUBINT BINTABLE with
TSUBINT, OFFS_SUB, PERIOD, DAT_FREQ, DAT_WTS, DAT_SCL, DAT_OFFS and
int16 DATA (TDIM (nbin, nchan, npol)), physical = DATA*SCL + OFFS.  This
matches the fold-mode PSRFITS core used by PSRCHIVE (scale/offset
semantics and column names per the PSRFITS definition).

Folding periods: real fold-mode archives carry a POLYCO or T2PREDICT
HDU and the period drifts across subints (the reference reads
``get_folding_period()`` per Integration, /root/reference/pplib.py:2733,
:3343).  The reader resolves per-subint periods in priority order:
explicit PERIOD column > POLYCO HDU evaluated at each epoch > T2PREDICT
HDU > single-F0 ephemeris fallback (with a warning).  The writer emits
a POLYCO HDU whenever ``Archive.polyco`` is set.
"""

import sys

import numpy as np

from ..utils.mjd import MJD
from .fits import HDU, read_fits, write_bintable_hdu, write_fits
from .polyco import Polyco, PolycoSegment, parse_t2predict_text

__all__ = ["Archive", "read_archive", "write_archive_file"]

Dconst = 0.000241 ** -1  # traditional dispersion constant, as config


def _rotate_np(data, shifts):
    """Host-side Fourier rotation of [..., nbin] by per-row shifts [rot].

    Positive shifts rotate to earlier phases (same convention as
    ops.fourier.rotate_data); NumPy here because Archive manipulation is
    host-side I/O territory.
    """
    FT = np.fft.rfft(data, axis=-1)
    k = np.arange(FT.shape[-1])
    FT *= np.exp(2j * np.pi * shifts[..., None] * k)
    return np.fft.irfft(FT, data.shape[-1], axis=-1)


class Archive:
    """In-memory fold-mode archive.

    data: [nsub, npol, nchan, nbin] float64 (physical units);
    freqs: [nsub, nchan] MHz; weights: [nsub, nchan];
    Ps: [nsub] folding periods [sec]; epochs: list of MJD (subint
    centers); durations: [nsub] sec; state: 'Intensity'|'Stokes'|
    'Coherence'; dedispersed: bool ("dmc" in the reference).
    """

    def __init__(self, data, freqs, weights, Ps, epochs, durations,
                 DM=0.0, state="Intensity", dedispersed=False,
                 source="FAKE", telescope="GBT", frontend="unknown",
                 backend="unknown", backend_delay=0.0, nu0=None, bw=None,
                 ephemeris_text="", doppler_factors=None,
                 parallactic_angles=None, filename="", polyco=None,
                 doppler_degraded=False, basis="LIN"):
        self.data = np.asarray(data, dtype=np.float64)
        self.nsub, self.npol, self.nchan, self.nbin = self.data.shape
        self.freqs = np.asarray(freqs, dtype=np.float64)
        if self.freqs.ndim == 1:
            self.freqs = np.tile(self.freqs, (self.nsub, 1))
        self.weights = np.asarray(weights, dtype=np.float64)
        self.Ps = np.asarray(Ps, dtype=np.float64)
        self.epochs = list(epochs)
        self.durations = np.asarray(durations, dtype=np.float64)
        self.DM = float(DM)
        self.state = state
        self.basis = str(basis).strip().upper() or "LIN"
        self.dedispersed = bool(dedispersed)
        self.source = source
        self.telescope = telescope
        self.frontend = frontend
        self.backend = backend
        self.backend_delay = float(backend_delay)
        chan_bw = (self.freqs[0, 1] - self.freqs[0, 0]) \
            if self.nchan > 1 else 0.0
        self.bw = float(bw if bw is not None else chan_bw * self.nchan)
        self.nu0 = float(nu0 if nu0 is not None
                         else self.freqs[0].mean())
        self.ephemeris_text = ephemeris_text
        # When not stored, compute Doppler factors / parallactic angles
        # from the observatory + source geometry (the reference gets
        # them from PSRCHIVE, pplib.py:2697-2708); unity/zero fallback
        # when the coordinates are unknown.
        # True when the factors are the fabricated unity fallback (set
        # below, or propagated by a caller copying a degraded archive)
        self.doppler_degraded = bool(doppler_degraded)
        if doppler_factors is None or parallactic_angles is None:
            from ..utils.ephem import doppler_parangle_for_archive

            # only warn when the Doppler factors themselves (the
            # barycentric-correction input) are the missing quantity
            dfs, pas = doppler_parangle_for_archive(
                self.epochs, ephemeris_text, telescope,
                warn=doppler_factors is None)
            if doppler_factors is None:
                if dfs is None:
                    # unity fallback: downstream bary=True corrections
                    # silently become topocentric — record it so TOAs
                    # can carry a -pp_topo flag
                    self.doppler_degraded = True
                    doppler_factors = np.ones(self.nsub)
                else:
                    doppler_factors = dfs
            if parallactic_angles is None:
                parallactic_angles = pas if pas is not None \
                    else np.zeros(self.nsub)
        self.doppler_factors = np.asarray(doppler_factors)
        self.parallactic_angles = np.asarray(parallactic_angles)
        self.filename = filename
        self.polyco = polyco  # Polyco predictor the data was folded with

    def copy(self):
        return Archive(self.data.copy(), self.freqs.copy(),
                       self.weights.copy(), self.Ps.copy(),
                       list(self.epochs), self.durations.copy(),
                       DM=self.DM, state=self.state,
                       dedispersed=self.dedispersed, source=self.source,
                       telescope=self.telescope, frontend=self.frontend,
                       backend=self.backend,
                       backend_delay=self.backend_delay, nu0=self.nu0,
                       bw=self.bw, ephemeris_text=self.ephemeris_text,
                       doppler_factors=self.doppler_factors.copy(),
                       parallactic_angles=self.parallactic_angles.copy(),
                       filename=self.filename, polyco=self.polyco,
                       doppler_degraded=self.doppler_degraded,
                       basis=self.basis)

    # -- state ----------------------------------------------------------
    def convert_state(self, state):
        """Convert polarization state like PSRCHIVE's convert_state
        (the reference reaches it through load_data's ``state`` kwarg,
        /root/reference/pplib.py:2678-2684).

        Supported: any -> 'Intensity' (total intensity, I or AA+BB),
        and the 4-pol linear maps Coherence <-> Stokes in the
        receptor basis ``self.basis`` (FD_POLN): for 'LIN' feeds
        I=AA+BB, Q=AA-BB, U=2CR, V=2CI; for 'CIRC' feeds the roles of
        Q/U and V rotate (I=AA+BB, V=AA-BB, Q=2CR, U=2CI).
        """
        if state == self.state:
            return
        if state == "Intensity":
            if self.state == "Coherence" and self.npol >= 2:
                I = self.data[:, 0:1] + self.data[:, 1:2]
            else:  # Stokes: first pol is I
                I = self.data[:, 0:1]
            self.data = I
            self.npol = 1
            self.state = "Intensity"
            return
        if self.state == "Coherence" and state == "Stokes" \
                and self.npol == 4:
            AA, BB = self.data[:, 0], self.data[:, 1]
            CR, CI = self.data[:, 2], self.data[:, 3]
            I, D = AA + BB, AA - BB
            if self.basis.startswith("CIRC"):
                self.data = np.stack([I, 2.0 * CR, 2.0 * CI, D], axis=1)
            else:  # LIN (default when the basis is unrecorded)
                self.data = np.stack([I, D, 2.0 * CR, 2.0 * CI], axis=1)
            self.state = "Stokes"
            return
        if self.state == "Stokes" and state == "Coherence" \
                and self.npol == 4:
            I, Q = self.data[:, 0], self.data[:, 1]
            U, V = self.data[:, 2], self.data[:, 3]
            if self.basis.startswith("CIRC"):
                AA, BB, CR, CI = (I + V) / 2.0, (I - V) / 2.0, \
                    Q / 2.0, U / 2.0
            else:
                AA, BB, CR, CI = (I + Q) / 2.0, (I - Q) / 2.0, \
                    U / 2.0, V / 2.0
            self.data = np.stack([AA, BB, CR, CI], axis=1)
            self.state = "Coherence"
            return
        raise NotImplementedError(
            f"State conversion {self.state} (npol={self.npol}) -> "
            f"{state} not supported; supported: -> 'Intensity', and "
            f"4-pol Coherence <-> Stokes.")

    def pscrunch(self):
        self.convert_state("Intensity")

    # -- dispersion -----------------------------------------------------
    def _dispersion_shifts(self):
        """Per (sub, chan) phase shifts [rot] that dedisperse to nu0."""
        return (Dconst * self.DM / self.Ps[:, None]) * \
            (self.freqs ** -2 - self.nu0 ** -2)

    def dedisperse(self):
        if not self.dedispersed:
            self.data = _rotate_np(self.data,
                                   self._dispersion_shifts()[:, None, :])
            self.dedispersed = True

    def dededisperse(self):
        if self.dedispersed:
            self.data = _rotate_np(self.data,
                                   -self._dispersion_shifts()[:, None, :])
            self.dedispersed = False

    # -- scrunches ------------------------------------------------------
    def tscrunch(self):
        if self.nsub == 1:
            return
        w = self.weights[:, None, :, None]
        wsum = self.weights.sum(axis=0)
        data = (self.data * w).sum(axis=0, keepdims=True)
        norm = np.where(wsum > 0.0, wsum, 1.0)[None, None, :, None]
        self.data = data / norm
        mid = self.epochs[0] + \
            (self.epochs[-1] - self.epochs[0]) / 2.0 / 86400.0
        self.epochs = [mid]
        self.Ps = self.Ps[:1]
        self.freqs = self.freqs.mean(axis=0, keepdims=True)
        self.weights = np.where(wsum > 0.0, 1.0, 0.0)[None, :]
        self.durations = np.array([self.durations.sum()])
        self.doppler_factors = self.doppler_factors[:1]
        self.parallactic_angles = self.parallactic_angles[:1]
        self.nsub = 1

    def fscrunch(self):
        if self.nchan == 1:
            return
        if not self.dedispersed:
            self.dedisperse()
        w = self.weights[:, None, :, None]
        wsum = self.weights.sum(axis=1)
        data = (self.data * w).sum(axis=2, keepdims=True)
        norm = np.where(wsum > 0.0, wsum, 1.0)[:, None, None, None]
        self.data = data / norm
        self.freqs = np.full((self.nsub, 1), self.nu0)
        self.weights = np.where(wsum > 0.0, 1.0, 0.0)[:, None]
        self.nchan = 1

    # -- baseline -------------------------------------------------------
    def remove_baseline(self, frac=0.125):
        """Subtract each profile's off-pulse baseline: the mean over the
        minimum-mean sliding window spanning ``frac`` of pulse phase
        (PSRCHIVE's default baseline algorithm)."""
        nwin = max(1, int(frac * self.nbin))
        kernel = np.zeros(self.nbin)
        kernel[:nwin] = 1.0 / nwin
        # circular windowed means via FFT convolution
        means = np.fft.irfft(np.fft.rfft(self.data, axis=-1)
                             * np.conj(np.fft.rfft(kernel)), self.nbin,
                             axis=-1)
        baseline = means.min(axis=-1, keepdims=True)
        self.data = self.data - baseline

    # -- unload ---------------------------------------------------------
    def unload(self, filename, quiet=True):
        write_archive_file(self, filename, quiet=quiet)
        self.filename = filename


def write_archive_file(arch, filename, nbits=16, quiet=True,
                       period_column=True):
    """Encode an Archive to a PSRFITS file (int16 + per-profile scale).

    ``period_column=False`` omits the explicit PERIOD column, as
    psrchive/dspsr-produced archives do — per-subint periods must then
    come from the POLYCO HDU (written when ``arch.polyco`` is set) or
    the ephemeris.
    """
    nsub, npol, nchan, nbin = arch.data.shape
    start = arch.epochs[0] - float(arch.durations[0]) / 2.0 / 86400.0

    primary = HDU()
    h = primary.header
    h.set("HDRVER", "6.1", "Header version")
    h.set("FITSTYPE", "PSRFITS", "FITS definition for pulsar data files")
    h.set("OBS_MODE", "PSR", "(PSR, CAL, SEARCH)")
    h.set("TELESCOP", arch.telescope)
    h.set("FRONTEND", arch.frontend)
    h.set("BACKEND", arch.backend)
    h.set("BE_DELAY", arch.backend_delay, "Backend propn delay [s]")
    h.set("FD_POLN", getattr(arch, "basis", "LIN"),
          "LIN or CIRC (receptor basis)")
    h.set("OBSFREQ", arch.nu0, "[MHz] Centre frequency")
    h.set("OBSBW", arch.bw, "[MHz] Bandwidth")
    h.set("OBSNCHAN", nchan, "Number of frequency channels")
    h.set("SRC_NAME", arch.source)
    h.set("STT_IMJD", start.intday(), "Start MJD (UTC days)")
    h.set("STT_SMJD", int(start.secs), "[s] Start time")
    h.set("STT_OFFS", start.secs - int(start.secs), "[s] Start offset")

    hdus = [primary]
    if arch.ephemeris_text:
        lines = [ln for ln in arch.ephemeris_text.splitlines() if ln]
        width = max(len(ln) for ln in lines)
        param = np.array([ln.ljust(width) for ln in lines],
                         dtype="S%d" % width)
        hdus.append(write_bintable_hdu("PSRPARAM", {"PARAM": param}))

    # int-encode: physical = DATA*scl + offs per (sub, pol, chan)
    data = arch.data
    dmax = data.max(axis=-1)
    dmin = data.min(axis=-1)
    span = np.where(dmax > dmin, dmax - dmin, 1.0)
    scl = span / (2 ** (nbits - 1) - 2)  # int16 range with margin
    offs = (dmax + dmin) / 2.0
    q = np.rint((data - offs[..., None]) / scl[..., None])
    q = np.clip(q, -(2 ** (nbits - 1) - 1), 2 ** (nbits - 1) - 1)
    enc = q.astype(np.int16)

    if getattr(arch, "polyco", None) is not None:
        segs = arch.polyco.segments
        ncoef = max(len(s.coeffs) for s in segs)
        hdus.append(write_bintable_hdu("POLYCO", {
            "NSPAN": np.array([s.nspan for s in segs], np.float64),
            "NCOEF": np.array([len(s.coeffs) for s in segs], np.int16),
            "NSITE": np.array([s.site.ljust(8)[:8] for s in segs], "S8"),
            "REF_FREQ": np.array([s.ref_freq for s in segs], np.float64),
            "REF_MJD": np.array([s.tmid for s in segs], np.float64),
            "REF_PHS": np.array([s.rphase for s in segs], np.float64),
            "REF_F0": np.array([s.f0ref for s in segs], np.float64),
            "LGFITERR": np.array([s.log10_fit_err for s in segs],
                                 np.float64),
            "COEFF": np.stack([np.pad(s.coeffs,
                                      (0, ncoef - len(s.coeffs)))
                               for s in segs]).astype(np.float64),
        }))

    offs_sub = np.array([ep - start for ep in arch.epochs])  # seconds
    columns = {
        "TSUBINT": arch.durations.astype(np.float64),
        "OFFS_SUB": offs_sub.astype(np.float64),
    }
    if period_column:
        columns["PERIOD"] = arch.Ps.astype(np.float64)
    if not getattr(arch, "doppler_degraded", False):
        # never persist the fabricated unity/zero fallback as if it were
        # measured: a degraded archive re-reads as degraded (and flags
        # its bary TOAs) instead of laundering ones into the file
        columns.update({
            "DOPPLER": arch.doppler_factors.astype(np.float64),
            "PAR_ANG": arch.parallactic_angles.astype(np.float64),
        })
    columns.update({
        "DAT_FREQ": arch.freqs.astype(np.float64),
        "DAT_WTS": arch.weights.astype(np.float32),
        "DAT_OFFS": offs.reshape(nsub, npol * nchan).astype(np.float32),
        "DAT_SCL": scl.reshape(nsub, npol * nchan).astype(np.float32),
        # FITS TDIM is reversed relative to the numpy shape:
        # (nbin, nchan, npol) in the header
        "DATA": enc,
    })
    extra = [
        ("INT_TYPE", "TIME", "Time axis"),
        ("INT_UNIT", "SEC", ""),
        ("SCALE", "FluxDen", ""),
        ("POL_TYPE", {"Intensity": "AA+BB", "Stokes": "IQUV",
                      "Coherence": "AABBCRCI"}[arch.state], ""),
        ("STATE", arch.state, "Polarization state"),
        ("NPOL", npol, "Nr of polarisations"),
        ("TBIN", float(arch.Ps[0] / nbin), "[s] Time per bin or sample"),
        ("NBIN", nbin, "Nr of bins"),
        ("NCHAN", nchan, "Number of channels"),
        ("CHAN_BW", arch.bw / nchan, "[MHz] Channel bandwidth"),
        ("DM", arch.DM, "[cm-3 pc] DM used for dedispersion"),
        ("DEDISP", arch.dedispersed, "Data dedispersed"),
        ("NBITS", 1, "Nr of bits/datum (unused for fold data)"),
        ("NSBLK", 1, "Samples/row"),
        ("EPOCHS", "MIDTIME", "Epoch convention"),
    ]
    hdus.append(write_bintable_hdu("SUBINT", columns, extra))
    write_fits(filename, hdus)
    if not quiet:
        print("Unloaded %s." % filename)


def _polyco_from_hdu(hdu):
    """POLYCO BINTABLE -> Polyco (one segment per row)."""
    cols = hdu.columns
    nseg = hdu.header["NAXIS2"]
    coeff = np.asarray(cols["COEFF"], dtype=np.float64).reshape(nseg, -1)
    ncoef = np.asarray(cols.get("NCOEF", [coeff.shape[1]] * nseg),
                       dtype=np.int64).reshape(nseg)
    sites = cols.get("NSITE", [b"@"] * nseg)
    segs = []
    for i in range(nseg):
        site = sites[i]
        site = site.decode() if isinstance(site, bytes) else str(site)
        segs.append(PolycoSegment(
            float(np.ravel(cols["REF_MJD"])[i]),
            float(np.ravel(cols["REF_PHS"])[i]),
            float(np.ravel(cols["REF_F0"])[i]),
            coeff[i, :ncoef[i]],
            nspan=float(np.ravel(cols.get("NSPAN", [1440] * nseg))[i]),
            ref_freq=float(np.ravel(cols.get("REF_FREQ",
                                             [0.0] * nseg))[i]),
            site=site.strip(),
            log10_fit_err=float(np.ravel(cols.get("LGFITERR",
                                                  [0.0] * nseg))[i])))
    return Polyco(segs)


def _t2predict_from_hdu(hdu):
    """T2PREDICT BINTABLE (text rows) -> ChebyModelSet."""
    col = hdu.columns.get("PREDICT")
    if col is None:
        return None
    text = "\n".join(v.decode() if isinstance(v, bytes) else str(v)
                     for v in np.ravel(col))
    return parse_t2predict_text(text)


def read_archive(filename):
    """Decode a PSRFITS file into an Archive."""
    hdus = read_fits(filename)
    primary = hdus[0].header
    subint = None
    ephemeris_text = ""
    polyco = None
    t2pred = None
    for hdu in hdus[1:]:
        name = str(hdu.header.get("EXTNAME", "")).strip()
        if name == "SUBINT":
            subint = hdu
        elif name in ("PSRPARAM", "PSREPHEM"):
            col = hdu.columns.get("PARAM")
            if col is not None:
                ephemeris_text = "\n".join(
                    v.decode() if isinstance(v, bytes) else str(v)
                    for v in col)
        elif name == "POLYCO":
            polyco = _polyco_from_hdu(hdu)
        elif name in ("T2PREDICT", "T2PRED"):
            t2pred = _t2predict_from_hdu(hdu)
    if subint is None:
        raise ValueError(f"{filename}: no SUBINT HDU found.")
    sh = subint.header
    cols = subint.columns
    nsub = sh["NAXIS2"]
    npol = int(sh.get("NPOL", 1))
    nchan = int(sh.get("NCHAN", primary.get("OBSNCHAN", 1)))
    raw = cols["DATA"]
    nbin = int(sh.get("NBIN", raw.shape[-1]))
    data = raw.reshape(nsub, npol, nchan, nbin).astype(np.float64)
    scl = np.asarray(cols.get("DAT_SCL",
                              np.ones((nsub, npol * nchan))),
                     dtype=np.float64).reshape(nsub, npol, nchan)
    offs = np.asarray(cols.get("DAT_OFFS",
                               np.zeros((nsub, npol * nchan))),
                      dtype=np.float64).reshape(nsub, npol, nchan)
    data = data * scl[..., None] + offs[..., None]

    freqs = np.asarray(cols["DAT_FREQ"], dtype=np.float64)
    if freqs.ndim == 1:
        freqs = freqs.reshape(nsub, nchan)
    weights = np.asarray(cols.get("DAT_WTS", np.ones((nsub, nchan))),
                         dtype=np.float64).reshape(nsub, nchan)
    durations = np.asarray(cols.get("TSUBINT", np.zeros(nsub)),
                           dtype=np.float64)
    start = MJD.from_imjd_smjd(primary.get("STT_IMJD", 0),
                               primary.get("STT_SMJD", 0),
                               primary.get("STT_OFFS", 0.0))
    offs_sub = np.asarray(cols.get("OFFS_SUB", np.zeros(nsub)),
                          dtype=np.float64)
    epochs = [start.add_seconds(float(o)) for o in offs_sub]
    # folding periods, in priority order: explicit PERIOD column >
    # POLYCO evaluated at each subint epoch > T2PREDICT > single-F0
    # ephemeris fallback (warned: real periods drift across subints,
    # ref /root/reference/pplib.py:2733)
    if "PERIOD" in cols:
        Ps = np.asarray(cols["PERIOD"], dtype=np.float64).reshape(nsub)
    elif polyco is not None:
        Ps = polyco.periods([ep.mjd() for ep in epochs])
    elif t2pred is not None:
        # evaluate the predictor per subint at that subint's weighted
        # center frequency (the reference's get_folding_period asks
        # each Integration for its own frequency; DAT_FREQ can drift)
        wsum = weights.sum(axis=1)
        has_w = wsum > 0.0
        nu_sub = np.where(
            has_w,
            (freqs * weights).sum(axis=1) / np.where(has_w, wsum, 1.0),
            freqs.mean(axis=1))
        Ps = np.array([float(t2pred.period(ep.mjd(), float(nu_sub[i])))
                       for i, ep in enumerate(epochs)])
    else:
        print(f"Warning: {filename} has no PERIOD column and no "
              "POLYCO/T2PREDICT HDU; folding all subints at the "
              "ephemeris F0 (periods do not drift).", file=sys.stderr)
        Ps = np.full(nsub, _period_from_ephemeris(ephemeris_text))
    pol_type = str(sh.get("POL_TYPE", "AA+BB")).strip()
    state = str(sh.get("STATE", "")).strip() or \
        {"IQUV": "Stokes", "AABBCRCI": "Coherence"}.get(pol_type,
                                                        "Intensity")
    # absent columns -> None so Archive computes them from geometry
    dop = cols.get("DOPPLER")
    if dop is not None:
        dop = np.asarray(dop, dtype=np.float64).reshape(nsub)
    par = cols.get("PAR_ANG")
    if par is not None:
        par = np.asarray(par, dtype=np.float64).reshape(nsub)
    return Archive(
        data, freqs, weights, Ps, epochs, durations,
        DM=float(sh.get("DM", 0.0)),
        state=state, dedispersed=bool(sh.get("DEDISP", False)),
        source=str(primary.get("SRC_NAME", "unknown")).strip(),
        telescope=str(primary.get("TELESCOP", "unknown")).strip(),
        frontend=str(primary.get("FRONTEND", "unknown")).strip(),
        backend=str(primary.get("BACKEND", "unknown")).strip(),
        backend_delay=float(primary.get("BE_DELAY", 0.0)),
        nu0=float(primary.get("OBSFREQ", freqs.mean())),
        bw=float(primary.get("OBSBW", 0.0)) or None,
        ephemeris_text=ephemeris_text, doppler_factors=dop,
        parallactic_angles=par, filename=filename, polyco=polyco,
        basis=str(primary.get("FD_POLN", "LIN")).strip() or "LIN")


def _period_from_ephemeris(text):
    for line in text.splitlines():
        toks = line.split()
        if len(toks) >= 2 and toks[0] == "F0":
            return 1.0 / float(toks[1])
        if len(toks) >= 2 and toks[0] == "P0":
            return float(toks[1])
    return 1.0
