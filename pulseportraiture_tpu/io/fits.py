"""Minimal FITS container I/O: headers + binary tables, pure NumPy.

The reference reaches PSRFITS through the external PSRCHIVE C++ library
(/root/reference/pplib.py:35 and SURVEY.md §0/L0); this framework keeps
archive I/O in-repo.  Only the FITS subset PSRFITS needs is implemented:
the primary HDU (header-only) and BINTABLE extensions with column types
L, X, B, I, J, K, E, D, A and TDIM reshaping.  All multi-byte fields are
big-endian per the FITS standard.
"""

import numpy as np

__all__ = ["Header", "HDU", "read_fits", "write_fits"]

BLOCK = 2880
CARD = 80

# FITS binary-table type code -> (numpy big-endian dtype, item size)
_TFORM_DTYPES = {
    "L": ("S1", 1), "B": (">u1", 1), "I": (">i2", 2), "J": (">i4", 4),
    "K": (">i8", 8), "E": (">f4", 4), "D": (">f8", 8), "A": ("S1", 1),
}


class Header(dict):
    """Ordered FITS header: mapping of keyword -> value, plus comments."""

    def __init__(self):
        super().__init__()
        self.comments = {}
        self.order = []

    def set(self, key, value, comment=""):
        if key not in self:
            self.order.append(key)
        self[key] = value
        if comment:
            self.comments[key] = comment

    @staticmethod
    def _parse_value(raw):
        raw = raw.strip()
        if raw.startswith("'"):
            end = raw.rfind("'")
            return raw[1:end].rstrip()
        if raw in ("T", "F"):
            return raw == "T"
        try:
            if any(c in raw for c in ".EeDd") and not raw.isdigit():
                return float(raw.replace("D", "E").replace("d", "e"))
            return int(raw)
        except ValueError:
            return raw

    @classmethod
    def from_bytes(cls, buf):
        """Parse header cards until END; returns (header, ncards_blocks)."""
        hdr = cls()
        offset = 0
        while True:
            card = buf[offset:offset + CARD].decode("ascii", "replace")
            offset += CARD
            key = card[:8].strip()
            if key == "END":
                break
            if key in ("COMMENT", "HISTORY", ""):
                continue
            body = card[8:]
            if not body.startswith("= "):
                continue
            rest = body[2:]
            # strip inline comment (outside quoted strings)
            if rest.lstrip().startswith("'"):
                q2 = rest.find("'", rest.find("'") + 1)
                val_str = rest[:q2 + 1]
            else:
                slash = rest.find("/")
                val_str = rest if slash < 0 else rest[:slash]
            hdr.set(key, cls._parse_value(val_str))
        nblocks = (offset + BLOCK - 1) // BLOCK
        return hdr, nblocks

    @staticmethod
    def _format_value(value):
        if isinstance(value, bool):
            return "T" if value else "F"
        if isinstance(value, (int, np.integer)):
            return "%20d" % value
        if isinstance(value, (float, np.floating)):
            s = "%20.14G" % value
            return s if len(s) <= 20 else "%20.8G" % value
        s = str(value)
        return "'%-8s'" % s if len(s) <= 8 else "'%s'" % s

    def to_bytes(self):
        cards = []
        for key in self.order:
            val = self._format_value(self[key])
            comment = self.comments.get(key, "")
            card = "%-8s= %20s" % (key, val)
            if comment:
                card += " / " + comment
            cards.append(card[:CARD].ljust(CARD))
        cards.append("END".ljust(CARD))
        data = "".join(cards).encode("ascii")
        pad = (-len(data)) % BLOCK
        return data + b" " * pad


class HDU:
    """One header-data unit: header + (for BINTABLE) dict of columns."""

    def __init__(self, header=None, columns=None, name=""):
        self.header = header or Header()
        self.columns = columns or {}
        self.name = name or self.header.get("EXTNAME", "")


def _parse_tform(tform):
    tform = tform.strip()
    i = 0
    while i < len(tform) and tform[i].isdigit():
        i += 1
    repeat = int(tform[:i]) if i else 1
    code = tform[i]
    return repeat, code


def _parse_tdim(tdim):
    return tuple(int(v) for v in tdim.strip().strip("()").split(","))


def _read_bintable(header, raw):
    nrow = header["NAXIS2"]
    rowbytes = header["NAXIS1"]
    tfields = header["TFIELDS"]
    names, fmts, shapes = [], [], {}
    for i in range(1, tfields + 1):
        name = str(header.get(f"TTYPE{i}", f"COL{i}")).strip()
        repeat, code = _parse_tform(str(header[f"TFORM{i}"]))
        dt, _ = _TFORM_DTYPES[code]
        names.append(name)
        if code == "A":
            fmts.append(("S%d" % repeat) if repeat else "S1")
        else:
            fmts.append("%d%s" % (repeat, dt) if repeat != 1 else dt)
        if f"TDIM{i}" in header:
            # FITS TDIM is Fortran (fastest-first); numpy is C — reverse.
            shapes[name] = tuple(reversed(_parse_tdim(
                str(header[f"TDIM{i}"]))))
    dtype = np.dtype({"names": names, "formats": fmts})
    if dtype.itemsize != rowbytes:
        raise ValueError(f"BINTABLE row size mismatch: dtype "
                         f"{dtype.itemsize} vs NAXIS1 {rowbytes}")
    table = np.frombuffer(raw[:nrow * rowbytes], dtype=dtype)
    columns = {}
    for name in names:
        col = table[name]
        if name in shapes:
            col = col.reshape((nrow,) + shapes[name])
        if col.dtype.kind in "iuf":
            col = col.astype(col.dtype.newbyteorder("="))
        columns[name] = col
    return columns


def read_fits(path):
    """Read a FITS file into a list of HDUs."""
    with open(path, "rb") as f:
        buf = f.read()
    hdus = []
    offset = 0
    while offset < len(buf):
        header, nblocks = Header.from_bytes(buf[offset:])
        offset += nblocks * BLOCK
        columns = {}
        if header.get("XTENSION", "").strip() == "BINTABLE":
            nbytes = header["NAXIS1"] * header["NAXIS2"]
            columns = _read_bintable(header, buf[offset:offset + nbytes])
            offset += ((nbytes + BLOCK - 1) // BLOCK) * BLOCK
        elif header.get("NAXIS", 0) > 0:
            nbytes = abs(header.get("BITPIX", 8)) // 8
            for i in range(1, header["NAXIS"] + 1):
                nbytes *= header[f"NAXIS{i}"]
            offset += ((nbytes + BLOCK - 1) // BLOCK) * BLOCK
        hdus.append(HDU(header, columns))
        if not header.get("XTENSION") and not hdus[0].header.get("EXTEND",
                                                                 True):
            break
    return hdus


def _column_tform(arr):
    """(tform, big-endian dtype str, per-row shape) for a column array."""
    kind = arr.dtype.kind
    if kind in ("S", "U"):
        size = int(arr.dtype.itemsize if kind == "S"
                   else arr.dtype.itemsize // 4)
        return "%dA" % size, "S%d" % size, ()
    per_row = int(np.prod(arr.shape[1:], dtype=int))
    code = {"f4": "E", "f8": "D", "i2": "I", "i4": "J", "i8": "K",
            "u1": "B"}[arr.dtype.str[-2:]]
    dt, _ = _TFORM_DTYPES[code]
    fmt = "%d%s" % (per_row, dt) if per_row != 1 else dt
    return ("%d%s" % (per_row, code) if per_row != 1 else code), fmt, \
        arr.shape[1:]


def write_bintable_hdu(name, columns, extra_header=None):
    """Build a BINTABLE HDU from an ordered {name: array} mapping.

    Arrays are [nrow, ...]; multi-dim columns get TDIM.  extra_header:
    ordered (key, value, comment) triples appended after the standard
    table keywords.
    """
    names = list(columns)
    nrow = len(next(iter(columns.values()))) if columns else 0
    fmts, tforms, tdims = [], [], {}
    for cname in names:
        arr = np.asarray(columns[cname])
        if arr.dtype.kind == "U":
            arr = arr.astype("S%d" % max(1, max((len(s) for s in
                                                 arr.ravel().astype(str)),
                                                default=1)))
            columns[cname] = arr
        tform, fmt, shape = _column_tform(arr)
        tforms.append(tform)
        fmts.append(fmt)
        if len(shape) >= 1 and arr.dtype.kind not in ("S",):
            if len(shape) > 1:
                tdims[cname] = "(" + ",".join(str(s) for s in
                                              reversed(shape)) + ")"
    dtype = np.dtype({"names": names, "formats": fmts})
    table = np.zeros(nrow, dtype=dtype)
    for cname in names:
        arr = np.asarray(columns[cname])
        if arr.dtype.kind == "S":
            table[cname] = arr
        else:
            table[cname] = arr.reshape(nrow, -1).astype(
                table.dtype[cname].base, copy=False).reshape(
                    table[cname].shape)
    hdr = Header()
    hdr.set("XTENSION", "BINTABLE", "binary table extension")
    hdr.set("BITPIX", 8)
    hdr.set("NAXIS", 2)
    hdr.set("NAXIS1", dtype.itemsize, "width of table in bytes")
    hdr.set("NAXIS2", nrow, "number of rows")
    hdr.set("PCOUNT", 0)
    hdr.set("GCOUNT", 1)
    hdr.set("TFIELDS", len(names))
    for i, (cname, tform) in enumerate(zip(names, tforms), start=1):
        hdr.set(f"TTYPE{i}", cname)
        hdr.set(f"TFORM{i}", tform)
        if cname in tdims:
            hdr.set(f"TDIM{i}", tdims[cname])
    hdr.set("EXTNAME", name)
    for key, value, comment in (extra_header or []):
        hdr.set(key, value, comment)
    hdu = HDU(hdr, dict(zip(names, (columns[n] for n in names))), name)
    hdu._table = table
    return hdu


def write_fits(path, hdus):
    """Write HDUs (primary first; BINTABLEs built by write_bintable_hdu)."""
    out = []
    primary = hdus[0]
    if "SIMPLE" not in primary.header:
        hdr = Header()
        hdr.set("SIMPLE", True, "file conforms to FITS standard")
        hdr.set("BITPIX", 8)
        hdr.set("NAXIS", 0)
        hdr.set("EXTEND", True)
        for key in primary.header.order:
            hdr.set(key, primary.header[key],
                    primary.header.comments.get(key, ""))
        primary = HDU(hdr)
    out.append(primary.header.to_bytes())
    for hdu in hdus[1:]:
        out.append(hdu.header.to_bytes())
        table = getattr(hdu, "_table", None)
        if table is not None:
            raw = table.tobytes()
            out.append(raw + b"\x00" * ((-len(raw)) % BLOCK))
    with open(path, "wb") as f:
        f.write(b"".join(out))
