"""Measure align-workload throughput through the survey engine
(PERF.md §6): 8 synthetic archives run twice in one process — a cold
pass (pays the phase-fit kernel compiles) and a warm pass into a
fresh workdir (the steady-state rate) — so the printed lines separate
first-compile amortization from the engine's real per-archive cost
(ledger + lease heartbeat + JSONL checkpoint + part file + reduce).

Run:  env JAX_PLATFORMS=cpu python -m tools.align_perf
"""

import os
import shutil
import sys
import tempfile
import time

import numpy as np


def main():
    workroot = tempfile.mkdtemp(prefix="pptpu_align_perf_")
    try:
        from pulseportraiture_tpu.io.archive import make_fake_pulsar
        from pulseportraiture_tpu.io.gmodel import write_model
        from pulseportraiture_tpu.runner import plan_survey, run_survey

        gm = os.path.join(workroot, "p.gmodel")
        write_model(gm, "p", "000", 1500.0,
                    np.array([0.0, 0.0, 0.4, 0.0, 0.05, 0.0, 1.0,
                              -0.5]),
                    np.ones(8, int), -4.0, 0, quiet=True)
        par = os.path.join(workroot, "p.par")
        with open(par, "w") as f:
            f.write("PSR J0\nRAJ 00:00:00\nDECJ 00:00:00\nF0 200.0\n"
                    "PEPOCH 56000.0\nDM 30.0\n")
        n = 8
        files = []
        for i in range(n):
            fits = os.path.join(workroot, "a%d.fits" % i)
            make_fake_pulsar(gm, par, fits, nsub=2, nchan=8, nbin=128,
                             nu0=1500.0, bw=400.0, tsub=60.0,
                             phase=0.01 * (i + 1), dDM=5e-4,
                             noise_stds=0.01, dedispersed=False,
                             seed=300 + i, quiet=True)
            files.append(fits)
        tmpl = os.path.join(workroot, "t.fits")
        make_fake_pulsar(gm, par, tmpl, nsub=1, nchan=8, nbin=128,
                         nu0=1500.0, bw=400.0, tsub=60.0,
                         noise_stds=0.004, dedispersed=True, seed=7,
                         quiet=True)
        plan = plan_survey(files, modelfile=gm)

        for label, wd in (("cold", "wd1"), ("warm", "wd2")):
            wdp = os.path.join(workroot, wd)
            t0 = time.perf_counter()
            s = run_survey(plan, wdp, workload="align",
                           workload_opts={"initial_guess": tmpl},
                           process_index=0, process_count=1,
                           backoff_s=0.0, merge=False)
            dt = time.perf_counter() - t0
            assert s["counts"]["done"] == n, s["counts"]
            print("%s engine: %.2f s  %.2f archives/s"
                  % (label, dt, n / dt))
        return 0
    finally:
        shutil.rmtree(workroot, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
